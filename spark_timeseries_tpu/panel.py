"""Panel: the central container — a keyed panel of time series on one index.

This one class replaces BOTH of the reference's containers:

- the local multivariate ``TimeSeries[K]`` (ref
  ``/root/reference/src/main/scala/com/cloudera/sparkts/TimeSeries.scala:28-403``)
- the distributed ``TimeSeriesRDD[K]`` (ref
  ``/root/reference/src/main/scala/com/cloudera/sparkts/TimeSeriesRDD.scala:52-648``)

because on TPU the "distributed collection of (key, vector) pairs" is simply a
single ``(n_series, n_obs)`` array sharded over the series axis of a
``jax.sharding.Mesh``.  Every per-series ``map`` in the reference becomes a
batched XLA kernel over axis 0; Spark's shuffle/aggregate machinery becomes
XLA collectives inserted automatically by ``jit`` on the sharded array.

Layout choice: series-major ``(n_series, n_obs)`` (the reference's DenseMatrix
is time-major obs x series).  Series-major puts the batch dimension first for
``vmap``/sharding and makes each series a contiguous HBM row.

Calendar logic (index arithmetic, key bookkeeping) stays host-side; only
resolved integer locations and float arrays enter jitted code.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .ops import univariate as uv
from .ops.lag import lag_matrix
from .ops.resample import resample as _resample_values
from .time import DateTimeIndex, Frequency, IrregularDateTimeIndex, UniformDateTimeIndex
from .time.rebase import rebaser as _rebaser
from .utils import metrics as _metrics


def lagged_string_key(key: str, lag_order: int) -> str:
    """Key-naming convention for lagged series (ref ``TimeSeries.scala:406-407``)."""
    return f"lag{lag_order}({key})" if lag_order > 0 else key


def lagged_pair_key(key: Any, lag_order: int) -> Tuple[Any, int]:
    """(key, lag) pair convention (ref ``TimeSeries.scala:409``)."""
    return (key, lag_order)


class Panel:
    """A keyed panel of univariate series sharing one ``DateTimeIndex``.

    Attributes:
      index: the shared time index (host-side).
      values: ``(n_series, n_obs)`` jax array; may carry a ``NamedSharding``
        over the series axis (see :meth:`shard`).
      keys: list of per-series keys (host-side).
    """

    def __init__(self, index: DateTimeIndex, values, keys: Sequence[Any]):
        values = jnp.asarray(values)
        if values.ndim != 2:
            raise ValueError(f"values must be (n_series, n_obs), got {values.shape}")
        if values.shape[1] != len(index):
            raise ValueError(
                f"values has {values.shape[1]} observations but index has "
                f"{len(index)} instants")
        if values.shape[0] != len(keys):
            raise ValueError(
                f"values has {values.shape[0]} series but {len(keys)} keys given")
        self.index = index
        self.values = values
        self.keys = list(keys)

    # -- basic introspection ------------------------------------------------

    @property
    def n_series(self) -> int:
        return self.values.shape[0]

    @property
    def n_obs(self) -> int:
        return self.values.shape[1]

    def __len__(self) -> int:
        return self.n_series

    def __repr__(self) -> str:
        return (f"Panel(n_series={self.n_series}, n_obs={self.n_obs}, "
                f"index={self.index!r})")

    def _with(self, values=None, index=None, keys=None) -> "Panel":
        return Panel(self.index if index is None else index,
                     self.values if values is None else values,
                     self.keys if keys is None else keys)

    # -- sharding (the L4 "distribution" tier) ------------------------------

    def shard(self, mesh, axis_name: str = "series") -> "Panel":
        """Place ``values`` on ``mesh`` sharded over the series axis.

        TPU-native equivalent of partitioning the RDD across executors
        (ref ``TimeSeriesRDD.scala:52-59``): one line of sharding metadata,
        after which every op in this class runs SPMD with XLA inserting any
        needed collectives over ICI.
        """
        from jax.sharding import NamedSharding, PartitionSpec as P
        sharding = NamedSharding(mesh, P(axis_name, None))
        with _metrics.span("panel.shard"):
            placed = jax.device_put(self.values, sharding)
        _metrics.inc("panel.h2d_bytes", int(self.values.nbytes))
        _metrics.inc("panel.shards")
        return self._with(values=placed)

    def to_row_matrix(self) -> jnp.ndarray:
        """Time-major ``(n_obs, n_series)`` matrix — the ``toRowMatrix``
        bridge (ref ``TimeSeriesRDD.scala:482-486``); requires no distributed
        matrix type here, the array IS the matrix."""
        return self.to_time_major()

    def to_indexed_row_matrix(self) -> jnp.ndarray:
        """Alias of :meth:`to_row_matrix` (ref ``TimeSeriesRDD.scala:456-471``
        — the row index is the position in the time axis)."""
        return self.to_time_major()

    def to_time_major(self) -> jnp.ndarray:
        """``(n_obs, n_series)`` view — the reference's ``toInstants`` shuffle
        transpose (ref ``TimeSeriesRDD.scala:276-391``) collapses to one
        transpose; under ``jit`` on a sharded panel XLA lowers the resharding
        to an ``all_to_all`` over ICI instead of a Spark shuffle."""
        return self.values.T

    # -- per-series iteration & lookup (ref TimeSeries.scala:273-293) -------

    def __iter__(self) -> Iterator[Tuple[Any, np.ndarray]]:
        host = np.asarray(self.values)
        for i, k in enumerate(self.keys):
            yield k, host[i]

    def head(self) -> Tuple[Any, np.ndarray]:
        """First (key, series) pair (ref ``TimeSeries.scala:365-368``)."""
        return self.keys[0], np.asarray(self.values[0])

    def find_series(self, key: Any) -> np.ndarray:
        """Series for ``key`` (ref ``TimeSeriesRDD.scala:265-273`` findSeries)."""
        return np.asarray(self.values[self.keys.index(key)])

    def select(self, keys: Sequence[Any]) -> "Panel":
        """Sub-panel with the given keys, in the given order.

        One key→position dict resolves every key (repeated ``list.index``
        was O(n_keys * n_series)); duplicate panel keys resolve to their
        first occurrence, matching ``list.index``.  A single vectorized
        gather builds the value matrix."""
        pos: dict = {}
        for i, k in enumerate(self.keys):
            pos.setdefault(k, i)
        try:
            locs = np.fromiter((pos[k] for k in keys), dtype=np.int64,
                               count=len(keys))
        except KeyError as e:
            raise ValueError(f"{e.args[0]!r} is not in the panel keys") \
                from None
        return self._with(values=self.values[jnp.asarray(locs)],
                          keys=list(keys))

    def filter_keys(self, predicate: Callable[[Any], bool]) -> "Panel":
        """Keep series whose key satisfies ``predicate``
        (ref ``TimeSeriesRDD.scala:133-138`` filter/findSeries family).
        One host pass over the keys, one vectorized gather."""
        locs = np.fromiter((i for i, k in enumerate(self.keys)
                            if predicate(k)), dtype=np.int64)
        return self._with(values=self.values[jnp.asarray(locs)],
                          keys=[self.keys[i] for i in locs])

    def filter_start_with(self, prefix: str) -> "Panel":
        """(ref ``TimeSeriesRDD.scala:140-145`` filterStartingWith)."""
        return self.filter_keys(lambda k: str(k).startswith(prefix))

    def filter_end_with(self, suffix: str) -> "Panel":
        """(ref ``TimeSeriesRDD.scala:147-151`` filterEndingWith)."""
        return self.filter_keys(lambda k: str(k).endswith(suffix))

    def union(self, other: "Panel") -> "Panel":
        """Stack another panel's series on the same index
        (ref ``TimeSeries.scala:163-168`` union)."""
        if len(other.index) != len(self.index):
            raise ValueError("union requires identical index lengths")
        return self._with(values=jnp.concatenate([self.values, other.values]),
                          keys=self.keys + other.keys)

    def add_series(self, key: Any, series) -> "Panel":
        return self.union(Panel(self.index, jnp.asarray(series)[None, :], [key]))

    # -- time slicing (ref TimeSeriesRDD.scala:218-243) ----------------------

    def islice(self, start: int, end: int) -> "Panel":
        """Slice by integer location range [start, end)."""
        return self._with(values=self.values[:, start:end],
                          index=self.index.islice(start, end))

    def slice(self, start, end) -> "Panel":
        """Slice by datetimes (inclusive, like the reference's ``slice``)."""
        lo = self.index.loc_at_or_after(start)
        hi = self.index.loc_at_or_before(end) + 1
        return self.islice(lo, hi)

    # -- elementwise / per-series transforms ---------------------------------

    def map_values(self, f: Callable[[jnp.ndarray], jnp.ndarray]) -> "Panel":
        """Apply an index-preserving batched transform to the value matrix
        (ref ``TimeSeriesRDD.scala:249-254`` mapSeries — but batched, not
        per-series closures)."""
        return self._with(values=f(self.values))

    def map_series(self, f: Callable[[jnp.ndarray], jnp.ndarray],
                   new_index: Optional[DateTimeIndex] = None) -> "Panel":
        """``vmap`` a single-series function over the panel
        (ref ``TimeSeries.scala:332-363`` mapSeries).  ``f`` takes ``(n,)`` and
        returns ``(m,)`` with ``m == len(new_index or index)``."""
        out = jax.vmap(f)(self.values)
        idx = self.index if new_index is None else new_index
        if out.shape[1] != len(idx):
            raise ValueError(
                f"mapped series length {out.shape[1]} != index size {len(idx)}")
        return self._with(values=out, index=idx)

    def fill(self, method: str) -> "Panel":
        """NaN imputation (ref ``TimeSeriesRDD.scala:241-243``)."""
        return self._with(values=uv.fillts(self.values, method))

    def differences(self, lag: int = 1) -> "Panel":
        """Order-``lag`` differencing, dropping the first ``lag`` instants
        (ref ``TimeSeries.scala:241-249``)."""
        vals = self.values[:, lag:] - self.values[:, :-lag]
        return self._with(values=vals, index=self.index.islice(lag, len(self.index)))

    def quotients(self, lag: int = 1) -> "Panel":
        """(ref ``TimeSeries.scala:255-263``)."""
        return self._with(values=uv.quotients(self.values, lag),
                          index=self.index.islice(lag, len(self.index)))

    def price2ret(self) -> "Panel":
        """Periodic returns (ref ``TimeSeries.scala:269-271``)."""
        return self._with(values=uv.price2ret(self.values, 1),
                          index=self.index.islice(1, len(self.index)))

    return_rates = price2ret  # ref TimeSeriesRDD.scala:126-131 returnRates

    def roll_sum(self, window: int) -> "Panel":
        """Sliding sum; drops the first ``window-1`` instants
        (ref ``TimeSeriesRDD.scala:611-620`` rollSum)."""
        return self._with(values=uv.roll_sum(self.values, window),
                          index=self.index.islice(window - 1, len(self.index)))

    def roll_mean(self, window: int) -> "Panel":
        """(ref ``TimeSeriesRDD.scala:629-647`` rollMean)."""
        return self._with(values=uv.roll_mean(self.values, window),
                          index=self.index.islice(window - 1, len(self.index)))

    def differences_by_frequency(self, frequency: Frequency) -> "Panel":
        """Difference each series against the value one ``frequency`` earlier,
        falling back to the most recent earlier observation
        (ref ``TimeSeries.scala:200-235`` differencesByFrequency).

        NaN semantics match the reference: if x[t] is NaN the output is NaN;
        if the looked-up earlier value is NaN, walk back to the most recent
        non-NaN (per series).  The calendar lookups are host-side; the
        per-series NaN walk-back is a batched cummax gather on device.
        """
        zone = self.index.zone
        start_nanos = frequency.advance(self.index.first_nanos, 1, zone)
        start = self.index.loc_at_or_after(start_nanos)
        if start == 0:
            start = 1
        n = len(self.index)
        new_index = self.index.islice(start, n)
        # host: for each kept instant, the location of (t - frequency), at or
        # before — one vectorized advance + one searchsorted over the whole
        # index; -1 clamps to 0 like the reference
        all_nanos = self.index.to_nanos_array()
        prev_nanos = frequency.advance_each(all_nanos[start:], -1, zone)
        prev_locs = np.maximum(self.index.locs_at_or_before(prev_nanos), 0)

        vals = self.values
        valid = ~jnp.isnan(vals)
        iota = jnp.arange(n)
        prev_valid = jax.lax.cummax(jnp.where(valid, iota, -1), axis=1)
        # per series: most recent non-NaN at or before prev_locs
        cand = prev_valid[:, jnp.asarray(prev_locs)]            # (s, m)
        base = jnp.take_along_axis(vals, jnp.clip(cand, 0, None), axis=1)
        base = jnp.where(cand < 0, jnp.nan, base)
        cur = vals[:, start:]
        return self._with(values=cur - base, index=new_index)

    # -- lagging (ref TimeSeries.scala:58-158, TimeSeriesRDD.scala:86-100) ---

    def lags(self, max_lag: int, include_original: bool,
             lagged_key: Callable[[Any, int], Any] = lagged_pair_key) -> "Panel":
        """Lagged panel: for each series, columns lag0 (optional), lag1..lagK,
        dropping the first ``max_lag`` instants.  Key layout matches the
        reference (per-series blocks, original first)."""
        if not isinstance(self.index, UniformDateTimeIndex):
            raise ValueError("lags requires a UniformDateTimeIndex")
        n = self.n_obs
        start = 0 if include_original else 1
        # (s, n - max_lag, cols) -> (s, cols, n - max_lag) -> flatten blocks
        lm = lag_matrix(self.values, max_lag, include_original)
        new_vals = jnp.moveaxis(lm, -1, -2).reshape(-1, n - max_lag)
        new_keys = [lagged_key(k, l)
                    for k in self.keys for l in range(start, max_lag + 1)]
        return self._with(values=new_vals, keys=new_keys,
                          index=self.index.islice(max_lag, n))

    def lags_per_key(self, lags_per_key: dict,
                     lagged_key: Callable[[Any, int], Any] = lagged_pair_key
                     ) -> "Panel":
        """Per-key (include_original, max_lag) lagging
        (ref ``TimeSeries.scala:117-158``)."""
        if not isinstance(self.index, UniformDateTimeIndex):
            raise ValueError("lags requires a UniformDateTimeIndex")
        max_lag = max(ml for _, ml in lags_per_key.values())
        n = self.n_obs
        rows, new_keys = [], []
        for i, k in enumerate(self.keys):
            include, ml = lags_per_key[k]
            for l in range(0 if include else 1, ml + 1):
                rows.append(self.values[i, max_lag - l:n - l])
                new_keys.append(lagged_key(k, l))
        return self._with(values=jnp.stack(rows), keys=new_keys,
                          index=self.index.islice(max_lag, n))

    # -- cross-series instant filters (ref TimeSeriesRDD.scala:158-210) ------

    def filter_by_instant(self, predicate: Callable[[jnp.ndarray], jnp.ndarray],
                          filter_keys: Optional[Sequence[Any]] = None) -> "Panel":
        """Keep instants where ``predicate`` holds for at least one of the
        selected series (ref ``TimeSeries.scala:305-327`` /
        ``TimeSeriesRDD.scala:158-177``).  ``predicate`` must be an
        elementwise jax-traceable function; the OR-reduction over the sharded
        series axis is XLA's psum equivalent of the reference's distributed
        ``aggregate``.  The result carries an irregular index (shape is
        data-dependent, so the gather is host-side).
        """
        sub = self if filter_keys is None else self.select(filter_keys)
        keep = np.asarray(jnp.any(predicate(sub.values), axis=0))
        locs = np.flatnonzero(keep)
        nanos = self.index.to_nanos_array()[locs]
        return self._with(values=self.values[:, jnp.asarray(locs)],
                          index=IrregularDateTimeIndex(nanos, self.index.zone))

    def remove_instants_with_nans(self) -> "Panel":
        """Drop instants where any series is NaN
        (ref ``TimeSeriesRDD.scala:184-210``)."""
        keep = np.asarray(~jnp.any(jnp.isnan(self.values), axis=0))
        locs = np.flatnonzero(keep)
        nanos = self.index.to_nanos_array()[locs]
        return self._with(values=self.values[:, jnp.asarray(locs)],
                          index=IrregularDateTimeIndex(nanos, self.index.zone))

    # -- resampling ----------------------------------------------------------

    def resample(self, target_index: DateTimeIndex, aggr: str = "mean",
                 closed_right: bool = False, stamp_right: bool = False) -> "Panel":
        """Window resampling onto ``target_index``
        (ref ``TimeSeries.scala:370-402`` / ``Resample.scala:47-121``)."""
        vals = _resample_values(self.values, self.index, target_index, aggr,
                                closed_right, stamp_right)
        return self._with(values=vals, index=target_index)

    def with_index(self, new_index: DateTimeIndex,
                   default_value: float = np.nan) -> "Panel":
        """Rebase every series onto a new index, NaN-filling missing instants
        (ref ``TimeSeriesRDD.scala:657-666`` constructor rebase path)."""
        with _metrics.span("panel.rebase"):
            rb = _rebaser(self.index, new_index, default_value)
            return self._with(values=jnp.asarray(rb(np.asarray(self.values))),
                              index=new_index)

    # -- summary stats (ref TimeSeriesRDD.scala:265-267 seriesStats) ----------

    def fit_resilient(self, family: str, *args, engine=None, **kwargs):
        """Fail-soft batched fit over the panel: per-series health masking,
        multi-start retry, and a declarative fallback chain — one pathological
        series (all-NaN, constant, too short, divergence-inducing) degrades
        its own lane's status instead of poisoning the batch or raising.

        ``family`` selects the model tier: ``"arima"`` (args: p, d, q),
        ``"arimax"`` (args: xreg, p, d, q, xreg_max_lag), ``"ar"`` (args:
        max_lag), ``"arx"`` (args: x, y_max_lag, x_max_lag), ``"ewma"``,
        ``"garch"``, ``"argarch"``, ``"egarch"``, ``"holt_winters"`` (args:
        period), ``"regression_arima"`` (args: regressors).  Extra args and
        kwargs (including ``retry=RetryPolicy(...)``, ``fallbacks=...``,
        and arima's ``auto_order=True`` — the adaptive searched-order
        fallback stage, whose per-lane selections come back in
        ``FitOutcome.orders``) pass through to the family's
        ``fit_resilient``.

        Returns ``(model, outcome)`` where ``outcome`` is a
        :class:`~spark_timeseries_tpu.utils.resilience.FitOutcome` with
        per-series status / health / attempts / fallback indices; healthy
        series match the family's plain ``fit`` bit-for-bit, and
        ``resilience.*`` counters land in the metrics registry (surfaced in
        bench JSON).

        Routes through the streaming fit engine's shape-bucketing
        front-end (``spark_timeseries_tpu.engine``): the series axis pads
        to its power-of-two bucket with all-NaN lanes — which the health
        classification masks out of every stage — so panels of varying
        series counts share the fit stages' compiled kernels instead of
        retracing per count.  Real lanes are bit-for-bit the unbucketed
        chain's results; the returned model and outcome are sliced to the
        real lanes.  ``engine=False`` restores the direct dispatch; an
        explicit :class:`~spark_timeseries_tpu.engine.FitEngine` uses
        that instance.
        """
        from .engine import FitEngine, default_engine
        with _metrics.span("panel.fit_resilient"):
            if engine is False:
                return FitEngine.resilient_dispatch(family)(
                    self.values, *args, **kwargs)
            eng = engine if engine is not None else default_engine()
            return eng.fit_resilient(self.values, family, *args, **kwargs)

    def auto_fit(self, max_p: int = 5, max_d: int = 2, max_q: int = 5,
                 **kwargs):
        """Batched automatic ARIMA order selection over the whole panel —
        the :func:`~spark_timeseries_tpu.models.arima.auto_fit_panel`
        front door (ROADMAP item 1): per-series d by batched KPSS, the
        full (p, q) candidate grid fitted in one fused dispatch, on-device
        admissibility screening and AIC argmin, then a full-budget
        refinement of each series' winner.

        NaN-padded ragged panels (the ``from_observations`` + ``union``
        ingestion shape) auto-fit directly — each lane's valid window
        drives its d-selection, init, masked solve, and AIC sample size;
        lanes too short for the grid quarantine (NaN coefficients, +inf
        aic, orders (0,0,0)) instead of failing the panel.  ``kwargs``
        pass through (``max_iter``, ``screen_max_iter``).  Returns a
        :class:`~spark_timeseries_tpu.models.arima.PanelARIMAFit`;
        ``.model_for(i)`` materializes one series' winner as a standalone
        model."""
        from .models import arima
        with _metrics.span("panel.auto_fit"):
            return arima.auto_fit_panel(self.values, max_p=max_p,
                                        max_d=max_d, max_q=max_q, **kwargs)

    def stream_fit(self, family: str = "arima", *, engine=None, **kwargs):
        """Stream this panel's series through the engine's chunked fit
        pipeline (:meth:`~spark_timeseries_tpu.engine.FitEngine.stream_fit`):
        out-of-core chunking with prefetch overlap and per-chunk failure
        isolation, plus the opt-in durability tier — ``journal=path``
        for crash-consistent per-chunk commits with validated resume,
        ``deadline_s=`` for the per-chunk watchdog
        (``STS_CHUNK_DEADLINE_S``), ``retry=`` for quarantine/backoff
        retries of failed chunks, and OOM-adaptive chunk halving
        (``degrade=``).  ``resilient=True`` routes every chunk through
        the family's fail-soft fallback chain (``auto_order=`` included
        for arima) instead of the AOT executables, keeping the same
        durability scaffolding.  ``chunk_size``/``prefetch``/``collect``
        and the family's static fit parameters pass through.  Returns
        the engine's :class:`~spark_timeseries_tpu.engine.StreamResult`;
        an explicit :class:`~spark_timeseries_tpu.engine.FitEngine`
        instance overrides the process default."""
        from .engine import default_engine
        with _metrics.span("panel.stream_fit"):
            eng = engine if engine is not None else default_engine()
            return eng.stream_fit(self.values, family, **kwargs)

    def backtest(self, grid=None, **kwargs):
        """Rolling-origin backtest + per-series champion selection over
        this panel — the
        :func:`~spark_timeseries_tpu.backtest.backtest_panel` front
        door: every grid candidate is fitted once per series on the
        schedule's fit window (streamed through ``engine.stream_fit`` —
        journaled, deadline-guarded, labelled per candidate in
        ``sts_top``), every origin is replayed through the pinned-gain
        filter path, and sMAPE / MASE / RMSE / interval coverage are
        scored in-graph with NaN lanes masked.  ``grid`` a
        :class:`~spark_timeseries_tpu.backtest.CandidateGrid` (default:
        a modest AR/ARIMA/EWMA grid); schedule, selection, and
        streaming knobs pass through (``n_origins``, ``mode``,
        ``min_train``, ``select_by``, ``journal``, ...).  Returns a
        :class:`~spark_timeseries_tpu.backtest.BacktestReport`."""
        from .backtest import backtest_panel
        with _metrics.span("panel.backtest"):
            return backtest_panel(self.values, grid, **kwargs)

    def describe_costs(self, family: str = "arima") -> dict:
        """What would one compiled ``family`` fit of this panel cost?
        Asks XLA directly (``utils.costs.fit_cost_report`` at this
        panel's exact ``(n_series, n_obs)`` shape and dtype): FLOPs,
        bytes accessed, argument/output/temp/peak bytes, and HLO op
        counts — one compile, no data fitted.  Sections a backend does
        not expose come back as ``None`` markers (see the report's
        ``available`` block)."""
        from .utils import costs as _costs
        return _costs.fit_cost_report(family, self.n_series, self.n_obs,
                                      dtype=self.values.dtype)

    def series_stats(self) -> dict:
        """Per-series count/mean/stdev/min/max, NaN-aware — the StatCounter
        equivalent.  Returns a dict of ``(n_series,)`` numpy arrays."""
        v = self.values
        m = ~jnp.isnan(v)
        cnt = jnp.sum(m, axis=1)
        safe_cnt = jnp.maximum(cnt, 1)
        mean = jnp.sum(jnp.where(m, v, 0.0), axis=1) / safe_cnt
        var = jnp.sum(jnp.where(m, (v - mean[:, None]) ** 2, 0.0), axis=1) \
            / jnp.maximum(safe_cnt - 1, 1)
        big = jnp.inf
        return {
            "count": np.asarray(cnt),
            "mean": np.asarray(mean),
            "stdev": np.asarray(jnp.sqrt(var)),
            "min": np.asarray(jnp.min(jnp.where(m, v, big), axis=1)),
            "max": np.asarray(jnp.max(jnp.where(m, v, -big), axis=1)),
        }

    # -- instants / pandas bridges -------------------------------------------

    def to_instants(self) -> List[Tuple[Any, np.ndarray]]:
        """List of (datetime, cross-section vector) pairs
        (ref ``TimeSeries.scala:295-298`` / ``TimeSeriesRDD.scala:276-391``)."""
        tm = np.asarray(self.to_time_major())
        return [(self.index.datetime_at_loc(i), tm[i]) for i in range(self.n_obs)]

    def to_instants_dataframe(self):
        """Wide DataFrame: one row per instant, one column per key
        (ref ``TimeSeriesRDD.scala:399-413``)."""
        import pandas as pd
        df = pd.DataFrame(np.asarray(self.to_time_major()),
                          columns=[str(k) for k in self.keys])
        df.insert(0, "instant", self.index.to_datetime_array())
        return df

    def to_observations_dataframe(self, ts_col: str = "timestamp",
                                  key_col: str = "key",
                                  value_col: str = "value"):
        """Long-format DataFrame of (timestamp, key, value) observations,
        NaNs dropped (ref ``TimeSeriesRDD.scala:419-443``)."""
        import pandas as pd
        host = np.asarray(self.values)
        dts = np.array(self.index.to_datetime_array(), dtype=object)
        mask = ~np.isnan(host)
        s_idx, t_idx = np.nonzero(mask)
        return pd.DataFrame({
            ts_col: dts[t_idx],
            key_col: np.array([str(k) for k in self.keys], dtype=object)[s_idx],
            value_col: host[mask],
        })

    def to_pandas(self):
        """Wide pandas DataFrame indexed by datetime (keys as columns)."""
        import pandas as pd
        return pd.DataFrame(np.asarray(self.to_time_major()),
                            index=pd.DatetimeIndex(self.index.to_datetime_array()),
                            columns=[str(k) for k in self.keys])

    def collect(self) -> Tuple[List[Any], np.ndarray]:
        """Materialize (keys, values) on host
        (ref ``TimeSeriesRDD.scala:61-75`` collectAsTimeSeries)."""
        with _metrics.span("panel.collect"):
            host = np.asarray(self.values)
        _metrics.inc("panel.d2h_bytes", int(host.nbytes))
        return self.keys, host

    # -- constructors --------------------------------------------------------

    @staticmethod
    def from_series(pairs: Iterable[Tuple[Any, DateTimeIndex, np.ndarray]],
                    target_index: DateTimeIndex) -> "Panel":
        """Build from (key, index, values) triples, rebasing each onto
        ``target_index`` (ref ``TimeSeriesRDD.scala:657-666``)."""
        with _metrics.span("panel.from_series"):
            keys, rows = [], []
            for key, idx, vals in pairs:
                rb = _rebaser(idx, target_index, np.nan)
                keys.append(key)
                rows.append(rb(np.asarray(vals, dtype=np.float64)))
            _metrics.inc("panel.ingested_series", len(keys))
            return Panel(target_index, jnp.asarray(np.stack(rows)), keys)

    @staticmethod
    def from_observations(df, target_index: DateTimeIndex,
                          ts_col: str = "timestamp", key_col: str = "key",
                          value_col: str = "value") -> "Panel":
        """Long-format observations DataFrame → panel
        (ref ``TimeSeriesRDD.scala:694-745`` timeSeriesRDDFromObservations).

        The reference's key-hash shuffle + secondary sort + per-observation
        index lookup becomes three vectorized host steps: factorize keys,
        bulk-resolve timestamp locations, one scatter into the dense panel.
        """
        with _metrics.span("panel.from_observations"):
            keys_arr = np.asarray(df[key_col])
            uniq_keys, key_codes = np.unique(keys_arr, return_inverse=True)
            ts = df[ts_col]
            nanos = _timestamps_to_nanos(ts)
            locs = target_index.locs_at(nanos)
            vals = np.asarray(df[value_col], dtype=np.float64)
            data = np.full((len(uniq_keys), len(target_index)), np.nan)
            ok = locs >= 0
            data[key_codes[ok], locs[ok]] = vals[ok]
            _metrics.inc("panel.ingested_observations", int(len(vals)))
            _metrics.inc("panel.ingested_series", int(len(uniq_keys)))
            return Panel(target_index, jnp.asarray(data), list(uniq_keys))

    @staticmethod
    def from_pandas(df, target_index: Optional[DateTimeIndex] = None) -> "Panel":
        """Wide DataFrame (datetime index, one column per key) → panel."""
        if target_index is None:
            nanos = _timestamps_to_nanos(df.index)
            target_index = IrregularDateTimeIndex(nanos)
        return Panel(target_index,
                     jnp.asarray(df.to_numpy(dtype=np.float64).T),
                     list(df.columns))


def _timestamps_to_nanos(ts) -> np.ndarray:
    """Vectorized datetime-like → epoch-nanos int64."""
    import pandas as pd
    dtindex = pd.DatetimeIndex(ts)
    if dtindex.tz is not None:
        dtindex = dtindex.tz_convert("UTC").tz_localize(None)
    return dtindex.as_unit("ns").asi8.astype(np.int64)
