"""Level-2 static analysis: jaxpr/HLO contract checks per fit family.

``tools/sts_lint`` (level 1) reads the *source*; this module checks what
actually **lowers** — the ARIMA_PLUS lesson (PAPERS.md) that plan-time
validation beats runtime failure, applied to XLA instead of a query
planner.  Each family of the compiled surface — the ten fit families
plus the program tier (the health-monitored serving update, the
longseries combiner, the fleet coalesced pump, the backtest metric
kernel, and the pinned-gain replay primitive ``pinned_state_path``) —
is traced and lowered from ``jax.ShapeDtypeStruct`` specs (the
``utils.costs.representative_fit`` path — shapes only, no data, no
fitting) and three machine-checkable contracts are asserted:

- **no-f64** — under the default x64-off config, no operation in the
  jaxpr produces (or converts to) ``float64``/``complex128``.  Trivially
  true while x64 stays off; the contract exists so the day someone
  flips ``jax_enable_x64`` for a debugging session and leaks a
  wide-dtype constant into a fit path, ``make verify-static`` says so
  instead of a TPU run silently doubling its HBM traffic.
- **no-host-callback** — the traced program contains no callback/
  infeed/outfeed primitives and the lowered StableHLO no callback
  custom-calls.  This is PR 2's "fallback stages must not introduce
  host round-trips" promise, enforced: an ``io_callback`` smuggled into
  a resilient-fit stage fails here, not in a profile.
- **stable-jaxpr** — lowering the same family at two raw shapes in the
  same padding bucket (:func:`pad_bucket`) yields byte-identical jaxprs
  (equal :func:`jaxpr_fingerprint`).  Tracing twice must also be
  deterministic — a fingerprint that differs between two traces of the
  same spec means trace-time state (``id()``, dict order, RNG) leaked
  into the program, which is exactly a compile-cache miss in production.

``check_all`` returns the summary block ``bench.py`` embeds
(``contracts_checked`` / ``contracts_failed`` / per-family detail);
``python -m spark_timeseries_tpu.utils.contracts`` is the CLI
``make verify-static`` runs.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import re
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from . import metrics as _metrics

__all__ = ["pad_bucket", "jaxpr_fingerprint", "trace_family",
           "check_no_float64", "check_no_host_callbacks",
           "check_jaxpr_stability", "check_family", "check_all",
           "pipeline_contracts", "PIPELINE_PROGRAM_BUDGET",
           "ContractResult", "CONTRACT_FAMILIES"]

# the same families utils.costs knows how to lower (ten fits + the
# serving/long/fleet/backtest/replay program tier)
from .costs import COST_FAMILIES as CONTRACT_FAMILIES  # noqa: E402

# padding-bucket policy: defined by the streaming fit engine (its hot
# path is what actually pads panels to buckets); re-exported here so the
# stable-jaxpr contract provably asserts the SAME policy the engine
# executes, and so `from utils.contracts import pad_bucket` keeps working.
from ..engine import (OBS_BUCKET_MULTIPLE,  # noqa: E402,F401
                      SERIES_BUCKET_FLOOR, pad_bucket)

# jaxpr primitives that reach back to the host at runtime
_CALLBACK_PRIMITIVES = ("pure_callback", "io_callback", "debug_callback",
                        "host_callback", "outside_call", "infeed",
                        "outfeed", "debug_print")
# custom-call targets in lowered StableHLO that imply a host round-trip
# (lapack/sharding custom-calls are fine and common on CPU)
_CALLBACK_TARGET_MARKERS = ("callback", "infeed", "outfeed",
                            "xla_python", "py_func")

_WIDE_DTYPES = ("float64", "complex128")


@dataclass
class ContractResult:
    contract: str
    family: str
    ok: bool
    detail: str = ""

    def to_json(self) -> Dict[str, Any]:
        return {"contract": self.contract, "family": self.family,
                "ok": self.ok, "detail": self.detail}


def trace_family(family: str, n_series: int, n_obs: int, dtype=None):
    """ClosedJaxpr of one representative batched fit, traced from
    ShapeDtypeStructs (no data, no compile)."""
    import jax

    from .costs import representative_fit
    fn, args = representative_fit(family, n_series, n_obs, dtype)
    return jax.make_jaxpr(fn)(*args)


# `custom_jvp_call` eqn params embed helper-function *reprs*
# (`jvp_jaxpr_thunk=<function _memoize.<locals>.memoized at 0x7f...>`);
# the thunk only matters to autodiff bookkeeping and its address is
# fresh per trace, so hashing it verbatim would flag every family that
# touches jax.scipy.special (garch/argarch via logit) as unstable while
# the lowered program is byte-identical.  Strip object reprs before
# hashing — the fingerprint must cover the *program*, not incidental
# Python object identities.
_OBJ_REPR_RE = re.compile(r"<[\w .<>]+ at 0x[0-9a-fA-F]+>")


def jaxpr_fingerprint(closed_jaxpr) -> str:
    """sha256 of the printed jaxpr (object addresses masked) — var names
    are assigned deterministically per trace, so equal programs print
    equally."""
    text = _OBJ_REPR_RE.sub("<obj>", str(closed_jaxpr))
    return hashlib.sha256(text.encode()).hexdigest()


def _iter_eqns(jaxpr) -> Iterator[Any]:
    """Every eqn, recursing through sub-jaxprs in eqn params (scan/while
    bodies, cond branches, closed calls, custom-derivative rules)."""
    stack = [jaxpr]
    seen = set()
    while stack:
        jx = stack.pop()
        if id(jx) in seen:
            continue
        seen.add(id(jx))
        for eqn in jx.eqns:
            yield eqn
            for val in eqn.params.values():
                stack.extend(_sub_jaxprs(val))


def _sub_jaxprs(val) -> List[Any]:
    out = []
    if hasattr(val, "jaxpr"):           # ClosedJaxpr
        out.append(val.jaxpr)
    elif hasattr(val, "eqns"):          # bare Jaxpr
        out.append(val)
    elif isinstance(val, (list, tuple)):
        for v in val:
            out.extend(_sub_jaxprs(v))
    return out


def _wide_vars(jaxpr) -> List[str]:
    hits = []
    for eqn in _iter_eqns(jaxpr):
        for var in list(eqn.invars) + list(eqn.outvars):
            dt = getattr(getattr(var, "aval", None), "dtype", None)
            if dt is not None and str(dt) in _WIDE_DTYPES:
                hits.append(f"{eqn.primitive.name}: {dt}")
        nd = eqn.params.get("new_dtype")
        if nd is not None and str(nd) in _WIDE_DTYPES:
            hits.append(f"{eqn.primitive.name}: new_dtype={nd}")
    return hits


def check_no_float64(family: str, n_series: int = 8, n_obs: int = 64,
                     closed_jaxpr=None) -> ContractResult:
    """No float64/complex128 anywhere in the traced program (x64-off
    default config)."""
    import jax

    if bool(jax.config.jax_enable_x64):
        # the contract is defined against the *default* x64-off config
        # (ISSUE 4); with x64 deliberately on (bench's degraded CPU
        # baseline runs f64 for reference parity) wide dtypes are the
        # requested behavior, not a leak
        return ContractResult(
            "no-f64", family, True,
            "skipped: x64 enabled — contract applies to the x64-off "
            "default config")
    if closed_jaxpr is None:
        closed_jaxpr = trace_family(family, n_series, n_obs)
    hits = _wide_vars(closed_jaxpr.jaxpr)
    if hits:
        return ContractResult(
            "no-f64", family, False,
            f"{len(hits)} wide-dtype value(s) in the jaxpr (x64=off): "
            f"{hits[:5]}")
    return ContractResult("no-f64", family, True,
                          f"jaxpr free of {'/'.join(_WIDE_DTYPES)}")


def check_no_host_callbacks(family: str, n_series: int = 8,
                            n_obs: int = 64, closed_jaxpr=None,
                            lowered_text: Optional[str] = None
                            ) -> ContractResult:
    """No callback/infeed/outfeed primitives in the jaxpr and no
    callback custom-calls in the lowered module."""
    import jax

    if closed_jaxpr is None:
        closed_jaxpr = trace_family(family, n_series, n_obs)
    prim_hits = [eqn.primitive.name for eqn in _iter_eqns(closed_jaxpr.jaxpr)
                 if any(m in eqn.primitive.name
                        for m in _CALLBACK_PRIMITIVES)]
    if prim_hits:
        return ContractResult(
            "no-host-callback", family, False,
            f"callback primitive(s) in jaxpr: {sorted(set(prim_hits))}")
    if lowered_text is None:
        from .costs import representative_fit
        fn, args = representative_fit(family, n_series, n_obs)
        lowered_text = jax.jit(fn).lower(*args).as_text()
    text_hits = []
    for line in lowered_text.splitlines():
        if "custom_call" not in line:
            continue
        low = line.lower()
        if any(m in low for m in _CALLBACK_TARGET_MARKERS):
            text_hits.append(line.strip()[:120])
    if text_hits:
        return ContractResult(
            "no-host-callback", family, False,
            f"callback custom-call(s) in lowered module: {text_hits[:3]}")
    return ContractResult("no-host-callback", family, True,
                          "no callback primitives or custom-calls")


def check_jaxpr_stability(family: str,
                          shape_a: Tuple[int, int] = (5, 50),
                          shape_b: Tuple[int, int] = (8, 61),
                          closed_jaxpr=None,
                          closed_shape: Optional[Tuple[int, int]] = None
                          ) -> ContractResult:
    """Two raw shapes in the same padding bucket must trace to
    byte-identical jaxprs (= one compile-cache entry).  The two raw
    shapes are padded with :func:`pad_bucket` first; the check also
    catches nondeterministic tracing, since each padded spec is traced
    independently."""
    bucket_a = pad_bucket(*shape_a)
    bucket_b = pad_bucket(*shape_b)
    if bucket_a != bucket_b:
        return ContractResult(
            "stable-jaxpr", family, False,
            f"test shapes {shape_a}/{shape_b} fall in different buckets "
            f"{bucket_a}/{bucket_b} — fix the test shapes")
    if closed_jaxpr is not None and closed_shape == bucket_a:
        # an already-traced program at exactly the bucket shape serves
        # as trace #1; the independent re-trace below still probes
        # determinism
        fp_a = jaxpr_fingerprint(closed_jaxpr)
    else:
        fp_a = jaxpr_fingerprint(trace_family(family, *bucket_a))
    fp_b = jaxpr_fingerprint(trace_family(family, *bucket_b))
    if fp_a != fp_b:
        return ContractResult(
            "stable-jaxpr", family, False,
            f"same padded bucket {bucket_a} traced to different jaxprs "
            f"({fp_a[:12]} != {fp_b[:12]}): trace-time state leaks into "
            f"the program — every fit at this shape recompiles")
    return ContractResult(
        "stable-jaxpr", family, True,
        f"bucket {bucket_a} fingerprint {fp_a[:12]} stable across "
        f"independent traces")


def check_family(family: str, n_series: int = 8, n_obs: int = 64
                 ) -> List[ContractResult]:
    """All three contracts for one family, sharing a single trace for
    the jaxpr-level checks (stability pays its own two traces)."""
    with _metrics.span(f"contracts.{family}"):
        try:
            closed = trace_family(family, n_series, n_obs)
        except Exception as e:  # noqa: BLE001 — a family that cannot
            # trace fails every contract with the reason, not a crash
            err = f"trace failed: {type(e).__name__}: {e}"
            return [ContractResult(c, family, False, err)
                    for c in ("no-f64", "no-host-callback",
                              "stable-jaxpr")]
        results = [
            check_no_float64(family, n_series, n_obs, closed_jaxpr=closed),
            check_no_host_callbacks(family, n_series, n_obs,
                                    closed_jaxpr=closed),
            check_jaxpr_stability(family, closed_jaxpr=closed,
                                  closed_shape=(n_series, n_obs)),
        ]
    return results


def check_all(families: Optional[Sequence[str]] = None,
              n_series: int = 8, n_obs: int = 64) -> Dict[str, Any]:
    """Contract sweep; returns the summary block bench.py embeds."""
    import jax

    fams = list(families) if families else list(CONTRACT_FAMILIES)
    results: List[ContractResult] = []
    for fam in fams:
        results.extend(check_family(fam, n_series, n_obs))
    failed = [r for r in results if not r.ok]
    return {
        "contracts_checked": len(results),
        "contracts_failed": len(failed),
        "families": fams,
        "platform": jax.default_backend(),
        "x64": bool(jax.config.jax_enable_x64),
        "failures": [r.to_json() for r in failed],
        "results": [r.to_json() for r in results],
    }


# ---------------------------------------------------------------------------
# host-boundary contracts: the warmed chunk path, end to end
# ---------------------------------------------------------------------------

# Distinct compiled programs each warmed pipeline stage is allowed to
# run.  The fit budget is the bucketed-cache promise (one executable per
# (family, bucket, variant) — a panel that divides evenly into chunks
# reuses one program for every chunk); the serving budget is the single
# coalesced per-tick update executable.  Raising a number here is a
# reviewed decision, exactly like extending the sanctioned-materialize
# table in tools/sts_lint/rules.py.
PIPELINE_PROGRAM_BUDGET: Dict[str, int] = {
    "fit": 1,
    "serving": 1,
    # the fused fit_long combination (docs/design.md §6e): every segment
    # chunk is padded to one width, so the whole fit→combine runs ONE
    # executable — enforced as warm-compiles-nothing below (all warm
    # dispatches share a single (shape, statics) jit key)
    "fit_long": 1,
}


def pipeline_contracts(family: str = "ewma", n_series: int = 256,
                       n_obs: int = 64, chunk: int = 128,
                       serving_family: str = "arima",
                       serving_n_series: int = 8) -> Dict[str, Any]:
    """Level-2 host-boundary contracts (the STS200 tier's runtime half).

    Runs the chunked fit path cold then warm on a fresh engine with a
    private metrics registry, plus a cold/warm serving-tier warmup, and
    pins three things the lint can only approximate from source:

    - **programs-per-stage** — the cold run's distinct compiled
      programs per stage stay within :data:`PIPELINE_PROGRAM_BUDGET`
      (fit: the engine's own ``engine.cache_misses`` counter — exact
      and process-history-independent);
    - **warm-path-compiles-nothing** — the warm repeat of both stages
      triggers zero XLA backend compiles and zero executable-cache
      misses (the ``jax.monitoring`` hooks in :mod:`utils.metrics`);
    - **transferred-bytes-per-warmed-chunk** — the engine-counted
      ``engine.bytes_d2h`` moved per warmed chunk equals
      :func:`~spark_timeseries_tpu.engine.expected_chunk_result_bytes`
      exactly: 0 unexpected bytes beyond sanctioned result
      materialization.

    Returns the ``static_analysis.boundary`` block ``bench.py`` embeds
    and ``tools/bench_gate.py`` gates (``pipeline_programs``,
    ``host_transfer_bytes_per_chunk``).
    """
    import numpy as np

    from ..engine import FitEngine, expected_chunk_result_bytes
    from ..statespace.serving import warmup_update
    from .metrics import (MetricsRegistry, install_jax_hooks,
                          jax_stats)

    if n_series % chunk:
        raise ValueError(
            f"n_series={n_series} must divide into chunk={chunk} whole "
            f"chunks — a ragged tail adds a second (tail-bucket) "
            f"executable and the budget below pins the steady state")

    reg = MetricsRegistry()
    hooks = install_jax_hooks(reg)
    eng = FitEngine(registry=reg)

    def counters() -> Dict[str, int]:
        return {k: int(v) for k, v in
                reg.snapshot()["counters"].items()}

    results: List[ContractResult] = []
    with _metrics.span("contracts.pipeline"):
        # --- fit stage: cold stream (compiles), then warm stream ------
        grid = np.arange(n_series * n_obs, dtype=np.float32)
        values = np.sin(grid).reshape(n_series, n_obs) + 2.0
        eng.stream_fit(values, family, chunk_size=chunk, fused=True)
        c0 = counters()
        fit_programs = c0.get("engine.cache_misses", 0)
        eng.stream_fit(values, family, chunk_size=chunk, fused=True)
        c1 = counters()

        n_chunks = n_series // chunk
        warm_compiles = c1.get("jax.jit_compiles", 0) \
            - c0.get("jax.jit_compiles", 0)
        warm_misses = c1.get("engine.cache_misses", 0) - fit_programs
        warm_bytes = c1.get("engine.bytes_d2h", 0) \
            - c0.get("engine.bytes_d2h", 0)
        expected = expected_chunk_result_bytes(family, (chunk, n_obs),
                                               dtype=values.dtype)
        per_chunk = warm_bytes // n_chunks
        unexpected = warm_bytes - n_chunks * expected

        budget = PIPELINE_PROGRAM_BUDGET["fit"]
        results.append(ContractResult(
            "pipeline-programs", "fit", fit_programs <= budget,
            f"{fit_programs} compiled program(s) for {n_chunks} chunks "
            f"(budget {budget})"))
        results.append(ContractResult(
            "pipeline-warm-nocompile", "fit",
            warm_misses == 0 and (not hooks or warm_compiles == 0),
            f"warm re-stream: {warm_misses} cache miss(es), "
            f"{warm_compiles} backend compile(s)"))
        results.append(ContractResult(
            "pipeline-transfer-bytes", "fit", unexpected == 0,
            f"{per_chunk} B/chunk materialized over {n_chunks} warmed "
            f"chunk(s), expected {expected} B "
            f"({unexpected:+d} B unsanctioned)"))

        # --- fit_long stage: fused fit→combine, cold then warm --------
        # (docs/design.md §6e) every chunk padded to one width → one
        # executable; the warm repeat must compile nothing and the ONLY
        # crossing is the final accumulator pull, byte-exact
        from ..longseries.combine import (expected_combine_acc_bytes,
                                          fused_fit_combine)
        greg = _metrics.get_registry()

        def gcounters() -> Dict[str, int]:
            return {k: int(v) for k, v in
                    greg.snapshot()["counters"].items()}

        seg_panel = np.sin(
            np.arange(8 * 64, dtype=np.float32)).reshape(8, 64) + 2.0
        long_kw = dict(p=1, q=0, n_ar=1, chunk_segments=4, max_iter=8)
        fused_fit_combine(seg_panel, **long_kw)
        l0, g0 = counters(), gcounters()
        fused_fit_combine(seg_panel, **long_kw)
        l1, g1 = counters(), gcounters()
        long_warm_compiles = l1.get("jax.jit_compiles", 0) \
            - l0.get("jax.jit_compiles", 0)
        long_programs = g1.get("longseries.fused_programs", 0) \
            - g0.get("longseries.fused_programs", 0)
        long_bytes = g1.get("longseries.fused_bytes_d2h", 0) \
            - g0.get("longseries.fused_bytes_d2h", 0)
        long_expected = expected_combine_acc_bytes(
            1, True, seg_panel.dtype)
        results.append(ContractResult(
            "pipeline-warm-nocompile", "fit_long",
            (not hooks or long_warm_compiles == 0) and long_programs == 2,
            f"warm fused fit→combine: {long_warm_compiles} backend "
            f"compile(s) over {long_programs} chunk dispatch(es) — one "
            f"executable serves every chunk (budget "
            f"{PIPELINE_PROGRAM_BUDGET['fit_long']})"))
        results.append(ContractResult(
            "pipeline-transfer-bytes", "fit_long",
            long_bytes == long_expected,
            f"{long_bytes} B materialized by the warm fused "
            f"combination, expected {long_expected} B (the one "
            f"accumulator pull)"))

        # --- serving stage: cold warmup compiles, warm repeat doesn't -
        s0 = counters()
        warmup_update(serving_family, serving_n_series)
        s1 = counters()
        warmup_update(serving_family, serving_n_series)
        s2 = counters()
        serving_cold = s1.get("jax.jit_compiles", 0) \
            - s0.get("jax.jit_compiles", 0)
        serving_warm = s2.get("jax.jit_compiles", 0) \
            - s1.get("jax.jit_compiles", 0)
        results.append(ContractResult(
            "pipeline-warm-nocompile", "serving",
            not hooks or serving_warm == 0,
            f"warm tick-update warmup: {serving_warm} backend "
            f"compile(s) (cold: {serving_cold})"))

    failed = [r for r in results if not r.ok]
    return {
        # the gated aggregate: the warmed pipeline's program count by
        # budget (fit measured exactly; serving's jit-cache is process-
        # global, so its measured cold count depends on history — the
        # warm==0 contract is the enforced half)
        "pipeline_programs": fit_programs
        + PIPELINE_PROGRAM_BUDGET["serving"],
        "programs_budget": dict(PIPELINE_PROGRAM_BUDGET),
        "host_transfer_bytes_per_chunk": int(per_chunk),
        "expected_result_bytes": int(expected),
        "unexpected_transfer_bytes": int(unexpected),
        "n_chunks": int(n_chunks),
        "fit_programs": int(fit_programs),
        "fit_warm_compiles": int(warm_compiles),
        "serving_cold_compiles": int(serving_cold),
        "serving_warm_compiles": int(serving_warm),
        "fit_long_warm_compiles": int(long_warm_compiles),
        "fit_long_programs": int(long_programs),
        "fit_long_bytes_d2h": int(long_bytes),
        "fit_long_expected_bytes": int(long_expected),
        "jax_hooks": bool(hooks),
        "transfer_events": jax_stats(reg)["transfers"],
        "boundary_checked": len(results),
        "boundary_failed": len(failed),
        "results": [r.to_json() for r in results],
        "ok": not failed,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_timeseries_tpu.utils.contracts",
        description="jaxpr/HLO contract checks per fit family "
                    "(no-f64, no-host-callback, stable-jaxpr).")
    ap.add_argument("--families", default=None,
                    help="comma-separated subset "
                         f"(default: all {len(CONTRACT_FAMILIES)})")
    ap.add_argument("--shape", default="8x64",
                    help="representative raw shape n_series x n_obs "
                         "(default 8x64)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the JSON report here ('-' = stdout)")
    ap.add_argument("--no-pipeline", action="store_true",
                    help="skip the host-boundary pipeline contracts "
                         "(program budget + transfer bytes; these "
                         "compile and run the chunk path)")
    args = ap.parse_args(argv)

    fams = [f for f in (args.families or "").split(",") if f] or None
    if fams:
        unknown = [f for f in fams if f not in CONTRACT_FAMILIES]
        if unknown:
            ap.error(f"unknown families: {unknown}; expected subset of "
                     f"{sorted(CONTRACT_FAMILIES)}")
    try:
        ns, no = (int(x) for x in args.shape.lower().split("x"))
        if ns < 1 or no < 1:
            raise ValueError
    except ValueError:
        ap.error(f"--shape must be <n_series>x<n_obs> with positive "
                 f"ints, got {args.shape!r}")

    report = check_all(fams, ns, no)
    for r in report["results"]:
        mark = "PASS" if r["ok"] else "FAIL"
        print(f"{mark} {r['family']:>18s} {r['contract']:<17s} "
              f"{r['detail']}")
    if not args.no_pipeline:
        boundary = pipeline_contracts()
        report["boundary"] = boundary
        for r in boundary["results"]:
            mark = "PASS" if r["ok"] else "FAIL"
            print(f"{mark} {r['family']:>18s} {r['contract']:<17s} "
                  f"{r['detail']}")
        print(f"boundary: {boundary['pipeline_programs']} pipeline "
              f"program(s) (budget "
              f"{sum(boundary['programs_budget'].values())}), "
              f"{boundary['host_transfer_bytes_per_chunk']} B/chunk "
              f"device→host ({boundary['unexpected_transfer_bytes']:+d} "
              f"B unsanctioned)")
    print(f"contracts: {report['contracts_checked']} checked, "
          f"{report['contracts_failed']} failed "
          f"(platform={report['platform']}, "
          f"x64={'on' if report['x64'] else 'off'})")
    if args.json_out:
        payload = json.dumps(report, indent=1)
        if args.json_out == "-":
            print(payload)
        else:
            with open(args.json_out, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    boundary_failed = report.get("boundary", {}).get("boundary_failed", 0)
    return 1 if (report["contracts_failed"] or boundary_failed) else 0


if __name__ == "__main__":
    sys.exit(main())
