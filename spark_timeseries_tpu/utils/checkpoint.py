"""Checkpoint/restore for model pytrees and panels.

The reference has no model persistence at all (constructor args are the
state; SURVEY.md §5 "checkpoint/resume") and delegates fault tolerance to
Spark lineage re-execution.  Here every fitted model is a pytree of arrays
plus static metadata (orders, flags, model-type strings), so checkpointing
writes the arrays to one ``.npz`` and a JSON *structure* sidecar that is
sufficient to rebuild the tree — restore needs no caller-side knowledge of
leaf order or model internals, and restart semantics are "re-run the batched
fit for any shard not in the checkpoint" (per-batch fits are idempotent).

Supported node types: numpy/JAX arrays, Python scalars (int/float/bool/str/
None), lists, tuples, dicts with string keys, and NamedTuples (recorded by
import path and re-imported on load — which covers every model class in
``spark_timeseries_tpu.models``).

Restore validates every array leaf against the shape/dtype the structure
sidecar recorded at save time and raises :class:`CheckpointMismatchError`
(a ``ValueError``) on any disagreement — a truncated ``.npz`` or a sidecar
paired with the wrong array file surfaces as one clear error instead of a
cryptic reshape failure mid-fit.  Sidecars written before the metadata was
recorded restore unvalidated, as before.
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Any

import jax
import numpy as np


class CheckpointMismatchError(ValueError):
    """A checkpoint's stored arrays disagree with its structure sidecar
    (shape, dtype, or leaf count) — corruption or a stale re-save.  Raised
    eagerly on restore so the mismatch surfaces as one clear error instead
    of a cryptic reshape/broadcast failure mid-fit."""


def _is_namedtuple(node: Any) -> bool:
    return isinstance(node, tuple) and hasattr(node, "_fields")


def _arr_spec(arrays: list, a: np.ndarray) -> dict:
    arrays.append(a)
    return {"k": "arr", "i": len(arrays) - 1,
            "shape": list(a.shape), "dtype": str(a.dtype)}


def _encode(node: Any, arrays: list) -> Any:
    """Recursively encode a pytree into a JSON-able structure spec; array
    leaves are appended to ``arrays`` and referenced by position, with
    shape/dtype recorded for restore-time validation."""
    if isinstance(node, (np.ndarray, jax.Array)):
        return _arr_spec(arrays, np.asarray(node))
    if isinstance(node, np.generic):            # numpy scalar -> 0-d array
        return _arr_spec(arrays, np.asarray(node))
    if node is None or isinstance(node, (bool, int, float, str)):
        return {"k": "py", "v": node}
    if _is_namedtuple(node):
        cls = type(node)
        return {"k": "nt", "cls": f"{cls.__module__}:{cls.__qualname__}",
                "items": [_encode(v, arrays) for v in node]}
    if isinstance(node, tuple):
        return {"k": "tuple", "items": [_encode(v, arrays) for v in node]}
    if isinstance(node, list):
        return {"k": "list", "items": [_encode(v, arrays) for v in node]}
    if isinstance(node, dict):
        if not all(isinstance(key, str) for key in node):
            raise TypeError("checkpoint dicts must have string keys")
        return {"k": "dict",
                "items": {key: _encode(v, arrays) for key, v in node.items()}}
    raise TypeError(f"cannot checkpoint node of type {type(node).__name__}")


def _decode(spec: Any, arrays: dict) -> Any:
    kind = spec["k"]
    if kind == "arr":
        name = f"leaf_{spec['i']}"
        if name not in arrays:
            raise CheckpointMismatchError(
                f"checkpoint structure references {name} but the .npz holds "
                f"only {len(arrays)} leaves — the sidecar and array file "
                f"are out of sync (re-save the checkpoint)")
        arr = arrays[name]
        # shape/dtype were recorded at save time (format >= 2 with metadata);
        # older sidecars without them restore unvalidated as before
        want_shape = spec.get("shape")
        if want_shape is not None and list(arr.shape) != list(want_shape):
            raise CheckpointMismatchError(
                f"checkpoint leaf {name} has shape {tuple(arr.shape)} but "
                f"the structure sidecar recorded {tuple(want_shape)} — the "
                f".npz does not belong to this .tree.json")
        want_dtype = spec.get("dtype")
        if want_dtype is not None and str(arr.dtype) != want_dtype:
            raise CheckpointMismatchError(
                f"checkpoint leaf {name} has dtype {arr.dtype} but the "
                f"structure sidecar recorded {want_dtype} — the .npz does "
                f"not belong to this .tree.json")
        return arr
    if kind == "py":
        return spec["v"]
    if kind == "nt":
        mod_name, _, qualname = spec["cls"].partition(":")
        obj = importlib.import_module(mod_name)
        for part in qualname.split("."):
            obj = getattr(obj, part)
        # the sidecar names an import path; only ever call an actual
        # NamedTuple class, never an arbitrary resolved callable
        if not (isinstance(obj, type) and issubclass(obj, tuple)
                and hasattr(obj, "_fields")):
            raise ValueError(
                f"checkpoint names {spec['cls']!r}, which is not a "
                "NamedTuple class — refusing to call it")
        return obj(*(_decode(s, arrays) for s in spec["items"]))
    if kind == "tuple":
        return tuple(_decode(s, arrays) for s in spec["items"])
    if kind == "list":
        return [_decode(s, arrays) for s in spec["items"]]
    if kind == "dict":
        return {key: _decode(s, arrays) for key, s in spec["items"].items()}
    raise ValueError(f"unknown checkpoint node kind {kind!r}")


def save_pytree(path: str, tree: Any) -> None:
    """Save an arbitrary pytree as ``<path>.npz`` (array leaves) plus a
    ``<path>.tree.json`` structure sidecar that fully describes the tree."""
    arrays: list = []
    spec = _encode(tree, arrays)
    np.savez(path + ".npz", **{f"leaf_{i}": a for i, a in enumerate(arrays)})
    with open(path + ".tree.json", "w") as f:
        json.dump({"format": 2, "spec": spec, "n_leaves": len(arrays)}, f)


def _fsync_replace(tmp: str, dst: str) -> None:
    with open(tmp, "rb+") as f:
        os.fsync(f.fileno())
    os.replace(tmp, dst)


def save_pytree_atomic(path: str, tree: Any) -> None:
    """:func:`save_pytree` through tmp-file + fsync + rename: a crash at
    any instant leaves either the previous files or the new ones at
    ``path``, never a torn ``.npz``/``.tree.json``.  The two renames are
    individually atomic but not as a pair — a caller that needs the pair
    committed as a unit writes its own marker after both (the chunk
    journal in ``utils.durability`` renames a ``.ok`` marker as its
    commit point)."""
    tmp = f"{path}.tmp-{os.getpid()}"
    save_pytree(tmp, tree)
    _fsync_replace(tmp + ".npz", path + ".npz")
    _fsync_replace(tmp + ".tree.json", path + ".tree.json")


def load_pytree(path: str) -> Any:
    """Rebuild the exact pytree saved by :func:`save_pytree` — structure,
    static Python fields, and array leaves — with no caller-side knowledge."""
    with open(path + ".tree.json") as f:
        meta = json.load(f)
    if "spec" not in meta:
        raise ValueError(
            f"{path}.tree.json is a format-1 checkpoint (opaque treedef); "
            "re-save it, or read the leaves directly with load_leaves()")
    with np.load(path + ".npz") as data:
        arrays = {name: data[name] for name in data.files}
    n_expected = meta.get("n_leaves")
    if n_expected is not None and len(arrays) != n_expected:
        raise CheckpointMismatchError(
            f"checkpoint {path!r} holds {len(arrays)} array leaves but its "
            f"structure sidecar recorded {n_expected} — the .npz and "
            f".tree.json are out of sync")
    return _decode(meta["spec"], arrays)


def load_leaves(path: str) -> list:
    """Load just the array leaves saved by :func:`save_pytree` (in order) —
    the escape hatch for format-1 checkpoints whose structure sidecar is
    opaque."""
    with np.load(path + ".npz") as data:
        return [data[f"leaf_{i}"] for i in range(len(data.files))]


def save_model(path: str, model: Any) -> None:
    """Save a model (NamedTuple pytree) with its class name recorded for
    sanity checks on restore."""
    save_pytree(path, model)
    with open(path + ".meta.json", "w") as f:
        json.dump({"class": type(model).__name__}, f)


def load_model(path: str, model_cls: type | None = None) -> Any:
    """Restore a model saved by :func:`save_model`; ``model_cls`` (optional)
    is checked against the recorded class name."""
    meta_path = path + ".meta.json"
    if model_cls is not None and os.path.exists(meta_path):
        with open(meta_path) as f:
            recorded = json.load(f).get("class")
        if recorded != model_cls.__name__:
            raise CheckpointMismatchError(
                f"checkpoint holds a {recorded}, not a {model_cls.__name__}")
    return load_pytree(path)
