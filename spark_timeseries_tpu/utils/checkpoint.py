"""Checkpoint/restore for model pytrees and panels.

The reference has no model persistence at all (constructor args are the
state; SURVEY.md §5 "checkpoint/resume") and delegates fault tolerance to
Spark lineage re-execution.  Here every fitted model is a pytree of arrays,
so checkpointing is orbax (or a plain ``.npz`` fallback) and restart
semantics are "re-run the batched fit for any shard not in the checkpoint"
— per-batch fits are idempotent.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np


def save_pytree(path: str, tree: Any) -> None:
    """Save an arbitrary pytree of arrays/scalars as ``<path>.npz`` plus a
    ``<path>.tree.json`` structure sidecar."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = {f"leaf_{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)}
    np.savez(path + ".npz", **arrays)
    with open(path + ".tree.json", "w") as f:
        json.dump({"treedef": str(treedef), "n_leaves": len(leaves)}, f)


def load_leaves(path: str) -> list:
    """Load the leaf arrays saved by :func:`save_pytree` (in order).  Callers
    rebuild their model types from the leaves (NamedTuple models: ``M(*leaves)``)."""
    with np.load(path + ".npz") as data:
        return [data[f"leaf_{i}"] for i in range(len(data.files))]


def save_model(path: str, model: Any) -> None:
    """Save a NamedTuple model with its class name recorded for sanity
    checks on restore."""
    save_pytree(path, tuple(model))
    with open(path + ".meta.json", "w") as f:
        json.dump({"class": type(model).__name__}, f)


def load_model(path: str, model_cls: type) -> Any:
    """Restore a NamedTuple model saved by :func:`save_model`."""
    meta_path = path + ".meta.json"
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            recorded = json.load(f).get("class")
        if recorded != model_cls.__name__:
            raise ValueError(
                f"checkpoint holds a {recorded}, not a {model_cls.__name__}")
    return model_cls(*load_leaves(path))
