"""Checkpoint/restore for model pytrees and panels.

The reference has no model persistence at all (constructor args are the
state; SURVEY.md §5 "checkpoint/resume") and delegates fault tolerance to
Spark lineage re-execution.  Here every fitted model is a pytree of arrays
plus static metadata (orders, flags, model-type strings), so checkpointing
writes the arrays to one ``.npz`` and a JSON *structure* sidecar that is
sufficient to rebuild the tree — restore needs no caller-side knowledge of
leaf order or model internals, and restart semantics are "re-run the batched
fit for any shard not in the checkpoint" (per-batch fits are idempotent).

Supported node types: numpy/JAX arrays, Python scalars (int/float/bool/str/
None), lists, tuples, dicts with string keys, and NamedTuples (recorded by
import path and re-imported on load — which covers every model class in
``spark_timeseries_tpu.models``).
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Any

import jax
import numpy as np


def _is_namedtuple(node: Any) -> bool:
    return isinstance(node, tuple) and hasattr(node, "_fields")


def _encode(node: Any, arrays: list) -> Any:
    """Recursively encode a pytree into a JSON-able structure spec; array
    leaves are appended to ``arrays`` and referenced by position."""
    if isinstance(node, (np.ndarray, jax.Array)):
        arrays.append(np.asarray(node))
        return {"k": "arr", "i": len(arrays) - 1}
    if isinstance(node, np.generic):            # numpy scalar -> 0-d array
        arrays.append(np.asarray(node))
        return {"k": "arr", "i": len(arrays) - 1}
    if node is None or isinstance(node, (bool, int, float, str)):
        return {"k": "py", "v": node}
    if _is_namedtuple(node):
        cls = type(node)
        return {"k": "nt", "cls": f"{cls.__module__}:{cls.__qualname__}",
                "items": [_encode(v, arrays) for v in node]}
    if isinstance(node, tuple):
        return {"k": "tuple", "items": [_encode(v, arrays) for v in node]}
    if isinstance(node, list):
        return {"k": "list", "items": [_encode(v, arrays) for v in node]}
    if isinstance(node, dict):
        if not all(isinstance(key, str) for key in node):
            raise TypeError("checkpoint dicts must have string keys")
        return {"k": "dict",
                "items": {key: _encode(v, arrays) for key, v in node.items()}}
    raise TypeError(f"cannot checkpoint node of type {type(node).__name__}")


def _decode(spec: Any, arrays: dict) -> Any:
    kind = spec["k"]
    if kind == "arr":
        return arrays[f"leaf_{spec['i']}"]
    if kind == "py":
        return spec["v"]
    if kind == "nt":
        mod_name, _, qualname = spec["cls"].partition(":")
        obj = importlib.import_module(mod_name)
        for part in qualname.split("."):
            obj = getattr(obj, part)
        # the sidecar names an import path; only ever call an actual
        # NamedTuple class, never an arbitrary resolved callable
        if not (isinstance(obj, type) and issubclass(obj, tuple)
                and hasattr(obj, "_fields")):
            raise ValueError(
                f"checkpoint names {spec['cls']!r}, which is not a "
                "NamedTuple class — refusing to call it")
        return obj(*(_decode(s, arrays) for s in spec["items"]))
    if kind == "tuple":
        return tuple(_decode(s, arrays) for s in spec["items"])
    if kind == "list":
        return [_decode(s, arrays) for s in spec["items"]]
    if kind == "dict":
        return {key: _decode(s, arrays) for key, s in spec["items"].items()}
    raise ValueError(f"unknown checkpoint node kind {kind!r}")


def save_pytree(path: str, tree: Any) -> None:
    """Save an arbitrary pytree as ``<path>.npz`` (array leaves) plus a
    ``<path>.tree.json`` structure sidecar that fully describes the tree."""
    arrays: list = []
    spec = _encode(tree, arrays)
    np.savez(path + ".npz", **{f"leaf_{i}": a for i, a in enumerate(arrays)})
    with open(path + ".tree.json", "w") as f:
        json.dump({"format": 2, "spec": spec, "n_leaves": len(arrays)}, f)


def load_pytree(path: str) -> Any:
    """Rebuild the exact pytree saved by :func:`save_pytree` — structure,
    static Python fields, and array leaves — with no caller-side knowledge."""
    with open(path + ".tree.json") as f:
        meta = json.load(f)
    if "spec" not in meta:
        raise ValueError(
            f"{path}.tree.json is a format-1 checkpoint (opaque treedef); "
            "re-save it, or read the leaves directly with load_leaves()")
    with np.load(path + ".npz") as data:
        arrays = {name: data[name] for name in data.files}
    return _decode(meta["spec"], arrays)


def load_leaves(path: str) -> list:
    """Load just the array leaves saved by :func:`save_pytree` (in order) —
    the escape hatch for format-1 checkpoints whose structure sidecar is
    opaque."""
    with np.load(path + ".npz") as data:
        return [data[f"leaf_{i}"] for i in range(len(data.files))]


def save_model(path: str, model: Any) -> None:
    """Save a model (NamedTuple pytree) with its class name recorded for
    sanity checks on restore."""
    save_pytree(path, model)
    with open(path + ".meta.json", "w") as f:
        json.dump({"class": type(model).__name__}, f)


def load_model(path: str, model_cls: type | None = None) -> Any:
    """Restore a model saved by :func:`save_model`; ``model_cls`` (optional)
    is checked against the recorded class name."""
    meta_path = path + ".meta.json"
    if model_cls is not None and os.path.exists(meta_path):
        with open(meta_path) as f:
            recorded = json.load(f).get("class")
        if recorded != model_cls.__name__:
            raise ValueError(
                f"checkpoint holds a {recorded}, not a {model_cls.__name__}")
    return load_pytree(path)
