"""Flight recorder: a forensic incident bundle for every failure.

The durability tier (docs/design.md §3c) makes a crashed job *resumable*
but not *explainable*: a dead chunk, an expired deadline, or an
unhandled exception leaves nothing behind except the journal and
whatever stdout survived.  This module is the black box — on every
incident the process writes a single self-contained JSON bundle to
``STS_INCIDENT_DIR`` carrying everything an operator needs for
post-mortem triage:

- the metrics **registry snapshot** (counters/gauges/histograms/spans
  at the instant of failure),
- the **trace ring** as Chrome trace JSON (the last
  ``STS_INCIDENT_TRACE_EVENTS`` events — load the bundle's ``trace``
  member in Perfetto to see exactly what ran before the death),
- the failing job's **JobProgress** (chunks done/failed/quarantined,
  heartbeat stage, EW cadence) plus every other active job,
- the **exception** (type, message, truncated traceback),
- the **journal manifest + committed ranges** when a journal is armed
  (read-only: bundle writing must never touch the journal itself — the
  resume path is sacred),
- **platform/config identity** (python, jax version/config if loaded,
  ``STS_*`` environment) so a bundle from a fleet machine is
  self-describing.

Bundles are written with the tmp+fsync+rename discipline from
:mod:`~spark_timeseries_tpu.utils.durability` (a bundle either exists
whole or not at all), into a bounded directory: the newest
``STS_INCIDENT_KEEP`` (default 20) bundles are kept, older ones pruned.
``incidents.written`` counts successful writes (``tools/bench_gate.py``
zero-baselines it — a bench round must not organically crash);
``incidents.errors`` counts recorder failures (the recorder itself must
never raise into the code it observes).

Trigger points (all host-side): chunk death and deadline expiry and
OOM-at-floor in ``engine.stream_fit``, heal failure in
``ServingSession.heal``, any unhandled exception escaping
``stream_fit``, and the ``kill_after_chunk`` fault (the bundle is
written immediately *before* the injected SIGKILL — the testable
stand-in for a crash; a real SIGKILL cannot run handlers by
definition, which is exactly why the recorder fires on every earlier
failure signal instead of relying on an exit hook).

Disabled (zero overhead, zero threads) unless ``STS_INCIDENT_DIR`` is
set or :func:`configure` names a directory.
"""

from __future__ import annotations

import json
import os
import platform as _platform
import sys
import time
import traceback as _traceback
from typing import Any, Dict, List, Optional

from . import durability as _durability
from . import metrics as _metrics
from . import telemetry as _telemetry

__all__ = [
    "INCIDENT_FORMAT", "DEFAULT_KEEP", "REQUIRED_KEYS",
    "configure", "incident_dir", "enabled",
    "record_incident", "list_incidents", "load_incident",
    "validate_bundle",
]

INCIDENT_FORMAT = 1

# newest-K retention (STS_INCIDENT_KEEP overrides)
DEFAULT_KEEP = 20

# newest trace-ring events embedded per bundle (STS_INCIDENT_TRACE_EVENTS
# overrides); the full 65536-event ring would make every bundle ~10 MB
DEFAULT_TRACE_EVENTS = 4096

# newest completed tick-lineage records embedded per bundle
# (STS_INCIDENT_LINEAGE_RECORDS overrides) — a crashed pump's recent
# in-flight ticks, stage by stage
DEFAULT_LINEAGE_RECORDS = 64

# top-level keys every schema-valid bundle must carry (the contract
# tests and sts_top validate against)
REQUIRED_KEYS = ("format", "kind", "time_unix", "time_iso", "pid",
                 "exception", "job", "jobs", "journal", "registry",
                 "trace", "config")

_PREFIX = "incident_"

_configured_dir: Optional[str] = None


def configure(path: Optional[str]) -> Optional[str]:
    """Set (or with None, clear) the incident directory in-process,
    overriding ``STS_INCIDENT_DIR``.  Returns the effective directory."""
    global _configured_dir
    _configured_dir = path
    return incident_dir()


def incident_dir() -> Optional[str]:
    """The armed incident directory, or None (recorder off)."""
    if _configured_dir:
        return _configured_dir
    return os.environ.get("STS_INCIDENT_DIR") or None


def enabled() -> bool:
    return incident_dir() is not None


def _keep() -> int:
    return _telemetry.env_positive("STS_INCIDENT_KEEP", int,
                                   DEFAULT_KEEP)


def _sanitize_kind(kind: str) -> str:
    return "".join(ch if ch.isalnum() or ch in "_-" else "_"
                   for ch in str(kind)) or "incident"


def _exception_block(exc: Optional[BaseException]) -> Optional[dict]:
    if exc is None:
        return None
    tb = "".join(_traceback.format_exception(type(exc), exc,
                                             exc.__traceback__))
    return {"type": type(exc).__name__,
            "message": str(exc)[:2000],
            "traceback": tb[-8000:]}


def _journal_block(journal_path: Optional[str]) -> Optional[dict]:
    """Read-only view of the armed journal: manifest + committed ranges.
    Pure reads — the recorder must never write inside the journal
    directory (corrupting the resume path to explain a crash would be
    the worst possible trade)."""
    if not journal_path or not os.path.isdir(journal_path):
        return None
    block: Dict[str, Any] = {"path": journal_path}
    try:
        mpath = os.path.join(journal_path,
                             _durability.ChunkJournal.MANIFEST)
        if os.path.exists(mpath):
            with open(mpath) as f:
                block["manifest"] = json.load(f)
        ranges = []
        for name in sorted(os.listdir(journal_path)):
            if name.endswith(".ok"):
                ranges.append(name[len("chunk_"):-len(".ok")])
        block["n_committed"] = len(ranges)
        block["committed"] = ranges[:64]
    except Exception as e:  # noqa: BLE001 — a half-readable journal
        # still yields a partial block, never a recorder failure
        block["read_error"] = f"{type(e).__name__}: {e}"
    return block


def _config_block() -> dict:
    cfg: Dict[str, Any] = {
        "python": sys.version.split()[0],
        "platform": _platform.platform(),
        "argv": sys.argv[:8],
        "env": {k: v for k, v in sorted(os.environ.items())
                if k.startswith(("STS_", "JAX_PLATFORMS", "XLA_FLAGS"))},
    }
    jx = sys.modules.get("jax")
    if jx is not None:
        cfg["jax_version"] = getattr(jx, "__version__", None)
        try:
            # config reads are safe; never call a backend-initializing
            # API (jax.devices / default_backend) from the recorder
            cfg["jax_platforms"] = jx.config.jax_platforms
            cfg["jax_enable_x64"] = bool(jx.config.jax_enable_x64)
        except Exception:  # noqa: BLE001 — config shape varies by jax
            pass
    return cfg


def _trace_block() -> dict:
    from . import tracing as _tracing

    # junk raises (the shared env_positive contract) — caught by
    # record_incident's no-raise guard and counted as incidents.errors,
    # the same "misconfigured recorder disables itself noisily" policy
    # as STS_INCIDENT_KEEP
    limit = _telemetry.env_positive("STS_INCIDENT_TRACE_EVENTS", int,
                                    DEFAULT_TRACE_EVENTS)
    return _tracing.to_chrome_trace(limit=limit)


def _lineage_block() -> dict:
    from . import lineage as _lineage

    limit = _telemetry.env_positive("STS_INCIDENT_LINEAGE_RECORDS", int,
                                    DEFAULT_LINEAGE_RECORDS)
    return _lineage.incident_block(limit=limit)


def record_incident(kind: str, *, exc: Optional[BaseException] = None,
                    job: Optional[Any] = None,
                    journal_path: Optional[str] = None,
                    extra: Optional[Dict[str, Any]] = None,
                    registry: Optional[Any] = None) -> Optional[str]:
    """Write one incident bundle; returns its path, or None when the
    recorder is off or the write failed (counted, never raised — the
    recorder must not take down the code it observes).

    ``job`` is the failing ``telemetry.JobProgress`` (every other
    active job is bundled too); ``extra`` is a JSON-able dict merged
    under the bundle's ``"extra"`` key (chunk geometry, failure
    records, fault names).
    """
    directory = incident_dir()
    if not directory:
        return None
    reg = registry if registry is not None else _metrics.get_registry()
    try:
        # parse retention up front: a misconfigured STS_INCIDENT_KEEP
        # must not leave a bundle the prune pass then can't bound
        keep = _keep()
        now = time.time()
        bundle: Dict[str, Any] = {
            "format": INCIDENT_FORMAT,
            "kind": str(kind),
            "time_unix": now,
            "time_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime(now)),
            "pid": os.getpid(),
            "exception": _exception_block(exc),
            "job": job.to_dict() if job is not None else None,
            "jobs": [p.to_dict() for p in _telemetry.active_jobs()],
            "journal": _journal_block(journal_path),
            "registry": _telemetry.json_safe(reg.snapshot()),
            "trace": _trace_block(),
            # optional (not in REQUIRED_KEYS: bundles from pre-lineage
            # builds stay schema-valid) — the newest completed tick
            # journeys at the moment of the incident
            "lineage": _lineage_block(),
            "config": _config_block(),
        }
        if extra is not None:
            bundle["extra"] = _telemetry.json_safe(extra)
        os.makedirs(directory, exist_ok=True)
        name = (f"{_PREFIX}{time.time_ns():020d}_{os.getpid()}_"
                f"{_sanitize_kind(kind)}.json")
        path = os.path.join(directory, name)
        _durability.atomic_write_json(path, bundle)
        reg.inc("incidents.written")
        _metrics.trace_instant("flightrec.incident",
                               {"kind": str(kind), "file": name})
        _prune(directory, keep)
        return path
    except Exception:  # noqa: BLE001 — see docstring
        try:
            reg.inc("incidents.errors")
        except Exception:  # noqa: BLE001 — truly last-resort
            pass
        return None


def _prune(directory: str, keep: int) -> None:
    names = sorted(n for n in os.listdir(directory)
                   if n.startswith(_PREFIX) and n.endswith(".json"))
    for name in names[:-keep] if len(names) > keep else []:
        try:
            os.remove(os.path.join(directory, name))
        except OSError:
            pass


def list_incidents(directory: Optional[str] = None,
                   limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """Newest-first index of bundles in the incident directory —
    filename-derived metadata only (kind, written-at, size), cheap
    enough for every ``/snapshot.json`` scrape."""
    d = directory or incident_dir()
    if not d or not os.path.isdir(d):
        return []
    names = sorted((n for n in os.listdir(d)
                    if n.startswith(_PREFIX) and n.endswith(".json")),
                   reverse=True)
    if limit is not None:
        names = names[:limit]
    out = []
    for name in names:
        parts = name[len(_PREFIX):-len(".json")].split("_", 2)
        entry: Dict[str, Any] = {"file": name,
                                 "path": os.path.join(d, name)}
        try:
            entry["time_unix"] = int(parts[0]) / 1e9
            entry["pid"] = int(parts[1])
            entry["kind"] = parts[2]
            entry["bytes"] = os.path.getsize(entry["path"])
        except (IndexError, ValueError, OSError):
            pass
        out.append(entry)
    return out


def load_incident(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def validate_bundle(bundle: Dict[str, Any]) -> List[str]:
    """Schema check: the list of violations (empty = schema-valid).
    The contract the acceptance tests (and any downstream triage
    tooling) pin."""
    problems = []
    if not isinstance(bundle, dict):
        return ["bundle is not a JSON object"]
    for key in REQUIRED_KEYS:
        if key not in bundle:
            problems.append(f"missing key {key!r}")
    if bundle.get("format") != INCIDENT_FORMAT:
        problems.append(f"format {bundle.get('format')!r} != "
                        f"{INCIDENT_FORMAT}")
    if not isinstance(bundle.get("kind"), str) or not bundle.get("kind"):
        problems.append("kind must be a non-empty string")
    if not isinstance(bundle.get("time_unix"), (int, float)):
        problems.append("time_unix must be a number")
    exc = bundle.get("exception")
    if exc is not None and (not isinstance(exc, dict)
                            or "type" not in exc
                            or "traceback" not in exc):
        problems.append("exception must be null or carry type/traceback")
    reg = bundle.get("registry")
    if not isinstance(reg, dict) or "counters" not in reg:
        problems.append("registry must be a snapshot dict with counters")
    tr = bundle.get("trace")
    if not isinstance(tr, dict) or "traceEvents" not in tr:
        problems.append("trace must be a Chrome trace object")
    if not isinstance(bundle.get("config"), dict):
        problems.append("config must be a dict")
    if not isinstance(bundle.get("jobs"), list):
        problems.append("jobs must be a list")
    # optional key (absent from pre-lineage bundles): validated only
    # when present, so old incidents stay schema-valid forever
    lin = bundle.get("lineage")
    if lin is not None and (not isinstance(lin, dict)
                            or "records" not in lin):
        problems.append("lineage, when present, must carry records")
    return problems
