"""Per-series failure isolation for batched fits: health classification,
retry policies, fallback chains, and a fault-injection harness.

The reference isolates failures per series for free — each ``mapValues``
closure fits one series, and a throw kills one task (ref
``/root/reference/src/main/scala/com/cloudera/sparkts/models/ARIMA.scala:315-319``
wraps candidate fits in ``Try``).  A batched TPU fit has no such boundary:
one all-NaN, constant, too-short, or divergence-inducing lane shares the
compiled program with a million healthy ones, so isolation must be built
from masks and explicit per-lane status instead of exceptions (SURVEY.md §7
hard part #3; PAPERS.md "Distributed ARIMA Models for Ultra-long Time
Series" and "ARIMA_PLUS" both treat per-series fallback as a prerequisite
for production-scale forecasting).

Three layers, composable and individually usable:

- **health classification** (:func:`classify_series`) — one vectorized pass
  labels every lane ok / all-NaN / constant / too-short / has-inf /
  interior-gap before any optimizer runs; unfittable lanes are *skipped
  with a status*, never raised on;
- **multi-start retry** (:class:`RetryPolicy`, consumed by the
  ``ops.optimize`` minimizers) — non-converged or non-finite lanes re-solve
  from jittered inits inside the batched computation (a ``lax.while`` over
  restarts with per-lane threaded PRNG keys; no host round-trips), and the
  per-lane attempt count comes back in ``MinimizeResult.attempts``;
- **fallback chains** (:func:`resilient_fit`, surfaced per model family as
  ``fit_resilient`` and on :class:`~spark_timeseries_tpu.panel.Panel`) — a
  declarative list of progressively simpler fits (e.g. ARIMA(p,d,q) →
  AR(p) → drift/mean) applied only to still-failed lanes, gather/scatter
  compacted so cost scales with the failed fraction, not the panel.

Every disposition is counted into the PR-1 metrics registry under
``resilience.*`` so bench artifacts record fraction-recovered,
fraction-fallback, and fraction-abandoned alongside throughput.

The :func:`fault_injection` context manager deterministically corrupts
inputs or forces optimizer non-convergence so all of the above is testable
without hunting for naturally pathological data; ``STS_FAULT_INJECT=1``
(the ``make verify-faults`` CI mode) activates a default
first-attempt-fails fault inside every ``resilient_fit`` call, driving the
retry path on every resilient fit while leaving plain fits untouched.
"""

from __future__ import annotations

import contextlib
import itertools
import os
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import metrics as _metrics

__all__ = [
    "HEALTH_OK", "HEALTH_ALL_NAN", "HEALTH_CONSTANT", "HEALTH_TOO_SHORT",
    "HEALTH_HAS_INF", "HEALTH_INTERIOR_GAP", "HEALTH_NAMES",
    "STATUS_OK", "STATUS_RETRIED", "STATUS_FALLBACK", "STATUS_SKIPPED",
    "STATUS_ABANDONED", "STATUS_NAMES",
    "classify_series", "unfittable_mask",
    "FitOutcome", "RetryPolicy", "retry_kwargs", "StageResult",
    "FaultSpec", "InjectedOOM", "InjectedPumpCrash",
    "fault_injection", "fault_spec",
    "chunk_fault", "serving_fault", "fleet_fault", "fault_scope_token",
    "forced_optimizer_failures", "corrupt_values", "resilient_fit",
]

# ---------------------------------------------------------------------------
# health classification
# ---------------------------------------------------------------------------

HEALTH_OK = 0            # contiguous finite window, long enough, non-constant
HEALTH_ALL_NAN = 1       # no finite observation at all
HEALTH_CONSTANT = 2      # finite but a single repeated value (fittable by a
#                          mean/drift fallback; degenerate for most solvers)
HEALTH_TOO_SHORT = 3     # valid window shorter than the fit's requirement
HEALTH_HAS_INF = 4       # an infinity anywhere — bad data, never padding
HEALTH_INTERIOR_GAP = 5  # NaN strictly inside the observed window

HEALTH_NAMES = {
    HEALTH_OK: "ok", HEALTH_ALL_NAN: "all_nan",
    HEALTH_CONSTANT: "constant", HEALTH_TOO_SHORT: "too_short",
    HEALTH_HAS_INF: "has_inf", HEALTH_INTERIOR_GAP: "interior_gap",
}

# health codes that no fit stage can do anything with: skipped up front.
# CONSTANT is *not* here — a constant lane legitimately fits a mean/drift
# fallback, so it goes through the chain like any hard lane.
_UNFITTABLE = (HEALTH_ALL_NAN, HEALTH_TOO_SHORT, HEALTH_HAS_INF,
               HEALTH_INTERIOR_GAP)


def classify_series(values: jnp.ndarray, min_len: int = 3) -> jnp.ndarray:
    """Per-lane health codes, fully vectorized: ``values (..., n)`` →
    int32 ``(...)``.

    The valid window is the span from the first to the last finite
    observation (leading/trailing NaN is padding, the ``ops.ragged``
    convention); ``min_len`` is the fit-specific minimum window length.
    Priority when several conditions hold:
    all-NaN > has-inf > interior-gap > too-short > constant > ok.
    """
    v = jnp.asarray(values)
    n = v.shape[-1]
    if n == 0:
        return jnp.full(v.shape[:-1], HEALTH_TOO_SHORT, jnp.int32)
    finite = jnp.isfinite(v)
    nan = jnp.isnan(v)
    obs = ~nan                                    # inf counts as observed
    n_obs = jnp.sum(obs, axis=-1)
    any_obs = n_obs > 0
    start = jnp.argmax(obs, axis=-1)
    last = n - 1 - jnp.argmax(obs[..., ::-1], axis=-1)
    window = jnp.where(any_obs, last - start + 1, 0)

    has_inf = jnp.any(jnp.isinf(v), axis=-1)
    # constant over the finite entries (big/-big sentinels never tie a real
    # max/min pair unless the lane is inf-laden, which outranks anyway)
    vmax = jnp.max(jnp.where(finite, v, -jnp.inf), axis=-1)
    vmin = jnp.min(jnp.where(finite, v, jnp.inf), axis=-1)
    constant = any_obs & (vmax == vmin)

    status = jnp.full(v.shape[:-1], HEALTH_OK, jnp.int32)
    status = jnp.where(constant, HEALTH_CONSTANT, status)
    status = jnp.where(window < min_len, HEALTH_TOO_SHORT, status)
    status = jnp.where(n_obs != window, HEALTH_INTERIOR_GAP, status)
    status = jnp.where(has_inf, HEALTH_HAS_INF, status)
    status = jnp.where(~any_obs, HEALTH_ALL_NAN, status)
    return status


def unfittable_mask(health: np.ndarray) -> np.ndarray:
    """Boolean mask of lanes no fit stage can attempt (skipped with an
    explicit status instead of poisoning the batch)."""
    return np.isin(np.asarray(health), _UNFITTABLE)


# ---------------------------------------------------------------------------
# outcome / policy structures
# ---------------------------------------------------------------------------

STATUS_OK = 0          # primary fit converged on the first attempt
STATUS_RETRIED = 1     # primary fit converged after >= 1 multi-start restart
STATUS_FALLBACK = 2    # a fallback stage produced the lane's parameters
STATUS_SKIPPED = 3     # unfittable (see classify_series); params are NaN
STATUS_ABANDONED = 4   # every stage failed; params are the best-effort
#                        primary result (quarantined init or cap-hit point)

STATUS_NAMES = {
    STATUS_OK: "ok", STATUS_RETRIED: "retried",
    STATUS_FALLBACK: "fallback", STATUS_SKIPPED: "skipped",
    STATUS_ABANDONED: "abandoned",
}


class FitOutcome(NamedTuple):
    """Per-series disposition of a resilient batched fit.

    ``params (n_series, k)`` is the final flattened parameter view (every
    float array leaf of the merged model, trailing dims flattened and
    concatenated — NaN for skipped lanes); ``status`` / ``health`` are the
    ``STATUS_*`` / ``HEALTH_*`` codes; ``attempts`` counts optimizer starts
    plus fallback stages actually run for the lane (0 for skipped);
    ``fallback_used`` is the index into the fit chain that produced the
    lane's parameters (-1 = the primary fit, or no stage at all).
    ``orders (n_series, 3)`` records the effective (p, d, q) the lane's
    parameters were selected at, for families with an order notion —
    populated per-lane by order-searching stages (:class:`StageResult`)
    and back-filled statically by the family wrapper; (-1, -1, -1) where
    no stage produced the lane (skipped).  None for order-free families.
    """
    params: Optional[np.ndarray]
    status: np.ndarray
    attempts: np.ndarray
    fallback_used: np.ndarray
    health: np.ndarray
    orders: Optional[np.ndarray] = None

    def counts(self) -> Dict[str, int]:
        """``{status_name: lane count}`` summary (only nonzero entries)."""
        s = np.asarray(self.status)
        return {name: int(np.sum(s == code))
                for code, name in STATUS_NAMES.items()
                if int(np.sum(s == code))}


class StageResult(NamedTuple):
    """Optional rich return for a fallback-chain stage: the fitted model
    plus per-lane ``lane_orders (n_sub, 3)`` — the (p, d, q) each gathered
    lane's parameters were actually selected at (the ``auto_order``
    stage's contract; plain stages just return the model and the chain's
    static order applies).  Distinguished by *type*, not tuple-ness —
    model pytrees are themselves NamedTuples."""
    model: Any
    lane_orders: Optional[np.ndarray] = None


class RetryPolicy(NamedTuple):
    """Multi-start retry knobs threaded from ``fit_resilient`` down to the
    batched minimizers (``ops.optimize``).

    ``max_restarts`` extra solves from jittered inits for lanes whose first
    solve did not converge or went non-finite; ``perturb_scale`` scales the
    Gaussian init jitter (relative: ``scale * (1 + |x0|)``) drawn from
    per-lane PRNG keys folded from ``seed``; ``max_iter`` overrides the
    fit's per-attempt iteration budget when set.
    """
    max_restarts: int = 2
    perturb_scale: float = 0.25
    seed: int = 0
    max_iter: Optional[int] = None


def retry_kwargs(retry: Optional[RetryPolicy]) -> Dict[str, Any]:
    """The ``restarts``/``restart_scale``/``restart_key`` kwargs a
    :class:`RetryPolicy` expands to for the ``ops.optimize`` minimizers.
    Empty when ``retry`` is None OR carries no restart budget — a
    zero-restart policy (e.g. one used only for its ``max_iter``) must
    leave the plain single-start path, and its solver routing (the arima
    css-lm Pallas gate keys off this dict's truthiness), bit-for-bit
    untouched."""
    if retry is None or retry.max_restarts <= 0:
        return {}
    return {"restarts": int(retry.max_restarts),
            "restart_scale": float(retry.perturb_scale),
            "restart_key": jax.random.PRNGKey(int(retry.seed))}


def override_kwargs(kwargs: Dict[str, Any], **pinned) -> Dict[str, Any]:
    """Merge a fallback stage's pinned arguments over user pass-through
    kwargs (the pin wins — a user's ``method=`` must not collide with a
    stage that exists precisely to try a different method)."""
    out = dict(kwargs)
    out.update(pinned)
    return out


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

class FaultSpec(NamedTuple):
    """One active fault.  ``mode``:

    - ``"force_nonconverge"``: every batched minimizer reports its first
      ``n_attempts`` solve attempts as non-converged (parameters intact) —
      deterministic optimizer divergence, exercising retry and fallback;
    - ``"corrupt_nan"``: every ``lane_stride``-th lane of a resilient fit's
      input panel becomes all-NaN before classification;
    - ``"corrupt_inf"``: every ``lane_stride``-th lane gets one interior
      ``inf`` observation.

    Streaming-chunk modes (consumed host-side by ``engine.stream_fit``
    via :func:`chunk_fault`; ``chunk_index`` selects the target chunk in
    partition order):

    - ``"hang_chunk"``: the target chunk's dispatch sleeps ``hang_s``
      seconds — a wedged compile/transfer, exercising the per-chunk
      deadline watchdog;
    - ``"oom_chunk"``: the target chunk's dispatch raises a synthetic
      ``RESOURCE_EXHAUSTED`` (:class:`InjectedOOM`) at its full chunk
      size only, exercising the halve-and-redispatch degradation path;
    - ``"kill_after_chunk"``: SIGKILL the process right after the target
      chunk's journal commit — the kill-9-then-resume scenario;
    - ``"corrupt_journal"``: garble the target chunk's journal entry
      right after commit, exercising detect-quarantine-refit on resume.

    Serving-tier modes (consumed host-side by
    ``statespace.serving.ServingSession.update`` via
    :func:`serving_fault`; deterministic per-lane stride, never traced):

    - ``"tick_corrupt_nan"``: every ``lane_stride``-th lane's incoming
      tick becomes NaN (a dropped observation) for the scope's duration;
    - ``"tick_corrupt_inf"``: same lanes get an ``inf`` tick — bad data
      on the wire, which the filter must degrade to a missed tick
      instead of poisoning the lane's state;
    - ``"state_poison"``: every ``lane_stride``-th lane's filter state
      mean is overwritten with a huge finite value ONCE per scope per
      session — the numerically-diverged-lane scenario the health
      monitor must quarantine and ``heal()`` must recover.

    Fleet-tier modes (consumed host-side by
    ``statespace.fleet.FleetScheduler`` via :func:`fleet_fault`; never
    traced):

    - ``"tenant_flood"``: every ``FleetScheduler.submit`` is amplified
      to ``n_attempts`` copies of the tick — deterministic ingress
      overload, driving the bounded queues into their admission policy
      (reject / drop-oldest / degrade) without a traffic generator;
    - ``"coalesce_straggler"``: every ``lane_stride``-th tenant of each
      coalescing group goes silent — its queued ticks are withheld from
      dispatch and it no longer counts toward group readiness, so the
      batch can only flush through the coalescing-window deadline (the
      slow-tenant-must-not-stall-the-batch scenario);
    - ``"drop_tenant_process"``: SIGKILL the process immediately after a
      ``drain()`` bundle commits (forensics bundle written first, like
      ``kill_after_chunk``) — the killed-mid-migration scenario whose
      bundle another process must ``adopt()`` bitwise.

    Fleet-runtime modes (consumed host-side by
    ``statespace.runtime.FleetRuntime``'s supervised pump loop via
    :func:`fleet_fault`; never traced):

    - ``"pump_crash"``: every ``n_attempts``-th pump sweep dies with
      :class:`InjectedPumpCrash` before dispatching — the crashed pump
      thread the watchdog must restart (with backoff) without losing a
      single admitted tick;
    - ``"pump_hang"``: one pump sweep per fault scope sleeps ``hang_s``
      seconds *outside* the runtime lock — the wedged-pump scenario the
      heartbeat watchdog must detect (``/healthz`` goes stale) and
      recover from by abandoning the hung thread;
    - ``"checkpoint_torn"``: an auto-checkpoint generation is SIGKILLed
      after ``n_attempts`` tenant bundles have landed but before the
      generation manifest commits (forensics bundle first) — the torn
      checkpoint whose recovery must fall back to the previous
      committed generation.
    """
    mode: str
    n_attempts: int = 1
    lane_stride: int = 2
    chunk_index: int = 0
    hang_s: float = 3600.0


class InjectedOOM(RuntimeError):
    """Synthetic device allocation failure raised by the ``oom_chunk``
    fault mode; the message carries ``RESOURCE_EXHAUSTED`` so it routes
    through exactly the classifier (``utils.durability.is_oom``) a real
    XLA OOM would."""


class InjectedPumpCrash(RuntimeError):
    """Synthetic pump-thread death raised by the ``pump_crash`` fault
    mode at the top of a ``FleetRuntime`` pump sweep — before any
    dispatch, so the admitted queues stay transactionally intact and the
    supervisor's restart must deliver every tick exactly once."""


_VALID_MODES = ("force_nonconverge", "corrupt_nan", "corrupt_inf",
                "hang_chunk", "oom_chunk", "kill_after_chunk",
                "corrupt_journal",
                "tick_corrupt_nan", "tick_corrupt_inf", "state_poison",
                "tenant_flood", "coalesce_straggler",
                "drop_tenant_process",
                "pump_crash", "pump_hang", "checkpoint_torn")
_CHUNK_MODES = _VALID_MODES[3:7]
_SERVING_MODES = _VALID_MODES[7:10]
_FLEET_MODES = _VALID_MODES[10:]
_active_fault: List[FaultSpec] = []
# monotonically increasing id per fault_injection scope entry — never
# reused, unlike id(spec) (a freed FaultSpec's address can be recycled
# by the very next scope), so "once per scope" consumers key on this
_scope_serial = itertools.count(1)
_active_scope_tokens: List[int] = []


def fault_scope_token() -> Optional[int]:
    """Unique token of the innermost active :func:`fault_injection`
    scope (None outside any scope).  Consumers that act once per scope
    (the ``state_poison`` mode) remember tokens, not spec ids."""
    return _active_scope_tokens[-1] if _active_scope_tokens else None


def fault_spec() -> Optional[FaultSpec]:
    """The innermost active fault, or None."""
    return _active_fault[-1] if _active_fault else None


def chunk_fault(mode: str, chunk_index: int) -> Optional[FaultSpec]:
    """The active fault spec when it is a streaming-chunk fault of the
    given ``mode`` targeting ``chunk_index``, else None.  Read host-side
    by ``engine.stream_fit`` at each chunk's dispatch/commit — these
    modes never touch traced code."""
    spec = fault_spec()
    if spec is not None and spec.mode == mode \
            and int(spec.chunk_index) == int(chunk_index):
        return spec
    return None


def serving_fault(mode: str) -> Optional[FaultSpec]:
    """The active fault spec when it is a serving-tier fault of the given
    ``mode``, else None.  Read host-side by
    ``statespace.serving.ServingSession.update`` — these modes corrupt
    host tick buffers / host-visible state only and never enter traced
    code, so no jit-cache flush is needed around their scopes."""
    if mode not in _SERVING_MODES:
        raise ValueError(
            f"unknown serving fault mode {mode!r}; expected one of "
            f"{_SERVING_MODES}")
    spec = fault_spec()
    if spec is not None and spec.mode == mode:
        return spec
    return None


def fleet_fault(mode: str) -> Optional[FaultSpec]:
    """The active fault spec when it is a fleet-tier fault of the given
    ``mode``, else None.  Read host-side by
    ``statespace.fleet.FleetScheduler`` at submit / coalesced-dispatch /
    drain time, and by ``statespace.runtime.FleetRuntime`` at pump-sweep
    / auto-checkpoint time — these modes amplify ingress, withhold
    straggler ticks, crash or wedge the pump, tear a checkpoint
    generation, or kill the process; none of them ever enters traced
    code."""
    if mode not in _FLEET_MODES:
        raise ValueError(
            f"unknown fleet fault mode {mode!r}; expected one of "
            f"{_FLEET_MODES}")
    spec = fault_spec()
    if spec is not None and spec.mode == mode:
        return spec
    return None


def forced_optimizer_failures() -> int:
    """Static attempt count the minimizers must report non-converged (0
    when no ``force_nonconverge`` fault is active).  Read at call/trace
    time by ``ops.optimize``."""
    spec = fault_spec()
    if spec is not None and spec.mode == "force_nonconverge":
        return int(spec.n_attempts)
    return 0


def _clear_jit_caches() -> None:
    # the fault flag is read at trace time; a jitted fit kernel traced
    # without the fault would silently serve the faulted call (and vice
    # versa) from the executable cache
    try:
        jax.clear_caches()
    except Exception:  # pragma: no cover — very old jax
        pass
    # the streaming engine's AOT executables are compiled objects held
    # outside jax's caches — same staleness hazard, same flush
    try:
        from .. import engine as _engine
        eng = _engine._default_engine
        if eng is not None:
            with eng._lock:
                eng._entries.clear()
    except Exception:  # pragma: no cover — engine import failure must
        # never break fault scoping
        pass


@contextlib.contextmanager
def fault_injection(mode: str, n_attempts: int = 1, lane_stride: int = 2,
                    chunk_index: int = 0, hang_s: float = 3600.0,
                    _clear_caches: Optional[bool] = None):
    """Deterministically inject one fault for the scope's duration::

        with resilience.fault_injection("force_nonconverge", n_attempts=1):
            model = arima.fit(2, 1, 2, panel,
                              retry=resilience.RetryPolicy(max_restarts=2))
        assert bool(model.diagnostics.converged.all())   # retry recovered

    Nesting is allowed (innermost wins).  For ``force_nonconverge`` —
    whose flag is baked into optimizer traces — entering and leaving the
    scope clears the jit executable cache so a fit jitted by the caller in
    the other regime is never served stale (the corruption modes mutate
    host inputs only, and the streaming-chunk modes are read host-side
    per chunk; both skip the flush; ``_clear_caches`` overrides).
    """
    if mode not in _VALID_MODES:
        raise ValueError(
            f"unknown fault mode {mode!r}; expected one of {_VALID_MODES}")
    if n_attempts < 1 or lane_stride < 1:
        raise ValueError("n_attempts and lane_stride must be >= 1")
    if chunk_index < 0 or hang_s <= 0:
        raise ValueError("chunk_index must be >= 0 and hang_s > 0")
    clear = mode == "force_nonconverge" if _clear_caches is None \
        else _clear_caches
    spec = FaultSpec(mode, int(n_attempts), int(lane_stride),
                     int(chunk_index), float(hang_s))
    _active_fault.append(spec)
    _active_scope_tokens.append(next(_scope_serial))
    if clear:
        _clear_jit_caches()
    try:
        yield spec
    finally:
        _active_fault.pop()
        _active_scope_tokens.pop()
        if clear:
            _clear_jit_caches()


def _env_fault_enabled() -> bool:
    return os.environ.get("STS_FAULT_INJECT") == "1"


def corrupt_values(values: np.ndarray, spec: FaultSpec) -> np.ndarray:
    """Apply a corruption-mode fault to a host panel copy (deterministic:
    every ``lane_stride``-th lane, starting at lane 0).  Non-corruption
    modes return the input untouched."""
    if spec.mode not in ("corrupt_nan", "corrupt_inf"):
        return values
    out = np.array(values, copy=True)
    lanes = np.arange(out.shape[0]) % spec.lane_stride == 0
    if spec.mode == "corrupt_nan":
        out[lanes, :] = np.nan
    else:
        out[lanes, out.shape[1] // 2] = np.inf
    return out


# ---------------------------------------------------------------------------
# placeholder rows + pytree lane surgery
# ---------------------------------------------------------------------------

def _placeholder_rows(n_obs: int, dtype) -> np.ndarray:
    """A benign stand-in series for unfittable lanes: the batched solve
    needs *some* finite, non-degenerate values in every lane (results for
    these lanes are discarded and NaN-ed, but NaN inputs would trip the
    ragged-gap check and constants would singularize the shared OLS
    stages).  Deterministic standard-normal draws."""
    rng = np.random.default_rng(0)
    return rng.standard_normal(n_obs).astype(dtype, copy=False)


def _is_array(leaf: Any) -> bool:
    return isinstance(leaf, (jnp.ndarray, np.ndarray, jax.Array))


def _strip_attempts(model: Any):
    """Normalize ``diagnostics.attempts`` to None so models from stages
    with and without multi-start retry share one treedef (attempts are
    tracked host-side by the engine and re-attached at the end)."""
    diag = getattr(model, "diagnostics", None)
    if diag is not None and getattr(diag, "attempts", None) is not None:
        return model._replace(diagnostics=diag._replace(attempts=None))
    return model


def _merge_lanes(model: Any, sub: Any, rows: np.ndarray, n_series: int):
    """Scatter ``sub``'s per-lane leaves (fitted on a compacted subset)
    into ``model`` at panel rows ``rows``.  Leaves without a leading
    ``n_series`` dim (static orders, flags) pass through from ``model``."""
    rows_j = jnp.asarray(rows)

    def merge(orig, new):
        if not _is_array(orig):
            return orig
        arr = jnp.asarray(orig)
        if arr.ndim >= 1 and arr.shape[0] == n_series:
            return arr.at[rows_j].set(
                jnp.asarray(new)[:rows.size].astype(arr.dtype))
        return orig

    return jax.tree_util.tree_map(merge, model, sub)


def _nan_lanes(model: Any, rows: np.ndarray, n_series: int):
    """NaN out the float parameter leaves of the given lanes (skipped
    series must read as explicitly absent, not as placeholder fits)."""
    if rows.size == 0:
        return model
    rows_j = jnp.asarray(rows)

    def blank(leaf):
        if not _is_array(leaf):
            return leaf
        arr = jnp.asarray(leaf)
        if arr.ndim >= 1 and arr.shape[0] == n_series \
                and arr.dtype.kind == "f":
            return arr.at[rows_j].set(jnp.nan)
        return leaf

    return jax.tree_util.tree_map(blank, model)


def _stack_params(model: Any, n_series: int) -> Optional[np.ndarray]:
    """Flatten every per-lane float leaf (diagnostics excluded) into one
    ``(n_series, k)`` parameter matrix for :class:`FitOutcome`."""
    core = model._replace(diagnostics=None) \
        if hasattr(model, "_replace") and hasattr(model, "diagnostics") \
        else model
    cols = []
    for leaf in jax.tree_util.tree_leaves(core):
        if not _is_array(leaf):
            continue
        arr = np.asarray(leaf)
        if arr.ndim >= 1 and arr.shape[0] == n_series \
                and arr.dtype.kind == "f":
            cols.append(arr.reshape(n_series, -1))
    if not cols:
        return None
    return np.concatenate(cols, axis=1)


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

def resilient_fit(values, fits: Sequence[Tuple[str, Callable]], *,
                  min_len: int = 3, family: str = "model",
                  registry: Optional["_metrics.MetricsRegistry"] = None,
                  suspect_fn: Optional[Callable[[Any], np.ndarray]] = None
                  ) -> Tuple[Any, FitOutcome]:
    """Run a fallback chain of batched fits with per-lane failure isolation.

    ``values (n_series, n)`` is the raw panel (NaN padding allowed);
    ``fits`` is the declarative chain ``[(name, fit_fn), ...]`` — every
    ``fit_fn(values) -> model`` must return the *same pytree structure*
    (the model-family ``fit_resilient`` wrappers guarantee this by
    re-expressing lower-order fallbacks in the primary parameter layout)
    with a ``diagnostics.converged`` entry per lane.  A stage may instead
    return a :class:`StageResult` to additionally report the per-lane
    (p, d, q) it selected (the ``auto_order`` stage); those land in
    ``FitOutcome.orders``.

    Flow: classify lane health → replace unfittable lanes with a benign
    placeholder (their results are NaN-ed afterwards; healthy lanes are
    untouched, so per-lane results match the plain fit bit-for-bit) → run
    the primary fit → for each fallback stage, gather the still-failed
    lanes, fit just those, and scatter back the lanes the stage converged.
    A stage that *raises* is recorded and skipped — the panel never dies on
    a stage error as long as some stage returns.

    ``suspect_fn(base_model) -> bool (n_series,)`` flags lanes whose
    primary fit *converged but plateaued* (e.g. near-cancelling AR/MA
    roots — common-factor cancellation): suspect lanes are offered to the
    fallback chain like failed lanes, but keep their primary parameters
    and OK/RETRIED status unless a stage actually converges them —
    a fallback may rescue a plateau, never worsen a healthy lane.

    Returns ``(model, outcome)``: the merged model (primary structure,
    final diagnostics reflecting the per-lane disposition) and the
    :class:`FitOutcome`.  Counts land in the registry as
    ``resilience.<family>.*`` plus aggregate ``resilience.*`` counters and
    ``frac_recovered`` / ``frac_fallback`` / ``frac_abandoned`` gauges;
    lanes an ``auto``-named stage attempted but nothing rescued count
    into ``resilience.auto_fallback_dead`` (zero-baselined by
    ``tools/bench_gate.py``).
    """
    if not fits:
        raise ValueError("resilient_fit needs at least one fit stage")
    reg = registry if registry is not None else _metrics.get_registry()
    host = np.asarray(values)
    if host.ndim != 2:
        raise ValueError(
            f"resilient_fit needs a (n_series, n) panel, got {host.shape}")
    n_series, n_obs = host.shape

    # env-armed CI fault (make verify-faults): scoped to the BASE-model
    # stage only, so the primary fit's retry path is forced on every
    # resilient fit while the fallback stages run clean — an optimizer
    # fallback must be able to *succeed* under the CI fault, or a
    # regression in it would be invisible there.  (An explicit
    # fault_injection scope set by the caller applies everywhere, as
    # asked.)  The env flag is constant for the process lifetime, so no
    # cross-regime jit cache exists to flush.
    env_armed = _env_fault_enabled() and fault_spec() is None
    with _metrics.span(f"resilience.fit.{family}"):
        spec = fault_spec()
        if spec is not None:
            host = corrupt_values(host, spec)

        health = np.asarray(classify_series(jnp.asarray(host),
                                            min_len=min_len))
        skipped = unfittable_mask(health)
        safe = host
        if skipped.any():
            safe = np.array(host, copy=True)
            safe[skipped] = _placeholder_rows(n_obs, host.dtype)
        safe_j = jnp.asarray(safe)

        # the first stage that returns is the base model; earlier stages
        # that raise are recorded (a primary that dies on static shape
        # grounds must not kill the panel when a fallback can run)
        errors: List[str] = []
        model = None
        base_idx = 0
        orders: Optional[np.ndarray] = None

        def _set_orders(rows_idx: np.ndarray,
                        lane_orders: np.ndarray) -> None:
            nonlocal orders
            if orders is None:
                orders = np.full((n_series, 3), -1, np.int32)
            orders[rows_idx] = np.asarray(lane_orders,
                                          np.int32)[:rows_idx.size]

        base_ctx = fault_injection("force_nonconverge", n_attempts=1,
                                   _clear_caches=False) \
            if env_armed else contextlib.nullcontext()
        with base_ctx:
            for i, (name, fn) in enumerate(fits):
                try:
                    model = fn(safe_j)
                    base_idx = i
                    break
                except Exception as e:  # noqa: BLE001 — stage isolation is
                    # the contract; anything fatal for the whole panel
                    # surfaces below when every stage has failed
                    errors.append(f"{name}: {type(e).__name__}: {e}")
                    reg.inc(f"resilience.{family}.stage_errors")
                    _metrics.trace_instant(
                        f"resilience.{family}.stage_error",
                        {"stage": name, "error": type(e).__name__})
        if model is None:
            raise RuntimeError(
                f"resilient_fit({family}): every fit stage raised — "
                + "; ".join(errors))
        if isinstance(model, StageResult):
            if model.lane_orders is not None:
                _set_orders(np.arange(n_series), model.lane_orders)
            model = model.model

        diag = getattr(model, "diagnostics", None)
        if diag is None:
            raise ValueError(
                f"resilient_fit({family}): stage {fits[base_idx][0]!r} "
                "returned a model without diagnostics")
        conv = np.asarray(diag.converged).reshape(-1).astype(bool)
        d_att = getattr(diag, "attempts", None)
        attempts = (np.asarray(d_att).reshape(-1).astype(np.int64)
                    if d_att is not None else np.ones(n_series, np.int64))
        model = _strip_attempts(model)

        status = np.full(n_series, STATUS_ABANDONED, np.int32)
        fallback_used = np.full(n_series, -1, np.int32)
        if base_idx == 0:
            status[conv & (attempts <= 1)] = STATUS_OK
            status[conv & (attempts > 1)] = STATUS_RETRIED
        else:
            status[conv] = STATUS_FALLBACK
            fallback_used[conv] = base_idx
        status[skipped] = STATUS_SKIPPED
        attempts[skipped] = 0

        # plateau detection: converged-but-suspect lanes (near-cancelling
        # AR/MA roots, ...) are offered to the fallback chain without
        # losing their primary result — they keep OK/RETRIED status and
        # parameters unless a stage actually converges them
        suspect = np.zeros(n_series, bool)
        if suspect_fn is not None:
            try:
                suspect = np.asarray(suspect_fn(model)) \
                    .reshape(-1).astype(bool)
            except Exception as e:  # noqa: BLE001 — detection is
                # advisory; a detector crash must not kill the panel
                errors.append(f"suspect_fn: {type(e).__name__}: {e}")
                reg.inc(f"resilience.{family}.stage_errors")
            suspect &= conv & ~skipped
            if suspect.any():
                reg.inc(f"resilience.{family}.suspect",
                        int(suspect.sum()))
                _metrics.trace_instant(
                    f"resilience.{family}.suspect",
                    {"lanes": int(suspect.sum())})

        auto_seen = np.zeros(n_series, bool)
        pending = (~conv | suspect) & ~skipped
        for j in range(base_idx + 1, len(fits)):
            if not pending.any():
                break
            name, fn = fits[j]
            rows = np.flatnonzero(pending)
            # timeline marker per fallback stage actually run: the trace
            # view then shows WHEN the chain escalated and for how many
            # lanes, not just the end-of-run counters
            _metrics.trace_instant(
                f"resilience.{family}.fallback",
                {"stage": name, "pending_lanes": int(rows.size)})
            try:
                sub = fn(jnp.asarray(safe[rows]))
            except Exception as e:  # noqa: BLE001 — see above
                errors.append(f"{name}: {type(e).__name__}: {e}")
                reg.inc(f"resilience.{family}.stage_errors")
                _metrics.trace_instant(
                    f"resilience.{family}.stage_error",
                    {"stage": name, "error": type(e).__name__})
                if name.startswith("auto"):
                    # only the order search may touch converged-but-
                    # suspect lanes; past it (even via a stage crash)
                    # they keep their primary fit — the simpler
                    # fallbacks must never replace a converged model
                    pending &= ~suspect
                continue
            sub_orders = None
            if isinstance(sub, StageResult):
                sub_orders = sub.lane_orders
                sub = sub.model
            if name.startswith("auto"):
                auto_seen[rows] = True
            sub_diag = getattr(sub, "diagnostics", None)
            if sub_diag is None:
                errors.append(f"{name}: returned model without diagnostics")
                reg.inc(f"resilience.{family}.stage_errors")
                continue
            sub_conv = np.asarray(sub_diag.converged).reshape(-1).astype(bool)
            sub = _strip_attempts(sub)
            attempts[rows] += 1
            took = rows[sub_conv]
            if took.size:
                # scatter only the lanes this stage actually fixed
                conv_rows = jnp.asarray(np.flatnonzero(sub_conv))

                def _take_conv(leaf, n_sub=rows.size, idx=conv_rows):
                    if _is_array(leaf):
                        arr = jnp.asarray(leaf)
                        if arr.ndim >= 1 and arr.shape[0] == n_sub:
                            return arr[idx]
                    return leaf

                sub_took = jax.tree_util.tree_map(_take_conv, sub)
                model = _merge_lanes(model, sub_took, took, n_series)
                status[took] = STATUS_FALLBACK
                fallback_used[took] = j
                pending[took] = False
                if sub_orders is not None:
                    _set_orders(took, np.asarray(sub_orders)[sub_conv])
            if name.startswith("auto"):
                # suspect lanes the order search did not rescue keep
                # their converged primary result: drop them from pending
                # so the hardcoded fallbacks cannot worsen them
                pending &= ~suspect

        model = _nan_lanes(model, np.flatnonzero(skipped), n_series)

        ok_mask = np.isin(status,
                          (STATUS_OK, STATUS_RETRIED, STATUS_FALLBACK))
        diag = getattr(model, "diagnostics", None)
        try:
            final_diag = type(diag)(jnp.asarray(ok_mask),
                                    jnp.asarray(diag.n_iter),
                                    jnp.asarray(diag.fun),
                                    jnp.asarray(attempts))
        except TypeError:       # a diagnostics type without an attempts slot
            final_diag = type(diag)(jnp.asarray(ok_mask),
                                    jnp.asarray(diag.n_iter),
                                    jnp.asarray(diag.fun))
        model = model._replace(diagnostics=final_diag)

        outcome = FitOutcome(_stack_params(model, n_series), status,
                             attempts, fallback_used, health, orders)

        if auto_seen.any():
            # auto-order lanes NOTHING rescued (suspect lanes that kept
            # their primary result are not dead — they still converged)
            n_auto_dead = int(np.sum(auto_seen
                                     & (status == STATUS_ABANDONED)))
            for prefix in (f"resilience.{family}", "resilience"):
                reg.inc(f"{prefix}.auto_fallback", int(auto_seen.sum()))
                if n_auto_dead:
                    # materializes only on first real death, so a clean
                    # history zero-baselines the bench gate
                    reg.inc(f"{prefix}.auto_fallback_dead", n_auto_dead)
            if n_auto_dead:
                _metrics.trace_instant(
                    f"resilience.{family}.auto_fallback_dead",
                    {"lanes": n_auto_dead})

        n_skip = int(skipped.sum())
        n_retr = int(np.sum(status == STATUS_RETRIED))
        n_fb = int(np.sum(status == STATUS_FALLBACK))
        n_aband = int(np.sum(status == STATUS_ABANDONED))
        for prefix in (f"resilience.{family}", "resilience"):
            reg.inc(f"{prefix}.series", n_series)
            reg.inc(f"{prefix}.skipped", n_skip)
            reg.inc(f"{prefix}.retried", n_retr)
            reg.inc(f"{prefix}.fallback", n_fb)
            reg.inc(f"{prefix}.abandoned", n_aband)
        if n_series:
            reg.set_gauge(f"resilience.{family}.frac_recovered",
                          (n_retr + n_fb) / n_series)
            reg.set_gauge(f"resilience.{family}.frac_fallback",
                          n_fb / n_series)
            reg.set_gauge(f"resilience.{family}.frac_abandoned",
                          n_aband / n_series)
        return model, outcome
