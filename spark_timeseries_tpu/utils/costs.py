"""Compiled-program cost & memory analysis per fit family.

ARIMA_PLUS (PAPERS.md) treats per-model cost accounting as a first-class
product feature for in-database forecasting at scale; the Spark
reference's analogue is the per-executor memory page.  This module is
that tier for the TPU build: *before* running a workload, ask XLA what
one compiled fit program actually does —

- :func:`fit_cost_report` lowers and compiles a representative batched
  fit for any model family at a given ``(n_series, n_obs)`` shape
  (``jax.jit(...).lower(...).compile()``) and reads the compiler's own
  accounting: ``cost_analysis()`` (FLOPs, bytes accessed,
  transcendentals) and ``memory_analysis()`` (argument / output /
  temp / generated-code bytes, whose sum is the peak-footprint
  estimate), plus HLO op counts parsed from the optimized module text.
  Backends that don't expose a section (CPU lacks ``memory_analysis``
  on some jaxlib versions) yield ``None`` markers, never an exception —
  the report's ``available`` block says which sections are real.
- :func:`device_memory_stats` / :func:`sample_device_memory` read live
  allocator state (``device.memory_stats()``) into ``device.mem.*``
  gauges — a graceful no-op on platforms that expose nothing (CPU).
- :func:`install_device_memory_sampler` hooks the sampler onto span
  exits (``metrics.add_span_listener``), so any instrumented workload
  tracks its HBM watermark with no per-call-site code.

Shapes only, never data: lowering takes ``jax.ShapeDtypeStruct`` specs,
so a cost report for a 1M-series panel costs one compile, not one fit.
``bench.py`` embeds a per-family block in every ``BENCH_*.json`` so the
perf trajectory records what the compiler thought the program costs
alongside what it measured.
"""

from __future__ import annotations

import re
import time
from typing import Any, Callable, Dict, Optional, Tuple

from . import metrics as _metrics

__all__ = ["fit_cost_report", "representative_fit", "hlo_op_counts",
           "device_memory_stats", "sample_device_memory",
           "install_device_memory_sampler", "COST_FAMILIES"]

# Exogenous-regressor column count used by the representative fits of
# the x-carrying families (arimax/arx/regression_arima).
N_XREG = 2

COST_FAMILIES = ("arima", "arimax", "ar", "arx", "ewma", "garch",
                 "argarch", "egarch", "holt_winters", "regression_arima",
                 "serving_update", "quality_update", "long_combine",
                 "fleet_pump", "backtest_metrics", "pinned_state_path")

# the long_combine representative's statics: ARIMA(2,?,2) segment
# estimates mapped into a 12-term AR truncation — the fit_long defaults
LONG_COMBINE_N_AR = 12

# the fleet_pump representative's group size: 3 tenants coalesce into a
# power-of-two slot pad of 4 (fleet._slots_for), so the pump program is
# the monitored update at 4x the per-tenant bucket width
FLEET_PUMP_TENANTS = 3

# the backtest_metrics representative's statics: the default smape/mase
# scoring horizons of a horizon-4 table
BACKTEST_METRIC_HORIZONS = (1, 4)


def _long_combine_representative(n_series: int, n_obs: int,
                                 dtype) -> Tuple[Callable, Tuple]:
    """The longseries tier's per-chunk combination program: one chunk of
    ``n_series`` segments of ``n_obs`` observations each, AR(∞)-mapped
    and gram/variance-weighted in-graph — exactly what
    ``longseries.combine.combine_segments`` dispatches between chunk
    boundaries (``_combine_chunk_impl`` with the ``fit_long`` default
    statics)."""
    import jax
    import jax.numpy as jnp

    from ..longseries.combine import _combine_chunk_impl

    p, q, icpt = 2, 2, 1
    n_ar = LONG_COMBINE_N_AR
    args = (jax.ShapeDtypeStruct((n_series, n_obs), dtype),
            jax.ShapeDtypeStruct((n_series, icpt + p + q), dtype),
            jax.ShapeDtypeStruct((n_series,), jnp.bool_))

    def chunk(segs, coefs, conv):
        return _combine_chunk_impl(segs, coefs, conv, p, q, icpt,
                                   n_ar, n_ar)

    return chunk, args


def _serving_update_representative(n_series: int,
                                   dtype) -> Tuple[Callable, Tuple]:
    """The serving tier's per-tick program: one *health-monitored*
    Kalman update across a panel of ARIMA(2,1,2)-shaped state-space
    lanes — exactly what ``statespace.serving.ServingSession.update``
    jits (filter step + χ²-band innovation tracking + non-finite
    detection + in-graph quarantine, Joseph-form covariance), traced
    from its flat array leaves (the ``SSMeta``/``HealthPolicy`` statics
    closed over).  ``n_obs`` does not apply: the whole point of the
    serving tier is that a tick is O(1) in history length."""
    import jax

    from ..statespace.health import HealthPolicy, LaneHealth
    from ..statespace.serving import _update_impl
    from ..statespace.ssm import FilterState, SSMeta, StateSpace

    md = 3                               # max(p, q+1) for ARIMA(2,1,2)
    meta = SSMeta("arima", "exact", 1, md)
    policy = HealthPolicy()
    s = n_series

    def sd(*shape, dt=dtype):
        return jax.ShapeDtypeStruct(shape, dt)

    import jax.numpy as jnp
    args = (sd(s, md, md), sd(s, md), sd(s, md), sd(s), sd(s),
            sd(s, md, md), sd(s, md),                       # StateSpace
            sd(s, md), sd(s, md, md), sd(s, meta.d_order), sd(s), sd(s),
            sd(s), sd(s, dt=jnp.int32),                     # FilterState
            sd(s), sd(s, dt=jnp.int32), sd(s, md),
            sd(s, meta.d_order),                            # LaneHealth
            sd(s), sd(s))                                   # y, offset

    def update(*leaves):
        ssm = StateSpace(*leaves[:7])
        state = FilterState(*leaves[7:14])
        health = LaneHealth(*leaves[14:18])
        return _update_impl(meta, policy, None, ssm, state, health,
                            None, leaves[18], leaves[19])

    return update, args


def _quality_update_representative(n_series: int,
                                   dtype) -> Tuple[Callable, Tuple]:
    """The serving tier's per-tick program with the forecast-quality
    plane ARMED (ISSUE 15): the same health-monitored Kalman update as
    ``serving_update`` plus the fused quality step — forecast-ring
    scoring, EW online sMAPE/MASE/coverage, Page-Hinkley drift, the
    ``drifted`` status overlay, and the next-horizon forecast write —
    exactly what a ``ServingSession(..., quality=QualityPolicy())``
    jits.  Contract-checking it proves the fused program (not just the
    quality-off path) stays f64-free, callback-free, and
    trace-stable."""
    import jax

    from ..statespace.health import HealthPolicy, LaneHealth
    from ..statespace.quality import QualityPolicy, QualityState
    from ..statespace.serving import _update_impl
    from ..statespace.ssm import FilterState, SSMeta, StateSpace

    md = 3                               # max(p, q+1) for ARIMA(2,1,2)
    meta = SSMeta("arima", "exact", 1, md)
    policy = HealthPolicy()
    quality = QualityPolicy()
    H = quality.horizon
    s = n_series

    def sd(*shape, dt=dtype):
        return jax.ShapeDtypeStruct(shape, dt)

    import jax.numpy as jnp
    args = (sd(s, md, md), sd(s, md), sd(s, md), sd(s), sd(s),
            sd(s, md, md), sd(s, md),                       # StateSpace
            sd(s, md), sd(s, md, md), sd(s, meta.d_order), sd(s), sd(s),
            sd(s), sd(s, dt=jnp.int32),                     # FilterState
            sd(s), sd(s, dt=jnp.int32), sd(s, md),
            sd(s, meta.d_order),                            # LaneHealth
            sd(s, H), sd(s, dt=jnp.int32), sd(s, dt=jnp.int32),
            sd(s), sd(s), sd(s), sd(s), sd(s),
            sd(s, dt=jnp.int32), sd(s),
            sd(s, dt=jnp.bool_),                            # QualityState
            sd(s), sd(s))                                   # y, offset

    def update(*leaves):
        ssm = StateSpace(*leaves[:7])
        state = FilterState(*leaves[7:14])
        health = LaneHealth(*leaves[14:18])
        qstate = QualityState(*leaves[18:29])
        return _update_impl(meta, policy, quality, ssm, state, health,
                            qstate, leaves[29], leaves[30])

    return update, args


def _fleet_pump_representative(n_series: int,
                               dtype) -> Tuple[Callable, Tuple]:
    """The fleet scheduler's coalesced pump program: one group of
    :data:`FLEET_PUMP_TENANTS` same-key tenants gathered lane-wise and
    run through the SAME jitted monitored update the sessions run solo
    (``fleet.FleetScheduler._dispatch_group``), so the device program is
    ``_update_impl`` at the power-of-two slot width.  Contract-checking
    it at coalesced width proves the pump path — not just the solo
    session path — stays f64-free, callback-free, and trace-stable."""
    from ..statespace.fleet import _slots_for

    return _serving_update_representative(
        _slots_for(FLEET_PUMP_TENANTS) * n_series, dtype)


def _backtest_metrics_representative(n_series: int, n_obs: int,
                                     dtype) -> Tuple[Callable, Tuple]:
    """The backtest tier's one jitted NaN-masked metric kernel
    (``backtest.evaluate._metric_tables_fn``): per-(S,H) sMAPE/MASE/
    RMSE/coverage tables plus per-origin score vectors over an
    ``(S, O, H)`` forecast block.  ``n_obs`` maps to the origin count
    (``O = n_obs // 8``) so the stable-jaxpr bucket pair lands on one
    origin geometry."""
    import jax

    from ..backtest.evaluate import _metric_tables_fn

    horizon = max(BACKTEST_METRIC_HORIZONS)
    n_origins = max(n_obs // 8, 2)
    blk = jax.ShapeDtypeStruct((n_series, n_origins, horizon), dtype)
    half = jax.ShapeDtypeStruct((n_series, horizon), dtype)
    scale = jax.ShapeDtypeStruct((n_series,), dtype)

    def kernel(fcst, actual, hw, sc):
        return _metric_tables_fn(fcst, actual, hw, sc,
                                 BACKTEST_METRIC_HORIZONS)

    return kernel, (blk, blk, half, scale)


def _pinned_state_path_representative(n_series: int, n_obs: int,
                                      dtype) -> Tuple[Callable, Tuple]:
    """The backtest/longseries replay primitive
    (``statespace.kalman.pinned_state_path``): every predicted state
    along the series under a pinned per-lane gain via
    ``affine_recurrence`` (O(log n) depth), at the ARIMA(2,1,2) state
    dimension the demo grids exercise."""
    import jax

    from ..statespace.kalman import pinned_state_path
    from ..statespace.ssm import StateSpace

    m = 3
    s = n_series

    def sd(*shape):
        return jax.ShapeDtypeStruct(shape, dtype)

    args = (sd(s, m, m), sd(s, m), sd(s, m), sd(s), sd(s),
            sd(s, m, m), sd(s, m),                   # StateSpace leaves
            sd(s, m), sd(s, n_obs), sd(s, m))        # x0, ys, K

    def path(*leaves):
        ssm = StateSpace(*leaves[:7])
        return pinned_state_path(ssm, leaves[7], leaves[8], leaves[9])

    return path, args


def representative_fit(family: str, n_series: int, n_obs: int,
                       dtype=None) -> Tuple[Callable, Tuple]:
    """A representative batched fit closure + abstract args for one
    family, at canonical small orders (the orders every family's tests
    and the bench exercise: ARIMA(2,1,2), AR(2), period-12 HW, ...).

    Returns ``(fn, abstract_args)`` where each arg is a
    ``jax.ShapeDtypeStruct`` — suitable for ``jax.jit(fn).lower(*args)``
    with no data materialized."""
    import jax
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32
    v = jax.ShapeDtypeStruct((n_series, n_obs), dtype)
    x = jax.ShapeDtypeStruct((n_obs, N_XREG), dtype)

    from .. import models as m

    def arrays_only(fit_fn):
        # a fitted model pytree may carry non-JAX leaves (Holt-Winters'
        # model_type string) that cannot cross the jit boundary; the
        # compiled program is identical either way, so return just the
        # array leaves
        def fn(*a):
            model = fit_fn(*a)
            return [leaf for leaf in jax.tree_util.tree_leaves(model)
                    if isinstance(leaf, (jax.Array, jnp.ndarray))
                    or hasattr(leaf, "dtype")]
        return fn

    table: Dict[str, Tuple[Callable, Tuple]] = {
        "arima": (lambda ts: m.arima.fit(2, 1, 2, ts, warn=False), (v,)),
        "arimax": (lambda ts, xr: m.arimax.fit(1, 1, 1, ts, xr, 1), (v, x)),
        "ar": (lambda ts: m.autoregression.fit(ts, max_lag=2), (v,)),
        "arx": (lambda ts, xr: m.autoregression_x.fit(ts, xr, 2, 1), (v, x)),
        "ewma": (lambda ts: m.ewma.fit(ts), (v,)),
        "garch": (lambda ts: m.garch.fit(ts), (v,)),
        "argarch": (lambda ts: m.garch.fit_ar_garch(ts), (v,)),
        "egarch": (lambda ts: m.garch.fit_egarch(ts), (v,)),
        "holt_winters": (
            lambda ts: m.holt_winters.fit(ts, period=12), (v,)),
        "regression_arima": (
            lambda ts, xr: m.regression_arima.fit(
                ts, xr, "cochrane-orcutt"), (v, x)),
    }
    # the program-tier families are built only on request: the classic
    # families' reports must not depend on the statespace/backtest
    # packages importing
    program_tier = {
        "serving_update":
            lambda: _serving_update_representative(n_series, dtype),
        "quality_update":
            lambda: _quality_update_representative(n_series, dtype),
        "long_combine":
            lambda: _long_combine_representative(n_series, n_obs, dtype),
        "fleet_pump":
            lambda: _fleet_pump_representative(n_series, dtype),
        "backtest_metrics":
            lambda: _backtest_metrics_representative(n_series, n_obs,
                                                     dtype),
        "pinned_state_path":
            lambda: _pinned_state_path_representative(n_series, n_obs,
                                                      dtype),
    }
    if family in program_tier:
        fit_fn, args = program_tier[family]()
    elif family in table:
        fit_fn, args = table[family]
    else:
        raise ValueError(
            f"unknown model family {family!r}; expected one of "
            f"{sorted(table) + sorted(program_tier)}")
    return arrays_only(fit_fn), args


_HLO_OP_RE = re.compile(r"=\s*\S+\s+([a-zA-Z][\w-]*)\(")


def hlo_op_counts(hlo_text: str, top: int = 15) -> Dict[str, int]:
    """Occurrence counts of the ``top`` most frequent HLO opcodes in an
    (optimized) HLO module dump — a compact fingerprint of what the
    compiled program is made of (how many fusions, while loops,
    dots, ...)."""
    counts: Dict[str, int] = {}
    for mo in _HLO_OP_RE.finditer(hlo_text):
        op = mo.group(1)
        counts[op] = counts.get(op, 0) + 1
    ranked = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
    return dict(ranked[:top])


def _first(obj):
    """cost_analysis() returns a dict on current JAX, a list of per-
    computation dicts on older versions; normalize to one dict."""
    if isinstance(obj, (list, tuple)):
        return obj[0] if obj else None
    return obj


def fit_cost_report(family: str, n_series: int, n_obs: int,
                    dtype=None, backend: Optional[str] = None
                    ) -> Dict[str, Any]:
    """What does one compiled ``family`` fit at ``(n_series, n_obs)``
    cost?  Lowers + compiles the representative fit and reports:

    - ``flops``, ``bytes_accessed``, ``transcendentals`` from XLA's
      ``cost_analysis`` (``None`` when the backend exposes none);
    - ``argument_bytes`` / ``output_bytes`` / ``temp_bytes`` /
      ``generated_code_bytes`` and their sum ``peak_bytes`` from
      ``memory_analysis`` (``None`` markers likewise — CPU jaxlibs
      often expose no memory analysis);
    - ``hlo_op_counts`` from the optimized module text, ``hlo_ops_total``
      over all opcodes;
    - ``lower_s`` / ``compile_s`` wall times, and flop/byte intensity
      when both numerator and denominator are real.

    The ``available`` sub-dict says which sections came from the
    compiler and which are absent markers, so a consumer never has to
    guess whether ``None`` means "zero" or "not exposed here".
    Shape-only: no panel data is materialized or fitted.
    """
    import jax

    fn, args = representative_fit(family, n_series, n_obs, dtype)
    with _metrics.span(f"costs.{family}"):
        t0 = time.perf_counter()
        lowered = jax.jit(fn, backend=backend).lower(*args)
        lower_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0

    cost = None
    try:
        cost = _first(compiled.cost_analysis())
    except Exception:           # noqa: BLE001 — backend-dependent API
        pass
    mem = None
    try:
        mem = compiled.memory_analysis()
    except Exception:           # noqa: BLE001 — backend-dependent API
        pass
    hlo = ""
    try:
        hlo = compiled.as_text()
    except Exception:           # noqa: BLE001 — backend-dependent API
        pass

    def c_get(key):
        if not cost:
            return None
        val = cost.get(key)
        return float(val) if val is not None else None

    def m_get(attr):
        val = getattr(mem, attr, None) if mem is not None else None
        try:
            return int(val) if val is not None else None
        except (TypeError, ValueError):
            return None

    arg_b = m_get("argument_size_in_bytes")
    out_b = m_get("output_size_in_bytes")
    tmp_b = m_get("temp_size_in_bytes")
    code_b = m_get("generated_code_size_in_bytes")
    alias_b = m_get("alias_size_in_bytes")
    parts = [b for b in (arg_b, out_b, tmp_b, code_b) if b is not None]
    # arguments + outputs + temps + code live simultaneously at peak;
    # aliased buffers are counted once (they overlap arguments)
    peak = sum(parts) - (alias_b or 0) if parts else None

    flops = c_get("flops")
    bytes_accessed = c_get("bytes accessed")
    report: Dict[str, Any] = {
        "family": family,
        "n_series": int(n_series),
        "n_obs": int(n_obs),
        "platform": jax.devices(backend)[0].platform,
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "transcendentals": c_get("transcendentals"),
        "flops_per_byte": (round(flops / bytes_accessed, 3)
                           if flops and bytes_accessed else None),
        "flops_per_series": (round(flops / n_series, 1)
                             if flops and n_series else None),
        "argument_bytes": arg_b,
        "output_bytes": out_b,
        "temp_bytes": tmp_b,
        "generated_code_bytes": code_b,
        "peak_bytes": peak,
        "hlo_op_counts": hlo_op_counts(hlo),
        "hlo_ops_total": len(_HLO_OP_RE.findall(hlo)),
        "lower_s": round(lower_s, 4),
        "compile_s": round(compile_s, 4),
        "available": {
            "cost_analysis": cost is not None,
            "memory_analysis": mem is not None,
            "hlo_text": bool(hlo),
        },
    }
    return report


# ---------------------------------------------------------------------------
# Live device-memory telemetry
# ---------------------------------------------------------------------------

def device_memory_stats() -> Dict[str, Dict[str, int]]:
    """``memory_stats()`` per local device, keyed ``"d<i>"``.  Devices
    (or whole platforms — CPU) that expose nothing are simply absent;
    an empty dict means no device reports memory here."""
    import jax

    out: Dict[str, Dict[str, int]] = {}
    try:
        devices = jax.local_devices()
    except Exception:           # noqa: BLE001 — uninitializable backend
        return out
    for i, d in enumerate(devices):
        try:
            stats = d.memory_stats()
        except Exception:       # noqa: BLE001 — platform-dependent API
            continue
        if stats:
            out[f"d{i}"] = {k: int(v) for k, v in stats.items()
                            if isinstance(v, (int, float))}
    return out


def sample_device_memory(registry: Optional["_metrics.MetricsRegistry"]
                         = None) -> bool:
    """One sample of live device memory into ``device.mem.*`` gauges
    (``device.mem.d0.bytes_in_use``, ``...peak_bytes_in_use``, ...).
    Returns False (recording nothing) when no device exposes stats —
    the CPU no-op."""
    reg = registry if registry is not None else _metrics.get_registry()
    if not reg.enabled:
        return False
    stats = device_memory_stats()
    for dev, kv in stats.items():
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                    "largest_alloc_size"):
            if key in kv:
                reg.set_gauge(f"device.mem.{dev}.{key}", kv[key])
    return bool(stats)


_sampler_state = {"installed": False, "dead": False}


def _span_memory_sampler(path: str, dt: float) -> None:
    # one failed/empty probe disarms the sampler for the process: a
    # platform that reports nothing now will report nothing per-span
    # forever, and span exit is a hot path.  A merely *disabled*
    # registry is NOT evidence about the platform — skip without
    # disarming so re-enabling resumes sampling.
    if _sampler_state["dead"]:
        return
    reg = _metrics.get_registry()
    if not reg.enabled:
        return
    if not sample_device_memory(reg):
        _sampler_state["dead"] = True


def install_device_memory_sampler() -> bool:
    """Sample device memory at every span boundary (gauges are
    last-write-wins; the ``peak_bytes_in_use`` gauge is the workload's
    HBM watermark).  Idempotent; self-disarms permanently after the
    first probe on a platform with no memory stats, so CPU runs pay one
    probe total."""
    if not _sampler_state["installed"]:
        _metrics.add_span_listener(_span_memory_sampler)
        _sampler_state["installed"] = True
    return not _sampler_state["dead"]
