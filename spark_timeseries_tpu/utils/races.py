"""Runtime concurrency sanitizer: instrumented locks, a recorded
lock-acquisition-order graph, and a deterministic adversarial scheduler.

Level 2 of the concurrency tier (ISSUE 14).  Level 1 —
``tools/sts_lint`` STS101–STS104 — reads the *source*; this module
checks what actually **runs**: under :func:`instrument`, every lock the
library touches is wrapped so acquire/release (and thread spawns) are
recorded, which yields

- the **acquisition-order graph actually exercised** by a workload
  (:meth:`RaceHarness.order_graph` / :meth:`RaceHarness.assert_acyclic`)
  — the runtime cross-check of the static STS102 cycle detection: the
  lint proves no cycle is *written*, the harness proves none is
  *executed* on the driven paths;
- a **deterministic adversarial scheduler** (``instrument(seed=...)``):
  threads spawned through :meth:`RaceHarness.spawn` are serialized and,
  at every instrumented boundary (lock acquire/release and explicit
  :func:`yield_point` calls), the next runnable thread is chosen by a
  seeded RNG — same seed, same thread programs ⇒ the **same
  interleaving**, recorded in :attr:`RaceHarness.schedule_trace`.  An
  adversarial permutation of yield points is how a check-then-act race
  is *provably* tripped in a test instead of flaking once a month in
  production.

Instrumentation model (all host-side, nothing here may run under a
trace):

- ``threading.Lock`` / ``threading.RLock`` factories are patched for
  the duration of the context manager, so every lock *created* inside
  it (a fresh ``FitEngine``, a ``JobProgress``, a serving session's
  registry handles) is traced;
- the module-level locks that already exist at import time (the engine
  jit/default locks, the telemetry registries, the native build lock,
  the serving jit lock — :data:`KNOWN_LOCKS`) are rebound to traced
  wrappers and restored on exit;
- the default metrics registry's shared ``RLock`` is wrapped in place
  (the registry and every live metric handle share one lock object, so
  the wrapper is pushed into each);
- ``threading.Thread.start`` is patched to record spawns.

The scheduler serializes only threads spawned via
:meth:`RaceHarness.spawn`; foreign threads (the telemetry exporter, a
watchdog) still run free but their lock events are recorded.  ``make
verify-races`` drives the known-hot pairs: concurrent scrape vs
``inc()``, watchdog expiry vs chunk materialize, fleet pump vs scrape,
journal commit vs flight-recorder read (see ``tests/test_races.py``).
"""

from __future__ import annotations

import contextlib
import importlib
import os
import random
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

__all__ = ["instrument", "yield_point", "active", "RaceHarness",
           "TracedLock", "AdversarialScheduler", "SchedulerStall",
           "KNOWN_LOCKS", "MAX_EVENTS"]

# real primitives captured at import time — the harness's own internals
# must never run through its own instrumentation
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition
_REAL_THREAD_START = threading.Thread.start

# module-level locks that exist before any instrument() call can patch
# the factories; rebound (and restored) by name.  Keep in sync with the
# sts-lint concurrency inventory (docs/design.md §6d lock-ordering
# table).
KNOWN_LOCKS: Tuple[Tuple[str, str], ...] = (
    ("spark_timeseries_tpu.engine", "_jit_lock"),
    ("spark_timeseries_tpu.engine", "_default_lock"),
    ("spark_timeseries_tpu.statespace.serving", "_jit_lock"),
    ("spark_timeseries_tpu.utils.telemetry", "_jobs_lock"),
    ("spark_timeseries_tpu.utils.telemetry", "_sessions_lock"),
    ("spark_timeseries_tpu.utils.telemetry", "_fleets_lock"),
    ("spark_timeseries_tpu.utils.telemetry", "_runtimes_lock"),
    ("spark_timeseries_tpu.utils.telemetry", "_server_lock"),
    ("spark_timeseries_tpu.utils.metrics", "_install_lock"),
    ("spark_timeseries_tpu.utils.lineage", "_lock"),
    ("spark_timeseries_tpu.native", "_lock"),
)

MAX_EVENTS = 200_000          # bounded event ring: recording never OOMs

# default for how long a scheduler boundary may wait before declaring
# the run wedged (a real deadlock among scheduled threads, or a
# scheduled thread blocked on something the scheduler cannot see);
# override per run with ``instrument(stall_timeout_s=...)`` — e.g. when
# a scheduled thread legitimately cold-compiles a jitted function
STALL_TIMEOUT_S = 30.0


class SchedulerStall(RuntimeError):
    """The adversarial scheduler waited :data:`STALL_TIMEOUT_S` without
    any scheduled thread making progress — a real deadlock among the
    scheduled threads, or one of them is blocked outside instrumented
    boundaries."""


class TracedLock:
    """A lock wrapper recording acquire/release into the harness.

    Supports the context-manager protocol, ``acquire``/``release``, and
    delegates anything else (``Condition`` integration's
    ``_is_owned``/``_release_save``/``_acquire_restore``) to the inner
    lock.  When the harness is closed (the ``instrument`` block exited)
    the wrapper degrades to a transparent passthrough, so objects that
    outlive the block keep working.
    """

    def __init__(self, inner: Any, name: str, harness: "RaceHarness"):
        self._inner = inner
        self._name = name
        self._harness = harness

    # -- lock protocol ------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        h = self._harness
        if not h.active:
            return self._inner.acquire(blocking, timeout)
        sched = h.scheduler
        if sched is not None and blocking and sched.participating():
            # never hold the scheduler turn while blocked on a real
            # lock: spin try-acquire, parking at a boundary per miss
            while True:
                if self._inner.acquire(False):
                    h.record("acquire", self._name)
                    try:
                        sched.boundary(f"acquire:{self._name}")
                    except BaseException:
                        # a SchedulerStall here must not leak the real
                        # lock we just took: the wrapper is later
                        # unwound and the still-held inner lock would
                        # deadlock the whole process, masking the
                        # named stall with a silent hang
                        h.record("release", self._name)
                        self._inner.release()
                        raise
                    return True
                sched.boundary(f"acquire_wait:{self._name}")
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            h.record("acquire", self._name)
        return ok

    def release(self) -> None:
        h = self._harness
        if h.active:
            h.record("release", self._name)
            sched = h.scheduler
            self._inner.release()
            if sched is not None and sched.participating():
                sched.boundary(f"release:{self._name}")
            return
        self._inner.release()

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"TracedLock({self._name!r})"


class AdversarialScheduler:
    """Seeded deterministic thread serializer.

    Threads pre-registered via :meth:`register` (done by
    :meth:`RaceHarness.spawn` *before* the thread starts, so the live
    set never depends on OS start timing) run one at a time: each
    instrumented boundary parks the calling thread; when every live
    scheduled thread is parked, the seeded RNG picks which one proceeds.
    The decision sequence (:attr:`trace`) is a pure function of the seed
    and the thread programs — the determinism ``tests/test_races.py``
    pins.
    """

    def __init__(self, seed: int, stall_timeout_s: Optional[float] = None):
        self.seed = int(seed)
        self.stall_timeout_s = float(stall_timeout_s) \
            if stall_timeout_s is not None else STALL_TIMEOUT_S
        self._rng = random.Random(self.seed)
        self._cv = _REAL_CONDITION(_REAL_LOCK())
        self._live: Set[str] = set()
        self._waiting: Dict[str, str] = {}    # parked label -> boundary
        self._chosen: Optional[str] = None
        self._labels: Dict[int, str] = {}     # thread ident -> label
        # the decision sequence: (chosen label, the boundary it was
        # parked at).  Appended only at choice time — when every live
        # thread is parked — so it is a pure function of seed + thread
        # programs (boundary *arrival* order is OS timing and is
        # deliberately not recorded here)
        self.trace: List[Tuple[str, str]] = []

    # -- registration -------------------------------------------------------

    def register(self, label: str) -> None:
        with self._cv:
            if label in self._live:
                raise ValueError(f"duplicate scheduled label {label!r}")
            self._live.add(label)
            self._cv.notify_all()

    def bind(self, label: str) -> None:
        """Called on the spawned thread's first instruction: maps its
        ident to the pre-registered label."""
        with self._cv:
            self._labels[threading.get_ident()] = label
            self._cv.notify_all()

    def unregister(self, label: str) -> None:
        with self._cv:
            self._live.discard(label)
            self._waiting.pop(label, None)
            self._labels.pop(threading.get_ident(), None)
            if self._chosen == label:
                self._chosen = None
            # a shrinking live set can complete the everyone-is-parked
            # condition: re-evaluate so parked peers are not stranded
            self._maybe_choose()
            self._cv.notify_all()

    def participating(self) -> bool:
        return threading.get_ident() in self._labels

    # -- the serializing boundary ------------------------------------------

    def boundary(self, what: str) -> None:
        me = self._labels.get(threading.get_ident())
        if me is None:
            return
        with self._cv:
            self._waiting[me] = what
            self._maybe_choose()
            # wall-clock deadline (not iteration-counted: notify_all
            # wakes waiters early, which would over-count a loop budget)
            deadline = time.monotonic() + self.stall_timeout_s
            while self._chosen != me:
                self._maybe_choose()
                if self._chosen == me:
                    break
                self._cv.wait(0.02)
                if time.monotonic() > deadline:
                    raise SchedulerStall(
                        f"no progress for {self.stall_timeout_s:g}s: "
                        f"live={sorted(self._live)} "
                        f"waiting={sorted(self._waiting)} "
                        f"chosen={self._chosen!r} — a scheduled thread "
                        f"is blocked outside instrumented boundaries "
                        f"(raise instrument(stall_timeout_s=...) if its "
                        f"work is legitimately slow), or the threads "
                        f"genuinely deadlock")
            self._chosen = None
            self._waiting.pop(me, None)

    def _maybe_choose(self) -> None:
        # choose only when every live scheduled thread is parked — the
        # one condition that makes the pick order independent of OS
        # timing (threads not yet at a boundary could otherwise race
        # the choice)
        if self._chosen is None and self._waiting \
                and set(self._waiting) >= self._live:
            pick = self._rng.choice(sorted(self._waiting))
            self._chosen = pick
            self.trace.append((pick, self._waiting[pick]))
            self._cv.notify_all()


class RaceHarness:
    """One ``instrument()`` block's recording + scheduling state."""

    def __init__(self, seed: Optional[int] = None,
                 stall_timeout_s: Optional[float] = None):
        self.active = True
        self.scheduler = AdversarialScheduler(seed, stall_timeout_s) \
            if seed is not None else None
        self.events: List[Tuple[str, str, str]] = []
        self.errors: List[BaseException] = []
        self._edges: Dict[Tuple[str, str], str] = {}
        self._held: Dict[int, List[str]] = {}
        self._ilock = _REAL_LOCK()
        self._site_counts: Dict[str, int] = {}
        self._threads: List[threading.Thread] = []
        self._pending: List[threading.Thread] = []
        # ident -> display name.  The recording path must NEVER call
        # threading.current_thread(): on a foreign thread it constructs
        # a _DummyThread whose internal Event uses the (patched) lock
        # factory — infinite recursion through record()
        self._names: Dict[int, str] = {threading.get_ident(): "main"}

    # -- recording ----------------------------------------------------------

    def record(self, op: str, name: str) -> None:
        ident = threading.get_ident()
        with self._ilock:
            tname = self._names.get(ident) or f"t{ident}"
            if len(self.events) < MAX_EVENTS:
                self.events.append((tname, op, name))
            held = self._held.setdefault(ident, [])
            if op == "acquire":
                for a in held:
                    if a != name:
                        self._edges.setdefault((a, name), tname)
                held.append(name)
            elif op == "release":
                # remove the innermost matching acquisition (reentrant
                # RLocks release in LIFO order)
                for i in range(len(held) - 1, -1, -1):
                    if held[i] == name:
                        del held[i]
                        break

    def site_name(self, site: str) -> str:
        """Disambiguate several locks minted at one source site
        (``a, b = Lock(), Lock()``): the first keeps the plain site
        name, later ones get ``#2``, ``#3``... — per-site creation
        order is (same-thread) deterministic where overall creation
        order is not."""
        with self._ilock:
            n = self._site_counts.get(site, 0) + 1
            self._site_counts[site] = n
            return site if n == 1 else f"{site}#{n}"

    # -- the runtime lock-order graph ---------------------------------------

    def order_graph(self) -> Dict[str, Set[str]]:
        """``lock -> {locks acquired while holding it}`` as exercised."""
        with self._ilock:
            pairs = list(self._edges)
        graph: Dict[str, Set[str]] = {}
        for a, b in pairs:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        return graph

    def cycles(self) -> List[List[str]]:
        """SCCs of size > 1 in the exercised acquisition-order graph —
        the runtime mirror of sts-lint STS102.

        The Tarjan body deliberately duplicates
        ``tools/sts_lint/analysis.py::ConcurrencyModel.lock_cycles``:
        the shipped package must not import ``tools/`` (not installed),
        and the pure-AST linter must not import the package it lints (a
        broken package would crash the tool that reports the break).
        Keep the two in lockstep."""
        graph = self.order_graph()
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        def strong(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in sorted(graph[v]):
                if w not in index:
                    strong(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))

        for v in sorted(graph):
            if v not in index:
                strong(v)
        return sorted(out)

    def assert_acyclic(self) -> None:
        cyc = self.cycles()
        if cyc:
            raise AssertionError(
                f"lock-acquisition-order cycle(s) exercised at runtime: "
                f"{cyc}; edges={sorted(self._edges)}")

    @property
    def schedule_trace(self) -> List[Tuple[str, str]]:
        """The scheduler's decision/boundary sequence (empty without a
        seed) — the object the same-seed determinism test compares."""
        return list(self.scheduler.trace) if self.scheduler else []

    # -- scheduled thread spawning ------------------------------------------

    def spawn(self, fn: Callable[[], Any], *,
              label: Optional[str] = None) -> threading.Thread:
        """Create a daemon worker with exception capture into
        :attr:`errors`.  Without a scheduler it starts immediately.
        With one armed, the worker is registered now but *started* by
        :meth:`start_all` / :meth:`join_all` — the full participant set
        must be fixed before the first scheduling decision, or the
        schedule would depend on how fast each spawn call raced the
        chooser."""
        name = label or f"worker-{len(self._threads)}"
        sched = self.scheduler
        if sched is not None:
            sched.register(name)

        def _runner() -> None:
            try:
                with self._ilock:
                    self._names[threading.get_ident()] = name
                if sched is not None:
                    sched.bind(name)
                    sched.boundary("start")
                fn()
            except BaseException as e:  # noqa: BLE001 — surfaced via
                # .errors; a silent thread death is exactly what
                # STS104 exists to prevent
                with self._ilock:
                    self.errors.append(e)
            finally:
                if sched is not None:
                    sched.unregister(name)

        t = threading.Thread(target=_runner, name=name, daemon=True)
        self._threads.append(t)
        if sched is None:
            t.start()
        else:
            self._pending.append(t)
        return t

    def start_all(self) -> None:
        """Start every scheduler-deferred worker (the participant set
        is now complete; the seeded chooser takes over from here)."""
        pending, self._pending = self._pending, []
        for t in pending:
            t.start()

    def join_all(self, timeout: float = 60.0) -> None:
        self.start_all()
        for t in self._threads:
            t.join(timeout)
        alive = [t.name for t in self._threads if t.is_alive()]
        if alive:
            raise AssertionError(f"workers still alive after "
                                 f"{timeout:g}s: {alive}")

    def raise_errors(self) -> None:
        if self.errors:
            raise self.errors[0]

    def wrap(self, name: str, lock: Any) -> "TracedLock":
        """Wrap an arbitrary pre-existing lock object (an engine
        instance's cache lock, a fixture's own lock); the caller rebinds
        the returned wrapper wherever the lock lives."""
        if isinstance(lock, TracedLock):
            return lock
        return TracedLock(lock, name, self)


_active: Optional[RaceHarness] = None


def active() -> Optional[RaceHarness]:
    """The harness of the enclosing ``instrument()`` block, if any."""
    return _active


def yield_point() -> None:
    """An explicit scheduling boundary: free when uninstrumented, a
    deterministic preemption point under ``instrument(seed=...)``.
    Sprinkle into check-then-act windows you want the adversarial
    scheduler to be able to split (see users.md "Checking your own
    extension for races")."""
    h = _active
    if h is not None and h.scheduler is not None:
        h.scheduler.boundary("yield")


def _wrap_registry(harness: RaceHarness, registry) -> List[Tuple[Any,
                                                                 str, Any]]:
    """Wrap the metrics registry's shared RLock in place: the registry
    and every live metric handle hold the SAME lock object, so each
    holder's ``_lock`` attribute is rebound to one shared wrapper."""
    restores: List[Tuple[Any, str, Any]] = []
    inner = registry._lock
    wrapper = TracedLock(inner, "metrics.registry", harness)
    holders = [registry]
    for table in (registry._counters, registry._gauges,
                  registry._histograms, registry._spans):
        holders.extend(table.values())
    for holder in holders:
        if getattr(holder, "_lock", None) is inner:
            restores.append((holder, "_lock", inner))
            holder._lock = wrapper
    return restores


@contextlib.contextmanager
def instrument(seed: Optional[int] = None, *, wrap_known: bool = True,
               wrap_registry: bool = True,
               stall_timeout_s: Optional[float] = None):
    """Arm the sanitizer for the dynamic extent of the block.

    ``seed=None`` records only (lock events, spawns, the order graph);
    an integer seed additionally arms the deterministic adversarial
    scheduler for threads spawned via :meth:`RaceHarness.spawn`
    (``stall_timeout_s`` overrides the :data:`STALL_TIMEOUT_S` wedge
    deadline — raise it when a scheduled thread legitimately does slow
    uninstrumented work, e.g. a cold XLA compile).  Pre-existing
    instance locks are wrapped via :meth:`RaceHarness.wrap`.  Nesting
    is rejected — one harness owns the factories at a time.
    """
    global _active
    if _active is not None:
        raise RuntimeError("races.instrument() blocks do not nest")
    harness = RaceHarness(seed, stall_timeout_s)
    restores: List[Tuple[Any, str, Any]] = []

    def _site_name(kind: str) -> str:
        # name by creation SITE, not creation order: the same program
        # must produce the same lock names run over run (the
        # determinism pin compares schedule traces containing them),
        # and import-time lock creation would otherwise shift a
        # counter between first and later runs
        frame = sys._getframe(2)
        return (f"{kind}@{os.path.basename(frame.f_code.co_filename)}"
                f":{frame.f_lineno}")

    def traced_lock_factory():
        return TracedLock(_REAL_LOCK(),
                          harness.site_name(_site_name("lock")), harness)

    def traced_rlock_factory():
        return TracedLock(_REAL_RLOCK(),
                          harness.site_name(_site_name("rlock")),
                          harness)

    def traced_start(thread, *a, **kw):
        harness.record("spawn", thread.name)
        return _REAL_THREAD_START(thread, *a, **kw)

    try:
        # import the known-lock owners BEFORE patching the factories,
        # so a first-ever import doesn't mint its module locks through
        # the traced path (names and counts must not depend on import
        # history)
        known_mods = []
        if wrap_known:
            for mod_name, attr in KNOWN_LOCKS:
                try:
                    known_mods.append(
                        (importlib.import_module(mod_name), mod_name,
                         attr))
                except Exception:  # noqa: BLE001 — a tier that cannot
                    continue       # import is simply not instrumented
        threading.Lock = traced_lock_factory        # type: ignore
        threading.RLock = traced_rlock_factory      # type: ignore
        threading.Thread.start = traced_start       # type: ignore
        restores.append((threading, "Lock", _REAL_LOCK))
        restores.append((threading, "RLock", _REAL_RLOCK))
        restores.append((threading.Thread, "start", _REAL_THREAD_START))
        for mod, mod_name, attr in known_mods:
            inner = getattr(mod, attr, None)
            if inner is None or isinstance(inner, TracedLock):
                continue
            short = f"{mod_name.rsplit('.', 1)[-1]}.{attr}"
            restores.append((mod, attr, inner))
            setattr(mod, attr, TracedLock(inner, short, harness))
        if wrap_registry:
            from . import metrics as _metrics
            restores.extend(_wrap_registry(harness,
                                           _metrics.get_registry()))
        _active = harness
        yield harness
    finally:
        _active = None
        harness.active = False
        for owner, attr, value in reversed(restores):
            try:
                setattr(owner, attr, value)
            except Exception:  # noqa: BLE001 — restoration must finish
                pass
