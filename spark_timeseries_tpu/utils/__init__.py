"""Auxiliary subsystems: checkpointing, observability, plotting.

The reference has no tracing/metrics/checkpoint tier (SURVEY.md §5) — its
fault tolerance is Spark lineage and its only observability is the Spark UI.
Here the equivalents are explicit: pytree checkpoints (fits are idempotent
and restartable), a profiler/timing harness, and convergence counters.
"""

from . import checkpoint, observability, plot  # noqa: F401

__all__ = ["checkpoint", "observability", "plot"]
