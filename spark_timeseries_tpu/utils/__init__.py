"""Auxiliary subsystems: checkpointing, observability, metrics, plotting.

The reference has no tracing/metrics/checkpoint tier (SURVEY.md §5) — its
fault tolerance is Spark lineage and its only observability is the Spark UI.
Here the equivalents are explicit: pytree checkpoints (fits are idempotent
and restartable), a profiler/timing harness plus convergence counters
(``observability``), the structured runtime-metrics spine —
counters/gauges/histograms, nested spans, XLA recompile tracking —
(``metrics``) that ``bench.py`` embeds into every benchmark artifact,
the Perfetto timeline export over the span ring buffer (``tracing``),
and the compiled-program cost/memory analysis tier (``costs``).
"""

from . import (checkpoint, costs, metrics, observability,  # noqa: F401
               races, resilience, tracing)

__all__ = ["checkpoint", "costs", "metrics", "observability", "plot",
           "races", "resilience", "tracing"]


def __getattr__(name):
    # plot pulls in the models tier, and the ops tier imports this package
    # for metrics — loading plot lazily (PEP 562) keeps ops -> utils free
    # of the ops -> utils -> plot -> models -> ops cycle
    if name == "plot":
        import importlib
        mod = importlib.import_module(".plot", __name__)
        globals()["plot"] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
