"""Auxiliary subsystems: checkpointing, observability, metrics, plotting.

The reference has no tracing/metrics/checkpoint tier (SURVEY.md §5) — its
fault tolerance is Spark lineage and its only observability is the Spark UI.
Here the equivalents are explicit: pytree checkpoints (fits are idempotent
and restartable), a profiler/timing harness plus convergence counters
(``observability``), and the structured runtime-metrics spine —
counters/gauges/histograms, nested spans, XLA recompile tracking —
(``metrics``) that ``bench.py`` embeds into every benchmark artifact.
"""

from . import checkpoint, metrics, observability, resilience  # noqa: F401

__all__ = ["checkpoint", "metrics", "observability", "plot", "resilience"]


def __getattr__(name):
    # plot pulls in the models tier, and the ops tier imports this package
    # for metrics — loading plot lazily (PEP 562) keeps ops -> utils free
    # of the ops -> utils -> plot -> models -> ops cycle
    if name == "plot":
        import importlib
        mod = importlib.import_module(".plot", __name__)
        globals()["plot"] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
