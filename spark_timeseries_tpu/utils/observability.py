"""Profiling, timing, and fit-convergence observability.

The reference's only in-library telemetry is ``println`` warnings for
non-stationary fits and ``seriesStats`` summaries
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/ARIMA.scala:248-256``,
``TimeSeriesRDD.scala:265-267``); everything else is delegated to the Spark
UI.  Here: ``jax.profiler`` traces, a ``block_until_ready`` timing harness,
and structured convergence counters off the batched optimizers
(SURVEY.md §5).
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from typing import Any, Callable, Dict

import jax
import numpy as np

logger = logging.getLogger("spark_timeseries_tpu")


@contextlib.contextmanager
def trace(name: str):
    """Named profiler scope; shows up in ``jax.profiler`` traces around the
    fit kernels."""
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def profile(log_dir: str):
    """Capture a full device trace to ``log_dir`` (view with TensorBoard or
    xprof)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def timed(fn: Callable, *args, warmup: int = 1, iters: int = 3,
          **kwargs) -> Dict[str, Any]:
    """Wall-time a jitted callable with ``block_until_ready`` fencing;
    returns {mean_s, min_s, result}."""
    result = None
    for _ in range(warmup):
        result = jax.block_until_ready(fn(*args, **kwargs))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        result = jax.block_until_ready(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    return {"mean_s": float(np.mean(times)), "min_s": float(np.min(times)),
            "result": result}


def fit_report(result_or_model) -> Dict[str, Any]:
    """Convergence counters — the batched answer to the reference's
    per-series println warnings (ref ``ARIMA.scala:246-256``).

    Accepts a batched ``MinimizeResult``, a ``FitDiagnostics``, or any fitted
    model (every ``fit``/``fit_panel`` attaches ``model.diagnostics``), so
    counting non-converged lanes is one call on the public fit output::

        model = arima.fit_panel(panel, 2, 1, 2)
        report = fit_report(model)          # {"n_converged": ..., ...}
    """
    diag = getattr(result_or_model, "diagnostics", None)
    if diag is not None:
        result_or_model = diag
    if not hasattr(result_or_model, "converged"):
        raise TypeError(
            f"{type(result_or_model).__name__} carries no fit diagnostics "
            "(was it produced by a fit()?)")
    converged = np.asarray(result_or_model.converged)
    n_iter = np.asarray(result_or_model.n_iter)
    fun = np.asarray(result_or_model.fun)
    report = {
        "n_series": int(converged.size),
        "n_converged": int(np.sum(converged)),
        "n_diverged": int(np.sum(~np.isfinite(fun))),
        "iters_mean": float(np.mean(n_iter)),
        "iters_max": int(np.max(n_iter)) if n_iter.size else 0,
    }
    logger.info("fit_report %s", json.dumps(report))
    return report
