"""Profiling, timing, and fit-convergence observability.

The reference's only in-library telemetry is ``println`` warnings for
non-stationary fits and ``seriesStats`` summaries
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/ARIMA.scala:248-256``,
``TimeSeriesRDD.scala:265-267``); everything else is delegated to the Spark
UI.  Here: ``jax.profiler`` traces, the shared wall-timing harnesses
(:func:`timed`, :func:`timed_min` — the one place the benchmark timing
protocol lives), and structured convergence counters off the batched
optimizers (SURVEY.md §5).  Structured counters/spans/recompile tracking
live next door in :mod:`spark_timeseries_tpu.utils.metrics`;
:func:`fit_report` feeds its registry so repeated fits accumulate.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

logger = logging.getLogger("spark_timeseries_tpu")

_configured_handler: Optional[logging.Handler] = None


def configure_logging(level=logging.INFO, stream=None) -> logging.Handler:
    """Opt-in console logging for the package logger.

    The package attaches only a ``NullHandler`` (library-logging hygiene:
    importing it never touches the root logger or prints anything), so
    ``fit_report``'s ``logger.info`` lines are invisible by default.  This
    helper makes them visible without the application configuring the
    root logger::

        observability.configure_logging("INFO")

    ``level`` is a logging level name or constant; ``stream`` defaults to
    stderr.  Idempotent — calling again replaces the previous handler
    (e.g. to change level or stream) instead of stacking duplicates.
    """
    global _configured_handler
    if isinstance(level, str):
        level = logging.getLevelName(level.upper())
    if _configured_handler is not None:
        logger.removeHandler(_configured_handler)
    handler = logging.StreamHandler(stream)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(name)s %(levelname)s %(message)s"))
    logger.addHandler(handler)
    logger.setLevel(level)
    # while this handler is active the package logger must not also
    # propagate to root, or an app with root logging configured would see
    # every record twice
    logger.propagate = False
    _configured_handler = handler
    return handler


@contextlib.contextmanager
def trace(name: str):
    """Named profiler scope; shows up in ``jax.profiler`` traces around the
    fit kernels.  For a scope that also records wall time into the metrics
    registry, use :func:`metrics.span`."""
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def profile(log_dir: str):
    """Capture a full device trace to ``log_dir`` (view with TensorBoard or
    xprof)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def timed(fn: Callable, *args, warmup: int = 1, iters: int = 3,
          **kwargs) -> Dict[str, Any]:
    """Wall-time a jitted callable with ``block_until_ready`` fencing;
    returns {mean_s, min_s, result}.  For the benchmark tier's stricter
    materializing protocol (min estimator, host round trip per rep), use
    :func:`timed_min`."""
    result = None
    for _ in range(warmup):
        result = jax.block_until_ready(fn(*args, **kwargs))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        result = jax.block_until_ready(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    return {"mean_s": float(np.mean(times)), "min_s": float(np.min(times)),
            "result": result}


def timed_min(fn, *args, reps: int = 3, want_out: bool = False):
    """Wall-time ``fn(*args)`` (materializing every output on host), min
    over ``reps`` after one warm call: the tunnel's per-call RTT jitter is
    strictly additive noise, so the minimum is the cleanest estimator.
    Materialization goes through ``np.asarray`` on every output leaf —
    on the tunneled TPU platform ``block_until_ready`` alone does not
    synchronize, so the host round trip is part of the protocol.

    THE shared timing protocol for every benchmark entry point
    (``bench.py``, ``benchmarks/roofline.py``, ``benchmarks/pallas_ab.py``,
    ``benchmarks/bench_suite.py`` — all import it, directly or via
    ``bench.timed_min``), so their numbers cannot drift apart.
    ``want_out=True`` returns ``(seconds, out)`` with the last run's
    materialized outputs.
    """
    def materialize():
        return jax.tree_util.tree_map(np.asarray, fn(*args))

    out = materialize()                                  # warm + sync
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = materialize()
        best = min(best, time.perf_counter() - t0)
    return (best, out) if want_out else best


def fit_report(result_or_model, family: Optional[str] = None
               ) -> Dict[str, Any]:
    """Convergence counters — the batched answer to the reference's
    per-series println warnings (ref ``ARIMA.scala:246-256``).

    Accepts a batched ``MinimizeResult``, a ``FitDiagnostics``, or any fitted
    model (every ``fit``/``fit_panel`` attaches ``model.diagnostics``), so
    counting non-converged lanes is one call on the public fit output::

        model = arima.fit_panel(panel, 2, 1, 2)
        report = fit_report(model)          # {"n_converged": ..., ...}

    Besides the headline counts the report carries ``frac_converged`` and
    the iteration distribution (``iters_mean``/``iters_p50``/``iters_p95``/
    ``iters_max``) — under vmap every lane pays the slowest lane's
    iterations, so the p95/max gap is the first thing to read when a fit
    stage regresses.  Each report is also accumulated into the metrics
    registry as a ``fit_report.<family>.*`` counter bundle
    (:func:`metrics.record_fit_report`), so repeated fits add up across a
    workload; ``family`` defaults to a name derived from the input's type
    (``ARIMAModel`` -> ``arima``).
    """
    source = result_or_model
    diag = getattr(result_or_model, "diagnostics", None)
    if diag is not None:
        result_or_model = diag
    if not hasattr(result_or_model, "converged"):
        raise TypeError(
            f"{type(result_or_model).__name__} carries no fit diagnostics "
            "(was it produced by a fit()?)")
    if family is None:
        import re
        name = type(source).__name__
        for suffix in ("Model", "Result", "Diagnostics"):
            if name.endswith(suffix):
                name = name[:-len(suffix)]
                break
        # snake_case so the derived family matches the instrument_fit
        # bundle spelling (HoltWintersModel -> holt_winters, matching
        # fit.holt_winters.*; RegressionARIMAModel -> regression_arima)
        name = re.sub(r"(?<=[a-z0-9])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])",
                      "_", name)
        family = name.lower() or "fit"
    converged = np.asarray(result_or_model.converged)
    n_iter = np.asarray(result_or_model.n_iter)
    fun = np.asarray(result_or_model.fun)
    n_series = int(converged.size)
    n_converged = int(np.sum(converged))
    report = {
        "n_series": n_series,
        "n_converged": n_converged,
        "frac_converged": (n_converged / n_series) if n_series else 0.0,
        "n_diverged": int(np.sum(~np.isfinite(fun))),
        "iters_mean": float(np.mean(n_iter)) if n_iter.size else 0.0,
        "iters_p50": float(np.percentile(n_iter, 50)) if n_iter.size else 0.0,
        "iters_p95": float(np.percentile(n_iter, 95)) if n_iter.size else 0.0,
        "iters_max": int(np.max(n_iter)) if n_iter.size else 0,
    }
    from . import metrics
    metrics.record_fit_report(family, report)
    logger.info("fit_report %s %s", family, json.dumps(report))
    return report
