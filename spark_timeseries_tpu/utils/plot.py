"""Plotting: line plots and ACF/PACF with confidence bands.

Capability parity with the reference's ``EasyPlot``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/EasyPlot.scala:24-120``),
with matplotlib replacing breeze-viz.  PACF uses the AR(maxLag) coefficients
exactly as the reference does (``EasyPlot.scala:85-96``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..models import autoregression
from ..ops.univariate import autocorr


def _figure():
    import matplotlib
    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt
    return plt.subplots()


def calc_conf_val(conf: float, n: int) -> float:
    """Two-sided normal confidence bound scaled by sqrt(n)
    (ref ``EasyPlot.scala:98-102``)."""
    from scipy.stats import norm
    return float(norm.ppf(1.0 - (1.0 - conf) / 2.0) / np.sqrt(n))


def ezplot(series, style: str = "-"):
    """Line plot of one series or a sequence of series
    (ref ``EasyPlot.scala:25-53``)."""
    fig, ax = _figure()
    arr = np.asarray(series)
    if arr.ndim == 1:
        arr = arr[None, :]
    for row in arr:
        ax.plot(np.arange(row.size), row, style)
    return fig


def forecast_plot(data, model, n_future: int, conf: float = 0.95):
    """History, point forecast, and shaded prediction bands for one series
    — beyond reference (``EasyPlot`` has no forecast view).

    ``model`` is any fitted model exposing
    ``forecast_interval(ts, n_future, conf)`` (ARIMA, Holt-Winters
    additive, EWMA).  ARIMA's full-length output (historical one-step fits
    + future) is split automatically; the bands always cover exactly the
    ``n_future`` future steps.
    """
    import jax.numpy as jnp

    arr = np.asarray(data)
    if arr.ndim != 1:
        raise ValueError("forecast_plot draws one series; slice the panel")
    point, lo, hi = model.forecast_interval(jnp.asarray(arr), n_future,
                                            conf)
    point, lo, hi = (np.asarray(v) for v in (point, lo, hi))
    if point.ndim != 1:
        raise ValueError(
            "forecast_plot draws one series, but the model is panel-fitted "
            "(batched parameters); select one lane's model first")
    future = point[..., -n_future:] \
        if point.shape[-1] != n_future else point

    fig, ax = _figure()
    n = arr.shape[-1]
    t_fut = n - 1 + np.arange(n_future + 1)
    ax.plot(np.arange(n), arr, color="C0", label="observed")
    # prepend the last observation so the forecast connects visually
    ax.plot(t_fut, np.r_[arr[-1], future], color="C1", label="forecast")
    ax.fill_between(t_fut[1:], lo, hi, color="C1", alpha=0.25,
                    label=f"{int(round(conf * 100))}% band")
    ax.legend()
    return fig


def _draw_corr(ax, corrs: np.ndarray, conf_val: float) -> None:
    """Vertical correlation bars + horizontal confidence lines
    (ref ``EasyPlot.scala:104-119``)."""
    for i, c in enumerate(corrs):
        ax.plot([i + 1, i + 1], [0.0, c], color="C0")
    n = len(corrs)
    xs = np.arange(n + 1)
    for v in (conf_val, -conf_val):
        ax.plot(xs, np.full(n + 1, v), "-", color="red")


def acf_plot(data, max_lag: int, conf: float = 0.95):
    """Autocorrelation plot (ref ``EasyPlot.scala:61-75``)."""
    arr = np.asarray(data)
    corrs = np.asarray(autocorr(arr, max_lag))
    fig, ax = _figure()
    ax.set_title("Autocorrelation function")
    ax.set_xlabel("Lag")
    ax.set_ylabel("Autocorrelation")
    _draw_corr(ax, corrs, calc_conf_val(conf, arr.size))
    return fig


def pacf_plot(data, max_lag: int, conf: float = 0.95):
    """Partial autocorrelation plot: the AR(maxLag) coefficients
    (ref ``EasyPlot.scala:77-96``)."""
    arr = np.asarray(data)
    model = autoregression.fit(arr, max_lag)
    pcorrs = np.asarray(model.coefficients)
    fig, ax = _figure()
    ax.set_title("Partial autocorrelation function")
    ax.set_xlabel("Lag")
    ax.set_ylabel("Partial Autocorrelation")
    _draw_corr(ax, pcorrs, calc_conf_val(conf, arr.size))
    return fig
