"""Live telemetry plane: an HTTP scrape endpoint over the running process.

Every observability surface before this one (the metrics registry, the
trace ring, bench's cost/static blocks) is **end-of-run**: you learn
what a job did only after it exits, and a wedged ``stream_fit`` or
``ServingSession`` is a black box until its deadline fires.  ARIMA_PLUS
(PAPERS.md, arXiv 2510.24452) runs forecasting as a continuously
*monitored* in-database service; this module is that tier — the running
process made observable from outside, with zero new dependencies:

- a **scrape server** (:func:`start` / ``STS_TELEMETRY_PORT``): a
  stdlib ``http.server`` daemon thread serving

  ===================  ====================================================
  route                payload
  ===================  ====================================================
  ``/metrics``         Prometheus text (``metrics.to_prometheus``)
  ``/snapshot.json``   registry snapshot + active job progress + serving
                       session summaries + recent incident index
  ``/trace.json``      the trace ring as Chrome trace JSON
                       (``?limit=N`` keeps the newest N events)
  ``/healthz``         liveness + per-job heartbeat staleness (HTTP 503
                       when any active job's heartbeat is stale)
  ===================  ====================================================

  **Zero threads and zero overhead when not started**: nothing here runs
  until :func:`start` is called (or a job/session entry point sees
  ``STS_TELEMETRY_PORT`` in the environment via
  :func:`ensure_started_from_env`).  Strictly host-side — the exporter
  reads registries and host-side progress structs; nothing enters traced
  code (STS001/STS002 stay clean by construction).

- **job heartbeats** (:class:`JobProgress`): ``engine.stream_fit``
  registers one per run and stamps it at every chunk dispatch and
  materialization, so a *hung* chunk is visible (heartbeat age grows)
  before its deadline fires.  Chunk completions feed an EW-smoothed
  chunk cadence, which yields the ETA ``/snapshot.json`` and
  ``tools/sts_top.py`` display.  Staleness contract (``/healthz``): a
  heartbeat older than ``STS_TELEMETRY_STALE_FACTOR`` (default 5) times
  the expected chunk cadence (the EW estimate once a chunk has
  completed, :data:`DEFAULT_EXPECTED_CHUNK_S` before that) reports the
  job — and the process — unhealthy.

- **serving session registry**: every live ``ServingSession`` is weakly
  tracked and summarized (label, lane health, rolling tick-latency
  p50/p95, SLO burn count) into ``/snapshot.json``; sessions vanish
  from the snapshot when garbage-collected, never pinned.  Live
  ``FleetScheduler`` instances are tracked the same way — the
  ``fleets`` section carries each scheduler's aggregate (tenants,
  groups, queue depth, p95, shed state) plus per-tenant admission/
  cache rows, the panel ``tools/sts_top.py`` renders.

The incident index in ``/snapshot.json`` comes from
:mod:`~spark_timeseries_tpu.utils.flightrec` (lazy import — the two
modules reference each other only at call time).
"""

from __future__ import annotations

import itertools
import json
import math
import os
import sys
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from . import metrics as _metrics

__all__ = [
    "JobProgress", "TelemetryAlreadyStarted", "TelemetryServer",
    "start", "stop", "server", "ensure_started_from_env",
    "new_job_id", "register_job", "finish_job", "active_jobs",
    "recent_jobs", "register_session", "live_sessions",
    "session_summaries", "register_fleet", "live_fleets",
    "fleet_summaries", "register_fleet_runtime", "live_fleet_runtimes",
    "fleet_runtime_rows",
    "snapshot_doc", "healthz_doc", "json_safe", "env_positive",
    "DEFAULT_STALE_FACTOR", "DEFAULT_EXPECTED_CHUNK_S", "RECENT_JOBS_KEPT",
]

# EW smoothing factor for the chunk-completion cadence (higher = more
# reactive ETA, noisier under jittery chunk times).
EW_ALPHA = 0.3

# heartbeat staleness = age > factor * expected chunk cadence
DEFAULT_STALE_FACTOR = 5.0

# cadence assumed for a job whose first chunk hasn't completed yet (a
# first chunk legitimately pays trace+compile time, so the pre-cadence
# grace must be generous; 5x60s = 5 minutes by default)
DEFAULT_EXPECTED_CHUNK_S = 60.0

# finished jobs kept for /snapshot.json context (bounded)
RECENT_JOBS_KEPT = 16


def json_safe(obj: Any) -> Any:
    """Recursively replace non-finite floats with None — strict JSON has
    no Infinity/NaN, and a scrape endpoint must never emit a payload the
    scraper's parser rejects."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


def env_positive(name: str, cast: type = float, default: Any = None):
    """Parse a positive numeric environment knob: unset (or empty)
    returns ``default``; junk or a non-positive value raises a named
    ValueError.  The one implementation behind every ``STS_*`` numeric
    knob the telemetry plane reads (staleness factor, serving SLO,
    incident retention/trace budget), so the error contract cannot
    drift between them."""
    env = os.environ.get(name)
    if not env:
        return default
    try:
        v = cast(env)
        if v <= 0:
            raise ValueError
        return v
    except ValueError:
        kind = "integer" if cast is int else "number"
        raise ValueError(
            f"{name} must be a positive {kind}, got {env!r}") from None


def _stale_factor() -> float:
    return env_positive("STS_TELEMETRY_STALE_FACTOR", float,
                        DEFAULT_STALE_FACTOR)


# ---------------------------------------------------------------------------
# JobProgress: the structured heartbeat one streaming job publishes
# ---------------------------------------------------------------------------

_job_seq = itertools.count(1)


def new_job_id(family: str = "job") -> str:
    """Process-unique, human-scannable job id (``<family>-<pid>-<n>``)."""
    return f"{family}-{os.getpid()}-{next(_job_seq)}"


class JobProgress:
    """Mutable, lock-protected progress/heartbeat record for one
    ``engine.stream_fit`` run.

    The engine stamps :meth:`heartbeat` at every chunk **dispatch** and
    **materialize** (so a hung chunk shows a growing heartbeat age while
    the watchdog counts down) and calls :meth:`note_chunk_done` on every
    completion, which feeds the EW-smoothed chunk cadence behind
    :attr:`eta_s`.  Everything is host wall-clock (``time.time``);
    nothing here may be called from traced code.
    """

    def __init__(self, job_id: str, family: str, n_series: int,
                 n_chunks: int, chunk_size: int, *,
                 journal_path: Optional[str] = None,
                 resilient: bool = False):
        self._lock = threading.Lock()
        self.job_id = str(job_id)
        self.family = str(family)
        self.n_series = int(n_series)
        self.n_chunks = int(n_chunks)
        self.chunk_size = int(chunk_size)
        self.journal_path = journal_path
        self.resilient = bool(resilient)
        now = time.time()
        self.started_unix = now
        self.finished_unix: Optional[float] = None
        self.last_heartbeat_unix = now
        self.heartbeat_stage = "submitted"
        self.heartbeat_chunk: Optional[List[int]] = None
        self.status = "running"           # running | done | failed
        self.error: Optional[str] = None
        self.chunks_done = 0
        self.chunks_restored = 0          # journal resume hits
        self.chunks_failed = 0            # declared dead (incl. data)
        self.chunks_quarantined = 0
        self.chunks_degraded = 0
        # OOM-degraded sub-ranges complete/die separately from their
        # parent chunk; counting them into chunks_done/failed would
        # push done past n_chunks and collapse the ETA — they get their
        # own counters (a split chunk whose halves partly die stays in
        # chunks_remaining: honest, slightly pessimistic ETA)
        self.subchunks_done = 0
        self.subchunks_failed = 0
        self.journal_commits = 0
        self.ew_chunk_s: Optional[float] = None
        self._last_done_t: Optional[float] = None

    # -- engine-side mutation -----------------------------------------------

    def heartbeat(self, stage: str,
                  chunk: Optional[tuple] = None) -> None:
        with self._lock:
            self.last_heartbeat_unix = time.time()
            self.heartbeat_stage = str(stage)
            if chunk is not None:
                self.heartbeat_chunk = [int(chunk[0]), int(chunk[1])]

    def note_chunk_done(self, *, restored: bool = False) -> None:
        """One chunk completed (fit or journal-restored): advance the
        done count and fold the completion-to-completion interval into
        the EW cadence (restored chunks are near-instant and would fake
        an optimistic cadence, so they only count, never smooth)."""
        now = time.time()
        with self._lock:
            self.last_heartbeat_unix = now
            self.chunks_done += 1
            if restored:
                self.chunks_restored += 1
                self.heartbeat_stage = "journal_restore"
            else:
                self.heartbeat_stage = "chunk_done"
                prev = self._last_done_t if self._last_done_t is not None \
                    else self.started_unix
                dt = max(now - prev, 0.0)
                self.ew_chunk_s = dt if self.ew_chunk_s is None \
                    else EW_ALPHA * dt + (1.0 - EW_ALPHA) * self.ew_chunk_s
                self._last_done_t = now

    def note(self, *, failed: int = 0, quarantined: int = 0,
             degraded: int = 0, journal_commits: int = 0,
             subchunks_done: int = 0, subchunks_failed: int = 0) -> None:
        with self._lock:
            self.chunks_failed += failed
            self.chunks_quarantined += quarantined
            self.chunks_degraded += degraded
            self.journal_commits += journal_commits
            self.subchunks_done += subchunks_done
            self.subchunks_failed += subchunks_failed
            if subchunks_done or subchunks_failed:
                self.last_heartbeat_unix = time.time()

    def finish(self, status: str, error: Optional[str] = None) -> None:
        with self._lock:
            self.status = status
            self.error = error
            self.finished_unix = time.time()
            self.last_heartbeat_unix = self.finished_unix
            self.heartbeat_stage = status

    # -- derived views ------------------------------------------------------

    @property
    def chunks_remaining(self) -> int:
        return max(self.n_chunks - self.chunks_done - self.chunks_failed, 0)

    @property
    def eta_s(self) -> Optional[float]:
        """Seconds until the stream drains at the EW cadence (None until
        the first non-restored chunk completes)."""
        if self.status != "running" or self.ew_chunk_s is None:
            return None
        return self.ew_chunk_s * self.chunks_remaining

    @property
    def throughput_series_per_s(self) -> Optional[float]:
        if self.ew_chunk_s is None or self.ew_chunk_s <= 0:
            return None
        return self.chunk_size / self.ew_chunk_s

    def heartbeat_age_s(self) -> float:
        return max(time.time() - self.last_heartbeat_unix, 0.0)

    def stale_after_s(self, factor: Optional[float] = None) -> float:
        """The heartbeat-age threshold past which this job reports
        unhealthy: ``factor``x the expected chunk cadence (the EW
        estimate, or :data:`DEFAULT_EXPECTED_CHUNK_S` before the first
        chunk completes)."""
        f = _stale_factor() if factor is None else float(factor)
        cadence = self.ew_chunk_s if self.ew_chunk_s \
            else DEFAULT_EXPECTED_CHUNK_S
        return f * max(cadence, 1.0)

    def is_stale(self, factor: Optional[float] = None) -> bool:
        return self.status == "running" \
            and self.heartbeat_age_s() > self.stale_after_s(factor)

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            eta = self.eta_s
            d = {
                "job_id": self.job_id,
                "family": self.family,
                "status": self.status,
                "resilient": self.resilient,
                "n_series": self.n_series,
                "chunk_size": self.chunk_size,
                "chunks_total": self.n_chunks,
                "chunks_done": self.chunks_done,
                "chunks_restored": self.chunks_restored,
                "chunks_failed": self.chunks_failed,
                "chunks_quarantined": self.chunks_quarantined,
                "chunks_degraded": self.chunks_degraded,
                "subchunks_done": self.subchunks_done,
                "subchunks_failed": self.subchunks_failed,
                "journal_commits": self.journal_commits,
                "journal_path": self.journal_path,
                "started_unix": self.started_unix,
                "finished_unix": self.finished_unix,
                "elapsed_s": round((self.finished_unix or time.time())
                                   - self.started_unix, 3),
                "heartbeat_stage": self.heartbeat_stage,
                "heartbeat_chunk": self.heartbeat_chunk,
                "heartbeat_age_s": round(self.heartbeat_age_s(), 3),
                "stale_after_s": round(self.stale_after_s(), 3),
                "ew_chunk_s": self.ew_chunk_s,
                "eta_s": round(eta, 3) if eta is not None else None,
                "throughput_series_per_s": self.throughput_series_per_s,
                "error": self.error,
            }
        return json_safe(d)


# ---------------------------------------------------------------------------
# job / session registries (what /snapshot.json walks)
# ---------------------------------------------------------------------------

_jobs_lock = threading.Lock()
_active_jobs: Dict[str, JobProgress] = {}
_recent_jobs: deque = deque(maxlen=RECENT_JOBS_KEPT)


def register_job(progress: JobProgress,
                 registry: Optional[Any] = None) -> JobProgress:
    reg = registry if registry is not None else _metrics.get_registry()
    with _jobs_lock:
        _active_jobs[progress.job_id] = progress
        n = len(_active_jobs)
    reg.set_gauge("engine.jobs_active", n)
    return progress


def finish_job(progress: JobProgress, status: str,
               error: Optional[str] = None,
               registry: Optional[Any] = None) -> None:
    reg = registry if registry is not None else _metrics.get_registry()
    progress.finish(status, error)
    with _jobs_lock:
        _active_jobs.pop(progress.job_id, None)
        _recent_jobs.append(progress)
        n = len(_active_jobs)
    reg.set_gauge("engine.jobs_active", n)


def active_jobs() -> List[JobProgress]:
    with _jobs_lock:
        return list(_active_jobs.values())


def recent_jobs() -> List[JobProgress]:
    with _jobs_lock:
        return list(_recent_jobs)


# live ServingSessions, weakly referenced: the telemetry plane must
# never keep a session (and its device buffers) alive.  The lock
# serializes registration against the exporter thread's copy — a bare
# WeakSet.add racing list(set) raises "Set changed size during
# iteration", which would turn /snapshot.json and /healthz scrapes
# into spurious 500s (GC-driven removals are deferred internally by
# WeakSet's own iteration guard; only add needs the lock).
_sessions_lock = threading.Lock()
_sessions: "weakref.WeakSet" = weakref.WeakSet()


def register_session(session: Any) -> None:
    with _sessions_lock:
        _sessions.add(session)


def live_sessions() -> List[Any]:
    with _sessions_lock:
        return list(_sessions)


def session_summaries() -> List[Dict[str, Any]]:
    """One summary dict per live session (``telemetry_summary()``),
    defensively: a session mid-mutation must degrade to an error entry,
    never take the scrape down."""
    out = []
    for sess in live_sessions():
        try:
            out.append(json_safe(sess.telemetry_summary()))
        except Exception as e:  # noqa: BLE001 — scrape isolation
            out.append({"error": f"{type(e).__name__}: {e}"})
    return out


# live FleetSchedulers, weakly referenced like the sessions (the
# exporter must never pin a scheduler and its tenants' device buffers)
_fleets_lock = threading.Lock()
_fleets: "weakref.WeakSet" = weakref.WeakSet()

# live FleetRuntimes (statespace.runtime): the /healthz route consults
# their pump heartbeats — a stale pump answers 503 so an external
# supervisor can restart the process.  Same weak-reference + lock
# discipline as the fleets above.
_runtimes_lock = threading.Lock()
_runtimes: "weakref.WeakSet" = weakref.WeakSet()


def register_fleet(fleet: Any) -> None:
    with _fleets_lock:
        _fleets.add(fleet)


def live_fleets() -> List[Any]:
    with _fleets_lock:
        return list(_fleets)


def register_fleet_runtime(runtime: Any) -> None:
    with _runtimes_lock:
        _runtimes.add(runtime)


def live_fleet_runtimes() -> List[Any]:
    with _runtimes_lock:
        return list(_runtimes)


def fleet_runtime_rows() -> List[Dict[str, Any]]:
    """One ``pump_health()`` row per live runtime for ``/healthz`` —
    scrape isolation as everywhere else."""
    out = []
    for rt in live_fleet_runtimes():
        try:
            out.append(json_safe(rt.pump_health()))
        except Exception as e:  # noqa: BLE001 — scrape isolation
            out.append({"error": f"{type(e).__name__}: {e}"})
    return out


def fleet_summaries() -> List[Dict[str, Any]]:
    """One per-fleet panel (``telemetry_summary()``: aggregate p95/SLO/
    shed state + per-tenant rows) for ``/snapshot.json`` — scrape
    isolation as for sessions."""
    out = []
    for fl in live_fleets():
        try:
            out.append(json_safe(fl.telemetry_summary()))
        except Exception as e:  # noqa: BLE001 — scrape isolation
            out.append({"error": f"{type(e).__name__}: {e}"})
    return out


# ---------------------------------------------------------------------------
# payload builders (route handlers call these; tests call them directly)
# ---------------------------------------------------------------------------

_started_unix = time.time()


def snapshot_doc(registry: Optional[Any] = None) -> Dict[str, Any]:
    """The ``/snapshot.json`` payload: registry snapshot, jobs (active +
    recent), serving session summaries, recent incident index, and
    process/platform identity.  Never imports jax (a scrape must not
    initialize a backend); platform facts appear only when jax is
    already loaded."""
    reg = registry if registry is not None else _metrics.get_registry()
    snap = reg.snapshot()
    doc: Dict[str, Any] = {
        "format": 1,
        "pid": os.getpid(),
        "time_unix": time.time(),
        "uptime_s": round(time.time() - _started_unix, 3),
        "registry": json_safe(snap),
        "jax": _metrics.jax_stats(reg, snap=snap),
        "jobs": [p.to_dict() for p in active_jobs()],
        "recent_jobs": [p.to_dict() for p in recent_jobs()],
        "serving_sessions": session_summaries(),
        "fleets": fleet_summaries(),
    }
    jx = sys.modules.get("jax")
    if jx is not None:
        doc["jax"]["version"] = getattr(jx, "__version__", None)
    # performance attribution (docs/design.md §6g): exclusive span
    # self-times with per-subsystem rollups from the process trace ring,
    # plus the streaming engine's host-overhead / bubble gauges — the
    # ATTRIBUTION panel sts_top renders
    try:
        from . import tracing as _tracing
        gauges = snap.get("gauges", {})
        doc["attribution"] = {
            "self_times": _tracing.self_time_report(8),
            "engine": {k: gauges[k]
                       for k in ("engine.host_overhead_frac",
                                 "engine.bubble_ms_total")
                       if k in gauges},
        }
    except Exception as e:  # noqa: BLE001 — scrape isolation
        doc["attribution"] = {"error": f"{type(e).__name__}: {e}"}
    # tick lineage (docs/design.md §6h): per-tenant end-to-end latency
    # with stage decomposition and slowest-tick exemplars — the E2E
    # panel sts_top renders
    try:
        from . import lineage as _lineage
        doc["lineage"] = json_safe(_lineage.lineage_summary())
    except Exception as e:  # noqa: BLE001 — scrape isolation
        doc["lineage"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        from . import flightrec as _flightrec
        doc["incident_dir"] = _flightrec.incident_dir()
        doc["incidents"] = _flightrec.list_incidents(limit=8)
    except Exception as e:  # noqa: BLE001 — scrape isolation
        doc["incidents"] = [{"error": f"{type(e).__name__}: {e}"}]
    return doc


def healthz_doc(registry: Optional[Any] = None) -> Dict[str, Any]:
    """The ``/healthz`` payload.  ``status`` is ``"ok"`` unless any
    active job's heartbeat is stale (older than the staleness threshold
    — see :meth:`JobProgress.stale_after_s`) or any fleet runtime's
    pump heartbeat is stale (same ``STS_TELEMETRY_STALE_FACTOR``
    contract; see ``FleetRuntime.stale_after_s``), in which case it is
    ``"stale"`` and the HTTP route answers 503 — the signal an external
    supervisor restarts the process on."""
    jobs = []
    any_stale = False
    for p in active_jobs():
        stale = p.is_stale()
        any_stale = any_stale or stale
        jobs.append({
            "job_id": p.job_id,
            "stage": p.heartbeat_stage,
            "heartbeat_age_s": round(p.heartbeat_age_s(), 3),
            "stale_after_s": round(p.stale_after_s(), 3),
            "stale": stale,
        })
    pumps = fleet_runtime_rows()
    for row in pumps:
        any_stale = any_stale or bool(row.get("stale"))
    return {
        "status": "stale" if any_stale else "ok",
        "pid": os.getpid(),
        "time_unix": time.time(),
        "uptime_s": round(time.time() - _started_unix, 3),
        "n_active_jobs": len(jobs),
        "n_serving_sessions": len(live_sessions()),
        "n_fleet_pumps": len(pumps),
        "jobs": jobs,
        "fleet_pumps": pumps,
    }


# ---------------------------------------------------------------------------
# the scrape server
# ---------------------------------------------------------------------------

class TelemetryAlreadyStarted(RuntimeError):
    """:func:`start` was called while an exporter is already serving.
    One process gets one scrape endpoint; :func:`stop` the old one
    first (double-binding would split scrapes across two ports)."""


def _trace_limit(query: str) -> Optional[int]:
    """``?limit=N`` for ``/trace.json``; a malformed value raises (the
    route answers 400) rather than silently serving the unbounded
    ~10 MB ring the limit exists to prevent."""
    for part in query.split("&"):
        if part.startswith("limit="):
            raw = part[len("limit="):]
            try:
                return max(1, int(raw))
            except ValueError:
                raise ValueError(
                    f"limit must be an integer, got {raw!r}") from None
    return None


class TelemetryServer:
    """A running scrape endpoint: stdlib ``ThreadingHTTPServer`` on a
    daemon thread.  Build via :func:`start`; :meth:`stop` shuts the
    socket down and joins the thread (bounded)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registry: Optional[Any] = None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        reg = registry if registry is not None else _metrics.get_registry()
        self._reg = reg
        outer = self

        class Handler(BaseHTTPRequestHandler):
            server_version = "sts-telemetry/1"

            def log_message(self, *args):  # silence stderr access logs
                pass

            def do_GET(self):
                t0 = time.perf_counter()
                raw = self.path.split("?", 1)
                route = raw[0]
                query = raw[1] if len(raw) > 1 else ""
                status = 200
                ctype = "application/json"
                try:
                    if route == "/metrics":
                        body = outer._reg.to_prometheus().encode()
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    elif route == "/snapshot.json":
                        body = json.dumps(
                            snapshot_doc(outer._reg)).encode()
                    elif route == "/trace.json":
                        from . import tracing as _tracing
                        try:
                            limit = _trace_limit(query)
                        except ValueError as e:
                            status = 400
                            body = json.dumps({"error": str(e)}).encode()
                        else:
                            body = json.dumps(_tracing.to_chrome_trace(
                                limit=limit)).encode()
                    elif route in ("/healthz", "/health"):
                        doc = healthz_doc(outer._reg)
                        status = 200 if doc["status"] == "ok" else 503
                        body = json.dumps(doc).encode()
                    elif route == "/":
                        body = json.dumps({
                            "routes": ["/metrics", "/snapshot.json",
                                       "/trace.json", "/healthz"],
                            "pid": os.getpid()}).encode()
                    else:
                        status = 404
                        body = json.dumps(
                            {"error": f"no route {route!r}"}).encode()
                except Exception as e:  # noqa: BLE001 — a scrape bug
                    # must answer 500, never kill the server thread
                    status = 500
                    body = json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode()
                    outer._reg.inc("telemetry.scrape_errors")
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                finally:
                    outer._reg.inc("telemetry.scrapes")
                    outer._reg.record("telemetry.scrape_s",
                                      time.perf_counter() - t0)

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="sts-telemetry", daemon=True)
        self._thread.start()
        reg.set_gauge("telemetry.port", self.port)

    @property
    def port(self) -> int:
        return int(self._httpd.server_address[1])

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def stop(self, timeout: float = 5.0) -> bool:
        """Shut down and join the server thread; True when the thread
        exited within ``timeout`` (no dangling thread)."""
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout)
        self._reg.set_gauge("telemetry.port", 0.0)
        return not self._thread.is_alive()


_server_lock = threading.Lock()
_server: Optional[TelemetryServer] = None


def server() -> Optional[TelemetryServer]:
    """The process' running exporter, or None."""
    return _server


def start(port: int = 0, host: str = "127.0.0.1",
          registry: Optional[Any] = None) -> TelemetryServer:
    """Start the process' scrape endpoint (``port=0`` picks a free
    port; read it back from ``.port``/``.url``).  Raises
    :class:`TelemetryAlreadyStarted` when one is already serving."""
    global _server
    with _server_lock:
        if _server is not None and _server.alive:
            raise TelemetryAlreadyStarted(
                f"telemetry exporter already serving at {_server.url}; "
                f"telemetry.stop() it before starting another")
        srv = TelemetryServer(host=host, port=port, registry=registry)
        _server = srv
    return srv


def stop(timeout: float = 5.0) -> bool:
    """Stop the module-level exporter (no-op → True when none runs)."""
    global _server
    with _server_lock:
        srv, _server = _server, None
    if srv is None:
        return True
    return srv.stop(timeout)


def ensure_started_from_env() -> Optional[TelemetryServer]:
    """The ``STS_TELEMETRY_PORT`` opt-in: called by the library's
    long-running entry points (``engine.stream_fit``, serving session
    construction).  Unset or already-started is a no-op; a junk value
    raises a named ValueError; a bind failure (port taken) is counted
    (``telemetry.start_errors``) and swallowed — observability must not
    take the job down."""
    env = os.environ.get("STS_TELEMETRY_PORT")
    if not env:
        return None
    if _server is not None and _server.alive:
        return _server
    try:
        port = int(env)
        if port < 0 or port > 65535:
            raise ValueError
    except ValueError:
        raise ValueError(
            f"STS_TELEMETRY_PORT must be a port number in [0, 65535] "
            f"(0 = pick a free port), got {env!r}") from None
    try:
        return start(port=port)
    except TelemetryAlreadyStarted:
        return _server
    except OSError:
        _metrics.inc("telemetry.start_errors")
        return None
