"""Chrome trace-event export: the span timeline as a Perfetto file.

The reference answers "where did the time go" with the Spark UI's
stage/task timeline; ``utils.metrics`` already aggregates span wall time
into histograms, but an aggregate can't show *when* — which fits
overlapped, where a recompile landed inside a round, which fallback
stage the resilient path took.  This module exports the trace ring
buffer (``metrics.trace_events()``) in the Chrome trace-event JSON
format, loadable in Perfetto (https://ui.perfetto.dev) or
``chrome://tracing``:

- each completed span scope is a **complete event** (``"ph": "X"``)
  whose name is the nested ``/``-joined path, laid out per thread;
- recompiles and resilience fallback stages are **instant events**
  (``"ph": "i"``) — the point-in-time arrows over the timeline;
- process/thread **metadata events** (``"ph": "M"``) label the rows.

Two entry points:

- ``STS_TRACE=/path.json`` (environment) dumps the buffer at interpreter
  exit — zero code changes, the opt-in for ad-hoc runs (registered by
  ``utils.metrics`` at import so any entry point that touches the
  package gets it);
- :func:`write_trace` / :func:`to_chrome_trace` for explicit dumps, and
  :func:`span_events` / :func:`slowest_spans` for embedding the top-N
  slowest scopes into bench artifacts (``bench.py`` does, per round).

Self-time attribution (docs/design.md §6g): inclusive span durations
answer "how long did this scope take" but not "which scope *itself* ate
the time" — a parent that merely wraps a slow child ranks above the
child.  :func:`self_times` computes each buffered span's **exclusive**
self-time (inclusive duration minus the durations of its enclosed
children, per thread, from the ring's begin+duration intervals), and
:func:`self_time_report` aggregates it by span name with per-subsystem
rollups (``engine`` / ``statespace`` / ``backtest`` / ``models`` /
``utils``) — the block ``bench.py`` embeds per round and
``tools/bench_diff.py`` diffs across rounds.

Timestamps ride the ``perf_counter`` clock (µs in the export, as the
format requires); the absolute wall-clock anchor of the trace is carried
in ``otherData.trace_start_walltime`` so a timeline can be correlated
with log lines.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from . import metrics as _metrics

__all__ = ["to_chrome_trace", "write_trace", "span_events",
           "slowest_spans", "self_times", "self_time_report",
           "span_subsystem"]

_S_TO_US = 1e6

# containment slack for self-time interval nesting: a child's exit
# timestamp is read by a separate perf_counter call than its parent's,
# so a nominally-enclosed child can arithmetically overhang by clock
# quantization — never by more than microseconds
_NEST_EPS = 1e-6

# leaf-prefix → subsystem rollup (the five attribution buckets).  The
# leaf segment of a nested path owns the time ("bench.fit/engine.stream"
# is engine time); prefixes not listed (bench.*, telemetry.*, io.*, ...)
# roll into "utils" — driver/observability glue, not model math.
SUBSYSTEMS = ("engine", "statespace", "backtest", "models", "utils")
_SUBSYSTEM_BY_PREFIX = {
    "engine": "engine",
    "serving": "statespace",
    "kalman": "statespace",
    "statespace": "statespace",
    "fleet": "statespace",
    "lineage": "statespace",
    "quality": "statespace",
    "backtest": "backtest",
    "arima": "models",
    "garch": "models",
    "hw": "models",
    "holtwinters": "models",
    "ar": "models",
    "ma": "models",
    "arma": "models",
    "ewma": "models",
    "rw": "models",
    "fit": "models",
    "optimize": "models",
    "resilience": "models",
    "longseries": "models",
}


def span_subsystem(path: str) -> str:
    """The attribution bucket owning a span path: decided by the *leaf*
    segment's dotted prefix (``"bench.fit_panel/arima.fit"`` → the
    ``arima`` leaf → ``"models"``); unknown prefixes are ``"utils"``."""
    leaf = path.rsplit("/", 1)[-1]
    head = leaf.split(".", 1)[0]
    return _SUBSYSTEM_BY_PREFIX.get(head, "utils")


def span_events(events: Optional[List[Dict[str, Any]]] = None
                ) -> List[Dict[str, Any]]:
    """The buffered span events (kind ``"span"``), begin-time order.

    The ring appends at scope *exit* (a nested child precedes its parent
    in arrival order); sorting by ``ts`` restores begin-time order, which
    is what both the exporter and a "what ran when" reader want."""
    if events is None:
        events = _metrics.trace_events()
    spans = [e for e in events if e.get("kind") == "span"]
    spans.sort(key=lambda e: e["ts"])
    return spans


def self_times(events: Optional[List[Dict[str, Any]]] = None
               ) -> List[Dict[str, Any]]:
    """Every buffered span with its **exclusive** self-time: inclusive
    duration minus the durations of its strictly-enclosed children,
    computed per thread from the ring's begin+duration intervals.

    ``span()`` scopes are well-nested per thread (a child records at
    exit, strictly inside its parent's window), so a single stack pass
    over begin-ordered events suffices: an event starting after the
    stack top's end closes that scope; an event whose window sits inside
    the top's subtracts from the top's self-time (immediate parent only
    — a grandchild already subtracted from its own parent).  A window
    that *partially* overlaps the top (impossible from ``span()``, but
    representable in a hand-built event list) is treated as a sibling:
    nothing is subtracted, so inclusive totals are never over-attributed.
    Self-times are clamped at 0 against clock quantization."""
    spans = span_events(events)
    rows: List[Dict[str, Any]] = []
    by_tid: Dict[Any, List[Dict[str, Any]]] = {}
    for e in spans:
        by_tid.setdefault(e.get("tid", 0), []).append(e)
    for evs in by_tid.values():
        # same begin → the longer window is the parent
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        # stack entries: [event, self_dur, end]
        stack: List[list] = []
        done: List[list] = []
        for e in evs:
            end = e["ts"] + e["dur"]
            while stack and stack[-1][2] <= e["ts"] + _NEST_EPS:
                done.append(stack.pop())
            if stack and end <= stack[-1][2] + _NEST_EPS:
                stack[-1][1] -= e["dur"]
            stack.append([e, e["dur"], end])
        done.extend(stack)
        for e, self_dur, _end in done:
            rows.append({"name": e["name"], "ts": e["ts"],
                         "dur": e["dur"], "self": max(0.0, self_dur),
                         "tid": e.get("tid", 0),
                         "tname": e.get("tname", "")})
    rows.sort(key=lambda r: r["ts"])
    return rows


def slowest_spans(n: int = 10,
                  events: Optional[List[Dict[str, Any]]] = None
                  ) -> List[Dict[str, Any]]:
    """Top-``n`` slowest span scopes still in the buffer, as compact
    JSON-able rows — the per-round "where did this round's time go"
    block ``bench.py`` embeds next to the aggregate span histograms.
    Each row carries both the inclusive duration and the exclusive
    self-time; ties on duration order by name so equal-duration spans
    don't reorder between runs."""
    rows = self_times(events)
    rows.sort(key=lambda r: (-r["dur"], r["name"]))
    return [{"name": r["name"], "dur_s": round(r["dur"], 6),
             "self_s": round(r["self"], 6),
             "thread": r.get("tname", "")} for r in rows[:n]]


def self_time_report(n: int = 10,
                     events: Optional[List[Dict[str, Any]]] = None
                     ) -> Dict[str, Any]:
    """The per-round self-time attribution block: spans aggregated by
    name (summed across occurrences and threads), top-``n`` by total
    self-time (name-stable on ties), plus the per-subsystem rollup over
    *all* buffered spans.  Every subsystem bucket is always present — a
    0 is a measured "this tier spent nothing", which is what
    ``tools/bench_diff.py`` needs to diff rounds that exercised
    different tiers."""
    agg: Dict[str, Dict[str, Any]] = {}
    for r in self_times(events):
        a = agg.setdefault(r["name"], {"name": r["name"], "count": 0,
                                       "dur_s": 0.0, "self_s": 0.0})
        a["count"] += 1
        a["dur_s"] += r["dur"]
        a["self_s"] += r["self"]
    subsystems = {sub: {"self_s": 0.0, "spans": 0} for sub in SUBSYSTEMS}
    total = 0.0
    for a in agg.values():
        sub = subsystems[span_subsystem(a["name"])]
        sub["self_s"] += a["self_s"]
        sub["spans"] += 1
        total += a["self_s"]
    top = sorted(agg.values(), key=lambda a: (-a["self_s"], a["name"]))
    return {
        "spans": [{"name": a["name"], "count": a["count"],
                   "dur_s": round(a["dur_s"], 6),
                   "self_s": round(a["self_s"], 6)} for a in top[:n]],
        "subsystems": {k: {"self_s": round(v["self_s"], 6),
                           "spans": v["spans"]}
                       for k, v in subsystems.items()},
        "total_self_s": round(total, 6),
    }


def to_chrome_trace(events: Optional[List[Dict[str, Any]]] = None,
                    limit: Optional[int] = None) -> Dict[str, Any]:
    """Render the trace buffer as a Chrome trace-event JSON object.

    Uses the object form (``{"traceEvents": [...]}``) so the file can
    carry ``otherData``; the array inside follows the trace-event spec:
    ``X`` (complete) events for spans with ``ts``/``dur`` in µs, ``i``
    (instant, thread scope) events for markers, and ``M`` metadata
    events naming the process and each thread row.

    ``limit`` keeps only the newest N events (by begin time) — the
    payload bound the telemetry exporter's ``/trace.json?limit=`` and
    the flight recorder's embedded trace use (a full 65536-event ring
    renders to ~10 MB, too heavy for a scrape or an incident bundle).

    When ``events`` is None the export also interleaves completed tick
    lineage stages (``utils.lineage``) as spans on synthetic
    ``lineage-*`` thread rows — the per-request journeys render right
    next to the engine spans they contain, which is the whole point of
    a trace: *this* tick's queue wait sits beside *that* dispatch.
    Only the export merges them — :func:`self_times` /
    :func:`self_time_report` keep reading the span ring alone, so
    attribution totals are unchanged by the lineage plane."""
    if events is None:
        events = _metrics.trace_events()
        try:
            from . import lineage as _lineage
            events = events + _lineage.trace_events()
        except Exception:  # noqa: BLE001 — the trace must render even
            pass           # if the lineage plane is broken mid-scrape
    if limit is not None and len(events) > limit:
        events = sorted(events, key=lambda e: e["ts"])[-int(limit):]
    pid = os.getpid()
    out: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "spark_timeseries_tpu"},
    }]
    threads: Dict[int, str] = {}
    body: List[Dict[str, Any]] = []
    for e in sorted(events, key=lambda e: e["ts"]):
        tid = e.get("tid", 0)
        if tid not in threads:
            threads[tid] = e.get("tname", str(tid))
        ev: Dict[str, Any] = {
            "name": e["name"],
            "cat": e["kind"],
            "pid": pid,
            "tid": tid,
            "ts": e["ts"] * _S_TO_US,
        }
        if e["kind"] == "span":
            ev["ph"] = "X"
            ev["dur"] = e["dur"] * _S_TO_US
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        if e.get("args"):
            ev["args"] = e["args"]
        body.append(ev)
    for tid, tname in sorted(threads.items()):
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": tname}})
    out.extend(body)
    wall0, perf0 = _metrics._TRACE_EPOCH
    buf = _metrics.trace_buffer()
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_start_walltime": wall0,
            "perf_counter_at_start": perf0,
            "events_dropped": buf.dropped,
            "events_exported": len(events),
            "capacity": buf.capacity,
        },
    }


def write_trace(path: str,
                events: Optional[List[Dict[str, Any]]] = None) -> str:
    """Write the Chrome trace JSON to ``path`` (parent dirs created);
    returns the path.  Load the file in https://ui.perfetto.dev or
    ``chrome://tracing``."""
    doc = to_chrome_trace(events)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
