"""Chrome trace-event export: the span timeline as a Perfetto file.

The reference answers "where did the time go" with the Spark UI's
stage/task timeline; ``utils.metrics`` already aggregates span wall time
into histograms, but an aggregate can't show *when* — which fits
overlapped, where a recompile landed inside a round, which fallback
stage the resilient path took.  This module exports the trace ring
buffer (``metrics.trace_events()``) in the Chrome trace-event JSON
format, loadable in Perfetto (https://ui.perfetto.dev) or
``chrome://tracing``:

- each completed span scope is a **complete event** (``"ph": "X"``)
  whose name is the nested ``/``-joined path, laid out per thread;
- recompiles and resilience fallback stages are **instant events**
  (``"ph": "i"``) — the point-in-time arrows over the timeline;
- process/thread **metadata events** (``"ph": "M"``) label the rows.

Two entry points:

- ``STS_TRACE=/path.json`` (environment) dumps the buffer at interpreter
  exit — zero code changes, the opt-in for ad-hoc runs (registered by
  ``utils.metrics`` at import so any entry point that touches the
  package gets it);
- :func:`write_trace` / :func:`to_chrome_trace` for explicit dumps, and
  :func:`span_events` / :func:`slowest_spans` for embedding the top-N
  slowest scopes into bench artifacts (``bench.py`` does, per round).

Timestamps ride the ``perf_counter`` clock (µs in the export, as the
format requires); the absolute wall-clock anchor of the trace is carried
in ``otherData.trace_start_walltime`` so a timeline can be correlated
with log lines.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from . import metrics as _metrics

__all__ = ["to_chrome_trace", "write_trace", "span_events",
           "slowest_spans"]

_S_TO_US = 1e6


def span_events(events: Optional[List[Dict[str, Any]]] = None
                ) -> List[Dict[str, Any]]:
    """The buffered span events (kind ``"span"``), begin-time order.

    The ring appends at scope *exit* (a nested child precedes its parent
    in arrival order); sorting by ``ts`` restores begin-time order, which
    is what both the exporter and a "what ran when" reader want."""
    if events is None:
        events = _metrics.trace_events()
    spans = [e for e in events if e.get("kind") == "span"]
    spans.sort(key=lambda e: e["ts"])
    return spans


def slowest_spans(n: int = 10,
                  events: Optional[List[Dict[str, Any]]] = None
                  ) -> List[Dict[str, Any]]:
    """Top-``n`` slowest span scopes still in the buffer, as compact
    JSON-able rows — the per-round "where did this round's time go"
    block ``bench.py`` embeds next to the aggregate span histograms."""
    spans = span_events(events)
    spans.sort(key=lambda e: e["dur"], reverse=True)
    return [{"name": e["name"], "dur_s": round(e["dur"], 6),
             "thread": e.get("tname", "")} for e in spans[:n]]


def to_chrome_trace(events: Optional[List[Dict[str, Any]]] = None,
                    limit: Optional[int] = None) -> Dict[str, Any]:
    """Render the trace buffer as a Chrome trace-event JSON object.

    Uses the object form (``{"traceEvents": [...]}``) so the file can
    carry ``otherData``; the array inside follows the trace-event spec:
    ``X`` (complete) events for spans with ``ts``/``dur`` in µs, ``i``
    (instant, thread scope) events for markers, and ``M`` metadata
    events naming the process and each thread row.

    ``limit`` keeps only the newest N events (by begin time) — the
    payload bound the telemetry exporter's ``/trace.json?limit=`` and
    the flight recorder's embedded trace use (a full 65536-event ring
    renders to ~10 MB, too heavy for a scrape or an incident bundle)."""
    if events is None:
        events = _metrics.trace_events()
    if limit is not None and len(events) > limit:
        events = sorted(events, key=lambda e: e["ts"])[-int(limit):]
    pid = os.getpid()
    out: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "spark_timeseries_tpu"},
    }]
    threads: Dict[int, str] = {}
    body: List[Dict[str, Any]] = []
    for e in sorted(events, key=lambda e: e["ts"]):
        tid = e.get("tid", 0)
        if tid not in threads:
            threads[tid] = e.get("tname", str(tid))
        ev: Dict[str, Any] = {
            "name": e["name"],
            "cat": e["kind"],
            "pid": pid,
            "tid": tid,
            "ts": e["ts"] * _S_TO_US,
        }
        if e["kind"] == "span":
            ev["ph"] = "X"
            ev["dur"] = e["dur"] * _S_TO_US
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        if e.get("args"):
            ev["args"] = e["args"]
        body.append(ev)
    for tid, tname in sorted(threads.items()):
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": tname}})
    out.extend(body)
    wall0, perf0 = _metrics._TRACE_EPOCH
    buf = _metrics.trace_buffer()
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_start_walltime": wall0,
            "perf_counter_at_start": perf0,
            "events_dropped": buf.dropped,
            "events_exported": len(events),
            "capacity": buf.capacity,
        },
    }


def write_trace(path: str,
                events: Optional[List[Dict[str, Any]]] = None) -> str:
    """Write the Chrome trace JSON to ``path`` (parent dirs created);
    returns the path.  Load the file in https://ui.perfetto.dev or
    ``chrome://tracing``."""
    doc = to_chrome_trace(events)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
