"""Tick lineage: per-request end-to-end tracing across the fleet path.

PR 17 made serving asynchronous — ``submit()`` returns immediately and a
supervised pump delivers the ``TickResult`` later — so the latency a
*caller* experiences is a multi-stage journey that no per-span surface
measures: ``serving.session.<label>.tick_p50_ms`` times only the jitted
dispatch, and the attribution plane aggregates span self-time per
subsystem, not per request.  This module closes the gap with a
request-scoped plane:

- Every admitted tick gets a cheap monotonic **trace id** and a compact
  host-side :class:`TickLineage` record that rides the tenant queue and
  accumulates contiguous stage segments: ``admit`` (validation plus any
  backpressure park time) -> ``queue`` (residency until the coalescer
  pops it) -> ``gather`` (host-side batch assembly) -> ``dispatch`` (the
  single jitted step plus result materialisation) -> ``scatter``
  (per-member state commit) -> ``deliver`` (result fan-out until the
  lineage completes).  Shed->cache serves record ``cache``; catch-up
  replay records ``replay``.  Stages are contiguous on one
  ``perf_counter`` timeline, so their sum reconstructs >=90% of the
  submit->delivery wall time (pinned by test).
- **Detour markers** flag the interesting journeys: ``backpressure``
  (the submit call parked on the runtime condvar), ``shed`` (rolled from
  the live queue into the catch-up ring), ``window_deadline`` (dispatched
  by coalesce-window expiry with stragglers missing), ``catchup_replay``,
  ``cache_stale``, ``drain`` / ``adopt_migration`` (cross-process
  migration), and ``pump_restart_redelivery`` (the tick survived a pump
  crash and was re-swept by the next generation).
- Completed lineages land in a bounded per-process **ring** modeled on
  :class:`~spark_timeseries_tpu.utils.metrics.TraceBuffer` (overwrite
  oldest, count ``ring_dropped`` — overflow is never silent), feeding the
  scrape plane (``/snapshot.json`` ``lineage`` section), the Chrome trace
  export (lineage stages interleave with spans in ``/trace.json``),
  flight-recorder bundles, and the bench headline
  (``fleet_e2e_p50_ms`` / ``fleet_e2e_p95_ms``).
- Per-tenant rolling windows drive ``fleet.e2e.<tenant>.p50_ms`` /
  ``.p95_ms`` gauges plus stage-decomposed rollups, so an SLO burn
  attributes to a *stage*, not just a number.  The N slowest delivered
  ticks per window keep their full stage timeline (exemplars).

Exactly-once contract: every ``begin()`` is finalised by exactly one
``complete()`` with a terminal outcome — ``delivered`` (histogrammed),
or ``rejected`` / ``dropped`` / ``migrated`` (counted, ring-recorded,
never histogrammed).  Queue entries carry their record across pump
generations (a crashed pump's queue survives intact), so supervision
restarts redeliver the *same* record rather than minting a duplicate;
``duplicate_completions`` and ``open_records()`` make any violation
countable, and the PR-13 race harness pins the property under seeded
interleavings.

Lock discipline (§6d): the module lock ``_lock`` is a **leaf** — it
guards only the ring, counters, and per-tenant windows, and is never
held across a registry call (gauges are set after release) or any other
lock.  Record mutation (``stage_end`` / ``detour``) is lock-free: a
record has exactly one owner at a time (the admitting thread, then the
pump thread that popped it), with hand-off through the tenant queue
under the runtime lock.  Everything here is host-side Python —
disarming (``STS_LINEAGE=0``) reduces the plane to one attribute read
per submit, and the warmed-tick 0-recompile pin holds with it armed.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .telemetry import env_positive

__all__ = [
    "TickLineage", "begin", "complete", "arm", "armed", "reset",
    "submit_entry", "submit_parked", "submit_abandon",
    "records", "set_capacity", "open_records", "lineage_summary",
    "trace_events", "incident_block",
    "LINEAGE_CAPACITY", "LINEAGE_WINDOW", "LINEAGE_EXEMPLARS", "STAGES",
]

# Stage vocabulary, in journey order.  ``cache`` and ``replay`` are the
# detour terminals (shed->cache serve, catch-up replay); the rest is the
# pumped dispatch path.
STAGES = ("admit", "queue", "gather", "dispatch", "scatter", "deliver",
          "cache", "replay")

OUTCOMES = ("delivered", "rejected", "dropped", "migrated")

#: Completed-record ring capacity (override: ``STS_LINEAGE_CAPACITY``).
LINEAGE_CAPACITY = 4096
#: Per-tenant rolling e2e window length (override: ``STS_LINEAGE_WINDOW``).
LINEAGE_WINDOW = 256
#: Slowest-tick exemplars kept per window (override: ``STS_LINEAGE_EXEMPLARS``).
LINEAGE_EXEMPLARS = 4
#: Per-tenant stat maps are bounded too — labels are caller-supplied
#: strings, so an adversarial (or merely enthusiastic) tenant churn must
#: not grow host memory without bound.  Beyond the cap, completions
#: still ring-record but skip per-tenant windows (counted, not silent).
MAX_TENANTS = 1024

# Chrome-trace lane ids for lineage events.  Kept far above real thread
# ids and *integers* (to_chrome_trace sorts tids to emit thread_name
# metadata; mixed types would break the sort).
_LINEAGE_TID_BASE = 1 << 20
_LINEAGE_LANES = 4


class TickLineage:
    """One tick's journey: contiguous stage segments on a shared
    ``perf_counter`` timeline plus detour markers.  Mutated lock-free by
    its single owner; handed off through the tenant queue."""

    __slots__ = ("trace_id", "tenant", "via", "t0", "t_last",
                 "segs", "detours", "done")

    def __init__(self, trace_id: int, tenant: str, t0: float,
                 via: str = "dispatch"):
        self.trace_id = trace_id
        self.tenant = tenant
        self.via = via              # "dispatch" | "cache" | "replay"
        self.t0 = t0                # journey start (perf_counter seconds)
        self.t_last = t0            # end of the last closed segment
        self.segs: List[tuple] = []          # (stage, t_start, dur_s)
        self.detours: List[str] = []
        self.done = False

    def stage_end(self, stage: str) -> None:
        """Close the current segment as ``stage`` ([t_last, now])."""
        now = time.perf_counter()
        self.segs.append((stage, self.t_last, now - self.t_last))
        self.t_last = now

    def detour(self, marker: str) -> None:
        """Flag a detour (idempotent — redelivery may mark repeatedly)."""
        if marker not in self.detours:
            self.detours.append(marker)


# ---------------------------------------------------------------------------
# module state (all mutation under _lock; see §6d — _lock is a leaf)

_lock = threading.Lock()
_trace_seq = itertools.count(1)

_armed = os.environ.get("STS_LINEAGE", "1") != "0"

_cap = env_positive("STS_LINEAGE_CAPACITY", int, LINEAGE_CAPACITY)
_window = env_positive("STS_LINEAGE_WINDOW", int, LINEAGE_WINDOW)
_n_exemplars = env_positive("STS_LINEAGE_EXEMPLARS", int, LINEAGE_EXEMPLARS)

_ring: List[dict] = []
_head = 0                   # next overwrite slot once full
_ring_dropped = 0

_started = 0
_outcomes: Dict[str, int] = {}
_duplicates = 0
_tenant_overflow = 0
_stage_ms: Dict[str, float] = {}        # delivered-stage rollup (ms)
# label -> {"e2e": [ms...], "stage_ms": {stage: ms}, "n": int, "cache": int}
_tenants: Dict[str, dict] = {}
_exemplars: List[dict] = []             # slowest delivered, current window
_exem_seen = 0                          # completions in current window

# Submit-side context: FleetRuntime.submit stamps entry/park here so the
# record minted later inside FleetScheduler._admit_one starts its clock
# *before* any backpressure wait.  Thread-local — no lock needed.
_tls = threading.local()


def arm(on: bool = True) -> bool:
    """(Dis)arm the plane; returns the previous state.  Disarmed,
    ``begin()`` returns ``None`` and every instrumentation site reduces
    to one ``is None`` check."""
    global _armed
    prev = _armed
    _armed = bool(on)
    return prev


def armed() -> bool:
    return _armed


def submit_entry() -> None:
    """Mark the start of a (possibly blocking) runtime submit on this
    thread.  Consumed by the next ``begin()`` so admission's stage
    includes backpressure park time."""
    if _armed:
        _tls.t0 = time.perf_counter()
        _tls.parked = False


def submit_parked() -> None:
    """The submitting thread is about to park on the backpressure
    condvar — the eventual record gets a ``backpressure`` detour."""
    if _armed and getattr(_tls, "t0", None) is not None:
        _tls.parked = True


def submit_abandon() -> None:
    """The submit failed terminally (e.g. backpressure timeout) without
    admitting a tick — drop the pending context so it cannot leak into
    an unrelated later admission on this thread."""
    _tls.t0 = None
    _tls.parked = False


def _consume_submit_ctx():
    t0 = getattr(_tls, "t0", None)
    parked = getattr(_tls, "parked", False)
    _tls.t0 = None
    _tls.parked = False
    return t0, parked


def begin(tenant: str, via: str = "dispatch") -> Optional[TickLineage]:
    """Mint a lineage record at admission; ``None`` when disarmed."""
    global _started
    if not _armed:
        return None
    t0, parked = _consume_submit_ctx()
    now = time.perf_counter()
    lin = TickLineage(next(_trace_seq), str(tenant),
                      now if t0 is None else t0, via=via)
    if parked:
        lin.detours.append("backpressure")
    with _lock:
        _started += 1
    return lin


def complete(lin: Optional[TickLineage], registry=None, *,
             outcome: str = "delivered") -> None:
    """Finalise a record exactly once: ring-append it, fold delivered
    outcomes into the per-tenant windows / stage rollups / exemplars,
    then (outside the lineage lock) publish the tenant's e2e gauges."""
    global _head, _ring_dropped, _duplicates, _tenant_overflow, _exem_seen
    if lin is None:
        return
    if lin.done:
        with _lock:
            _duplicates += 1
        if registry is not None:
            registry.inc("fleet.e2e.duplicate_completions")
        return
    lin.done = True
    e2e_ms = (time.perf_counter() - lin.t0) * 1e3
    stage_ms: Dict[str, float] = {}
    for stage, _, dur in lin.segs:
        stage_ms[stage] = stage_ms.get(stage, 0.0) + dur * 1e3
    rec = {
        "trace_id": lin.trace_id,
        "tenant": lin.tenant,
        "via": lin.via,
        "outcome": outcome,
        "e2e_ms": e2e_ms,
        "t0": lin.t0,
        "stages": stage_ms,
        "segs": [(s, ts, dur) for (s, ts, dur) in lin.segs],
        "detours": list(lin.detours),
    }
    delivered = outcome == "delivered"
    e2e_window: Optional[list] = None
    with _lock:
        _outcomes[outcome] = _outcomes.get(outcome, 0) + 1
        if len(_ring) < _cap:
            _ring.append(rec)
        else:
            _ring[_head] = rec
            _head = (_head + 1) % _cap
            _ring_dropped += 1
        if delivered:
            for stage, ms in stage_ms.items():
                _stage_ms[stage] = _stage_ms.get(stage, 0.0) + ms
            st = _tenants.get(lin.tenant)
            if st is None:
                if len(_tenants) >= MAX_TENANTS:
                    _tenant_overflow += 1
                else:
                    st = _tenants[lin.tenant] = {
                        "e2e": [], "stage_ms": {}, "n": 0, "cache": 0}
            if st is not None:
                st["n"] += 1
                if lin.via == "cache":
                    st["cache"] += 1
                st["e2e"].append(e2e_ms)
                if len(st["e2e"]) > _window:
                    del st["e2e"][:len(st["e2e"]) - _window]
                for stage, ms in stage_ms.items():
                    st["stage_ms"][stage] = st["stage_ms"].get(stage, 0.0) + ms
                e2e_window = list(st["e2e"])
            # exemplars: keep the N slowest full timelines per window
            _exem_seen += 1
            if _exem_seen > _window:
                _exem_seen = 1
                del _exemplars[:]
            _exemplars.append(rec)
            _exemplars.sort(key=lambda r: r["e2e_ms"], reverse=True)
            del _exemplars[_n_exemplars:]
    if registry is not None:
        registry.inc(f"fleet.e2e.{outcome}")
        if e2e_window:
            arr = np.asarray(e2e_window, dtype=np.float64)
            registry.set_gauge(f"fleet.e2e.{lin.tenant}.p50_ms",
                               float(np.percentile(arr, 50)))
            registry.set_gauge(f"fleet.e2e.{lin.tenant}.p95_ms",
                               float(np.percentile(arr, 95)))


def records() -> List[dict]:
    """Copy of the completed-record ring, oldest first."""
    with _lock:
        return _ring[_head:] + _ring[:_head]


def set_capacity(capacity: int) -> None:
    """Resize the ring, keeping the newest records that still fit."""
    global _ring, _head, _cap
    capacity = int(capacity)
    if capacity < 1:
        raise ValueError(f"lineage capacity must be >= 1, got {capacity}")
    with _lock:
        ordered = _ring[_head:] + _ring[:_head]
        _ring = ordered[-capacity:]
        _head = 0
        _cap = capacity


def open_records() -> int:
    """Records begun but not yet finalised (should be 0 at quiesce —
    any residue is an orphan and an exactly-once violation)."""
    with _lock:
        return _started - sum(_outcomes.values())


def reset() -> None:
    """Clear all completed state and counters (capacity and armed state
    survive).  In-flight records still complete afterwards; they simply
    land in the fresh window.  Test/bench isolation hook."""
    global _ring, _head, _ring_dropped, _started, _duplicates
    global _tenant_overflow, _exem_seen
    with _lock:
        _ring = []
        _head = 0
        _ring_dropped = 0
        _started = 0
        _duplicates = 0
        _tenant_overflow = 0
        _exem_seen = 0
        _outcomes.clear()
        _stage_ms.clear()
        _tenants.clear()
        del _exemplars[:]


def _pcts(vals: list) -> Dict[str, Optional[float]]:
    if not vals:
        return {"n": 0, "p50_ms": None, "p95_ms": None}
    arr = np.asarray(vals, dtype=np.float64)
    return {"n": len(vals),
            "p50_ms": round(float(np.percentile(arr, 50)), 3),
            "p95_ms": round(float(np.percentile(arr, 95)), 3)}


def _worst_stage(stage_ms: Dict[str, float]):
    total = sum(stage_ms.values())
    if total <= 0.0:
        return None, None
    stage = max(stage_ms, key=lambda s: stage_ms[s])
    return stage, round(stage_ms[stage] / total, 4)


def _exemplar_doc(rec: dict) -> dict:
    return {
        "trace_id": rec["trace_id"],
        "tenant": rec["tenant"],
        "via": rec["via"],
        "e2e_ms": round(rec["e2e_ms"], 3),
        "stages": {s: round(ms, 3) for s, ms in rec["stages"].items()},
        "detours": rec["detours"],
    }


def lineage_summary() -> Dict[str, Any]:
    """JSON-able roll-up for ``/snapshot.json`` / bench / sts_top."""
    with _lock:
        tenants = {label: {"e2e": list(st["e2e"]),
                           "stage_ms": dict(st["stage_ms"]),
                           "n": st["n"], "cache": st["cache"]}
                   for label, st in _tenants.items()}
        doc: Dict[str, Any] = {
            "armed": _armed,
            "started": _started,
            "outcomes": dict(_outcomes),
            "open": _started - sum(_outcomes.values()),
            "duplicate_completions": _duplicates,
            "tenant_overflow": _tenant_overflow,
            "ring": {"len": len(_ring), "capacity": _cap,
                     "dropped": _ring_dropped},
            "stage_totals_ms": {s: round(ms, 3)
                                for s, ms in _stage_ms.items()},
            "exemplars": [_exemplar_doc(r) for r in _exemplars],
        }
    pooled: List[float] = []
    tdocs: Dict[str, Any] = {}
    for label, st in tenants.items():
        pooled.extend(st["e2e"])
        stage, share = _worst_stage(st["stage_ms"])
        tdocs[label] = {**_pcts(st["e2e"]),
                        "delivered": st["n"],
                        "cache_serves": st["cache"],
                        "worst_stage": stage,
                        "worst_stage_share": share}
    doc["e2e"] = _pcts(pooled)
    stage, share = _worst_stage(doc["stage_totals_ms"])
    doc["worst_stage"] = stage
    doc["worst_stage_share"] = share
    doc["tenants"] = tdocs
    return doc


def trace_events(limit: Optional[int] = None) -> List[dict]:
    """Completed lineage stages as timeline events compatible with the
    :func:`~spark_timeseries_tpu.utils.tracing.to_chrome_trace` input
    shape (``span`` dicts on the shared ``perf_counter`` clock), so
    ``/trace.json`` interleaves them with engine spans.  Records are
    striped over a few synthetic integer lanes to keep concurrent ticks
    visually separable."""
    recs = records()
    if limit is not None and limit >= 0:
        recs = recs[-limit:]
    events: List[dict] = []
    for rec in recs:
        lane = rec["trace_id"] % _LINEAGE_LANES
        tid = _LINEAGE_TID_BASE + lane
        tname = f"lineage-{lane}"
        for stage, ts, dur in rec["segs"]:
            events.append({
                "kind": "span",
                "name": f"lineage.{stage}",
                "ts": ts,
                "dur": dur,
                "tid": tid,
                "tname": tname,
                "args": {"trace_id": rec["trace_id"],
                         "tenant": rec["tenant"],
                         "via": rec["via"],
                         "outcome": rec["outcome"]},
            })
    return events


def incident_block(limit: int = 64) -> Dict[str, Any]:
    """Newest lineage records + counters for flight-recorder bundles,
    so a crashed pump's recent ticks are forensically reconstructible."""
    recs = records()[-max(int(limit), 0):]
    with _lock:
        counters = {
            "armed": _armed,
            "started": _started,
            "outcomes": dict(_outcomes),
            "open": _started - sum(_outcomes.values()),
            "duplicate_completions": _duplicates,
            "ring_dropped": _ring_dropped,
        }
    return {**counters,
            "records": [{**r, "e2e_ms": round(r["e2e_ms"], 3),
                         "stages": {s: round(ms, 3)
                                    for s, ms in r["stages"].items()},
                         "segs": [(s, round(ts, 6), round(d, 6))
                                  for s, ts, d in r["segs"]]}
                        for r in recs]}
