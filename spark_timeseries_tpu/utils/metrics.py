"""Structured runtime metrics: registry, spans, and XLA recompile tracking.

The reference's only telemetry is per-series ``println`` warnings on
non-stationary fits (ref ``ARIMA.scala:248-256``); the Spark UI answers
"where did the time go" for it.  This module is that tier for the TPU
build — the production questions the ROADMAP north-star poses (how many
times did XLA recompile this workload, where did wall-time go, which fit
stage regressed between benches) are answered by three pieces, no new
dependencies:

- a process-local **registry** of counters / gauges / histograms with
  explicit :meth:`MetricsRegistry.record` / :meth:`MetricsRegistry.snapshot`
  / :meth:`MetricsRegistry.reset` and JSON + Prometheus-text export;
- a **span** API (``with metrics.span("arima.fit_panel"): ...``) that
  nests (paths join with ``/``), records wall-time histograms, and
  forwards each scope to ``jax.profiler.TraceAnnotation`` so the same
  names show up in xprof device traces;
- **recompile / transfer tracking** off ``jax.monitoring``'s event hooks
  (:func:`install_jax_hooks`): XLA backend compiles become the
  ``jax.jit_compiles`` counter + ``jax.compile_s`` histogram, jaxpr
  tracing becomes ``jax.trace_s``, compilation-cache and transfer events
  are counted when the installed JAX emits them — with a graceful no-op
  fallback (``install_jax_hooks() -> False``) when the hooks are absent.

Everything here is **host-side only**: instrumented library code (model
``fit`` entry points, the batched optimizers, panel/io choke points) adds
no operations to traced graphs.  Values that may be tracers (a ``fit``
called under ``jit``) are detected and counted as traced calls instead of
being materialized — see :func:`record_fit` / :func:`observe_minimize`.

``bench.py`` embeds :func:`snapshot` + :func:`jax_stats` into every
``BENCH_*.json`` record, so the perf trajectory carries *why* (recompiles,
compile seconds, per-span wall time) alongside *how fast*.

``STS_METRICS=0`` disables all recording (spans still forward to the
profiler); :func:`set_enabled` overrides at runtime.

Besides the aggregate histograms, every span scope also records a
**timeline event** (begin timestamp + duration + thread) into a bounded
process-global ring buffer, and recompiles / resilience fallback stages
record **instant events** — the raw material ``utils.tracing`` exports as
a Chrome trace-event file loadable in Perfetto (``STS_TRACE=/path.json``
dumps it atexit).  The ring holds the most recent
``STS_TRACE_CAPACITY`` events (default 65536, ~100 bytes each) so the
timeline tier is always-on without unbounded growth; ``STS_METRICS=0``
disables it together with everything else.
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "counter", "gauge", "histogram", "inc", "set_gauge", "record",
    "snapshot", "reset", "to_json", "to_prometheus",
    "span", "current_span_path",
    "TraceBuffer", "trace_buffer", "trace_events", "trace_instant",
    "clear_trace", "set_trace_capacity", "add_span_listener",
    "remove_span_listener",
    "install_jax_hooks", "jax_hooks_installed", "jax_stats",
    "record_fit", "record_fit_report", "observe_minimize",
    "instrument_fit", "instrumented", "enabled", "set_enabled",
    "get_registry",
]

# Percentile sample cap per histogram: count/sum/min/max stay exact past
# it; p50/p95 come from a deterministic ring of the most recent samples.
MAX_SAMPLES = 4096


def _fmt(v) -> str:
    """Deterministic number formatting shared by the text exports."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return format(f, ".10g")


class Counter:
    """Monotonically increasing integer.  Mutations hold the owning
    registry's lock (standalone construction gets its own), so handles
    obtained via ``registry.counter(name)`` increment safely across
    threads."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock: Optional[threading.RLock] = None):
        self.value = 0
        self._lock = lock if lock is not None else threading.RLock()

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self.value += int(n)


class Gauge:
    """Last-write-wins float."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock: Optional[threading.RLock] = None):
        self.value = 0.0
        self._lock = lock if lock is not None else threading.RLock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Streaming count/sum/min/max plus a bounded sample ring for
    percentiles (deterministic: the ring keeps the most recent
    ``max_samples`` observations, overwritten in arrival order).
    ``record`` holds the owning registry's lock so concurrent recorders
    never tear the count/sum/ring triple."""

    __slots__ = ("count", "sum", "min", "max", "_samples", "_cap", "_lock")

    def __init__(self, max_samples: int = MAX_SAMPLES,
                 lock: Optional[threading.RLock] = None):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._samples: list = []
        self._cap = max_samples
        self._lock = lock if lock is not None else threading.RLock()

    def record(self, v: float) -> None:
        v = float(v)
        with self._lock:
            if len(self._samples) < self._cap:
                self._samples.append(v)
            else:
                self._samples[self.count % self._cap] = v
            self.count += 1
            self.sum += v
            self.min = min(self.min, v)
            self.max = max(self.max, v)

    def raw(self) -> tuple:
        """Consistent ``(count, sum, min, max, samples-copy)`` under one
        lock acquisition — the snapshot tier's raw material.  Percentile
        math happens on the copy *outside* the lock, so a scrape never
        stalls concurrent ``record()`` calls for the numpy work."""
        with self._lock:
            return (self.count, self.sum, self.min, self.max,
                    list(self._samples))

    def percentile(self, q: float) -> float:
        _, _, _, _, samples = self.raw()
        if not samples:
            return float("nan")
        return float(np.percentile(np.asarray(samples), q))

    def stats(self) -> Dict[str, float]:
        return _hist_stats(*self.raw())


def _hist_stats(count: int, total: float, mn: float, mx: float,
                samples: list) -> Dict[str, float]:
    """Histogram summary off one consistent :meth:`Histogram.raw` read
    (lock already released — see snapshot hardening note there)."""
    if count == 0:
        return {"count": 0, "sum": 0.0}
    s = np.asarray(samples)
    return {
        "count": count,
        "sum": total,
        "min": mn,
        "max": mx,
        "mean": total / count,
        "p50": float(np.percentile(s, 50)),
        "p95": float(np.percentile(s, 95)),
    }


class MetricsRegistry:
    """Process-local named metrics.  One reentrant lock is shared by the
    registry and every metric object it creates, so both registry-level
    calls (``inc``/``record``/``snapshot``) and direct handle mutations
    (``registry.counter(n).inc()``) are safe across concurrent host
    threads (e.g. a double-buffered pipeline's puller)."""

    def __init__(self, max_samples: int = MAX_SAMPLES):
        self._lock = threading.RLock()
        self._max_samples = max_samples
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._spans: Dict[str, Histogram] = {}
        self.enabled = os.environ.get("STS_METRICS", "1") != "0"

    # -- get-or-create accessors -------------------------------------------

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(self._lock)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(self._lock)
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(self._max_samples,
                                                       self._lock)
            return h

    # -- explicit record / snapshot / reset --------------------------------

    def inc(self, name: str, n: int = 1) -> None:
        if self.enabled:
            self.counter(name).inc(n)

    def set_gauge(self, name: str, v: float) -> None:
        if self.enabled:
            self.gauge(name).set(v)

    def record(self, name: str, value: float) -> None:
        """Record one observation into the named histogram."""
        if self.enabled:
            self.histogram(name).record(value)

    def record_span(self, path: str, seconds: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            h = self._spans.get(path)
            if h is None:
                h = self._spans[path] = Histogram(self._max_samples,
                                                  self._lock)
        h.record(seconds)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view of every metric.  Span stats carry ``_s``
        suffixes to make the unit unambiguous in bench artifacts.

        Concurrency-hardened for the telemetry exporter (the first
        consumer that snapshots from a *different* thread while worker
        threads mutate): the registry lock is held only long enough to
        copy scalar values and histogram sample rings, so a scrape can
        never observe a torn count/sum/ring triple — and the numpy
        percentile work runs on the copies *after* the lock drops, so
        scraping never stalls the instrumented hot paths either."""
        with self._lock:
            counters = {k: v.value for k, v in sorted(self._counters.items())}
            gauges = {k: v.value for k, v in sorted(self._gauges.items())}
            hist_raw = {k: v.raw()
                        for k, v in sorted(self._histograms.items())}
            span_raw = {k: v.raw() for k, v in sorted(self._spans.items())}
        hists = {k: _hist_stats(*r) for k, r in hist_raw.items()}
        spans = {}
        for k, (count, total, mn, mx, samples) in span_raw.items():
            if count:
                s = np.asarray(samples)
                p50, p95 = (float(np.percentile(s, q)) for q in (50, 95))
            else:
                p50 = p95 = 0.0
            spans[k] = {
                "count": count,
                "total_s": total,
                "mean_s": total / count if count else 0.0,
                "min_s": mn if count else 0.0,
                "max_s": mx if count else 0.0,
                "p50_s": p50,
                "p95_s": p95,
            }
        return {"counters": counters, "gauges": gauges,
                "histograms": hists, "spans": spans}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._spans.clear()

    # -- export -------------------------------------------------------------

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def to_prometheus(self, prefix: str = "sts") -> str:
        """Prometheus text exposition (format 0.0.4 — what a real
        scraper parses off the telemetry exporter's ``/metrics``).

        Conformance notes: every metric family gets a ``# HELP`` line
        (help text escapes ``\\`` and newlines per the exposition
        grammar) followed by its ``# TYPE``; histograms and spans export
        as ``summary`` families whose ``{quantile=...}`` samples are
        always accompanied by the ``_sum``/``_count`` samples the
        summary type *requires* (quantile samples alone are rejected or
        misread by real scrapers); metric names are sanitized to
        ``[a-zA-Z0-9_]`` with the given prefix; an empty registry
        exports as an empty string (a lone blank line is not valid
        exposition text)."""

        def sanitize(name: str) -> str:
            return prefix + "_" + "".join(
                ch if ch.isalnum() or ch == "_" else "_" for ch in name)

        def esc_help(text: str) -> str:
            return text.replace("\\", "\\\\").replace("\n", "\\n")

        snap = self.snapshot()
        lines = []
        for name, value in snap["counters"].items():
            m = sanitize(name)
            lines.append(f"# HELP {m} {esc_help(name)} (counter)")
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {_fmt(value)}")
        for name, value in snap["gauges"].items():
            m = sanitize(name)
            lines.append(f"# HELP {m} {esc_help(name)} (gauge)")
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_fmt(value)}")
        for section, unit, kind in (("histograms", "", "histogram"),
                                    ("spans", "_seconds", "span")):
            for name, st in snap[section].items():
                m = sanitize(name) + unit
                lines.append(f"# HELP {m} {esc_help(name)} ({kind})")
                lines.append(f"# TYPE {m} summary")
                if st["count"]:
                    p50 = st.get("p50", st.get("p50_s"))
                    p95 = st.get("p95", st.get("p95_s"))
                    lines.append(f'{m}{{quantile="0.5"}} {_fmt(p50)}')
                    lines.append(f'{m}{{quantile="0.95"}} {_fmt(p95)}')
                total = st.get("sum", st.get("total_s", 0.0))
                lines.append(f"{m}_sum {_fmt(total)}")
                lines.append(f"{m}_count {_fmt(st['count'])}")
        return "\n".join(lines) + "\n" if lines else ""


# ---------------------------------------------------------------------------
# Default registry + module-level convenience API
# ---------------------------------------------------------------------------

_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default_registry


def enabled() -> bool:
    return _default_registry.enabled


def set_enabled(on: bool) -> None:
    _default_registry.enabled = bool(on)


def counter(name: str) -> Counter:
    return _default_registry.counter(name)


def gauge(name: str) -> Gauge:
    return _default_registry.gauge(name)


def histogram(name: str) -> Histogram:
    return _default_registry.histogram(name)


def inc(name: str, n: int = 1) -> None:
    _default_registry.inc(name, n)


def set_gauge(name: str, v: float) -> None:
    _default_registry.set_gauge(name, v)


def record(name: str, value: float) -> None:
    _default_registry.record(name, value)


def snapshot() -> Dict[str, Any]:
    return _default_registry.snapshot()


def reset() -> None:
    _default_registry.reset()


def to_json(indent: Optional[int] = None) -> str:
    return _default_registry.to_json(indent)


def to_prometheus(prefix: str = "sts") -> str:
    return _default_registry.to_prometheus(prefix)


# ---------------------------------------------------------------------------
# Trace timeline: bounded ring buffer of span / instant events
# ---------------------------------------------------------------------------

# Default event capacity; overridable via STS_TRACE_CAPACITY or
# set_trace_capacity().  Each event is a small dict (~100 bytes), so the
# default ring tops out around ~6 MB — cheap enough to leave always-on.
TRACE_CAPACITY = 65536

# perf_counter <-> wall-clock anchor taken at import, so the exporter can
# stamp the trace with an absolute start time without every event paying
# for a time.time() call.
_TRACE_EPOCH = (time.time(), time.perf_counter())


class TraceBuffer:
    """Bounded ring of timeline events (most recent ``capacity`` kept).

    Two event kinds, both JSON-able dicts:

    - ``span``: one per completed :func:`span` scope — ``name`` is the
      nested ``/``-joined path, ``ts`` the scope's *begin* on the
      ``perf_counter`` clock (seconds), ``dur`` its duration (seconds),
      ``tid``/``tname`` the recording thread.  Begin + duration is the
      begin/end pair in one record (Chrome trace "complete" events).
    - ``instant``: a zero-duration marker (recompiles, resilience
      fallback stages) with optional ``args``.

    Appends hold a private lock (never the registry's: an event append
    must not contend with snapshot walks); overwrite order is arrival
    order, exactly like :class:`Histogram`'s sample ring.
    """

    def __init__(self, capacity: int = TRACE_CAPACITY):
        self._lock = threading.Lock()
        self._cap = int(capacity)
        self._events: list = []
        self._head = 0          # next overwrite slot once full
        self.dropped = 0        # events overwritten since last clear

    def append(self, event: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) < self._cap:
                self._events.append(event)
            else:
                self._events[self._head] = event
                self._head = (self._head + 1) % self._cap
                self.dropped += 1

    def events(self) -> list:
        """Copy of the buffered events, oldest first."""
        with self._lock:
            return self._events[self._head:] + self._events[:self._head]

    def clear(self) -> None:
        with self._lock:
            self._events = []
            self._head = 0
            self.dropped = 0

    def set_capacity(self, capacity: int) -> None:
        """Resize, keeping the newest events that still fit."""
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError(f"trace capacity must be >= 1, got {capacity}")
        with self._lock:
            ordered = self._events[self._head:] + self._events[:self._head]
            self._events = ordered[-capacity:]
            self._head = 0
            self._cap = capacity

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    @property
    def capacity(self) -> int:
        return self._cap


_trace_buffer = TraceBuffer(
    int(os.environ.get("STS_TRACE_CAPACITY", str(TRACE_CAPACITY))))


def trace_buffer() -> TraceBuffer:
    return _trace_buffer


def trace_events() -> list:
    """Buffered timeline events, oldest first.  Note spans land at scope
    *exit*, so a nested child precedes its parent here; sort by ``ts``
    for begin-time order (``utils.tracing`` does)."""
    return _trace_buffer.events()


def clear_trace() -> None:
    _trace_buffer.clear()


def set_trace_capacity(capacity: int) -> None:
    _trace_buffer.set_capacity(capacity)


def _thread_ids():
    t = threading.current_thread()
    return t.ident or 0, t.name


def trace_instant(name: str, args: Optional[Dict[str, Any]] = None) -> None:
    """Record a zero-duration timeline marker (shown as an instant arrow
    in Perfetto).  Used for recompiles and resilience fallback stages;
    library code is free to add its own."""
    if not _default_registry.enabled:
        return
    tid, tname = _thread_ids()
    ev = {"kind": "instant", "name": name, "ts": time.perf_counter(),
          "tid": tid, "tname": tname}
    if args:
        ev["args"] = args
    _trace_buffer.append(ev)


def _trace_span_event(reg: "MetricsRegistry", path: str, t0: float,
                      dur: float) -> None:
    # the ring is the DEFAULT registry's timeline: spans recorded against
    # a private registry (test isolation) must not leak phantom events
    # into STS_TRACE dumps or bench slowest-spans blocks
    if reg is not _default_registry or not reg.enabled:
        return
    tid, tname = _thread_ids()
    _trace_buffer.append({"kind": "span", "name": path, "ts": t0,
                          "dur": dur, "tid": tid, "tname": tname})


# Span-exit listeners: callables ``fn(path, seconds)`` invoked after each
# scope records (utils.costs registers the device-memory sampler here).
# A listener that raises is dropped — observability must never take the
# instrumented code down with it.
_span_listeners: list = []


def add_span_listener(fn: Callable[[str, float], None]) -> None:
    if fn not in _span_listeners:
        _span_listeners.append(fn)


def remove_span_listener(fn: Callable[[str, float], None]) -> None:
    if fn in _span_listeners:
        _span_listeners.remove(fn)


def _notify_span_listeners(path: str, dt: float) -> None:
    for fn in list(_span_listeners):
        try:
            fn(path, dt)
        except Exception:       # noqa: BLE001 — see note above
            remove_span_listener(fn)


# STS_TRACE=/path.json: dump the Chrome trace at interpreter exit.  The
# tracing module imports this one, so the import happens lazily inside
# the handler (registered here because metrics is the module everything
# else already pulls in).
if os.environ.get("STS_TRACE"):
    import atexit

    def _dump_trace_atexit(_path=os.environ["STS_TRACE"]) -> None:
        try:
            from . import tracing
            tracing.write_trace(_path)
        except Exception:       # noqa: BLE001 — exit paths must not raise
            pass

    atexit.register(_dump_trace_atexit)


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------

_span_state = threading.local()


def _trace_annotation(path: str):
    """The xprof bridge: every span scope is also a profiler
    TraceAnnotation, so span names line up between bench JSON and device
    traces.  Falls back to a null scope if the profiler is unavailable."""
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation(path)
    except Exception:  # pragma: no cover — jax always present in-tree
        return contextlib.nullcontext()


def current_span_path() -> str:
    """``/``-joined path of the active span stack ("" at top level)."""
    return "/".join(getattr(_span_state, "stack", []))


@contextlib.contextmanager
def span(name: str, registry: Optional[MetricsRegistry] = None
         ) -> Iterator[None]:
    """Named wall-time scope.  Nesting joins paths with ``/``
    (``arima.fit_panel/arima.fit``); each distinct path accumulates its
    own wall-time histogram in the registry, and the scope forwards to
    ``jax.profiler.TraceAnnotation`` so it shows up in xprof too.

    Host-side only: wall time of a scope that merely *traces* jitted code
    is trace+compile time, which is exactly what the recompile-tracking
    story wants surfaced (the span's ``count`` then counts retraces).

    Each completed scope additionally lands one timeline event in the
    trace ring buffer (begin + duration — the Perfetto export's raw
    material) and fires the registered span-exit listeners.
    """
    reg = registry if registry is not None else _default_registry
    stack = getattr(_span_state, "stack", None)
    if stack is None:
        stack = _span_state.stack = []
    stack.append(name)
    path = "/".join(stack)
    t0 = time.perf_counter()
    try:
        with _trace_annotation(path):
            yield
    finally:
        dt = time.perf_counter() - t0
        stack.pop()
        reg.record_span(path, dt)
        _trace_span_event(reg, path, t0, dt)
        _notify_span_listeners(path, dt)


# ---------------------------------------------------------------------------
# jax.monitoring bridge: recompiles, compile seconds, transfers
# ---------------------------------------------------------------------------

import weakref

# Registries receiving jax.monitoring events.  Weakly referenced: the
# module-lifetime listeners must not pin short-lived registries (and their
# sample rings) in memory forever.
_hooked_registries: "weakref.WeakSet" = weakref.WeakSet()
_listeners_registered = False
_install_lock = threading.Lock()


def _is_tracer(x) -> bool:
    try:
        from jax.core import Tracer
    except Exception:  # pragma: no cover
        return False
    return isinstance(x, Tracer)


def install_jax_hooks(registry: Optional[MetricsRegistry] = None) -> bool:
    """Register ``jax.monitoring`` listeners feeding the registry.

    Counts/aggregates, per process since install:

    - ``jax.jit_compiles`` (counter) + ``jax.compile_s`` (histogram) from
      ``/jax/core/compile/backend_compile_duration`` — one event per XLA
      backend compilation, i.e. the recompile question;
    - ``jax.trace_s`` from ``jaxpr_trace_duration`` (Python tracing time);
    - ``jax.cache_misses`` / ``jax.cache_hits`` from the persistent
      compilation cache's events (when that cache is enabled);
    - any event whose name mentions ``transfer`` as ``jax.transfers`` (+
      ``jax.transfer_s`` when it carries a duration) — versions of JAX
      that don't emit transfer events simply leave these at 0 (the panel
      tier counts its own explicit H2D/D2H bytes independently).

    Returns False (and records nothing, ever) when the installed JAX
    lacks the hooks — the graceful no-op fallback.  Idempotent per
    registry.  Exactly ONE listener pair is ever registered with JAX (the
    hooks cannot be unregistered on this JAX version); it dispatches to a
    weak set of hooked registries, so hooking a short-lived registry
    neither leaks it nor stacks listeners (recording is further gated by
    ``registry.enabled``).
    """
    global _listeners_registered
    reg = registry if registry is not None else _default_registry
    try:
        from jax import monitoring
        register_event = monitoring.register_event_listener
        register_duration = monitoring.register_event_duration_secs_listener
    except (ImportError, AttributeError):
        return False
    if not callable(register_event) or not callable(register_duration):
        return False
    with _install_lock:
        # locked check-then-act: JAX listeners cannot be unregistered, so
        # a concurrent double-install would double-count every compile
        # event for the life of the process
        if reg in _hooked_registries:
            return True
        if not _listeners_registered:
            register_event(_on_jax_event)
            register_duration(_on_jax_event_duration)
            _listeners_registered = True
        _hooked_registries.add(reg)
    # eagerly materialize the headline keys so a snapshot taken before the
    # first compile still carries them (bench artifacts stay uniform)
    reg.counter("jax.jit_compiles")
    reg.counter("jax.cache_misses")
    reg.counter("jax.cache_hits")
    reg.counter("jax.transfers")
    reg.histogram("jax.compile_s")
    return True


def _on_jax_event(event: str, **kw) -> None:
    for reg in list(_hooked_registries):
        if not reg.enabled:
            continue
        if event.endswith("cache_misses"):
            reg.counter("jax.cache_misses").inc()
        elif event.endswith("cache_hits"):
            reg.counter("jax.cache_hits").inc()
        elif "transfer" in event:
            reg.counter("jax.transfers").inc()


def _on_jax_event_duration(event: str, duration_secs: float, **kw) -> None:
    for reg in list(_hooked_registries):
        if not reg.enabled:
            continue
        if event.endswith("backend_compile_duration"):
            reg.counter("jax.jit_compiles").inc()
            reg.histogram("jax.compile_s").record(duration_secs)
            if reg is _default_registry:
                # a recompile is a point-in-time story the timeline view
                # wants marked (one instant arrow per XLA backend compile)
                trace_instant("jax.compile",
                              {"duration_s": round(duration_secs, 6),
                               "span": current_span_path()})
        elif event.endswith("jaxpr_trace_duration"):
            reg.histogram("jax.trace_s").record(duration_secs)
        elif "transfer" in event:
            reg.counter("jax.transfers").inc()
            reg.histogram("jax.transfer_s").record(duration_secs)


def jax_hooks_installed(registry: Optional[MetricsRegistry] = None) -> bool:
    reg = registry if registry is not None else _default_registry
    return reg in _hooked_registries


def jax_stats(registry: Optional[MetricsRegistry] = None,
              snap: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Compact recompile/transfer summary for bench artifacts.  Keys are
    always present (0 when the hooks saw nothing or aren't installed).
    Pass ``snap`` (a ``snapshot()`` already in hand) to avoid walking the
    registry a second time."""
    reg = registry if registry is not None else _default_registry
    if snap is None:
        snap = reg.snapshot()
    c, h = snap["counters"], snap["histograms"]

    def hist_sum(name):
        return float(h.get(name, {}).get("sum", 0.0))

    return {
        "hooks_installed": jax_hooks_installed(reg),
        "jit_compiles": int(c.get("jax.jit_compiles", 0)),
        "compile_s_total": hist_sum("jax.compile_s"),
        "trace_s_total": hist_sum("jax.trace_s"),
        "cache_misses": int(c.get("jax.cache_misses", 0)),
        "cache_hits": int(c.get("jax.cache_hits", 0)),
        "transfers": int(c.get("jax.transfers", 0)),
        "transfer_s_total": hist_sum("jax.transfer_s"),
    }


# ---------------------------------------------------------------------------
# Instrumentation helpers for the library's choke points
# ---------------------------------------------------------------------------

def record_fit(family: str, model,
               registry: Optional[MetricsRegistry] = None) -> None:
    """One fit-report counter bundle off a fitted model's diagnostics.

    Host-side only: when the model's diagnostics are tracers (the fit ran
    under ``jit``/``vmap`` tracing, where materializing would either fail
    or bake host constants into the graph) the call counts a
    ``fit.<family>.traced`` retrace instead — the concrete numbers for
    such fits surface through the jit caller's own ``fit_report``.

    Cost note: on an *eager* fit the ``np.asarray`` reads block until the
    fit's device computation finishes, trading async-dispatch overlap for
    exact counters.  The perf-critical paths are unaffected — jitted fits
    (bench, production pipelines) hit the tracer branch above — and
    ``STS_METRICS=0`` removes the reads entirely for eager-mode loops
    that need maximal dispatch pipelining.
    """
    reg = registry if registry is not None else _default_registry
    if not reg.enabled:
        return
    reg.counter(f"fit.{family}.calls").inc()
    diag = getattr(model, "diagnostics", None)
    if diag is None:
        return
    if any(_is_tracer(leaf) for leaf in
           (diag.converged, diag.n_iter, diag.fun)):
        reg.counter(f"fit.{family}.traced").inc()
        return
    try:
        conv = np.asarray(diag.converged).reshape(-1)
        n_iter = np.asarray(diag.n_iter).reshape(-1)
        fun = np.asarray(diag.fun).reshape(-1)
    except Exception:
        # e.g. eval_shape's ShapeDtypeStruct leaves — nothing concrete
        reg.counter(f"fit.{family}.traced").inc()
        return
    reg.counter(f"fit.{family}.series").inc(int(conv.size))
    reg.counter(f"fit.{family}.converged").inc(int(np.sum(conv)))
    reg.counter(f"fit.{family}.diverged").inc(int(np.sum(~np.isfinite(fun))))
    if n_iter.size:
        reg.histogram(f"fit.{family}.iters_mean").record(
            float(np.mean(n_iter)))
        reg.histogram(f"fit.{family}.iters_max").record(
            float(np.max(n_iter)))


def record_fit_report(family: str, report: Dict[str, Any],
                      registry: Optional[MetricsRegistry] = None) -> None:
    """Accumulate an ``observability.fit_report`` dict as a counter bundle
    (``fit_report.<family>.*``), so repeated fits add up across a workload.
    Kept in a separate namespace from :func:`record_fit`'s automatic
    ``fit.<family>.*`` bundle — a user calling ``fit_report`` on an
    already-instrumented model must not double-count the automatic one."""
    reg = registry if registry is not None else _default_registry
    if not reg.enabled:
        return
    pre = f"fit_report.{family}"
    reg.counter(f"{pre}.reports").inc()
    reg.counter(f"{pre}.n_series").inc(int(report.get("n_series", 0)))
    reg.counter(f"{pre}.n_converged").inc(int(report.get("n_converged", 0)))
    reg.counter(f"{pre}.n_diverged").inc(int(report.get("n_diverged", 0)))
    if report.get("n_series"):
        reg.histogram(f"{pre}.iters_mean").record(
            float(report.get("iters_mean", 0.0)))
        reg.histogram(f"{pre}.frac_converged").record(
            float(report.get("frac_converged", 0.0)))


def observe_minimize(solver: str, result,
                     registry: Optional[MetricsRegistry] = None):
    """Per-call iteration/convergence histograms off a ``MinimizeResult``.

    Called at the tail of every public optimizer in ``ops.optimize``.
    Host-side only: under tracing only ``optimize.<solver>.traced_calls``
    increments (a retrace count in its own right).  Returns the result so
    call sites can tail-call it.
    """
    reg = registry if registry is not None else _default_registry
    if not reg.enabled:
        return result
    pre = f"optimize.{solver}"
    reg.counter(f"{pre}.calls").inc()
    if any(_is_tracer(leaf) for leaf in
           (result.x, result.converged, result.n_iter)):
        reg.counter(f"{pre}.traced_calls").inc()
        return result
    try:
        conv = np.asarray(result.converged).reshape(-1)
        n_iter = np.asarray(result.n_iter).reshape(-1)
    except Exception:
        reg.counter(f"{pre}.traced_calls").inc()
        return result
    reg.counter(f"{pre}.lanes").inc(int(conv.size))
    reg.counter(f"{pre}.lanes_converged").inc(int(np.sum(conv)))
    if n_iter.size:
        reg.histogram(f"{pre}.iters_mean").record(float(np.mean(n_iter)))
        reg.histogram(f"{pre}.iters_max").record(float(np.max(n_iter)))
    return result


def instrumented(span_name: str) -> Callable:
    """Span-only decorator for non-fit choke points (io load/save paths,
    panel conversions): wall-time histogram + xprof annotation, nothing
    recorded off the return value."""

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(span_name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def instrument_fit(family: str, record: bool = True,
                   name: Optional[str] = None) -> Callable:
    """Decorator for model fit entry points: one span
    (``<family>.<fn name>``, nesting under any active span) plus, when
    ``record`` is True, one :func:`record_fit` counter bundle off the
    returned model.  ``record=False`` is for wrappers (``fit_panel``,
    ``auto_fit_panel``) whose inner ``fit`` already records — the wrapper
    still gets its span so the nesting shows where panel time goes."""

    def deco(fn: Callable) -> Callable:
        span_name = name or f"{family}.{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            # record INSIDE the span: on an eager accelerator fit the
            # recorder's np.asarray is what blocks until the device work
            # finishes, so recording outside would attribute the compute
            # wall-time to no span at all (dispatch-only spans)
            with span(span_name):
                out = fn(*args, **kwargs)
                if record:
                    record_fit(family, out)
            return out

        return wrapper

    return deco
