"""Durability layer for streaming fit jobs: crash-consistent chunk
journal, resume validation, and deterministic retry/backoff policy.

PR 5's ``engine.stream_fit`` made every batched fit a chunked stream, but
the stream itself was a single point of failure: a process death, a hung
compile, or an OOM mid-stream lost every completed chunk and could wedge
the job.  ARIMA_PLUS (PAPERS.md, arXiv 2510.24452) argues that what makes
in-database forecasting a *product* is hands-off operation at scale;
DARIMA (arXiv 2007.09577) frames exactly this workload — long-running
distributed fits over huge panels — where partial-progress durability is
the missing robustness tier on top of PR 2's per-series fallback.

This module is the host-side substrate the engine's durable streaming
builds on (``engine.stream_fit(..., journal=...)``); nothing here ever
runs under a JAX trace:

- :class:`ChunkJournal` — a directory of per-chunk result commits.  Each
  committed chunk is a :mod:`~spark_timeseries_tpu.utils.checkpoint`
  pytree pair (``.npz`` + ``.tree.json``, both written tmp-file+rename)
  plus a ``.ok`` commit marker whose atomic rename IS the commit point:
  a chunk exists iff its marker does, so a kill -9 at any instant leaves
  either a fully committed chunk or no chunk, never a torn one.  The
  journal's ``MANIFEST.json`` records a content hash of the job spec
  (family, statics, dtype, bucket policy, chunk partition); opening the
  same path with a different spec refuses with
  :class:`JournalSpecMismatch` instead of silently mixing results from
  two different jobs.  Restores go through ``checkpoint.load_pytree``'s
  shape/dtype-validated path, so bit-rot or a swapped ``.npz`` surfaces
  as a detected corruption — the entry is moved to ``quarantine/`` and
  the chunk refits — never as silently wrong numbers.
- :class:`BackoffPolicy` — bounded exponential backoff for the engine's
  end-of-stream quarantine retries.  Purely deterministic (the delay is
  a closed form of the attempt number; no wall-clock reads feed traced
  code) and host-side (``time.sleep`` between attempts).
- :class:`ChunkDeadlineExceeded` / :func:`is_oom` — the failure taxonomy
  the engine's watchdog and degradation tiers route on.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from . import checkpoint as _checkpoint

__all__ = [
    "BackoffPolicy", "as_backoff",
    "ChunkDeadlineExceeded", "JournalSpecMismatch",
    "is_oom", "spec_digest", "array_digest", "atomic_write_json",
    "ChunkJournal",
]


class JournalSpecMismatch(ValueError):
    """A chunk journal was written by a different job spec (family,
    statics, dtype, bucket policy, or chunk partition) than the one now
    trying to resume from it.  Raised eagerly when the journal is opened
    — resuming would silently mix results from two different jobs."""


class ChunkDeadlineExceeded(RuntimeError):
    """A streaming chunk's dispatch or result materialization outlived
    the armed per-chunk deadline (``STS_CHUNK_DEADLINE_S`` or
    ``stream_fit(..., deadline_s=)``).  The watchdog abandons the hung
    worker thread and the stream continues; the chunk is recorded like
    any other chunk failure and quarantined for end-of-stream retry."""


class BackoffPolicy(NamedTuple):
    """Bounded exponential backoff for quarantined-chunk retries.

    ``max_retries`` attempts after the original failure (0 = declare the
    chunk dead immediately — the pre-durability behavior);
    :meth:`delay` for attempt ``k`` (1-based) is
    ``min(base_delay_s * multiplier**(k-1), max_delay_s)`` — a closed
    form of the attempt number, so retry schedules are deterministic and
    no wall-clock value ever feeds traced code (the sleep itself is
    host-side, between dispatches).
    """
    max_retries: int = 2
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 2.0

    def delay(self, attempt: int) -> float:
        """Seconds to back off before retry ``attempt`` (1-based)."""
        if attempt < 1:
            raise ValueError(f"attempt is 1-based, got {attempt}")
        d = self.base_delay_s * self.multiplier ** (attempt - 1)
        return float(min(d, self.max_delay_s))


def as_backoff(retry: Any) -> BackoffPolicy:
    """Coerce ``stream_fit``'s ``retry=`` argument to a policy.

    ``None`` reads ``STS_CHUNK_RETRIES`` (default 0 — failures are
    declared dead immediately, the pre-durability stream semantics); an
    int is a retry count with the default backoff curve; a
    :class:`BackoffPolicy` passes through."""
    if retry is None:
        env = os.environ.get("STS_CHUNK_RETRIES")
        try:
            return BackoffPolicy(max_retries=max(0, int(env)) if env else 0)
        except ValueError:
            raise ValueError(
                f"STS_CHUNK_RETRIES must be an integer, got {env!r}"
            ) from None
    if isinstance(retry, BackoffPolicy):
        return retry
    if isinstance(retry, bool):
        raise TypeError("retry must be None, an int, or a BackoffPolicy")
    if isinstance(retry, int):
        return BackoffPolicy(max_retries=max(0, retry))
    raise TypeError(f"retry must be None, an int, or a BackoffPolicy, "
                    f"got {type(retry).__name__}")


def is_oom(e: BaseException) -> bool:
    """Does this exception look like an XLA allocation failure?  XLA
    surfaces device OOM as ``RESOURCE_EXHAUSTED`` status strings (or
    ``Out of memory`` on some backends); the engine's degradation tier
    keys off this classification to split the chunk instead of killing
    the stream."""
    text = f"{type(e).__name__}: {e}"
    return ("RESOURCE_EXHAUSTED" in text
            or "out of memory" in text.lower()
            or "OutOfMemory" in text)


def spec_digest(spec: Dict[str, Any]) -> str:
    """Content hash of a job spec dict (order-insensitive JSON)."""
    blob = json.dumps(spec, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def array_digest(arr) -> str:
    """Content hash of a host array's raw bytes — the job-spec field
    that refuses a resume when the panel's *data* changed under the same
    geometry (a refreshed daily panel with identical shape/dtype would
    otherwise silently restore the previous job's results).  Zero-copy
    over the array's buffer; a one-pass SHA-256 is noise next to fitting
    the panel, and runs only when a journal is armed."""
    a = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(memoryview(a).cast("B"))
    return h.hexdigest()[:16]


def atomic_write_json(path: str, obj: Any) -> None:
    """tmp-file + fsync + rename: the file either has its full contents
    or does not exist — the rename is the visibility point.  The journal
    commit marker and the flight recorder's incident bundles share this
    one implementation, so every on-disk forensic artifact carries the
    same crash-consistency guarantee."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# backwards-compatible private alias (pre-telemetry name)
_atomic_write_json = atomic_write_json


class ChunkJournal:
    """Crash-consistent per-chunk result journal for one streaming job.

    Directory layout::

        <path>/MANIFEST.json                   job-spec hash (format 1)
        <path>/chunk_<start>_<stop>.npz        array leaves (checkpoint)
        <path>/chunk_<start>_<stop>.tree.json  structure sidecar
        <path>/chunk_<start>_<stop>.ok         commit marker (atomic)
        <path>/quarantine/...                  corrupt entries, moved aside

    Commit protocol: payload files land first (each tmp+rename'd), then
    the ``.ok`` marker is renamed into place — the marker IS the commit
    point, so a chunk is committed if and only if its marker exists and a
    crash at any instant leaves no torn entries.  Entries are keyed by
    their half-open series-row range ``[start, stop)``; a chunk that was
    degraded into sub-chunks under memory pressure commits each sub-range
    separately, and :meth:`covering` recognizes an exact tiling of the
    full chunk range on resume.
    """

    MANIFEST = "MANIFEST.json"
    QUARANTINE_DIR = "quarantine"

    def __init__(self, path: str, spec: Dict[str, Any], digest: str):
        self.path = path
        self.spec = spec
        self.digest = digest
        self._index: Dict[Tuple[int, int], Dict[str, Any]] = {}
        self._scan()

    # -- open / scan --------------------------------------------------------

    @classmethod
    def open(cls, path: str, spec: Dict[str, Any]) -> "ChunkJournal":
        """Create or resume the journal at ``path`` for job ``spec``.

        A fresh directory gets a manifest recording the spec and its
        content hash; an existing one is validated against it —
        :class:`JournalSpecMismatch` (with the differing fields spelled
        out) refuses a resume under a different job."""
        os.makedirs(path, exist_ok=True)
        digest = spec_digest(spec)
        mpath = os.path.join(path, cls.MANIFEST)
        if os.path.exists(mpath):
            with open(mpath) as f:
                manifest = json.load(f)
            if manifest.get("digest") != digest:
                old = manifest.get("spec") or {}
                diffs = [f"  {k}: journal={old.get(k)!r} vs job={v!r}"
                         for k, v in sorted(spec.items())
                         if old.get(k) != v]
                raise JournalSpecMismatch(
                    f"journal at {path!r} belongs to a different job spec "
                    f"and cannot resume this one; differing fields:\n"
                    + ("\n".join(diffs)
                       or "  (fields match but recorded hash differs)")
                    + "\nuse a fresh journal path for a different job")
        else:
            _atomic_write_json(mpath, {"format": 1, "digest": digest,
                                       "spec": spec})
        return cls(path, spec, digest)

    def _scan(self) -> None:
        self._index.clear()
        for name in sorted(os.listdir(self.path)):
            if not name.endswith(".ok"):
                continue
            try:
                with open(os.path.join(self.path, name)) as f:
                    meta = json.load(f)
                key = (int(meta["start"]), int(meta["stop"]))
            except (OSError, ValueError, KeyError, TypeError):
                continue        # torn/garbled marker: not committed
            self._index[key] = meta

    def _prefix(self, start: int, stop: int) -> str:
        return os.path.join(self.path, f"chunk_{start:010d}_{stop:010d}")

    # -- queries ------------------------------------------------------------

    @property
    def n_committed(self) -> int:
        return len(self._index)

    def committed_ranges(self) -> List[Tuple[int, int]]:
        return sorted(self._index)

    def covering(self, start: int, stop: int
                 ) -> Optional[List[Dict[str, Any]]]:
        """Committed entry metas exactly tiling ``[start, stop)`` in
        order, or None when the range is not fully committed (a partial
        cover refits the whole chunk — per-chunk fits are idempotent)."""
        inside = sorted(k for k in self._index
                        if start <= k[0] and k[1] <= stop)
        if not inside:
            return None
        cursor = start
        out = []
        for k in inside:
            if k[0] != cursor:
                return None
            out.append(self._index[k])
            cursor = k[1]
        return out if cursor == stop else None

    # -- entry IO -----------------------------------------------------------

    def load(self, meta: Dict[str, Any]) -> Tuple[Any, Dict[str, Any]]:
        """Validated restore of one committed entry: the chunk's host
        model pytree plus the payload meta.  Raises (checkpoint mismatch,
        zip CRC, JSON, ...) on any corruption — callers quarantine the
        entry and refit the chunk."""
        start, stop = int(meta["start"]), int(meta["stop"])
        payload = _checkpoint.load_pytree(self._prefix(start, stop))
        pmeta = payload["meta"]
        if (int(pmeta.get("start", -1)), int(pmeta.get("stop", -1))) \
                != (start, stop):
            raise _checkpoint.CheckpointMismatchError(
                f"journal entry [{start}, {stop}) payload claims range "
                f"[{pmeta.get('start')}, {pmeta.get('stop')}) — the files "
                f"do not belong to this commit marker")
        return payload["model"], pmeta

    def commit(self, start: int, stop: int, model: Any,
               meta: Dict[str, Any]) -> None:
        """Atomically commit one chunk's fitted model.  Payload files are
        written tmp+rename first; the ``.ok`` marker rename that follows
        is the commit point.

        Any committed entry strictly inside ``[start, stop)`` is
        superseded (a full-chunk refit after a partially corrupt
        degraded cover would otherwise leave sub-entries that overlap
        the new one and defeat :meth:`covering` on every future resume).
        Stale markers drop *before* the new marker lands: a crash in
        between leaves the range uncommitted — a refit, never a mixed
        cover."""
        start, stop = int(start), int(stop)
        meta = dict(meta, start=start, stop=stop)
        prefix = self._prefix(start, stop)
        _checkpoint.save_pytree_atomic(prefix, {"model": model,
                                                "meta": meta})
        for k in [k for k in self._index
                  if k != (start, stop)
                  and start <= k[0] and k[1] <= stop]:
            sub = self._prefix(*k)
            for suffix in (".ok", ".npz", ".tree.json"):
                if os.path.exists(sub + suffix):
                    os.remove(sub + suffix)
            del self._index[k]
        _atomic_write_json(prefix + ".ok", meta)
        self._index[(start, stop)] = meta

    def quarantine(self, meta: Dict[str, Any]) -> str:
        """Move a corrupt entry's files into ``quarantine/`` so the entry
        is never trusted again (the chunk refits and recommits a fresh
        entry).  Returns the quarantine directory."""
        start, stop = int(meta["start"]), int(meta["stop"])
        qdir = os.path.join(self.path, self.QUARANTINE_DIR)
        os.makedirs(qdir, exist_ok=True)
        prefix = self._prefix(start, stop)
        base = os.path.basename(prefix)
        for suffix in (".ok", ".npz", ".tree.json"):
            src = prefix + suffix
            if os.path.exists(src):
                os.replace(src, os.path.join(qdir, base + suffix))
        self._index.pop((start, stop), None)
        return qdir

    def corrupt_entry(self, start: int, stop: int) -> None:
        """Garble a committed entry's array payload in place, leaving the
        commit marker intact — the ``corrupt_journal`` fault-injection
        hook (and test helper).  Only a validated restore can catch what
        this does; that is the point."""
        npz = self._prefix(int(start), int(stop)) + ".npz"
        size = os.path.getsize(npz)
        with open(npz, "r+b") as f:
            f.seek(size // 2)
            f.write(b"\x00CORRUPTED\x00")
