"""Distribution tier: meshes, shardings, resharding, multi-host.

The reference's distribution layer is Spark's shuffle + task scheduler over a
partitioned ``RDD[(key, Vector)]``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/TimeSeriesRDD.scala:52-648``).
The TPU-native equivalents (SURVEY.md §5):

| Spark mechanism                         | here                               |
|-----------------------------------------|------------------------------------|
| RDD partitioning over series            | ``NamedSharding(mesh, P("series"))``|
| ``toInstants`` shuffle transpose        | resharding constraint → XLA ``all_to_all`` over ICI |
| ``aggregate`` mask OR-reduction         | ``jnp.any`` over the sharded axis (XLA ``psum``) |
| driver ``collect``                      | :func:`collect` (process-0 gather) |
| Kryo serialization                      | n/a — arrays are already bytes     |
| cluster manager / executors             | ``jax.distributed`` + one process per host |

Everything here is ordinary pjit-era JAX: annotate shardings, let XLA insert
the collectives, and the same program runs on 1 chip, a v5e-8 slice, or a
multi-host DCN-connected pod.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SERIES_AXIS = "series"
TIME_AXIS = "time"


def make_mesh(n_series_shards: Optional[int] = None,
              n_time_shards: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """A ``(series, time)`` mesh over the available devices.

    The series axis is the primary data-parallel axis (the analogue of the
    reference's RDD partitioning); a time axis > 1 additionally shards the
    observation dimension for long series (sequence parallelism — beyond the
    reference's capability envelope, which keeps each series on one machine,
    ref ``src/site/markdown/index.md:35-40``).
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_series_shards is None:
        n_series_shards = len(devs) // n_time_shards
    n = n_series_shards * n_time_shards
    if n > len(devs):
        raise ValueError(
            f"mesh {n_series_shards}x{n_time_shards} needs {n} devices, "
            f"have {len(devs)}")
    grid = np.array(devs[:n]).reshape(n_series_shards, n_time_shards)
    return Mesh(grid, (SERIES_AXIS, TIME_AXIS))


def series_sharding(mesh: Mesh) -> NamedSharding:
    """Series-major panel layout: ``(n_series, n_obs)`` split over the series
    axis (and the time axis if the mesh has one)."""
    return NamedSharding(mesh, P(SERIES_AXIS, TIME_AXIS))


def instant_sharding(mesh: Mesh) -> NamedSharding:
    """Time-major layout: ``(n_obs, n_series)`` split over the time axis."""
    return NamedSharding(mesh, P(TIME_AXIS, SERIES_AXIS))


def shard_panel_values(values: jnp.ndarray, mesh: Mesh) -> jnp.ndarray:
    """Place ``(n_series, n_obs)`` values with series-major sharding."""
    return jax.device_put(values, series_sharding(mesh))


@functools.lru_cache(maxsize=None)
def _to_instants_jit(mesh: Mesh):
    return jax.jit(
        lambda v: jax.lax.with_sharding_constraint(
            v.T, instant_sharding(mesh)),
        in_shardings=series_sharding(mesh),
        out_shardings=instant_sharding(mesh))


def to_instants(values: jnp.ndarray, mesh: Mesh) -> jnp.ndarray:
    """Series-major → time-major relayout — the ``toInstants`` equivalent
    (ref ``TimeSeriesRDD.scala:276-391``).

    The reference implements this as its only all-to-all shuffle (map-side
    chunking, range partitioner, secondary sort).  Here it is a transpose
    with a sharding constraint; XLA lowers the resharding to an
    ``all_to_all`` that rides ICI.  The jitted relayout is cached per mesh.
    """
    return _to_instants_jit(mesh)(values)


@functools.lru_cache(maxsize=None)
def _instant_mask_any_jit(mesh: Mesh):
    return jax.jit(lambda m: jnp.any(m, axis=0),
                   in_shardings=series_sharding(mesh))


def instant_mask_any(mask: jnp.ndarray, mesh: Mesh) -> jnp.ndarray:
    """Per-instant OR-reduction over the sharded series axis — the
    ``aggregate``/mask-reduce equivalent (ref ``TimeSeriesRDD.scala:158-210``);
    XLA inserts the cross-shard reduction (``psum``).  Cached per mesh."""
    return _instant_mask_any_jit(mesh)(mask)


def collect(values: jnp.ndarray) -> np.ndarray:
    """Materialize a (possibly sharded, possibly multi-host) array on the
    host — the driver-``collect`` equivalent
    (ref ``TimeSeriesRDD.scala:61-75``)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        values = multihost_utils.process_allgather(values, tiled=True)
    return np.asarray(values)


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> Tuple[int, int]:
    """Join a multi-host mesh via ``jax.distributed`` (the analogue of the
    reference's Spark cluster manager; collectives then ride ICI within a
    slice and DCN across slices).  No-ops on a single process with no
    coordinator configured.  Returns (process_id, process_count)."""
    if coordinator_address is not None:
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)
    return jax.process_index(), jax.process_count()
