"""Batched statistical tests (L6).

Capability parity with the reference's ``TimeSeriesStatisticalTests``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/stats/TimeSeriesStatisticalTests.scala:33-431``):
ADF (with the MacKinnon 1994 approximate p-value surface), KPSS (Newey-West
long-run variance, R tseries semantics), Durbin-Watson, Breusch-Godfrey,
Ljung-Box, and Breusch-Pagan.

Every test accepts ``(..., n)`` inputs and returns batched statistics — the
whole panel is tested in one XLA program (the reference runs one
Commons-Math OLS per series).  The MacKinnon tau tables and KPSS critical
values are the published constants (MacKinnon 1994; Kwiatkowski et al. 1992),
the same sources the reference credits (statsmodels / R tseries).
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np
from jax.scipy.stats import chi2, norm

from .ops.lag import lag_matrix, lag_stack
from .ops.linalg import ols, r_squared, t_statistics

# ---------------------------------------------------------------------------
# MacKinnon 1994 approximate asymptotic p-value surface for unit-root tests.
# Published constants ("Approximate Asymptotic Distribution Functions for
# Unit-Root and Cointegration Tests", JBES 12.2), as tabulated in statsmodels
# adfvalues.py and the reference (``TimeSeriesStatisticalTests.scala:33-127``).
# Row index = n-1 (number of I(1) series); ADF uses row 0.
# ---------------------------------------------------------------------------

_ADF_REGRESSIONS = ("nc", "c", "ct", "ctt")

_ADF_TAU_STAR = {
    "nc": [-1.04, -1.53, -2.68, -3.09, -3.07, -3.77],
    "c": [-1.61, -2.62, -3.13, -3.47, -3.78, -3.93],
    "ct": [-2.89, -3.19, -3.50, -3.65, -3.80, -4.36],
    "ctt": [-3.21, -3.51, -3.81, -3.83, -4.12, -4.63],
}
_ADF_TAU_MIN = {
    "nc": [-19.04, -19.62, -21.21, -23.25, -21.63, -25.74],
    "c": [-18.83, -18.86, -23.48, -28.07, -25.96, -23.27],
    "ct": [-16.18, -21.15, -25.37, -26.63, -26.53, -26.18],
    "ctt": [-17.17, -21.1, -24.33, -24.03, -24.33, -28.22],
}
_ADF_TAU_MAX = {
    "nc": [np.inf, 1.51, 0.86, 0.88, 1.05, 1.24],
    "c": [2.74, 0.92, 0.55, 0.61, 0.79, 1.0],
    "ct": [0.7, 0.63, 0.71, 0.93, 1.19, 1.42],
    "ctt": [0.54, 0.79, 1.08, 1.43, 3.49, 1.92],
}
# small-p polynomials: ascending coefficients [b0, b1, b2]
_ADF_TAU_SMALLP = {
    "nc": [[0.6344, 1.2378, 3.2496e-2], [1.9129, 1.3857, 3.5322e-2],
           [2.7648, 1.4502, 3.4186e-2], [3.4336, 1.4835, 3.19e-2],
           [4.0999, 1.5533, 3.59e-2], [4.5388, 1.5344, 2.9807e-2]],
    "c": [[2.1659, 1.4412, 3.8269e-2], [2.92, 1.5012, 3.9796e-2],
          [3.4699, 1.4856, 3.164e-2], [3.9673, 1.4777, 2.6315e-2],
          [4.5509, 1.5338, 2.9545e-2], [5.1399, 1.6036, 3.4445e-2]],
    "ct": [[3.2512, 1.6047, 4.9588e-2], [3.6646, 1.5419, 3.6448e-2],
           [4.0983, 1.5173, 2.9898e-2], [4.5844, 1.5338, 2.8796e-2],
           [5.0722, 1.5634, 2.9472e-2], [5.53, 1.5914, 3.0392e-2]],
    "ctt": [[4.0003, 1.658, 4.8288e-2], [4.3534, 1.6016, 3.7947e-2],
            [4.7343, 1.5768, 3.2396e-2], [5.214, 1.6077, 3.3449e-2],
            [5.6481, 1.6274, 3.3455e-2], [5.9296, 1.5929, 2.8223e-2]],
}
# large-p polynomials: ascending [b0, b1*1e-1, b2*1e-1, b3*1e-2]
_ADF_LARGE_SCALING = np.array([1.0, 1e-1, 1e-1, 1e-2])
_ADF_TAU_LARGEP = {
    "nc": [[0.4797, 9.3557, -0.6999, 3.3066], [1.5578, 8.558, -2.083, -3.3549],
           [2.2268, 6.8093, -3.2362, -5.4448], [2.7654, 6.4502, -3.0811, -4.4946],
           [3.2684, 6.8051, -2.6778, -3.4972], [3.7268, 7.167, -2.3648, -2.8288]],
    "c": [[1.7339, 9.3202, -1.2745, -1.0368], [2.1945, 6.4695, -2.9198, -4.2377],
          [2.5893, 4.5168, -3.6529, -5.0074], [3.0387, 4.5452, -3.3666, -4.1921],
          [3.5049, 5.2098, -2.9158, -3.3468], [3.9489, 5.8933, -2.5359, -2.721]],
    "ct": [[2.5261, 6.1654, -3.7956, -6.0285], [2.85, 5.272, -3.6622, -5.1695],
           [3.221, 5.255, -3.2685, -4.1501], [3.652, 5.9758, -2.7483, -3.2081],
           [4.0712, 6.6428, -2.3464, -2.546], [4.4735, 7.1757, -2.0681, -2.1196]],
    "ctt": [[3.0778, 4.9529, -4.1477, -5.9359], [3.4713, 5.967, -3.2507, -4.2286],
            [3.8637, 6.7852, -2.6286, -3.1381], [4.2736, 7.6199, -2.1534, -2.4026],
            [4.6679, 8.2618, -1.822, -1.9147], [5.0009, 8.3735, -1.6994, -1.6928]],
}

# KPSS critical-value tables (Kwiatkowski, Phillips, Schmidt & Shin 1992,
# Journal of Econometrics; ref ``TimeSeriesStatisticalTests.scala:331-351``).
KPSS_CONSTANT_CRITICAL_VALUES: Dict[float, float] = {
    0.10: 0.347, 0.05: 0.463, 0.025: 0.574, 0.01: 0.739}
KPSS_CONSTANT_AND_TREND_CRITICAL_VALUES: Dict[float, float] = {
    0.10: 0.119, 0.05: 0.146, 0.025: 0.176, 0.01: 0.216}


def _polyval_ascending(coefs: np.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    out = jnp.zeros_like(x)
    for c in coefs[::-1]:
        out = out * x + c
    return out


def mackinnonp(test_stat: jnp.ndarray, regression: str = "c",
               n: int = 1) -> jnp.ndarray:
    """MacKinnon 1994 approximate p-value, batched over ``test_stat``
    (ref ``TimeSeriesStatisticalTests.scala:129-159``)."""
    i = n - 1
    stat = jnp.asarray(test_stat)
    small = _polyval_ascending(np.array(_ADF_TAU_SMALLP[regression][i]), stat)
    large = _polyval_ascending(
        np.array(_ADF_TAU_LARGEP[regression][i]) * _ADF_LARGE_SCALING, stat)
    poly = jnp.where(stat <= _ADF_TAU_STAR[regression][i], small, large)
    p = norm.cdf(poly)
    p = jnp.where(stat > _ADF_TAU_MAX[regression][i], 1.0, p)
    return jnp.where(stat < _ADF_TAU_MIN[regression][i], 0.0, p)


@functools.lru_cache(maxsize=64)
def _trend_columns(n_obs: int, regression: str, dtype) -> jnp.ndarray:
    """Deterministic trend regressors [1, t, t^2][:order+1], t = 1..n
    (ref ``addTrend``/``vanderflipped`` ``TimeSeriesStatisticalTests.scala:161-196``).
    Cached per (length, regression, dtype) — repeated KPSS/ADF sweeps reuse
    the same design."""
    order = {"nc": -1, "c": 0, "ct": 1, "ctt": 2}[regression]
    t = np.arange(1, n_obs + 1, dtype=np.float64)
    cols = [t ** k for k in range(order + 1)]
    if not cols:
        return jnp.zeros((n_obs, 0), dtype)
    return jnp.asarray(np.stack(cols, axis=1), dtype)


def adftest(ts: jnp.ndarray, max_lag: int,
            regression: str = "c") -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Augmented Dickey-Fuller unit-root test, batched
    (ref ``TimeSeriesStatisticalTests.scala:209-242``).

    Regresses ``Δy_t`` on ``[y_{t-1}, Δy_{t-1}, ..., Δy_{t-maxLag}, trend]``
    with no intercept beyond the trend columns; the statistic is the t-stat
    of the ``y_{t-1}`` coefficient, p-value from :func:`mackinnonp`.
    Returns ``(stat, p_value)`` with shape ``ts.shape[:-1]``.
    """
    if regression not in _ADF_REGRESSIONS:
        raise ValueError(f"regression must be one of {_ADF_REGRESSIONS}")
    ts = jnp.asarray(ts)
    n = ts.shape[-1]
    diff = ts[..., 1:] - ts[..., :-1]               # (..., n-1)
    lm = lag_matrix(diff, max_lag, include_original=True)
    n_obs = n - 1 - max_lag
    # column 0 (the lag-0 diff) is replaced by the lagged *level* y_{t-1}
    levels = ts[..., n - n_obs - 1:n - 1]
    X = jnp.concatenate([levels[..., None], lm[..., 1:]], axis=-1)
    trend = _trend_columns(n_obs, regression, ts.dtype)
    trend = jnp.broadcast_to(trend, (*X.shape[:-1], trend.shape[-1]))
    X = jnp.concatenate([X, trend], axis=-1)
    y = diff[..., -n_obs:]
    res = ols(X, y, add_intercept=False)
    stat = t_statistics(res)[..., 0]
    return stat, mackinnonp(stat, regression, 1)


def dwtest(residuals: jnp.ndarray) -> jnp.ndarray:
    """Durbin-Watson serial-correlation statistic, batched
    (ref ``TimeSeriesStatisticalTests.scala:251-262``)."""
    r = jnp.asarray(residuals)
    diffs = r[..., 1:] - r[..., :-1]
    return jnp.sum(diffs * diffs, axis=-1) / jnp.sum(r * r, axis=-1)


def bgtest(residuals: jnp.ndarray, factors: jnp.ndarray,
           max_lag: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Breusch-Godfrey serial-correlation test, batched
    (ref ``TimeSeriesStatisticalTests.scala:276-288``).

    Auxiliary regression (with intercept) of residuals on
    ``[factors ‖ lagged residuals]``; statistic ``nObs * R²`` ~ χ²(maxLag).
    ``residuals (..., n)``, ``factors (..., n, k)``.
    """
    u = jnp.asarray(residuals)
    X = jnp.asarray(factors)
    lag_u = lag_matrix(u, max_lag)                  # (..., n - maxLag, maxLag)
    n_obs = u.shape[-1] - max_lag
    aux_X = jnp.concatenate([X[..., max_lag:, :], lag_u], axis=-1)
    aux_y = u[..., max_lag:]
    res = ols(aux_X, aux_y, add_intercept=True)
    stat = n_obs * r_squared(res, aux_y)
    return stat, 1.0 - chi2.cdf(stat, max_lag)


def lbtest(residuals: jnp.ndarray,
           max_lag: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Ljung-Box test on residual autocorrelations, batched
    (ref ``TimeSeriesStatisticalTests.scala:298-307``)."""
    from .ops.univariate import autocorr
    r = jnp.asarray(residuals)
    n = r.shape[-1]
    ac = autocorr(r, max_lag)                       # (..., maxLag)
    divisors = jnp.asarray(
        [n - k - 1 for k in range(max_lag)], dtype=r.dtype)
    stat = n * (n + 2) * jnp.sum(ac * ac / divisors, axis=-1)
    return stat, 1.0 - chi2.cdf(stat, max_lag)


def bptest(residuals: jnp.ndarray,
           factors: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Breusch-Pagan heteroskedasticity test, batched
    (ref ``TimeSeriesStatisticalTests.scala:320-329``).

    Auxiliary regression (with intercept) of squared residuals on the
    original factors; statistic ``n * R²`` ~ χ²(k).
    """
    u = jnp.asarray(residuals)
    X = jnp.asarray(factors)
    u2 = u * u
    res = ols(X, u2, add_intercept=True)
    stat = u.shape[-1] * r_squared(res, u2)
    df = X.shape[-1]
    return stat, 1.0 - chi2.cdf(stat, df)


def _newey_west_variance(errors: jnp.ndarray, lag: int,
                         n_eff=None) -> jnp.ndarray:
    """Newey-West long-run variance with Bartlett weights, batched
    (ref ``TimeSeriesStatisticalTests.scala:405-431``, itself following R
    tseries' ppsum.c).

    All ``lag`` autocovariances come from ONE stacked contraction (an MXU
    matmul over the panel) instead of a per-lag reduction loop — KPSS runs
    ``max_d + 1`` times over the whole panel inside ``auto_fit_panel``, so
    this is on the batch hot path.

    ``n_eff (...)`` replaces the denominator for ragged lanes whose
    errors are zero beyond their valid window (zeros contribute nothing
    to the sums, so only the normalization changes)."""
    e = jnp.asarray(errors)
    n = e.shape[-1] if n_eff is None else n_eff
    var0 = jnp.sum(e * e, axis=-1) / n
    if lag == 0:
        return var0
    # left-pad so every lag-i row aligns with e over the full [0, n) range:
    # row i of the stack is [0]*i ++ e[:n-i], and row_i · e = Σ_t e[t-i]e[t]
    ep = jnp.concatenate(
        [jnp.zeros((*e.shape[:-1], lag), e.dtype), e], axis=-1)
    stk = lag_stack(ep, lag)                       # (..., lag, n)
    covs = jnp.einsum("...ln,...n->...l", stk, e)
    w = 1.0 - jnp.arange(1, lag + 1, dtype=e.dtype) / (lag + 1.0)
    return 2.0 * jnp.sum(covs * w, axis=-1) / n + var0


def kpsstest(ts: jnp.ndarray, method: str = "c", n_valid=None
             ) -> Tuple[jnp.ndarray, Dict[float, float]]:
    """KPSS stationarity test, batched
    (ref ``TimeSeriesStatisticalTests.scala:369-394``; R tseries semantics,
    including the default Newey-West lag ``int(3·sqrt(n)/13)``).

    Returns ``(stat, critical_values)`` where ``stat`` has shape
    ``ts.shape[:-1]`` and the critical values are the KPSS table for the
    chosen method.

    ``n_valid (...)`` restricts each lane to its left-aligned valid
    window (``ops.ragged``; ``"c"`` only): the demeaning, partial sums,
    long-run variance, and ``n²`` normalization all see the per-lane
    window length.  One documented deviation: the Newey-West lag stays
    the panel-level ``int(3·sqrt(n)/13)`` (a per-lane lag would be a
    data-dependent shape) — for d-selection this only matters when
    windows differ from the panel width by orders of magnitude.
    """
    if method not in ("c", "ct"):
        raise ValueError("method must be 'c' or 'ct'")
    ts = jnp.asarray(ts)
    n = ts.shape[-1]
    if n_valid is not None:
        if method != "c":
            raise ValueError("n_valid supports method 'c' only")
        nv = jnp.asarray(n_valid).astype(ts.dtype)
        w = ((jnp.arange(n) < nv[..., None])).astype(ts.dtype)
        mean = jnp.sum(ts * w, axis=-1, keepdims=True) \
            / jnp.maximum(nv[..., None], 1.0)
        resid = (ts - mean) * w
        s2 = jnp.sum(jnp.cumsum(resid, axis=-1) ** 2 * w, axis=-1)
        lag = int(3 * np.sqrt(n) / 13)
        long_run_var = _newey_west_variance(resid, lag,
                                            n_eff=jnp.maximum(nv, 1.0))
        stat = (s2 / long_run_var) / jnp.maximum(nv * nv, 1.0)
        return stat, KPSS_CONSTANT_CRITICAL_VALUES
    if method == "c":
        resid = ts - jnp.mean(ts, axis=-1, keepdims=True)
        critical_values = KPSS_CONSTANT_CRITICAL_VALUES
    else:
        X = _trend_columns(n, "ct", ts.dtype)
        X = jnp.broadcast_to(X, (*ts.shape[:-1], *X.shape))
        resid = ols(X, ts, add_intercept=False).residuals
        critical_values = KPSS_CONSTANT_AND_TREND_CRITICAL_VALUES
    s2 = jnp.sum(jnp.cumsum(resid, axis=-1) ** 2, axis=-1)
    lag = int(3 * np.sqrt(n) / 13)
    long_run_var = _newey_west_variance(resid, lag)
    stat = (s2 / long_run_var) / (n * n)
    return stat, critical_values


# ---------------------------------------------------------------------------
# DARIMA segmentation heuristics (the longseries tier; PAPERS.md
# "Distributed ARIMA Models for Ultra-long Time Series")
# ---------------------------------------------------------------------------

class SegmentPlan(NamedTuple):
    """One ultra-long series' split geometry (``longseries.split``).

    ``n_segments`` contiguous windows of ``window`` observations each
    (``window = seg_len + overlap``: every window extends ``overlap``
    observations left of its own ``seg_len`` stride for burn-in context);
    windows tile the **tail** of the series, so ``head_drop`` leading
    observations are excluded from estimation — the most recent data
    always participates, mirroring ``arima.fit_long``.  ``n_used`` counts
    the distinct observations covered (``n_segments·seg_len + overlap``).
    """
    n_segments: int
    seg_len: int
    overlap: int
    window: int
    n_used: int
    head_drop: int


def segment_plan(n_obs: int, p: int = 2, q: int = 2, *,
                 seg_len: int | None = None, overlap: int = 0,
                 min_seg_len: int | None = None,
                 max_segments: int = 4096) -> SegmentPlan:
    """Choose the DARIMA split geometry for an ``n_obs``-long series.

    The divide-and-conquer tradeoff (the paper's tuning discussion): each
    segment's CSS estimate carries O(1/seg_len) conditioning bias from
    its zero-initialized MA ring, while the combined estimator's variance
    shrinks with the segment count — balancing the two puts ``seg_len``
    near ``sqrt(n)`` up to a constant.  The default takes the power of
    two nearest ``8·sqrt(n)`` (powers of two keep every segment panel on
    one engine bucket), clamped to

    - at least ``min_seg_len`` (default: four Hannan-Rissanen floors for
      the order, ``4·(2·max(p,q) + 2 + p + q + 1)``, and never < 64), so
      each segment supports a reliable fit, and
    - at most ``n_obs // 2`` (two segments minimum — fewer means the
      split buys nothing; callers should use ``arima.fit`` directly).

    ``max_segments`` caps the panel height (more segments then simply
    get a longer ``seg_len``).  Raises when ``n_obs`` cannot hold two
    minimum-length segments.
    """
    n_obs = int(n_obs)
    overlap = max(0, int(overlap))
    mx = max(int(p), int(q))
    hr_floor = 2 * mx + 2 + int(p) + int(q) + 1
    floor = max(64, 4 * hr_floor, overlap + 1) if min_seg_len is None \
        else max(int(min_seg_len), overlap + 1)
    if n_obs < 2 * floor + overlap:
        raise ValueError(
            f"series too short to segment: {n_obs} obs cannot hold two "
            f"segments of >= {floor} (overlap={overlap}); call "
            f"arima.fit directly")
    if seg_len is None:
        target = 8.0 * float(np.sqrt(n_obs))
        seg_len = 1 << max(0, int(round(np.log2(max(target, 1.0)))))
        seg_len = max(floor, min(seg_len, n_obs // 2))
        # respect the panel-height cap: grow seg_len until it fits
        while (n_obs - overlap) // seg_len > int(max_segments):
            seg_len *= 2
    else:
        seg_len = int(seg_len)
        if seg_len < floor:
            raise ValueError(
                f"seg_len={seg_len} is below the reliability floor "
                f"{floor} for order (p={p}, q={q}, overlap={overlap}); "
                f"raise seg_len or pass min_seg_len explicitly")
    n_segments = (n_obs - overlap) // seg_len
    if n_segments < 2:
        raise ValueError(
            f"seg_len={seg_len} leaves {n_segments} segment(s) of "
            f"{n_obs} obs (overlap={overlap}); shrink seg_len or call "
            f"arima.fit directly")
    n_used = n_segments * seg_len + overlap
    return SegmentPlan(n_segments=int(n_segments), seg_len=int(seg_len),
                       overlap=overlap, window=int(seg_len + overlap),
                       n_used=int(n_used),
                       head_drop=int(n_obs - n_used))
