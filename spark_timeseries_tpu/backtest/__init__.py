"""Backtest tier: vectorized rolling-origin evaluation and per-series
champion selection (ROADMAP item 5).

Nothing else in the stack answers "which model family and order is best
for each of my million series" with *out-of-sample* evidence —
``auto_fit_panel`` ranks by in-sample AIC only.  This subsystem
evaluates a (family × order × horizon × origin) grid as bucketed
batches instead of per-(series, origin) refits:

- :mod:`grid` — candidate grids, rolling-origin schedules (expanding /
  sliding fit windows, min-train floors), per-family adapters;
- :mod:`evaluate` — fit-once / replay-every-origin scoring: pinned-gain
  ``affine_recurrence`` state paths in O(log n) depth, one gathered row
  per origin, in-graph NaN-masked sMAPE / MASE / RMSE / interval
  coverage (with a sequential-refilter oracle path for tests);
- :mod:`api` — ``backtest_panel`` streaming the grid through
  ``engine.stream_fit`` (journal-backed crash-consistent sweeps,
  per-candidate telemetry labels) into a :class:`~api.BacktestReport`
  of per-series champions, per-horizon error tables, and per-origin
  error bars.
"""

from . import api, evaluate, grid  # noqa: F401
from .api import BacktestReport, backtest_panel  # noqa: F401
from .evaluate import CandidateEval, evaluate_candidate  # noqa: F401
from .grid import (Candidate, CandidateGrid, OriginSchedule,  # noqa: F401
                   default_grid, plan_origins)

__all__ = ["backtest_panel", "BacktestReport", "evaluate_candidate",
           "CandidateEval", "Candidate", "CandidateGrid",
           "OriginSchedule", "plan_origins", "default_grid",
           "grid", "evaluate", "api"]
