"""Rolling-origin evaluation: fit once, replay every origin, score in-graph.

The naive backtest refits one model per (candidate, series, origin) —
O(origins) full optimizations.  This module replaces the refits with a
*filter replay* (docs/design.md §9):

1. parameters are estimated ONCE per (candidate, series) on the
   schedule's fit window (``engine.stream_fit`` upstream);
2. the fitted model converts to state-space form
   (``statespace.to_statespace``) and the sequential Kalman filter runs
   over the training prefix — converging the predicted covariance and
   calibrating σ² from the innovations;
3. the converged gain is pinned (``statespace.kalman.steady_gain``; the
   exact filter's gain sequence is data-independent and Riccati-converges
   geometrically), which turns the remaining state recursion into an
   affine map — ``statespace.kalman.pinned_state_path`` evaluates every
   predicted state over the evaluation region in O(log n) depth, and
   each origin's forecast basis is ONE GATHERED ROW of that path;
4. h-step forecast means propagate from all origins at once
   (``x ← Tx + c``, read ``d + Zx``, integrate through the per-origin
   raw-difference ring), and the error metrics — sMAPE, MASE (scaled by
   the in-sample naive MAE), RMSE, empirical interval coverage — are
   computed in one jitted, NaN-masked kernel, so ragged/missing lanes
   score only real observations.

``replay="refilter"`` swaps step 3 for the oracle: a full sequential
filter from scratch per origin — O(origins · n) — kept for tests, which
pin the pinned-gain path against it to 1e-9 on dense f64 lanes.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.base import normal_quantile
from ..ops.univariate import differences_of_order_d
from ..statespace.convert import to_statespace
from ..statespace.kalman import (filter_panel, pinned_state_path,
                                 steady_gain)
from ..statespace.ssm import SSMeta, initial_state
from ..utils import metrics as _metrics

__all__ = ["CandidateEval", "evaluate_candidate", "masked_pointwise"]

# families the replay supports: every family whose state-space form has
# no per-tick exogenous offsets (ARX/ARIMAX offsets would need a future-
# regressor contract) and whose initial state needs no model internals
# (Holt-Winters seeds from _init_components — a batch refit concern, not
# a replay one)
REPLAY_FAMILIES = ("arima", "ar", "ewma")


class CandidateEval(NamedTuple):
    """One candidate's rolling-origin scorecard over a panel.

    Tables are per-series per-horizon (``(S, H)``, horizons 1..H) masked
    means over origins; ``score_*`` collapse origins AND the schedule's
    listed horizons; ``origin_*`` are per-origin means over the listed
    horizons (the dispersion behind the report's error bars).  All NaN
    where no finite (forecast, actual) pair exists; ``forecasts`` are
    raw-scale point forecasts (``(S, O, H)``) and ``half`` the
    symmetric coverage-interval half-widths (``(S, H)``)."""
    forecasts: np.ndarray
    half: np.ndarray
    smape: np.ndarray
    mase: np.ndarray
    rmse: np.ndarray
    coverage: np.ndarray
    score_smape: np.ndarray
    score_mase: np.ndarray
    score_rmse: np.ndarray
    origin_smape: np.ndarray
    origin_mase: np.ndarray
    sigma2: np.ndarray


# ---------------------------------------------------------------------------
# traced kernels (module-level jits — STS006: one function object per
# program so every candidate/backtest call shares the cache)
# ---------------------------------------------------------------------------

def _train_state_fn(ssm, state, ys, meta):
    return filter_panel(ssm, state, ys, meta).state


_train_state = jax.jit(_train_state_fn, static_argnums=(3,))


def _propagate(ssm, states, rings, d: int, horizon: int):
    """h-step forecast means from a batch of origins at once.

    ``states (S, O, m)`` one-step-predicted origin states, ``rings
    (S, O, d)`` the last raw differences before each origin
    (``rings[..., j] = Δʲ y_{t-1}``).  Mean propagation with zero future
    innovations (``z = d + Z x``, ``x ← T(x) + c``), each step
    integrated back to the raw scale through the ring — the vectorized-
    over-origins twin of :func:`statespace.kalman.forecast_mean`.
    Returns ``(S, O, horizon)`` raw-scale forecasts."""
    def step(carry, _):
        x, lasts = carry
        z = ssm.d[:, None] + jnp.einsum("sm,som->so", ssm.Z, x)
        if d:
            cur = z
            vals = []
            for j in range(d - 1, -1, -1):
                cur = cur + lasts[..., j]
                vals.append(cur)
            y_out = cur
            lasts = jnp.stack(vals[::-1], axis=-1)
        else:
            y_out = z
        x = jnp.einsum("smk,sok->som", ssm.T, x) + ssm.c[:, None, :]
        return (x, lasts), y_out

    _, ys = lax.scan(step, (states, rings), None, length=horizon)
    return jnp.moveaxis(ys, 0, -1)                           # (S, O, H)


def _replay_fn(ssm, state, ys_eval, oidx, rings, meta, d, horizon):
    """Pinned-gain origin replay: states over the eval region in
    O(log n) depth, one gathered row per origin, forecasts propagated
    from all origins at once."""
    if meta.mode == "exact":
        K, _ = steady_gain(ssm, state.P)
    else:
        K = ssm.gain
    path = pinned_state_path(ssm, state.a, ys_eval, K)   # (n_eval+1, S, m)
    states = jnp.moveaxis(path[oidx], 0, 1)              # (S, O, m)
    return _propagate(ssm, states, rings, d, horizon)


_replay = jax.jit(_replay_fn, static_argnums=(5, 6, 7))


def _propagate_only_fn(ssm, states, rings, d, horizon):
    return _propagate(ssm, states, rings, d, horizon)


_propagate_jit = jax.jit(_propagate_only_fn, static_argnums=(3, 4))


def _half_widths_fn(ssm, sigma2, meta, d, horizon, conf):
    """Symmetric forecast-band half-widths for horizons 1..H, per lane.

    ψ-weight construction on the filter scale — exact mode reads the
    noise loading off the unit-scale ``Q``'s first column (the Harvey
    companion form has ``Q = RRᵀ`` with ``R₀ = 1``, so ``Q[:, 0] = R``
    and ``ψ_k = Z Tᵏ R``); innovations mode is the single-source-of-
    error expansion ``ψ₀ = 1, ψ_k = Z T^{k-1} gain`` (for SES this
    reproduces ``var_h = σ²(1 + (h-1)α²)`` exactly).  ``d`` integrations
    are ``d`` cumulative sums of the ψ sequence (the classical
    nonstationary widening — same construction as
    ``models.arima._psi_half_widths``), then
    ``var_h = σ̂² Σ_{j<h} ψ̃_j²`` with σ̂² calibrated from the training
    innovations."""
    dtype = sigma2.dtype
    psis = []
    if meta.mode == "exact":
        x = ssm.Q[:, :, 0]
        for _ in range(horizon):
            psis.append(jnp.einsum("sm,sm->s", ssm.Z, x))
            x = jnp.einsum("smk,sk->sm", ssm.T, x)
    else:
        x = ssm.gain
        psis.append(jnp.ones_like(sigma2))
        for _ in range(horizon - 1):
            psis.append(jnp.einsum("sm,sm->s", ssm.Z, x))
            x = jnp.einsum("smk,sk->sm", ssm.T, x)
    psi = jnp.stack(psis, axis=-1)                           # (S, H)
    for _ in range(d):
        psi = jnp.cumsum(psi, axis=-1)
    var = sigma2[:, None] * jnp.cumsum(psi * psi, axis=-1)
    return normal_quantile(conf, dtype) * jnp.sqrt(var)


_half_widths = jax.jit(_half_widths_fn, static_argnums=(2, 3, 4, 5))


def _masked_mean(pt, mask, axis):
    cnt = jnp.sum(mask, axis=axis)
    s = jnp.sum(jnp.where(mask, pt, 0.0), axis=axis)
    return jnp.where(cnt > 0, s / jnp.maximum(cnt, 1), jnp.nan)


def masked_pointwise(fcst, actual):
    """The NaN-masked pointwise error primitives every quality consumer
    shares — the backtest metric tables here and the serving tier's
    fused online-accuracy step (``statespace.quality.quality_step``),
    so the two surfaces can never disagree on a definition.

    A point contributes only when both forecast and actual are finite;
    sMAPE's 0/0 (both sides zero — a perfect forecast of a zero)
    contributes 0.  Returns ``(mask, abserr, smape_pt)`` with masked-out
    points zeroed, at any broadcastable shape."""
    mask = jnp.isfinite(actual) & jnp.isfinite(fcst)
    a = jnp.where(mask, actual, 0.0)
    f = jnp.where(mask, fcst, 0.0)
    abserr = jnp.abs(f - a)
    denom = jnp.abs(f) + jnp.abs(a)
    smape_pt = jnp.where(denom > 0,
                         200.0 * abserr / jnp.where(denom > 0, denom, 1.0),
                         jnp.zeros_like(abserr))
    return mask, abserr, smape_pt


def _metric_tables_fn(fcst, actual, half, scale, hs):
    """All four metric families in one NaN-masked pass.

    ``fcst``/``actual (S, O, H)``, ``half (S, H)``, ``scale (S,)`` the
    in-sample naive MAE (MASE denominator), ``hs`` the static 1-based
    horizons the scores average.  Pointwise definitions live in
    :func:`masked_pointwise` (shared with the serving quality plane)."""
    mask, abserr, smape_pt = masked_pointwise(fcst, actual)
    ok_scale = jnp.isfinite(scale) & (scale > 0)
    mase_pt = abserr / jnp.where(ok_scale, scale, 1.0)[:, None, None]
    mase_mask = mask & ok_scale[:, None, None]
    sq_pt = abserr * abserr
    cover_pt = (abserr <= half[:, None, :]).astype(abserr.dtype)

    smape_tab = _masked_mean(smape_pt, mask, 1)              # (S, H)
    mase_tab = _masked_mean(mase_pt, mase_mask, 1)
    rmse_tab = jnp.sqrt(_masked_mean(sq_pt, mask, 1))
    cover_tab = _masked_mean(cover_pt, mask, 1)

    idx = jnp.asarray([h - 1 for h in hs])
    sm_h = smape_pt[..., idx]
    ms_h = mase_pt[..., idx]
    sq_h = sq_pt[..., idx]
    m_h = mask[..., idx]
    mm_h = mase_mask[..., idx]
    score_smape = _masked_mean(sm_h, m_h, (1, 2))            # (S,)
    score_mase = _masked_mean(ms_h, mm_h, (1, 2))
    score_rmse = jnp.sqrt(_masked_mean(sq_h, m_h, (1, 2)))
    origin_smape = _masked_mean(sm_h, m_h, 2)                # (S, O)
    origin_mase = _masked_mean(ms_h, mm_h, 2)
    return (smape_tab, mase_tab, rmse_tab, cover_tab, score_smape,
            score_mase, score_rmse, origin_smape, origin_mase)


_metric_tables = jax.jit(_metric_tables_fn, static_argnums=(4,))


def _naive_scale_fn(values, start, stop, m_period):
    """In-sample naive MAE over the fit window (the MASE denominator),
    NaN pairs masked.  ``m_period = 1`` is the classic lag-1 scaling;
    ``m_period = m`` scales by the *seasonal*-naive forecast
    ``|y_t - y_{t-m}|`` (Hyndman & Koehler's seasonal MASE), so seasonal
    panels aren't judged against a denominator their seasonality
    inflates."""
    w = values[:, start:stop]
    d1 = w[:, m_period:] - w[:, :-m_period]
    m = jnp.isfinite(d1)
    cnt = jnp.sum(m, axis=1)
    s = jnp.sum(jnp.where(m, jnp.abs(d1), 0.0), axis=1)
    return jnp.where(cnt > 0, s / jnp.maximum(cnt, 1), jnp.nan)


_naive_scale = jax.jit(_naive_scale_fn, static_argnums=(1, 2, 3))


# ---------------------------------------------------------------------------
# host driver
# ---------------------------------------------------------------------------

def _seeded_initial(ssm, meta0, family: str, diffed):
    """Initial filter state + the index the train filter starts at.

    Exact-mode families start from the stationary prior at t = 0 (the
    exact-likelihood convention).  EWMA mirrors its converter's
    bootstrap: ``S_0 = y_0`` exactly, filtering from t = 1 — without
    this the level would relax from 0 over ~1/α ticks and the σ²
    calibration would eat the transient."""
    state0 = initial_state(ssm, meta0)
    if family == "ewma":
        first = diffed[:, 0]
        a0 = jnp.where(jnp.isfinite(first), first, 0.0)[:, None]
        return state0._replace(a=a0), 1
    return state0, 0


def evaluate_candidate(values, model, schedule, horizons, *,
                       replay: str = "pinned",
                       coverage: float = 0.9,
                       mase_m: int = 1) -> CandidateEval:
    """Score one fitted candidate over a panel's rolling origins.

    ``values (S, n)`` the raw panel; ``model`` the candidate's batched
    fitted pytree (one lane per series; NaN-coefficient lanes forecast
    NaN and score NaN → +inf downstream); ``schedule`` an
    :class:`~spark_timeseries_tpu.backtest.grid.OriginSchedule`;
    ``horizons`` the 1-based steps the scores average.  ``replay``:
    ``"pinned"`` (the O(log n) production path) or ``"refilter"`` (the
    sequential per-origin oracle).  ``coverage`` sets the nominal level
    of the interval-coverage metric; ``mase_m`` the MASE scaling period
    (1 = lag-1 naive, the default; a seasonal period scales by the
    seasonal-naive in-sample MAE instead).
    """
    if replay not in ("pinned", "refilter"):
        raise ValueError(f"unknown replay mode {replay!r}; expected "
                         f"'pinned' or 'refilter'")
    mase_m = int(mase_m)
    if mase_m < 1:
        raise ValueError(f"mase_m must be a period >= 1, got {mase_m}")
    vals = jnp.asarray(values)
    if vals.ndim != 2:
        raise ValueError(f"evaluate_candidate needs an (n_series, n_obs) "
                         f"panel, got {vals.shape}")
    dtype = vals.dtype
    ssm, meta = to_statespace(model)
    if meta.family not in REPLAY_FAMILIES:
        raise ValueError(
            f"family {meta.family!r} is not replayable; supported: "
            f"{REPLAY_FAMILIES}")
    ssm = type(ssm)(*(jnp.asarray(leaf, dtype) for leaf in ssm))
    d = meta.d_order
    meta0 = SSMeta(meta.family, meta.mode, 0, meta.m)
    origins = np.asarray(schedule.origins, np.int64)
    t0, t_last = int(origins[0]), int(origins[-1])
    H = int(schedule.horizon)
    hs = tuple(sorted({int(h) for h in horizons}))
    if hs[0] < 1 or hs[-1] > H:
        raise ValueError(f"horizons {hs} outside 1..{H}")
    if t0 - d < 2:
        raise ValueError(f"first origin {t0} leaves no differenced "
                         f"training prefix (d={d})")

    diffed = differences_of_order_d(vals, d)[..., d:]        # (S, n-d)
    state0, skip = _seeded_initial(ssm, meta0, meta.family, diffed)

    with _metrics.span("backtest.replay"):
        # training prefix: converge the covariance, calibrate σ²
        train = diffed[:, skip:t0 - d]
        origin0 = _train_state(ssm, state0, train, meta0)
        n_tr = jnp.maximum(origin0.n_obs.astype(dtype), 1.0)
        sigma2 = origin0.ssq / n_tr
        sigma2 = jnp.where(jnp.isfinite(sigma2) & (sigma2 > 0),
                           sigma2, 1.0)

        # per-origin raw-difference rings: rings[..., j] = Δʲ y_{t-1}
        host = np.asarray(values)
        if d:
            rings_np = np.stack(
                [np.diff(host, n=j, axis=1)[:, origins - 1 - j]
                 for j in range(d)], axis=-1)
        else:
            rings_np = np.zeros((host.shape[0], origins.size, 0),
                                host.dtype)
        rings = jnp.asarray(rings_np, dtype)

        if replay == "pinned" and t_last == t0:
            # single origin: nothing to replay past the training prefix
            fcst = _propagate_jit(ssm, origin0.a[:, None, :], rings, d, H)
        elif replay == "pinned":
            ys_eval = diffed[:, t0 - d:t_last - d]
            oidx = jnp.asarray(origins - t0)
            fcst = _replay(ssm, origin0, ys_eval, oidx, rings, meta0, d, H)
        else:
            # oracle: one full sequential filter per origin
            states = [origin0.a]
            for t in origins[1:]:
                st = _train_state(ssm, state0, diffed[:, skip:int(t) - d],
                                  meta0)
                states.append(st.a)
            fcst = _propagate_jit(ssm, jnp.stack(states, axis=1), rings,
                                  d, H)

        half = _half_widths(ssm, sigma2, meta0, d, H, float(coverage))

    with _metrics.span("backtest.score"):
        idx = origins[:, None] + np.arange(H)[None, :]        # (O, H)
        actual = vals[:, jnp.asarray(idx)]                    # (S, O, H)
        fs, ft = schedule.fit_window()
        if ft - fs <= mase_m:
            raise ValueError(
                f"mase_m={mase_m} leaves no seasonal-naive pair in the "
                f"[{fs}, {ft}) fit window — shrink the period or widen "
                f"the window")
        scale = _naive_scale(vals, int(fs), int(ft), mase_m)
        tabs = _metric_tables(fcst, actual, half, scale, hs)

    (smape_tab, mase_tab, rmse_tab, cover_tab, score_smape, score_mase,
     score_rmse, origin_smape, origin_mase) = (np.asarray(t) for t in tabs)
    return CandidateEval(
        forecasts=np.asarray(fcst), half=np.asarray(half),
        smape=smape_tab, mase=mase_tab, rmse=rmse_tab,
        coverage=cover_tab, score_smape=score_smape,
        score_mase=score_mase, score_rmse=score_rmse,
        origin_smape=origin_smape, origin_mase=origin_mase,
        sigma2=np.asarray(sigma2))
