"""Candidate grids and rolling-origin schedules — the backtest planner.

A backtest sweep is a (family × order × horizon × origin) grid
(ROADMAP item 5; the embarrassingly-parallel structure of PAPERS.md,
arXiv 1511.06493 applied to *evaluation* instead of fitting).  This
module holds the static half of that plan:

- :class:`Candidate` / :class:`CandidateGrid` — which (family, order)
  pairs compete, and at which forecast horizons they are scored;
- :func:`plan_origins` / :class:`OriginSchedule` — where the forecast
  origins sit, how much history the one-shot parameter fit sees
  (expanding prefix or sliding window), and the min-train floor;
- :data:`FAMILIES` — the per-family adapters (stream-fit kwargs,
  chunk-row extraction, batched-model rebuild, parameter counts) that
  let ``evaluate``/``api`` treat every family uniformly.

Everything here is host-side bookkeeping: tiny, hashable, and
JSON-describable so the journal spec can content-hash the plan
(``describe()``) and refuse to resume a sweep whose geometry changed.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Sequence, Tuple

import numpy as np

__all__ = ["Candidate", "CandidateGrid", "OriginSchedule", "plan_origins",
           "FAMILIES", "FamilySpec", "default_grid"]


class Candidate(NamedTuple):
    """One competitor: a model family plus its (family-specific) order
    tuple — ``("arima", (p, d, q))``, ``("ar", (p,))``, ``("ewma", ())``."""
    family: str
    order: Tuple[int, ...]

    @property
    def label(self) -> str:
        inner = ",".join(str(v) for v in self.order)
        return f"{self.family}({inner})"

    @property
    def slug(self) -> str:
        """Filesystem-safe label (per-candidate journal subdirectories)."""
        inner = "-".join(str(v) for v in self.order)
        return f"{self.family}-{inner}" if self.order else self.family


class FamilySpec(NamedTuple):
    """Adapter making one model family grid-able.

    ``stream_kwargs(order)`` → the family statics ``engine.stream_fit``
    needs; ``row_width(order)`` → the flattened per-series coefficient
    width; ``rows_of(model)`` → ``(chunk_series, row_width)`` rows from
    one chunk's fitted pytree; ``rebuild(order, rows)`` → the batched
    model a full ``(n_series, row_width)`` row matrix describes (NaN
    rows = failed chunks; they forecast NaN and score +inf);
    ``n_params(order)`` → the parsimony key for champion tie-breaking;
    ``d_of(order)`` → the integration order the replay must difference
    out; ``min_train_floor(order)`` → the fewest training obs a fit of
    this order supports."""
    family: str
    order_len: int
    stream_kwargs: Callable[[Tuple[int, ...]], Dict[str, Any]]
    row_width: Callable[[Tuple[int, ...]], int]
    rows_of: Callable[[Any], np.ndarray]
    rebuild: Callable[[Tuple[int, ...], np.ndarray], Any]
    n_params: Callable[[Tuple[int, ...]], int]
    d_of: Callable[[Tuple[int, ...]], int]
    min_train_floor: Callable[[Tuple[int, ...]], int]


def _arima_rows(model) -> np.ndarray:
    return np.asarray(model.coefficients).reshape(
        -1, model.coefficients.shape[-1])


def _arima_rebuild(order, rows):
    import jax.numpy as jnp

    from ..models.arima import ARIMAModel
    p, d, q = order
    return ARIMAModel(p, d, q, jnp.asarray(rows), True)


def _ar_rows(model) -> np.ndarray:
    c = np.asarray(model.c).reshape(-1, 1)
    coefs = np.asarray(model.coefficients)
    return np.concatenate([c, coefs.reshape(c.shape[0], -1)], axis=1)


def _ar_rebuild(order, rows):
    import jax.numpy as jnp

    from ..models.autoregression import ARModel
    return ARModel(c=jnp.asarray(rows[:, 0]),
                   coefficients=jnp.asarray(rows[:, 1:]))


def _ewma_rows(model) -> np.ndarray:
    return np.asarray(model.smoothing).reshape(-1, 1)


def _ewma_rebuild(order, rows):
    import jax.numpy as jnp

    from ..models.ewma import EWMAModel
    return EWMAModel(smoothing=jnp.asarray(rows[:, 0]))


FAMILIES: Dict[str, FamilySpec] = {
    "arima": FamilySpec(
        family="arima", order_len=3,
        stream_kwargs=lambda o: {"p": o[0], "d": o[1], "q": o[2],
                                 "include_intercept": True},
        row_width=lambda o: 1 + o[0] + o[2],
        rows_of=_arima_rows,
        rebuild=_arima_rebuild,
        n_params=lambda o: 1 + o[0] + o[2],
        d_of=lambda o: o[1],
        # differencing burn-in + CSS residual window + a solve's worth
        # of rows per estimated parameter
        min_train_floor=lambda o: o[1] + 2 * max(o[0], o[2]) + 4 * (
            1 + o[0] + o[2])),
    "ar": FamilySpec(
        family="ar", order_len=1,
        stream_kwargs=lambda o: {"max_lag": o[0]},
        row_width=lambda o: 1 + o[0],
        rows_of=_ar_rows,
        rebuild=_ar_rebuild,
        n_params=lambda o: 1 + o[0],
        d_of=lambda o: 0,
        min_train_floor=lambda o: 4 * (1 + o[0]) + o[0]),
    "ewma": FamilySpec(
        family="ewma", order_len=0,
        stream_kwargs=lambda o: {},
        row_width=lambda o: 1,
        rows_of=_ewma_rows,
        rebuild=_ewma_rebuild,
        n_params=lambda o: 1,
        d_of=lambda o: 0,
        min_train_floor=lambda o: 8),
}


def _normalize_order(family: str, order) -> Tuple[int, ...]:
    spec = FAMILIES.get(family)
    if spec is None:
        raise ValueError(
            f"unknown backtest family {family!r}; supported: "
            f"{sorted(FAMILIES)} (families must have a state-space "
            f"form the origin replay can pin a gain for)")
    if order is None or order == ():
        tup: Tuple[int, ...] = ()
    elif isinstance(order, int):
        tup = (order,)
    else:
        tup = tuple(int(v) for v in order)
    if len(tup) != spec.order_len:
        raise ValueError(
            f"family {family!r} takes a length-{spec.order_len} order, "
            f"got {order!r}")
    if any(v < 0 for v in tup):
        raise ValueError(f"negative order terms in {family}{tup}")
    if family == "arima" and tup[0] == 0 and tup[2] == 0 and tup[1] == 0:
        raise ValueError("arima(0,0,0) has no dynamics to evaluate; "
                         "drop it from the grid")
    return tup


class CandidateGrid:
    """The competitors and scoring horizons of one backtest sweep.

    ``families`` maps family name → iterable of orders (``arima``:
    ``(p, d, q)`` triples; ``ar``: ``p`` ints or ``(p,)`` tuples;
    ``ewma``: a single empty order, spelled ``[()]`` or ``True``).
    ``horizons`` are the 1-based forecast steps candidates are scored
    at (tables cover every step up to ``max(horizons)``; the champion
    score averages the listed steps only).
    """

    def __init__(self, families: Dict[str, Any],
                 horizons: Sequence[int] = (1, 4, 8)):
        if not families:
            raise ValueError("CandidateGrid needs at least one family")
        cands = []
        for family, orders in families.items():
            if orders is True:
                orders = [()]
            if isinstance(orders, (int, tuple)):
                orders = [orders]
            orders = list(orders)
            if not orders:
                raise ValueError(f"family {family!r} lists no orders")
            for o in orders:
                cands.append(Candidate(family, _normalize_order(family, o)))
        if len(set(cands)) != len(cands):
            dupes = sorted({c.label for c in cands
                            if cands.count(c) > 1})
            raise ValueError(f"duplicate grid candidates: {dupes}")
        hs = tuple(sorted({int(h) for h in horizons}))
        if not hs or hs[0] < 1:
            raise ValueError(
                f"horizons must be >= 1 forecast steps, got {horizons!r}")
        self.candidates: Tuple[Candidate, ...] = tuple(cands)
        self.horizons: Tuple[int, ...] = hs

    @property
    def horizon(self) -> int:
        return self.horizons[-1]

    def __len__(self) -> int:
        return len(self.candidates)

    def __iter__(self):
        return iter(self.candidates)

    def min_train_floor(self) -> int:
        """The fewest training obs EVERY candidate's fit supports."""
        return max(FAMILIES[c.family].min_train_floor(c.order)
                   for c in self.candidates)

    def describe(self) -> Dict[str, Any]:
        """JSON-able grid description (journal spec hashing, reports)."""
        return {"candidates": [[c.family, list(c.order)]
                               for c in self.candidates],
                "horizons": list(self.horizons)}

    def __repr__(self) -> str:
        labels = ", ".join(c.label for c in self.candidates)
        return f"CandidateGrid([{labels}], horizons={self.horizons})"


def default_grid(horizons: Sequence[int] = (1, 4, 8)) -> CandidateGrid:
    """A modest general-purpose grid: AR(1)/AR(2) for autoregressive
    level series, ARMA(1,0,1)/ARIMA(1,1,1) for mixed/integrated
    dynamics, EWMA for local-level streams."""
    return CandidateGrid({"ar": [1, 2],
                          "arima": [(1, 0, 1), (1, 1, 1)],
                          "ewma": True}, horizons=horizons)


class OriginSchedule(NamedTuple):
    """Where the rolling origins sit and what the one-shot parameter fit
    may see.

    ``origins[j] = t`` means: forecast conditioning on the first ``t``
    observations, scoring against observations ``t .. t+horizon-1``
    (0-based).  Parameters are estimated ONCE per (candidate, series) on
    ``fit_window()`` — the expanding prefix ``[0, origins[0])`` or, in
    sliding mode, the trailing ``window`` obs ``[origins[0]-window,
    origins[0])`` — and the *state* conditioning always expands (the
    filter replay sees every observation before the origin; see
    docs/design.md §9 for the replay-vs-refit contract)."""
    origins: np.ndarray          # (n_origins,) int64, strictly increasing
    horizon: int
    mode: str                    # "expanding" | "sliding"
    min_train: int
    window: Optional[int]        # sliding-mode fit-window length
    n_obs: int

    @property
    def n_origins(self) -> int:
        return int(self.origins.size)

    def fit_window(self) -> Tuple[int, int]:
        """``(start, stop)`` of the parameter-estimation slice."""
        stop = int(self.origins[0])
        if self.mode == "sliding":
            return stop - int(self.window), stop
        return 0, stop

    def describe(self) -> Dict[str, Any]:
        return {"origins": [int(t) for t in self.origins],
                "horizon": int(self.horizon), "mode": self.mode,
                "min_train": int(self.min_train),
                "window": None if self.window is None else int(self.window),
                "n_obs": int(self.n_obs)}


def plan_origins(n_obs: int, horizon: int, *, n_origins: int = 8,
                 stride: Optional[int] = None,
                 min_train: Optional[int] = None,
                 mode: str = "expanding",
                 window: Optional[int] = None) -> OriginSchedule:
    """Plan a rolling-origin schedule over an ``n_obs``-long panel.

    Origins are placed as late as possible — the last origin leaves
    exactly ``horizon`` obs to score against — and walk backwards:
    evenly spaced between ``min_train`` (default ``n_obs // 2``) and
    ``n_obs - horizon`` when ``stride`` is None, else every ``stride``
    obs until ``n_origins`` are placed or the min-train floor stops
    them.  ``mode="sliding"`` caps the parameter-fit window at
    ``window`` (default ``min_train``) trailing obs instead of the whole
    prefix — a drift guard for long histories; the state conditioning
    expands either way.
    """
    n_obs = int(n_obs)
    horizon = int(horizon)
    n_origins = int(n_origins)
    if horizon < 1:
        raise ValueError(f"horizon must be >= 1, got {horizon}")
    if n_origins < 1:
        raise ValueError(f"n_origins must be >= 1, got {n_origins}")
    if mode not in ("expanding", "sliding"):
        raise ValueError(f"unknown origin-schedule mode {mode!r}; "
                         f"expected 'expanding' or 'sliding'")
    floor = n_obs // 2 if min_train is None else int(min_train)
    last = n_obs - horizon
    if last < floor or floor < 2:
        raise ValueError(
            f"cannot place any origin: n_obs={n_obs} leaves last origin "
            f"{last} under the min-train floor {floor} (horizon="
            f"{horizon}); shorten the horizon, lower min_train, or "
            f"bring more history")
    if stride is None:
        if n_origins == 1:
            # linspace(num=1) yields only the START point; the contract
            # is origins pack LATE — a single holdout sits at the end
            origins = np.array([last], dtype=np.int64)
        else:
            origins = np.unique(np.linspace(floor, last, num=n_origins,
                                            dtype=np.int64))
    else:
        stride = int(stride)
        if stride < 1:
            raise ValueError(f"stride must be >= 1, got {stride}")
        origins = np.array(sorted(last - k * stride
                                  for k in range(n_origins)
                                  if last - k * stride >= floor),
                           dtype=np.int64)
    if mode == "sliding":
        window = floor if window is None else int(window)
        if window < 2 or window > int(origins[0]):
            raise ValueError(
                f"sliding window {window} must lie in [2, first origin "
                f"{int(origins[0])}]")
    else:
        window = None
    return OriginSchedule(origins=origins, horizon=horizon, mode=mode,
                          min_train=floor, window=window, n_obs=n_obs)
