"""``backtest_panel``: the rolling-origin model-selection front door.

One call answers "which model family and order is best for each of my
series, on out-of-sample evidence" (ROADMAP item 5 — ``auto_fit_panel``
ranks by in-sample AIC only; ARIMA_PLUS, PAPERS.md arXiv 2510.24452,
shows automatic selection with honest accuracy reporting is the
production workload):

1. plan the origins (``grid.plan_origins`` — expanding or sliding fit
   window, min-train floor);
2. fit every grid candidate ONCE per series on the fit window, each
   candidate streamed through ``engine.stream_fit`` chunks — bucketed
   executables, per-chunk deadlines/retry/OOM-halving, ``JobProgress``
   heartbeats (each candidate's stream is labelled ``backtest:<cand>``
   so ``sts_top`` shows per-candidate sweep ETA), and, with
   ``journal=``, crash-consistent per-chunk commits whose spec
   content-hashes the candidate AND the schedule geometry (a changed
   plan refuses resume); ultra-long single-series panels route arima
   candidates through ``longseries.fit_long`` instead;
3. replay every origin through the pinned-gain filter path and score
   sMAPE / MASE / RMSE / interval coverage in-graph, NaN-masked
   (``evaluate.evaluate_candidate``);
4. crown a per-series champion: lowest ``select_by`` score, with
   statistical near-ties — a mean *paired per-origin* score excess
   within ``tie_z`` paired standard errors, plus a ``tie_tol``
   relative floor — broken toward fewer parameters, then grid order;
   deterministic by construction (see ``_select_champions``).

Returns a :class:`BacktestReport`: per-series champions, per-horizon
error tables, per-origin dispersion (the error bars), and a stable
content digest (the durability tests' bitwise-resume pin).
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..utils import metrics as _metrics
from .evaluate import CandidateEval, evaluate_candidate
from .grid import (FAMILIES, Candidate, CandidateGrid, OriginSchedule,
                   default_grid, plan_origins)

__all__ = ["backtest_panel", "BacktestReport"]


class BacktestReport(NamedTuple):
    """The scorecard of one backtest sweep.

    ``champion[i]`` indexes ``candidates`` (−1 when every candidate
    failed on lane ``i``); ``scores_smape``/``scores_mase`` are
    ``(S, C)`` per-series per-candidate scores over the listed horizons;
    ``score_std`` the per-origin standard error of the ``select_by``
    score (honest error bars — forecast-accuracy estimates without
    origin dispersion overstate certainty); ``smape``/``mase``/``rmse``/
    ``coverage`` the full ``(S, C, H)`` per-horizon tables (horizons
    1..H); ``sigma2`` each candidate's calibrated innovation variance.
    """
    candidates: Tuple[Candidate, ...]
    horizons: Tuple[int, ...]
    schedule: OriginSchedule
    select_by: str
    tie_tol: float
    tie_z: float
    mase_m: int               # MASE scaling period (1 = lag-1 naive)
    champion: np.ndarray          # (S,) int64, -1 = no finite candidate
    scores_smape: np.ndarray      # (S, C)
    scores_mase: np.ndarray       # (S, C)
    score_std: np.ndarray         # (S, C)
    smape: np.ndarray             # (S, C, H)
    mase: np.ndarray              # (S, C, H)
    rmse: np.ndarray              # (S, C, H)
    coverage: np.ndarray          # (S, C, H)
    sigma2: np.ndarray            # (S, C)
    n_params: np.ndarray          # (C,)
    stream_stats: Tuple[Dict[str, Any], ...]

    @property
    def n_series(self) -> int:
        return int(self.champion.size)

    @property
    def scores(self) -> np.ndarray:
        """The ``(S, C)`` score matrix champions were selected on."""
        return (self.scores_smape if self.select_by == "smape"
                else self.scores_mase)

    def champion_for(self, i: int) -> Optional[Candidate]:
        ci = int(self.champion[i])
        return None if ci < 0 else self.candidates[ci]

    def champion_counts(self) -> Dict[str, int]:
        """How many series each candidate won (``"<none>"`` = dead)."""
        out: Dict[str, int] = {}
        for ci in self.champion:
            label = "<none>" if ci < 0 else self.candidates[int(ci)].label
            out[label] = out.get(label, 0) + 1
        return out

    def champion_score(self, metric: Optional[str] = None) -> np.ndarray:
        """``(S,)`` — each series' champion's score (NaN for dead
        lanes).  ``metric``: "smape" or "mase" (default: ``select_by``)."""
        metric = self.select_by if metric is None else metric
        table = {"smape": self.scores_smape,
                 "mase": self.scores_mase}[metric]
        out = np.full(self.champion.shape, np.nan, table.dtype)
        alive = self.champion >= 0
        out[alive] = table[np.nonzero(alive)[0], self.champion[alive]]
        return out

    def horizon_table(self, metric: str = "smape") -> np.ndarray:
        """``(H,)`` panel-mean per-horizon error of each series'
        champion — the "how fast does my best model degrade with
        horizon" curve."""
        table = {"smape": self.smape, "mase": self.mase,
                 "rmse": self.rmse, "coverage": self.coverage}[metric]
        alive = self.champion >= 0
        if not alive.any():
            return np.full((table.shape[-1],), np.nan, table.dtype)
        rows = table[np.nonzero(alive)[0], self.champion[alive]]
        return np.nanmean(rows, axis=0)

    def summary(self) -> Dict[str, Any]:
        cs = self.champion_score("smape")
        cm = self.champion_score("mase")
        return {
            "n_series": self.n_series,
            "n_candidates": len(self.candidates),
            "n_origins": self.schedule.n_origins,
            "horizons": list(self.horizons),
            "select_by": self.select_by,
            "mase_m": int(self.mase_m),
            "champion_counts": self.champion_counts(),
            "champion_smape": float(np.nanmean(cs))
            if np.isfinite(cs).any() else None,
            "champion_mase": float(np.nanmean(cm))
            if np.isfinite(cm).any() else None,
        }

    def digest(self) -> str:
        """Stable content hash of everything selection-relevant — two
        sweeps that agree here agree on every champion and every table
        (the kill-9 resume test's bitwise pin)."""
        h = hashlib.sha256()
        h.update(repr([c.label for c in self.candidates]).encode())
        h.update(repr(self.schedule.describe()).encode())
        h.update(repr((self.select_by, float(self.tie_tol),
                       float(self.tie_z), int(self.mase_m),
                       self.horizons)).encode())
        for arr in (self.champion, self.scores_smape, self.scores_mase,
                    self.score_std, self.smape, self.mase, self.rmse,
                    self.coverage, self.sigma2):
            h.update(np.ascontiguousarray(arr).tobytes())
        return h.hexdigest()

    def __repr__(self) -> str:
        counts = ", ".join(f"{k}: {v}"
                           for k, v in sorted(self.champion_counts().items()))
        return (f"BacktestReport({self.n_series} series x "
                f"{len(self.candidates)} candidates x "
                f"{self.schedule.n_origins} origins; champions: {counts})")


def _fit_candidate_long(train: np.ndarray, cand: Candidate,
                        jdir: Optional[str], deadline_s, retry,
                        degrade: bool):
    """Ultra-long route: arima candidates fit per series through the
    DARIMA split-and-combine tier (its own journaled segment streams);
    the combined AR(n_ar) models stack into one batched ARIMAModel."""
    import jax.numpy as jnp

    from ..longseries import fit_long
    from ..models.arima import ARIMAModel
    rows = []
    stats = {"path": "longseries", "journal_hits": 0, "journal_commits": 0,
             "chunk_failures": 0}
    n_ar = None
    d = cand.order[1]
    for i in range(train.shape[0]):
        lf = fit_long(
            train[i], order=cand.order, warn=False,
            journal=os.path.join(jdir, f"s{i:05d}") if jdir else None,
            deadline_s=deadline_s, chunk_retry=retry, degrade=degrade)
        rows.append(np.asarray(lf.model.coefficients).reshape(-1))
        n_ar = lf.model.p
        ss = lf.stream_stats or {}
        stats["journal_hits"] += int(ss.get("journal_hits", 0))
        stats["journal_commits"] += int(ss.get("journal_commits", 0))
        stats["chunk_failures"] += int(ss.get("chunk_failures", 0))
    model = ARIMAModel(n_ar, d, 0,
                       jnp.asarray(np.stack(rows).astype(train.dtype)),
                       True)
    return model, stats


# families whose engine fit accepts NaN-padded ragged lanes (leading/
# trailing padding); everything else needs fully-observed lanes
_RAGGED_FIT_FAMILIES = ("arima", "ar")


def _fittable_lanes(train: np.ndarray, family: str) -> np.ndarray:
    """Which lanes this family's fit path can take as-is.

    Ragged-capable families accept contiguous valid windows (leading/
    trailing NaN padding); interior gaps violate the fit tier's data
    contract ("impute first") and would fail the WHOLE chunk, so gap
    lanes are gathered out and score as dead instead.  Non-ragged
    families (ewma) need fully-observed lanes."""
    f = np.isfinite(train)
    if family not in _RAGGED_FIT_FAMILIES:
        return f.all(axis=1)
    has = f.any(axis=1)
    n = train.shape[1]
    first = np.argmax(f, axis=1)
    last = n - 1 - np.argmax(f[:, ::-1], axis=1)
    span = last - first + 1
    return has & (f.sum(axis=1) == span)


def _fit_candidate(train: np.ndarray, cand: Candidate, idx: int,
                   schedule: OriginSchedule, *, engine, chunk_size: int,
                   journal: Optional[str], deadline_s, retry,
                   degrade: bool, long_threshold: int):
    """One candidate's parameters for the whole panel, streamed.

    Lanes the family's fit path cannot take (interior gaps anywhere;
    any NaN for non-ragged families) are gathered out before the
    stream — one dirty lane must cost ITSELF its scores, not its whole
    chunk — and come back as NaN coefficient rows (NaN forecasts,
    masked metrics, never champion)."""
    spec = FAMILIES[cand.family]
    jdir = os.path.join(journal, f"cand-{idx:02d}-{cand.slug}") \
        if journal else None
    if cand.family == "arima" and train.shape[1] >= long_threshold:
        return _fit_candidate_long(train, cand, jdir, deadline_s, retry,
                                   degrade)
    from ..engine import default_engine
    eng = engine if engine is not None else default_engine()
    ok = _fittable_lanes(train, cand.family)
    n_skipped = int((~ok).sum())
    if not ok.any():
        raise ValueError(
            f"no lane of the fit window is fittable for "
            f"{cand.label}: every lane has interior gaps"
            + ("" if cand.family in _RAGGED_FIT_FAMILIES
               else " or missing ticks (this family has no ragged fit)")
            + " — impute first (Panel.fill)")
    sub = train if n_skipped == 0 else np.ascontiguousarray(train[ok])
    meta = {"tier": "backtest",
            "candidate": [cand.family, list(cand.order)],
            "schedule": schedule.describe()}
    res = eng.stream_fit(
        sub, cand.family, chunk_size=int(chunk_size), collect=True,
        journal=jdir, job_meta=meta, deadline_s=deadline_s, retry=retry,
        degrade=degrade, job_label=f"backtest:{cand.label}",
        **spec.stream_kwargs(cand.order))
    width = spec.row_width(cand.order)
    rows = np.full((train.shape[0], width), np.nan, train.dtype)
    lane_ids = np.nonzero(ok)[0]
    for rng, m in zip(res.stats.get("collected_ranges") or [],
                      res.models):
        rows[lane_ids[rng[0]:rng[1]]] = \
            spec.rows_of(m).astype(train.dtype)
    stats = {"path": "stream", "n_chunks": res.n_chunks,
             "chunk_failures": len(res.chunk_failures),
             "lanes_skipped": n_skipped,
             "journal_hits": int(res.stats.get("journal_hits", 0)),
             "journal_commits": int(res.stats.get("journal_commits", 0))}
    return spec.rebuild(cand.order, rows), stats


def _select_champions(origin_scores: np.ndarray, scores: np.ndarray,
                      n_params: np.ndarray, tie_tol: float,
                      tie_z: float) -> np.ndarray:
    """Lowest score wins; statistical near-ties break toward fewer
    parameters, then grid order.

    The tie test is *paired per origin*: a candidate ties the minimum
    when its mean per-origin score excess over the best candidate is
    within ``tie_z`` paired standard errors (origins are shared, so the
    common forecast-noise component cancels — exactly the dispersion
    the report's error bars publish) plus a ``tie_tol`` relative floor.
    Without the parsimony ply, a nested over-parameterized candidate
    (AR(2) on a true AR(1)) would win ~half the lanes on fit-noise
    alone; without the *paired* band, the fixed tolerance would have to
    straddle both the nested-fit noise and the genuine margin of a
    wrong-but-close family — a window that closes as grids grow."""
    sc = np.where(np.isfinite(scores), scores, np.inf)
    best_idx = np.argmin(sc, axis=1)
    best = sc[np.arange(sc.shape[0]), best_idx]
    alive = np.isfinite(best)
    best_o = np.take_along_axis(
        origin_scores, best_idx[:, None, None], axis=1)   # (S, 1, O)
    diff = origin_scores - best_o                          # (S, C, O)
    m = np.isfinite(diff)
    cnt = m.sum(axis=2)
    mean_d = np.where(m, diff, 0.0).sum(axis=2) / np.maximum(cnt, 1)
    var_d = np.where(m, (diff - mean_d[..., None]) ** 2,
                     0.0).sum(axis=2) / np.maximum(cnt, 1)
    se = np.sqrt(var_d) / np.sqrt(np.maximum(cnt, 1))
    band = float(tie_z) * se + float(tie_tol) * np.abs(best)[:, None]
    ties = np.isfinite(scores) & (cnt > 0) & (mean_d <= band)
    ties[np.arange(sc.shape[0]), best_idx] = True
    C = sc.shape[1]
    key = n_params.astype(np.float64)[None, :] * C \
        + np.arange(C, dtype=np.float64)[None, :]
    key = np.where(ties, key, np.inf)
    champ = np.argmin(key, axis=1).astype(np.int64)
    champ[~alive] = -1
    return champ


def backtest_panel(values, grid: Optional[CandidateGrid] = None, *,
                   horizons: Optional[Sequence[int]] = None,
                   n_origins: int = 8, stride: Optional[int] = None,
                   min_train: Optional[int] = None,
                   mode: str = "expanding", window: Optional[int] = None,
                   select_by: str = "mase", tie_tol: float = 1e-3,
                   tie_z: float = 2.0, mase_m: int = 1,
                   coverage: float = 0.9, replay: str = "pinned",
                   engine=None, chunk_size: int = 131072,
                   journal: Optional[str] = None,
                   deadline_s: Optional[float] = None, retry=None,
                   degrade: bool = True,
                   long_threshold: int = 500_000) -> BacktestReport:
    """Rolling-origin backtest + per-series champion selection.

    ``values (n_series, n_obs)`` the raw panel (NaN = missing; masked
    out of every metric).  ``grid`` the
    :class:`~spark_timeseries_tpu.backtest.grid.CandidateGrid` of
    (family, order) competitors (default :func:`default_grid`);
    ``horizons`` overrides the grid's scoring horizons.

    Schedule knobs (→ :func:`~spark_timeseries_tpu.backtest.grid.
    plan_origins`): ``n_origins``/``stride``/``min_train``, and
    ``mode="sliding"`` with ``window`` to cap the parameter-fit window.
    Selection knobs: ``select_by`` ("mase" — scale-free, the default —
    or "smape"); ``tie_z``/``tie_tol`` shape the statistical near-tie
    band the parsimony tie-break applies inside (``tie_z`` paired
    per-origin standard errors plus a ``tie_tol`` relative floor — see
    docs/design.md §9 champion tie-breaking); ``mase_m`` the MASE
    scaling period (1 = lag-1 naive; pass the seasonal period to scale
    by the seasonal-naive in-sample MAE — Hyndman & Koehler's seasonal
    MASE — so seasonal panels compete on a denominator their
    seasonality doesn't inflate); ``coverage`` the nominal interval
    level the coverage metric tests; ``replay`` ("pinned" | "refilter"
    — the sequential oracle, O(origins) slower, for verification).

    Streaming knobs pass straight to ``engine.stream_fit`` per
    candidate: ``engine``/``chunk_size``/``deadline_s``/``retry``/
    ``degrade``, and ``journal=dir`` arms one crash-consistent journal
    per candidate under ``dir/cand-XX-<slug>`` — a killed sweep rerun
    with the same arguments resumes committed fits (``journal_hits`` in
    ``stream_stats``) and reproduces a digest-identical report.  Panels
    with ``n_obs >= long_threshold`` route arima candidates through
    ``longseries.fit_long`` (one journaled segment stream per series).
    """
    if select_by not in ("smape", "mase"):
        raise ValueError(f"select_by must be 'smape' or 'mase', got "
                         f"{select_by!r} (rmse/coverage are table "
                         f"metrics, not selection scores)")
    if tie_tol < 0 or tie_z < 0:
        raise ValueError(f"tie_tol/tie_z must be >= 0, got "
                         f"{tie_tol}/{tie_z}")
    mase_m = int(mase_m)
    if mase_m < 1:
        # fail before the first candidate's full streamed fit
        raise ValueError(f"mase_m must be a period >= 1, got {mase_m}")
    if replay not in ("pinned", "refilter"):
        # fail before the first candidate's full streamed fit, not after
        raise ValueError(f"unknown replay mode {replay!r}; expected "
                         f"'pinned' or 'refilter'")
    host = np.asarray(values)
    if host.ndim == 1:
        host = host[None, :]
    if host.ndim != 2:
        raise ValueError(f"backtest_panel needs an (n_series, n_obs) "
                         f"panel, got {host.shape}")
    if not np.issubdtype(host.dtype, np.floating):
        host = host.astype(np.float32)
    S, n = host.shape

    if grid is None:
        grid = default_grid() if horizons is None \
            else default_grid(horizons)
    elif horizons is not None:
        grid = CandidateGrid(
            {**_group_orders(grid)}, horizons=horizons)
    schedule = plan_origins(n, grid.horizon, n_origins=n_origins,
                            stride=stride, min_train=min_train,
                            mode=mode, window=window)
    fs, ft = schedule.fit_window()
    floor = grid.min_train_floor()
    if ft - fs < floor:
        raise ValueError(
            f"fit window [{fs}, {ft}) is too short for the grid: the "
            f"widest candidate needs >= {floor} training obs — raise "
            f"min_train/window or shrink the candidate orders")

    reg = _metrics.get_registry()
    cands = tuple(grid.candidates)
    with _metrics.span("backtest.backtest_panel"):
        train = host[:, fs:ft]
        evals: list[CandidateEval] = []
        stream_stats = []
        for ci, cand in enumerate(cands):
            with _metrics.span("backtest.fit"):
                try:
                    model, stats = _fit_candidate(
                        train, cand, ci, schedule, engine=engine,
                        chunk_size=chunk_size, journal=journal,
                        deadline_s=deadline_s, retry=retry,
                        degrade=degrade, long_threshold=long_threshold)
                except Exception as e:  # noqa: BLE001 — candidate
                    # isolation: one family's fit path refusing the
                    # panel (e.g. ewma has no traced ragged fit for
                    # NaN-padded lanes) must cost that CANDIDATE its
                    # scores, not the whole sweep — mirroring the
                    # engine's per-chunk failure isolation.  A journal
                    # spec mismatch is the ONE exception that must stay
                    # loud: it means this journal belongs to a
                    # different sweep (changed data/plan), and silently
                    # scoring the candidate as dead would bury exactly
                    # the refusal the spec hash exists to surface.
                    from ..utils.durability import JournalSpecMismatch
                    if isinstance(e, JournalSpecMismatch):
                        raise
                    reg.inc("backtest.candidate_failures")
                    spec = FAMILIES[cand.family]
                    rows = np.full(
                        (train.shape[0], spec.row_width(cand.order)),
                        np.nan, train.dtype)
                    model = spec.rebuild(cand.order, rows)
                    stats = {"path": "failed",
                             "error": f"{type(e).__name__}: {e}"}
            evals.append(evaluate_candidate(
                host, model, schedule, grid.horizons, replay=replay,
                coverage=coverage, mase_m=mase_m))
            stream_stats.append(stats)

        scores_smape = np.stack([e.score_smape for e in evals], axis=1)
        scores_mase = np.stack([e.score_mase for e in evals], axis=1)
        sel = scores_smape if select_by == "smape" else scores_mase
        n_params = np.asarray([FAMILIES[c.family].n_params(c.order)
                               for c in cands], np.int64)
        origin_sel = np.stack([e.origin_smape if select_by == "smape"
                               else e.origin_mase for e in evals], axis=1)
        champion = _select_champions(origin_sel, sel, n_params, tie_tol,
                                     tie_z)

        # error bars from the SAME per-origin scores the tie band uses
        o_cnt = np.sum(np.isfinite(origin_sel), axis=2)      # (S, C)
        score_std = np.where(
            o_cnt > 1, _nanstd0(origin_sel) / np.sqrt(np.maximum(o_cnt, 1)),
            np.where(o_cnt > 0, 0.0, np.nan))

        report = BacktestReport(
            candidates=cands, horizons=grid.horizons, schedule=schedule,
            select_by=select_by, tie_tol=float(tie_tol),
            tie_z=float(tie_z), mase_m=mase_m,
            champion=champion, scores_smape=scores_smape,
            scores_mase=scores_mase, score_std=score_std,
            smape=np.stack([e.smape for e in evals], axis=1),
            mase=np.stack([e.mase for e in evals], axis=1),
            rmse=np.stack([e.rmse for e in evals], axis=1),
            coverage=np.stack([e.coverage for e in evals], axis=1),
            sigma2=np.stack([e.sigma2 for e in evals], axis=1),
            n_params=n_params, stream_stats=tuple(stream_stats))

        reg.inc("backtest.runs")
        reg.inc("backtest.candidates", len(cands))
        reg.inc("backtest.series", S)
        reg.inc("backtest.origins", schedule.n_origins)
        reg.inc("backtest.journal_hits",
                sum(s.get("journal_hits", 0) for s in stream_stats))
        dead = int(np.sum(champion < 0))
        if dead:
            reg.inc("backtest.dead_lanes", dead)
        cs = report.champion_score("smape")
        if np.isfinite(cs).any():
            reg.set_gauge("backtest.last_champion_smape",
                          float(np.nanmean(cs)))
    return report


def _nanstd0(x: np.ndarray) -> np.ndarray:
    """nanstd(axis=-1) without the all-NaN RuntimeWarning."""
    m = np.isfinite(x)
    cnt = np.maximum(m.sum(axis=-1), 1)
    mean = np.where(m, x, 0.0).sum(axis=-1) / cnt
    var = np.where(m, (x - mean[..., None]) ** 2, 0.0).sum(axis=-1) / cnt
    return np.sqrt(var)


def _group_orders(grid: CandidateGrid) -> Dict[str, Any]:
    """Regroup a grid's candidates family → order list (rebuilding the
    grid with overridden horizons)."""
    out: Dict[str, Any] = {}
    for c in grid.candidates:
        out.setdefault(c.family, []).append(c.order)
    return out
