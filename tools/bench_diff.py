"""bench_diff: mechanical regression forensics between two BENCH rounds.

``tools/bench_gate.py`` answers *whether* the newest round regressed;
this tool answers *where the milliseconds went*.  ``make bench-diff``
(or ``python tools/bench_diff.py [OLD] [NEW]``) loads two
``BENCH_r*.json`` artifacts — by default the newest two **comparable**
rounds, with exactly bench_gate's filter (same platform as the newest
valid round, ``rc == 0``, a non-null headline value) — and attributes
the headline throughput delta to the concrete spans and counters that
moved:

- **headline**: old/new series-per-second and the signed percentage
  delta, plus the gated headline metrics (fit wall, compile seconds,
  serving p50, ...) side by side;
- **spans**: per-span inclusive seconds (``metrics.spans[*].total_s``)
  diffed by name, ranked by absolute change, each with its signed
  contribution and its share of the total absolute span movement — the
  "top host-side spans responsible" table;
- **self-times**: when both rounds carry the attribution plane's
  ``metrics.self_times`` block (PR 16+), the same table on *exclusive*
  self-time — a parent that merely wraps a slower child drops out —
  plus the per-subsystem rollup deltas (engine / statespace / backtest /
  models / utils);
- **counters**: the engine / fit / serving / backtest counter blocks
  diffed by key, ranked by relative change (a counter that appears or
  disappears ranks first);
- **attribution**: old-vs-new ``engine_attribution`` summary
  (host_overhead_frac, bubble_ms_total, per-phase totals) when present;
- **cost**: the headline family's compiled-program cost report deltas
  (flops, bytes, peak memory, HLO op count, compile seconds);
- **curve**: the scaling-curve points both rounds measured, diffed
  per panel size.

Output is a human table by default, the same structure as JSON with
``--json``.  This is a forensics tool, not a gate: it exits 0 whenever
it could diff (regressions and improvements alike), 2 on usage errors
(unknown round, fewer than two comparable rounds).

Round selectors are forgiving: ``r04``, ``04``, ``4``, or a path to the
artifact file all name round 4.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple


def _load_bench_gate():
    """bench_gate is both a sibling script and (via tools/__init__.py) a
    package module; load it whichever way the interpreter allows so
    ``python tools/bench_diff.py``, ``python -m tools.bench_diff``, and
    an importlib-loaded test all work."""
    try:
        from tools import bench_gate  # type: ignore
        return bench_gate
    except Exception:  # noqa: BLE001 — fall back to a file load
        spec = importlib.util.spec_from_file_location(
            "bench_gate", os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "bench_gate.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod


bench_gate = _load_bench_gate()

# counter blocks diffed by key (each block's keys are already
# namespace-prefixed, so one merged dict cannot collide)
_COUNTER_BLOCKS = ("engine", "fit_counters", "serving", "backtest")

# scalar cost-report fields worth diffing (the HLO op histogram is too
# wide for a diff table; hlo_ops_total summarizes it)
_COST_FIELDS = ("flops", "bytes_accessed", "transcendentals",
                "peak_bytes", "temp_bytes", "hlo_ops_total",
                "lower_s", "compile_s")


def _num(v: Any) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) else None


def _metrics(headline: Optional[dict]) -> dict:
    m = (headline or {}).get("metrics")
    return m if isinstance(m, dict) else {}


def span_totals(headline: Optional[dict]) -> Dict[str, float]:
    """``{span path: inclusive total seconds}`` from a round's aggregate
    span histograms."""
    out: Dict[str, float] = {}
    spans = _metrics(headline).get("spans")
    if isinstance(spans, dict):
        for name, st in spans.items():
            v = _num((st or {}).get("total_s")) if isinstance(st, dict) \
                else None
            if v is not None:
                out[name] = v
    return out


def self_totals(headline: Optional[dict]
                ) -> Optional[Dict[str, float]]:
    """``{span name: exclusive self seconds}`` from the attribution
    plane's ``metrics.self_times`` block; None when the round predates
    it (r01–r07) — a diff must not fabricate zeros for an unmeasured
    round."""
    st = _metrics(headline).get("self_times")
    if not isinstance(st, dict):
        return None
    out: Dict[str, float] = {}
    for row in st.get("spans") or []:
        if isinstance(row, dict) and isinstance(row.get("name"), str):
            v = _num(row.get("self_s"))
            if v is not None:
                out[row["name"]] = v
    return out


def subsystem_totals(headline: Optional[dict]
                     ) -> Optional[Dict[str, float]]:
    st = _metrics(headline).get("self_times")
    if not isinstance(st, dict) \
            or not isinstance(st.get("subsystems"), dict):
        return None
    out: Dict[str, float] = {}
    for sub, row in st["subsystems"].items():
        v = _num((row or {}).get("self_s")) if isinstance(row, dict) \
            else None
        if v is not None:
            out[str(sub)] = v
    return out


def counter_totals(headline: Optional[dict]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    m = _metrics(headline)
    for block in _COUNTER_BLOCKS:
        b = m.get(block)
        if isinstance(b, dict):
            for k, v in b.items():
                n = _num(v)
                if n is not None:
                    out[str(k)] = n
    return out


def _delta_rows(old: Dict[str, float], new: Dict[str, float],
                top: int) -> List[Dict[str, Any]]:
    """Signed per-key deltas over the union of keys, ranked by absolute
    change (name-stable on ties), each with its share of the total
    absolute movement — the attribution weights."""
    keys = set(old) | set(new)
    rows = []
    for k in keys:
        o, n = old.get(k, 0.0), new.get(k, 0.0)
        d = n - o
        if d == 0.0:
            continue    # a diff shows movement; unchanged rows are noise
        rows.append({"name": k, "old": round(o, 6), "new": round(n, 6),
                     "delta": round(d, 6)})
    total_abs = sum(abs(r["delta"]) for r in rows)
    for r in rows:
        r["share_pct"] = round(100.0 * abs(r["delta"]) / total_abs, 1) \
            if total_abs > 0 else 0.0
    rows.sort(key=lambda r: (-abs(r["delta"]), r["name"]))
    return rows[:top]


def _rel_delta_rows(old: Dict[str, float], new: Dict[str, float],
                    top: int) -> List[Dict[str, Any]]:
    """Counter deltas ranked by *relative* change (mixed units — bytes
    next to chunk counts — make absolute ranking meaningless); a key
    present on only one side ranks by its absolute size."""
    keys = set(old) | set(new)
    rows = []
    for k in keys:
        o, n = old.get(k), new.get(k)
        ov, nv = o or 0.0, n or 0.0
        if ov == nv:
            continue
        base = min(abs(ov), abs(nv))
        rel = abs(nv - ov) / base if base > 0 else float("inf")
        rows.append({"name": k, "old": o, "new": n,
                     "delta": round(nv - ov, 6), "_rel": rel})
    rows.sort(key=lambda r: (-r["_rel"], -abs(r["delta"]), r["name"]))
    for r in rows:
        del r["_rel"]
    return rows[:top]


def diff_rounds(old: Dict[str, Any], new: Dict[str, Any],
                top: int = 12) -> Dict[str, Any]:
    """The full diff document between two loaded rounds (the
    ``bench_gate.load_round`` shape).  Pure; the CLI renders it."""
    ho, hn = old["headline"], new["headline"]
    vo, vn = _num((ho or {}).get("value")), _num((hn or {}).get("value"))
    headline: Dict[str, Any] = {"old": vo, "new": vn}
    if vo is not None and vn is not None:
        headline["delta"] = round(vn - vo, 1)
        headline["delta_pct"] = round(100.0 * (vn - vo) / vo, 1) \
            if vo else None
    gated_old = bench_gate.extract_metrics(ho)
    gated_new = bench_gate.extract_metrics(hn)
    gated = {}
    for k in sorted(set(gated_old) | set(gated_new)):
        gated[k] = {"old": gated_old.get(k), "new": gated_new.get(k)}

    selfs_o, selfs_n = self_totals(ho), self_totals(hn)
    subs_o, subs_n = subsystem_totals(ho), subsystem_totals(hn)

    att = None
    ea_o, ea_n = (ho or {}).get("engine_attribution"), \
        (hn or {}).get("engine_attribution")
    if isinstance(ea_o, dict) or isinstance(ea_n, dict):
        att = {}
        for field in ("host_overhead_frac", "bubble_ms_total", "host_ms",
                      "wall_ms"):
            att[field] = {
                "old": _num((ea_o or {}).get(field)),
                "new": _num((ea_n or {}).get(field)),
            }
        att["totals_ms"] = {
            "old": (ea_o or {}).get("totals_ms"),
            "new": (ea_n or {}).get("totals_ms"),
        }

    cost = None
    co = ((ho or {}).get("cost_reports") or {})
    cn = ((hn or {}).get("cost_reports") or {})
    fam = next(iter(cn), None) or next(iter(co), None)
    if fam and isinstance(cn.get(fam) or co.get(fam), dict):
        cost = {"family": fam}
        for field in _COST_FIELDS:
            o = _num((co.get(fam) or {}).get(field))
            n = _num((cn.get(fam) or {}).get(field))
            if o is not None or n is not None:
                cost[field] = {"old": o, "new": n}

    curve = []
    curve_o = (ho or {}).get("scaling_curve") or {}
    curve_n = (hn or {}).get("scaling_curve") or {}
    if isinstance(curve_o, dict) and isinstance(curve_n, dict):
        for k in sorted(set(curve_o) & set(curve_n),
                        key=lambda s: int(s) if s.isdigit() else 0):
            o, n = _num(curve_o[k]), _num(curve_n[k])
            if o is None or n is None:
                continue
            curve.append({
                "n": int(k) if k.isdigit() else k, "old": o, "new": n,
                "delta_pct": round(100.0 * (n - o) / o, 1) if o else None,
            })

    return {
        "old_round": old["round"],
        "new_round": new["round"],
        "platform": (hn or {}).get("platform"),
        "headline": headline,
        "gated_metrics": gated,
        "spans": _delta_rows(span_totals(ho), span_totals(hn), top),
        "self_times": _delta_rows(selfs_o, selfs_n, top)
        if selfs_o is not None and selfs_n is not None else None,
        "subsystems": _delta_rows(subs_o, subs_n, top)
        if subs_o is not None and subs_n is not None else None,
        "counters": _rel_delta_rows(counter_totals(ho),
                                    counter_totals(hn), top),
        "attribution": att,
        "cost": cost,
        "curve": curve,
    }


def _fmt(v: Any, fmt: str = "{:.3f}") -> str:
    return fmt.format(v) if isinstance(v, (int, float)) else "-"


def _delta_table(title: str, rows: List[Dict[str, Any]],
                 unit: str = "s") -> List[str]:
    lines = [title]
    if not rows:
        lines.append("  (nothing moved)")
        return lines
    w = max(len(r["name"]) for r in rows)
    hdr = (f"  {'name':<{w}} {'old_' + unit:>12} {'new_' + unit:>12} "
           f"{'delta':>12} {'share%':>7}")
    lines.append(hdr)
    lines.append("  " + "-" * (len(hdr) - 2))
    for r in rows:
        share = r.get("share_pct")
        lines.append(
            f"  {r['name']:<{w}} {_fmt(r['old']):>12} "
            f"{_fmt(r['new']):>12} "
            f"{_fmt(r['delta'], '{:+.3f}'):>12} "
            f"{_fmt(share, '{:.1f}'):>7}")
    return lines


def render(d: Dict[str, Any]) -> str:
    lines = [
        f"bench diff: r{d['old_round']:02d} -> r{d['new_round']:02d} "
        f"(platform={d['platform']})",
    ]
    h = d["headline"]
    pct = h.get("delta_pct")
    lines.append(
        f"headline: {_fmt(h.get('old'), '{:.1f}')} -> "
        f"{_fmt(h.get('new'), '{:.1f}')} series/s"
        + (f"  ({pct:+.1f}%)" if isinstance(pct, (int, float)) else ""))
    lines.append("")

    if d.get("self_times") is not None:
        lines += _delta_table(
            "SPAN SELF-TIME (exclusive seconds, ranked by |delta|)",
            d["self_times"])
        lines.append("")
        if d.get("subsystems") is not None:
            lines += _delta_table("SUBSYSTEM SELF-TIME (seconds)",
                                  d["subsystems"])
            lines.append("")
    lines += _delta_table(
        "SPAN TOTALS (inclusive seconds, ranked by |delta|)", d["spans"])
    lines.append("")
    lines += _delta_table(
        "COUNTERS (ranked by relative change)", d["counters"], unit="n")
    lines.append("")

    att = d.get("attribution")
    if att:
        f = att.get("host_overhead_frac", {})
        b = att.get("bubble_ms_total", {})
        lines.append(
            f"engine attribution: host_overhead_frac "
            f"{_fmt(f.get('old'))} -> {_fmt(f.get('new'))}   "
            f"bubble_ms {_fmt(b.get('old'), '{:.1f}')} -> "
            f"{_fmt(b.get('new'), '{:.1f}')}")
        lines.append("")
    cost = d.get("cost")
    if cost:
        parts = []
        for field in _COST_FIELDS:
            fv = cost.get(field)
            if isinstance(fv, dict):
                parts.append(f"{field} {_fmt(fv['old'], '{:.4g}')} -> "
                             f"{_fmt(fv['new'], '{:.4g}')}")
        if parts:
            lines.append(f"cost ({cost.get('family')}): "
                         + "  ".join(parts))
            lines.append("")
    if d.get("curve"):
        lines.append("scaling curve (series/s):")
        for p in d["curve"]:
            pct = p.get("delta_pct")
            lines.append(
                f"  n={p['n']:<8} {_fmt(p['old'], '{:.1f}'):>10} -> "
                f"{_fmt(p['new'], '{:.1f}'):>10}"
                + (f"  ({pct:+.1f}%)"
                   if isinstance(pct, (int, float)) else ""))
    return "\n".join(lines).rstrip() + "\n"


def _find_round(history: List[Dict[str, Any]], selector: str
                ) -> Optional[Dict[str, Any]]:
    """Resolve ``r04`` / ``04`` / ``4`` / a path to a loaded round."""
    sel = selector.strip()
    if os.path.sep in sel or sel.endswith(".json"):
        target = os.path.abspath(sel)
        for r in history:
            if os.path.abspath(r["path"]) == target:
                return r
        return None
    digits = sel[1:] if sel[:1] in ("r", "R") else sel
    if not digits.isdigit():
        return None
    num = int(digits)
    for r in history:
        if r["round"] == num:
            return r
    return None


def pick_default_rounds(history: List[Dict[str, Any]]
                        ) -> Tuple[Optional[dict], Optional[dict], str]:
    """The newest two comparable rounds, bench_gate's definition: the
    newest round with a measured headline fixes the platform; both
    sides must be rc==0 (or unknown) with a non-null value on that
    platform."""
    newest = None
    for r in reversed(history):
        h = r["headline"]
        if isinstance(h, dict) and _num(h.get("value")) is not None:
            newest = r
            break
    if newest is None:
        return None, None, "no round with a measured headline value"
    platform = newest["headline"].get("platform")
    comp = [r for r in history if bench_gate.comparable(r, platform)]
    if len(comp) < 2:
        return None, None, (f"{len(comp)} comparable round(s) on "
                            f"platform {platform!r}, need 2")
    return comp[-2], comp[-1], ""


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.bench_diff",
        description="Attribute the throughput delta between two BENCH "
                    "rounds to the spans/counters that moved "
                    "(default: the newest two comparable rounds).")
    ap.add_argument("old", nargs="?", default=None,
                    help="older round: r04 / 4 / a path "
                         "(default: second-newest comparable)")
    ap.add_argument("new", nargs="?", default=None,
                    help="newer round (default: newest comparable)")
    ap.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--glob", default=bench_gate.DEFAULT_GLOB,
                    help=f"artifact glob (default "
                         f"{bench_gate.DEFAULT_GLOB})")
    ap.add_argument("--top", type=int, default=12,
                    help="rows per delta table (default 12)")
    ap.add_argument("--json", action="store_true",
                    help="emit the diff document as JSON")
    args = ap.parse_args(argv)
    if (args.old is None) != (args.new is None):
        ap.error("give both OLD and NEW rounds, or neither")

    history = bench_gate.load_history(args.dir, args.glob)
    if args.old is not None:
        old = _find_round(history, args.old)
        new = _find_round(history, args.new)
        for sel, r in ((args.old, old), (args.new, new)):
            if r is None:
                print(f"bench diff: no round matching {sel!r} under "
                      f"{args.dir}", file=sys.stderr)
                return 2
    else:
        old, new, why = pick_default_rounds(history)
        if old is None:
            print(f"bench diff: {why}", file=sys.stderr)
            return 2
    d = diff_rounds(old, new, top=max(1, args.top))
    if args.json:
        print(json.dumps(d, indent=2, sort_keys=True))
    else:
        print(render(d), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
