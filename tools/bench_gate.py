"""Bench regression gate: is the newest BENCH round worse than its past?

The repo's perf evidence is the ordered ``BENCH_r*.json`` trajectory;
until now nothing *checked* it — a silent 2x wall-time regression would
ride along unnoticed until a human read the numbers.  This tool is the
automated check (``make gate``):

- loads every ``BENCH_r*.json`` in round order (each is either the
  driver's wrapper ``{"n", "cmd", "rc", "tail", "parsed"}`` or a raw
  bench JSON-lines dump; the headline record is the last parseable JSON
  line);
- keeps only rounds *comparable* to the newest one — same ``platform``
  (a degraded CPU fallback must never be gated against a TPU round),
  ``rc == 0``, a non-null headline value;
- per headline metric, compares the newest round against the median of
  the trailing ``--window`` comparable rounds and fails past the
  metric's threshold:

  ============================  ============================================  ======
  metric                        source                                        worse
  ============================  ============================================  ======
  throughput                    headline ``value`` (series/sec)               lower
  fit_wall_s                    ``metrics.spans["bench.fit_panel"]`` p50      higher
  compile_s_total               ``metrics.compile_s_total``                   higher
  jit_compiles                  ``metrics.jit_compiles``                      higher
  engine_cache_misses           ``metrics.engine["engine.cache_misses"]``     higher
  engine_chunk_failures         ``metrics.engine["engine.chunk_failures"]``   higher
  engine_dead_chunks            ``metrics.engine["engine.dead_chunks"]``      higher
  serving_update_p50            ``metrics.spans["serving.update"]`` p50       higher
  serving_update_p95            ``metrics.spans["serving.update"]`` p95       higher
  serving_diverged_lanes        ``metrics.serving["serving.diverged"]``       higher
  resilience_auto_fallback_dead ``metrics.fit_counters[...auto_fallback_dead]`` higher
  heal_p50                      ``metrics.spans["serving.heal"]`` p50         higher
  long_obs_per_s                headline ``long_demo.obs_per_s``              lower
  incidents_written             ``metrics.telemetry["incidents_written"]``    higher
  fleet_ticks_per_s             headline ``fleet_demo.fleet_ticks_per_s``     lower
  fleet_shed_lanes              headline ``fleet_demo.shed_lanes``            higher
  fleet_pump_restarts           headline ``fleet_demo.pump_restarts``         higher
  fleet_checkpoint_failures     headline ``fleet_demo.checkpoint_failures``   higher
  backtest_champion_smape       headline ``backtest_demo.champion_smape``     higher
  backtest_champion_mase        headline ``backtest_demo.champion_mase``      higher
  serving_live_smape            headline ``serving_demo.quality.live_smape``  higher
  drift_false_alarms            headline ``serving_demo.quality.drift_alarms`` higher
  engine_host_overhead_frac     headline ``engine_attribution.host_overhead_frac`` higher
  fleet_e2e_p95_ms              headline ``fleet_demo.fleet_e2e_p95_ms``      higher
  ============================  ============================================  ======

  (``engine_cache_misses`` is the streaming engine's executable-cache
  miss count — a >50% jump over the trailing median means fits stopped
  sharing bucketed executables, i.e. the compile-amortization win
  regressed even if wall time hasn't caught it yet.
  ``engine_chunk_failures``/``engine_dead_chunks`` are the stream's
  reliability counters: when an ``engine`` block is present but the
  counter is absent the round ran CLEAN and the value is a real 0 —
  registry counters only materialize on first increment — so a history
  of zeros flags ANY newly nonzero round via the zero-baseline rule
  below, exactly the "a chunk silently started dying every round"
  regression the durability tier exists to prevent.

  ``serving_update_p50``/``p95`` are the serving tier's per-tick
  latency SLO (ISSUE 7): the ``serving.update`` span wraps exactly one
  cached-executable Kalman step *including* result materialization, so
  a >25% jump over the trailing median means tick ingest itself got
  slower — a recompile leaking into the hot path, a bucket policy
  change, or per-tick work that stopped being O(1).

  ``serving_diverged_lanes`` and ``resilience_auto_fallback_dead`` are
  the self-healing tier's reliability counters (ISSUE 9), zero-baselined
  exactly like the engine's: when the record carries a ``serving`` /
  ``fit_counters`` block but the counter key is absent, the run was
  CLEAN and the value is a real 0 (registry counters materialize on
  first increment) — so any round where serving lanes started diverging,
  or where the auto-order fallback stage started losing lanes it was
  offered, is flagged by the zero-baseline rule even though a 0 baseline
  admits no percentage.  ``heal_p50`` is the ``serving.heal`` span's
  median — the wall cost of one quarantine-refit-splice cycle — and is
  tolerated-absent in rounds that never healed (or predate healing).

  ``long_obs_per_s`` is the ultra-long tier's end-to-end throughput
  (ISSUE 8): the bench's ``long_demo`` fits one 10⁶-observation
  synthetic ARMA through the DARIMA split-and-combine path — global
  differencing, obs-axis segmentation, segments streamed through
  ``engine.stream_fit``, in-graph WLS combination — and reports
  observations fitted per second.  A >25% drop means the obs-axis
  pipeline regressed (segment streaming stopped sharing executables,
  the combiner grew host round-trips, ...).  Like the serving SLO it
  is absent in rounds that predate the tier — no fabricated zeros.)

  ``incidents_written`` is the flight recorder's bundle counter
  (ISSUE 10), zero-baselined: a bench round must not organically crash
  — any round where ``stream_fit`` chunks started dying, deadlines
  started expiring, or a stream exception escaped writes bundles, and
  the first such round is flagged against an all-zero history.
  Tolerated-absent in rounds that predate the telemetry block.

  ``fleet_ticks_per_s`` is the fleet tier's aggregate throughput
  (ISSUE 12): the bench's ``fleet_demo`` multiplexes ≥64 tenant
  sessions onto coalesced update dispatches through the
  ``FleetScheduler`` and reports lane-ticks ingested per second — a
  >25% drop means the coalescing path regressed (ticks stopped
  sharing device calls, a recompile leaked into the pump, the gather/
  scatter grew host overhead).  ``fleet_shed_lanes`` is zero-baselined
  like the reliability counters: the demo's nominal load must not burn
  the SLO, so any round where the scheduler started shedding lanes is
  flagged against an all-zero history.  Both tolerated-absent in
  pre-fleet rounds.

  ``fleet_pump_restarts`` / ``fleet_checkpoint_failures`` are the
  autonomous-runtime supervision gates (ISSUE 17): the fleet demo now
  runs through ``FleetRuntime``'s supervised background pump, and a
  healthy round restarts that pump zero times and fails zero
  auto-checkpoint generations.  Zero-baselined like the reliability
  counters (block present + key absent = measured 0, since registry
  counters materialize on first increment); tolerated-absent in
  pre-runtime rounds.  ``fleet_ticks_per_s`` doubling as the guard
  that arming the async runtime did not tax throughput.

  ``fleet_e2e_p95_ms`` is the tick-lineage plane's end-to-end gate
  (ISSUE 18): the fleet demo's pumped run reports the p95
  submit→delivery wall time per tick from the lineage ring — the full
  async path including admission backpressure, per-tenant queueing,
  coalesce gather, the jitted dispatch, scatter and delivery.  A >25%
  jump over the trailing median means tail latency regressed somewhere
  ``fleet_ticks_per_s`` (an aggregate rate) can't see — one slow stage
  is invisible to throughput until it dominates.  Tolerated-absent in
  rounds that predate the lineage plane (and in runs with the plane
  disarmed, which emit nulls) — same protocol as ``serving_update_p50``,
  no fabricated zeros.

  ``backtest_champion_smape`` / ``backtest_champion_mase`` are the
  repo's first ACCURACY gates (ISSUE 13): the bench's ``backtest_demo``
  sweeps a pinned, seeded synthetic panel (known AR(1) / ARMA(1,1) /
  SES generators) through ``backtest_panel``'s candidate grid and
  reports the panel-mean out-of-sample error of each series' champion
  model.  Higher is a regression: a change to the fit math, the origin
  replay, or champion selection that degrades forecast quality now
  fails the gate even when every throughput metric improves — quality
  is gated, not just speed.  The demo is deterministic per platform, so
  both thresholds trip on real modeling changes rather than noise;
  tolerated-absent in rounds that predate the tier.

  ``serving_live_smape`` / ``drift_false_alarms`` are the live
  forecast-quality plane's gates (ISSUE 15): bench's quality demo
  streams a quality-armed ``ServingSession`` over a stationary slice of
  the seeded panel and reports the EW online sMAPE
  (higher-is-regression: the ONLINE accuracy surface now fails the gate
  if the fused tick-path scoring — or the serving math underneath it —
  degrades) and the drift-alarm count, zero-baselined in the house
  style: the demo stream is stationary by construction, so ANY alarm is
  a false positive and the first alarming round is flagged against an
  all-zero history (the Page-Hinkley calibration regression the
  quality tier exists to prevent).  Both tolerated-absent in rounds
  that predate the quality tier.

- prints a pass/fail table with signed percentage deltas (``--json``
  emits the same verdict as machine-readable JSON for CI, exit codes
  unchanged) and exits 1 on any regression, 0 otherwise.  A newest round that crashed (``rc != 0``)
  or carries no measured headline value fails outright — a broken bench
  is the regression, not a reason to skip.  Fewer than ``--min-history``
  comparable prior rounds passes with an ``insufficient history`` note
  (``--strict`` turns that into a failure) — a fresh repo must not be
  red by default.

Thresholds: throughput/fit wall default 25%, compile metrics 50%
(compiles are coarser-grained and noisier); ``--threshold PCT``
overrides all four, ``BENCH_GATE_THRESHOLD`` likewise from the
environment.  Rounds whose artifacts predate a metric (the metrics
block landed in PR 1) simply don't contribute baseline samples for it.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
import sys
from typing import Any, Dict, List, Optional

DEFAULT_GLOB = "BENCH_r*.json"

#                 name            direction      default threshold %
METRICS = [
    ("throughput", "higher_better", 25.0),
    ("fit_wall_s", "lower_better", 25.0),
    ("compile_s_total", "lower_better", 50.0),
    ("jit_compiles", "lower_better", 50.0),
    ("engine_cache_misses", "lower_better", 50.0),
    ("engine_chunk_failures", "lower_better", 50.0),
    ("engine_dead_chunks", "lower_better", 50.0),
    ("serving_update_p50", "lower_better", 25.0),
    ("serving_update_p95", "lower_better", 25.0),
    ("serving_diverged_lanes", "lower_better", 50.0),
    ("resilience_auto_fallback_dead", "lower_better", 50.0),
    ("heal_p50", "lower_better", 50.0),
    ("long_obs_per_s", "higher_better", 25.0),
    ("incidents_written", "lower_better", 50.0),
    ("fleet_ticks_per_s", "higher_better", 25.0),
    ("fleet_shed_lanes", "lower_better", 50.0),
    ("fleet_pump_restarts", "lower_better", 50.0),
    ("fleet_checkpoint_failures", "lower_better", 50.0),
    ("fleet_e2e_p95_ms", "lower_better", 25.0),
    ("backtest_champion_smape", "lower_better", 25.0),
    ("backtest_champion_mase", "lower_better", 25.0),
    ("serving_live_smape", "lower_better", 25.0),
    ("drift_false_alarms", "lower_better", 50.0),
    ("engine_host_overhead_frac", "lower_better", 25.0),
    ("lint_findings", "lower_better", 50.0),
    ("contracts_failed", "lower_better", 50.0),
    ("pipeline_programs", "lower_better", 50.0),
    ("host_transfer_bytes_per_chunk", "lower_better", 25.0),
    ("fused_ab_rate", "higher_better", 25.0),
    ("staged_ab_rate", "higher_better", 25.0),
]


def _round_number(path: str) -> int:
    m = re.search(r"r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else -1


def _headline_from_lines(text: str) -> Optional[dict]:
    """Last parseable JSON object line — bench.py's contract is that
    consumers read the LAST line (earlier lines are partial records)."""
    headline = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            headline = obj
    return headline


def load_round(path: str) -> Dict[str, Any]:
    """One round's ``{"round", "rc", "headline"}`` from either artifact
    shape (driver wrapper or raw JSON-lines dump)."""
    with open(path) as f:
        text = f.read()
    rc: Optional[int] = None
    headline: Optional[dict] = None
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict) and ("parsed" in obj or "tail" in obj):
        rc = obj.get("rc")
        headline = obj.get("parsed")
        if headline is None and isinstance(obj.get("tail"), str):
            headline = _headline_from_lines(obj["tail"])
    elif isinstance(obj, dict) and "metric" in obj:
        headline = obj
    else:
        headline = _headline_from_lines(text)
    return {"round": _round_number(path), "path": path, "rc": rc,
            "headline": headline}


def load_history(directory: str, pattern: str = DEFAULT_GLOB
                 ) -> List[Dict[str, Any]]:
    paths = sorted(glob.glob(os.path.join(directory, pattern)),
                   key=_round_number)
    return [load_round(p) for p in paths]


def _leaf_span(spans: Dict[str, Any], leaf: str) -> Optional[dict]:
    """The span entry whose path ends at ``leaf`` (exact key, or nested
    ``".../<leaf>"``); ties go to the highest count."""
    best = None
    for key, val in spans.items():
        if key != leaf and not key.endswith("/" + leaf):
            continue
        if isinstance(val, dict) and val.get("count"):
            if best is None or val["count"] > best["count"]:
                best = val
    return best


def extract_metrics(headline: Optional[dict]) -> Dict[str, float]:
    """The gated metric values present in one headline record.  Absent
    sources (pre-PR-1 artifacts without a metrics block) are simply
    missing keys — never fabricated zeros."""
    out: Dict[str, float] = {}
    if not isinstance(headline, dict):
        return out
    if isinstance(headline.get("value"), (int, float)):
        out["throughput"] = float(headline["value"])
    # ultra-long tier throughput: absent in rounds that predate the
    # long_demo block (no fabricated zeros), like serving_update_*
    ld = headline.get("long_demo")
    if isinstance(ld, dict) and isinstance(ld.get("obs_per_s"),
                                           (int, float)):
        out["long_obs_per_s"] = float(ld["obs_per_s"])
    # fleet tier (ISSUE 12): aggregate coalesced lane-tick throughput
    # across the many-session fleet demo (higher-better) and its shed
    # counter — a block present with shed_lanes absent is a measured 0
    # (the zero-baseline rule: a bench fleet must not shed under its
    # own nominal load), and both are tolerated-absent in rounds that
    # predate the fleet tier
    fd = headline.get("fleet_demo")
    if isinstance(fd, dict):
        if isinstance(fd.get("fleet_ticks_per_s"), (int, float)):
            out["fleet_ticks_per_s"] = float(fd["fleet_ticks_per_s"])
        # lineage plane (ISSUE 18): end-to-end submit→delivery p95 from
        # the tick-lineage ring.  Present-and-numeric only — a disarmed
        # plane emits null and pre-lineage rounds omit the key, and
        # neither contributes a baseline sample (no fabricated zeros).
        if isinstance(fd.get("fleet_e2e_p95_ms"), (int, float)):
            out["fleet_e2e_p95_ms"] = float(fd["fleet_e2e_p95_ms"])
        if "error" not in fd:
            v = fd.get("shed_lanes", 0)
            if isinstance(v, (int, float)):
                out["fleet_shed_lanes"] = float(v)
            # runtime supervision gates (ISSUE 17): a healthy bench
            # fleet restarts its pump zero times and tears zero
            # checkpoints — block present + key absent = measured 0
            # (pre-runtime rounds emit no fleet block keys at all)
            for src, dst in (("pump_restarts", "fleet_pump_restarts"),
                             ("checkpoint_failures",
                              "fleet_checkpoint_failures")):
                v = fd.get(src, 0)
                if isinstance(v, (int, float)):
                    out[dst] = float(v)
    # backtest tier (ISSUE 13): the first accuracy (not throughput)
    # gates — panel-mean champion out-of-sample error on the pinned
    # synthetic demo panel, higher-is-regression; tolerated-absent in
    # rounds that predate the tier (no fabricated zeros)
    bt = headline.get("backtest_demo")
    if isinstance(bt, dict):
        for key, name in (("champion_smape", "backtest_champion_smape"),
                          ("champion_mase", "backtest_champion_mase")):
            v = bt.get(key)
            if isinstance(v, (int, float)):
                out[name] = float(v)
    # forecast-quality plane (ISSUE 15): the ONLINE accuracy gate
    # (EW sMAPE of the quality demo's stationary stream, higher-is-
    # regression) and the drift false-alarm counter — a quality block
    # present with drift_alarms absent is a measured 0 (the zero-
    # baseline rule: a stationary stream must never alarm); both
    # tolerated-absent in rounds that predate the quality tier
    sd = headline.get("serving_demo")
    if isinstance(sd, dict) and "error" not in sd:
        q = sd.get("quality")
        if isinstance(q, dict) and "error" not in q:
            v = q.get("live_smape")
            if isinstance(v, (int, float)):
                out["serving_live_smape"] = float(v)
            v = q.get("drift_alarms", 0)
            if isinstance(v, (int, float)):
                out["drift_false_alarms"] = float(v)
    # attribution plane (ISSUE 16): the headline point's measured
    # host-overhead fraction — host-side phase seconds (prep, pad,
    # dispatch, reattach, commit) over the stream's wall, from
    # stream_fit's per-chunk phase accounting.  Lower-better: a rising
    # fraction means the interpretive boundary crossings (the Flare
    # cost) grew even if throughput hasn't caught it yet.  Tolerated-
    # absent in rounds that predate the attribution plane — same
    # protocol as serving_update_p50, no fabricated zeros.
    ea = headline.get("engine_attribution")
    if isinstance(ea, dict) \
            and isinstance(ea.get("host_overhead_frac"), (int, float)):
        out["engine_host_overhead_frac"] = \
            float(ea["host_overhead_frac"])
    # fused vs staged A/B (ISSUE 20): both publish paths through the
    # one cached executable, timed at a FIXED panel point (8192 or
    # n_target, whichever is smaller) so the comparison is stable even
    # when best_n moves.  The staged oracle path is no longer the
    # headline, so without its own gate it could rot silently.
    # Tolerated-absent in rounds that predate the fusion PR.
    ab = headline.get("fused_vs_staged")
    if isinstance(ab, dict):
        for key, name in (("fused", "fused_ab_rate"),
                          ("staged", "staged_ab_rate")):
            side = ab.get(key)
            if isinstance(side, dict) \
                    and isinstance(side.get("rate"), (int, float)):
                out[name] = float(side["rate"])
    m = headline.get("metrics")
    if isinstance(m, dict):
        spans = m.get("spans")
        if isinstance(spans, dict):
            fit = spans.get("bench.fit_panel")
            if isinstance(fit, dict) and fit.get("count"):
                out["fit_wall_s"] = float(fit.get("p50_s",
                                                  fit.get("mean_s", 0.0)))
            # per-tick serving latency SLO: the serving.update span is
            # one warmed Kalman step incl. materialization; absent in
            # rounds that predate the serving tier (no fabricated zeros).
            # Spans nest under their enclosing scope ("a/b/serving.update"
            # when bench drives the session), so match by path leaf —
            # the busiest entry when several scopes ticked sessions.
            upd = _leaf_span(spans, "serving.update")
            if isinstance(upd, dict) and upd.get("count"):
                if isinstance(upd.get("p50_s"), (int, float)):
                    out["serving_update_p50"] = float(upd["p50_s"])
                if isinstance(upd.get("p95_s"), (int, float)):
                    out["serving_update_p95"] = float(upd["p95_s"])
            # heal latency: tolerated-absent — rounds that never healed
            # (or predate healing) contribute no baseline sample
            heal = _leaf_span(spans, "serving.heal")
            if isinstance(heal, dict) and heal.get("count") \
                    and isinstance(heal.get("p50_s"), (int, float)):
                out["heal_p50"] = float(heal["p50_s"])
        if isinstance(m.get("compile_s_total"), (int, float)):
            out["compile_s_total"] = float(m["compile_s_total"])
        if isinstance(m.get("jit_compiles"), (int, float)):
            out["jit_compiles"] = float(m["jit_compiles"])
        eng = m.get("engine")
        if isinstance(eng, dict):
            if isinstance(eng.get("engine.cache_misses"), (int, float)):
                out["engine_cache_misses"] = \
                    float(eng["engine.cache_misses"])
            # reliability counters: an engine block without the key means
            # the stream ran clean (counters materialize on first
            # increment), so 0 here is a measurement, not a fabrication —
            # it seeds the zero baseline that flags the first failing
            # round
            for key, name in (("engine.chunk_failures",
                               "engine_chunk_failures"),
                              ("engine.dead_chunks",
                               "engine_dead_chunks")):
                v = eng.get(key, 0)
                if isinstance(v, (int, float)):
                    out[name] = float(v)
        # self-healing reliability counters (ISSUE 9), zero-baselined
        # like the engine's: block present + key absent = a measured 0
        sv = m.get("serving")
        if isinstance(sv, dict):
            v = sv.get("serving.diverged", 0)
            if isinstance(v, (int, float)):
                out["serving_diverged_lanes"] = float(v)
        fc = m.get("fit_counters")
        if isinstance(fc, dict):
            v = fc.get("resilience.auto_fallback_dead", 0)
            if isinstance(v, (int, float)):
                out["resilience_auto_fallback_dead"] = float(v)
        # flight-recorder counter (ISSUE 10), zero-baselined like the
        # engine's reliability counters: a telemetry block present with
        # the key absent means the round wrote no incident bundles — a
        # measured 0 that seeds the baseline, so the first round where
        # a bench run organically crashes (deadline expiries, dead
        # chunks, stream exceptions) is flagged even though a 0
        # baseline admits no percentage.  Absent in pre-telemetry
        # rounds — no fabricated zeros.
        tel = m.get("telemetry")
        if isinstance(tel, dict):
            v = tel.get("incidents_written", 0)
            if isinstance(v, (int, float)):
                out["incidents_written"] = float(v)
        # static-analysis gates (ISSUE 14), zero-baselined in the house
        # style: the static_analysis block landed in PR 4 and is
        # embedded in every record since — block present with the
        # findings key absent means lint ran clean (bench only records
        # error keys on failure), a measured 0.  Two non-measurements
        # must NOT read as clean zeros: a lint_error/contracts_error
        # key (the sub-check CRASHED) and contracts_checked == 0 (the
        # sweep was skipped via BENCH_CONTRACT_FAMILIES="" — bench
        # writes 0/0 then, which is absence of evidence, not evidence).
        sa = m.get("static_analysis")
        if isinstance(sa, dict):
            if "lint_error" not in sa:
                v = sa.get("findings", 0)
                if isinstance(v, (int, float)):
                    out["lint_findings"] = float(v)
            checked = sa.get("contracts_checked", 0)
            if "contracts_error" not in sa \
                    and isinstance(checked, (int, float)) and checked > 0:
                v = sa.get("contracts_failed", 0)
                if isinstance(v, (int, float)):
                    out["contracts_failed"] = float(v)
            # boundary sub-block (PR 19): absent or crashed → no keys,
            # same absence-of-evidence rule as lint/contracts above.
            b = sa.get("boundary")
            if isinstance(b, dict) and "boundary_error" not in sa:
                for src, dst in (
                        ("pipeline_programs", "pipeline_programs"),
                        ("host_transfer_bytes_per_chunk",
                         "host_transfer_bytes_per_chunk")):
                    v = b.get(src)
                    if isinstance(v, (int, float)):
                        out[dst] = float(v)
    return out


def comparable(r: Dict[str, Any], platform) -> bool:
    h = r["headline"]
    return (isinstance(h, dict)
            and isinstance(h.get("value"), (int, float))
            and r.get("rc") in (0, None)
            and h.get("platform") == platform)


def evaluate(history: List[Dict[str, Any]], *, window: int = 4,
             min_history: int = 2,
             threshold_override: Optional[float] = None
             ) -> Dict[str, Any]:
    """Compare the newest round against the trailing median of its
    comparable predecessors.  Returns the verdict structure the CLI
    renders; ``status`` is ``"pass"``, ``"regressed"``, or
    ``"insufficient-history"``."""
    if not history:
        return {"status": "insufficient-history", "rows": [],
                "note": "no BENCH_r*.json rounds found"}
    newest = history[-1]
    h = newest["headline"]
    # a crashed or valueless newest round is itself the regression the
    # gate exists to catch — it must never slide through as "nothing to
    # compare" (bench.py emits value=null when the first fit dies)
    if newest.get("rc") not in (0, None) \
            or not isinstance(h, dict) \
            or not isinstance(h.get("value"), (int, float)):
        return {"status": "regressed", "rows": [],
                "round": newest["round"],
                "note": f"newest round r{newest['round']:02d} crashed or "
                        f"has no measured headline value "
                        f"(rc={newest.get('rc')})"}
    platform = h.get("platform")
    prior = [r for r in history[:-1] if comparable(r, platform)]
    if len(prior) < min_history:
        return {"status": "insufficient-history", "rows": [],
                "note": f"{len(prior)} comparable prior round(s) on "
                        f"platform {platform!r}, need {min_history}"}
    baseline_rounds = prior[-window:]
    new_vals = extract_metrics(h)
    base_metrics = [extract_metrics(r["headline"]) for r in baseline_rounds]

    rows = []
    regressed = False
    for name, direction, default_thr in METRICS:
        thr = threshold_override if threshold_override is not None \
            else default_thr
        base_samples = [m[name] for m in base_metrics if name in m]
        row: Dict[str, Any] = {"metric": name, "threshold_pct": thr,
                               "n_baseline": len(base_samples)}
        if name not in new_vals:
            row.update(status="skipped", note="absent in newest round")
            rows.append(row)
            continue
        if len(base_samples) < min_history:
            row.update(status="skipped", value=new_vals[name],
                       note=f"{len(base_samples)} baseline sample(s), "
                            f"need {min_history}")
            rows.append(row)
            continue
        base = statistics.median(base_samples)
        value = new_vals[name]
        row.update(value=value, baseline=base)
        if base == 0:
            # a 0 baseline admits no percentage; only flag a lower-better
            # metric that became nonzero from an all-zero history
            worse = direction == "lower_better" and value > 0
            row["delta_pct"] = None
        else:
            delta = 100.0 * (value - base) / base
            row["delta_pct"] = round(delta, 1)
            worse = (delta < -thr if direction == "higher_better"
                     else delta > thr)
        row["status"] = "REGRESSED" if worse else "ok"
        regressed = regressed or worse
        rows.append(row)
    return {"status": "regressed" if regressed else "pass",
            "rows": rows, "round": newest["round"], "platform": platform,
            "baseline_rounds": [r["round"] for r in baseline_rounds]}


def render(verdict: Dict[str, Any]) -> str:
    lines = []
    if verdict["status"] == "insufficient-history":
        lines.append(f"bench gate: PASS (insufficient history: "
                     f"{verdict['note']})")
        return "\n".join(lines)
    if verdict["status"] == "regressed" and not verdict["rows"]:
        lines.append(f"bench gate: REGRESSED ({verdict['note']})")
        return "\n".join(lines)
    lines.append(f"bench gate: round r{verdict['round']:02d} "
                 f"(platform={verdict['platform']}) vs median of rounds "
                 f"{['r%02d' % r for r in verdict['baseline_rounds']]}")
    hdr = (f"{'metric':<22} {'newest':>12} {'baseline':>12} "
           f"{'delta%':>8} {'thr%':>6}  status")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for row in verdict["rows"]:
        if row["status"] == "skipped":
            lines.append(f"{row['metric']:<22} {'-':>12} {'-':>12} "
                         f"{'-':>8} {row['threshold_pct']:>6.0f}  "
                         f"skipped ({row['note']})")
            continue
        delta = row.get("delta_pct")
        lines.append(
            f"{row['metric']:<22} {row['value']:>12.2f} "
            f"{row['baseline']:>12.2f} "
            f"{('%+.1f' % delta) if delta is not None else '-':>8} "
            f"{row['threshold_pct']:>6.0f}  {row['status']}")
    lines.append(f"bench gate: {verdict['status'].upper()}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=(
        "Gate the newest BENCH_r*.json round against the trailing median "
        "of comparable prior rounds; exit 1 on regression."))
    ap.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), help="directory holding BENCH_r*.json "
        "(default: repo root)")
    ap.add_argument("--glob", default=DEFAULT_GLOB,
                    help=f"artifact glob (default {DEFAULT_GLOB})")
    ap.add_argument("--window", type=int, default=4,
                    help="trailing rounds in the baseline median (default 4)")
    ap.add_argument("--min-history", type=int, default=2,
                    help="comparable prior rounds required before gating "
                         "(default 2)")
    ap.add_argument("--threshold", type=float,
                    default=(float(os.environ["BENCH_GATE_THRESHOLD"])
                             if os.environ.get("BENCH_GATE_THRESHOLD")
                             else None),
                    help="override every metric's regression threshold "
                         "(percent; default: per-metric 25/25/50/50)")
    ap.add_argument("--strict", action="store_true",
                    help="fail (exit 2) on insufficient history instead of "
                         "passing")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict as machine-readable JSON "
                         "instead of the table (CI consumption; exit "
                         "codes unchanged, and the payload carries them "
                         "as 'exit_code')")
    args = ap.parse_args(argv)

    history = load_history(args.dir, args.glob)
    verdict = evaluate(history, window=args.window,
                       min_history=args.min_history,
                       threshold_override=args.threshold)
    if verdict["status"] == "regressed":
        code = 1
    elif verdict["status"] == "insufficient-history" and args.strict:
        code = 2
    else:
        code = 0
    if args.json:
        print(json.dumps(dict(verdict, exit_code=code), indent=2,
                         sort_keys=True))
    else:
        print(render(verdict))
    return code


if __name__ == "__main__":
    sys.exit(main())
