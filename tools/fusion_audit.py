"""fusion-audit: the machine-readable evidence base for ROADMAP item 1.

Whole-pipeline fusion (Flare, arXiv 1703.08219) only pays where the
orchestration between compiled programs actually spends time.  This tool
joins the three planes that know:

- **lint** (level 1): the STS200 host-boundary tier's findings, in
  particular the STS205 advice inventory — every
  compiled-call → host transform → compiled-call chain in the hot-path
  modules (``tools/sts_lint``);
- **contracts** (level 2): :func:`pipeline_contracts` — measured
  programs-per-stage against the budget table and device→host bytes
  per warmed chunk (``spark_timeseries_tpu.utils.contracts``);
- **attribution** (runtime): per-span *self* time from the newest
  comparable ``BENCH_r*.json`` round (the PR 17 attribution plane),
  used to rank the STS205 chains by how much wall the host work
  between their dispatches actually burns.

Output is one JSON document (``--json``, default ``-`` = stdout):
``chains`` ranked by span self-time, the ``boundary`` contract block,
and the lint summary.  ``make fusion-audit`` writes
``FUSION_AUDIT.json``; the fusion PR consumes it and claws back
against the pinned baseline.

Exit code is 0 unless a *gating* STS200 finding or a boundary contract
failure surfaces — the audit is an inventory, but it refuses to bless a
tree the gate itself would fail.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# which span-name prefixes carry a hot-path module's runtime (the span
# taxonomy is per-tier, the lint model is per-file)
_MODULE_SPAN_PREFIXES: Dict[str, tuple] = {
    "engine": ("engine.",),
    "serving": ("serving.",),
    "fleet": ("fleet.",),
    "runtime": ("fleet.", "runtime."),
    "kalman": ("serving.", "backtest."),
    "combine": ("long.",),
    "segment": ("long.",),
    "evaluate": ("backtest.",),
}

_CHAIN_COUNTS_RE = re.compile(
    r"\((\d+) dispatch, (\d+) host-materialize")


def span_self_times(spans: Dict[str, Any]) -> Dict[str, float]:
    """Per-leaf *self* seconds aggregated over every nested span path:
    a path's self time is its total minus its immediate children's
    totals (the attribution plane's oracle, recomputed from the bench
    artifact's span stats)."""
    totals = {k: float(v.get("total_s", 0.0))
              for k, v in spans.items() if isinstance(v, dict)}
    child_sum: Dict[str, float] = {}
    for k, t in totals.items():
        if "/" in k:
            parent = k.rsplit("/", 1)[0]
            child_sum[parent] = child_sum.get(parent, 0.0) + t
    out: Dict[str, float] = {}
    for k, t in totals.items():
        leaf = k.rsplit("/", 1)[-1]
        self_s = max(0.0, t - child_sum.get(k, 0.0))
        out[leaf] = out.get(leaf, 0.0) + self_s
    return out


def newest_round_spans(directory: str = _REPO
                       ) -> tuple:
    """``(spans, round_path)`` from the newest bench round that has a
    metrics block; ``({}, None)`` when no artifact qualifies."""
    from tools.bench_gate import load_history
    for rnd in reversed(load_history(directory)):
        h = rnd.get("headline")
        if not isinstance(h, dict):
            continue
        spans = (h.get("metrics") or {}).get("spans")
        if isinstance(spans, dict) and spans:
            return spans, rnd["path"]
    return {}, None


def _modbase(path: str) -> str:
    name = os.path.basename(path)
    return name[:-3] if name.endswith(".py") else name


def rank_chains(findings: List[Any], self_times: Dict[str, float]
                ) -> List[Dict[str, Any]]:
    """STS205 findings → chain records ranked by the self time of the
    spans their module's runtime books (descending; chains with no span
    evidence rank by dispatch count at the bottom)."""
    chains = []
    for f in findings:
        base = _modbase(f.path)
        prefixes = _MODULE_SPAN_PREFIXES.get(base, (base + ".",))
        span_hits = {leaf: round(s, 6)
                     for leaf, s in self_times.items()
                     if any(leaf.startswith(p) for p in prefixes)}
        mo = _CHAIN_COUNTS_RE.search(f.message)
        dispatches, mats = (int(mo.group(1)), int(mo.group(2))) \
            if mo else (0, 0)
        chains.append({
            "module": f.path,
            "symbol": f.symbol,
            "line": f.line,
            "dispatch_sites": dispatches,
            "materialize_sites": mats,
            "span_self_s": round(sum(span_hits.values()), 6),
            "spans": dict(sorted(span_hits.items(),
                                 key=lambda kv: -kv[1])[:6]),
        })
    chains.sort(key=lambda c: (-c["span_self_s"], -c["dispatch_sites"]))
    return chains


def run_audit(paths: Optional[List[str]] = None,
              with_contracts: bool = True,
              bench_dir: str = _REPO) -> Dict[str, Any]:
    from tools.sts_lint import (DEFAULT_BASELINE, HOST_BOUNDARY_RULES,
                                lint_paths, load_baseline)

    result, _src = lint_paths(
        paths or [os.path.join(_REPO, "spark_timeseries_tpu")],
        root=_REPO, baseline=load_baseline(DEFAULT_BASELINE),
        select=list(HOST_BOUNDARY_RULES))
    spans, round_path = newest_round_spans(bench_dir)
    self_times = span_self_times(spans)
    chains = rank_chains(result.advice, self_times)

    boundary: Dict[str, Any] = {}
    if with_contracts:
        from spark_timeseries_tpu.utils.contracts import \
            pipeline_contracts
        try:
            boundary = pipeline_contracts()
        except Exception as e:  # noqa: BLE001 — report, don't crash
            boundary = {"error": f"{type(e).__name__}: {e}"}

    gating = [f.to_json() for f in result.new]
    return {
        "version": 1,
        "tool": "fusion-audit",
        "bench_round": round_path,
        "lint": {
            "summary": result.summary(),
            "gating_findings": gating,
        },
        "chains": chains,
        "boundary": boundary,
        "ok": (not gating
               and not boundary.get("error")
               and not boundary.get("boundary_failed", 0)),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.fusion_audit",
        description="Host-boundary fusion audit: STS205 chain inventory "
                    "ranked by span self-time + pipeline program/"
                    "transfer contracts (ROADMAP item 1 evidence base).")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint "
                         "(default: spark_timeseries_tpu)")
    ap.add_argument("--json", dest="json_out", default="-",
                    help="write the JSON report here (default '-' = "
                         "stdout)")
    ap.add_argument("--no-contracts", action="store_true",
                    help="skip pipeline_contracts() (lint + span "
                         "ranking only; no compiles)")
    ap.add_argument("--bench-dir", default=_REPO,
                    help="directory holding BENCH_r*.json artifacts")
    args = ap.parse_args(argv)

    report = run_audit(args.paths or None,
                       with_contracts=not args.no_contracts,
                       bench_dir=args.bench_dir)

    human = sys.stderr if args.json_out == "-" else sys.stdout
    print(f"fusion-audit: {len(report['chains'])} STS205 chain(s), "
          f"{len(report['lint']['gating_findings'])} gating finding(s), "
          f"bench round: {report['bench_round'] or 'none'}", file=human)
    for c in report["chains"]:
        print(f"  {c['span_self_s']:9.3f}s  {c['module']}:{c['line']} "
              f"{c['symbol']} ({c['dispatch_sites']} dispatch / "
              f"{c['materialize_sites']} materialize)", file=human)
    b = report["boundary"]
    if b.get("error"):
        print(f"  boundary contracts ERROR: {b['error']}", file=human)
    elif b:
        print(f"  boundary: {b['pipeline_programs']} pipeline "
              f"program(s), {b['host_transfer_bytes_per_chunk']} "
              f"B/chunk, {b['unexpected_transfer_bytes']:+d} B "
              f"unsanctioned, {b['boundary_failed']} contract "
              f"failure(s)", file=human)

    payload = json.dumps(report, indent=1)
    if args.json_out == "-":
        print(payload)
    else:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            fh.write(payload + "\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
