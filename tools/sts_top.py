"""sts_top: a terminal dashboard over a live telemetry endpoint.

``python -m tools.sts_top http://127.0.0.1:<port>`` tails the
exporter's ``/snapshot.json`` (``utils.telemetry``; armed with
``STS_TELEMETRY_PORT`` or ``telemetry.start()``) and renders, curses-free
(plain ANSI, any terminal or a CI log):

- **jobs**: per-``stream_fit`` progress — chunks done/total, failures/
  quarantines/degradations, journal commits, EW throughput, ETA, and
  the heartbeat age (with a ``STALE`` flag past the staleness
  threshold, the same contract ``/healthz`` serves);
- **serving**: per-session lane health and the rolling tick-latency
  window — p50/p95 ms, SLO burns against ``STS_SERVING_SLO_MS``,
  quarantined lanes;
- **quality**: the live forecast-quality plane per quality-armed
  session/tenant — EW online sMAPE/MASE/coverage, the lane-anomaly
  p95, drifted lanes and drift alarms (``statespace.quality``);
- **fleet**: per-scheduler admission/coalescing/shed state — tenants
  (live vs shed, queue depth, admitted/rejected/dropped, cache
  serves) under the aggregate p95 and SLO burn count;
- **attribution**: the performance attribution plane (docs/design.md
  §6g) — top span self-times with per-subsystem rollups, and the
  streaming engine's host-overhead fraction / device-idle bubble;
- **e2e**: the tick lineage plane (docs/design.md §6h) — per-tenant
  end-to-end p50/p95 with worst-stage attribution, pooled stage
  shares, exactly-once counters, and the slowest tick's full stage
  timeline;
- **incidents**: the flight recorder's newest bundles (kind, age,
  size) so a crash's forensics are one glance away.

``--once`` prints a single frame and exits (scripts/CI); the default
loop redraws every ``--interval`` seconds (default 2.0; junk or a
non-positive value is rejected up front) until Ctrl-C.  ``--sort``
orders the JOBS panel by ``eta`` (soonest-finishing first, the
default), ``hb-age`` (stalest heartbeat first), or ``fails`` (most
failed chunks first); an unknown key is rejected up front, named, like
a bad ``--interval``.  Rendering is
pure (``render_snapshot(dict) -> str``) and **version-tolerant**: a
snapshot from an older exporter (no ``fleets`` section, no per-session
``quality`` block) or with junk entries renders with the missing panels
marked absent instead of KeyError-ing the dashboard — the scraper must
never be newer-or-older than the process it watches.  Tests drive it
without a server.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

CLEAR = "\x1b[2J\x1b[H"


def fetch_snapshot(url: str, timeout: float = 5.0) -> Dict[str, Any]:
    """GET ``<url>/snapshot.json`` (a bare host:port URL is enough)."""
    base = url.rstrip("/")
    if not base.startswith("http"):
        base = "http://" + base
    with urllib.request.urlopen(base + "/snapshot.json",
                                timeout=timeout) as resp:
        return json.load(resp)


def _fmt_eta(seconds: Optional[float]) -> str:
    if seconds is None:
        return "-"
    s = int(seconds)
    if s >= 3600:
        return f"{s // 3600}h{(s % 3600) // 60:02d}m"
    if s >= 60:
        return f"{s // 60}m{s % 60:02d}s"
    return f"{s}s"


def _fmt_age(seconds: Optional[float]) -> str:
    return "-" if seconds is None else _fmt_eta(seconds)


def _table(headers: List[str], rows: List[List[str]]) -> List[str]:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))
    out = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    out.append("  ".join("-" * w for w in widths))
    for row in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    return out


# JOBS panel orderings (--sort): each key maps a job dict to a sort
# tuple; jobs missing the field sort last (a None ETA is "unknown", not
# "imminent")
JOB_SORTS: Dict[str, Any] = {
    "eta": lambda j: (not isinstance(j.get("eta_s"), (int, float)),
                      j.get("eta_s") or 0.0),
    "hb-age": lambda j: (
        not isinstance(j.get("heartbeat_age_s"), (int, float)),
        -(j.get("heartbeat_age_s") or 0.0)),
    "fails": lambda j: (
        not isinstance(j.get("chunks_failed"), (int, float)),
        -(j.get("chunks_failed") or 0)),
}


def _job_rows(jobs: List[Dict[str, Any]]) -> List[List[str]]:
    rows = []
    for j in jobs:
        stale = j.get("heartbeat_age_s") is not None \
            and j.get("stale_after_s") is not None \
            and j.get("status") == "running" \
            and j["heartbeat_age_s"] > j["stale_after_s"]
        status = j.get("status", "?")
        if stale:
            status = "STALE"
        thr = j.get("throughput_series_per_s")
        rows.append([
            str(j.get("job_id", "?")),
            str(j.get("family", "?")),
            f"{j.get('chunks_done', 0)}/{j.get('chunks_total', '?')}",
            str(j.get("chunks_failed", 0)),
            str(j.get("chunks_quarantined", 0)),
            str(j.get("chunks_degraded", 0)),
            str(j.get("journal_commits", 0)),
            f"{thr:.0f}/s" if isinstance(thr, (int, float)) else "-",
            _fmt_eta(j.get("eta_s")),
            _fmt_age(j.get("heartbeat_age_s")),
            f"{j.get('heartbeat_stage', '-')}",
            status,
        ])
    return rows


def _dicts(seq: Any) -> List[Dict[str, Any]]:
    """Only the dict entries of a snapshot list — a junk or None entry
    (truncated scrape, older exporter) must not KeyError the frame."""
    return [x for x in (seq or []) if isinstance(x, dict)]


def _fmt_num(v: Any, fmt: str = "{:.2f}") -> str:
    return fmt.format(v) if isinstance(v, (int, float)) else "-"


def _quality_rows(sessions: List[Dict[str, Any]]) -> List[List[str]]:
    """One row per quality-armed session (sessions without a ``quality``
    block — quality off, or an older exporter — simply don't appear)."""
    rows = []
    for s in sessions:
        q = s.get("quality")
        if not isinstance(q, dict):
            continue
        rows.append([
            str(s.get("label", "?")),
            str(q.get("horizon", "?")),
            str(q.get("scored_lanes", "-")),
            _fmt_num(q.get("live_smape")),
            _fmt_num(q.get("live_mase"), "{:.3f}"),
            _fmt_num(q.get("live_coverage"), "{:.3f}"),
            _fmt_num(q.get("anomaly_p95"), "{:.3f}"),
            str(q.get("drifted_lanes", 0)),
            str(q.get("drift_alarms", 0)),
        ])
    return rows


def _serving_rows(sessions: List[Dict[str, Any]]) -> List[List[str]]:
    rows = []
    for s in sessions:
        if "error" in s and "label" not in s:
            rows.append(["?", "?", "-", "-", "-", "-", "-", "-",
                         s["error"][:40]])
            continue
        health = s.get("health") or {}
        hstr = " ".join(f"{k}:{v}" for k, v in sorted(health.items())) \
            or "-"
        p50 = s.get("tick_p50_ms")
        p95 = s.get("tick_p95_ms")
        rows.append([
            str(s.get("label", "?")),
            str(s.get("family", "?")),
            str(s.get("n_series", "?")),
            str(s.get("ticks_seen", "?")),
            f"{p50:.3f}" if isinstance(p50, (int, float)) else "-",
            f"{p95:.3f}" if isinstance(p95, (int, float)) else "-",
            str(s.get("slo_burns", 0)),
            str(s.get("quarantined_lanes", 0)),
            hstr,
        ])
    return rows


def _fleet_pump_line(pump: Dict[str, Any]) -> str:
    """Pump-liveness sub-line for one supervised fleet (absent on
    snapshots from pre-runtime exporters — the caller skips it)."""
    if "error" in pump and "runtime" not in pump:
        return f"  pump: (scrape error: {str(pump['error'])[:60]})"
    restarts = pump.get("restarts", 0)
    line = (f"  pump {pump.get('runtime', '?')}: "
            f"hb {_fmt_age(pump.get('heartbeat_age_s'))}  "
            f"restarts {restarts}  "
            f"waiters {pump.get('backpressure_waiters', 0)}  "
            f"ckpt-gen {pump.get('checkpoint_generation', 0)}")
    fails = pump.get("checkpoint_failures", 0)
    if fails:
        line += f"  ckpt-fail {fails}"
    if not pump.get("running", True):
        line += "  [STOPPED]"
    elif pump.get("stalled"):
        line += "  [STALLED]"
    return line


def _fleet_tenant_rows(rows: List[Dict[str, Any]],
                       queue_depth: Any = None) -> List[List[str]]:
    out = []
    for t in rows:
        health = t.get("health") or {}
        hstr = " ".join(f"{k}:{v}" for k, v in sorted(health.items())) \
            or "-"
        queued = t.get("queued", 0)
        # backpressure depth: fill over the bounded ingress queue
        # (pre-runtime exporters don't send queue_depth — show raw)
        qstr = f"{queued}/{queue_depth}" \
            if isinstance(queue_depth, int) and queue_depth > 0 \
            else str(queued)
        out.append([
            str(t.get("tenant", "?")),
            str(t.get("mode", "?")).upper(),
            str(t.get("n_series", "?")),
            qstr,
            str(t.get("admitted", 0)),
            str(t.get("rejected", 0)),
            str(t.get("dropped", 0)),
            str(t.get("cache_serves", 0)),
            hstr,
        ])
    return out


def _incident_rows(incidents: List[Dict[str, Any]],
                   now: float) -> List[List[str]]:
    rows = []
    for inc in incidents:
        if "error" in inc and "file" not in inc:
            rows.append(["?", "-", "-", inc["error"][:60]])
            continue
        t = inc.get("time_unix")
        age = _fmt_age(max(now - t, 0.0)) if isinstance(
            t, (int, float)) else "-"
        size = inc.get("bytes")
        rows.append([
            str(inc.get("kind", "?")),
            age,
            f"{size / 1024:.0f}K" if isinstance(size, (int, float))
            else "-",
            str(inc.get("file", "?")),
        ])
    return rows


def _attribution_lines(att: Any) -> List[str]:
    """The ATTRIBUTION panel body: top span self-times, the subsystem
    rollup, and the engine's host-overhead/bubble gauges.  Version-
    tolerant like every other panel — an older exporter (no
    ``attribution`` section) or a scrape-isolated error renders as a
    marked absence, never a KeyError."""
    if not isinstance(att, dict):
        return ["  (exporter predates the attribution plane)"]
    if "error" in att and "self_times" not in att:
        return [f"  (scrape error: {str(att['error'])[:60]})"]
    lines: List[str] = []
    st = att.get("self_times")
    spans = _dicts((st or {}).get("spans"))
    if spans:
        lines += _table(
            ["SPAN", "SELF-s", "TOTAL-s", "N"],
            [[str(s.get("name", "?")),
              _fmt_num(s.get("self_s"), "{:.3f}"),
              _fmt_num(s.get("dur_s"), "{:.3f}"),
              str(s.get("count", "-"))] for s in spans])
    else:
        lines.append("  (no spans in the trace ring)")
    subs = (st or {}).get("subsystems")
    if isinstance(subs, dict):
        lines.append("  subsystems: " + "  ".join(
            f"{k} {_fmt_num((v or {}).get('self_s'), '{:.3f}')}s"
            for k, v in sorted(subs.items())
            if isinstance(v, dict)))
    eng = att.get("engine")
    if isinstance(eng, dict) and eng:
        frac = eng.get("engine.host_overhead_frac")
        bub = eng.get("engine.bubble_ms_total")
        lines.append(
            f"  engine: host_overhead_frac "
            f"{_fmt_num(frac, '{:.3f}')}  "
            f"bubble {_fmt_num(bub, '{:.1f}')}ms")
    return lines


def _e2e_lines(lin: Any) -> List[str]:
    """The E2E panel body: per-tenant end-to-end latency percentiles
    with worst-stage attribution, the pooled stage shares, exactly-once
    counters, and the slowest exemplar's full stage timeline.  Version-
    tolerant like ATTRIBUTION — an older exporter (no ``lineage``
    section) or a scrape-isolated error renders as a marked absence."""
    if not isinstance(lin, dict):
        return ["  (exporter predates the lineage plane)"]
    if "error" in lin and "tenants" not in lin:
        return [f"  (scrape error: {str(lin['error'])[:60]})"]
    if lin.get("armed") is False:
        return ["  (lineage plane disarmed: STS_LINEAGE=0)"]
    lines: List[str] = []
    e2e = lin.get("e2e") or {}
    outcomes = lin.get("outcomes") or {}
    ring = lin.get("ring") or {}
    lines.append(
        f"  e2e p50 {_fmt_num(e2e.get('p50_ms'), '{:.3f}')}ms  "
        f"p95 {_fmt_num(e2e.get('p95_ms'), '{:.3f}')}ms  "
        f"delivered {outcomes.get('delivered', 0)}  "
        f"open {lin.get('open', '-')}  "
        f"dups {lin.get('duplicate_completions', 0)}  "
        f"ring {ring.get('len', '-')}/{ring.get('capacity', '-')}"
        f" (dropped {ring.get('dropped', 0)})")
    shares = lin.get("stage_totals_ms")
    if isinstance(shares, dict) and shares:
        total = sum(v for v in shares.values()
                    if isinstance(v, (int, float))) or 1.0
        lines.append("  stages: " + "  ".join(
            f"{k} {v / total:.0%}" for k, v in sorted(
                shares.items(), key=lambda kv: -kv[1])
            if isinstance(v, (int, float))))
    tenants = lin.get("tenants")
    rows = []
    if isinstance(tenants, dict):
        for label, td in sorted(tenants.items()):
            if not isinstance(td, dict):
                continue
            share = td.get("worst_stage_share")
            worst = td.get("worst_stage") or "-"
            rows.append([
                str(label),
                _fmt_num(td.get("p50_ms"), "{:.3f}"),
                _fmt_num(td.get("p95_ms"), "{:.3f}"),
                str(td.get("delivered", "-")),
                str(td.get("cache_serves", "-")),
                f"{worst} {share:.0%}" if isinstance(
                    share, (int, float)) else str(worst),
            ])
    if rows:
        lines += _table(
            ["TENANT", "P50ms", "P95ms", "TICKS", "CACHE", "WORST-STAGE"],
            rows)
    else:
        lines.append("  (no delivered ticks yet)")
    exemplars = _dicts(lin.get("exemplars"))
    if exemplars:
        ex = exemplars[0]
        stages = ex.get("stages")
        timeline = "  ".join(
            f"{k} {v:.2f}" for k, v in sorted(
                stages.items(), key=lambda kv: -kv[1])
            if isinstance(v, (int, float))) \
            if isinstance(stages, dict) else "-"
        det = ",".join(ex.get("detours") or []) or "-"
        lines.append(
            f"  slowest: #{ex.get('trace_id', '?')} "
            f"{ex.get('tenant', '?')} via={ex.get('via', '?')} "
            f"{_fmt_num(ex.get('e2e_ms'), '{:.3f}')}ms  "
            f"[{timeline}]  detours: {det}")
    return lines


def render_snapshot(snap: Dict[str, Any], job_sort: str = "eta") -> str:
    """One full frame from a ``/snapshot.json`` payload (pure).
    ``job_sort`` orders the JOBS panel (a key of :data:`JOB_SORTS`;
    unknown keys fall back to snapshot order rather than crashing the
    frame — the CLI validates before calling)."""
    now = snap.get("time_unix") or time.time()
    counters = (snap.get("registry") or {}).get("counters", {})
    jx = snap.get("jax") or {}
    lines = [
        f"sts_top — pid {snap.get('pid', '?')}  "
        f"uptime {_fmt_age(snap.get('uptime_s'))}  "
        f"scrapes {counters.get('telemetry.scrapes', 0)}  "
        f"jit_compiles {jx.get('jit_compiles', '-')}  "
        f"incidents {counters.get('incidents.written', 0)}",
        "",
    ]
    jobs = _dicts(snap.get("jobs"))
    recent = [j for j in _dicts(snap.get("recent_jobs"))
              if j.get("status") != "done" or j.get("chunks_failed")]
    lines.append(f"JOBS ({len(jobs)} active, sort={job_sort})")
    all_jobs = jobs + recent[-4:]
    key = JOB_SORTS.get(job_sort)
    if key is not None:
        all_jobs = sorted(all_jobs, key=key)
    if all_jobs:
        lines += _table(
            ["JOB", "FAMILY", "CHUNKS", "FAIL", "QUAR", "DEG", "JRNL",
             "RATE", "ETA", "HB-AGE", "STAGE", "STATUS"],
            _job_rows(all_jobs))
    else:
        lines.append("  (no active streaming jobs)")
    lines.append("")

    sessions = _dicts(snap.get("serving_sessions"))
    lines.append(f"SERVING ({len(sessions)} sessions)")
    if sessions:
        lines += _table(
            ["SESSION", "FAMILY", "SERIES", "TICKS", "P50ms", "P95ms",
             "SLO-BURN", "QUAR", "HEALTH"],
            _serving_rows(sessions))
    else:
        lines.append("  (no live serving sessions)")
    lines.append("")

    qrows = _quality_rows(sessions)
    lines.append(f"QUALITY ({len(qrows)} tracked sessions)")
    if qrows:
        lines += _table(
            ["SESSION", "H", "SCORED", "SMAPE", "MASE", "COVER",
             "ANOM-P95", "DRIFTED", "ALARMS"], qrows)
    else:
        lines.append("  (no quality-tracked sessions)")
    lines.append("")

    fleets = _dicts(snap.get("fleets"))
    lines.append(f"FLEET ({len(fleets)} schedulers)")
    if fleets:
        for fl in fleets:
            if "error" in fl and "label" not in fl:
                lines.append(f"  (scrape error: {fl['error'][:60]})")
                continue
            p95 = fl.get("p95_ms")
            p95s = f"{p95:.3f}ms" if isinstance(p95, (int, float)) \
                else "-"
            lines.append(
                f"  {fl.get('label', '?')}: "
                f"{fl.get('tenants', '?')} tenants / "
                f"{fl.get('groups', '?')} groups  "
                f"queued {fl.get('queued', 0)}  "
                f"shed {fl.get('shed_tenants', 0)}  p95 {p95s}  "
                f"slo_burns {fl.get('slo_burns', 0)}  "
                f"slo_ms {fl.get('slo_ms') or '-'}")
            pump = fl.get("pump")
            if isinstance(pump, dict):
                lines.append(_fleet_pump_line(pump))
            rows = _dicts(fl.get("tenant_rows"))
            if rows:
                lines += ["    " + ln for ln in _table(
                    ["TENANT", "MODE", "SERIES", "QUEUED", "ADM",
                     "REJ", "DROP", "CACHE", "HEALTH"],
                    _fleet_tenant_rows(rows, fl.get("queue_depth")))]
    else:
        lines.append("  (no live fleet schedulers)")
    lines.append("")

    lines.append("ATTRIBUTION (span self-time)")
    lines += _attribution_lines(snap.get("attribution"))
    lines.append("")

    lines.append("E2E (tick lineage)")
    lines += _e2e_lines(snap.get("lineage"))
    lines.append("")

    incidents = _dicts(snap.get("incidents"))
    dirname = snap.get("incident_dir")
    lines.append(f"INCIDENTS"
                 + (f" ({dirname})" if dirname else " (recorder off)"))
    if incidents:
        lines += _table(["KIND", "AGE", "SIZE", "FILE"],
                        _incident_rows(incidents, now))
    else:
        lines.append("  (none recorded)")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.sts_top",
        description="Tail a live telemetry endpoint's /snapshot.json and "
                    "render job progress, ETA, serving lane health, and "
                    "recent incidents.")
    ap.add_argument("url", help="exporter base URL, e.g. "
                               "http://127.0.0.1:8321 (the value of "
                               "telemetry.start().url)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds (default 2.0; must "
                         "be a positive number)")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit (scripts/CI)")
    ap.add_argument("--no-clear", action="store_true",
                    help="append frames instead of clearing the screen")
    ap.add_argument("--sort", default="eta",
                    help="JOBS panel order: eta (soonest-finishing "
                         "first; default), hb-age (stalest heartbeat "
                         "first), or fails (most failed chunks first)")
    args = ap.parse_args(argv)
    if not math.isfinite(args.interval) or args.interval <= 0:
        # a zero/negative/NaN interval would spin the scrape loop flat
        # out against the exporter — reject it up front, named
        ap.error(f"--interval must be a positive number of seconds, "
                 f"got {args.interval!r}")
    if args.sort not in JOB_SORTS:
        # same contract as --interval: junk is rejected up front, named,
        # not discovered as a silently-unsorted frame
        ap.error(f"--sort must be one of "
                 f"{', '.join(sorted(JOB_SORTS))}, got {args.sort!r}")

    while True:
        try:
            snap = fetch_snapshot(args.url)
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"sts_top: cannot scrape {args.url}: {e}",
                  file=sys.stderr)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        frame = render_snapshot(snap, job_sort=args.sort)
        if args.once:
            sys.stdout.write(frame)
            return 0
        sys.stdout.write(("" if args.no_clear else CLEAR) + frame)
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
