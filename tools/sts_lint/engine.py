"""sts-lint engine: file walking, suppression, baseline, reporting.

Finding lifecycle: a rule emits a raw finding; the engine then

1. drops it if the offending line carries a matching
   ``# sts: noqa[STS0xx]`` (bare ``# sts: noqa`` matches every code) —
   counted as *suppressed*;
2. matches it against the checked-in baseline — counted as *baselined*
   (the debt ledger: visible in the JSON report, not a failure);
3. otherwise it is *new* and the lint exits nonzero.

Baseline entries are line-number-independent fingerprints
(``code|relpath|symbol|hash(stripped line text)``) with per-fingerprint
counts, so unrelated edits above a baselined finding don't resurrect
it, while a new copy of an already-baselined pattern still fails.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .analysis import ModuleModel, Project
from .rules import RULES

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

_NOQA_RE = re.compile(r"#\s*sts:\s*noqa(?:\[([A-Z0-9,\s]+)\])?",
                      re.IGNORECASE)


@dataclass
class Finding:
    code: str
    path: str            # repo-relative, forward slashes
    line: int
    col: int
    symbol: str
    message: str
    status: str = "new"  # new | suppressed | baselined | advice

    def fingerprint(self, line_text: str) -> str:
        h = hashlib.sha1(line_text.strip().encode()).hexdigest()[:10]
        return f"{self.code}|{self.path}|{self.symbol}|{h}"

    def to_json(self) -> Dict:
        return {"code": self.code, "path": self.path, "line": self.line,
                "col": self.col, "symbol": self.symbol,
                "message": self.message, "status": self.status}

    def render(self) -> str:
        where = f" [in {self.symbol}]" if self.symbol else ""
        tag = " advice" if self.status == "advice" else ""
        return (f"{self.path}:{self.line}:{self.col + 1}: {self.code}"
                f"{tag} {self.message}{where}")


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    files_scanned: int = 0
    parse_errors: List[str] = field(default_factory=list)

    @property
    def new(self) -> List[Finding]:
        return [f for f in self.findings if f.status == "new"]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.status == "suppressed"]

    @property
    def baselined(self) -> List[Finding]:
        return [f for f in self.findings if f.status == "baselined"]

    @property
    def advice(self) -> List[Finding]:
        return [f for f in self.findings if f.status == "advice"]

    @property
    def exit_code(self) -> int:
        return 1 if (self.new or self.parse_errors) else 0

    def summary(self) -> Dict:
        by_code: Dict[str, int] = {}
        for f in self.new:
            by_code[f.code] = by_code.get(f.code, 0) + 1
        return {
            "findings": len(self.new),
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "advice": len(self.advice),
            "files_scanned": self.files_scanned,
            "by_code": dict(sorted(by_code.items())),
        }

    def to_json(self) -> Dict:
        return {
            "version": 1,
            "tool": "sts-lint",
            "rules": {code: {"name": r.name, "summary": r.summary}
                      for code, r in sorted(RULES.items())},
            "summary": self.summary(),
            "parse_errors": self.parse_errors,
            "findings": [f.to_json() for f in self.findings],
        }


def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
    return out


def _suppressions_for(source: str) -> Dict[int, Optional[set]]:
    """line number -> set of suppressed codes (None = all codes)."""
    out: Dict[int, Optional[set]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        mo = _NOQA_RE.search(text)
        if not mo:
            continue
        codes = mo.group(1)
        out[i] = None if codes is None else \
            {c.strip().upper() for c in codes.split(",") if c.strip()}
    return out


def load_baseline(path: str) -> Dict[str, int]:
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return {}
    entries = data.get("entries", {})
    return {str(k): int(v) for k, v in entries.items()}


def write_baseline(path: str, result: "LintResult",
                   sources: Dict[str, str]) -> Dict[str, int]:
    """Regenerate the baseline from every non-suppressed finding of this
    run (suppressed lines are already handled in-source).  Entries carry
    a human-readable context line so reviews of baseline diffs can see
    what debt was admitted."""
    entries: Dict[str, int] = {}
    context: Dict[str, str] = {}
    for f in result.findings:
        if f.status in ("suppressed", "advice"):
            continue
        line_text = _line_of(sources.get(f.path, ""), f.line)
        fp = f.fingerprint(line_text)
        entries[fp] = entries.get(fp, 0) + 1
        context.setdefault(fp, f"{f.path}:{f.line} {line_text.strip()}")
    payload = {
        "version": 1,
        "comment": "sts-lint debt ledger — regenerate with "
                   "`make lint-baseline`; every entry needs a written "
                   "justification in the PR that adds it",
        "entries": dict(sorted(entries.items())),
        "context": dict(sorted(context.items())),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return entries


def _line_of(source: str, lineno: int) -> str:
    lines = source.splitlines()
    return lines[lineno - 1] if 0 < lineno <= len(lines) else ""


def lint_paths(paths: Sequence[str], root: Optional[str] = None,
               baseline: Optional[Dict[str, int]] = None,
               select: Optional[Sequence[str]] = None
               ) -> Tuple[LintResult, Dict[str, str]]:
    """Lint ``paths`` (files or directories).  Returns the result plus the
    relpath->source map (the baseline writer needs the line text)."""
    root = os.path.abspath(root or os.getcwd())
    files = _iter_py_files(paths)
    modules: List[ModuleModel] = []
    result = LintResult()
    sources: Dict[str, str] = {}
    for path in files:
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, root).replace(os.sep, "/")
        try:
            source = open(ap, encoding="utf-8").read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            result.parse_errors.append(f"{rel}: {e}")
            continue
        sources[rel] = source
        modules.append(ModuleModel(ap, rel, source, tree))
    result.files_scanned = len(modules)
    project = Project(modules)

    active = [RULES[c] for c in (select or sorted(RULES))]
    baseline = dict(baseline or {})
    remaining = dict(baseline)
    for mod in modules:
        sup = _suppressions_for(mod.source)
        for rule in active:
            for raw in rule.check(project, mod):
                f = Finding(raw.code, mod.relpath, raw.line, raw.col,
                            raw.symbol, raw.message)
                codes = sup.get(raw.line, False)
                if codes is not False and (codes is None
                                           or raw.code in codes):
                    f.status = "suppressed"
                elif rule.severity == "advice":
                    # inventory, not debt: never gates, never baselines
                    f.status = "advice"
                else:
                    fp = f.fingerprint(_line_of(mod.source, raw.line))
                    if remaining.get(fp, 0) > 0:
                        remaining[fp] -= 1
                        f.status = "baselined"
                result.findings.append(f)
    result.findings.sort(key=lambda f: (f.path, f.line, f.code))
    return result, sources
