"""Semantic model behind the sts-lint rules.

The rules need one non-local fact about every function in a module: *does
its body run under a JAX trace?*  A function is **traced** when it is

- decorated with ``jit`` (directly or via ``functools.partial``),
- passed to a JAX transform (``jit``/``vmap``/``grad``/``lax.scan``/
  ``lax.while_loop``/``lax.cond``/``pallas_call``/...),
- passed to a *transformer parameter* of another function — a parameter
  that function (transitively) hands to a transform.  This is how the
  model objectives reach the optimizers: ``models/arima.py`` passes a
  residual closure to ``ops.optimize.minimize_least_squares``, whose
  ``solve_one`` vmaps it — so the closure is traced even though no
  transform appears near its definition, or
- referenced by name inside an already-traced function (helpers called
  from traced code trace too).

The computation is a whole-lint-run fixpoint over every parsed module:
transform call sites seed the traced set and the transformer-parameter
sets; name references inside traced functions grow the traced set; a
parameter of an enclosing function referenced inside a traced nested
function marks the *enclosing* function as a transformer in that
parameter (the ``minimize_bfgs(fn, ...)`` shape).  Cross-module calls
resolve through each module's import table into a global registry keyed
by ``(module basename, function name)``.

This is a linter's model, not an interpreter's: aliasing is tracked only
through simple ``name = other_name`` assignments, return values are not
tracked, and attribute-stored callables are invisible.  Misses
under-report (a finding never fires in code the model cannot see);
over-reporting is bounded by the name-reference closure being restricted
to *defined functions*, never arbitrary data.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

# canonical transform name -> positions of function-valued args whose
# bodies run under trace (variadic branch-taking forms live in
# TRANSFORM_VARIADIC below)
TRANSFORM_POSITIONS: Dict[str, Tuple[int, ...]] = {
    "jax.jit": (0,),
    "jax.vmap": (0,),
    "jax.pmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.hessian": (0,),
    "jax.jacfwd": (0,),
    "jax.jacrev": (0,),
    "jax.linearize": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "jax.custom_jvp": (0,),
    "jax.custom_vjp": (0,),
    "jax.lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.map": (0,),
    "jax.lax.associative_scan": (0,),
    "jax.lax.custom_root": (0, 1, 2),
    "jax.experimental.pallas.pallas_call": (0,),
    "jax.experimental.shard_map.shard_map": (0,),
}
# cond/switch: every function-valued operand from position 1 is a branch
TRANSFORM_VARIADIC: Dict[str, int] = {
    "jax.lax.cond": 1,
    "jax.lax.switch": 1,
}

# attribute accesses on a tracer that yield *static* Python values —
# taint does not flow through these (branching on x.ndim is fine)
STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding",
                          "aval", "weak_type"})


def canonical_tail(canon: str) -> str:
    """Strip relative-import dots so suffix matching works uniformly."""
    return canon.lstrip(".")


class FuncInfo:
    """One function (def or lambda) plus the analysis state hung off it."""

    __slots__ = ("node", "module", "qualname", "name", "params", "parent",
                 "transformer_params", "static_params", "traced",
                 "traced_via", "traced_root", "instrumented",
                 "local_funcs", "is_lambda", "decorators")

    def __init__(self, node: ast.AST, module: "ModuleModel",
                 qualname: str, parent: Optional["FuncInfo"]):
        self.node = node
        self.module = module
        self.qualname = qualname
        self.parent = parent
        self.is_lambda = isinstance(node, ast.Lambda)
        self.name = "<lambda>" if self.is_lambda else node.name
        self.params = _param_names(node.args)
        self.transformer_params: Set[str] = set()
        self.static_params: Set[str] = set()
        self.traced = False
        self.traced_via: Optional[str] = None
        # a *root* receives tracer arguments directly (transform target /
        # objective passed into a transformer param); a non-root merely
        # runs at trace time because traced code references it — its
        # params are only tracers if a tainted value visibly flows in
        self.traced_root = False
        # wrapped by utils.metrics.instrument_fit — its plain call form
        # opens a span, so traced code must go through .__wrapped__
        self.instrumented = False
        self.local_funcs: Dict[str, "FuncInfo"] = {}
        self.decorators = [] if self.is_lambda else list(node.decorator_list)

    def mark_traced(self, via: str, root: bool = True) -> bool:
        if self.traced:
            if root and not self.traced_root:
                self.traced_root = True
                self.traced_via = via
                return True
            return False
        self.traced = True
        self.traced_root = root
        self.traced_via = via
        return True

    def scope_chain(self) -> Iterator["FuncInfo"]:
        f: Optional[FuncInfo] = self
        while f is not None:
            yield f
            f = f.parent

    def resolve_local(self, name: str) -> Optional["FuncInfo"]:
        """Innermost-scope-first lookup of a locally defined function."""
        for scope in self.scope_chain():
            if name in scope.local_funcs:
                return scope.local_funcs[name]
        return self.module.module_funcs.get(name)


def _param_names(args: ast.arguments) -> List[str]:
    names = [a.arg for a in args.posonlyargs] + [a.arg for a in args.args]
    # kwonly params participate in keyword matching; *args/**kwargs don't
    # carry individual identities worth tracking
    return names + [a.arg for a in args.kwonlyargs]


def iter_scope(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's *own* execution scope: its body, excluding the
    bodies of nested defs/lambdas (their code runs when *they* run).
    Nested def/lambda nodes themselves are yielded (they are statements
    of this scope) — just not descended into."""
    body = fn_node.body if not isinstance(fn_node, ast.Lambda) \
        else [fn_node.body]
    stack: List[ast.AST] = list(body) if isinstance(body, list) else [body]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # decorators and default-arg expressions evaluate here
            if not isinstance(node, ast.Lambda):
                stack.extend(node.decorator_list)
                stack.extend(d for d in node.args.defaults)
                stack.extend(d for d in node.args.kw_defaults if d)
            continue
        stack.extend(ast.iter_child_nodes(node))


class ModuleModel:
    """Parsed module + import table + function index."""

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.Module):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.aliases: Dict[str, str] = {}       # local name -> dotted canon
        self.module_funcs: Dict[str, FuncInfo] = {}
        self.functions: List[FuncInfo] = []     # every def/lambda, any depth
        self.func_of_node: Dict[ast.AST, FuncInfo] = {}
        self._index()

    # -- import table -----------------------------------------------------
    def _index(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
                    if a.asname:
                        self.aliases[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom):
                base = ("." * node.level) + (node.module or "")
                for a in node.names:
                    if a.name == "*":
                        continue
                    canon = f"{base}.{a.name}" if base else a.name
                    self.aliases[a.asname or a.name] = canon
        self._index_module_scope()

    def _index_module_scope(self) -> None:
        # descend through module-level control flow and class bodies, but
        # never into a function body — functions register themselves and
        # recurse via iter_scope
        stack: List[Tuple[ast.AST, str]] = [
            (n, "") for n in ast.iter_child_nodes(self.tree)]
        while stack:
            node, prefix = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                self._register(node, None, prefix)
            elif isinstance(node, ast.ClassDef):
                stack.extend((c, f"{prefix}{node.name}.")
                             for c in ast.iter_child_nodes(node))
            else:
                stack.extend((c, prefix)
                             for c in ast.iter_child_nodes(node))

    def _register(self, node: ast.AST, parent: Optional[FuncInfo],
                  prefix: str) -> None:
        if node in self.func_of_node:
            return
        name = "<lambda>" if isinstance(node, ast.Lambda) else node.name
        qual = f"{prefix}{name}" if parent is None \
            else f"{parent.qualname}.{name}"
        info = FuncInfo(node, self, qual, parent)
        self.functions.append(info)
        self.func_of_node[node] = info
        if not info.is_lambda:
            if parent is None and not prefix:
                self.module_funcs.setdefault(name, info)
            elif parent is not None:
                parent.local_funcs[name] = info
        for child in iter_scope(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                self._register(child, info, prefix="")

    # -- name resolution --------------------------------------------------
    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted canonical name of a Name/Attribute chain, with the base
        segment rewritten through the import table.  None for anything
        that is not a plain dotted chain."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.aliases.get(node.id, node.id)
        parts.append(base)
        return ".".join(reversed(parts))


class Project:
    """All parsed modules + the cross-module traced-function fixpoint."""

    def __init__(self, modules: List[ModuleModel]):
        self.modules = modules
        self._param_taint: Optional[Dict[FuncInfo, Set[str]]] = None
        # (module basename, function name) -> FuncInfo, for cross-module
        # call resolution through import tails.  Collisions keep the first
        # registration and merge transformer params conservatively.
        self.registry: Dict[Tuple[str, str], FuncInfo] = {}
        for m in modules:
            base = m.relpath.rsplit("/", 1)[-1].removesuffix(".py")
            for name, fi in m.module_funcs.items():
                self.registry.setdefault((base, name), fi)
        self._fixpoint()

    # -- cross-module lookup ---------------------------------------------
    def lookup(self, canon: Optional[str], scope: Optional[FuncInfo],
               module: ModuleModel) -> Optional[FuncInfo]:
        if canon is None:
            return None
        tail = canonical_tail(canon).split(".")
        if len(tail) == 1:
            if scope is not None:
                hit = scope.resolve_local(tail[0])
                if hit is not None:
                    return hit
            return module.module_funcs.get(tail[0])
        return self.registry.get((tail[-2], tail[-1]))

    # -- fixpoint ---------------------------------------------------------
    def _fixpoint(self, max_rounds: int = 25) -> None:
        for m in self.modules:
            for fi in m.functions:
                self._seed_decorators(fi)
        for _ in range(max_rounds):
            changed = False
            for m in self.modules:
                for fi in m.functions:
                    changed |= self._scan_calls(fi)
            for m in self.modules:
                for fi in m.functions:
                    if fi.traced:
                        changed |= self._propagate_traced(fi)
            if not changed:
                return

    def _seed_decorators(self, fi: FuncInfo) -> None:
        for dec in fi.decorators:
            canon = fi.module.resolve(dec if not isinstance(dec, ast.Call)
                                      else dec.func)
            tail = canonical_tail(canon) if canon else ""
            if tail.split(".")[-1] == "instrument_fit":
                fi.instrumented = True
            if tail in TRANSFORM_POSITIONS and tail != \
                    "jax.experimental.pallas.pallas_call":
                fi.mark_traced(f"@{tail}")
                if isinstance(dec, ast.Call):
                    self._record_statics(fi, dec)
            elif isinstance(dec, ast.Call) and tail in (
                    "functools.partial", "partial") and dec.args:
                inner = fi.module.resolve(dec.args[0])
                if inner and canonical_tail(inner) in TRANSFORM_POSITIONS:
                    fi.mark_traced(f"@partial({canonical_tail(inner)})")
                    self._record_statics(fi, dec)

    def _record_statics(self, fi: FuncInfo, call: ast.Call) -> None:
        """static_argnums/static_argnames from a visible jit(...) call —
        those parameters are Python values, not tracers (STS005 must not
        taint them)."""
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for n in _const_strings(kw.value):
                    fi.static_params.add(n)
            elif kw.arg == "static_argnums":
                for i in _const_ints(kw.value):
                    if 0 <= i < len(fi.params):
                        fi.static_params.add(fi.params[i])

    def _param_aliases(self, fi: FuncInfo) -> Dict[str, str]:
        """name -> param it aliases, through simple assignments."""
        out = {p: p for p in fi.params}
        for node in iter_scope(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Name):
                src = out.get(node.value.id)
                if src is not None:
                    out[node.targets[0].id] = src
        return out

    def _traced_arg_positions(self, canon_tail: str,
                              call: ast.Call) -> List[ast.AST]:
        args: List[ast.AST] = []
        if canon_tail in TRANSFORM_POSITIONS:
            for pos in TRANSFORM_POSITIONS[canon_tail]:
                if pos < len(call.args):
                    args.append(call.args[pos])
        elif canon_tail in TRANSFORM_VARIADIC:
            args.extend(call.args[TRANSFORM_VARIADIC[canon_tail]:])
        return args

    def _scan_calls(self, fi: FuncInfo) -> bool:
        changed = False
        aliases = self._param_aliases(fi)
        for node in iter_scope(fi.node):
            if not isinstance(node, ast.Call):
                continue
            canon = fi.module.resolve(node.func)
            tail = canonical_tail(canon) if canon else ""
            fn_args = self._traced_arg_positions(tail, node)
            is_jit = tail == "jax.jit"
            if not fn_args:
                target = self.lookup(canon, fi, fi.module)
                if target is None or not target.transformer_params:
                    continue
                fn_args = []
                for i, a in enumerate(node.args):
                    if i < len(target.params) \
                            and target.params[i] in target.transformer_params:
                        fn_args.append(a)
                for kw in node.keywords:
                    if kw.arg in target.transformer_params:
                        fn_args.append(kw.value)
                is_jit = False
            for arg in fn_args:
                changed |= self._mark_function_arg(fi, arg, aliases, tail,
                                                  node if is_jit else None)
        return changed

    def _mark_function_arg(self, fi: FuncInfo, arg: ast.AST,
                           aliases: Dict[str, str], via: str,
                           jit_call: Optional[ast.Call]) -> bool:
        if isinstance(arg, ast.Lambda):
            target = fi.module.func_of_node.get(arg)
            if target is not None:
                hit = target.mark_traced(via)
                if hit and jit_call is not None:
                    self._record_statics(target, jit_call)
                return hit
            return False
        if isinstance(arg, ast.Name):
            param = aliases.get(arg.id)
            if param is not None and param in fi.params:
                if param not in fi.transformer_params:
                    fi.transformer_params.add(param)
                    return True
                return False
            target = self.lookup(fi.module.resolve(arg), fi, fi.module)
            if target is not None:
                hit = target.mark_traced(via)
                if hit and jit_call is not None:
                    self._record_statics(target, jit_call)
                return hit
        elif isinstance(arg, ast.Attribute):
            target = self.lookup(fi.module.resolve(arg), fi, fi.module)
            if target is not None:
                return target.mark_traced(via)
        return False

    def _propagate_traced(self, fi: FuncInfo) -> bool:
        """Inside a traced body: referenced functions trace too, and a
        reference to an *enclosing* function's parameter marks that
        parameter as transforming (objectives passed into optimizers)."""
        changed = False
        # names appearing as the callee of a call in this traced scope:
        # the only evidence strong enough to conclude an enclosing
        # function's parameter is a callable invoked under trace
        called_names = {n.func.id for n in iter_scope(fi.node)
                        if isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Name)}
        for node in iter_scope(fi.node):
            if isinstance(node, ast.Lambda):
                target = fi.module.func_of_node.get(node)
                if target is not None:
                    changed |= target.mark_traced(
                        f"defined in traced {fi.qualname}", root=False)
                continue
            if not isinstance(node, ast.Name) \
                    or not isinstance(node.ctx, ast.Load):
                continue
            target = fi.resolve_local(node.id)
            if target is not None:
                changed |= target.mark_traced(
                    f"referenced in traced {fi.qualname}", root=False)
                continue
            if node.id in fi.params or node.id not in called_names:
                continue
            for ancestor in fi.scope_chain():
                if ancestor is fi:
                    continue
                if node.id in ancestor.params \
                        and node.id not in ancestor.static_params \
                        and node.id not in ancestor.transformer_params:
                    ancestor.transformer_params.add(node.id)
                    changed = True
                    break
        return changed


    # -- tracer taint -----------------------------------------------------
    def param_taint(self) -> Dict[FuncInfo, Set[str]]:
        """Which parameters of each traced function hold tracer values.

        Roots (transform targets, objectives handed to transformer
        params) receive tracers in every non-static parameter.  A
        non-root traced function — a helper that merely *runs* at trace
        time — only holds a tracer in a parameter if a tainted
        expression visibly flows into it at a call site inside traced
        code (including through ``functools.partial``, whose bound
        leading arguments are usually the static config ints).  This is
        what lets ``_remove_effects_one(params, ts, p, d, q, icpt)``
        branch on ``p``/``q`` freely: the call site binds them from host
        ints, so only ``params``/``ts`` taint."""
        if self._param_taint is not None:
            return self._param_taint
        taint: Dict[FuncInfo, Set[str]] = {}
        traced = [fi for m in self.modules for fi in m.functions
                  if fi.traced]
        for fi in traced:
            taint[fi] = (set(fi.params) - fi.static_params
                         - fi.transformer_params) if fi.traced_root \
                else set()
        for _ in range(10):
            changed = False
            for fi in traced:
                names = local_tainted_names(fi, taint[fi])
                for node in iter_scope(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    changed |= self._flow_call_taint(fi, node, names,
                                                     taint)
            if not changed:
                break
        self._param_taint = taint
        return taint

    def _flow_call_taint(self, fi: FuncInfo, call: ast.Call,
                         names: Set[str],
                         taint: Dict[FuncInfo, Set[str]]) -> bool:
        mod = fi.module
        canon = mod.resolve(call.func)
        tail = canonical_tail(canon) if canon else ""
        changed = False
        if tail in ("functools.partial", "partial") and call.args:
            g = self.lookup(mod.resolve(call.args[0]), fi, mod)
            if g is None or not g.traced or g.traced_root \
                    or g not in taint:
                return False
            bound = call.args[1:]
            for i, a in enumerate(bound):
                if isinstance(a, ast.Starred):
                    break
                if i < len(g.params) and taint_expr(a, names) \
                        and g.params[i] not in taint[g]:
                    taint[g].add(g.params[i])
                    changed = True
            for kw in call.keywords:
                if kw.arg in g.params and taint_expr(kw.value, names) \
                        and kw.arg not in taint[g]:
                    taint[g].add(kw.arg)
                    changed = True
            # the unbound trailing params receive the runtime operands
            # (refs/tracers) when the partial is finally invoked
            for p in g.params[len(bound):]:
                if p not in g.static_params and p not in taint[g]:
                    taint[g].add(p)
                    changed = True
            return changed
        g = self.lookup(canon, fi, mod)
        if g is None or not g.traced or g.traced_root or g not in taint:
            return False
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                # conservatively taint the rest: *args forwarding
                for p in g.params[i:]:
                    if p not in g.static_params and p not in taint[g]:
                        taint[g].add(p)
                        changed = True
                break
            if i < len(g.params) and taint_expr(a, names) \
                    and g.params[i] not in taint[g]:
                taint[g].add(g.params[i])
                changed = True
        for kw in call.keywords:
            if kw.arg in g.params and taint_expr(kw.value, names) \
                    and kw.arg not in taint[g]:
                taint[g].add(kw.arg)
                changed = True
        return changed


# ---------------------------------------------------------------------------
# expression-level tracer taint
# ---------------------------------------------------------------------------

_UNTAINTING_CALLS = frozenset({"len", "isinstance", "getattr", "hasattr",
                               "type", "range", "enumerate", "zip", "int",
                               "float", "bool"})


def taint_expr(node: ast.AST, tainted: Set[str]) -> bool:
    """Does this expression's *value* flow from a tracer-typed name?"""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return False
        return taint_expr(node.value, tainted)
    if isinstance(node, ast.Subscript):
        return taint_expr(node.value, tainted)
    if isinstance(node, ast.BinOp):
        return taint_expr(node.left, tainted) \
            or taint_expr(node.right, tainted)
    if isinstance(node, ast.UnaryOp):
        return taint_expr(node.operand, tainted)
    if isinstance(node, ast.Compare):
        # `x is None` / `x is not None` is an identity check on the
        # Python object, not a value read
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False
        return taint_expr(node.left, tainted) \
            or any(taint_expr(c, tainted) for c in node.comparators)
    if isinstance(node, ast.BoolOp):
        return any(taint_expr(v, tainted) for v in node.values)
    if isinstance(node, ast.IfExp):
        return taint_expr(node.body, tainted) \
            or taint_expr(node.orelse, tainted)
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(taint_expr(e, tainted) for e in node.elts)
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) \
                and node.func.id in _UNTAINTING_CALLS:
            return False
        # a method call on a tainted object yields a tainted value
        # ((params > 0).any()); .shape/.ndim chains already untaint in
        # the Attribute case above
        if isinstance(node.func, ast.Attribute) \
                and taint_expr(node.func.value, tainted):
            return True
        return any(taint_expr(a, tainted) for a in node.args) \
            or any(taint_expr(kw.value, tainted) for kw in node.keywords)
    if isinstance(node, ast.Starred):
        return taint_expr(node.value, tainted)
    return False


def local_tainted_names(fi: FuncInfo, seed: Set[str]) -> Set[str]:
    """Grow a function's tainted-name set through simple local flow
    (assignments; two passes for use-before-def in loops)."""
    tainted = set(seed)
    for _ in range(2):
        for node in iter_scope(fi.node):
            if isinstance(node, ast.Assign):
                if taint_expr(node.value, tainted):
                    for t in node.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name) \
                        and taint_expr(node.value, tainted):
                    tainted.add(node.target.id)
            elif isinstance(node, ast.NamedExpr):
                if isinstance(node.target, ast.Name) \
                        and taint_expr(node.value, tainted):
                    tainted.add(node.target.id)
            elif isinstance(node, ast.For):
                if taint_expr(node.iter, tainted):
                    for n in ast.walk(node.target):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
    return tainted


def _const_strings(node: ast.AST) -> List[str]:
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append(n.value)
    return out


# ---------------------------------------------------------------------------
# concurrency model (the STS100-series substrate)
# ---------------------------------------------------------------------------
#
# The STS0xx rules need "is this function traced?"; the STS1xx rules need
# the host-side mirror image: which names are *locks*, which statements
# run *holding* which locks, which functions run on *threads*, and what
# the whole-tree lock-acquisition-order graph looks like.  Same modeling
# stance as the tracer model above: misses under-report (a lock stored
# in a dict, or an object whose type the model cannot see, is invisible),
# and over-reporting is bounded by only ever reasoning about names the
# inventory proved to be locks.

_LOCK_FACTORY_TAILS = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
})

# container-mutating method names: a call `<state>.append(...)` mutates
# the shared object just as surely as `<state>[k] = v`
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "add", "pop", "popleft", "popitem", "update",
    "clear", "extend", "extendleft", "remove", "discard", "insert",
    "setdefault",
})

# calls that block the calling thread (directly); held locks make these
# whole-process stalls
_BLOCKING_CALL_TAILS = frozenset({
    "time.sleep", "subprocess.run", "subprocess.call", "subprocess.Popen",
    "subprocess.check_output", "subprocess.check_call",
    "urllib.request.urlopen", "socket.create_connection", "os.fsync",
    "input",
})
_BLOCKING_METHODS = frozenset({"block_until_ready", "wait", "join",
                               "recv", "accept"})
# ...except join on obvious string-building (", ".join(xs)) and wait on
# the condition variable being held (Condition.wait RELEASES its lock)

_HTTP_HANDLER_METHODS = frozenset({"do_GET", "do_POST", "do_PUT",
                                   "do_DELETE", "do_HEAD", "handle"})


def _modbase(mod: ModuleModel) -> str:
    """Short module name.  Unlike the traced-function registry this
    maps ``pkg/__init__.py`` to ``pkg`` — import tails resolve through
    the package name (``from .native import _lock`` ends
    ``native._lock``)."""
    parts = mod.relpath.split("/")
    name = parts[-1].removesuffix(".py")
    if name == "__init__" and len(parts) >= 2:
        return parts[-2]
    return name


def _modpath(mod: ModuleModel) -> str:
    """Fully-qualified dotted module path (collision-free)."""
    parts = mod.relpath.removesuffix(".py").split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _contains_lock_factory(mod: ModuleModel, value: ast.AST) -> bool:
    for n in ast.walk(value):
        if isinstance(n, ast.Call):
            canon = mod.resolve(n.func)
            if canon and canonical_tail(canon) in _LOCK_FACTORY_TAILS:
                return True
    return False


def _self_attr_target(node: ast.AST) -> Optional[str]:
    """The attribute name when ``node`` is a write target rooted at
    ``self.<attr>`` (plain, subscripted, or nested-subscripted)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _global_target(node: ast.AST) -> Optional[str]:
    """The base name when ``node`` is a subscript write target rooted at
    a bare name (``_jobs[k] = v``)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


class MutationEvent:
    """One write to shared state: a ``self.<attr>`` / module-global
    assignment, subscript store, or mutator-method call."""

    __slots__ = ("node", "fi", "kind", "name", "held", "how")

    def __init__(self, node: ast.AST, fi: FuncInfo, kind: str, name: str,
                 held: Tuple[str, ...], how: str):
        self.node = node
        self.fi = fi
        self.kind = kind          # "attr" | "global"
        self.name = name          # attr name / global name
        self.held = held          # known lock ids held at the write
        self.how = how            # "assign" | "subscript" | "mutator"


class ThreadSpawn:
    """One ``threading.Thread(...)`` construction site."""

    __slots__ = ("node", "fi", "daemon", "target", "assigned", "joined")

    def __init__(self, node: ast.Call, fi: FuncInfo):
        self.node = node
        self.fi = fi
        self.daemon = False
        self.target: Optional[FuncInfo] = None
        self.assigned: Optional[str] = None
        self.joined = False


class ConcurrencyModel:
    """Whole-tree lock/thread facts, computed once per lint run.

    - ``module_locks``: ``(module basename, global name) -> lock id``;
    - ``class_locks``: ``(module basename, class name) -> {attr}`` for
      attributes ever assigned a ``threading`` lock/condition;
    - per-function statement events annotated with the *known* locks
      lexically held (``with`` regions only — bare ``.acquire()`` is
      recorded as an acquisition but opens no region);
    - the global acquisition-order graph (lock held -> lock acquired,
      including one level through resolvable calls via transitive
      per-function acquisition summaries);
    - transitive blocking-call summaries;
    - thread spawn sites, thread-entry functions (``Thread(target=...)``
      plus HTTP-handler methods), and the functions reachable from them
      through resolvable calls.
    """

    def __init__(self, project: Project):
        self.project = project
        self.module_locks: Dict[Tuple[str, str], str] = {}
        self.class_locks: Dict[Tuple[str, str], Set[str]] = {}
        self.class_names: Dict[str, Set[str]] = {}
        self.module_globals: Dict[str, Set[str]] = {}
        self.events: Dict[FuncInfo, List[Tuple[ast.AST, Tuple[str, ...]]]] \
            = {}
        self.acquires: Dict[FuncInfo, Set[str]] = {}
        self.acquires_tc: Dict[FuncInfo, Set[str]] = {}
        self.blocking: Dict[FuncInfo, Optional[str]] = {}
        self.blocking_tc: Dict[FuncInfo, Optional[str]] = {}
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self.spawns: List[ThreadSpawn] = []
        self.thread_entries: Set[FuncInfo] = set()
        self.thread_reachable: Set[FuncInfo] = set()
        self.mutations: Dict[FuncInfo, List[MutationEvent]] = {}
        self.event_objects: List[Tuple[ast.AST, FuncInfo, str]] = []
        self._modkeys: Dict[int, str] = {}
        self._assign_modkeys()
        self._inventory()
        self._walk_all()
        self._close_summaries()
        self._call_edges()
        self._thread_closure()

    # -- module keys --------------------------------------------------------

    def _assign_modkeys(self) -> None:
        """One unambiguous key per module.  A bare basename is readable
        (``engine._jit_lock``) but two same-named modules in different
        packages (``backtest/api.py`` vs ``longseries/api.py``) would
        silently overwrite each other's inventory — colliding basenames
        ALL demote to their last-two dotted segments (none keeps the
        bare name, so a bare import tail can never resolve to the wrong
        module), and a still-colliding pair falls back to the full
        dotted path."""
        by_base: Dict[str, List[ModuleModel]] = {}
        for mod in self.project.modules:
            by_base.setdefault(_modbase(mod), []).append(mod)
        taken: Set[str] = set()
        for base, mods in by_base.items():
            if len(mods) == 1 and base not in taken:
                self._modkeys[id(mods[0])] = base
                taken.add(base)
                continue
            for mod in mods:
                parts = _modpath(mod).split(".")
                key = ".".join(parts[-2:])
                if key in taken:
                    key = _modpath(mod)
                self._modkeys[id(mod)] = key
                taken.add(key)

    def modkey(self, mod: ModuleModel) -> str:
        return self._modkeys[id(mod)]

    # -- inventory ----------------------------------------------------------

    def _inventory(self) -> None:
        for mod in self.project.modules:
            base = self.modkey(mod)
            classes: Set[str] = {n.name for n in ast.walk(mod.tree)
                                 if isinstance(n, ast.ClassDef)}
            self.class_names[base] = classes
            top: Set[str] = set()
            for node in mod.tree.body:
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets = [node.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        top.add(t.id)
                        if node.value is not None and \
                                _contains_lock_factory(mod, node.value):
                            self.module_locks[(base, t.id)] = \
                                f"{base}.{t.id}"
            self.module_globals[base] = top
            for fi in mod.functions:
                cls = self._class_of(fi)
                if cls is None:
                    continue
                for node in iter_scope(fi.node):
                    if not isinstance(node, ast.Assign) \
                            or node.value is None:
                        continue
                    for t in node.targets:
                        attr = _self_attr_target(t)
                        if attr and not isinstance(t, ast.Subscript) \
                                and _contains_lock_factory(mod, node.value):
                            self.class_locks.setdefault(
                                (base, cls), set()).add(attr)

    def _class_of(self, fi: FuncInfo) -> Optional[str]:
        """Class name for a method (qualname ``C.m``, first param
        ``self``); None for plain functions and nested defs."""
        if fi.parent is not None or not fi.params \
                or fi.params[0] != "self":
            return None
        parts = fi.qualname.split(".")
        if len(parts) != 2:
            return None
        cls = parts[0]
        return cls if cls in self.class_names.get(self.modkey(fi.module),
                                                  ()) else None

    def lock_ids_of_class(self, mod: ModuleModel, cls: str) -> Set[str]:
        base = self.modkey(mod)
        return {f"{base}.{cls}.{a}"
                for a in self.class_locks.get((base, cls), ())}

    def resolve_lock(self, fi: FuncInfo, expr: ast.AST) -> Optional[str]:
        """The inventory lock id a ``with`` item / ``.acquire()`` base
        refers to, or None for anything the inventory doesn't know."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            cls = self.method_class(fi)
            if cls is not None:
                base = self.modkey(fi.module)
                if expr.attr in self.class_locks.get((base, cls), ()):
                    return f"{base}.{cls}.{expr.attr}"
            return None
        canon = fi.module.resolve(expr)
        if canon is None:
            return None
        tail = canonical_tail(canon).split(".")
        if len(tail) == 1:
            candidates = [(self.modkey(fi.module), tail[0])]
        else:
            # a demoted (basename-colliding) module is keyed by its
            # last-two segments; try the bare tail first — no demoted
            # module keeps a bare key, so this can never mis-resolve
            candidates = [(tail[-2], tail[-1])]
            if len(tail) >= 3:
                candidates.append((".".join(tail[-3:-1]), tail[-1]))
        for key in candidates:
            lid = self.module_locks.get(key)
            if lid is not None:
                return lid
        return None

    def method_class(self, fi: FuncInfo) -> Optional[str]:
        """Class owning ``fi`` or an enclosing method (a nested def in a
        method sees the method's ``self``)."""
        for scope in fi.scope_chain():
            cls = self._class_of(scope)
            if cls is not None:
                return cls
        return None

    # -- held-region walk ---------------------------------------------------

    def _walk_all(self) -> None:
        for mod in self.project.modules:
            for fi in mod.functions:
                out: List[Tuple[ast.AST, Tuple[str, ...]]] = []
                direct: Set[str] = set()
                body = [fi.node.body] if isinstance(fi.node, ast.Lambda) \
                    else list(fi.node.body)
                self._walk(body, (), out, direct, fi)
                self.events[fi] = out
                self.acquires[fi] = direct
                self._scan_function(fi, out)

    def _walk(self, stmts: List[ast.AST], held: Tuple[str, ...],
              out: List[Tuple[ast.AST, Tuple[str, ...]]],
              direct: Set[str], fi: FuncInfo) -> None:
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # nested defs execute in their own (usually thread /
                # callback) context: no lock inherited lexically
                out.append((node, held))
                continue
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    out.append((item.context_expr, inner))
                    self._walk(list(ast.iter_child_nodes(
                        item.context_expr)), inner, out, direct, fi)
                    lid = self.resolve_lock(fi, item.context_expr)
                    if lid is not None:
                        self._acquire(lid, inner, fi,
                                      item.context_expr, direct)
                        if lid not in inner:
                            inner = inner + (lid,)
                self._walk(node.body, inner, out, direct, fi)
                continue
            out.append((node, held))
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                lid = self.resolve_lock(fi, node.func.value)
                if lid is not None:
                    self._acquire(lid, held, fi, node, direct)
            self._walk(list(ast.iter_child_nodes(node)), held, out,
                       direct, fi)

    def _acquire(self, lid: str, held: Tuple[str, ...], fi: FuncInfo,
                 node: ast.AST, direct: Set[str]) -> None:
        direct.add(lid)
        for h in held:
            if h != lid:
                self.edges.setdefault(
                    (h, lid),
                    (fi.module.relpath, getattr(node, "lineno", 0),
                     fi.qualname))

    # -- per-function fact extraction ---------------------------------------

    def _scan_function(self, fi: FuncInfo,
                       events: List[Tuple[ast.AST, Tuple[str, ...]]]
                       ) -> None:
        muts: List[MutationEvent] = []
        blocking: Optional[str] = None
        for node, held in events:
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                if isinstance(node, ast.AnnAssign) and node.value is None:
                    continue          # bare annotation: not a write
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    attr = _self_attr_target(t)
                    if attr is not None:
                        how = "subscript" if isinstance(t, ast.Subscript) \
                            else "assign"
                        muts.append(MutationEvent(node, fi, "attr", attr,
                                                  held, how))
                        continue
                    g = _global_target(t)
                    if g is not None and (isinstance(t, ast.Subscript)
                                          or g in fi_globals(fi)):
                        muts.append(
                            MutationEvent(node, fi, "global", g, held,
                                          "subscript"
                                          if isinstance(t, ast.Subscript)
                                          else "assign"))
            elif isinstance(node, ast.Call):
                blocking = blocking or self.blocking_reason(fi, node)
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _MUTATOR_METHODS:
                    attr = _self_attr_target(node.func.value)
                    if attr is not None:
                        muts.append(MutationEvent(node, fi, "attr", attr,
                                                  held, "mutator"))
                    elif isinstance(node.func.value, ast.Name):
                        muts.append(MutationEvent(
                            node, fi, "global", node.func.value.id, held,
                            "mutator"))
                canon = fi.module.resolve(node.func)
                if canon and canonical_tail(canon) == "threading.Thread":
                    self._record_spawn(node, fi)
                if canon and canonical_tail(canon) in (
                        "threading.Event",):
                    self.event_objects.append((node, fi, "event"))
        self.mutations[fi] = muts
        self.blocking[fi] = blocking
        if fi.name in _HTTP_HANDLER_METHODS \
                and self._class_of(fi) is not None:
            self.thread_entries.add(fi)

    def blocking_reason(self, fi: FuncInfo,
                        node: ast.Call) -> Optional[str]:
        """A human-readable reason when this call blocks the thread
        (independent of held locks — the *summary*; STS103 combines it
        with held regions)."""
        canon = fi.module.resolve(node.func)
        tail = canonical_tail(canon) if canon else ""
        if tail in _BLOCKING_CALL_TAILS or tail == "open":
            return f"{tail}()"
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _BLOCKING_METHODS:
            if node.func.attr == "join" and not self._threadish(
                    fi, node.func.value):
                return None          # ", ".join(parts) et al.
            return f".{node.func.attr}()"
        return None

    def _threadish(self, fi: FuncInfo, base: ast.AST) -> bool:
        """Is ``<base>.join()`` plausibly a thread/process join?  Only
        when the base name was assigned a Thread/Process in the same
        function scope — string joins dominate otherwise."""
        if not isinstance(base, (ast.Name, ast.Attribute)):
            return False
        name = base.id if isinstance(base, ast.Name) else base.attr
        for node in iter_scope(fi.node):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                canon = fi.module.resolve(node.value.func)
                tail = canonical_tail(canon) if canon else ""
                if tail.split(".")[-1] in ("Thread", "Process"):
                    for t in node.targets:
                        tn = t.id if isinstance(t, ast.Name) else (
                            t.attr if isinstance(t, ast.Attribute)
                            else None)
                        if tn == name:
                            return True
        return False

    def _record_spawn(self, node: ast.Call, fi: FuncInfo) -> None:
        spawn = ThreadSpawn(node, fi)
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True:
                spawn.daemon = True
            elif kw.arg == "target":
                target = self.project.lookup(
                    fi.module.resolve(kw.value), fi, fi.module)
                if target is not None:
                    spawn.target = target
                    self.thread_entries.add(target)
        # the assigned name + later .join(...) / .daemon = True
        for n in iter_scope(fi.node):
            if isinstance(n, ast.Assign) and n.value is node:
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        spawn.assigned = t.id
        if spawn.assigned:
            for n in iter_scope(fi.node):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "join" \
                        and isinstance(n.func.value, ast.Name) \
                        and n.func.value.id == spawn.assigned:
                    spawn.joined = True
                if isinstance(n, ast.Assign) \
                        and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Attribute) \
                        and n.targets[0].attr == "daemon" \
                        and isinstance(n.targets[0].value, ast.Name) \
                        and n.targets[0].value.id == spawn.assigned \
                        and isinstance(n.value, ast.Constant) \
                        and n.value.value is True:
                    spawn.daemon = True
        self.spawns.append(spawn)

    # -- call resolution shared by the closures -----------------------------

    def resolve_call(self, fi: FuncInfo,
                     node: ast.Call) -> Optional[FuncInfo]:
        """Callee FuncInfo for module functions, imported functions, and
        same-class ``self.m(...)`` methods."""
        if isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            cls = self.method_class(fi)
            if cls is not None:
                for other in fi.module.functions:
                    if other.qualname == f"{cls}.{node.func.attr}":
                        return other
            return None
        return self.project.lookup(fi.module.resolve(node.func), fi,
                                   fi.module)

    # -- fixpoints ----------------------------------------------------------

    def _close_summaries(self, max_rounds: int = 20) -> None:
        funcs = [fi for m in self.project.modules for fi in m.functions]
        self.acquires_tc = {fi: set(self.acquires[fi]) for fi in funcs}
        self.blocking_tc = dict(self.blocking)
        for _ in range(max_rounds):
            changed = False
            for fi in funcs:
                for node, _held in self.events[fi]:
                    if not isinstance(node, ast.Call):
                        continue
                    g = self.resolve_call(fi, node)
                    if g is None or g not in self.acquires_tc:
                        continue
                    missing = self.acquires_tc[g] - self.acquires_tc[fi]
                    if missing:
                        self.acquires_tc[fi] |= missing
                        changed = True
                    if self.blocking_tc.get(g) \
                            and not self.blocking_tc.get(fi):
                        self.blocking_tc[fi] = (
                            f"{g.qualname}() -> "
                            f"{self.blocking_tc[g]}")
                        changed = True
            if not changed:
                return

    def _call_edges(self) -> None:
        """Holding lock A, a call into a function that (transitively)
        acquires B is an A->B edge too — this is how the graph crosses
        modules."""
        for m in self.project.modules:
            for fi in m.functions:
                for node, held in self.events[fi]:
                    if not held or not isinstance(node, ast.Call):
                        continue
                    g = self.resolve_call(fi, node)
                    if g is None:
                        continue
                    for b in self.acquires_tc.get(g, ()):
                        for a in held:
                            if a != b:
                                self.edges.setdefault(
                                    (a, b),
                                    (m.relpath,
                                     getattr(node, "lineno", 0),
                                     f"{fi.qualname} -> {g.qualname}"))

    def _thread_closure(self) -> None:
        frontier = list(self.thread_entries)
        seen = set(frontier)
        while frontier:
            fi = frontier.pop()
            for node, _held in self.events.get(fi, ()):
                if not isinstance(node, ast.Call):
                    continue
                g = self.resolve_call(fi, node)
                if g is not None and g not in seen:
                    seen.add(g)
                    frontier.append(g)
        self.thread_reachable = seen

    # -- lock-order cycles --------------------------------------------------

    def lock_cycles(self) -> List[List[str]]:
        """Strongly connected components of size > 1 in the acquisition-
        order graph (a self-loop cannot happen: with-reacquisition of the
        same id is filtered at edge creation).  Each SCC is one potential
        ABBA deadlock family; returned sorted for determinism.

        The Tarjan body is deliberately mirrored in
        ``spark_timeseries_tpu/utils/races.py::RaceHarness.cycles`` (the
        runtime cross-check): neither side may import the other across
        the tools-vs-shipped-package boundary.  Keep the two in
        lockstep."""
        graph: Dict[str, Set[str]] = {}
        for a, b in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strong(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in sorted(graph[v]):
                if w not in index:
                    strong(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

        for v in sorted(graph):
            if v not in index:
                strong(v)
        return sorted(sccs)


def fi_globals(fi: FuncInfo) -> Set[str]:
    """Names the function declares ``global``."""
    out: Set[str] = set()
    for node in iter_scope(fi.node):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def locally_bound(fi: FuncInfo, name: str) -> bool:
    """Is ``name`` a local binding of this function (parameter, plain
    assignment without ``global``, loop/with/except/comprehension
    target)?  Used to keep a module-global rule from firing on a local
    that merely shadows the global's name."""
    if name in fi.params:
        return True
    if name in fi_globals(fi):
        return False
    for node in iter_scope(fi.node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name) and n.id == name \
                            and isinstance(n.ctx, ast.Store):
                        return True
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if isinstance(node.target, ast.Name) \
                    and node.target.id == name:
                return True
        elif isinstance(node, ast.For):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name) and n.id == name:
                    return True
        elif isinstance(node, ast.With):
            for item in node.items:
                if item.optional_vars is not None:
                    for n in ast.walk(item.optional_vars):
                        if isinstance(n, ast.Name) and n.id == name:
                            return True
        elif isinstance(node, ast.ExceptHandler):
            if node.name == name:
                return True
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                for n in ast.walk(gen.target):
                    if isinstance(n, ast.Name) and n.id == name:
                        return True
        elif isinstance(node, ast.NamedExpr):
            if isinstance(node.target, ast.Name) \
                    and node.target.id == name:
                return True
    return False


def concurrency_model(project: Project) -> ConcurrencyModel:
    """The per-run cached concurrency model (built on first rule use)."""
    model = getattr(project, "_concurrency_model", None)
    if model is None:
        model = ConcurrencyModel(project)
        project._concurrency_model = model
    return model


def _const_ints(node: ast.AST) -> List[int]:
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                and not isinstance(n.value, bool):
            out.append(n.value)
    return out


# ---------------------------------------------------------------------------
# Host-boundary model (the STS200 series): where do compiled-program
# outputs cross back to the host on the hot path?
# ---------------------------------------------------------------------------

# The modules *between* the compiled programs — the orchestration layer
# where a stray device→host crossing taxes every chunk/tick rather than
# one call.  Matched by relpath suffix so the same scoping works when
# linting the package directory, the repo root, or a test fixture tree.
HOT_PATH_FILES = frozenset({
    "engine.py",
    "statespace/serving.py",
    "statespace/fleet.py",
    "statespace/runtime.py",
    "statespace/kalman.py",
    "backtest/evaluate.py",
})
HOT_PATH_DIRS = ("longseries",)


def hot_path_module(mod: ModuleModel) -> bool:
    """Is this module part of the chunk/tick hot path?"""
    rel = mod.relpath
    parts = rel.split("/")
    # the lint package's own engine.py (and anything under tools/tests)
    # is host tooling, not the pipeline
    if "tools" in parts or "tests" in parts or "sts_lint" in parts:
        return False
    for f in HOT_PATH_FILES:
        if rel == f or rel.endswith("/" + f):
            return True
    return any(d in parts[:-1] for d in HOT_PATH_DIRS)


def _is_jit_call(mod: ModuleModel, node: ast.AST) -> bool:
    """A Call expression that *produces a compiled callable*:
    ``jax.jit(...)`` or an AOT ``<...>.lower(...).compile()`` chain."""
    if not isinstance(node, ast.Call):
        return False
    canon = mod.resolve(node.func)
    if canon and canonical_tail(canon) == "jax.jit":
        return True
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "compile"
            and isinstance(f.value, ast.Call)
            and isinstance(f.value.func, ast.Attribute)
            and f.value.func.attr == "lower")


def _donated_positions(node: ast.Call) -> Tuple[int, ...]:
    """``donate_argnums`` constants of a ``jax.jit(...)`` call site."""
    for kw in node.keywords:
        if kw.arg == "donate_argnums":
            return tuple(_const_ints(kw.value))
    return ()


def _bind_names(targets, into: Set[str]) -> None:
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                into.add(n.id)


class HostBoundaryModel:
    """Device-taint dataflow for the hot-path modules.

    Two taint kinds, both proven from the source rather than assumed:

    - **executable taint** — names holding a compiled callable: a
      module-level ``name = jax.jit(...)`` binding, the result of a
      ``.lower(...).compile()`` chain, a call to a *jit factory* (a
      function whose own body creates such a callable and returns a
      value — ``serving._jitted``, ``engine.FitEngine._entry``), or an
      attribute read off an executable-tainted value (``entry.compiled``).
    - **device taint** — values returned by *calling* an
      executable-tainted callable.  Flows through the same local walk
      the tracer model uses (tuple unpacks, subscripts, non-static
      attributes, arithmetic); ``jnp.*``/``jax.*`` calls preserve it;
      any call the model cannot prove device-preserving launders it.

    Same modeling stance as the tracer and concurrency models: misses
    under-report, over-reporting is bounded because taint only starts at
    proven compiled-callable bindings, never arbitrary data.
    """

    # known host-materializing callees: taint does NOT flow through
    # these (their result is a host value) — the rules flag them instead
    MATERIALIZE_TAILS = frozenset({
        "numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
        "numpy.copyto", "numpy.save", "numpy.savetxt",
        "jax.device_get",
    })
    MATERIALIZE_BUILTINS = frozenset({"float", "int", "bool", "complex",
                                      "list", "tuple"})
    MATERIALIZE_METHODS = frozenset({"item", "tolist",
                                     "block_until_ready"})

    def __init__(self, project: Project):
        self.project = project
        # relpath -> {module-level jit-handle name: donated positions}
        self.module_jit_names: Dict[str, Dict[str, Tuple[int, ...]]] = {}
        # FuncInfos whose call result is a compiled callable
        self.jit_factories: Set[FuncInfo] = set()
        self._scan()

    # -- whole-project scan -------------------------------------------------

    def _scan(self) -> None:
        for mod in self.project.modules:
            names: Dict[str, Tuple[int, ...]] = {}
            stack: List[ast.AST] = list(mod.tree.body)
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    continue
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and _is_jit_call(mod, node.value):
                    names[node.targets[0].id] = \
                        _donated_positions(node.value)
                stack.extend(ast.iter_child_nodes(node))
            if names:
                self.module_jit_names[mod.relpath] = names
        # jit factories: a function whose own scope builds a compiled
        # callable and returns a value.  Two rounds close one level of
        # wrapping (a function returning a factory's result).
        for _ in range(2):
            for mod in self.project.modules:
                for fi in mod.functions:
                    if fi in self.jit_factories or fi.is_lambda:
                        continue
                    builds = returns = False
                    for node in iter_scope(fi.node):
                        if _is_jit_call(mod, node):
                            builds = True
                        elif isinstance(node, ast.Call):
                            callee = self._resolve_callee(mod, fi,
                                                          node.func)
                            if callee in self.jit_factories:
                                builds = True
                        elif isinstance(node, ast.Return) \
                                and node.value is not None:
                            returns = True
                    if builds and returns:
                        self.jit_factories.add(fi)

    def _resolve_callee(self, mod: ModuleModel, scope: Optional[FuncInfo],
                        func: ast.AST) -> Optional[FuncInfo]:
        """Callee FuncInfo for a call expression, including the
        ``self.method()`` form (resolved within the same module)."""
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name) \
                and func.value.id == "self":
            for fi in mod.functions:
                if fi.name == func.attr and "." in fi.qualname:
                    return fi
            return None
        canon = mod.resolve(func)
        if canon is None:
            return None
        return self.project.lookup(canon, scope, mod)

    # -- per-function taint -------------------------------------------------

    def is_exec_expr(self, mod: ModuleModel, fi: FuncInfo, node: ast.AST,
                     execn: Set[str]) -> bool:
        """Does this expression evaluate to a compiled callable?"""
        jit_names = self.module_jit_names.get(mod.relpath, {})
        if isinstance(node, ast.Name):
            return node.id in execn or node.id in jit_names
        if isinstance(node, ast.Attribute):
            # entry.compiled — the executable hangs off the handle
            return self.is_exec_expr(mod, fi, node.value, execn)
        if isinstance(node, ast.Call):
            if _is_jit_call(mod, node):
                return True
            callee = self._resolve_callee(mod, fi, node.func)
            return callee in self.jit_factories
        return False

    def is_device_expr(self, mod: ModuleModel, fi: FuncInfo,
                       node: ast.AST, dev: Set[str],
                       execn: Set[str]) -> bool:
        """Does this expression evaluate to a device-resident value?"""
        if isinstance(node, ast.Name):
            return node.id in dev
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.is_device_expr(mod, fi, node.value, dev, execn)
        if isinstance(node, ast.Subscript):
            return self.is_device_expr(mod, fi, node.value, dev, execn)
        if isinstance(node, ast.Starred):
            return self.is_device_expr(mod, fi, node.value, dev, execn)
        if isinstance(node, ast.BinOp):
            return self.is_device_expr(mod, fi, node.left, dev, execn) \
                or self.is_device_expr(mod, fi, node.right, dev, execn)
        if isinstance(node, ast.UnaryOp):
            return self.is_device_expr(mod, fi, node.operand, dev, execn)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_device_expr(mod, fi, e, dev, execn)
                       for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self.is_device_expr(mod, fi, node.body, dev, execn) \
                or self.is_device_expr(mod, fi, node.orelse, dev, execn)
        if isinstance(node, ast.Call):
            # calling a compiled callable: the output lives on device
            if self.is_exec_expr(mod, fi, node.func, execn):
                return True
            canon = mod.resolve(node.func)
            tail = canonical_tail(canon) if canon else ""
            base = tail.split(".")[-1] if tail else ""
            if tail in self.MATERIALIZE_TAILS \
                    or tail in self.MATERIALIZE_BUILTINS:
                return False            # the result is a host value now
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in self.MATERIALIZE_METHODS:
                return False
            if tail.startswith("jax.") or tail.startswith("jnp."):
                # device ops keep device operands on device
                return any(self.is_device_expr(mod, fi, a, dev, execn)
                           for a in node.args)
            _ = base
            return False                # unknown call launders
        return False

    def function_taints(self, mod: ModuleModel, fi: FuncInfo
                        ) -> Tuple[Set[str], Set[str],
                                   Dict[str, Tuple[int, ...]]]:
        """``(exec_names, device_names, donated)`` for one function,
        grown through two local-flow passes (use-before-def in loops).
        ``donated`` maps local jit-handle names to their
        ``donate_argnums`` positions."""
        execn: Set[str] = set()
        dev: Set[str] = set()
        donated: Dict[str, Tuple[int, ...]] = dict(
            self.module_jit_names.get(mod.relpath, {}))
        for _ in range(2):
            for node in iter_scope(fi.node):
                if isinstance(node, ast.Assign):
                    val = node.value
                    if self.is_exec_expr(mod, fi, val, execn):
                        _bind_names(node.targets, execn)
                        if isinstance(val, ast.Call) \
                                and _is_jit_call(mod, val) \
                                and len(node.targets) == 1 \
                                and isinstance(node.targets[0], ast.Name):
                            pos = _donated_positions(val)
                            if pos:
                                donated[node.targets[0].id] = pos
                    elif self.is_device_expr(mod, fi, val, dev, execn):
                        _bind_names(node.targets, dev)
                elif isinstance(node, ast.AugAssign):
                    if self.is_device_expr(mod, fi, node.value, dev,
                                           execn):
                        _bind_names([node.target], dev)
                elif isinstance(node, ast.NamedExpr):
                    if self.is_device_expr(mod, fi, node.value, dev,
                                           execn) \
                            and isinstance(node.target, ast.Name):
                        dev.add(node.target.id)
                elif isinstance(node, ast.For):
                    if self.is_device_expr(mod, fi, node.iter, dev,
                                           execn):
                        _bind_names([node.target], dev)
        return execn, dev, donated


def loop_node_ids(fi: FuncInfo) -> Set[int]:
    """``id()`` of every node lexically inside a loop body of this
    function's own scope (nested defs excluded, matching iter_scope)."""
    out: Set[int] = set()
    for node in iter_scope(fi.node):
        if isinstance(node, (ast.For, ast.While)):
            stack: List[ast.AST] = list(node.body)
            while stack:
                n = stack.pop()
                out.add(id(n))
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue
                stack.extend(ast.iter_child_nodes(n))
    return out


def host_boundary_model(project: Project) -> HostBoundaryModel:
    """The per-run cached host-boundary model (built on first use)."""
    model = getattr(project, "_host_boundary_model", None)
    if model is None:
        model = HostBoundaryModel(project)
        project._host_boundary_model = model
    return model
