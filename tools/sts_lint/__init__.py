"""sts-lint: JAX-aware static analysis for the spark_timeseries_tpu tree.

Level 1 of the two-level checking stack (level 2 is
``spark_timeseries_tpu.utils.contracts``, which checks what actually
lowers).  This package runs AST rules over the source and enforces the
invariants the last three PRs only promised in prose:

- ``STS001`` host-sync / impure calls reachable from traced code
  (``float()``/``int()``/``.item()``/``np.asarray``/``time.time()``/
  ``print`` inside ``jit``/``vmap``/``scan``/``while_loop`` bodies);
- ``STS002`` metrics / span / registry calls inside traced code (the
  PR 1 "tracer-safe observability" promise, now machine-checked);
- ``STS003`` implicit-float array creation in ``ops/`` and ``models/``
  (``jnp.zeros(shape)`` with no ``dtype=`` flips to f64 under x64);
- ``STS004`` numpy float64 creation in device code paths (silent
  promotion under x64);
- ``STS005`` Python-level branching on tracer-typed values;
- ``STS006`` recompile hazards: ``jax.jit`` of a fresh lambda/closure
  per call (defeats the global jit cache — every call retraces).

The STS100 series is the *concurrency* tier (ISSUE 14), built on a
whole-tree model of which names are locks, which statements run holding
them, and which functions run on threads:

- ``STS101`` write to lock-guarded shared state (class attribute /
  module global) outside the owning lock;
- ``STS102`` cycle in the cross-module lock-acquisition-order graph
  (potential ABBA deadlock);
- ``STS103`` blocking call (``time.sleep``, I/O, device sync, user
  callback) while holding a lock;
- ``STS104`` thread-lifecycle hygiene (non-daemon thread never joined,
  ``Event`` set without a waiter, thread target that can raise past its
  outermost try).

Level 2 of the concurrency tier is the *runtime* race harness
(``spark_timeseries_tpu.utils.races``): instrumented locks record the
acquisition-order graph actually exercised (cross-checking STS102) and
a seeded deterministic scheduler adversarially permutes thread
interleavings at instrumented boundaries (``make verify-races``).

The STS200 series is the *host-boundary* tier (ISSUE 19): a dataflow
model over the hot-path modules (``engine.py``,
``statespace/{serving,fleet,runtime,kalman}.py``, ``longseries/``,
``backtest/evaluate.py``) taints values returned by jitted /
engine-cached executables as device-resident, then polices where they
cross back to the host:

- ``STS201`` implicit device→host materialization of a device-tainted
  value (``np.asarray``/``float()``/``.item()``/``.tolist()``/
  ``__iter__``/``.block_until_ready()``) outside the sanctioned
  materialize sites — the complement of STS001, which only covers
  *inside* traced code;
- ``STS202`` ``jax.jit`` / ``.lower().compile()`` call sites inside a
  loop body on the hot path (per-iteration trace/compile hazard);
- ``STS203`` device-output slicing materialized per loop iteration
  (the per-chunk pad-slice regression engine.py already fixed once,
  now pinned tree-wide);
- ``STS204`` read of a buffer after donating it to a compiled call
  (``donate_argnums`` use-after-donate);
- ``STS205`` (advice severity — inventory, never fails the gate)
  compiled-call → host transform → compiled-call chains: the
  fusion-opportunity evidence base for ROADMAP item 1, ranked by span
  self-time in ``make fusion-audit``.

Level 2 of the host-boundary tier is
``spark_timeseries_tpu.utils.contracts.pipeline_contracts()``: it runs
the warmed chunk path and pins distinct-compiled-programs-per-stage
against a budget table plus device→host transferred bytes per warmed
chunk (0 unexpected bytes beyond result materialization).

Suppression: append ``# sts: noqa[STS0xx]`` (or bare ``# sts: noqa``)
to the offending line.  Known-and-accepted findings live in the
checked-in baseline (``tools/sts_lint/baseline.json``); only *new*
findings fail the build — and the baseline is kept EMPTY for the
tracer-safety and concurrency rules (those are fixed or suppressed
in-source with a justification, never carried as debt).
``python -m tools.sts_lint --help`` for the CLI; ``make lint`` /
``make verify-static`` are the canonical entry points.
"""

from .engine import (Finding, LintResult, lint_paths, load_baseline,
                     write_baseline, DEFAULT_BASELINE)
from .rules import (CONCURRENCY_RULES, EXAMPLES, HOST_BOUNDARY_RULES,
                    RULES, TRACER_SAFETY_RULES)

__all__ = ["Finding", "LintResult", "lint_paths", "load_baseline",
           "write_baseline", "DEFAULT_BASELINE", "RULES", "EXAMPLES",
           "TRACER_SAFETY_RULES", "CONCURRENCY_RULES",
           "HOST_BOUNDARY_RULES"]
