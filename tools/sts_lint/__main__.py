"""CLI: ``python -m tools.sts_lint [paths ...]``.

Exit 0 when every finding is suppressed or baselined; exit 1 on any new
finding (or parse error).  ``--write-baseline`` regenerates the debt
ledger instead of failing.  ``--json PATH`` writes the full machine
report (the block ``bench.py`` embeds); ``-`` writes it to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .engine import (DEFAULT_BASELINE, lint_paths, load_baseline,
                     write_baseline)
from .rules import EXAMPLES, RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="sts-lint",
        description="JAX-aware static analysis for spark_timeseries_tpu "
                    "(tracer safety, dtype discipline, recompile "
                    "stability).")
    ap.add_argument("paths", nargs="*", default=["spark_timeseries_tpu"],
                    help="files or directories to lint "
                         "(default: spark_timeseries_tpu)")
    ap.add_argument("--root", default=None,
                    help="path findings are reported relative to "
                         "(default: cwd)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON (debt ledger) to match against")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: every finding is new")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from this run's "
                         "findings and exit 0")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule codes to run "
                         "(default: all)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the JSON report here ('-' = stdout)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--explain", metavar="STSxxx", default=None,
                    help="print one rule's catalogue entry plus a "
                         "minimal violating/fixed example pair and exit")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress per-finding lines (summary only)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code, rule in sorted(RULES.items()):
            sev = " (advice)" if rule.severity == "advice" else ""
            print(f"{code}  {rule.name:24s} {rule.summary}{sev}")
        return 0

    if args.explain:
        code = args.explain.strip().upper()
        rule = RULES.get(code)
        if rule is None:
            ap.error(f"unknown rule code: {args.explain} "
                     f"(see --list-rules)")
        print(f"{code} — {rule.name} [{rule.severity}]")
        print(f"  {rule.summary}")
        bad, good = EXAMPLES[code]
        print("\nViolates:")
        for line in bad.splitlines():
            print(f"    {line}")
        print("\nFixed:")
        for line in good.splitlines():
            print(f"    {line}")
        return 0

    select = None
    if args.select:
        select = [c.strip().upper() for c in args.select.split(",")]
        unknown = [c for c in select if c not in RULES]
        if unknown:
            ap.error(f"unknown rule code(s): {', '.join(unknown)}")

    baseline = {} if (args.no_baseline or args.write_baseline) \
        else load_baseline(args.baseline)
    result, sources = lint_paths(args.paths, root=args.root,
                                 baseline=baseline, select=select)

    if args.write_baseline:
        if result.parse_errors:
            # an unparseable file's findings would silently vanish from
            # the ledger — refuse to write an incomplete baseline
            for e in result.parse_errors:
                print(f"PARSE ERROR: {e}", file=sys.stderr)
            print("sts-lint: baseline NOT written (fix parse errors "
                  "first)", file=sys.stderr)
            return 1
        entries = write_baseline(args.baseline, result, sources)
        print(f"sts-lint: baseline written to {args.baseline} "
              f"({len(entries)} fingerprints, "
              f"{sum(entries.values())} findings)")
        return 0

    # keep stdout machine-clean when the JSON report streams there
    human_out = sys.stderr if args.json_out == "-" else sys.stdout
    if not args.quiet:
        for f in result.new:
            print(f.render(), file=human_out)
        for f in result.advice:
            print(f.render(), file=human_out)
        for e in result.parse_errors:
            print(f"PARSE ERROR: {e}", file=sys.stderr)

    if args.json_out:
        payload = json.dumps(result.to_json(), indent=1)
        if args.json_out == "-":
            print(payload)
        else:
            os.makedirs(os.path.dirname(args.json_out) or ".",
                        exist_ok=True)
            with open(args.json_out, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")

    s = result.summary()
    print(f"sts-lint: {s['files_scanned']} files, "
          f"{s['findings']} new finding(s), "
          f"{s['suppressed']} suppressed, {s['baselined']} baselined, "
          f"{s['advice']} advice"
          + (f"; by code: {s['by_code']}" if s["by_code"] else ""),
          file=human_out)
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
