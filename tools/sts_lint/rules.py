"""The STS0xx rule catalogue.

Every rule is a function ``(Project, ModuleModel) -> Iterator[RawFinding]``
registered in :data:`RULES`.  Rules lean on the semantic model in
``analysis.py`` (which functions are traced, which parameters are static)
and never re-derive it.

Rule design notes, for anyone tuning these:

- STS001/STS002/STS005 only fire *inside traced functions* — the whole
  point of the model.  Host orchestration code (the ``minimize_*``
  drivers, the fit entry points) may sync, print, and record metrics
  freely; that is where those calls belong.
- STS003 deliberately distinguishes float-defaulting creators
  (``jnp.zeros(shape)`` is f32 today, f64 the day someone enables x64)
  from dtype-preserving ones (``jnp.asarray(x)`` keeps x's dtype and is
  exempt unless a float literal makes the result dtype implicit).
  Integer index math (``jnp.arange(n)``) is exempt: its default dtype
  follows the int-width config and flagging it would bury the real
  findings in noise.
- STS006 encodes a measured fact (see docs/design.md §6d): re-jitting
  the *same module-level function object* hits jax's global jit cache,
  while ``jax.jit(lambda ...)`` or jitting a nested def inside a
  per-call body compiles fresh every call.  Only the latter is flagged;
  an ``functools.lru_cache`` on the enclosing factory exempts it.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, Optional

from .analysis import (ConcurrencyModel, FuncInfo, HostBoundaryModel,
                       ModuleModel, Project, _donated_positions, _is_jit_call,
                       _modbase, _self_attr_target, canonical_tail,
                       concurrency_model, host_boundary_model,
                       hot_path_module, iter_scope, local_tainted_names,
                       locally_bound, loop_node_ids, taint_expr)


@dataclass
class RawFinding:
    code: str
    line: int
    col: int
    symbol: str          # qualname of the enclosing function ("" = module)
    message: str


@dataclass(frozen=True)
class Rule:
    code: str
    name: str
    summary: str
    check: Callable[[Project, ModuleModel], Iterator[RawFinding]]
    # "error" findings fail the gate when new; "advice" findings are
    # inventory only — reported, never baselined, never exit-nonzero
    severity: str = "error"


# ---------------------------------------------------------------------------
# STS001 — host sync / impurity inside traced code
# ---------------------------------------------------------------------------

_HOST_SYNC_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.sleep", "datetime.datetime.now",
    "datetime.datetime.utcnow", "input", "random.random",
    "random.uniform", "random.randint",
}
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NUMPY_SYNC_TAILS = {"asarray", "array", "copyto", "save", "savetxt"}


def _is_constant_expr(node: ast.AST) -> bool:
    return all(isinstance(n, (ast.Constant, ast.Tuple, ast.List,
                              ast.expr_context, ast.UnaryOp, ast.USub,
                              ast.UAdd))
               for n in ast.walk(node))


def _check_host_sync(project: Project, mod: ModuleModel
                     ) -> Iterator[RawFinding]:
    for fi in mod.functions:
        if not fi.traced:
            continue
        via = fi.traced_via or "traced"
        for node in iter_scope(fi.node):
            if not isinstance(node, ast.Call):
                continue
            canon = mod.resolve(node.func)
            tail = canonical_tail(canon) if canon else ""
            if tail in _HOST_SYNC_CALLS:
                yield RawFinding(
                    "STS001", node.lineno, node.col_offset, fi.qualname,
                    f"impure host call {tail}() inside traced code "
                    f"({via}): evaluated once at trace time, baked into "
                    f"the compiled program")
            elif tail == "print":
                yield RawFinding(
                    "STS001", node.lineno, node.col_offset, fi.qualname,
                    f"print() inside traced code ({via}) runs at trace "
                    f"time only — use jax.debug.print for runtime output")
            elif tail in ("float", "int", "bool", "complex") and node.args \
                    and not _is_constant_expr(node.args[0]):
                yield RawFinding(
                    "STS001", node.lineno, node.col_offset, fi.qualname,
                    f"{tail}() on a non-constant inside traced code "
                    f"({via}): host sync in eager, ConcretizationError "
                    f"under jit")
            elif tail.startswith("numpy.random."):
                yield RawFinding(
                    "STS001", node.lineno, node.col_offset, fi.qualname,
                    f"{tail}() inside traced code ({via}): trace-time "
                    f"randomness is baked in — thread a jax.random key")
            elif tail.startswith("numpy.") \
                    and tail.split(".")[-1] in _NUMPY_SYNC_TAILS \
                    and node.args and not _is_constant_expr(node.args[0]):
                yield RawFinding(
                    "STS001", node.lineno, node.col_offset, fi.qualname,
                    f"{tail}() on a non-constant inside traced code "
                    f"({via}): device→host materialization (fails on "
                    f"tracers under jit)")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _HOST_SYNC_METHODS \
                    and not node.args:
                yield RawFinding(
                    "STS001", node.lineno, node.col_offset, fi.qualname,
                    f".{node.func.attr}() inside traced code ({via}): "
                    f"blocking device→host sync")


# ---------------------------------------------------------------------------
# STS002 — metrics / span / registry calls inside traced code
# ---------------------------------------------------------------------------

_METRICS_MODULE_TAILS = ("utils.metrics", "utils.tracing")
_METRICS_BARE_NAMES = {
    "span", "counter", "inc", "observe", "set_gauge", "gauge",
    "histogram", "trace_instant", "observe_minimize", "record_fit",
    "instrument_fit", "get_registry", "snapshot", "add_span_listener",
}


def _metrics_canon(mod: ModuleModel, node: ast.Call) -> Optional[str]:
    canon = mod.resolve(node.func)
    if canon is None:
        return None
    tail = canonical_tail(canon)
    parts = tail.rsplit(".", 1)
    if len(parts) == 2:
        base, name = parts
        if any(base.endswith(t) or base == t.split(".")[-1]
               for t in _METRICS_MODULE_TAILS):
            return tail
    # bare name imported straight from the metrics module
    if isinstance(node.func, ast.Name):
        aliased = mod.aliases.get(node.func.id, "")
        if any(canonical_tail(aliased).startswith(t) or
               f".{t}." in aliased for t in _METRICS_MODULE_TAILS):
            return canonical_tail(aliased)
        if node.func.id in _METRICS_BARE_NAMES and aliased \
                and aliased != node.func.id:
            return canonical_tail(aliased)
    return None


def _check_metrics_in_trace(project: Project, mod: ModuleModel
                            ) -> Iterator[RawFinding]:
    for fi in mod.functions:
        if not fi.traced:
            continue
        for node in iter_scope(fi.node):
            if not isinstance(node, ast.Call):
                continue
            hit = _metrics_canon(mod, node)
            if hit:
                yield RawFinding(
                    "STS002", node.lineno, node.col_offset, fi.qualname,
                    f"observability call {hit}() inside traced code "
                    f"({fi.traced_via}): spans/counters are host-side "
                    f"only — record around the traced call, not in it")
                continue
            # calling an @instrument_fit-wrapped entry point from traced
            # code opens its span under the trace; call .__wrapped__
            canon = mod.resolve(node.func)
            target = project.lookup(canon, fi, mod)
            if target is not None and target.instrumented \
                    and not (canon or "").endswith(".__wrapped__"):
                yield RawFinding(
                    "STS002", node.lineno, node.col_offset, fi.qualname,
                    f"call to @instrument_fit-wrapped "
                    f"{canonical_tail(canon or target.name)}() inside "
                    f"traced code ({fi.traced_via}): the wrapper's span/"
                    f"counters fire at trace time — call "
                    f"{target.name}.__wrapped__ instead")


# ---------------------------------------------------------------------------
# STS003 / STS004 — dtype discipline in ops/ and models/
# ---------------------------------------------------------------------------

# creators whose no-dtype default is the *config-dependent* float width
_FLOAT_DEFAULT_CREATORS = {"zeros", "ones", "empty", "full", "eye",
                           "identity", "linspace"}
# dtype-preserving / int-defaulting creators: flagged only when a float
# literal makes the implicit result dtype float
_VALUE_DEFAULT_CREATORS = {"array", "asarray", "arange"}

_DTYPE_NAME_HINTS = {"bool", "int", "float", "complex"}


def _arg_is_dtype_like(mod: ModuleModel, node: ast.AST) -> bool:
    canon = mod.resolve(node)
    if canon is not None:
        tail = canonical_tail(canon)
        last = tail.split(".")[-1]
        if last in _DTYPE_NAME_HINTS or last.startswith(
                ("float", "int", "uint", "bool", "complex", "bfloat")):
            return True
        # a local named `dtype` / `out_dtype` / `np_dtype` passed
        # positionally is an explicit dtype choice
        if last == "dtype" or last.endswith("dtype"):
            return True
    if isinstance(node, ast.Attribute) and node.attr == "dtype":
        return True
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.startswith(("float", "int", "uint", "bool",
                                      "complex", "bfloat"))
    return False


def _has_dtype(mod: ModuleModel, call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "dtype":
            return True
    return any(_arg_is_dtype_like(mod, a) for a in call.args)


def _has_float_literal(call: ast.Call) -> bool:
    for a in call.args:
        for n in ast.walk(a):
            if isinstance(n, ast.Constant) and isinstance(n.value, float):
                return True
    return False


def _dtype_scoped(mod: ModuleModel) -> bool:
    parts = mod.relpath.split("/")
    return "ops" in parts or "models" in parts


def _enclosing(mod: ModuleModel, node: ast.AST,
               parents: Dict[ast.AST, ast.AST]) -> str:
    cur = parents.get(node)
    while cur is not None:
        fi = mod.func_of_node.get(cur)
        if fi is not None:
            return fi.qualname
        cur = parents.get(cur)
    return ""


def _parent_map(tree: ast.Module) -> Dict[ast.AST, ast.AST]:
    return {child: parent for parent in ast.walk(tree)
            for child in ast.iter_child_nodes(parent)}


def _check_dtype_discipline(project: Project, mod: ModuleModel
                            ) -> Iterator[RawFinding]:
    if not _dtype_scoped(mod):
        return
    parents = _parent_map(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = mod.resolve(node.func)
        if canon is None:
            continue
        tail = canonical_tail(canon)
        if not tail.startswith("jax.numpy."):
            continue
        name = tail.split(".")[-1]
        if name in _FLOAT_DEFAULT_CREATORS:
            if not _has_dtype(mod, node):
                where = "ops" if "ops" in mod.relpath.split("/") \
                    else "models"
                yield RawFinding(
                    "STS003", node.lineno, node.col_offset,
                    _enclosing(mod, node, parents),
                    f"jnp.{name}(...) without dtype= in {where}: "
                    f"implicit default-float dtype flips f32→f64 when "
                    f"x64 is enabled — pass dtype= explicitly")
        elif name in _VALUE_DEFAULT_CREATORS:
            if not _has_dtype(mod, node) and _has_float_literal(node):
                yield RawFinding(
                    "STS003", node.lineno, node.col_offset,
                    _enclosing(mod, node, parents),
                    f"jnp.{name}(...) with a bare float literal and no "
                    f"dtype=: the literal's implicit dtype follows the "
                    f"x64 config — pass dtype= (or derive it from an "
                    f"input's .dtype)")


_NUMPY_FLOAT_DEFAULT = {"zeros", "ones", "empty", "full", "linspace",
                        "eye", "identity"}


def _check_numpy_promotion(project: Project, mod: ModuleModel
                           ) -> Iterator[RawFinding]:
    if not _dtype_scoped(mod):
        return
    parents = _parent_map(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = mod.resolve(node.func)
        if canon is None:
            continue
        tail = canonical_tail(canon)
        if not tail.startswith("numpy."):
            continue
        name = tail.split(".")[-1]
        if name == "float64":
            yield RawFinding(
                "STS004", node.lineno, node.col_offset,
                _enclosing(mod, node, parents),
                "np.float64(...) in device code: a strongly-typed f64 "
                "scalar silently promotes every jnp operand under x64 — "
                "use a Python float (weak) or an explicit f32")
        elif name in _NUMPY_FLOAT_DEFAULT and not _has_dtype(mod, node):
            yield RawFinding(
                "STS004", node.lineno, node.col_offset,
                _enclosing(mod, node, parents),
                f"np.{name}(...) without dtype= in device code: numpy "
                f"defaults to float64, which promotes the jnp side "
                f"under x64 — pass dtype= explicitly")


# ---------------------------------------------------------------------------
# STS005 — Python-level branching on tracer values
# ---------------------------------------------------------------------------

def _check_tracer_branch(project: Project, mod: ModuleModel
                         ) -> Iterator[RawFinding]:
    taints = project.param_taint()
    for fi in mod.functions:
        if not fi.traced:
            continue
        seed = taints.get(fi, set())
        if not seed:
            continue
        tainted = local_tainted_names(fi, seed)
        for node in iter_scope(fi.node):
            test = None
            kind = None
            if isinstance(node, ast.If):
                test, kind = node.test, "if"
            elif isinstance(node, ast.While):
                test, kind = node.test, "while"
            elif isinstance(node, ast.Assert):
                test, kind = node.test, "assert"
            elif isinstance(node, ast.IfExp):
                test, kind = node.test, "conditional expression"
            if test is None or not taint_expr(test, tainted):
                continue
            yield RawFinding(
                "STS005", node.lineno, node.col_offset, fi.qualname,
                f"Python {kind} on a tracer-typed value inside traced "
                f"code ({fi.traced_via}): trace-time branch freezes one "
                f"side into the program (ConcretizationError under jit) "
                f"— use jnp.where / lax.cond, or mark the argument "
                f"static")


# ---------------------------------------------------------------------------
# STS006 — recompile hazards: fresh jit wrappers around closures
# ---------------------------------------------------------------------------

_CACHE_DECORATORS = {"functools.lru_cache", "functools.cache", "lru_cache",
                     "cache"}


def _has_cache_decorator(fi: FuncInfo) -> bool:
    for f in fi.scope_chain():
        for dec in f.decorators:
            target = dec.func if isinstance(dec, ast.Call) else dec
            canon = f.module.resolve(target)
            if canon and canonical_tail(canon) in _CACHE_DECORATORS:
                return True
    return False


def _check_recompile_hazard(project: Project, mod: ModuleModel
                            ) -> Iterator[RawFinding]:
    for fi in mod.functions:
        # jit calls at module scope run once per process — fine.  Only
        # jit calls inside function bodies can churn the cache.
        for node in iter_scope(fi.node):
            if not isinstance(node, ast.Call):
                continue
            canon = mod.resolve(node.func)
            if not canon or canonical_tail(canon) != "jax.jit" \
                    or not node.args:
                continue
            target = node.args[0]
            fresh: Optional[str] = None
            if isinstance(target, ast.Lambda):
                fresh = "a lambda"
            elif isinstance(target, ast.Name):
                resolved = fi.resolve_local(target.id)
                if resolved is not None and resolved.parent is not None:
                    fresh = f"nested function {target.id!r}"
            if fresh is None:
                continue
            if _has_cache_decorator(fi):
                continue
            yield RawFinding(
                "STS006", node.lineno, node.col_offset, fi.qualname,
                f"jax.jit({fresh}) inside a function body: a fresh "
                f"function object per call defeats jit's global cache — "
                f"every call recompiles.  Hoist the jitted callee to "
                f"module scope (closure state becomes arguments / "
                f"static args) or cache the wrapper (functools.lru_cache)")


# ---------------------------------------------------------------------------
# STS101 — shared-state write outside the owning lock
# ---------------------------------------------------------------------------
#
# Guard inference, not annotation: within a class that owns a lock (an
# attribute assigned threading.Lock/RLock/Condition), every attribute
# that is EVER mutated while holding one of the class's locks is
# *lock-guarded state*; any other mutation of the same attribute outside
# the lock is a finding.  Module globals get the same treatment against
# the module's lock globals.  ``__init__`` is exempt (the object is not
# shared yet), as are private helpers whose every intra-class call site
# holds the lock (the ``_pop_tenant`` shape: caller-holds-lock
# conventions are fine as long as every caller in fact holds it).

def _method_name(model: ConcurrencyModel, fi: FuncInfo) -> str:
    """The top-level method a (possibly nested) function belongs to."""
    top = fi
    for scope in fi.scope_chain():
        top = scope
    return top.name


def _called_locked_methods(model: ConcurrencyModel, mod: ModuleModel,
                           cls: str, lock_ids) -> set:
    """Private methods of ``cls`` whose every ``self.m(...)`` call site
    (at least one exists) runs with one of the class's locks held."""
    sites: Dict[str, list] = {}
    for fi in mod.functions:
        if model.method_class(fi) != cls:
            continue
        for node, held in model.events.get(fi, ()):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                sites.setdefault(node.func.attr, []).append(
                    bool(set(held) & lock_ids))
    return {m for m, ctx in sites.items()
            if m.startswith("_") and ctx and all(ctx)}


def _check_shared_state(project: Project, mod: ModuleModel
                        ) -> Iterator[RawFinding]:
    model = concurrency_model(project)
    base = model.modkey(mod)

    # -- class attributes against the class's own locks -------------------
    for cls in sorted(model.class_names.get(base, ())):
        lock_ids = model.lock_ids_of_class(mod, cls)
        if not lock_ids:
            continue
        lock_attrs = model.class_locks.get((base, cls), set())
        members = [fi for fi in mod.functions
                   if model.method_class(fi) == cls]
        guarded = set()
        for fi in members:
            for ev in model.mutations.get(fi, ()):
                if ev.kind == "attr" and set(ev.held) & lock_ids:
                    guarded.add(ev.name)
        if not guarded:
            continue
        relieved = _called_locked_methods(model, mod, cls, lock_ids)
        for fi in members:
            method = _method_name(model, fi)
            if method == "__init__" or method in relieved:
                continue
            for ev in model.mutations.get(fi, ()):
                if ev.kind != "attr" or ev.name not in guarded \
                        or ev.name in lock_attrs \
                        or set(ev.held) & lock_ids:
                    continue
                reach = " (thread-reachable)" \
                    if fi in model.thread_reachable else ""
                yield RawFinding(
                    "STS101", ev.node.lineno, ev.node.col_offset,
                    fi.qualname,
                    f"write to lock-guarded state self.{ev.name} outside "
                    f"`with {sorted(lock_ids)[0].rsplit('.', 1)[-1]}` "
                    f"({cls} mutates it under its lock elsewhere)"
                    f"{reach}: a concurrent reader/writer can observe a "
                    f"torn or lost update")

    # -- module globals against the module's lock globals -----------------
    mod_locks = {lid for (b, _n), lid in model.module_locks.items()
                 if b == base}
    if not mod_locks:
        return
    guarded_globals = set()
    for fi in mod.functions:
        for ev in model.mutations.get(fi, ()):
            if ev.kind == "global" \
                    and ev.name in model.module_globals.get(base, ()) \
                    and not locally_bound(ev.fi, ev.name) \
                    and set(ev.held) & mod_locks:
                guarded_globals.add(ev.name)
    for fi in mod.functions:
        for ev in model.mutations.get(fi, ()):
            if ev.kind != "global" or ev.name not in guarded_globals \
                    or locally_bound(ev.fi, ev.name) \
                    or set(ev.held) & mod_locks:
                continue
            reach = " (thread-reachable)" \
                if fi in model.thread_reachable else ""
            yield RawFinding(
                "STS101", ev.node.lineno, ev.node.col_offset, fi.qualname,
                f"write to lock-guarded module global {ev.name} outside "
                f"its module lock (it is mutated under "
                f"{sorted(mod_locks)[0]} elsewhere){reach}: concurrent "
                f"mutation can tear or lose the update")


# ---------------------------------------------------------------------------
# STS102 — lock-acquisition-order cycles (potential ABBA deadlock)
# ---------------------------------------------------------------------------

def _check_lock_order(project: Project, mod: ModuleModel
                      ) -> Iterator[RawFinding]:
    model = concurrency_model(project)
    for cycle in model.lock_cycles():
        in_cycle = set(cycle)
        edges = sorted((pair, loc) for pair, loc in model.edges.items()
                       if pair[0] in in_cycle and pair[1] in in_cycle)
        if not edges:
            continue
        anchor_pair, anchor = edges[0]
        if anchor[0] != mod.relpath:
            continue          # reported once, in the first edge's module
        detail = "; ".join(
            f"{a}->{b} at {loc[0]}:{loc[1]} ({loc[2]})"
            for (a, b), loc in edges[:4])
        yield RawFinding(
            "STS102", anchor[1], 0, anchor[2],
            f"lock-acquisition-order cycle {' -> '.join(cycle)} -> "
            f"{cycle[0]}: two threads taking these locks in opposite "
            f"orders deadlock (ABBA).  Edges: {detail}.  Pick one global "
            f"order (see docs/design.md §6d lock-ordering table) and "
            f"restructure the out-of-order acquisition")


# ---------------------------------------------------------------------------
# STS103 — blocking call while holding a lock
# ---------------------------------------------------------------------------

def _check_blocking_under_lock(project: Project, mod: ModuleModel
                               ) -> Iterator[RawFinding]:
    model = concurrency_model(project)
    for fi in mod.functions:
        for node, held in model.events.get(fi, ()):
            if not held or not isinstance(node, ast.Call):
                continue
            # Condition.wait on the lock being held RELEASES that lock
            # while waiting — the one legitimate blocking-wait-under-lock
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "wait" \
                    and model.resolve_lock(fi, node.func.value) in held:
                continue
            reason = model.blocking_reason(fi, node)
            if reason is not None:
                yield RawFinding(
                    "STS103", node.lineno, node.col_offset, fi.qualname,
                    f"blocking call {reason} while holding "
                    f"{', '.join(held)}: every thread needing the lock "
                    f"stalls behind this wait — move the blocking work "
                    f"outside the `with` block")
                continue
            # user-supplied callback invoked under the lock
            if isinstance(node.func, ast.Name):
                name = node.func.id
                in_params = any(name in scope.params
                                for scope in fi.scope_chain())
                if in_params and fi.resolve_local(name) is None:
                    yield RawFinding(
                        "STS103", node.lineno, node.col_offset,
                        fi.qualname,
                        f"user callback {name}() invoked while holding "
                        f"{', '.join(held)}: arbitrary user code can "
                        f"block (or re-enter the lock) — snapshot state "
                        f"under the lock, call the callback after "
                        f"releasing it")
                    continue
            g = model.resolve_call(fi, node)
            if g is not None and model.blocking_tc.get(g):
                yield RawFinding(
                    "STS103", node.lineno, node.col_offset, fi.qualname,
                    f"call to {g.qualname}() while holding "
                    f"{', '.join(held)}; it blocks "
                    f"({model.blocking_tc[g]}) — move it outside the "
                    f"`with` block")


# ---------------------------------------------------------------------------
# STS104 — thread-lifecycle hygiene
# ---------------------------------------------------------------------------

def _broad_try(stmt: ast.AST) -> bool:
    if not isinstance(stmt, ast.Try):
        return False
    for h in stmt.handlers:
        if h.type is None:
            return True
        elts = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        for n in elts:
            last = n.attr if isinstance(n, ast.Attribute) else (
                n.id if isinstance(n, ast.Name) else "")
            if last in ("Exception", "BaseException"):
                return True
    return False


def _is_trivial_stmt(stmt: ast.AST) -> bool:
    if isinstance(stmt, (ast.Pass, ast.Global, ast.Nonlocal)):
        return True
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
        return True          # docstring / bare literal
    if isinstance(stmt, ast.Return):
        return stmt.value is None or isinstance(stmt.value, ast.Constant)
    if isinstance(stmt, ast.Assign):
        return isinstance(stmt.value, (ast.Constant, ast.Name))
    return False


def _event_base_names(fi: FuncInfo, call: ast.Call) -> list:
    """Names an Event construction is bound to (local name or self attr)."""
    out = []
    for node in iter_scope(fi.node):
        if isinstance(node, ast.Assign) and node.value is call:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.append(t.id)
                else:
                    attr = _self_attr_target(t)
                    if attr:
                        out.append(attr)
    return out


def _attr_calls_on(mod: ModuleModel, base_name: str, attrs: set) -> bool:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Attribute) and node.attr in attrs:
            v = node.value
            if isinstance(v, ast.Name) and v.id == base_name:
                return True
            if isinstance(v, ast.Attribute) and v.attr == base_name \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == "self":
                return True
    return False


def _check_thread_lifecycle(project: Project, mod: ModuleModel
                            ) -> Iterator[RawFinding]:
    model = concurrency_model(project)
    for spawn in model.spawns:
        if spawn.fi.module is not mod:
            continue
        if not spawn.daemon and not spawn.joined:
            what = f"thread {spawn.assigned!r}" if spawn.assigned \
                else "anonymous thread"
            yield RawFinding(
                "STS104", spawn.node.lineno, spawn.node.col_offset,
                spawn.fi.qualname,
                f"non-daemon {what} is never joined: it outlives its "
                f"owner and blocks interpreter shutdown — pass "
                f"daemon=True (abandonable work) or join it on every "
                f"exit path")
        # a thread target that can raise past its outermost try kills
        # the thread silently (the exception is printed, the work is
        # lost, nothing upstream notices)
        t = spawn.target
        if t is not None and t.module is mod:
            body = list(t.node.body)
            risky = [s for s in body
                     if not _broad_try(s) and not _is_trivial_stmt(s)]
            if risky:
                yield RawFinding(
                    "STS104", spawn.node.lineno, spawn.node.col_offset,
                    spawn.fi.qualname,
                    f"thread target {t.qualname}() can raise past its "
                    f"outermost try (line {risky[0].lineno} is not "
                    f"exception-contained): an escaping exception kills "
                    f"the thread silently — wrap the body in "
                    f"try/except and surface the failure (flag, queue, "
                    f"counter)")
    for call, fi, _kind in model.event_objects:
        if fi.module is not mod:
            continue
        for name in _event_base_names(fi, call):
            if _attr_calls_on(mod, name, {"set"}) \
                    and not _attr_calls_on(mod, name,
                                           {"wait", "is_set"}):
                yield RawFinding(
                    "STS104", call.lineno, call.col_offset, fi.qualname,
                    f"threading.Event {name!r} is set() but never "
                    f"wait()ed on or polled in this module: either dead "
                    f"signaling (delete it) or the waiter lives behind "
                    f"an interface the model cannot see (suppress with "
                    f"a justification)")


# ---------------------------------------------------------------------------
# STS201–STS205 — the host-boundary tier (hot-path modules only)
# ---------------------------------------------------------------------------
#
# These rules run on the orchestration layer *between* compiled programs
# — the complement of STS001, which polices code *inside* the trace.
# Device taint starts only at proven compiled-callable call results (see
# HostBoundaryModel), so a finding always names a value that really did
# come off an executable.

# Sanctioned device→host materialize sites: the places where results are
# *supposed* to land on the host (chunk-result collection, serving tick
# delivery, segment combination).  Matched against the whole enclosing
# scope chain, so nested helpers of a sanctioned function are covered.
# Additions here are reviewed policy — see docs/design.md §6d.
SANCTIONED_MATERIALIZE = frozenset({
    # engine: the one chunk-result collection point + pad-slice rebuild
    ("engine", "FitEngine._rebuild"),
    ("engine", "FitEngine.fit"),
    ("engine", "FitEngine.stream_fit"),
    # serving: tick/forecast delivery back to the caller
    ("serving", "ServingSession.update"),
    ("serving", "ServingSession.update_batch"),
    ("serving", "ServingSession.forecast"),
    ("serving", "ServingSession.warmup"),
    ("serving", "ServingSession.heal"),
    # fleet: coalesced-tick scatter-back (hoisted; regression-pinned)
    ("fleet", "FleetScheduler._dispatch_group"),
    ("fleet", "FleetScheduler.warmup"),
    # longseries: the one post-loop accumulator pull per combination
    # (device-resident cross-chunk reduction, docs/design.md §6e) —
    # same policy for the staged and the fused fit→combine drivers
    ("combine", "combine_segments"),
    ("combine", "fused_fit_combine"),
    # backtest: metric-table delivery at the end of a sweep
    ("evaluate", "evaluate_candidate"),
})


def _sanctioned(mod: ModuleModel, fi: FuncInfo) -> bool:
    base = _modbase(mod)
    return any((base, f.qualname) in SANCTIONED_MATERIALIZE
               for f in fi.scope_chain())


_COMPREHENSIONS = (ast.ListComp, ast.SetComp, ast.GeneratorExp)


def _materialize_site(model: HostBoundaryModel, mod: ModuleModel,
                      fi: FuncInfo, node: ast.AST, dev, execn):
    """``(kind, device_arg)`` when ``node`` is a host-materialization of
    a device-tainted value; None otherwise."""
    if isinstance(node, ast.Call):
        canon = mod.resolve(node.func)
        tail = canonical_tail(canon) if canon else ""
        if (tail in model.MATERIALIZE_TAILS
                or tail in model.MATERIALIZE_BUILTINS) and node.args \
                and model.is_device_expr(mod, fi, node.args[0], dev, execn):
            return f"{tail}()", node.args[0]
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in model.MATERIALIZE_METHODS \
                and model.is_device_expr(mod, fi, node.func.value, dev,
                                         execn):
            return f".{node.func.attr}()", node.func.value
    elif isinstance(node, ast.For):
        if model.is_device_expr(mod, fi, node.iter, dev, execn):
            return "__iter__ (for-loop over a device array)", node.iter
    elif isinstance(node, _COMPREHENSIONS):
        for gen in node.generators:
            if model.is_device_expr(mod, fi, gen.iter, dev, execn):
                return "__iter__ (comprehension over a device array)", \
                    gen.iter
    return None


def _has_dev_slice(model: HostBoundaryModel, mod: ModuleModel,
                   fi: FuncInfo, expr: ast.AST, dev, execn) -> bool:
    """Does ``expr`` contain a *slice* subscript of a device value?
    Plain integer/tuple indexing (``out[0]``) is not the pad-slice
    pattern and stays out of STS203's domain."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Subscript) \
                and any(isinstance(s, ast.Slice) for s in ast.walk(n.slice)) \
                and model.is_device_expr(mod, fi, n.value, dev, execn):
            return True
    return False


def _boundary_functions(project: Project, mod: ModuleModel):
    """Hot-path functions the STS200 rules inspect, with their taints.
    Traced functions are STS001's domain; lambdas carry no useful
    qualname and their params are never device-tainted by this model."""
    if not hot_path_module(mod):
        return
    model = host_boundary_model(project)
    for fi in mod.functions:
        if fi.traced or fi.is_lambda:
            continue
        execn, dev, donated = model.function_taints(mod, fi)
        if not dev and not execn and not donated:
            continue
        yield model, fi, execn, dev, donated


def _check_implicit_materialize(project: Project, mod: ModuleModel
                                ) -> Iterator[RawFinding]:
    for model, fi, execn, dev, _donated in _boundary_functions(project,
                                                               mod):
        loops = loop_node_ids(fi)
        in_sanctioned = _sanctioned(mod, fi)
        for node in iter_scope(fi.node):
            hit = _materialize_site(model, mod, fi, node, dev, execn)
            if hit is None:
                continue
            kind, arg = hit
            if id(node) in loops and _has_dev_slice(model, mod, fi,
                                                    arg, dev, execn):
                continue          # STS203's finding, not this one
            if in_sanctioned:
                continue
            yield RawFinding(
                "STS201", node.lineno, node.col_offset, fi.qualname,
                f"implicit device→host materialization via {kind} of a "
                f"compiled-program output on the hot path: each crossing "
                f"blocks on the device and serializes the pipeline — "
                f"move it to a sanctioned materialize site (or extend "
                f"the sanctioned table in a reviewed change)")


def _check_jit_in_loop(project: Project, mod: ModuleModel
                       ) -> Iterator[RawFinding]:
    if not hot_path_module(mod):
        return
    for fi in mod.functions:
        loops = loop_node_ids(fi)
        if not loops:
            continue
        for node in iter_scope(fi.node):
            if id(node) not in loops or not _is_jit_call(mod, node):
                continue
            if _has_cache_decorator(fi):
                continue
            what = "jax.jit(...)" if not (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "compile") else ".lower().compile()"
            yield RawFinding(
                "STS202", node.lineno, node.col_offset, fi.qualname,
                f"{what} inside a loop body on the hot path: every "
                f"iteration pays trace+compile (or at best a cache "
                f"probe) — hoist the compiled callable out of the loop "
                f"or route it through the engine's executable cache")


def _check_device_slice_in_loop(project: Project, mod: ModuleModel
                                ) -> Iterator[RawFinding]:
    for model, fi, execn, dev, _donated in _boundary_functions(project,
                                                               mod):
        loops = loop_node_ids(fi)
        if not loops:
            continue
        for node in iter_scope(fi.node):
            if id(node) not in loops:
                continue
            hit = _materialize_site(model, mod, fi, node, dev, execn)
            if hit is None:
                continue
            kind, arg = hit
            if not _has_dev_slice(model, mod, fi, arg, dev, execn):
                continue
            yield RawFinding(
                "STS203", node.lineno, node.col_offset, fi.qualname,
                f"per-iteration device-output slice materialized via "
                f"{kind} inside a loop: each iteration compiles/launches "
                f"a slice program and blocks on its transfer (the "
                f"per-chunk pad-slice regression engine.py already fixed "
                f"once) — materialize the whole array once before the "
                f"loop and slice on the host")


def _check_use_after_donate(project: Project, mod: ModuleModel
                            ) -> Iterator[RawFinding]:
    for model, fi, execn, dev, donated in _boundary_functions(project,
                                                              mod):
        if not donated:
            continue
        # (donated argument name, dispatch line) per dispatch site
        dispatches = []
        for node in iter_scope(fi.node):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Name):
                continue
            positions = donated.get(node.func.id)
            if not positions:
                continue
            for p in positions:
                if p < len(node.args) and isinstance(node.args[p],
                                                     ast.Name):
                    dispatches.append((node.args[p].id, node.lineno,
                                       node.col_offset, node.func.id))
        if not dispatches:
            continue
        for name, line, col, callee in dispatches:
            for node in iter_scope(fi.node):
                if isinstance(node, ast.Name) and node.id == name \
                        and isinstance(node.ctx, ast.Load) \
                        and node.lineno > line:
                    yield RawFinding(
                        "STS204", node.lineno, node.col_offset,
                        fi.qualname,
                        f"use of {name!r} after it was donated to "
                        f"{callee}() (donate_argnums) at line {line}: "
                        f"the buffer is deleted on dispatch — reading "
                        f"it raises or returns garbage.  Rebind the "
                        f"result or copy before donating")
                    break


def _check_fusion_chain(project: Project, mod: ModuleModel
                        ) -> Iterator[RawFinding]:
    """STS205 (advice): jitted-call → host transform → jitted-call —
    the fusion-opportunity inventory for ROADMAP item 1.  One finding
    per function; ranked by span self-time in `make fusion-audit`."""
    for model, fi, execn, dev, _donated in _boundary_functions(project,
                                                               mod):
        loops = loop_node_ids(fi)
        mats = []           # (lineno, in_loop) of host materializations
        disps = []          # (lineno, in_loop) of compiled dispatches
        for node in iter_scope(fi.node):
            if _materialize_site(model, mod, fi, node, dev, execn):
                mats.append((node.lineno, id(node) in loops))
            if isinstance(node, ast.Call) \
                    and model.is_exec_expr(mod, fi, node.func, execn):
                disps.append((node.lineno, id(node) in loops))
        if not mats or not disps:
            continue
        chained = any(d > m for m, _ in mats for d, _ in disps) \
            or (any(il for _, il in mats) and any(il for _, il in disps))
        if not chained:
            continue
        first = min(m for m, _ in mats)
        yield RawFinding(
            "STS205", first, 0, fi.qualname,
            f"fusion opportunity: compiled-call → host transform → "
            f"compiled-call chain ({len(disps)} dispatch, {len(mats)} "
            f"host-materialize site(s)) — candidate for whole-pipeline "
            f"fusion (ROADMAP item 1); see `make fusion-audit` for the "
            f"ranked inventory")


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

RULES: Dict[str, Rule] = {r.code: r for r in [
    Rule("STS001", "host-sync-in-trace",
         "Host-sync / impure calls (float/int/.item/np.asarray/time/"
         "print) reachable from traced code", _check_host_sync),
    Rule("STS002", "metrics-in-trace",
         "Metrics / span / registry calls inside traced code "
         "(tracer-safe observability)", _check_metrics_in_trace),
    Rule("STS003", "implicit-float-dtype",
         "Array creation in ops/ and models/ without an explicit dtype",
         _check_dtype_discipline),
    Rule("STS004", "numpy-promotion",
         "numpy float64 creation in device code paths (silent promotion "
         "under x64)", _check_numpy_promotion),
    Rule("STS005", "tracer-branch",
         "Python-level branching on tracer-typed values",
         _check_tracer_branch),
    Rule("STS006", "recompile-hazard",
         "jax.jit of a per-call closure (defeats the jit cache)",
         _check_recompile_hazard),
    Rule("STS101", "unguarded-shared-write",
         "Write to lock-guarded shared state (class attr / module "
         "global) outside the owning lock", _check_shared_state),
    Rule("STS102", "lock-order-cycle",
         "Cycle in the whole-tree lock-acquisition-order graph "
         "(potential ABBA deadlock)", _check_lock_order),
    Rule("STS103", "blocking-under-lock",
         "Blocking call (sleep/IO/device sync/user callback) while "
         "holding a lock", _check_blocking_under_lock),
    Rule("STS104", "thread-lifecycle",
         "Thread-lifecycle hygiene: unjoined non-daemon threads, "
         "waiterless Events, raise-through thread targets",
         _check_thread_lifecycle),
    Rule("STS201", "implicit-materialize",
         "Implicit device→host materialization of a compiled-program "
         "output outside sanctioned sites (hot path)",
         _check_implicit_materialize),
    Rule("STS202", "jit-in-loop",
         "jax.jit / .lower().compile() call site inside a loop body on "
         "the hot path", _check_jit_in_loop),
    Rule("STS203", "device-slice-in-loop",
         "Device-output slice materialized per loop iteration (the "
         "per-chunk pad-slice pattern)", _check_device_slice_in_loop),
    Rule("STS204", "use-after-donate",
         "Read of a buffer after donating it to a compiled call "
         "(donate_argnums)", _check_use_after_donate),
    Rule("STS205", "fusion-chain",
         "Compiled-call → host transform → compiled-call chain "
         "(fusion-opportunity inventory; advice only)",
         _check_fusion_chain, severity="advice"),
]}

TRACER_SAFETY_RULES = ("STS001", "STS002", "STS005", "STS006")
DTYPE_RULES = ("STS003", "STS004")
# the concurrency tier: like the tracer-safety rules these must never be
# baselined — every real finding is fixed or suppressed in-source with a
# written justification
CONCURRENCY_RULES = ("STS101", "STS102", "STS103", "STS104")
# the host-boundary tier: STS201–204 are correctness/perf gates (empty
# baseline, same policy as above); STS205 is advice severity — it feeds
# the fusion audit and never fails the gate
HOST_BOUNDARY_RULES = ("STS201", "STS202", "STS203", "STS204", "STS205")


# ---------------------------------------------------------------------------
# --explain examples: one minimal violating / fixed pair per rule
# ---------------------------------------------------------------------------

EXAMPLES: Dict[str, tuple] = {
    "STS001": (
        "@jax.jit\n"
        "def step(x):\n"
        "    t = time.time()          # baked in at trace time\n"
        "    return x * t",
        "def step(x, t):               # pass host values as arguments\n"
        "    return x * t\n"
        "step_j = jax.jit(step)\n"
        "out = step_j(x, time.time())",
    ),
    "STS002": (
        "@jax.jit\n"
        "def fit(y):\n"
        "    with metrics.span(\"fit\"):   # fires at trace time only\n"
        "        return solve(y)",
        "def fit(y):\n"
        "    with metrics.span(\"fit\"):   # span around the traced call\n"
        "        return fit_jit(y)",
    ),
    "STS003": (
        "def init(n):\n"
        "    return jnp.zeros((n,))        # f32 today, f64 under x64",
        "def init(n, dtype):\n"
        "    return jnp.zeros((n,), dtype=dtype)",
    ),
    "STS004": (
        "scale = np.float64(2.0)           # strong f64, promotes jnp\n"
        "y = x * scale",
        "scale = 2.0                       # weak Python float\n"
        "y = x * scale",
    ),
    "STS005": (
        "@jax.jit\n"
        "def clip(x, lo):\n"
        "    if x < lo:                    # tracer in a Python branch\n"
        "        return lo\n"
        "    return x",
        "@jax.jit\n"
        "def clip(x, lo):\n"
        "    return jnp.where(x < lo, lo, x)",
    ),
    "STS006": (
        "def fit(y, order):\n"
        "    f = jax.jit(lambda y: solve(y, order))   # fresh per call\n"
        "    return f(y)",
        "_solve_j = jax.jit(solve, static_argnums=(1,))  # module scope\n"
        "def fit(y, order):\n"
        "    return _solve_j(y, order)",
    ),
    "STS101": (
        "def put(self, k, v):\n"
        "    self._cache[k] = v            # mutated under lock elsewhere",
        "def put(self, k, v):\n"
        "    with self._lock:\n"
        "        self._cache[k] = v",
    ),
    "STS102": (
        "# thread 1: with a: with b: ...\n"
        "# thread 2: with b: with a: ...   # opposite order → ABBA",
        "# pick one global order (design.md §6d table) and take both\n"
        "# locks in that order everywhere:\n"
        "# with a: with b: ...",
    ),
    "STS103": (
        "with self._lock:\n"
        "    arr.block_until_ready()       # every waiter stalls",
        "with self._lock:\n"
        "    arr = self._pending\n"
        "arr.block_until_ready()           # blocking wait outside",
    ),
    "STS104": (
        "t = threading.Thread(target=work)\n"
        "t.start()                         # never joined, non-daemon",
        "t = threading.Thread(target=work, daemon=True)\n"
        "t.start()                         # or: join on every exit path",
    ),
    "STS201": (
        "out = compiled(batch)\n"
        "for row in np.asarray(out):       # implicit D2H crossing\n"
        "    publish(row)",
        "# materialize once, at the sanctioned collection site:\n"
        "host = collect(out)               # engine._materialize\n"
        "for row in host:\n"
        "    publish(row)",
    ),
    "STS202": (
        "for chunk in chunks:\n"
        "    f = jax.jit(step)             # per-iteration cache probe\n"
        "    out = f(chunk)",
        "f = jax.jit(step)                 # hoisted: compile once\n"
        "for chunk in chunks:\n"
        "    out = f(chunk)",
    ),
    "STS203": (
        "out = compiled(batch)\n"
        "for lo in offsets:\n"
        "    part = np.asarray(out[lo:lo + n])   # slice program + D2H\n"
        "    deliver(part)",
        "host = np.asarray(out)            # one transfer\n"
        "for lo in offsets:\n"
        "    deliver(host[lo:lo + n])      # host-side slicing is free",
    ),
    "STS204": (
        "f = jax.jit(step, donate_argnums=(0,))\n"
        "out = f(state)\n"
        "print(state.sum())                # state was deleted on dispatch",
        "f = jax.jit(step, donate_argnums=(0,))\n"
        "state = f(state)                  # rebind: old buffer is gone",
    ),
    "STS205": (
        "x = f_jit(a)\n"
        "h = np.asarray(x) * w             # host hop between programs\n"
        "y = g_jit(h)",
        "# fuse the host transform into one compiled program\n"
        "# (ROADMAP item 1):\n"
        "y = fg_jit(a, w)",
    ),
}
