"""The STS0xx rule catalogue.

Every rule is a function ``(Project, ModuleModel) -> Iterator[RawFinding]``
registered in :data:`RULES`.  Rules lean on the semantic model in
``analysis.py`` (which functions are traced, which parameters are static)
and never re-derive it.

Rule design notes, for anyone tuning these:

- STS001/STS002/STS005 only fire *inside traced functions* — the whole
  point of the model.  Host orchestration code (the ``minimize_*``
  drivers, the fit entry points) may sync, print, and record metrics
  freely; that is where those calls belong.
- STS003 deliberately distinguishes float-defaulting creators
  (``jnp.zeros(shape)`` is f32 today, f64 the day someone enables x64)
  from dtype-preserving ones (``jnp.asarray(x)`` keeps x's dtype and is
  exempt unless a float literal makes the result dtype implicit).
  Integer index math (``jnp.arange(n)``) is exempt: its default dtype
  follows the int-width config and flagging it would bury the real
  findings in noise.
- STS006 encodes a measured fact (see docs/design.md §6d): re-jitting
  the *same module-level function object* hits jax's global jit cache,
  while ``jax.jit(lambda ...)`` or jitting a nested def inside a
  per-call body compiles fresh every call.  Only the latter is flagged;
  an ``functools.lru_cache`` on the enclosing factory exempts it.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, Optional

from .analysis import (FuncInfo, ModuleModel, Project, canonical_tail,
                       iter_scope, local_tainted_names, taint_expr)


@dataclass
class RawFinding:
    code: str
    line: int
    col: int
    symbol: str          # qualname of the enclosing function ("" = module)
    message: str


@dataclass(frozen=True)
class Rule:
    code: str
    name: str
    summary: str
    check: Callable[[Project, ModuleModel], Iterator[RawFinding]]


# ---------------------------------------------------------------------------
# STS001 — host sync / impurity inside traced code
# ---------------------------------------------------------------------------

_HOST_SYNC_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.sleep", "datetime.datetime.now",
    "datetime.datetime.utcnow", "input", "random.random",
    "random.uniform", "random.randint",
}
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NUMPY_SYNC_TAILS = {"asarray", "array", "copyto", "save", "savetxt"}


def _is_constant_expr(node: ast.AST) -> bool:
    return all(isinstance(n, (ast.Constant, ast.Tuple, ast.List,
                              ast.expr_context, ast.UnaryOp, ast.USub,
                              ast.UAdd))
               for n in ast.walk(node))


def _check_host_sync(project: Project, mod: ModuleModel
                     ) -> Iterator[RawFinding]:
    for fi in mod.functions:
        if not fi.traced:
            continue
        via = fi.traced_via or "traced"
        for node in iter_scope(fi.node):
            if not isinstance(node, ast.Call):
                continue
            canon = mod.resolve(node.func)
            tail = canonical_tail(canon) if canon else ""
            if tail in _HOST_SYNC_CALLS:
                yield RawFinding(
                    "STS001", node.lineno, node.col_offset, fi.qualname,
                    f"impure host call {tail}() inside traced code "
                    f"({via}): evaluated once at trace time, baked into "
                    f"the compiled program")
            elif tail == "print":
                yield RawFinding(
                    "STS001", node.lineno, node.col_offset, fi.qualname,
                    f"print() inside traced code ({via}) runs at trace "
                    f"time only — use jax.debug.print for runtime output")
            elif tail in ("float", "int", "bool", "complex") and node.args \
                    and not _is_constant_expr(node.args[0]):
                yield RawFinding(
                    "STS001", node.lineno, node.col_offset, fi.qualname,
                    f"{tail}() on a non-constant inside traced code "
                    f"({via}): host sync in eager, ConcretizationError "
                    f"under jit")
            elif tail.startswith("numpy.random."):
                yield RawFinding(
                    "STS001", node.lineno, node.col_offset, fi.qualname,
                    f"{tail}() inside traced code ({via}): trace-time "
                    f"randomness is baked in — thread a jax.random key")
            elif tail.startswith("numpy.") \
                    and tail.split(".")[-1] in _NUMPY_SYNC_TAILS \
                    and node.args and not _is_constant_expr(node.args[0]):
                yield RawFinding(
                    "STS001", node.lineno, node.col_offset, fi.qualname,
                    f"{tail}() on a non-constant inside traced code "
                    f"({via}): device→host materialization (fails on "
                    f"tracers under jit)")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _HOST_SYNC_METHODS \
                    and not node.args:
                yield RawFinding(
                    "STS001", node.lineno, node.col_offset, fi.qualname,
                    f".{node.func.attr}() inside traced code ({via}): "
                    f"blocking device→host sync")


# ---------------------------------------------------------------------------
# STS002 — metrics / span / registry calls inside traced code
# ---------------------------------------------------------------------------

_METRICS_MODULE_TAILS = ("utils.metrics", "utils.tracing")
_METRICS_BARE_NAMES = {
    "span", "counter", "inc", "observe", "set_gauge", "gauge",
    "histogram", "trace_instant", "observe_minimize", "record_fit",
    "instrument_fit", "get_registry", "snapshot", "add_span_listener",
}


def _metrics_canon(mod: ModuleModel, node: ast.Call) -> Optional[str]:
    canon = mod.resolve(node.func)
    if canon is None:
        return None
    tail = canonical_tail(canon)
    parts = tail.rsplit(".", 1)
    if len(parts) == 2:
        base, name = parts
        if any(base.endswith(t) or base == t.split(".")[-1]
               for t in _METRICS_MODULE_TAILS):
            return tail
    # bare name imported straight from the metrics module
    if isinstance(node.func, ast.Name):
        aliased = mod.aliases.get(node.func.id, "")
        if any(canonical_tail(aliased).startswith(t) or
               f".{t}." in aliased for t in _METRICS_MODULE_TAILS):
            return canonical_tail(aliased)
        if node.func.id in _METRICS_BARE_NAMES and aliased \
                and aliased != node.func.id:
            return canonical_tail(aliased)
    return None


def _check_metrics_in_trace(project: Project, mod: ModuleModel
                            ) -> Iterator[RawFinding]:
    for fi in mod.functions:
        if not fi.traced:
            continue
        for node in iter_scope(fi.node):
            if not isinstance(node, ast.Call):
                continue
            hit = _metrics_canon(mod, node)
            if hit:
                yield RawFinding(
                    "STS002", node.lineno, node.col_offset, fi.qualname,
                    f"observability call {hit}() inside traced code "
                    f"({fi.traced_via}): spans/counters are host-side "
                    f"only — record around the traced call, not in it")
                continue
            # calling an @instrument_fit-wrapped entry point from traced
            # code opens its span under the trace; call .__wrapped__
            canon = mod.resolve(node.func)
            target = project.lookup(canon, fi, mod)
            if target is not None and target.instrumented \
                    and not (canon or "").endswith(".__wrapped__"):
                yield RawFinding(
                    "STS002", node.lineno, node.col_offset, fi.qualname,
                    f"call to @instrument_fit-wrapped "
                    f"{canonical_tail(canon or target.name)}() inside "
                    f"traced code ({fi.traced_via}): the wrapper's span/"
                    f"counters fire at trace time — call "
                    f"{target.name}.__wrapped__ instead")


# ---------------------------------------------------------------------------
# STS003 / STS004 — dtype discipline in ops/ and models/
# ---------------------------------------------------------------------------

# creators whose no-dtype default is the *config-dependent* float width
_FLOAT_DEFAULT_CREATORS = {"zeros", "ones", "empty", "full", "eye",
                           "identity", "linspace"}
# dtype-preserving / int-defaulting creators: flagged only when a float
# literal makes the implicit result dtype float
_VALUE_DEFAULT_CREATORS = {"array", "asarray", "arange"}

_DTYPE_NAME_HINTS = {"bool", "int", "float", "complex"}


def _arg_is_dtype_like(mod: ModuleModel, node: ast.AST) -> bool:
    canon = mod.resolve(node)
    if canon is not None:
        tail = canonical_tail(canon)
        last = tail.split(".")[-1]
        if last in _DTYPE_NAME_HINTS or last.startswith(
                ("float", "int", "uint", "bool", "complex", "bfloat")):
            return True
        # a local named `dtype` / `out_dtype` / `np_dtype` passed
        # positionally is an explicit dtype choice
        if last == "dtype" or last.endswith("dtype"):
            return True
    if isinstance(node, ast.Attribute) and node.attr == "dtype":
        return True
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.startswith(("float", "int", "uint", "bool",
                                      "complex", "bfloat"))
    return False


def _has_dtype(mod: ModuleModel, call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "dtype":
            return True
    return any(_arg_is_dtype_like(mod, a) for a in call.args)


def _has_float_literal(call: ast.Call) -> bool:
    for a in call.args:
        for n in ast.walk(a):
            if isinstance(n, ast.Constant) and isinstance(n.value, float):
                return True
    return False


def _dtype_scoped(mod: ModuleModel) -> bool:
    parts = mod.relpath.split("/")
    return "ops" in parts or "models" in parts


def _enclosing(mod: ModuleModel, node: ast.AST,
               parents: Dict[ast.AST, ast.AST]) -> str:
    cur = parents.get(node)
    while cur is not None:
        fi = mod.func_of_node.get(cur)
        if fi is not None:
            return fi.qualname
        cur = parents.get(cur)
    return ""


def _parent_map(tree: ast.Module) -> Dict[ast.AST, ast.AST]:
    return {child: parent for parent in ast.walk(tree)
            for child in ast.iter_child_nodes(parent)}


def _check_dtype_discipline(project: Project, mod: ModuleModel
                            ) -> Iterator[RawFinding]:
    if not _dtype_scoped(mod):
        return
    parents = _parent_map(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = mod.resolve(node.func)
        if canon is None:
            continue
        tail = canonical_tail(canon)
        if not tail.startswith("jax.numpy."):
            continue
        name = tail.split(".")[-1]
        if name in _FLOAT_DEFAULT_CREATORS:
            if not _has_dtype(mod, node):
                where = "ops" if "ops" in mod.relpath.split("/") \
                    else "models"
                yield RawFinding(
                    "STS003", node.lineno, node.col_offset,
                    _enclosing(mod, node, parents),
                    f"jnp.{name}(...) without dtype= in {where}: "
                    f"implicit default-float dtype flips f32→f64 when "
                    f"x64 is enabled — pass dtype= explicitly")
        elif name in _VALUE_DEFAULT_CREATORS:
            if not _has_dtype(mod, node) and _has_float_literal(node):
                yield RawFinding(
                    "STS003", node.lineno, node.col_offset,
                    _enclosing(mod, node, parents),
                    f"jnp.{name}(...) with a bare float literal and no "
                    f"dtype=: the literal's implicit dtype follows the "
                    f"x64 config — pass dtype= (or derive it from an "
                    f"input's .dtype)")


_NUMPY_FLOAT_DEFAULT = {"zeros", "ones", "empty", "full", "linspace",
                        "eye", "identity"}


def _check_numpy_promotion(project: Project, mod: ModuleModel
                           ) -> Iterator[RawFinding]:
    if not _dtype_scoped(mod):
        return
    parents = _parent_map(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = mod.resolve(node.func)
        if canon is None:
            continue
        tail = canonical_tail(canon)
        if not tail.startswith("numpy."):
            continue
        name = tail.split(".")[-1]
        if name == "float64":
            yield RawFinding(
                "STS004", node.lineno, node.col_offset,
                _enclosing(mod, node, parents),
                "np.float64(...) in device code: a strongly-typed f64 "
                "scalar silently promotes every jnp operand under x64 — "
                "use a Python float (weak) or an explicit f32")
        elif name in _NUMPY_FLOAT_DEFAULT and not _has_dtype(mod, node):
            yield RawFinding(
                "STS004", node.lineno, node.col_offset,
                _enclosing(mod, node, parents),
                f"np.{name}(...) without dtype= in device code: numpy "
                f"defaults to float64, which promotes the jnp side "
                f"under x64 — pass dtype= explicitly")


# ---------------------------------------------------------------------------
# STS005 — Python-level branching on tracer values
# ---------------------------------------------------------------------------

def _check_tracer_branch(project: Project, mod: ModuleModel
                         ) -> Iterator[RawFinding]:
    taints = project.param_taint()
    for fi in mod.functions:
        if not fi.traced:
            continue
        seed = taints.get(fi, set())
        if not seed:
            continue
        tainted = local_tainted_names(fi, seed)
        for node in iter_scope(fi.node):
            test = None
            kind = None
            if isinstance(node, ast.If):
                test, kind = node.test, "if"
            elif isinstance(node, ast.While):
                test, kind = node.test, "while"
            elif isinstance(node, ast.Assert):
                test, kind = node.test, "assert"
            elif isinstance(node, ast.IfExp):
                test, kind = node.test, "conditional expression"
            if test is None or not taint_expr(test, tainted):
                continue
            yield RawFinding(
                "STS005", node.lineno, node.col_offset, fi.qualname,
                f"Python {kind} on a tracer-typed value inside traced "
                f"code ({fi.traced_via}): trace-time branch freezes one "
                f"side into the program (ConcretizationError under jit) "
                f"— use jnp.where / lax.cond, or mark the argument "
                f"static")


# ---------------------------------------------------------------------------
# STS006 — recompile hazards: fresh jit wrappers around closures
# ---------------------------------------------------------------------------

_CACHE_DECORATORS = {"functools.lru_cache", "functools.cache", "lru_cache",
                     "cache"}


def _has_cache_decorator(fi: FuncInfo) -> bool:
    for f in fi.scope_chain():
        for dec in f.decorators:
            target = dec.func if isinstance(dec, ast.Call) else dec
            canon = f.module.resolve(target)
            if canon and canonical_tail(canon) in _CACHE_DECORATORS:
                return True
    return False


def _check_recompile_hazard(project: Project, mod: ModuleModel
                            ) -> Iterator[RawFinding]:
    for fi in mod.functions:
        # jit calls at module scope run once per process — fine.  Only
        # jit calls inside function bodies can churn the cache.
        for node in iter_scope(fi.node):
            if not isinstance(node, ast.Call):
                continue
            canon = mod.resolve(node.func)
            if not canon or canonical_tail(canon) != "jax.jit" \
                    or not node.args:
                continue
            target = node.args[0]
            fresh: Optional[str] = None
            if isinstance(target, ast.Lambda):
                fresh = "a lambda"
            elif isinstance(target, ast.Name):
                resolved = fi.resolve_local(target.id)
                if resolved is not None and resolved.parent is not None:
                    fresh = f"nested function {target.id!r}"
            if fresh is None:
                continue
            if _has_cache_decorator(fi):
                continue
            yield RawFinding(
                "STS006", node.lineno, node.col_offset, fi.qualname,
                f"jax.jit({fresh}) inside a function body: a fresh "
                f"function object per call defeats jit's global cache — "
                f"every call recompiles.  Hoist the jitted callee to "
                f"module scope (closure state becomes arguments / "
                f"static args) or cache the wrapper (functools.lru_cache)")


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

RULES: Dict[str, Rule] = {r.code: r for r in [
    Rule("STS001", "host-sync-in-trace",
         "Host-sync / impure calls (float/int/.item/np.asarray/time/"
         "print) reachable from traced code", _check_host_sync),
    Rule("STS002", "metrics-in-trace",
         "Metrics / span / registry calls inside traced code "
         "(tracer-safe observability)", _check_metrics_in_trace),
    Rule("STS003", "implicit-float-dtype",
         "Array creation in ops/ and models/ without an explicit dtype",
         _check_dtype_discipline),
    Rule("STS004", "numpy-promotion",
         "numpy float64 creation in device code paths (silent promotion "
         "under x64)", _check_numpy_promotion),
    Rule("STS005", "tracer-branch",
         "Python-level branching on tracer-typed values",
         _check_tracer_branch),
    Rule("STS006", "recompile-hazard",
         "jax.jit of a per-call closure (defeats the jit cache)",
         _check_recompile_hazard),
]}

TRACER_SAFETY_RULES = ("STS001", "STS002", "STS005", "STS006")
DTYPE_RULES = ("STS003", "STS004")
