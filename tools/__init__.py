# tools/ is a package so `python -m tools.sts_lint` works from the repo
# root (bench_gate stays runnable as a plain script).
