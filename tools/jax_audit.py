"""Static inventory of version-sensitive JAX API touchpoints.

ROADMAP item 2 (the multi-device tier) is gated on a JAX upgrade, and
its first instruction is *audit the version-sensitive touchpoints
first*: the APIs this tree leans on that have moved, been renamed, or
changed shape across recent JAX releases.  This tool is that audit,
automated — a pure-AST scan (no JAX import required to run it) over the
package that emits a machine-readable report of every site touching:

====================  =====================================================
category              what is matched
====================  =====================================================
``monitoring``        ``jax.monitoring.*`` (the PR 1 recompile/compile-
                      seconds hooks — ``register_event_listener`` et al.
                      have moved between ``jax.monitoring`` and internal
                      modules across versions)
``profiler``          ``jax.profiler.*`` incl. ``TraceAnnotation`` (the
                      span forwarding in ``utils.metrics``)
``compilation_cache`` ``jax_compilation_cache_dir`` config updates and
                      ``jax.experimental.compilation_cache`` imports (the
                      engine's persistent executable cache)
``shard_map``         ``jax.shard_map`` / ``jax.experimental.shard_map``
                      (dead on 0.4.37 pristine HEAD — the upgrade target)
``pallas``            ``jax.experimental.pallas`` imports/uses
                      (``ops/pallas_arma.py``)
``experimental``      any other ``jax.experimental.*`` reference — the
                      namespace with no stability promise at all
``metrics_bridge``    call sites of the ``utils.metrics`` APIs that
                      forward to ``jax.profiler``/``jax.monitoring``
                      (``span`` → ``TraceAnnotation``;
                      ``install_jax_hooks``/``jax_stats`` → the event
                      listeners).  PRs 15–18 (fleet runtime, lineage,
                      attribution plane) lean on these everywhere, so
                      the upgrade blast radius is the *bridge callers*,
                      not just the two files importing jax directly
====================  =====================================================

Usage: ``python -m tools.jax_audit`` (or ``make jax-audit``); ``--json
PATH`` writes the report (``-`` = stdout).  Exit code 0 always — this
is an inventory, not a gate; the upgrade PR consumes it.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

from .sts_lint.analysis import ModuleModel, canonical_tail
from .sts_lint.engine import _iter_py_files

CATEGORIES = ("monitoring", "profiler", "compilation_cache", "shard_map",
              "pallas", "experimental", "metrics_bridge")

# utils.metrics symbols that forward into jax.profiler / jax.monitoring;
# a caller of one of these breaks (or goes dark) when those APIs move.
# trace_instant rides along: its markers share the TraceBuffer clock
# with the profiler-annotated spans, so the runtime/lineage plane's
# timeline goes incoherent if the span side moves without it.
_BRIDGE_SYMBOLS = frozenset({"span", "install_jax_hooks", "jax_stats",
                             "trace_instant"})


def _category(tail: str) -> Optional[str]:
    if tail.startswith("jax.monitoring"):
        return "monitoring"
    if tail.startswith("jax.profiler"):
        return "profiler"
    if "compilation_cache" in tail:
        return "compilation_cache"
    if tail.startswith(("jax.shard_map", "jax.experimental.shard_map")):
        return "shard_map"
    if tail.startswith("jax.experimental.pallas"):
        return "pallas"
    if tail.startswith("jax.experimental."):
        return "experimental"
    if ("utils.metrics." in tail or tail.startswith("metrics.")) \
            and tail.rsplit(".", 1)[-1] in _BRIDGE_SYMBOLS:
        return "metrics_bridge"
    return None


def _enclosing_symbol(mod: ModuleModel, node: ast.AST) -> str:
    best = ""
    for fi in mod.functions:
        n = fi.node
        if hasattr(n, "lineno") and n.lineno <= node.lineno and (
                getattr(n, "end_lineno", None) is None
                or node.lineno <= n.end_lineno):
            best = fi.qualname
    return best


def audit_module(mod: ModuleModel) -> List[Dict[str, Any]]:
    """Touchpoint records for one module: canonical-name references
    (through the import table), import statements, and config-string
    constants (``jax.config.update("jax_compilation_cache_dir", ...)``)."""
    hits: List[Dict[str, Any]] = []
    seen = set()

    def add(node: ast.AST, category: str, detail: str) -> None:
        # one record per (line, category): ast.walk visits the outer
        # (most specific) attribute chain before its bases, so the
        # first hit is the fullest dotted path
        key = (node.lineno, category)
        if key in seen:
            return
        seen.add(key)
        hits.append({
            "category": category,
            "path": mod.relpath,
            "line": node.lineno,
            "symbol": _enclosing_symbol(mod, node),
            "detail": detail,
        })

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom):
            base = ("." * node.level) + (node.module or "")
            for a in node.names:
                canon = canonical_tail(f"{base}.{a.name}"
                                       if base else a.name)
                cat = _category(canon)
                if cat:
                    add(node, cat, f"from {base or '.'} import {a.name}")
        elif isinstance(node, ast.Import):
            for a in node.names:
                cat = _category(a.name)
                if cat:
                    add(node, cat, f"import {a.name}")
        elif isinstance(node, ast.Attribute):
            # bare Names (an aliased `pl`) are just uses of an import
            # already recorded at its import site — only dotted chains
            # carry API-shape information
            canon = mod.resolve(node)
            if canon is None:
                continue
            cat = _category(canonical_tail(canon))
            if cat:
                add(node, cat, canonical_tail(canon))
        elif isinstance(node, ast.Constant) \
                and isinstance(node.value, str) \
                and node.value.startswith("jax_") \
                and "cache" in node.value:
            add(node, "compilation_cache", f"config key {node.value!r}")
    return hits


def audit_paths(paths: Sequence[str],
                root: Optional[str] = None) -> Dict[str, Any]:
    root = os.path.abspath(root or os.getcwd())
    touchpoints: List[Dict[str, Any]] = []
    parse_errors: List[str] = []
    files = _iter_py_files(paths)
    for path in files:
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, root).replace(os.sep, "/")
        try:
            source = open(ap, encoding="utf-8").read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            parse_errors.append(f"{rel}: {e}")
            continue
        touchpoints.extend(audit_module(ModuleModel(ap, rel, source,
                                                    tree)))
    touchpoints.sort(key=lambda t: (t["path"], t["line"], t["category"]))
    counts = {c: 0 for c in CATEGORIES}
    for t in touchpoints:
        counts[t["category"]] += 1
    jax_version = None
    try:                         # report-only; never initializes jax
        from importlib import metadata
        jax_version = metadata.version("jax")
    except Exception:  # noqa: BLE001 — version is informational
        pass
    return {
        "version": 1,
        "tool": "jax-audit",
        "jax_version": jax_version,
        "files_scanned": len(files),
        "counts": counts,
        "touchpoints": touchpoints,
        "parse_errors": parse_errors,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.jax_audit",
        description="Inventory version-sensitive JAX API touchpoints "
                    "(monitoring, profiler, compilation cache, "
                    "shard_map, pallas) ahead of a JAX upgrade.")
    ap.add_argument("paths", nargs="*", default=["spark_timeseries_tpu"],
                    help="files or directories to audit "
                         "(default: spark_timeseries_tpu)")
    ap.add_argument("--root", default=None,
                    help="path touchpoints are reported relative to "
                         "(default: cwd)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the JSON report here ('-' = stdout)")
    args = ap.parse_args(argv)

    report = audit_paths(args.paths, root=args.root)
    human_out = sys.stderr if args.json_out == "-" else sys.stdout
    for t in report["touchpoints"]:
        where = f" [in {t['symbol']}]" if t["symbol"] else ""
        print(f"{t['path']}:{t['line']}: {t['category']:<18s} "
              f"{t['detail']}{where}", file=human_out)
    for e in report["parse_errors"]:
        print(f"PARSE ERROR: {e}", file=sys.stderr)
    counts = ", ".join(f"{c}={n}" for c, n in report["counts"].items()
                       if n)
    print(f"jax-audit: {report['files_scanned']} files, "
          f"{len(report['touchpoints'])} touchpoint(s) "
          f"({counts or 'none'}); jax=={report['jax_version']}",
          file=human_out)
    if args.json_out:
        payload = json.dumps(report, indent=1)
        if args.json_out == "-":
            print(payload)
        else:
            with open(args.json_out, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
