#!/bin/bash
# Probe the tunneled TPU in a disposable subprocess; the moment it is healthy,
# run the full capture runbook (CAPTURE.md) streaming into $OUT so a mid-run
# wedge cannot void lines already taken. Exits 0 after one full capture.
#
# Usage: benchmarks/watch_capture.sh [outdir]
OUT=${1:-/tmp/r04}
mkdir -p "$OUT" || exit 1
OUT=$(cd "$OUT" && pwd) || exit 1    # absolute, survives the cd below
cd "$(dirname "$0")/.." || exit 1
while true; do
  if timeout 90 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
assert d.platform != 'cpu', d
x = (jnp.ones((256, 256)) @ jnp.ones((256, 256))).sum()
assert float(x) == 256.0 * 256 * 256
" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) probe OK - capturing" >> "$OUT/log"
    python -u bench.py                  > "$OUT/bench_tpu.jsonl"    2> "$OUT/bench_tpu.err"
    rc=$?
    echo "$(date -u +%FT%TZ) bench.py done rc=$rc" >> "$OUT/log"
    python -u benchmarks/bench_suite.py > "$OUT/suite_tpu.jsonl"    2> "$OUT/suite_tpu.err"
    rc=$?
    echo "$(date -u +%FT%TZ) bench_suite.py done rc=$rc" >> "$OUT/log"
    python -u benchmarks/roofline.py    > "$OUT/roofline_tpu.jsonl" 2> "$OUT/roofline_tpu.err"
    rc=$?
    echo "$(date -u +%FT%TZ) roofline.py done rc=$rc" >> "$OUT/log"
    python -u benchmarks/pallas_ab.py   > "$OUT/pallas_ab_tpu.jsonl" 2> "$OUT/pallas_ab_tpu.err"
    rc=$?
    echo "$(date -u +%FT%TZ) pallas_ab.py done rc=$rc - capture complete" >> "$OUT/log"
    exit 0
  fi
  echo "$(date -u +%FT%TZ) probe failed; retry in 240s" >> "$OUT/log"
  sleep 240
done
