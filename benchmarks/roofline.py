"""Roofline breakdown for the headline ARIMA CSS-LM fit (verdict r2 #10).

Answers, with measurements rather than guesswork: at the measured headline
rate, is the fused LM pass scan-latency-bound or MXU/throughput-bound, and
what is the next lever?

Decomposition measured on one chunk (default 131072 x 128, the bench.py
chunk shape):

- ``residual_pass``   — one primal one-step-error scan over the chunk
- ``normal_eqs_pass`` — primal + 5 tangent scans + JJT/Jr contractions
  (one full LM iteration's recurrence work; ratio to residual_pass shows
  the tangent-pass share)
- ``lm_iteration``    — marginal wall time per LM iteration, from fits at
  max_iter=2 vs max_iter=12 (includes the solve + bookkeeping)
- ``obs_scaling``     — normal_eqs time at n_obs 64/128/256: linear growth
  = throughput-bound in the scan body; flat = per-step latency dominates
- ``batch_scaling``   — normal_eqs time at 16k/64k/131k series: flat time
  = latency-bound (vector units idle); proportional = saturated

Prints one JSON line per measurement.  Run on the TPU chip; CPU runs are
for smoke only.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _timed(fn, *args, reps=5):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)
    np.asarray(jax.tree_util.tree_leaves(out)[0])       # tunnel sync
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
        jax.block_until_ready(out)
        np.asarray(jax.tree_util.tree_leaves(out)[0])
    return (time.perf_counter() - t0) / reps


def main():
    # probe before touching the backend in-process — a wedged tunnel hangs
    # backend init (shared contract, bench._resolve_platform)
    from bench import _resolve_platform
    platform, degraded = _resolve_platform()

    import jax

    if platform == "cpu":
        os.environ.setdefault("ROOF_N_SERIES", "16384")

    import jax.numpy as jnp

    from bench import _synthetic_arima_panel
    from spark_timeseries_tpu.models import arima
    from spark_timeseries_tpu.models.arima import _one_step_errors

    n = int(os.environ.get("ROOF_N_SERIES", "131072"))
    n_obs = int(os.environ.get("ROOF_N_OBS", "128"))
    dtype = jnp.float32
    panel = _synthetic_arima_panel(n, n_obs)

    def emit(metric, seconds, **kw):
        line = {"metric": metric, "value": round(seconds * 1e3, 2),
                "unit": "ms", "platform": platform}
        if degraded:
            from bench import DEGRADED_NOTE
            line["degraded"] = DEGRADED_NOTE
        line.update(kw)
        print(json.dumps(line), flush=True)

    p = q = 2
    k = 1 + p + q
    x0 = jnp.tile(jnp.asarray([0.1, 0.2, 0.2, 0.1, 0.1], dtype), (n, 1))

    def residual(prm, y):
        return _one_step_errors(prm, y, p, q, 1)[1]

    # every pass reduces its outputs to one scalar ON DEVICE: the tunneled
    # D2H link moves ~10 MB/s, so returning the raw (S, n) residuals or the
    # (S, k, k) grams would time the transfer, not the compute (the first
    # TPU capture showed the strictly-smaller normal-equations pass
    # "faster" than the residual pass for exactly this reason)
    def residual_pass(prm, y):
        return jnp.sum(jax.vmap(residual)(prm, y) ** 2)

    def normal_eqs_pass(prm, y):
        eye = jnp.eye(k, dtype=dtype)

        def one(prm_i, y_i):
            r, fwd = jax.linearize(lambda x: residual(x, y_i), prm_i)
            Jr = jax.vmap(fwd)(eye)
            return Jr @ Jr.T, Jr @ r, jnp.sum(r * r)
        JJt, Jr_, sse = jax.vmap(one)(prm, y)
        return jnp.sum(JJt) + jnp.sum(Jr_) + jnp.sum(sse)

    diffed = jnp.asarray(np.diff(panel, axis=1), dtype)
    rp = jax.jit(residual_pass)
    ne = jax.jit(normal_eqs_pass)

    t_resid = _timed(rp, x0, diffed)
    emit(f"residual primal pass ({n}x{n_obs})", t_resid)
    t_ne = _timed(ne, x0, diffed)
    emit(f"normal-equations pass: primal + {k} tangents ({n}x{n_obs})",
         t_ne, tangent_share=round(1 - t_resid / t_ne, 3))

    # the production pass: hand-fused carry accumulation (design.md §9)
    from spark_timeseries_tpu.models.arima import _arma_normal_eqs
    @jax.jit
    def fused_scalar(prm, y):
        jtj, jtr, sse = jax.vmap(
            lambda prm_i, y_i: _arma_normal_eqs(prm_i, y_i, p, q, 1))(
                prm, y)
        return jnp.sum(jtj) + jnp.sum(jtr) + jnp.sum(sse)

    t_fused = _timed(fused_scalar, x0, diffed)
    emit(f"fused-carry normal-equations pass ({n}x{n_obs})", t_fused,
         vs_linearize=round(t_ne / t_fused, 2))

    # marginal LM iteration cost from two fixed-budget fits
    vals = jnp.asarray(panel, dtype)
    f2 = jax.jit(lambda v: jnp.sum(arima.fit(2, 1, 2, v, warn=False,
                                             max_iter=2).coefficients))
    f12 = jax.jit(lambda v: jnp.sum(arima.fit(2, 1, 2, v, warn=False,
                                              max_iter=12).coefficients))
    t2 = _timed(f2, vals, reps=3)
    t12 = _timed(f12, vals, reps=3)
    emit(f"marginal LM iteration ({n}x{n_obs})", (t12 - t2) / 10.0,
         fit_2iter_ms=round(t2 * 1e3, 2), fit_12iter_ms=round(t12 * 1e3, 2))

    # n_obs scaling of the normal-equations pass
    for m in (64, 128, 256):
        pm = _synthetic_arima_panel(n, m, seed=1)
        dm = jnp.asarray(np.diff(pm, axis=1), dtype)
        t = _timed(ne, x0, dm, reps=3)       # same jit object: one compile
        emit(f"normal-equations pass, n_obs={m} ({n} series)", t)

    # batch scaling of the normal-equations pass
    for b in dict.fromkeys(min(b, n) for b in (16384, 65536, n)):
        t = _timed(ne, x0[:b], diffed[:b], reps=3)
        emit(f"normal-equations pass, batch={b} (n_obs={n_obs})", t,
             series_per_sec=round(b / t, 1))


if __name__ == "__main__":
    main()
