"""Roofline breakdown for the headline ARIMA CSS-LM fit (verdict r2 #10).

Answers, with measurements rather than guesswork: at the measured headline
rate, is the fused LM pass scan-latency-bound or MXU/throughput-bound, and
what is the next lever?

Decomposition measured on one chunk (default 131072 x 128, the bench.py
chunk shape):

- ``residual_pass``   — one primal one-step-error scan over the chunk
- ``normal_eqs_pass`` — primal + 5 tangent scans + JJT/Jr contractions
  (one full LM iteration's recurrence work; ratio to residual_pass shows
  the tangent-pass share)
- ``lm_iteration``    — marginal wall time per LM iteration, from fits at
  max_iter=2 vs max_iter=52 (includes the solve + bookkeeping; the wide
  span keeps the delta far above the tunnel's RTT jitter)
- ``obs_scaling``     — normal_eqs time at n_obs 64/128/256: linear growth
  = throughput-bound in the scan body; flat = per-step latency dominates
- ``batch_scaling``   — normal_eqs time at 16k/64k/131k series: flat time
  = latency-bound (vector units idle); proportional = saturated

Prints one JSON line per measurement.  Run on the TPU chip; CPU runs are
for smoke only.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from bench import timed_min as _timed  # noqa: E402 — needs the sys.path line


def main():
    # probe before touching the backend in-process — a wedged tunnel hangs
    # backend init (shared contract, bench._resolve_platform)
    from bench import _resolve_platform
    platform, degraded = _resolve_platform()

    import jax

    if platform == "cpu":
        os.environ.setdefault("ROOF_N_SERIES", "16384")

    import jax.numpy as jnp

    from bench import _synthetic_arima_panel
    from spark_timeseries_tpu.models import arima
    from spark_timeseries_tpu.models.arima import _one_step_errors

    n = int(os.environ.get("ROOF_N_SERIES", "131072"))
    n_obs = int(os.environ.get("ROOF_N_OBS", "128"))
    dtype = jnp.float32
    panel = _synthetic_arima_panel(n, n_obs)

    def emit(metric, seconds, **kw):
        line = {"metric": metric, "value": round(seconds * 1e3, 2),
                "unit": "ms", "platform": platform}
        if degraded:
            from bench import DEGRADED_NOTE
            line["degraded"] = DEGRADED_NOTE
        line.update(kw)
        print(json.dumps(line), flush=True)

    p = q = 2
    k = 1 + p + q
    x0 = jnp.tile(jnp.asarray([0.1, 0.2, 0.2, 0.1, 0.1], dtype), (n, 1))

    def residual(prm, y):
        return _one_step_errors(prm, y, p, q, 1)[1]

    # every pass reduces its outputs to one scalar ON DEVICE: the tunneled
    # D2H link moves ~10 MB/s, so returning the raw (S, n) residuals or the
    # (S, k, k) grams would time the transfer, not the compute (the first
    # TPU capture showed the strictly-smaller normal-equations pass
    # "faster" than the residual pass for exactly this reason)
    def residual_pass(prm, y):
        return jnp.sum(jax.vmap(residual)(prm, y) ** 2)

    def normal_eqs_pass(prm, y):
        eye = jnp.eye(k, dtype=dtype)

        def one(prm_i, y_i):
            r, fwd = jax.linearize(lambda x: residual(x, y_i), prm_i)
            Jr = jax.vmap(fwd)(eye)
            return Jr @ Jr.T, Jr @ r, jnp.sum(r * r)
        JJt, Jr_, sse = jax.vmap(one)(prm, y)
        return jnp.sum(JJt) + jnp.sum(Jr_) + jnp.sum(sse)

    diffed = jnp.asarray(np.diff(panel, axis=1), dtype)

    # standalone pass timings CHAIN R passes inside one jit with a data
    # dependence (the r04 capture's single-call numbers were ~140 ms of
    # pure tunnel RTT floor — batch=16384 vs 131072 differed by 6 ms):
    # the feedback term stops CSE, the scalar output keeps D2H at one
    # float, and the fixed round trip amortizes 1/R
    R = int(os.environ.get("ROOF_CHAIN", "8"))
    from bench import chained

    rp = chained(residual_pass, R)
    ne = chained(normal_eqs_pass, R)

    t_resid = _timed(rp, x0, diffed) / R
    emit(f"residual primal pass ({n}x{n_obs}, chained x{R})", t_resid)
    t_ne = _timed(ne, x0, diffed) / R
    emit(f"normal-equations pass: primal + {k} tangents ({n}x{n_obs}, "
         f"chained x{R})",
         t_ne, tangent_share=round(1 - t_resid / t_ne, 3))

    # the production pass: hand-fused carry accumulation (design.md §9)
    from spark_timeseries_tpu.models.arima import _arma_normal_eqs

    def fused_scalar(prm, y):
        jtj, jtr, sse = jax.vmap(
            lambda prm_i, y_i: _arma_normal_eqs(prm_i, y_i, p, q, 1))(
                prm, y)
        return jnp.sum(jtj) + jnp.sum(jtr) + jnp.sum(sse)

    fused = chained(fused_scalar, R)
    t_fused = _timed(fused, x0, diffed) / R
    emit(f"fused-carry normal-equations pass ({n}x{n_obs}, chained x{R})",
         t_fused, vs_linearize=round(t_ne / t_fused, 2))

    # marginal LM iteration cost from two fixed-budget fits — wide span
    # (2 vs 52) so the ~100-350 ms delta dwarfs the RTT jitter
    vals = jnp.asarray(panel, dtype)
    f2 = jax.jit(lambda v: jnp.sum(arima.fit(2, 1, 2, v, warn=False,
                                             max_iter=2).coefficients))
    f52 = jax.jit(lambda v: jnp.sum(arima.fit(2, 1, 2, v, warn=False,
                                              max_iter=52).coefficients))
    t2 = _timed(f2, vals, reps=3)
    t52 = _timed(f52, vals, reps=3)
    emit(f"marginal LM iteration ({n}x{n_obs})", (t52 - t2) / 50.0,
         fit_2iter_ms=round(t2 * 1e3, 2), fit_52iter_ms=round(t52 * 1e3, 2))

    # n_obs scaling of the normal-equations pass
    for m in (64, 128, 256):
        pm = _synthetic_arima_panel(n, m, seed=1)
        dm = jnp.asarray(np.diff(pm, axis=1), dtype)
        t = _timed(ne, x0, dm, reps=3) / R   # same jit object per shape
        emit(f"normal-equations pass, n_obs={m} ({n} series, "
             f"chained x{R})", t)

    # batch scaling of the normal-equations pass
    for b in dict.fromkeys(min(b, n) for b in (16384, 65536, n)):
        t = _timed(ne, x0[:b], diffed[:b], reps=3) / R
        emit(f"normal-equations pass, batch={b} (n_obs={n_obs}, "
             f"chained x{R})", t, series_per_sec=round(b / t, 1))


if __name__ == "__main__":
    main()
