"""Roofline breakdown for the headline ARIMA CSS-LM fit (verdict r2 #10;
pass-level floor analysis r4 verdict weak #3).

Answers, with measurements rather than guesswork: at the measured headline
rate, is the fused LM pass scan-latency-bound or MXU/throughput-bound, and
what is the next lever?

Decomposition measured on one chunk (default 131072 x 128, the bench.py
chunk shape):

- ``residual_pass``   — one primal one-step-error scan over the chunk
- ``normal_eqs_pass`` — primal + 5 tangent scans + JJT/Jr contractions
  (one full LM iteration's recurrence work; ratio to residual_pass shows
  the tangent-pass share)
- ``lm_iteration``    — marginal wall time per LM iteration for BOTH
  css-lm paths (XLA fused-carry and the Pallas kernel driver), from fits
  at max_iter=2 vs max_iter=52 (includes the solve + bookkeeping; the
  wide span keeps the delta far above the tunnel's RTT jitter).  This is
  the number that bounds fit throughput — NOT the standalone chained
  pass lines below, whose r4 readings were inflated ~8x by a per-rep
  panel re-blocking the real LM loop hoists (it blocks the panel ONCE,
  then iterates)
- ``kernel-only pass`` — the Pallas NE kernel chained on PRE-blocked
  inputs (the layout the LM loop actually feeds it), plus the batched
  ``spd_solve`` alone: decomposes the marginal iteration
- ``floor analysis`` — analytic FLOP and HBM-byte counts for one NE pass
  against stated peaks (``ROOF_VPU_GFLOPS``, default 3900 — the v5e
  VPU's f32 order of magnitude; ``ROOF_HBM_GBPS``, default 819 — v5e
  HBM), with achieved GFLOP/s, GB/s, and the ratio of measured in-loop
  pass time to the larger floor
- ``obs_scaling``     — normal_eqs time at n_obs 64/128/256: linear growth
  = throughput-bound in the scan body; flat = per-step latency dominates
- ``batch_scaling``   — normal_eqs time at 16k/64k/131k series: flat time
  = latency-bound (vector units idle); proportional = saturated

Prints one JSON line per measurement.  Run on the TPU chip; CPU runs are
for smoke only.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from bench import timed_min as _timed  # noqa: E402 — needs the sys.path line


def main():
    # probe before touching the backend in-process — a wedged tunnel hangs
    # backend init (shared contract, bench._resolve_platform)
    from bench import _resolve_platform
    platform, degraded = _resolve_platform()

    import jax

    if platform == "cpu":
        os.environ.setdefault("ROOF_N_SERIES", "16384")

    import jax.numpy as jnp

    from bench import _synthetic_arima_panel
    from spark_timeseries_tpu.models import arima
    from spark_timeseries_tpu.models.arima import _one_step_errors

    n = int(os.environ.get("ROOF_N_SERIES", "131072"))
    n_obs = int(os.environ.get("ROOF_N_OBS", "128"))
    dtype = jnp.float32
    panel = _synthetic_arima_panel(n, n_obs)

    def emit(metric, seconds, **kw):
        line = {"metric": metric, "value": round(seconds * 1e3, 2),
                "unit": "ms", "platform": platform}
        if degraded:
            from bench import DEGRADED_NOTE
            line["degraded"] = DEGRADED_NOTE
        line.update(kw)
        print(json.dumps(line), flush=True)

    p = q = 2
    k = 1 + p + q
    x0 = jnp.tile(jnp.asarray([0.1, 0.2, 0.2, 0.1, 0.1], dtype), (n, 1))

    def residual(prm, y):
        return _one_step_errors(prm, y, p, q, 1)[1]

    # every pass reduces its outputs to one scalar ON DEVICE: the tunneled
    # D2H link moves ~10 MB/s, so returning the raw (S, n) residuals or the
    # (S, k, k) grams would time the transfer, not the compute (the first
    # TPU capture showed the strictly-smaller normal-equations pass
    # "faster" than the residual pass for exactly this reason)
    def residual_pass(prm, y):
        return jnp.sum(jax.vmap(residual)(prm, y) ** 2)

    def normal_eqs_pass(prm, y):
        eye = jnp.eye(k, dtype=dtype)

        def one(prm_i, y_i):
            r, fwd = jax.linearize(lambda x: residual(x, y_i), prm_i)
            Jr = jax.vmap(fwd)(eye)
            return Jr @ Jr.T, Jr @ r, jnp.sum(r * r)
        JJt, Jr_, sse = jax.vmap(one)(prm, y)
        return jnp.sum(JJt) + jnp.sum(Jr_) + jnp.sum(sse)

    diffed = jnp.asarray(np.diff(panel, axis=1), dtype)

    # standalone pass timings CHAIN R passes inside one jit with a data
    # dependence (the r04 capture's single-call numbers were ~140 ms of
    # pure tunnel RTT floor — batch=16384 vs 131072 differed by 6 ms):
    # the feedback term stops CSE, the scalar output keeps D2H at one
    # float, and the fixed round trip amortizes 1/R
    R = int(os.environ.get("ROOF_CHAIN", "8"))
    from bench import chained

    rp = chained(residual_pass, R)
    ne = chained(normal_eqs_pass, R)

    t_resid = _timed(rp, x0, diffed) / R
    emit(f"residual primal pass ({n}x{n_obs}, chained x{R})", t_resid)
    t_ne = _timed(ne, x0, diffed) / R
    emit(f"normal-equations pass: primal + {k} tangents ({n}x{n_obs}, "
         f"chained x{R})",
         t_ne, tangent_share=round(1 - t_resid / t_ne, 3))

    # the production pass: hand-fused carry accumulation (design.md §9b)
    from spark_timeseries_tpu.models.arima import _arma_normal_eqs

    def fused_scalar(prm, y):
        jtj, jtr, sse = jax.vmap(
            lambda prm_i, y_i: _arma_normal_eqs(prm_i, y_i, p, q, 1))(
                prm, y)
        return jnp.sum(jtj) + jnp.sum(jtr) + jnp.sum(sse)

    fused = chained(fused_scalar, R)
    t_fused = _timed(fused, x0, diffed) / R
    emit(f"fused-carry normal-equations pass ({n}x{n_obs}, chained x{R})",
         t_fused, vs_linearize=round(t_ne / t_fused, 2))

    # marginal LM iteration cost from two fixed-budget fits — wide span
    # (2 vs 52) so the ~100-350 ms delta dwarfs the RTT jitter.  Forced
    # routing per path (fit decides at call time on the concrete env),
    # one jit per (path, budget) so nothing is baked across toggles
    vals = jnp.asarray(panel, dtype)

    def marginal(flag):
        prior = os.environ.get("STS_PALLAS")
        os.environ["STS_PALLAS"] = flag
        try:
            f2 = jax.jit(lambda v: jnp.sum(arima.fit(
                2, 1, 2, v, warn=False, max_iter=2).coefficients))
            f52 = jax.jit(lambda v: jnp.sum(arima.fit(
                2, 1, 2, v, warn=False, max_iter=52).coefficients))
            t2 = _timed(f2, vals, reps=3)
            t52 = _timed(f52, vals, reps=3)
        finally:
            if prior is None:
                os.environ.pop("STS_PALLAS", None)
            else:
                os.environ["STS_PALLAS"] = prior
        return t2, t52

    it_ms = {}
    for flag, name in (("0", "xla"), ("1", "pallas")):
        if name == "pallas" and platform == "cpu" \
                and os.environ.get("ROOF_CPU_PALLAS") != "1":
            continue            # interpreter-mode kernel: hours, not data
        t2, t52 = marginal(flag)
        it_ms[name] = (t52 - t2) / 50.0
        emit(f"marginal LM iteration, {name} path ({n}x{n_obs})",
             it_ms[name],
             fit_2iter_ms=round(t2 * 1e3, 2),
             fit_52iter_ms=round(t52 * 1e3, 2))

    # decompose the Pallas iteration: the NE kernel chained on
    # PRE-blocked inputs (exactly the LM loop's layout — blocking the
    # panel per call, as the r4 standalone lines did, costs a 64 MB
    # relayout per rep and was the bulk of their ~8-9 ms readings), and
    # the batched SPD solve alone
    from spark_timeseries_tpu.ops import pallas_arma
    from spark_timeseries_tpu.ops.linalg import spd_solve

    if platform != "cpu" or os.environ.get("ROOF_CPU_PALLAS") == "1":
        interpret = platform == "cpu"
        rows = pallas_arma._block_rows(n, n_obs - 1)
        y_b, n_blocks = pallas_arma._blocked(
            diffed.astype(jnp.float32), n, rows)

        def kernel_pass(prm, yb):
            jtj, jtr, sse = pallas_arma._ne_from_blocked(
                prm, yb, n, rows, n_blocks, p, q, 1, n_obs - 1, interpret)
            return jnp.sum(sse) + 1e-30 * (jnp.sum(jtj) + jnp.sum(jtr))

        t_kernel = _timed(chained(kernel_pass, R), x0, y_b) / R
        emit(f"Pallas NE kernel pass, pre-blocked ({n}x{n_obs}, "
             f"chained x{R})", t_kernel)

        jtj0, jtr0, _ = pallas_arma._ne_from_blocked(
            x0, y_b, n, rows, n_blocks, p, q, 1, n_obs - 1, interpret)
        damped = jtj0 + 1e-3 * jnp.eye(k, dtype=jnp.float32)

        def solve_pass(prm, jtj_, jtr_):
            return jnp.sum(spd_solve(jtj_, jtr_ + 1e-30 * jnp.sum(prm)))

        t_solve = _timed(chained(
            lambda prm, jtj_, jtr_: solve_pass(prm, jtj_, jtr_), R),
            x0, damped, jtr0) / R
        emit(f"batched spd_solve ({n}x{k}x{k}, chained x{R})", t_solve)

    # analytic floors for ONE fused NE pass at this shape, against stated
    # peaks.  FLOPs per lane-step (k = icpt+p+q, fused recurrence:
    # residual ~2(p+q)+2, tangent rows k(q+1) mul-adds ~2k(q+1), JtJ
    # upper triangle 2*T(k), Jtr 2k, sse 2):
    tri = k * (k + 1) // 2
    flops_step = (2 * (p + q) + 2) + 2 * k * (q + 1) + 2 * tri + 2 * k + 2
    steps = (n_obs - 1) - max(p, q)
    flops_pass = flops_step * steps * n
    bytes_pass = 4 * n * (n_obs - 1 + k + tri + k + 1)  # y + params + outs
    vpu = float(os.environ.get("ROOF_VPU_GFLOPS", "3900")) * 1e9
    hbm = float(os.environ.get("ROOF_HBM_GBPS", "819")) * 1e9
    floor_compute = flops_pass / vpu
    floor_memory = bytes_pass / hbm
    floor = max(floor_compute, floor_memory)
    measured = it_ms.get("pallas", it_ms.get("xla"))
    line = {"metric": f"NE pass floor analysis ({n}x{n_obs}, ARIMA(2,1,2))",
            "flops_per_pass": flops_pass,
            "hbm_bytes_per_pass": bytes_pass,
            "vpu_floor_ms": round(1e3 * floor_compute, 3),
            "hbm_floor_ms": round(1e3 * floor_memory, 3),
            "assumed_vpu_gflops": vpu / 1e9,
            "assumed_hbm_gbps": hbm / 1e9,
            "platform": platform}
    if measured is not None:
        line.update({
            "measured_inloop_iteration_ms": round(1e3 * measured, 3),
            "achieved_gflops": round(flops_pass / measured / 1e9, 1),
            "achieved_gbps": round(bytes_pass / measured / 1e9, 1),
            "ratio_to_floor": round(measured / floor, 2)})
    if degraded:
        from bench import DEGRADED_NOTE
        line["degraded"] = DEGRADED_NOTE
    print(json.dumps(line), flush=True)

    # n_obs scaling of the normal-equations pass
    for m in (64, 128, 256):
        pm = _synthetic_arima_panel(n, m, seed=1)
        dm = jnp.asarray(np.diff(pm, axis=1), dtype)
        t = _timed(ne, x0, dm, reps=3) / R   # same jit object per shape
        emit(f"normal-equations pass, n_obs={m} ({n} series, "
             f"chained x{R})", t)

    # batch scaling of the normal-equations pass
    for b in dict.fromkeys(min(b, n) for b in (16384, 65536, n)):
        t = _timed(ne, x0[:b], diffed[:b], reps=3) / R
        emit(f"normal-equations pass, batch={b} (n_obs={n_obs}, "
             f"chained x{R})", t, series_per_sec=round(b / t, 1))


if __name__ == "__main__":
    main()
