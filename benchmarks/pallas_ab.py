"""A/B: Pallas fused normal-equations kernel vs the XLA fused-carry path.

Round-4 verdict item 2: settle whether a Pallas kernel that keeps the LM
accumulators in VMEM for the whole time axis beats XLA's scan codegen at
the fused shape (the round-1 experiment predates the fused-carry kernel,
so its negative result no longer answers this).  Measures, at the bench
chunk (131072 x 128 f32, ARIMA(2,1,2), override via ``AB_N_SERIES`` /
``AB_N_OBS``):

- one fused NE pass, XLA vs Pallas (chained R times inside one jit with a
  tiny data dependence so iterations serialize; scalar-reduced outputs —
  the tunnel's ~150 ms RTT and slow D2H never touch the timing);
- one in-loop LM iteration, XLA vs Pallas (differenced fits:
  ``(fit(max_iter=52) - fit(max_iter=2)) / 50`` — fixed costs cancel,
  and the wide span keeps the delta far above the tunnel's RTT jitter);
- the full fit wall time, both paths (driver-level);
- the PUBLIC ``arima.fit`` end to end, ``STS_PALLAS=0`` vs forced
  (``AB_N_SERIES x AB_N_OBS``);
- ``auto_fit_panel``'s fused grid, XLA vs Pallas screen/refine
  (``AB_GRID_SERIES`` lanes, clamped to the panel).

(The Holt-Winters box-fit A/B lives with its archived driver in
``docs/experiments/hw_pallas.py``, runnable directly.)

Prints one JSON line per measurement; shares ``bench._resolve_platform``
(probe in subprocess, labeled degraded CPU fallback, rc 0 either way).
On CPU the Pallas kernel runs interpreted — orders of magnitude slow —
so CPU runs shrink the shape and the lines are marked
``"cpu_interpret": true`` (compile/behavior smoke, not a perf record).
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from bench import DEGRADED_NOTE, _resolve_platform, _synthetic_arima_panel
    platform, degraded = _resolve_platform()

    def emit(obj):
        if degraded:
            obj.setdefault("degraded", DEGRADED_NOTE)
        obj["platform"] = platform
        print(json.dumps(obj), flush=True)

    import jax
    import jax.numpy as jnp

    from spark_timeseries_tpu.models import arima
    from spark_timeseries_tpu.ops import pallas_arma
    from spark_timeseries_tpu.ops.optimize import minimize_least_squares
    from spark_timeseries_tpu.ops.univariate import differences_of_order_d

    on_tpu = platform != "cpu"
    S = int(os.environ.get("AB_N_SERIES", "131072" if on_tpu else "1024"))
    n_obs = int(os.environ.get("AB_N_OBS", "128"))
    p = q = 2
    icpt = 1
    interpret = not on_tpu

    panel = _synthetic_arima_panel(S, n_obs)
    diffed = np.asarray(
        differences_of_order_d(jnp.asarray(panel, jnp.float32), 1))[:, 1:]
    y = jnp.asarray(diffed, jnp.float32)
    init = arima.hannan_rissanen_init(p, q, y, True).astype(jnp.float32)
    init = jnp.where(jnp.isfinite(init), init, 0.0)

    from bench import timed_min as timed   # shared timing protocol

    from contextlib import contextmanager

    @contextmanager
    def pallas_flag(value):
        prior = os.environ.get("STS_PALLAS")
        os.environ["STS_PALLAS"] = value
        try:
            yield
        finally:
            if prior is None:
                os.environ.pop("STS_PALLAS", None)
            else:
                os.environ["STS_PALLAS"] = prior

    def emit_ab(metric, t_xla, t_pl, unit, n_items=None):
        line = {"metric": metric,
                "xla_s": round(t_xla, 3), "pallas_s": round(t_pl, 3),
                "speedup": round(t_xla / t_pl, 2), "unit": unit}
        if n_items is not None:
            line["xla_series_per_sec"] = round(n_items / t_xla, 1)
            line["pallas_series_per_sec"] = round(n_items / t_pl, 1)
        if interpret:
            line["cpu_interpret"] = True
        emit(line)

    # --- one fused NE pass, chained so fixed costs amortize -----------------
    # The Pallas side runs on PRE-blocked inputs — the layout its LM loop
    # actually feeds it.  The r4 artifact's 0.91x pass line called
    # normal_equations() per rep, which re-blocks (pads + transposes) the
    # 64 MB panel every rep; the production driver hoists that, so the
    # old line compared "XLA pass" against "Pallas pass + panel relayout"
    # (the r4-verdict ~10x floor puzzle traced to exactly this).
    R = 8
    from bench import chained

    # every output (jtj included) feeds the data dependence through the
    # chained scalar: XLA's DCE would otherwise strip the unused JtJ
    # accumulation from its side of the A/B while the Pallas kernel
    # always computes its fused output
    def ne_xla(x, yy):
        jtj, jtr, sse = jax.vmap(
            lambda pp, vv: arima._arma_normal_eqs(pp, vv, p, q, icpt)
        )(x, yy)
        return jnp.sum(sse) + 1e-30 * (jnp.sum(jtj) + jnp.sum(jtr))

    S_y, n_y = y.shape
    rows = pallas_arma._block_rows(S_y, n_y)
    y_blocked, n_blocks = pallas_arma._blocked(
        y.astype(jnp.float32), S_y, rows)

    def ne_pl(x, yb):
        jtj, jtr, sse = pallas_arma._ne_from_blocked(
            x, yb, S_y, rows, n_blocks, p, q, icpt, n_y, interpret)
        return jnp.sum(sse) + 1e-30 * (jnp.sum(jtj) + jnp.sum(jtr))

    t_xla = timed(chained(ne_xla, R), init, y) / R
    t_pl = timed(chained(ne_pl, R), init, y_blocked) / R
    emit({"metric": f"fused NE pass ({S}x{n_obs} f32, chained x{R}, "
                    f"pallas pre-blocked)",
          "xla_ms": round(1e3 * t_xla, 3), "pallas_ms": round(1e3 * t_pl, 3),
          "speedup": round(t_xla / t_pl, 2), "unit": "ms/pass",
          **({"cpu_interpret": True} if interpret else {})})

    # --- one in-loop LM iteration (differenced fits) ------------------------
    def lm_xla(iters):
        def run(x0):
            return minimize_least_squares(
                None, x0, y, max_iter=iters,
                normal_eqs_fn=lambda prm, yy: arima._arma_normal_eqs(
                    prm, yy, p, q, icpt)).x
        return timed(jax.jit(run), init)

    def lm_pl(iters):
        def run(x0):
            return pallas_arma.fit_css_lm(
                x0, y, p, q, icpt, max_iter=iters, interpret=interpret)[0]
        return timed(jax.jit(run), init)

    # differenced over a 50-iteration span so the delta (~100-350 ms)
    # dwarfs the tunnel's RTT jitter — the original 12-2 span differenced
    # two ~200 ms timings under ±10 ms jitter and could go negative
    it_xla = (lm_xla(52) - lm_xla(2)) / 50.0
    it_pl = (lm_pl(52) - lm_pl(2)) / 50.0
    emit({"metric": f"LM iteration ({S}x{n_obs} f32, differenced 52-2)",
          "xla_ms": round(1e3 * it_xla, 3), "pallas_ms": round(1e3 * it_pl, 3),
          "speedup": round(it_xla / it_pl, 2), "unit": "ms/iteration",
          **({"cpu_interpret": True} if interpret else {})})

    # --- full fit wall time -------------------------------------------------
    emit_ab(f"full css-lm fit ({S}x{n_obs} f32, max_iter=50)",
            lm_xla(50), lm_pl(50), "s/fit", n_items=S)

    # --- the PUBLIC fit, end to end: STS_PALLAS=0 vs =1 (forced) ------------
    # (the full arima.fit includes differencing + HR init + quarantine
    # around the solver, so its ratio can exceed the driver-level line
    # above.  Forced rather than default routing so the measurement is
    # the same on any host: under jit the default gate's tracer branch
    # falls back to a device-count proxy, which on a multi-device host
    # would silently measure XLA vs XLA)
    panel_j = jax.device_put(jnp.asarray(panel, jnp.float32))

    def fit_wall(flag):
        with pallas_flag(flag):
            f = jax.jit(lambda v: arima.fit(2, 1, 2, v, warn=False)
                        .coefficients)
            return timed(f, panel_j)

    emit_ab(f"public arima.fit(2,1,2) device-resident, forced routing "
            f"({S}x{n_obs} f32)",
            fit_wall("0"), fit_wall("1"), "s/fit", n_items=S)

    # --- auto_fit_panel's fused grid: XLA vs Pallas screen/refine -----------
    S_grid = min(int(os.environ.get("AB_GRID_SERIES",
                                    "16384" if on_tpu else "128")),
                 panel.shape[0])
    grid_y = jnp.asarray(panel[:S_grid], jnp.float32)

    def grid_wall(flag):
        with pallas_flag(flag):
            return timed(lambda v: arima.auto_fit_panel(
                v, max_p=2, max_d=2, max_q=2).orders, grid_y)

    emit_ab(f"auto_fit_panel grid (p,q<=2, d<=2) ({S_grid}x{n_obs} f32)",
            grid_wall("0"), grid_wall("1"), "s/search", n_items=S_grid)

    # (the Holt-Winters Pallas A/B moved with its archived driver to
    # docs/experiments/hw_pallas.py — run that file directly on a
    # healthy chip; the r4-r5 chips never admitted the measurement)


if __name__ == "__main__":
    main()
