"""Extended benchmark suite — the five BASELINE.md configs, each with a
measured CPU-baseline ratio.

``python benchmarks/bench_suite.py`` prints one JSON line per config:
EWMA, ARIMA (the headline, same as bench.py), Holt-Winters seasonal,
AR-GARCH volatility, and RegressionARIMA + stationarity tests — plus the
batched auto-ARIMA order search.  Synthetic panels stand in for the
M4/minute-bar datasets (zero-egress environment); shapes match their scale
profile.  All timings are to host materialization (the tunneled TPU platform
does not synchronize on block_until_ready alone).

BASELINE.md requires every config to "run on both the reference CPU path and
the new TPU path": the reference publishes no numbers and is a JVM library,
so its per-series scalar path (Commons-Math CGD/BOBYQA loops, numpy-scalar
recurrences) is emulated per model on a pinned subsample and extrapolated;
each output line carries ``vs_baseline`` and the emulation's sample size.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASELINE_SAMPLE = 6


def _timed(fn, *args, reps=3):
    """Shared protocol (bench.timed_min): min over reps — the tunnel's
    RTT jitter is additive, so the previous mean-of-reps biased the
    suite's records high relative to roofline/pallas_ab.  Returns
    ``(seconds, leaves)`` with the last run's materialized leaf list
    (this file's historical contract: callers index ``out[0]``)."""
    import jax

    from bench import timed_min
    dt, out = timed_min(fn, *args, reps=reps, want_out=True)
    return dt, [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(out)]


def _baseline(per_series_fn, panel: np.ndarray,
              sample: int = BASELINE_SAMPLE) -> tuple:
    """Time ``per_series_fn(row)`` over a pinned subsample; returns
    (series/sec, actual sample) for the emulated reference CPU path.
    The rate divides by the rows actually timed — a capped smoke panel
    may hold fewer rows than ``sample``."""
    sub = panel[:sample]
    t0 = time.perf_counter()
    for row in sub:
        per_series_fn(np.asarray(row, np.float64))
    return sub.shape[0] / (time.perf_counter() - t0), sub.shape[0]


# ---------------------------------------------------------------------------
# per-series reference-path emulations (scalar numpy + scipy optimizers,
# the Commons-Math cost shapes; no code shared with the JAX fits)
# ---------------------------------------------------------------------------

def _ewma_sse_scalar(a: float, x: np.ndarray) -> float:
    """ref EWMA.scala:81-96 — sequential smoothing recurrence."""
    s = x[0]
    sse = 0.0
    for t in range(1, x.shape[0]):
        sse += (x[t] - s) ** 2
        s = a * x[t] + (1.0 - a) * s
    return sse


def _ewma_baseline(row: np.ndarray) -> None:
    from scipy.optimize import minimize_scalar
    minimize_scalar(lambda a: _ewma_sse_scalar(a, row), bounds=(1e-4, 1.0),
                    method="bounded", options={"xatol": 1e-6})


def _hw_sse_scalar(params, x: np.ndarray, period: int) -> float:
    """ref HoltWinters.scala:106-121,180-226 — additive triple smoothing."""
    a, b, g = params
    if not (0 <= a <= 1 and 0 <= b <= 1 and 0 <= g <= 1):
        return np.inf
    # moving-average detrend init (Hyndman recipe, as the reference does)
    k = period
    trend0 = np.convolve(x[:2 * k], np.full(k, 1.0 / k), mode="valid") \
        if k % 2 else np.convolve(
            x[:2 * k],
            np.r_[0.5 / k, np.full(k - 1, 1.0 / k), 0.5 / k], mode="valid")
    idx = np.arange(1, trend0.shape[0] + 1)
    slope, intercept = np.polyfit(idx, trend0, 1)
    level = intercept
    trend = slope
    pad = (len(x[:2 * k]) - len(trend0)) // 2
    detrended = np.zeros(2 * k)
    detrended[pad:pad + len(trend0)] = x[pad:pad + len(trend0)] - trend0
    season = np.zeros(k)
    for i in range(k):
        season[i] = (detrended[i] + detrended[i + k]) / 2.0
    season -= season.mean()
    sse = 0.0
    seasons = list(season)
    for t in range(k, x.shape[0]):
        s_i = seasons[0]
        base = level + trend
        sse += (x[t] - (base + s_i)) ** 2
        new_level = a * (x[t] - s_i) + (1 - a) * base
        new_trend = b * (new_level - level) + (1 - b) * trend
        new_season = g * (x[t] - new_level) + (1 - g) * s_i
        level, trend = new_level, new_trend
        seasons = seasons[1:] + [new_season]
    return sse


def _hw_baseline_factory(period: int):
    from scipy.optimize import minimize as sp_minimize

    def run(row: np.ndarray) -> None:
        sp_minimize(_hw_sse_scalar, np.array([0.3, 0.1, 0.1]),
                    args=(row, period), method="Powell",
                    bounds=[(0, 1)] * 3, options={"maxiter": 500})
    return run


def _garch_neg_ll_scalar(params, x: np.ndarray) -> float:
    """ref GARCH.scala:82-129 — sequential variance recurrence."""
    omega, alpha, beta = params
    if omega <= 0 or alpha < 0 or beta < 0 or alpha + beta >= 1:
        return np.inf
    h = omega / (1.0 - alpha - beta)
    ll = 0.0
    for t in range(1, x.shape[0]):
        h = omega + alpha * x[t - 1] ** 2 + beta * h
        ll += -0.5 * np.log(h) - 0.5 * x[t] ** 2 / h
    return -ll


def _argarch_baseline(row: np.ndarray) -> None:
    from scipy.optimize import minimize as sp_minimize
    # stage 1: AR(1) OLS (ref GARCH.scala:63-69)
    yprev, ycur = row[:-1], row[1:]
    X = np.stack([np.ones_like(yprev), yprev], axis=1)
    coef, *_ = np.linalg.lstsq(X, ycur, rcond=None)
    resid = np.r_[row[0] - coef[0], ycur - X @ coef]
    # stage 2: GARCH(1,1) MLE, derivative-free
    sp_minimize(_garch_neg_ll_scalar, np.array([0.2, 0.2, 0.2]),
                args=(resid,), method="Nelder-Mead",
                options={"maxiter": 600})


def _regarima_baseline_factory(X: np.ndarray, max_iter: int = 10,
                               adf_lag: int = 4):
    def dw(e: np.ndarray) -> float:
        return np.sum(np.diff(e) ** 2) / np.sum(e ** 2)

    def run(row: np.ndarray) -> None:
        """ref RegressionARIMA.scala:83-160 per-series Cochrane-Orcutt, plus
        the per-series ADF/KPSS OLS work the TPU config also computes
        (ref TimeSeriesStatisticalTests.scala:209-242,369-394)."""
        A = np.column_stack([np.ones(X.shape[0]), X])
        beta, *_ = np.linalg.lstsq(A, row, rcond=None)
        resid = row - A @ beta
        if abs(dw(resid) - 2.0) >= 0.05:
            rho_prev = 0.0
            for it in range(max_iter):
                e_prev, e_cur = resid[:-1], resid[1:]
                rho = float(e_prev @ e_cur / (e_prev @ e_prev))
                y_d = row[1:] - rho * row[:-1]
                X_d = X[1:] - rho * X[:-1]
                A_d = np.column_stack([np.ones(X_d.shape[0]), X_d])
                b_d, *_ = np.linalg.lstsq(A_d, y_d, rcond=None)
                b_d[0] /= (1.0 - rho)
                resid = row - np.column_stack(
                    [np.ones(X.shape[0]), X]) @ b_d
                tres = y_d - A_d @ np.r_[b_d[0] * (1 - rho), b_d[1:]]
                if abs(dw(tres) - 2.0) < 0.05 or \
                        (it >= 1 and abs(rho - rho_prev) <= 0.001):
                    break
                rho_prev = rho

        # ADF: OLS t-stat of the lagged level in the Dickey-Fuller design
        dy = np.diff(row)
        lvl = row[adf_lag:-1]
        lags = np.column_stack([dy[adf_lag - k:len(dy) - k]
                                for k in range(1, adf_lag + 1)])
        D = np.column_stack([lvl, np.ones_like(lvl), lags])
        target = dy[adf_lag:]
        coef, *_ = np.linalg.lstsq(D, target, rcond=None)
        r = target - D @ coef
        s2 = (r @ r) / max(len(target) - D.shape[1], 1)
        cov = s2 * np.linalg.inv(D.T @ D)
        _ = coef[0] / np.sqrt(cov[0, 0])

        # KPSS: demeaned cumsum statistic with Newey-West variance
        _ = _kpss_stat_scalar(row)
    return run


def _kpss_stat_scalar(x: np.ndarray) -> float:
    """Scalar KPSS statistic (demeaned cumsum + Bartlett-weighted Newey-West
    variance) shared by the auto-ARIMA and RegressionARIMA baseline
    emulations (ref TimeSeriesStatisticalTests.scala:369-394 cost shape)."""
    e = x - x.mean()
    s = np.cumsum(e)
    n = len(x)
    lags = int(4 * (n / 100.0) ** 0.25)
    var = (e @ e) / n
    for k in range(1, lags + 1):
        var += 2.0 * (1.0 - k / (lags + 1.0)) * (e[k:] @ e[:-k]) / n
    return (s @ s) / (n * n * var)


def _auto_arima_baseline_factory(max_p: int = 2, max_d: int = 2,
                                 max_q: int = 2):
    """ref ARIMA.scala:280-375 per-series autoFit cost shape: KPSS-driven d
    selection, then a stepwise (p, q) neighborhood search where every
    candidate is a full scalar CSS fit compared on approximate AIC."""
    from bench import _css_neg_ll
    from scipy.optimize import minimize as sp_minimize

    kpss_stat = _kpss_stat_scalar

    def css_fit_aic(diffed: np.ndarray, p: int, q: int) -> float:
        x0 = np.concatenate([[np.mean(diffed)], np.full(p + q, 0.1)])
        res = sp_minimize(_css_neg_ll, x0, args=(diffed, p, q),
                          method="Powell", options={"maxiter": 1000})
        return 2.0 * res.fun + 2.0 * (p + q + 1)

    def run(row: np.ndarray) -> None:
        # d: first difference order whose KPSS statistic passes ~0.463
        diffed = row
        for d in range(max_d + 1):
            if kpss_stat(diffed) < 0.463 or d == max_d:
                break
            diffed = np.diff(diffed)
        # stepwise neighborhood walk from (1, 1), Hyndman-Khandakar style
        best = (1, 1)
        best_aic = css_fit_aic(diffed, *best)
        tried = {best}
        improved = True
        while improved:
            improved = False
            p0, q0 = best
            for p, q in ((p0 + 1, q0), (p0 - 1, q0), (p0, q0 + 1),
                         (p0, q0 - 1)):
                if not (0 <= p <= max_p and 0 <= q <= max_q) \
                        or (p, q) in tried or p + q == 0:
                    continue
                tried.add((p, q))
                aic = css_fit_aic(diffed, p, q)
                if aic < best_aic:
                    best, best_aic, improved = (p, q), aic, True
    return run


def _arima_baseline(row: np.ndarray) -> None:
    # shares bench.py's scalar CSS objective so the headline vs_baseline and
    # this config's ratio can never drift apart
    from bench import _css_neg_ll
    from scipy.optimize import minimize as sp_minimize
    diffed = np.diff(row)
    x0 = np.array([np.mean(diffed), 0.1, 0.1, 0.1, 0.1])
    sp_minimize(_css_neg_ll, x0, args=(diffed,), method="Powell",
                options={"maxiter": 2000})


def main():
    # probe the accelerator in a disposable subprocess BEFORE touching the
    # backend in-process (shared contract, bench._resolve_platform: a
    # wedged TPU tunnel hangs backend init indefinitely — round 2's record
    # was voided that way, and the first round-3 CPU smoke of this suite
    # died the same death because the axon sitecustomize overrides
    # JAX_PLATFORMS=cpu).  On CPU the long-series knobs shrink to feasible
    # defaults unless explicitly set; a probe-failure fallback is stamped
    # "degraded" on every line so it can never read as a deliberate CPU
    # capture.
    from bench import _resolve_platform
    platform, degraded = _resolve_platform()

    import jax

    if platform == "cpu":
        os.environ.setdefault("BENCH_LONG_OBS", "16384")
        os.environ.setdefault("BENCH_ULTRA_OBS", "16384")

    import jax.numpy as jnp

    def emit(obj):
        # probe-failure fallback is visible on every line (review r3:
        # a wedged-TPU run must never read as a deliberate CPU capture)
        if degraded:
            from bench import DEGRADED_NOTE
            # setdefault per the shared contract: a site that already set a
            # more specific degraded message keeps its own
            obj.setdefault("degraded", DEGRADED_NOTE)
        print(json.dumps(obj), flush=True)

    from bench import _synthetic_arima_panel
    from spark_timeseries_tpu import stats
    from spark_timeseries_tpu.models import (arima, ewma, garch,
                                             holt_winters,
                                             regression_arima)

    dtype = jnp.float32 if platform != "cpu" else jnp.float64
    if dtype == jnp.float64:
        jax.config.update("jax_enable_x64", True)
    rng = np.random.default_rng(0)
    results = []
    failures = []      # correctness checks, raised AFTER all lines print

    # smoke knobs so the resilience contract covers this entry point too
    # (tests/test_bench_resilience.py runs the suite at tiny shapes with
    # the probe forced to fail): caps apply to every config's panel, never
    # below what the models structurally need at the default configs
    cap_n = int(os.environ.get("BENCH_SUITE_SERIES_CAP", "0")) or None
    cap_obs = int(os.environ.get("BENCH_SUITE_OBS_CAP", "0")) or None

    def sized(n, n_obs):
        return (min(n, cap_n) if cap_n else n,
                min(n_obs, cap_obs) if cap_obs else n_obs)

    # 1. EWMA on an AR(1) panel (BASELINE config #1)
    n, n_obs = sized(65536, 128)
    ar1 = np.cumsum(rng.normal(size=(n, n_obs)), axis=1) + 100.0
    vals = jnp.asarray(ar1, dtype)
    dt, _ = _timed(jax.jit(lambda v: ewma.fit(v).smoothing), vals)
    results.append(("EWMA fit", n, n_obs, n / dt,
                    _baseline(_ewma_baseline, ar1)))

    # 2. ARIMA(2,1,2) (BASELINE config #2; headline, mirrors bench.py)
    n, n_obs = sized(8192, 128)
    arima_panel = _synthetic_arima_panel(n, n_obs)
    vals = jnp.asarray(arima_panel, dtype)
    dt, _ = _timed(
        jax.jit(lambda v: arima.fit(2, 1, 2, v, warn=False).coefficients),
        vals)
    results.append(("ARIMA(2,1,2) CSS+HR fit", n, n_obs, n / dt,
                    _baseline(_arima_baseline, arima_panel)))

    # 3. Holt-Winters additive, monthly seasonality (BASELINE config #3)
    (n, n_obs), period = sized(4096, 120), 12
    t = np.arange(n_obs)
    season = 10 * np.sin(2 * np.pi * t / period)
    base = (100 + 0.5 * t + season)[None, :] \
        + rng.normal(scale=2.0, size=(n, n_obs))
    vals = jnp.asarray(base, dtype)
    fit_hw = jax.jit(lambda v: holt_winters.fit(v, period, "additive",
                                                max_iter=200).alpha)
    dt, _ = _timed(fit_hw, vals)
    results.append(("HoltWinters additive fit", n, n_obs, n / dt,
                    _baseline(_hw_baseline_factory(period), base)))

    # 4. AR-GARCH volatility (BASELINE config #4, minute-bar profile)
    n, n_obs = sized(4096, 1024)
    gen = garch.ARGARCHModel(jnp.asarray(0.1), jnp.asarray(0.3),
                             jnp.asarray(0.05), jnp.asarray(0.1),
                             jnp.asarray(0.85))
    sample_panel = np.asarray(
        gen.sample(n_obs, jax.random.PRNGKey(1), shape=(n,)))
    vals = jnp.asarray(sample_panel, dtype)
    dt, _ = _timed(jax.jit(lambda v: garch.fit_ar_garch(v).alpha), vals)
    results.append(("ARGARCH(1,1) fit", n, n_obs, n / dt,
                    _baseline(_argarch_baseline, sample_panel, sample=4)))

    # 5. RegressionARIMA + batched ADF/KPSS (BASELINE config #5)
    (n, n_obs), k = sized(8192, 256), 3
    X = rng.normal(size=(n_obs, k)).cumsum(axis=0)
    beta = rng.normal(size=k)
    e = np.zeros((n, n_obs))
    w = rng.normal(size=(n, n_obs))
    for tt in range(1, n_obs):
        e[:, tt] = 0.6 * e[:, tt - 1] + w[:, tt]
    y_np = X @ beta + e
    y = jnp.asarray(y_np, dtype)
    Xj = jnp.asarray(X, dtype)

    def reg_and_tests(v):
        m = regression_arima.fit_cochrane_orcutt(v, Xj, 10)
        adf, _ = stats.adftest(v, 4)
        kpss, _ = stats.kpsstest(v, "c")
        return m.arima_coeff, adf, kpss

    dt, _ = _timed(jax.jit(reg_and_tests), y)
    results.append(("RegressionARIMA + ADF/KPSS", n, n_obs, n / dt,
                    _baseline(_regarima_baseline_factory(X), y_np,
                              sample=256)))

    # 6. batched auto-ARIMA order selection (SURVEY §3.5 — the strongest
    # argument for batched fitting; grid (p,q) <= 2x2 to bound runtime)
    n, n_obs = sized(2048, 128)
    auto_panel = _synthetic_arima_panel(n, n_obs, seed=3)
    vals = jnp.asarray(auto_panel, dtype)

    def run_auto(v):
        return arima.auto_fit_panel(v, max_p=2, max_d=2, max_q=2)

    run_auto(vals)          # warm every (d, p, q) trace
    t0 = time.perf_counter()
    out = run_auto(vals)
    np.asarray(out.coefficients)
    dt = time.perf_counter() - t0
    results.append(("auto-ARIMA grid search (p,q<=2)", n, n_obs, n / dt,
                    _baseline(_auto_arima_baseline_factory(), auto_panel,
                              sample=3)))

    # 7. long-series volatility — the sequence dimension at the reference's
    # qualitative scale envelope ("a couple million elements" per 10y
    # minutely series, ref src/site/markdown/index.md:35-40).  The GARCH
    # likelihood and EWMA smooth are associative-scan recurrences
    # (ops/scan_parallel), so the time axis evaluates in O(log n) depth and
    # can shard over a mesh; metric is observations/sec since the panel is
    # wide in time, not series.
    from spark_timeseries_tpu.ops import scan_parallel

    n, n_obs = sized(64, 0)[0], int(os.environ.get("BENCH_LONG_OBS", "262144"))
    gen = garch.GARCHModel(jnp.asarray(0.05), jnp.asarray(0.1),
                           jnp.asarray(0.85))
    long_panel = np.asarray(gen.sample(n_obs, jax.random.PRNGKey(2),
                                       shape=(n,)))
    vals = jnp.asarray(long_panel, dtype)

    def long_fit(v):
        m = garch.fit(v, max_iter=50)
        smooth = scan_parallel.ewma_smooth(v * v, jnp.asarray(0.06, dtype))
        return m.alpha, smooth[..., -1]

    dt, _ = _timed(jax.jit(long_fit), vals, reps=1)
    obs_rate = n * n_obs / dt

    # CPU baseline: the scalar variance-recurrence MLE on a 65536-obs slice
    # of one series, extrapolated linearly (the scalar path is O(n))
    from scipy.optimize import minimize as sp_minimize
    sub = min(65536, n_obs)
    t0 = time.perf_counter()
    sp_minimize(_garch_neg_ll_scalar, np.array([0.2, 0.2, 0.2]),
                args=(long_panel[0, :sub].astype(np.float64),),
                method="Nelder-Mead", options={"maxiter": 200})
    cpu_obs_rate = sub / (time.perf_counter() - t0)
    results.append(("long-series GARCH fit + EWMA smooth (obs/sec)",
                    n, n_obs, obs_rate, (cpu_obs_rate, 1)))

    # 8. ultra-long ARIMA: segment-parallel fit_long vs the direct CSS fit
    # on the same series.  The direct fit's lax.scan serializes the time
    # axis (its wall time is scan-latency-bound); fit_long folds time
    # blocks into the batch axis.  vs_baseline here is the measured speedup
    # over the DIRECT TPU fit (an in-framework baseline, not the CPU
    # emulation), with coefficient agreement asserted so the speed is not
    # buying a different answer.
    n, n_obs = 8, int(os.environ.get("BENCH_ULTRA_OBS", "262144"))
    # seg_len must leave >= 2 segments after d=1 differencing; skip the
    # config (without discarding the 7 configs already measured) when
    # BENCH_ULTRA_OBS is set too small to segment meaningfully
    if n_obs - 1 >= 2 * 4096:
        seg_len = max(4096, n_obs // 16)
        ultra = _synthetic_arima_panel(n, n_obs, seed=7)
        vals = jnp.asarray(ultra, dtype)
        fit_direct = jax.jit(
            lambda v: arima.fit(2, 1, 2, v, warn=False).coefficients)
        fit_seg = jax.jit(
            lambda v: arima.fit_long(2, 1, 2, v, segment_len=seg_len,
                                     warn=False).coefficients)
        dt_direct, out_d = _timed(fit_direct, vals, reps=1)
        dt_seg, out_s = _timed(fit_seg, vals, reps=1)
        agree = float(np.max(np.abs(out_d[0] - out_s[0])))
        # the speedup must not buy a different answer, at bench scale too
        # (unit tests only cover <= 32k obs) — but the check must not
        # discard the seven configs already measured, so it is recorded
        # here and raised only after every result line has been printed
        if not agree < 0.05:
            failures.append(
                f"fit_long diverged from the direct fit at bench scale: "
                f"max coefficient delta {agree:.4f} >= 0.05")
        results.append(("ultra-long ARIMA fit_long (obs/sec)", n, n_obs,
                        n * n_obs / dt_seg, (n * n_obs / dt_direct, 1)))
        emit({
            "metric": "fit_long vs direct coefficient max-abs-diff "
                      f"({n}x{n_obs}, asserted < 0.05)",
            "value": round(agree, 4), "unit": "coefficient delta",
            "platform": platform})
    else:
        emit({
            "metric": "ultra-long ARIMA fit_long", "value": None,
            "unit": "obs/sec", "platform": platform,
            "note": f"skipped: BENCH_ULTRA_OBS={n_obs} too short to segment"})

    # 9. panel-scale CSV persistence round trip (the reference's
    # saveAsCsv/timeSeriesRDDFromCsv contract at 100k series): vectorized
    # save + load, bit-exactness asserted so speed isn't buying corruption
    import tempfile

    from spark_timeseries_tpu import io as stio
    from spark_timeseries_tpu.panel import Panel
    from spark_timeseries_tpu.time import uniform
    from spark_timeseries_tpu.time.frequency import DayFrequency

    n, n_obs = int(os.environ.get("BENCH_CSV_SERIES", "100000")), 64
    csv_vals = rng.normal(size=(n, n_obs))
    csv_panel = Panel(uniform("2020-01-01T00:00Z", n_obs, DayFrequency(1)),
                      jnp.asarray(csv_vals, jnp.float64),
                      [f"k{i}" for i in range(n)])
    with tempfile.TemporaryDirectory() as tmp:
        t0 = time.perf_counter()
        stio.save_csv(csv_panel, tmp)
        back = stio.load_csv(tmp)
        dt = time.perf_counter() - t0
    if not np.array_equal(np.asarray(back.values, np.float64),
                          np.asarray(csv_panel.values), equal_nan=True):
        failures.append("CSV round trip was not bit-exact")
    emit({
        "metric": f"CSV save+load round trip series/sec ({n}x{n_obs}, "
                  "bit-exact)",
        "value": round(n / dt, 1), "unit": "series/sec",
        "platform": platform})

    for name, n, n_obs, rate, baseline in results:
        unit = "obs/sec" if "obs/sec" in name else "series/sec"
        label = name.replace(" (obs/sec)", "")
        line = {
            "metric": f"{label} {unit}/chip ({n}x{n_obs})",
            "value": round(rate, 1),
            "unit": unit,
            "platform": platform,
        }
        if baseline is not None:
            base_rate, sample = baseline
            kind = ("direct (unsegmented) fit of the same series on the "
                    "same device — in-framework baseline"
                    if "ultra-long" in name else
                    "per-series scalar numpy/scipy, reference cost shape")
            line["vs_baseline"] = round(rate / base_rate, 2)
            line["baseline_emulation"] = {
                "kind": kind,
                "sample": sample,
                "rate": round(base_rate, 3),
            }
        emit(line)

    if failures:
        raise AssertionError("; ".join(failures))


if __name__ == "__main__":
    main()
