"""Extended benchmark suite — the five BASELINE.md configs.

``python benchmarks/bench_suite.py`` prints one JSON line per config:
EWMA, ARIMA (the headline, same as bench.py), Holt-Winters seasonal,
AR-GARCH volatility, and RegressionARIMA + stationarity tests.  Synthetic
panels stand in for the M4/minute-bar datasets (zero-egress environment);
shapes are chosen to match their scale profile.  All timings are to host
materialization (the tunneled TPU platform does not synchronize on
block_until_ready alone).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _timed(fn, *args, reps=3):
    import jax

    def materialize(out):
        return [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(out)]

    materialize(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = materialize(fn(*args))
    return (time.perf_counter() - t0) / reps, out


def main():
    import jax
    import jax.numpy as jnp

    from bench import _synthetic_arima_panel
    from spark_timeseries_tpu import stats
    from spark_timeseries_tpu.models import (arima, ewma, garch,
                                             holt_winters,
                                             regression_arima)

    dtype = jnp.float32 if jax.devices()[0].platform == "tpu" else jnp.float64
    if dtype == jnp.float64:
        jax.config.update("jax_enable_x64", True)
    rng = np.random.default_rng(0)
    results = []

    # 1. EWMA on an AR(1) panel (BASELINE config #1)
    n, n_obs = 65536, 128
    ar1 = np.cumsum(rng.normal(size=(n, n_obs)), axis=1) + 100.0
    vals = jnp.asarray(ar1, dtype)
    dt, _ = _timed(jax.jit(lambda v: ewma.fit(v).smoothing), vals)
    results.append(("EWMA fit", n, n_obs, n / dt))

    # 2. ARIMA(2,1,2) (BASELINE config #2; headline, mirrors bench.py)
    n, n_obs = 8192, 128
    vals = jnp.asarray(_synthetic_arima_panel(n, n_obs), dtype)
    dt, _ = _timed(
        jax.jit(lambda v: arima.fit(2, 1, 2, v, warn=False).coefficients),
        vals)
    results.append(("ARIMA(2,1,2) CSS+HR fit", n, n_obs, n / dt))

    # 3. Holt-Winters additive, monthly seasonality (BASELINE config #3)
    n, n_obs, period = 4096, 120, 12
    t = np.arange(n_obs)
    season = 10 * np.sin(2 * np.pi * t / period)
    base = (100 + 0.5 * t + season)[None, :] \
        + rng.normal(scale=2.0, size=(n, n_obs))
    vals = jnp.asarray(base, dtype)
    fit_hw = jax.jit(lambda v: holt_winters.fit(v, period, "additive",
                                                max_iter=200).alpha)
    dt, _ = _timed(fit_hw, vals)
    results.append(("HoltWinters additive fit", n, n_obs, n / dt))

    # 4. AR-GARCH volatility (BASELINE config #4, minute-bar profile)
    n, n_obs = 4096, 1024
    gen = garch.ARGARCHModel(jnp.asarray(0.1), jnp.asarray(0.3),
                             jnp.asarray(0.05), jnp.asarray(0.1),
                             jnp.asarray(0.85))
    vals = gen.sample(n_obs, jax.random.PRNGKey(1), shape=(n,)).astype(dtype)
    dt, _ = _timed(jax.jit(lambda v: garch.fit_ar_garch(v).alpha), vals)
    results.append(("ARGARCH(1,1) fit", n, n_obs, n / dt))

    # 5. RegressionARIMA + batched ADF/KPSS (BASELINE config #5)
    n, n_obs, k = 8192, 256, 3
    X = rng.normal(size=(n_obs, k)).cumsum(axis=0)
    beta = rng.normal(size=k)
    e = np.zeros((n, n_obs))
    w = rng.normal(size=(n, n_obs))
    for tt in range(1, n_obs):
        e[:, tt] = 0.6 * e[:, tt - 1] + w[:, tt]
    y = jnp.asarray(X @ beta + e, dtype)
    Xj = jnp.asarray(X, dtype)

    def reg_and_tests(v):
        m = regression_arima.fit_cochrane_orcutt(v, Xj, 10)
        adf, _ = stats.adftest(v, 4)
        kpss, _ = stats.kpsstest(v, "c")
        return m.arima_coeff, adf, kpss

    dt, _ = _timed(jax.jit(reg_and_tests), y)
    results.append(("RegressionARIMA + ADF/KPSS", n, n_obs, n / dt))

    for name, n, n_obs, rate in results:
        print(json.dumps({
            "metric": f"{name} series/sec/chip ({n}x{n_obs})",
            "value": round(rate, 1),
            "unit": "series/sec",
        }))


if __name__ == "__main__":
    main()
