"""Autonomous fleet runtime (ISSUE 17).

The acceptance scenarios live here:

- the supervised background pump delivers every admitted tick exactly
  once, **bitwise** what per-session updates produce — including across
  injected pump crashes (``pump_crash`` → watchdog restart, counted in
  ``fleet.pump_restarts``, flight-recorder bundle per death);
- a wedged pump (``pump_hang``) flips ``/healthz`` to stale under the
  jobs' ``STS_TELEMETRY_STALE_FACTOR`` contract, the watchdog abandons
  and respawns it, and the endpoint flips back;
- blocking admission backpressure parks the producer instead of raising
  ``FleetSaturated`` and raises the named ``FleetBackpressureTimeout``
  past its deadline;
- auto-checkpointing commits per-tenant drain bundles as atomic
  *generations* (fsync'd ``MANIFEST.json`` is the commit point): a
  ``kill -9`` mid-pass (``checkpoint_torn``, subprocess pair) leaves the
  torn generation invisible and ``restore_latest()`` resumes bitwise
  from the previous committed one;
- the self-driving rebalancer consolidates fragmented coalescing groups
  across shards through the drain/adopt path with zero tick loss;
- the PR-13 race harness drives pump vs submit vs checkpoint vs scrape
  with an acyclic acquisition-order graph, and the warmed tick path
  stays at **zero** recompiles with runtime + quality + telemetry armed.

Fast in-process scenarios run in tier-1; the subprocess pair and the
jax-heavy race run are ``slow`` and run via ``make verify-runtime``
(the ``runtime`` marker), which ``verify-faults`` also drives under
``STS_FAULT_INJECT=1``.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax.numpy as jnp

from spark_timeseries_tpu import statespace as ss
from spark_timeseries_tpu.models import arima
from spark_timeseries_tpu.statespace.fleet import (
    AdmissionPolicy, FleetSaturated, FleetScheduler)
from spark_timeseries_tpu.statespace.runtime import (
    _GEN_PREFIX, _MANIFEST, FleetBackpressureTimeout, FleetRuntime,
    RuntimePolicy)
from spark_timeseries_tpu.utils import metrics, resilience, telemetry

pytestmark = pytest.mark.runtime

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

S, N_HIST = 4, 120       # the shared test_fleet geometry -> one shared
#                          fit executable and serving bucket module-wide


def _ar2_panel(n_series, n, seed=0):
    rng = np.random.default_rng(seed)
    e = rng.normal(size=(n_series, n + 16))
    y = np.zeros((n_series, n + 16))
    for t in range(2, n + 16):
        y[:, t] = 0.3 + 0.5 * y[:, t - 1] - 0.2 * y[:, t - 2] + e[:, t]
    return y[:, 16:]


def _tenant_fixtures(n_tenants, seed0=1):
    hists = [_ar2_panel(S, N_HIST, seed=seed0 + i)
             for i in range(n_tenants)]
    models = [arima.fit(2, 0, 0, jnp.asarray(h), warn=False)
              for h in hists]
    return models, hists


def _build_runtime(n_tenants, *, policy=None, admission=None, seed0=1,
                   n_shards=1, warm=True):
    """(runtime, models, hists, registry) — n same-geometry tenants
    spread round-robin over n_shards schedulers under one runtime."""
    reg = metrics.MetricsRegistry()
    models, hists = _tenant_fixtures(n_tenants, seed0=seed0)
    shards = [FleetScheduler(admission, registry=reg, auto_pump=False)
              for _ in range(n_shards)]
    for i, (m, h) in enumerate(zip(models, hists)):
        sess = ss.ServingSession.start(m, h, label=f"t{i}", registry=reg)
        shards[i % n_shards].attach(sess)
    rt = FleetRuntime(shards if n_shards > 1 else shards[0],
                      policy=policy, registry=reg)
    if warm:
        rt.warmup()
    return rt, models, hists, reg


def _mirrors(models, hists):
    return [ss.ServingSession.start(m, h,
                                    registry=metrics.MetricsRegistry())
            for m, h in zip(models, hists)]


def _assert_bitwise(rt, mirrors):
    for i, mirror in enumerate(mirrors):
        sh, t = rt._find(f"t{i}")
        sess = t.session
        assert sess.ticks_seen == mirror.ticks_seen
        np.testing.assert_array_equal(np.asarray(sess._state.a),
                                      np.asarray(mirror._state.a))
        np.testing.assert_array_equal(np.asarray(sess._state.P),
                                      np.asarray(mirror._state.P))
        np.testing.assert_array_equal(sess.loglik, mirror.loglik)


# ---------------------------------------------------------------------------
# policy + plumbing
# ---------------------------------------------------------------------------

def test_runtime_policy_validation_rejects_nonsense():
    with pytest.raises(ValueError, match="pump_interval_s"):
        RuntimePolicy(pump_interval_s=0).validate()
    with pytest.raises(ValueError, match="stall_after_s"):
        RuntimePolicy(stall_after_s=-1.0).validate()
    with pytest.raises(ValueError, match="keep_generations"):
        RuntimePolicy(keep_generations=0).validate()
    with pytest.raises(ValueError, match="rebalance_imbalance"):
        RuntimePolicy(rebalance_imbalance=0.5).validate()
    with pytest.raises(ValueError, match="max_moves_per_cycle"):
        RuntimePolicy(max_moves_per_cycle=0).validate()
    # auto-checkpoint triggers without a directory are a config error,
    # not a silent no-op
    with pytest.raises(ValueError, match="checkpoint_dir"):
        RuntimePolicy(checkpoint_interval_s=1.0).validate()
    with pytest.raises(ValueError, match="checkpoint_dir"):
        RuntimePolicy(checkpoint_dirty_ticks=8).validate()
    assert RuntimePolicy().validate() == RuntimePolicy()


def test_runtime_fault_modes_are_registered():
    for mode in ("pump_crash", "pump_hang", "checkpoint_torn"):
        assert mode in resilience._VALID_MODES
        assert resilience.fleet_fault(mode) is None      # no scope armed
        with resilience.fault_injection(mode, n_attempts=2):
            spec = resilience.fleet_fault(mode)
            assert spec is not None and spec.n_attempts == 2
    assert issubclass(resilience.InjectedPumpCrash, RuntimeError)


def test_runtime_rejects_duplicate_labels_across_shards():
    reg = metrics.MetricsRegistry()
    models, hists = _tenant_fixtures(1, seed0=61)
    shards = [FleetScheduler(registry=reg, auto_pump=False)
              for _ in range(2)]
    for sh in shards:
        sh.attach(ss.ServingSession.start(models[0], hists[0],
                                          label="dup", registry=reg))
    with pytest.raises(ValueError, match="dup"):
        FleetRuntime(shards, registry=reg)
    with pytest.raises(ValueError, match="at least one"):
        FleetRuntime([], registry=reg)


def test_attach_routes_least_loaded_and_validates():
    rt, models, hists, reg = _build_runtime(2, n_shards=2, seed0=63,
                                            warm=False)
    m, h = _tenant_fixtures(1, seed0=66)
    extra = ss.ServingSession.start(m[0], h[0], label="extra",
                                    registry=reg)
    # both shards hold 1 tenant; least-loaded picks the first min
    assert rt.attach(extra) == "extra"
    with pytest.raises(ValueError, match="already"):
        rt.attach(extra)
    m2, h2 = _tenant_fixtures(1, seed0=67)
    other = ss.ServingSession.start(m2[0], h2[0], label="other",
                                    registry=reg)
    with pytest.raises(KeyError, match="no shard"):
        rt.attach(other, shard="nope")
    with pytest.raises(KeyError, match="no tenant"):
        rt.forecast("missing", 3)
    assert reg.snapshot()["counters"]["fleet.runtimes"] == 1


# ---------------------------------------------------------------------------
# async dispatch: bitwise, exactly-once
# ---------------------------------------------------------------------------

def test_async_runtime_delivers_ticks_bitwise():
    rt, models, hists, reg = _build_runtime(3, seed0=11)
    mirrors = _mirrors(models, hists)
    rng = np.random.default_rng(3)
    ticks = rng.normal(size=(3, S, 10))
    with rt:
        for t in range(10):
            for i in range(3):
                rt.submit(f"t{i}", ticks[i, :, t], block=True,
                          timeout=30.0)
        assert rt.quiesce(timeout=30.0)
        for i in range(3):
            for t in range(10):
                mirrors[i].update(ticks[i, :, t])
        _assert_bitwise(rt, mirrors)
        # forecasts ride the same locked passthrough, bitwise
        np.testing.assert_array_equal(rt.forecast("t0", 5),
                                      mirrors[0].forecast(5))
    assert not rt.running
    assert rt.pump_summary()["restarts"] == 0
    counters = reg.snapshot()["counters"]
    assert counters.get("fleet.pump_restarts", 0) == 0


def test_stopped_runtime_cannot_restart_and_stop_is_idempotent():
    rt, models, hists, _ = _build_runtime(1, seed0=21, warm=False)
    with rt:
        assert rt.running
        with pytest.raises(RuntimeError, match="already"):
            rt.start()
    rt.stop()                                # second stop: no-op
    with pytest.raises(RuntimeError, match="stopped"):
        rt.start()


# ---------------------------------------------------------------------------
# backpressure
# ---------------------------------------------------------------------------

def test_unstarted_runtime_degrades_to_nonblocking_admission():
    rt, models, hists, _ = _build_runtime(
        1, admission=AdmissionPolicy(queue_depth=2), seed0=31,
        warm=False)
    tick = np.zeros(S)
    rt.submit("t0", tick)                    # queue 1/2
    rt.submit("t0", tick)                    # queue 2/2
    # blocking would never end without a pump; the call degrades to the
    # raw admission behavior instead of deadlocking the producer
    with pytest.raises(FleetSaturated):
        rt.submit("t0", tick, block=True)
    # manual sweeps drain: one coalesced dispatch per sweep per group
    assert rt.pump_once() == 1
    assert rt.pump_once() == 1
    assert rt.pump_once() == 0


def test_backpressure_blocks_waits_and_times_out():
    rt, models, hists, reg = _build_runtime(
        1, admission=AdmissionPolicy(queue_depth=2), seed0=33,
        policy=RuntimePolicy(pump_interval_s=0.005, stall_after_s=30.0))
    mirror = _mirrors(models, hists)[0]
    rng = np.random.default_rng(7)
    ticks = rng.normal(size=(S, 8))
    with resilience.fault_injection("pump_hang", hang_s=2.0):
        with rt:
            # the first sweep sleeps 2 s OUTSIDE the lock: submits
            # proceed, nothing drains
            rt.submit("t0", ticks[:, 0], block=False)
            rt.submit("t0", ticks[:, 1], block=False)
            t0 = time.monotonic()
            with pytest.raises(FleetBackpressureTimeout, match="t0"):
                rt.submit("t0", ticks[:, 2], block=True, timeout=0.4)
            assert time.monotonic() - t0 >= 0.4
            # no deadline: the producer parks until the pump drains
            for t in range(2, 8):
                rt.submit("t0", ticks[:, t], block=True, timeout=30.0)
            assert rt.quiesce(timeout=30.0)
            for t in range(8):
                mirror.update(ticks[:, t])
            _assert_bitwise(rt, [mirror])
    counters = reg.snapshot()["counters"]
    assert counters["fleet.backpressure_timeouts"] == 1
    assert counters["fleet.backpressure_waits"] >= 1
    assert counters.get("fleet.rejected", 0) == 0, \
        "a blocking producer saw the saturation path"


# ---------------------------------------------------------------------------
# pump supervision: crash + hang
# ---------------------------------------------------------------------------

def test_pump_crash_supervision_restarts_and_stays_bitwise(
        tmp_path, monkeypatch):
    monkeypatch.setenv("STS_INCIDENT_DIR", str(tmp_path / "incidents"))
    rt, models, hists, reg = _build_runtime(
        3, seed0=41,
        policy=RuntimePolicy(pump_interval_s=0.002,
                             watchdog_interval_s=0.01))
    mirrors = _mirrors(models, hists)
    rng = np.random.default_rng(9)
    ticks = rng.normal(size=(3, S, 10))
    with resilience.fault_injection("pump_crash", n_attempts=3):
        with rt:
            for t in range(10):
                for i in range(3):
                    rt.submit(f"t{i}", ticks[i, :, t], block=True,
                              timeout=60.0)
            assert rt.quiesce(timeout=60.0)
            summary = rt.pump_summary()
    assert summary["restarts"] >= 1, summary
    counters = reg.snapshot()["counters"]
    assert counters["fleet.pump_restarts"] == summary["restarts"]
    assert counters["fleet.pump_deaths"] >= 1
    # every admitted tick was dispatched exactly once across the crashes
    for i in range(3):
        for t in range(10):
            mirrors[i].update(ticks[i, :, t])
    _assert_bitwise(rt, mirrors)
    # each death left a flight-recorder bundle
    inc_dir = str(tmp_path / "incidents")
    names = os.listdir(inc_dir) if os.path.isdir(inc_dir) else []
    assert any("fleet_pump_death" in n for n in names), names


def test_pump_hang_flips_healthz_and_watchdog_recovers(
        tmp_path, monkeypatch):
    monkeypatch.setenv("STS_INCIDENT_DIR", str(tmp_path / "incidents"))
    monkeypatch.setenv("STS_TELEMETRY_STALE_FACTOR", "0.25")
    rt, models, hists, reg = _build_runtime(
        1, seed0=43,
        policy=RuntimePolicy(pump_interval_s=0.005,
                             watchdog_interval_s=0.05,
                             stall_after_s=0.8))
    assert rt.stale_after_s() == pytest.approx(0.25)  # 0.25 * max(.005,1)

    def _my_row(doc):
        return [r for r in doc["fleet_pumps"]
                if r.get("runtime") == rt.label]

    with resilience.fault_injection("pump_hang", hang_s=1.5):
        with rt:
            # the hung pump's heartbeat ages past the scrape-plane
            # threshold (0.25 s) well before the watchdog's 0.8 s
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                doc = telemetry.healthz_doc()
                rows = _my_row(doc)
                if rows and rows[0]["stale"]:
                    break
                time.sleep(0.01)
            else:
                pytest.fail("healthz never went stale during the hang")
            assert doc["status"] == "stale"
            # watchdog: declare wedged, record the stall, respawn
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if rt.pump_summary()["restarts"] >= 1:
                    break
                time.sleep(0.01)
            else:
                pytest.fail("watchdog never restarted the hung pump")
            # the replacement pump heartbeats -> healthz flips back
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                doc = telemetry.healthz_doc()
                rows = _my_row(doc)
                if rows and not rows[0]["stale"] \
                        and doc["status"] == "ok":
                    break
                time.sleep(0.01)
            else:
                pytest.fail("healthz never recovered after the restart")
            # and the recovered runtime still serves
            rng = np.random.default_rng(13)
            ticks = rng.normal(size=(S, 4))
            for t in range(4):
                rt.submit("t0", ticks[:, t], block=True, timeout=30.0)
            assert rt.quiesce(timeout=30.0)
            sh, ten = rt._find("t0")
            assert ten.session.ticks_seen == N_HIST + 4
    assert reg.snapshot()["counters"]["fleet.pump_restarts"] >= 1
    inc_dir = str(tmp_path / "incidents")
    names = os.listdir(inc_dir) if os.path.isdir(inc_dir) else []
    assert any("fleet_pump_stall" in n for n in names), names


# ---------------------------------------------------------------------------
# auto-checkpoint generations
# ---------------------------------------------------------------------------

def test_auto_checkpoint_commits_generations_and_prunes(tmp_path):
    ck = str(tmp_path / "ck")
    rt, models, hists, reg = _build_runtime(
        2, seed0=51,
        policy=RuntimePolicy(checkpoint_dir=ck, checkpoint_dirty_ticks=4,
                             keep_generations=2))
    rng = np.random.default_rng(15)
    for gen in range(3):                     # 3 dirty-tick triggers
        for k in range(2):
            for i in range(2):
                rt.submit(f"t{i}", rng.normal(size=S))
        rt.pump_once()                       # 4 dirty -> commit
    assert reg.snapshot()["counters"]["fleet.checkpoints"] == 3
    committed = FleetRuntime._scan_generations(ck)
    assert [g for g, _ in committed] == [2, 3]   # pruned to keep=2
    found = FleetRuntime.latest_generation(ck)
    assert found is not None
    gen, gdir, manifest = found
    assert gen == 3 and manifest["format"] == 1
    rows = {r["tenant"]: r for r in manifest["tenants"]}
    assert set(rows) == {"t0", "t1"}
    assert all(os.path.exists(os.path.join(gdir, la) + ".npz")
               for la in rows)
    assert rt.pump_summary()["checkpoint_generation"] == 3


def test_restore_latest_replays_pending_bitwise(tmp_path):
    ck = str(tmp_path / "ck")
    rt, models, hists, reg = _build_runtime(
        2, seed0=53, policy=RuntimePolicy(checkpoint_dir=ck))
    mirrors = _mirrors(models, hists)
    rng = np.random.default_rng(17)
    ticks = rng.normal(size=(2, S, 12))
    for t in range(6):
        for i in range(2):
            rt.submit(f"t{i}", ticks[i, :, t])
        rt.pump_once()
    for i in range(2):                       # two pending ticks ride
        rt.submit(f"t{i}", ticks[i, :, 6])   # the bundles
        rt.submit(f"t{i}", ticks[i, :, 7])
    rep = rt.checkpoint()
    assert rep == {"generation": 1,
                   "dir": os.path.join(ck, f"{_GEN_PREFIX}00000001"),
                   "tenants": 2}
    # a fresh runtime (empty shards) adopts + replays the generation
    reg2 = metrics.MetricsRegistry()
    rt2 = FleetRuntime(FleetScheduler(registry=reg2, auto_pump=False),
                       policy=RuntimePolicy(checkpoint_dir=ck),
                       registry=reg2)
    assert sorted(rt2.restore_latest()) == ["t0", "t1"]
    for i in range(2):
        for t in range(8):
            mirrors[i].update(ticks[i, :, t])
    _assert_bitwise(rt2, mirrors)
    # and keeps serving bitwise
    for t in range(8, 12):
        for i in range(2):
            rt2.submit(f"t{i}", ticks[i, :, t])
            mirrors[i].update(ticks[i, :, t])
        rt2.pump_once()
    _assert_bitwise(rt2, mirrors)
    np.testing.assert_array_equal(rt2.forecast("t1", 4),
                                  mirrors[1].forecast(4))
    assert reg2.snapshot()["counters"]["fleet.restored_tenants"] == 2


def test_torn_generation_is_invisible_and_never_reused(tmp_path):
    ck = str(tmp_path / "ck")
    rt, models, hists, _ = _build_runtime(
        1, seed0=55, policy=RuntimePolicy(checkpoint_dir=ck))
    rt.submit("t0", np.zeros(S))
    rt.pump_once()
    assert rt.checkpoint()["generation"] == 1
    # fabricate torn debris: bundles landed, manifest never did
    torn = os.path.join(ck, f"{_GEN_PREFIX}00000002")
    os.makedirs(torn)
    with open(os.path.join(torn, "t0.npz"), "wb") as f:
        f.write(b"half a bundle")
    found = FleetRuntime.latest_generation(ck)
    assert found is not None and found[0] == 1
    assert FleetRuntime._scan_generations(ck, committed_only=False)[-1][0] \
        == 2
    # a new incarnation numbers PAST the debris — gen 2 is never reused
    reg2 = metrics.MetricsRegistry()
    sched2 = FleetScheduler(registry=reg2, auto_pump=False)
    rt2 = FleetRuntime(sched2, policy=RuntimePolicy(checkpoint_dir=ck),
                       registry=reg2)
    assert rt2.restore_latest() == ["t0"]
    assert rt2.checkpoint()["generation"] == 3


def test_checkpoint_requires_dir_and_failures_never_commit(tmp_path):
    rt, models, hists, _ = _build_runtime(1, seed0=57, warm=False)
    with pytest.raises(RuntimeError, match="checkpoint_dir"):
        rt.checkpoint()
    with pytest.raises(RuntimeError, match="checkpoint_dir"):
        rt.restore_latest()
    # a generation dir that cannot be created: the pass fails, counts,
    # and commits nothing (crash-only — the pump would survive it)
    ck = str(tmp_path / "ck")
    reg2 = metrics.MetricsRegistry()
    models2, hists2 = _tenant_fixtures(1, seed0=58)
    sched2 = FleetScheduler(registry=reg2, auto_pump=False)
    sched2.attach(ss.ServingSession.start(models2[0], hists2[0],
                                          label="t0", registry=reg2))
    rt2 = FleetRuntime(sched2, registry=reg2,
                       policy=RuntimePolicy(checkpoint_dir=ck))
    # a regular file squats on the next generation's directory path
    with open(os.path.join(ck, f"{_GEN_PREFIX}00000001"), "w") as f:
        f.write("file in the way")
    assert rt2.checkpoint() is None
    assert reg2.snapshot()["counters"]["fleet.checkpoint_failures"] == 1
    assert rt2.pump_summary()["checkpoint_failures"] == 1
    assert FleetRuntime.latest_generation(ck) is None


def test_stop_takes_a_final_checkpoint(tmp_path):
    ck = str(tmp_path / "ck")
    rt, models, hists, reg = _build_runtime(
        1, seed0=59,
        policy=RuntimePolicy(checkpoint_dir=ck,
                             checkpoint_dirty_ticks=10_000))
    with rt:
        rt.submit("t0", np.zeros(S), block=True, timeout=30.0)
        assert rt.quiesce(timeout=30.0)
    found = FleetRuntime.latest_generation(ck)
    assert found is not None
    assert found[2]["tenants"][0]["tenant"] == "t0"


_TORN_CHILD = """
import os
import numpy as np
import jax.numpy as jnp
from spark_timeseries_tpu import statespace as ss
from spark_timeseries_tpu.models import arima
from spark_timeseries_tpu.utils import metrics, resilience

def panel(n_series, n, seed):
    rng = np.random.default_rng(seed)
    e = rng.normal(size=(n_series, n + 16))
    y = np.zeros((n_series, n + 16))
    for t in range(2, n + 16):
        y[:, t] = 0.3 + 0.5*y[:, t-1] - 0.2*y[:, t-2] + e[:, t]
    return y[:, 16:]

S = 4
reg = metrics.MetricsRegistry()
sched = ss.FleetScheduler(registry=reg, auto_pump=False)
for i in range(2):
    hist = panel(S, 120, 71 + i)
    model = arima.fit(2, 0, 0, jnp.asarray(hist), warn=False)
    sched.attach(ss.ServingSession.start(model, hist, label=f"t{i}",
                                         registry=reg))
rt = ss.FleetRuntime(
    sched, registry=reg,
    policy=ss.RuntimePolicy(checkpoint_dir=os.environ["STS_TEST_CKPT"]))
live = [panel(S, 40, 81 + i) for i in range(2)]
for t in range(8):
    for i in range(2):
        rt.submit(f"t{i}", live[i][:, t])
    rt.pump_once()
for i in range(2):
    rt.submit(f"t{i}", live[i][:, 8])      # one pending tick per tenant
rep = rt.checkpoint()                      # generation 1 commits
assert rep is not None and rep["generation"] == 1, rep
rt.pump_once()                             # dispatch tick 8
for i in range(2):
    rt.submit(f"t{i}", live[i][:, 9])
with resilience.fault_injection("checkpoint_torn", n_attempts=1):
    rt.checkpoint()                        # t0 bundle lands, then kill -9
print("UNREACHABLE: checkpoint survived checkpoint_torn", flush=True)
raise SystemExit(3)
"""


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_kill9_mid_auto_checkpoint_restores_previous_generation(tmp_path):
    """The crash-only acceptance pin: a process SIGKILLed between a
    generation's bundles and its manifest leaves the torn generation
    invisible — a fresh process resumes from the previous *committed*
    generation, replays its buffered ticks, and every subsequent tick
    and forecast is bitwise an uninterrupted fleet's."""
    ck = str(tmp_path / "ck")
    inc_dir = str(tmp_path / "incidents")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               STS_TEST_CKPT=ck, STS_INCIDENT_DIR=inc_dir)
    out = subprocess.run([sys.executable, "-c", _TORN_CHILD],
                         capture_output=True, text=True, cwd=REPO,
                         env=env, timeout=600)
    assert out.returncode == -9, (out.returncode, out.stderr[-2000:])
    # gen 1 committed; gen 2 is torn debris (bundles, no manifest)
    found = FleetRuntime.latest_generation(ck)
    assert found is not None and found[0] == 1, found
    torn = os.path.join(ck, f"{_GEN_PREFIX}00000002")
    assert os.path.isdir(torn)
    assert not os.path.exists(os.path.join(torn, _MANIFEST))
    # the pre-kill forensics bundle landed
    names = os.listdir(inc_dir) if os.path.isdir(inc_dir) else []
    assert any("checkpoint_torn" in n for n in names), names

    # restore in THIS process; the uninterrupted mirror recomputes the
    # child's whole stream locally (fits are cross-process bitwise
    # deterministic — the journal resume suite pins that)
    def panel(n_series, n, seed):
        rng = np.random.default_rng(seed)
        e = rng.normal(size=(n_series, n + 16))
        y = np.zeros((n_series, n + 16))
        for t in range(2, n + 16):
            y[:, t] = 0.3 + 0.5 * y[:, t - 1] - 0.2 * y[:, t - 2] \
                + e[:, t]
        return y[:, 16:]

    hists = [panel(S, 120, 71 + i) for i in range(2)]
    live = [panel(S, 40, 81 + i) for i in range(2)]
    models = [arima.fit(2, 0, 0, jnp.asarray(h), warn=False)
              for h in hists]
    mirrors = _mirrors(models, hists)
    reg = metrics.MetricsRegistry()
    rt = FleetRuntime(FleetScheduler(registry=reg, auto_pump=False),
                      policy=RuntimePolicy(checkpoint_dir=ck),
                      registry=reg)
    assert sorted(rt.restore_latest()) == ["t0", "t1"]
    # gen 1 = ticks 0..7 applied + tick 8 pending; adopt replayed it
    for i in range(2):
        for t in range(9):
            mirrors[i].update(live[i][:, t])
    _assert_bitwise(rt, mirrors)
    # the resumed fleet keeps serving bitwise — and checkpoints number
    # PAST the torn debris (generation 3, never a reused 2)
    for t in range(9, 13):
        for i in range(2):
            rt.submit(f"t{i}", live[i][:, t])
            mirrors[i].update(live[i][:, t])
        rt.pump_once()
    _assert_bitwise(rt, mirrors)
    np.testing.assert_array_equal(rt.forecast("t0", 6),
                                  mirrors[0].forecast(6))
    assert rt.checkpoint()["generation"] == 3


# ---------------------------------------------------------------------------
# self-driving rebalance
# ---------------------------------------------------------------------------

def test_rebalance_consolidates_fragmented_group_bitwise(tmp_path):
    # 3 same-key tenants split 2/1 across shards: the group dispatches
    # two under-filled batches per sweep until consolidation heals it
    rt, models, hists, reg = _build_runtime(
        3, n_shards=2, seed0=73,
        policy=RuntimePolicy(checkpoint_dir=str(tmp_path / "ck")))
    mirrors = _mirrors(models, hists)
    rng = np.random.default_rng(19)
    ticks = rng.normal(size=(3, S, 6))
    for t in range(3):
        for i in range(3):
            rt.submit(f"t{i}", ticks[i, :, t])
        rt.pump_once()
    assert len(rt.shards[0]._tenants) == 2        # t0, t2
    assert len(rt.shards[1]._tenants) == 1        # t1 — the fragment
    moves = rt.rebalance()
    assert [(m["tenant"], m["from"], m["to"]) for m in moves] == \
        [("t1", rt.shards[1].label, rt.shards[0].label)]
    assert len(rt.shards[0]._tenants) == 3
    assert len(rt.shards[1]._tenants) == 0
    assert rt.rebalance() == []                   # converged: no churn
    # zero tick loss, bitwise, through the move
    for t in range(3, 6):
        for i in range(3):
            rt.submit(f"t{i}", ticks[i, :, t])
        rt.pump_once()
    for i in range(3):
        for t in range(6):
            mirrors[i].update(ticks[i, :, t])
    _assert_bitwise(rt, mirrors)
    assert reg.snapshot()["counters"]["fleet.rebalanced_tenants"] == 1


def test_rebalance_spreads_load_when_groups_are_whole(tmp_path):
    # distinct update keys (different model orders) -> no fragmentation;
    # a 3-vs-0 load split exceeds the imbalance ratio and spreads
    reg = metrics.MetricsRegistry()
    hists = [_ar2_panel(S, N_HIST, seed=75 + i) for i in range(3)]
    orders = [(2, 0, 0), (1, 0, 0), (0, 0, 1)]
    models = [arima.fit(p, d, q, jnp.asarray(h), warn=False)
              for (p, d, q), h in zip(orders, hists)]
    shards = [FleetScheduler(registry=reg, auto_pump=False)
              for _ in range(2)]
    for i, (m, h) in enumerate(zip(models, hists)):
        shards[0].attach(ss.ServingSession.start(m, h, label=f"t{i}",
                                                 registry=reg))
    rt = FleetRuntime(shards, registry=reg,
                      policy=RuntimePolicy(
                          checkpoint_dir=str(tmp_path / "ck"),
                          rebalance_imbalance=2.0))
    moves = rt.rebalance()
    assert len(moves) == 1
    assert moves[0]["from"] == shards[0].label
    assert moves[0]["to"] == shards[1].label
    assert len(shards[0]._tenants) == 2
    assert len(shards[1]._tenants) == 1
    assert reg.snapshot()["counters"]["fleet.rebalanced_tenants"] == 1


# ---------------------------------------------------------------------------
# race harness: pump vs submit vs checkpoint vs scrape (+ drain/adopt)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.timeout(900)
@pytest.mark.parametrize("seed", [1, 5])
def test_runtime_pump_submit_checkpoint_scrape_acyclic(seed, tmp_path):
    """Seeded adversarial interleavings of every runtime entry point;
    the recorded acquisition-order graph must stay acyclic (the runtime
    cross-check of the §6d lock table rows 1-2) and no thread may see a
    torn scheduler state."""
    from spark_timeseries_tpu.utils import races

    reg = metrics.MetricsRegistry()
    models, hists = _tenant_fixtures(3, seed0=77)
    shards = [FleetScheduler(AdmissionPolicy(queue_depth=64),
                             registry=reg, auto_pump=False)
              for _ in range(2)]
    for i, (m, h) in enumerate(zip(models, hists)):
        shards[i % 2].attach(ss.ServingSession.start(
            m, h, label=f"t{i}", registry=reg))
    for sh in shards:
        sh.warmup()
    rng = np.random.default_rng(21)
    ticks = rng.normal(size=(3, S, 4))
    with races.instrument(seed=seed) as h:
        # built INSIDE the scope: the runtime's instance locks (and the
        # condition variable sharing the main one) come from the traced
        # factories
        rt = FleetRuntime(shards, registry=reg,
                          policy=RuntimePolicy(
                              checkpoint_dir=str(tmp_path / f"ck{seed}")))

        def producer():
            for t in range(4):
                for i in range(3):
                    # queues stay far below depth: a blocking wait would
                    # park outside the instrumented boundaries
                    rt.submit(f"t{i}", ticks[i, :, t], block=False)

        def pumper():
            for _ in range(6):
                rt.pump_once()

        def checkpointer():
            for _ in range(2):
                rt.checkpoint()

        def scraper():
            for _ in range(6):
                rt.pump_summary()
                for sh in rt.shards:
                    sh.telemetry_summary()
                telemetry.healthz_doc()

        def rebalancer():
            rt.rebalance()

        for fn, label in ((producer, "producer"), (pumper, "pumper"),
                          (checkpointer, "checkpointer"),
                          (scraper, "scraper"),
                          (rebalancer, "rebalancer")):
            h.spawn(fn, label=label)
        h.join_all()
        h.raise_errors()
        h.assert_acyclic()
    # drain the remainder: uneven queues park behind the coalesce
    # window (0.05 s), so sweep until empty, not until one idle sweep
    deadline = time.monotonic() + 30.0
    while any(t.queue for sh in rt.shards
              for t in sh._tenants.values()):
        assert time.monotonic() < deadline, "post-race drain wedged"
        rt.pump_once()
    total = sum(t.session.ticks_seen - N_HIST
                for sh in rt.shards for t in sh._tenants.values())
    assert total == 12, "ticks lost or double-dispatched under races"


# ---------------------------------------------------------------------------
# 0-recompile pin with runtime + quality + telemetry armed; surfaces
# ---------------------------------------------------------------------------

def test_warmed_runtime_zero_compiles_with_quality_and_telemetry():
    metrics.install_jax_hooks()
    reg = metrics.MetricsRegistry()
    models, hists = _tenant_fixtures(3, seed0=91)
    sched = FleetScheduler(registry=reg, auto_pump=False)
    for i, (m, h) in enumerate(zip(models, hists)):
        sched.attach(ss.ServingSession.start(
            m, h, label=f"t{i}", registry=reg,
            quality=ss.QualityPolicy()))
    rt = FleetRuntime(sched, registry=reg)
    srv = telemetry.start(port=0)
    try:
        rt.warmup()
        for i in range(3):
            rt.forecast(f"t{i}", 5)          # warm this horizon
        rng = np.random.default_rng(23)
        ticks = rng.normal(size=(3, S, 4))
        with rt:
            before = metrics.jax_stats()["jit_compiles"]
            for t in range(4):
                for i in range(3):
                    rt.submit(f"t{i}", ticks[i, :, t], block=True,
                              timeout=30.0)
            assert rt.quiesce(timeout=30.0)
            for i in range(3):
                rt.forecast(f"t{i}", 5)
            assert metrics.jax_stats()["jit_compiles"] - before == 0, \
                "compiles leaked into the runtime-armed warmed tick path"
            # the scrape surfaces carry the pump while traffic flows
            doc = telemetry.healthz_doc()
            mine = [r for r in doc["fleet_pumps"]
                    if r.get("runtime") == rt.label]
            assert mine and mine[0]["running"] and not mine[0]["stale"]
            assert doc["n_fleet_pumps"] >= 1
            snap = telemetry.snapshot_doc()
            panel = [f for f in snap["fleets"]
                     if f.get("label") == sched.label]
            assert panel and isinstance(panel[0].get("pump"), dict)
            assert panel[0]["pump"]["runtime"] == rt.label
            assert panel[0]["queue_depth"] == sched.policy.queue_depth
    finally:
        telemetry.stop()


def test_sts_top_renders_pump_line_and_degrades():
    from tools.sts_top import _fleet_pump_line, render_snapshot

    snap = {"pid": 1, "time_unix": time.time(), "fleets": [{
        "label": "fl0", "tenants": 1, "groups": 1, "queued": 3,
        "shed_tenants": 0, "p95_ms": 1.5, "slo_burns": 0, "slo_ms": None,
        "queue_depth": 8,
        "pump": {"runtime": "rtA", "running": True, "pumps": 42,
                 "restarts": 2, "heartbeat_age_s": 0.01,
                 "stale_after_s": 5.0, "stalled": False,
                 "backpressure_waiters": 1, "checkpoint_generation": 7,
                 "checkpoint_failures": 0, "last_checkpoint_unix": None,
                 "last_error": None},
        "tenant_rows": [{"tenant": "t0", "mode": 0, "n_series": 4,
                         "queued": 3, "admitted": 9, "rejected": 0,
                         "dropped": 0, "cache_serves": 0, "health": {}}],
    }]}
    frame = render_snapshot(json.loads(json.dumps(snap)))
    assert "pump rtA" in frame
    assert "restarts 2" in frame
    assert "ckpt-gen 7" in frame
    assert "3/8" in frame                    # backpressure fill / depth
    assert "STALLED" not in frame
    # stalled and stopped pumps flag loudly
    stalled = dict(snap["fleets"][0]["pump"], stalled=True)
    assert "[STALLED]" in _fleet_pump_line(stalled)
    stopped = dict(snap["fleets"][0]["pump"], running=False)
    assert "[STOPPED]" in _fleet_pump_line(stopped)
    assert "scrape error" in _fleet_pump_line({"error": "boom"})
    # version tolerance: pre-runtime exporters send no pump block and
    # no queue_depth — the panel renders, raw queue depth shown
    old = {"pid": 1, "fleets": [{"label": "fl0", "tenants": 1,
                                 "tenant_rows": [{"tenant": "t0",
                                                  "queued": 3}]}]}
    frame = render_snapshot(old)
    assert "fl0" in frame and "pump" not in frame
    assert " 3 " in frame or "3" in frame


def test_bench_gate_extracts_runtime_supervision_metrics():
    from tools.bench_gate import METRICS, extract_metrics

    names = [m[0] for m in METRICS]
    assert "fleet_pump_restarts" in names
    assert "fleet_checkpoint_failures" in names

    # fleet block present + key absent = measured 0 (registry counters
    # materialize on first increment)
    h = {"value": 1.0, "fleet_demo": {"fleet_ticks_per_s": 5000.0}}
    got = extract_metrics(h)
    assert got["fleet_pump_restarts"] == 0.0
    assert got["fleet_checkpoint_failures"] == 0.0

    h = {"value": 1.0, "fleet_demo": {
        "fleet_ticks_per_s": 5000.0, "pump_restarts": 2,
        "checkpoint_failures": 1}}
    got = extract_metrics(h)
    assert got["fleet_pump_restarts"] == 2.0
    assert got["fleet_checkpoint_failures"] == 1.0

    # pre-runtime rounds and errored demos fabricate nothing
    assert "fleet_pump_restarts" not in extract_metrics({"value": 1.0})
    assert "fleet_pump_restarts" not in extract_metrics(
        {"value": 1.0, "fleet_demo": {"error": "boom"}})
