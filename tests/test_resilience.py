"""Resilient batched fitting (ISSUE 2): health classification, multi-start
retry, fallback chains, fault injection, and checkpoint restore validation.

The acceptance contract: a panel containing all-NaN, constant, and
divergence-inducing series completes ``fit_resilient`` for every model
family without raising, returns explicit per-series ``FitOutcome``
statuses, matches the non-resilient path bit-for-bit on healthy series,
and emits ``resilience.*`` metrics.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_timeseries_tpu import models
from spark_timeseries_tpu.models import arima
from spark_timeseries_tpu.ops.optimize import minimize_least_squares
from spark_timeseries_tpu.panel import Panel
from spark_timeseries_tpu.time import DayFrequency, uniform
from spark_timeseries_tpu.utils import checkpoint, metrics
from spark_timeseries_tpu.utils import resilience as res

FAULT_ENV = os.environ.get("STS_FAULT_INJECT") == "1"


# ---------------------------------------------------------------------------
# health classification edge cases
# ---------------------------------------------------------------------------

def test_classify_empty_panel():
    out = np.asarray(res.classify_series(jnp.zeros((3, 0))))
    assert (out == res.HEALTH_TOO_SHORT).all()


def test_classify_edge_cases():
    n = 32
    rows = np.zeros((7, n))
    rows[0] = np.random.default_rng(0).standard_normal(n)    # healthy
    rows[1] = np.nan                                         # all-NaN
    rows[2] = 4.25                                           # constant
    rows[3, :] = np.nan
    rows[3, 5] = 1.0                                         # single point
    rows[4, 10] = np.inf                                     # has inf
    rows[5] = np.arange(n, dtype=float)
    rows[5, 15] = np.nan                                     # interior gap
    rows[6, :] = np.nan
    rows[6, :4] = [1.0, 2.0, 1.5, 0.5]                       # short window
    out = np.asarray(res.classify_series(jnp.asarray(rows), min_len=8))
    assert out.tolist() == [res.HEALTH_OK, res.HEALTH_ALL_NAN,
                            res.HEALTH_CONSTANT, res.HEALTH_TOO_SHORT,
                            res.HEALTH_HAS_INF, res.HEALTH_INTERIOR_GAP,
                            res.HEALTH_TOO_SHORT]
    skip = res.unfittable_mask(out)
    assert skip.tolist() == [False, True, False, True, True, True, True]


def test_classify_ragged_padding_is_ok():
    # leading/trailing NaN padding with a long contiguous window is the
    # ingestion shape the ragged fits accept — health OK, not a gap
    n = 40
    row = np.full(n, np.nan)
    row[5:35] = np.random.default_rng(1).standard_normal(30) + 3.0
    out = np.asarray(res.classify_series(jnp.asarray(row[None]), min_len=8))
    assert out.tolist() == [res.HEALTH_OK]


# ---------------------------------------------------------------------------
# fault injection + multi-start retry at the optimizer tier
# ---------------------------------------------------------------------------

def _toy_lsq(restarts=0):
    def rfn(x, t):
        return x[0] * t - 2.0 * t           # optimum at x = 2

    t = jnp.linspace(1.0, 2.0, 16)
    x0 = jnp.full((4, 1), 0.3)
    ts = jnp.broadcast_to(t, (4, 16))
    return minimize_least_squares(rfn, x0, ts, restarts=restarts)


def test_fault_forces_nonconvergence_without_retry():
    with res.fault_injection("force_nonconverge", n_attempts=1):
        r = _toy_lsq(restarts=0)
    assert not bool(np.any(np.asarray(r.converged)))
    assert np.asarray(r.attempts).tolist() == [1, 1, 1, 1]
    # parameters still carry the best-found point, not garbage
    np.testing.assert_allclose(np.asarray(r.x).ravel(), 2.0, atol=1e-5)


def test_retry_recovers_forced_divergence():
    with res.fault_injection("force_nonconverge", n_attempts=1):
        r = _toy_lsq(restarts=2)
    assert bool(np.all(np.asarray(r.converged)))
    assert np.asarray(r.attempts).tolist() == [2, 2, 2, 2]
    np.testing.assert_allclose(np.asarray(r.x).ravel(), 2.0, atol=1e-5)


def test_retry_noop_on_clean_solve():
    plain = _toy_lsq(restarts=0)
    retried = _toy_lsq(restarts=3)
    assert plain.attempts is None
    assert np.asarray(retried.attempts).tolist() == [1, 1, 1, 1]
    np.testing.assert_array_equal(np.asarray(plain.x),
                                  np.asarray(retried.x))


def test_fault_injection_validates_mode():
    with pytest.raises(ValueError):
        with res.fault_injection("explode"):
            pass


def test_arima_fit_retry_recovers_under_fault():
    key = jax.random.PRNGKey(3)
    m = arima.ARIMAModel(1, 0, 1, jnp.array([4.0, 0.45, 0.3]))
    panel = m.sample(120, key, shape=(3,))
    with res.fault_injection("force_nonconverge", n_attempts=1):
        fitted = arima.fit(1, 0, 1, panel, warn=False,
                           retry=res.RetryPolicy(max_restarts=2))
    d = fitted.diagnostics
    assert bool(np.all(np.asarray(d.converged)))
    assert np.asarray(d.attempts).tolist() == [2, 2, 2]
    # and the recovered optimum matches the un-faulted fit's
    clean = arima.fit(1, 0, 1, panel, warn=False)
    np.testing.assert_allclose(np.asarray(fitted.coefficients),
                               np.asarray(clean.coefficients),
                               rtol=1e-3, atol=1e-3)


# ---------------------------------------------------------------------------
# fallback chains: equivalence + the mixed acceptance panel
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _healthy_panel_cached(n_series, n):
    key = jax.random.PRNGKey(7)
    m = arima.ARIMAModel(1, 0, 1, jnp.array([4.0, 0.45, 0.3]))
    return np.asarray(m.sample(n, key, shape=(n_series,)))


def _healthy_panel(n_series=3, n=120):
    return _healthy_panel_cached(n_series, n).copy()


def _mixed_panel(n=120):
    healthy = _healthy_panel(3, n)
    bad = np.zeros((3, n))
    bad[0] = np.nan                                  # all-NaN
    bad[1] = 7.5                                     # constant
    bad[2] = np.cumsum(np.cumsum(                    # divergence-inducing
        np.exp(0.08 * np.arange(n))))
    return np.concatenate([healthy, bad])


@pytest.mark.skipif(FAULT_ENV, reason="fault injection forces the retry "
                    "path, so bit-for-bit equivalence cannot hold")
def test_fallback_chain_equivalence_on_clean_panel():
    panel = jnp.asarray(_healthy_panel())
    plain = arima.fit(1, 0, 1, panel, warn=False)
    model, outcome = arima.fit_resilient(panel, 1, 0, 1)
    np.testing.assert_array_equal(np.asarray(model.coefficients),
                                  np.asarray(plain.coefficients))
    assert outcome.counts() == {"ok": panel.shape[0]}
    assert (outcome.fallback_used == -1).all()


def test_mixed_panel_statuses_and_healthy_lane_equivalence():
    mixed = _mixed_panel()
    model, outcome = arima.fit_resilient(jnp.asarray(mixed), 1, 0, 1)
    # explicit per-series statuses: healthy lanes attempted, all-NaN lane
    # skipped, constant + divergent lanes recovered by some stage
    assert outcome.status[3] == res.STATUS_SKIPPED
    assert outcome.health[3] == res.HEALTH_ALL_NAN
    assert outcome.health[4] == res.HEALTH_CONSTANT
    assert set(outcome.status[[4, 5]]) <= {res.STATUS_OK, res.STATUS_RETRIED,
                                           res.STATUS_FALLBACK,
                                           res.STATUS_ABANDONED}
    assert np.isnan(np.asarray(model.coefficients)[3]).all()
    assert not bool(np.asarray(model.diagnostics.converged)[3])
    if not FAULT_ENV:
        # healthy lanes match the non-resilient path bit-for-bit
        plain = arima.fit(1, 0, 1, jnp.asarray(mixed[:3]), warn=False)
        np.testing.assert_array_equal(
            np.asarray(model.coefficients)[:3],
            np.asarray(plain.coefficients))


ALL_FAMILIES = ["arima", "arimax", "ar", "arx", "ewma", "garch", "argarch",
                "egarch", "holt_winters", "regression_arima"]


@pytest.mark.parametrize("family", ALL_FAMILIES)
def test_every_family_completes_on_mixed_panel(family):
    mixed = jnp.asarray(_mixed_panel(96))
    n_obs = mixed.shape[1]
    rng = np.random.default_rng(5)
    xreg = jnp.asarray(rng.standard_normal((n_obs, 2)))
    args = {
        "arima": (1, 0, 1), "arimax": (xreg, 1, 0, 1, 1), "ar": (2,),
        "arx": (xreg, 1, 1), "ewma": (), "garch": (), "argarch": (),
        "egarch": (), "holt_winters": (4,), "regression_arima": (xreg,),
    }[family]
    index = uniform("2020-01-01T00:00Z", n_obs, DayFrequency(1))
    panel = Panel(index, mixed, [f"s{i}" for i in range(mixed.shape[0])])
    model, outcome = panel.fit_resilient(family, *args)
    # completes without raising, with explicit per-series statuses
    assert outcome.status.shape == (6,)
    assert outcome.status[3] == res.STATUS_SKIPPED      # all-NaN lane
    assert np.all(outcome.status[:3] != res.STATUS_SKIPPED)
    conv = np.asarray(model.diagnostics.converged)
    assert not conv[3]
    ok = np.isin(outcome.status,
                 (res.STATUS_OK, res.STATUS_RETRIED, res.STATUS_FALLBACK))
    np.testing.assert_array_equal(conv, ok)
    # outcome params view is NaN exactly on the skipped lane
    if outcome.params is not None:
        assert np.isnan(outcome.params[3]).all()


def test_resilience_metrics_recorded():
    reg = metrics.get_registry()
    before = reg.snapshot()["counters"].get("resilience.series", 0)
    arima.fit_resilient(jnp.asarray(_mixed_panel(96)), 1, 0, 1)
    snap = reg.snapshot()
    c = snap["counters"]
    assert c["resilience.series"] == before + 6
    assert c["resilience.arima.skipped"] >= 1
    assert "resilience.arima.frac_abandoned" in snap["gauges"]
    assert any("resilience.fit.arima" in k for k in snap["spans"])


def test_corrupt_nan_fault_skips_lanes():
    panel = jnp.asarray(_healthy_panel(4, 96))
    with res.fault_injection("corrupt_nan", lane_stride=2):
        model, outcome = arima.fit_resilient(panel, 1, 0, 1)
    assert outcome.status[0] == res.STATUS_SKIPPED
    assert outcome.status[2] == res.STATUS_SKIPPED
    assert outcome.health[0] == res.HEALTH_ALL_NAN
    assert np.all(outcome.status[[1, 3]] != res.STATUS_SKIPPED)


def test_corrupt_inf_fault_flags_lanes():
    panel = jnp.asarray(_healthy_panel(4, 96))
    with res.fault_injection("corrupt_inf", lane_stride=2):
        _, outcome = arima.fit_resilient(panel, 1, 0, 1)
    assert outcome.health[0] == res.HEALTH_HAS_INF
    assert outcome.status[0] == res.STATUS_SKIPPED


def test_retry_policy_defaults_and_kwargs():
    rk = res.retry_kwargs(None)
    assert rk == {}
    rk = res.retry_kwargs(res.RetryPolicy(max_restarts=3, perturb_scale=0.5,
                                          seed=11))
    assert rk["restarts"] == 3 and rk["restart_scale"] == 0.5
    assert "restart_key" in rk


# ---------------------------------------------------------------------------
# checkpoint restore validation (ISSUE 2 satellite)
# ---------------------------------------------------------------------------

def test_checkpoint_shape_mismatch_raises_clearly(tmp_path):
    path = str(tmp_path / "ck")
    model = arima.fit(1, 0, 1, jnp.asarray(_healthy_panel(2, 96)),
                      warn=False)
    checkpoint.save_model(path, model)
    # corrupt: overwrite the npz with truncated leaves (wrong shapes)
    with np.load(path + ".npz") as data:
        leaves = {k: data[k] for k in data.files}
    first = next(k for k in leaves if leaves[k].ndim >= 1
                 and leaves[k].size > 1)
    leaves[first] = leaves[first].reshape(-1)[:-1]
    np.savez(path + ".npz", **leaves)
    with pytest.raises(checkpoint.CheckpointMismatchError,
                       match="shape"):
        checkpoint.load_model(path)


def test_checkpoint_leaf_count_mismatch_raises(tmp_path):
    path = str(tmp_path / "ck2")
    checkpoint.save_pytree(path, {"a": np.arange(3.0), "b": np.arange(2.0)})
    with np.load(path + ".npz") as data:
        leaves = {k: data[k] for k in data.files}
    leaves.pop("leaf_1")
    np.savez(path + ".npz", **leaves)
    with pytest.raises(checkpoint.CheckpointMismatchError):
        checkpoint.load_pytree(path)


def test_checkpoint_dtype_mismatch_raises(tmp_path):
    path = str(tmp_path / "ck3")
    checkpoint.save_pytree(path, [np.arange(4.0)])
    with np.load(path + ".npz") as data:
        leaves = {k: data[k] for k in data.files}
    leaves["leaf_0"] = leaves["leaf_0"].astype(np.float32)
    np.savez(path + ".npz", **leaves)
    with pytest.raises(checkpoint.CheckpointMismatchError, match="dtype"):
        checkpoint.load_pytree(path)


def test_checkpoint_roundtrip_still_works(tmp_path):
    path = str(tmp_path / "ck4")
    model = arima.fit(1, 0, 1, jnp.asarray(_healthy_panel(2, 96)),
                      warn=False)
    checkpoint.save_model(path, model)
    back = checkpoint.load_model(path, arima.ARIMAModel)
    np.testing.assert_array_equal(np.asarray(back.coefficients),
                                  np.asarray(model.coefficients))


# ---------------------------------------------------------------------------
# resilient model round trip: the merged model still forecasts
# ---------------------------------------------------------------------------

def test_resilient_model_is_usable_downstream():
    mixed = jnp.asarray(_mixed_panel(120))
    model, outcome = arima.fit_resilient(mixed, 1, 0, 1)
    # forecasting the whole panel works; the skipped lane's forecast is NaN
    fc = np.asarray(model.forecast(jnp.nan_to_num(mixed), 5))
    assert fc.shape == (6, 125)
    ok = np.isin(outcome.status,
                 (res.STATUS_OK, res.STATUS_RETRIED, res.STATUS_FALLBACK))
    assert np.isfinite(fc[ok][:, -5:]).all()
