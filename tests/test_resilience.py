"""Resilient batched fitting (ISSUE 2): health classification, multi-start
retry, fallback chains, fault injection, and checkpoint restore validation.

The acceptance contract: a panel containing all-NaN, constant, and
divergence-inducing series completes ``fit_resilient`` for every model
family without raising, returns explicit per-series ``FitOutcome``
statuses, matches the non-resilient path bit-for-bit on healthy series,
and emits ``resilience.*`` metrics.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_timeseries_tpu import models
from spark_timeseries_tpu.models import arima
from spark_timeseries_tpu.ops.optimize import minimize_least_squares
from spark_timeseries_tpu.panel import Panel
from spark_timeseries_tpu.time import DayFrequency, uniform
from spark_timeseries_tpu.utils import checkpoint, metrics
from spark_timeseries_tpu.utils import resilience as res

FAULT_ENV = os.environ.get("STS_FAULT_INJECT") == "1"


# ---------------------------------------------------------------------------
# health classification edge cases
# ---------------------------------------------------------------------------

def test_classify_empty_panel():
    out = np.asarray(res.classify_series(jnp.zeros((3, 0))))
    assert (out == res.HEALTH_TOO_SHORT).all()


def test_classify_edge_cases():
    n = 32
    rows = np.zeros((7, n))
    rows[0] = np.random.default_rng(0).standard_normal(n)    # healthy
    rows[1] = np.nan                                         # all-NaN
    rows[2] = 4.25                                           # constant
    rows[3, :] = np.nan
    rows[3, 5] = 1.0                                         # single point
    rows[4, 10] = np.inf                                     # has inf
    rows[5] = np.arange(n, dtype=float)
    rows[5, 15] = np.nan                                     # interior gap
    rows[6, :] = np.nan
    rows[6, :4] = [1.0, 2.0, 1.5, 0.5]                       # short window
    out = np.asarray(res.classify_series(jnp.asarray(rows), min_len=8))
    assert out.tolist() == [res.HEALTH_OK, res.HEALTH_ALL_NAN,
                            res.HEALTH_CONSTANT, res.HEALTH_TOO_SHORT,
                            res.HEALTH_HAS_INF, res.HEALTH_INTERIOR_GAP,
                            res.HEALTH_TOO_SHORT]
    skip = res.unfittable_mask(out)
    assert skip.tolist() == [False, True, False, True, True, True, True]


def test_classify_ragged_padding_is_ok():
    # leading/trailing NaN padding with a long contiguous window is the
    # ingestion shape the ragged fits accept — health OK, not a gap
    n = 40
    row = np.full(n, np.nan)
    row[5:35] = np.random.default_rng(1).standard_normal(30) + 3.0
    out = np.asarray(res.classify_series(jnp.asarray(row[None]), min_len=8))
    assert out.tolist() == [res.HEALTH_OK]


# ---------------------------------------------------------------------------
# fault injection + multi-start retry at the optimizer tier
# ---------------------------------------------------------------------------

def _toy_lsq(restarts=0):
    def rfn(x, t):
        return x[0] * t - 2.0 * t           # optimum at x = 2

    t = jnp.linspace(1.0, 2.0, 16)
    x0 = jnp.full((4, 1), 0.3)
    ts = jnp.broadcast_to(t, (4, 16))
    return minimize_least_squares(rfn, x0, ts, restarts=restarts)


def test_fault_forces_nonconvergence_without_retry():
    with res.fault_injection("force_nonconverge", n_attempts=1):
        r = _toy_lsq(restarts=0)
    assert not bool(np.any(np.asarray(r.converged)))
    assert np.asarray(r.attempts).tolist() == [1, 1, 1, 1]
    # parameters still carry the best-found point, not garbage
    np.testing.assert_allclose(np.asarray(r.x).ravel(), 2.0, atol=1e-5)


def test_retry_recovers_forced_divergence():
    with res.fault_injection("force_nonconverge", n_attempts=1):
        r = _toy_lsq(restarts=2)
    assert bool(np.all(np.asarray(r.converged)))
    assert np.asarray(r.attempts).tolist() == [2, 2, 2, 2]
    np.testing.assert_allclose(np.asarray(r.x).ravel(), 2.0, atol=1e-5)


def test_retry_noop_on_clean_solve():
    plain = _toy_lsq(restarts=0)
    retried = _toy_lsq(restarts=3)
    assert plain.attempts is None
    assert np.asarray(retried.attempts).tolist() == [1, 1, 1, 1]
    np.testing.assert_array_equal(np.asarray(plain.x),
                                  np.asarray(retried.x))


def test_fault_injection_validates_mode():
    with pytest.raises(ValueError):
        with res.fault_injection("explode"):
            pass


def test_arima_fit_retry_recovers_under_fault():
    key = jax.random.PRNGKey(3)
    m = arima.ARIMAModel(1, 0, 1, jnp.array([4.0, 0.45, 0.3]))
    panel = m.sample(120, key, shape=(3,))
    with res.fault_injection("force_nonconverge", n_attempts=1):
        fitted = arima.fit(1, 0, 1, panel, warn=False,
                           retry=res.RetryPolicy(max_restarts=2))
    d = fitted.diagnostics
    assert bool(np.all(np.asarray(d.converged)))
    assert np.asarray(d.attempts).tolist() == [2, 2, 2]
    # and the recovered optimum matches the un-faulted fit's
    clean = arima.fit(1, 0, 1, panel, warn=False)
    np.testing.assert_allclose(np.asarray(fitted.coefficients),
                               np.asarray(clean.coefficients),
                               rtol=1e-3, atol=1e-3)


# ---------------------------------------------------------------------------
# fallback chains: equivalence + the mixed acceptance panel
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _healthy_panel_cached(n_series, n):
    key = jax.random.PRNGKey(7)
    m = arima.ARIMAModel(1, 0, 1, jnp.array([4.0, 0.45, 0.3]))
    return np.asarray(m.sample(n, key, shape=(n_series,)))


def _healthy_panel(n_series=3, n=120):
    return _healthy_panel_cached(n_series, n).copy()


def _mixed_panel(n=120):
    healthy = _healthy_panel(3, n)
    bad = np.zeros((3, n))
    bad[0] = np.nan                                  # all-NaN
    bad[1] = 7.5                                     # constant
    bad[2] = np.cumsum(np.cumsum(                    # divergence-inducing
        np.exp(0.08 * np.arange(n))))
    return np.concatenate([healthy, bad])


@pytest.mark.skipif(FAULT_ENV, reason="fault injection forces the retry "
                    "path, so bit-for-bit equivalence cannot hold")
def test_fallback_chain_equivalence_on_clean_panel():
    panel = jnp.asarray(_healthy_panel())
    plain = arima.fit(1, 0, 1, panel, warn=False)
    model, outcome = arima.fit_resilient(panel, 1, 0, 1)
    np.testing.assert_array_equal(np.asarray(model.coefficients),
                                  np.asarray(plain.coefficients))
    assert outcome.counts() == {"ok": panel.shape[0]}
    assert (outcome.fallback_used == -1).all()


def test_mixed_panel_statuses_and_healthy_lane_equivalence():
    mixed = _mixed_panel()
    model, outcome = arima.fit_resilient(jnp.asarray(mixed), 1, 0, 1)
    # explicit per-series statuses: healthy lanes attempted, all-NaN lane
    # skipped, constant + divergent lanes recovered by some stage
    assert outcome.status[3] == res.STATUS_SKIPPED
    assert outcome.health[3] == res.HEALTH_ALL_NAN
    assert outcome.health[4] == res.HEALTH_CONSTANT
    assert set(outcome.status[[4, 5]]) <= {res.STATUS_OK, res.STATUS_RETRIED,
                                           res.STATUS_FALLBACK,
                                           res.STATUS_ABANDONED}
    assert np.isnan(np.asarray(model.coefficients)[3]).all()
    assert not bool(np.asarray(model.diagnostics.converged)[3])
    if not FAULT_ENV:
        # healthy lanes match the non-resilient path bit-for-bit
        plain = arima.fit(1, 0, 1, jnp.asarray(mixed[:3]), warn=False)
        np.testing.assert_array_equal(
            np.asarray(model.coefficients)[:3],
            np.asarray(plain.coefficients))


ALL_FAMILIES = ["arima", "arimax", "ar", "arx", "ewma", "garch", "argarch",
                "egarch", "holt_winters", "regression_arima"]


@pytest.mark.parametrize("family", ALL_FAMILIES)
def test_every_family_completes_on_mixed_panel(family):
    mixed = jnp.asarray(_mixed_panel(96))
    n_obs = mixed.shape[1]
    rng = np.random.default_rng(5)
    xreg = jnp.asarray(rng.standard_normal((n_obs, 2)))
    args = {
        "arima": (1, 0, 1), "arimax": (xreg, 1, 0, 1, 1), "ar": (2,),
        "arx": (xreg, 1, 1), "ewma": (), "garch": (), "argarch": (),
        "egarch": (), "holt_winters": (4,), "regression_arima": (xreg,),
    }[family]
    index = uniform("2020-01-01T00:00Z", n_obs, DayFrequency(1))
    panel = Panel(index, mixed, [f"s{i}" for i in range(mixed.shape[0])])
    model, outcome = panel.fit_resilient(family, *args)
    # completes without raising, with explicit per-series statuses
    assert outcome.status.shape == (6,)
    assert outcome.status[3] == res.STATUS_SKIPPED      # all-NaN lane
    assert np.all(outcome.status[:3] != res.STATUS_SKIPPED)
    conv = np.asarray(model.diagnostics.converged)
    assert not conv[3]
    ok = np.isin(outcome.status,
                 (res.STATUS_OK, res.STATUS_RETRIED, res.STATUS_FALLBACK))
    np.testing.assert_array_equal(conv, ok)
    # outcome params view is NaN exactly on the skipped lane
    if outcome.params is not None:
        assert np.isnan(outcome.params[3]).all()


def test_resilience_metrics_recorded():
    reg = metrics.get_registry()
    before = reg.snapshot()["counters"].get("resilience.series", 0)
    arima.fit_resilient(jnp.asarray(_mixed_panel(96)), 1, 0, 1)
    snap = reg.snapshot()
    c = snap["counters"]
    assert c["resilience.series"] == before + 6
    assert c["resilience.arima.skipped"] >= 1
    assert "resilience.arima.frac_abandoned" in snap["gauges"]
    assert any("resilience.fit.arima" in k for k in snap["spans"])


def test_corrupt_nan_fault_skips_lanes():
    panel = jnp.asarray(_healthy_panel(4, 96))
    with res.fault_injection("corrupt_nan", lane_stride=2):
        model, outcome = arima.fit_resilient(panel, 1, 0, 1)
    assert outcome.status[0] == res.STATUS_SKIPPED
    assert outcome.status[2] == res.STATUS_SKIPPED
    assert outcome.health[0] == res.HEALTH_ALL_NAN
    assert np.all(outcome.status[[1, 3]] != res.STATUS_SKIPPED)


def test_corrupt_inf_fault_flags_lanes():
    panel = jnp.asarray(_healthy_panel(4, 96))
    with res.fault_injection("corrupt_inf", lane_stride=2):
        _, outcome = arima.fit_resilient(panel, 1, 0, 1)
    assert outcome.health[0] == res.HEALTH_HAS_INF
    assert outcome.status[0] == res.STATUS_SKIPPED


def test_retry_policy_defaults_and_kwargs():
    rk = res.retry_kwargs(None)
    assert rk == {}
    rk = res.retry_kwargs(res.RetryPolicy(max_restarts=3, perturb_scale=0.5,
                                          seed=11))
    assert rk["restarts"] == 3 and rk["restart_scale"] == 0.5
    assert "restart_key" in rk


# ---------------------------------------------------------------------------
# checkpoint restore validation (ISSUE 2 satellite)
# ---------------------------------------------------------------------------

def test_checkpoint_shape_mismatch_raises_clearly(tmp_path):
    path = str(tmp_path / "ck")
    model = arima.fit(1, 0, 1, jnp.asarray(_healthy_panel(2, 96)),
                      warn=False)
    checkpoint.save_model(path, model)
    # corrupt: overwrite the npz with truncated leaves (wrong shapes)
    with np.load(path + ".npz") as data:
        leaves = {k: data[k] for k in data.files}
    first = next(k for k in leaves if leaves[k].ndim >= 1
                 and leaves[k].size > 1)
    leaves[first] = leaves[first].reshape(-1)[:-1]
    np.savez(path + ".npz", **leaves)
    with pytest.raises(checkpoint.CheckpointMismatchError,
                       match="shape"):
        checkpoint.load_model(path)


def test_checkpoint_leaf_count_mismatch_raises(tmp_path):
    path = str(tmp_path / "ck2")
    checkpoint.save_pytree(path, {"a": np.arange(3.0), "b": np.arange(2.0)})
    with np.load(path + ".npz") as data:
        leaves = {k: data[k] for k in data.files}
    leaves.pop("leaf_1")
    np.savez(path + ".npz", **leaves)
    with pytest.raises(checkpoint.CheckpointMismatchError):
        checkpoint.load_pytree(path)


def test_checkpoint_dtype_mismatch_raises(tmp_path):
    path = str(tmp_path / "ck3")
    checkpoint.save_pytree(path, [np.arange(4.0)])
    with np.load(path + ".npz") as data:
        leaves = {k: data[k] for k in data.files}
    leaves["leaf_0"] = leaves["leaf_0"].astype(np.float32)
    np.savez(path + ".npz", **leaves)
    with pytest.raises(checkpoint.CheckpointMismatchError, match="dtype"):
        checkpoint.load_pytree(path)


def test_checkpoint_roundtrip_still_works(tmp_path):
    path = str(tmp_path / "ck4")
    model = arima.fit(1, 0, 1, jnp.asarray(_healthy_panel(2, 96)),
                      warn=False)
    checkpoint.save_model(path, model)
    back = checkpoint.load_model(path, arima.ARIMAModel)
    np.testing.assert_array_equal(np.asarray(back.coefficients),
                                  np.asarray(model.coefficients))


# ---------------------------------------------------------------------------
# resilient model round trip: the merged model still forecasts
# ---------------------------------------------------------------------------

def test_resilient_model_is_usable_downstream():
    mixed = jnp.asarray(_mixed_panel(120))
    model, outcome = arima.fit_resilient(mixed, 1, 0, 1)
    # forecasting the whole panel works; the skipped lane's forecast is NaN
    fc = np.asarray(model.forecast(jnp.nan_to_num(mixed), 5))
    assert fc.shape == (6, 125)
    ok = np.isin(outcome.status,
                 (res.STATUS_OK, res.STATUS_RETRIED, res.STATUS_FALLBACK))
    assert np.isfinite(fc[ok][:, -5:]).all()


# ---------------------------------------------------------------------------
# adaptive auto-order fallback (ISSUE 9, ROADMAP item 1 resilience wiring)
# ---------------------------------------------------------------------------

def _arma11_panel(S=10, n=256, seed=0):
    """ARMA(1,1) truth — fitted at (2, 0, 2) this is the classic
    common-factor-cancellation plateau shape."""
    rng = np.random.default_rng(seed)
    e = rng.standard_normal((S, n + 8)).astype(np.float32)
    y = np.zeros((S, n + 8), np.float32)
    for t in range(1, n + 8):
        y[:, t] = 0.4 + 0.6 * y[:, t - 1] + e[:, t] + 0.5 * e[:, t - 1]
    return y[:, 8:]


def _leaves(model):
    return [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(model)
            if hasattr(leaf, "dtype")]


@pytest.mark.slow
@pytest.mark.serving
@pytest.mark.parametrize("family", ALL_FAMILIES)
def test_default_chains_bitwise_unchanged_by_auto_order_machinery(family):
    """The bitwise-equivalence regression for every family: the default
    resilient chain is deterministic, and for arima an explicit
    ``auto_order=False`` is bit-for-bit the default call — the new
    suspect/StageResult/orders machinery must be invisible when off."""
    mixed = jnp.asarray(_mixed_panel(96))
    n_obs = mixed.shape[1]
    rng = np.random.default_rng(5)
    xreg = jnp.asarray(rng.standard_normal((n_obs, 2)))
    args = {
        "arima": (1, 0, 1), "arimax": (xreg, 1, 0, 1, 1), "ar": (2,),
        "arx": (xreg, 1, 1), "ewma": (), "garch": (), "argarch": (),
        "egarch": (), "holt_winters": (4,), "regression_arima": (xreg,),
    }[family]
    from spark_timeseries_tpu.engine import FitEngine
    fit_fn = FitEngine.resilient_dispatch(family)
    m1, o1 = fit_fn(mixed, *args)
    m2, o2 = fit_fn(mixed, *args)
    for a, b in zip(_leaves(m1), _leaves(m2)):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(o1.status, o2.status)
    np.testing.assert_array_equal(o1.fallback_used, o2.fallback_used)
    if family == "arima":
        m3, o3 = fit_fn(mixed, *args, auto_order=False)
        for a, b in zip(_leaves(m1), _leaves(m3)):
            np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(o1.status, o3.status)
        # orders are recorded either way for arima (total per-lane map)
        assert o3.orders is not None
        assert o3.orders.shape == (mixed.shape[0], 3)
    else:
        assert o1.orders is None


def test_cancellation_detector_flags_common_factors():
    from spark_timeseries_tpu.models.arima import _cancellation_suspects
    # lane 0: AR and MA roots coincide (phi = -theta): 1-0.9z | 1-0.9z
    # lane 1: well-separated roots; lane 2: NaN coefficients
    coefs = np.array([[0.1, 0.9, -0.9],
                      [0.1, 0.5, 0.5],
                      [np.nan, np.nan, np.nan]], np.float32)
    m = arima.ARIMAModel(1, 0, 1, jnp.asarray(coefs), True)
    got = _cancellation_suspects(m, tol=0.15)
    assert got.tolist() == [True, False, False]
    # pure AR / pure MA layouts can never cancel
    m_ar = arima.ARIMAModel(1, 0, 0, jnp.asarray(coefs[:, :2]), True)
    assert not _cancellation_suspects(m_ar).any()


@pytest.mark.slow
@pytest.mark.serving
def test_auto_order_rescues_forced_failures_with_searched_orders():
    panel = jnp.asarray(_arma11_panel())
    with res.fault_injection("force_nonconverge", n_attempts=10):
        model, outcome = arima.fit_resilient(
            panel, 2, 0, 2, auto_order=True,
            retry=res.RetryPolicy(max_restarts=0))
    # the auto stage (chain index 1) rescued every lane the primary
    # could not converge, at searched orders within (2, 2)
    rescued = outcome.fallback_used == 1
    assert rescued.any()
    assert (outcome.status[rescued] == res.STATUS_FALLBACK).all()
    assert outcome.orders is not None
    assert (outcome.orders[rescued, 0] <= 2).all()
    assert (outcome.orders[rescued, 2] <= 2).all()
    assert (outcome.orders[rescued, 1] == 0).all()
    conv = np.asarray(model.diagnostics.converged)
    np.testing.assert_array_equal(conv[rescued], True)


@pytest.mark.skipif(FAULT_ENV, reason="fault injection forces the retry "
                    "path; the plateau statuses differ under it")
@pytest.mark.slow
@pytest.mark.serving
def test_auto_order_reselects_plateaued_lanes_without_degrading_ok():
    """ARMA(1,1) truth fitted at (2,0,2): the cancellation detector
    flags plateaued lanes and the auto stage re-selects a strictly
    smaller order for at least some of them; lanes it does not rescue
    keep their converged primary result (never worsened)."""
    panel = jnp.asarray(_arma11_panel())
    base, o_base = arima.fit_resilient(panel, 2, 0, 2)
    model, outcome = arima.fit_resilient(panel, 2, 0, 2, auto_order=True)
    reselected = outcome.fallback_used == 1
    assert reselected.any(), "no lane was re-ordered on a plateau panel"
    sub = outcome.orders[reselected]
    assert ((sub[:, 0] + sub[:, 2]) < 4).all()     # strictly lower order
    untouched = ~reselected
    np.testing.assert_array_equal(
        np.asarray(model.coefficients)[untouched],
        np.asarray(base.coefficients)[untouched])
    # every non-skipped lane still converges
    ok = np.isin(outcome.status, (res.STATUS_OK, res.STATUS_RETRIED,
                                  res.STATUS_FALLBACK))
    assert ok.all()


def test_auto_order_validates_arguments():
    panel = jnp.asarray(_healthy_panel(3, 96))
    with pytest.raises(ValueError, match="include_intercept"):
        arima.fit_resilient(panel, 1, 0, 1, include_intercept=False,
                            auto_order=True)
    with pytest.raises(ValueError, match="p > 0 or q > 0"):
        arima.fit_resilient(panel, 0, 1, 0, auto_order=True)


@pytest.mark.slow
@pytest.mark.serving
def test_engine_bucketing_slices_orders():
    """A non-power-of-two panel through engine.fit_resilient: pad lanes
    sliced off the orders map too, real lanes keep a total map."""
    from spark_timeseries_tpu.engine import FitEngine
    panel = _arma11_panel(S=5)
    model, outcome = FitEngine().fit_resilient(jnp.asarray(panel),
                                               "arima", 2, 0, 2,
                                               auto_order=True)
    assert outcome.status.shape == (5,)
    assert outcome.orders.shape == (5, 3)
    assert (outcome.orders[:, 0] >= 0).all()


@pytest.mark.slow
@pytest.mark.serving
def test_stream_fit_resilient_path_with_auto_order(tmp_path):
    """stream_fit(resilient=True): chunks run the fallback chain under
    the durability scaffolding — statuses aggregate, journal resume is
    exact, and a different resilient spec refuses the journal."""
    from spark_timeseries_tpu.engine import FitEngine, JournalSpecMismatch
    panel = _arma11_panel(S=24)
    panel[5] = np.nan
    eng = FitEngine()
    jr = str(tmp_path / "jr")
    r1 = eng.stream_fit(panel, "arima", chunk_size=8, resilient=True,
                        p=2, d=0, q=2, auto_order=True, journal=jr)
    assert r1.stats["resilient"] is True
    agg = r1.stats["resilient_statuses"]
    assert agg.get("skipped") == 1
    assert r1.n_converged == sum(agg.get(k, 0) for k in
                                 ("ok", "retried", "fallback"))
    r2 = eng.stream_fit(panel, "arima", chunk_size=8, resilient=True,
                        p=2, d=0, q=2, auto_order=True, journal=jr)
    assert r2.stats["journal_hits"] == r1.n_chunks
    assert r2.n_converged == r1.n_converged
    assert r2.stats["resilient_statuses"] == agg
    with pytest.raises(JournalSpecMismatch):
        eng.stream_fit(panel, "arima", chunk_size=8, resilient=True,
                       p=1, d=0, q=1, journal=jr)


@pytest.mark.slow
@pytest.mark.serving
def test_auto_fallback_dead_counter_zero_baseline():
    """Lanes the auto stage saw but nothing rescued count into
    resilience.auto_fallback_dead; a fully-rescued run leaves the
    counter unmaterialized (the bench gate's zero-baseline)."""
    reg = metrics.get_registry()
    base_dead = reg.snapshot()["counters"].get(
        "resilience.auto_fallback_dead", 0)
    # clean rescue: no deaths recorded
    with res.fault_injection("force_nonconverge", n_attempts=10):
        arima.fit_resilient(jnp.asarray(_arma11_panel(S=6)), 2, 0, 2,
                            auto_order=True,
                            retry=res.RetryPolicy(max_restarts=0))
    snap = reg.snapshot()["counters"]
    assert snap.get("resilience.auto_fallback_dead", 0) == base_dead
    assert snap.get("resilience.auto_fallback", 0) > 0


def test_suspect_lanes_never_fall_past_the_auto_stage():
    """Contract pin (review finding): a converged-but-suspect lane the
    auto stage cannot rescue keeps its primary parameters and OK status
    — the simpler hardcoded fallbacks must never replace a converged
    model with an intercept-only one."""
    from spark_timeseries_tpu.models.base import FitDiagnostics

    n_series, n = 4, 64
    rng = np.random.default_rng(0)
    values = rng.standard_normal((n_series, n)).astype(np.float32)

    class FakeModel:
        pass

    def make_model(rows, conv, tag):
        import jax.numpy as jnp
        from typing import NamedTuple, Optional

        class M(NamedTuple):
            coefficients: jnp.ndarray
            diagnostics: Optional[FitDiagnostics] = None

        coefs = jnp.full((rows, 2), float(tag), jnp.float32)
        return M(coefs, FitDiagnostics(jnp.asarray(conv),
                                       jnp.zeros((rows,), jnp.int32),
                                       jnp.zeros((rows,), jnp.float32)))

    primary = lambda v: make_model(v.shape[0], np.ones(v.shape[0], bool), 1)
    auto_fails = lambda v: make_model(v.shape[0],
                                      np.zeros(v.shape[0], bool), 2)
    mean_takes_all = lambda v: make_model(v.shape[0],
                                          np.ones(v.shape[0], bool), 3)
    model, outcome = res.resilient_fit(
        values,
        [("primary", primary), ("auto_order", auto_fails),
         ("mean", mean_takes_all)],
        family="fake",
        suspect_fn=lambda m: np.array([False, True, False, True]))
    # every lane keeps the primary's parameters (tag 1), none fell to
    # the mean stage, statuses stay OK
    np.testing.assert_array_equal(np.asarray(model.coefficients),
                                  np.full((n_series, 2), 1.0))
    assert outcome.counts() == {"ok": n_series}
    assert (outcome.fallback_used == -1).all()
