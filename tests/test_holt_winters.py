"""Holt-Winters tests — contracts and R `stats::HoltWinters` oracle values
mirror the reference's ``HoltWintersModelSuite``
(ref /root/reference/src/test/scala/com/cloudera/sparkts/models/HoltWintersModelSuite.scala)."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_timeseries_tpu.models import holt_winters as hw
from r_datasets import AIR_PASSENGERS, CO2

R_ADDITIVE_FORECAST = np.array([
    453.4977, 429.3906, 467.0361, 503.2574, 512.3395, 571.8880,
    652.6095, 637.4623, 539.7548, 490.7250, 424.4593, 469.5315])

R_MULT_FORECAST = np.array([
    365.1079, 365.9664, 366.7343, 368.1364, 368.6674, 367.9508,
    366.5318, 364.3799, 362.4731, 362.7520, 364.2203, 365.6741])


def test_additive_optimal_parameters():
    # ref HoltWintersModelSuite.scala:44-52: R gives (0.24796, 0.03453, 1.0)
    model = hw.fit(jnp.asarray(AIR_PASSENGERS), 12, "additive")
    assert abs(float(model.alpha) - 0.24796) < 0.01
    assert abs(float(model.beta) - 0.03453) < 0.01
    assert abs(float(model.gamma) - 1.0) < 0.01


def test_additive_forecast():
    # ref HoltWintersModelSuite.scala:54-77 (tolerance ±10 vs R forecast)
    model = hw.fit(jnp.asarray(AIR_PASSENGERS), 12, "additive")
    fc = np.asarray(model.forecast(jnp.asarray(AIR_PASSENGERS), 12))
    np.testing.assert_allclose(fc, R_ADDITIVE_FORECAST, atol=10)


def test_multiplicative_optimal_parameters():
    # ref HoltWintersModelSuite.scala:139-146: R gives (0.51265, 0.00949, 0.47289)
    model = hw.fit(jnp.asarray(CO2), 12, "multiplicative")
    assert abs(float(model.alpha) - 0.51265) < 0.01
    assert abs(float(model.beta) - 0.00949) < 0.01
    assert abs(float(model.gamma) - 0.47289) < 0.1


def test_multiplicative_forecast():
    # ref HoltWintersModelSuite.scala:148-170 (tolerance ±10 vs R forecast)
    model = hw.fit(jnp.asarray(CO2), 12, "multiplicative")
    fc = np.asarray(model.forecast(jnp.asarray(CO2), 12))
    np.testing.assert_allclose(fc, R_MULT_FORECAST, atol=10)


def test_invalid_model_type():
    with pytest.raises(ValueError):
        hw.HoltWintersModel("banana", 12, 0.3, 0.1, 0.1).additive


def test_remove_effects_unsupported():
    m = hw.HoltWintersModel("additive", 12, 0.3, 0.1, 0.1)
    with pytest.raises(NotImplementedError):
        m.remove_time_dependent_effects(jnp.zeros(24))


def test_sse_positive_and_fitted_shape():
    m = hw.HoltWintersModel("additive", 12,
                            jnp.asarray(0.3), jnp.asarray(0.1),
                            jnp.asarray(0.1))
    fitted = m.add_time_dependent_effects(jnp.asarray(AIR_PASSENGERS))
    assert fitted.shape == AIR_PASSENGERS.shape
    # first `period` entries are zero (no prediction available there)
    np.testing.assert_array_equal(np.asarray(fitted[:12]), 0.0)
    assert float(m.sse(jnp.asarray(AIR_PASSENGERS))) > 0


def test_batched_panel_fit_matches_single():
    panel = jnp.stack([jnp.asarray(AIR_PASSENGERS),
                       jnp.asarray(AIR_PASSENGERS) * 1.7 + 3.0])
    fitted = hw.fit(panel, 12, "additive")
    assert fitted.alpha.shape == (2,)
    single = hw.fit(jnp.asarray(AIR_PASSENGERS), 12, "additive")
    np.testing.assert_allclose(float(fitted.alpha[0]), float(single.alpha),
                               atol=1e-6)
    np.testing.assert_allclose(float(fitted.beta[0]), float(single.beta),
                               atol=1e-6)
    fc = fitted.forecast(panel, 6)
    assert fc.shape == (2, 6)


def test_forecast_interval_additive_formula():
    """Bands match the class-1 state-space variance formula exactly and
    the seasonal c_j bump appears at j = period."""
    a, b, g, period = 0.4, 0.2, 0.3, 4
    m = hw.HoltWintersModel("additive", period, jnp.asarray(a),
                                      jnp.asarray(b), jnp.asarray(g))
    t = np.arange(40, dtype=np.float64)
    y = jnp.asarray(10 + 0.5 * t + 3 * np.sin(2 * np.pi * t / period)
                    + np.random.default_rng(0).normal(scale=0.5, size=40))
    h = 9
    point, lo, hi = m.forecast_interval(y, h)
    assert point.shape == lo.shape == hi.shape == (h,)

    fitted = np.asarray(m.add_time_dependent_effects(y))
    err = np.asarray(y)[period:] - fitted[period:]
    sigma2 = np.mean(err * err)
    # seasonal coefficient is γ(1-α): the R-style recurrence updates the
    # season ring by γ(1-α)e per one-step error (ETS map γ_ets = γ(1-α))
    cj = np.array([a * (1 + j * b) + (g * (1 - a) if j % period == 0
                                      else 0.0)
                   for j in range(1, h)])
    var = sigma2 * np.r_[1.0, 1.0 + np.cumsum(cj * cj)]
    half = 1.959964 * np.sqrt(var)
    np.testing.assert_allclose(np.asarray(hi - lo) / 2, half, rtol=1e-5)
    # widths strictly widen and jump extra at the seasonal lag
    w = np.asarray(hi - lo)
    assert (np.diff(w) > 0).all()


def _simulate_forward(model_type, a, b_r, g, l0, b0, seas0, sigma, h,
                      n_paths, seed=0):
    """Monte-Carlo the components recurrence forward from given states with
    Gaussian one-step noise; returns per-horizon variance of the paths."""
    rng = np.random.default_rng(seed)
    level = np.full(n_paths, l0)
    trend = np.full(n_paths, b0)
    ring = np.tile(seas0, (n_paths, 1)).astype(float)
    out = np.empty((n_paths, h))
    for i in range(h):
        s = ring[:, 0]
        base = level + trend
        yhat = base + s if model_type == "additive" else base * s
        y = yhat + rng.normal(scale=sigma, size=n_paths)
        out[:, i] = y
        lw = (y - s) if model_type == "additive" else (y / s)
        nl = a * lw + (1 - a) * base
        nt = b_r * (nl - level) + (1 - b_r) * trend
        sw = (y - nl) if model_type == "additive" else (y / nl)
        ring = np.concatenate([ring[:, 1:], (g * sw + (1 - g) * s)[:, None]],
                              axis=1)
        level, trend = nl, nt
    return out.var(axis=0)


@pytest.mark.parametrize("model_type", ["additive", "multiplicative"])
def test_forecast_interval_matches_simulation(model_type):
    """Band variance matches a seeded Monte-Carlo of the recurrence itself
    (the ground truth the linearization approximates) at every horizon."""
    a, b_r, g, period, h = 0.4, 0.2, 0.3, 4, 12
    m = hw.HoltWintersModel(model_type, period, jnp.asarray(a),
                            jnp.asarray(b_r), jnp.asarray(g))
    t = np.arange(48, dtype=np.float64)
    if model_type == "additive":
        y = 50 + 0.5 * t + 3 * np.sin(2 * np.pi * t / period)
    else:
        y = (50 + 0.5 * t) * (1 + 0.06 * np.sin(2 * np.pi * t / period))
    y = jnp.asarray(y + np.random.default_rng(3).normal(scale=1.0, size=48))

    point, lo, hi = m.forecast_interval(y, h)
    var_formula = (np.asarray(hi - lo) / (2 * 1.959964)) ** 2

    fitted, level, trend, seasons = m.get_holt_winters_components(y)
    err = np.asarray(y)[period:] - np.asarray(fitted)[period:]
    sigma = float(np.sqrt(np.mean(err * err)))
    var_sim = _simulate_forward(
        model_type, a, b_r, g, float(level), float(trend),
        np.asarray(seasons), sigma, h, n_paths=200_000)
    np.testing.assert_allclose(var_formula, var_sim, rtol=0.03)


def test_forecast_interval_batched_lanes():
    period = 6
    rng = np.random.default_rng(1)
    t = np.arange(60.)
    panel = jnp.asarray(50 + 0.3 * t + 5 * np.sin(2 * np.pi * t / period)
                        + rng.normal(scale=1.0, size=(3, 60)))
    m = hw.fit(panel, period, "additive", max_iter=200)
    point, lo, hi = m.forecast_interval(panel, 7)
    assert point.shape == (3, 7)
    w = np.asarray(hi - lo)
    assert np.isfinite(w).all() and (w > 0).all()
    # per-lane isolation: lane 0 alone gives identical bands
    m0 = hw.HoltWintersModel(
        "additive", period, m.alpha[0], m.beta[0], m.gamma[0])
    _, lo0, hi0 = m0.forecast_interval(panel[0], 7)
    np.testing.assert_allclose(np.asarray(hi[0] - lo[0]),
                               np.asarray(hi0 - lo0), rtol=1e-6)


def test_forecast_interval_mixed_batch_shapes():
    # scalar model over a panel, and per-lane model on one series — both
    # supported by forecast(); bands must broadcast the same way
    m = hw.HoltWintersModel("additive", 4, jnp.asarray(0.4),
                            jnp.asarray(0.2), jnp.asarray(0.3))
    panel = jnp.asarray(np.random.default_rng(0).normal(size=(2, 40)) + 50)
    pt, lo, hi = m.forecast_interval(panel, 5)
    assert pt.shape == lo.shape == hi.shape == (2, 5)
    mb = hw.HoltWintersModel("additive", 4, jnp.asarray([0.4, 0.3]),
                             jnp.asarray([0.2, 0.1]),
                             jnp.asarray([0.3, 0.2]))
    pt2, lo2, hi2 = mb.forecast_interval(panel, 5)
    assert pt2.shape == (2, 5)
    assert bool(jnp.all(jnp.isfinite(hi2 - lo2)))


def test_fused_value_and_grad_matches_autodiff():
    # the fused forward tangent pass used by fit() must agree with
    # reverse-mode autodiff through the components recurrence at f64
    # rounding, for both model types and across the [0,1]^3 box
    import jax

    rng = np.random.default_rng(7)
    t = np.arange(96)
    add_series = jnp.asarray(
        80 + 0.4 * t + 8 * np.sin(2 * np.pi * t / 12)
        + rng.normal(size=96))
    mult_series = jnp.asarray(
        (80 + 0.4 * t) * (1 + 0.12 * np.sin(2 * np.pi * t / 12))
        + rng.normal(size=96) * 0.5)
    for mt, s in (("additive", add_series),
                  ("multiplicative", mult_series)):
        def obj(p):
            return hw.HoltWintersModel(
                mt, 12, p[0], p[1], p[2]).sse(s)

        for p0 in ([0.3, 0.1, 0.1], [0.7, 0.4, 0.6], [0.05, 0.9, 0.3]):
            prm = jnp.asarray(p0)
            f_ref, g_ref = jax.value_and_grad(obj)(prm)
            f, g = hw._hw_sse_value_and_grad(prm, s, 12, mt)
            np.testing.assert_allclose(f, f_ref, rtol=1e-12)
            np.testing.assert_allclose(g, g_ref, rtol=1e-9, atol=1e-9)


def test_fit_fused_path_forced_on_cpu(monkeypatch):
    # STS_HW_FUSED=1 drives fit() end-to-end through the fused
    # value-and-grad pass on the CPU backend (advisor r3: the accelerator
    # gate otherwise leaves that path unit-tested only); results must agree
    # with the default reverse-mode path at optimizer tolerance
    rng = np.random.default_rng(5)
    t = np.arange(72.)
    for mt, y in (
        ("additive", 50 + 0.3 * t + 4 * np.sin(2 * np.pi * t / 6)
         + rng.normal(scale=0.5, size=72)),
        ("multiplicative", (50 + 0.3 * t)
         * (1 + 0.08 * np.sin(2 * np.pi * t / 6))
         + rng.normal(scale=0.3, size=72)),
    ):
        y = jnp.asarray(y)
        base = hw.fit(y, 6, mt, max_iter=300)
        monkeypatch.setenv("STS_HW_FUSED", "1")
        fused = hw.fit(y, 6, mt, max_iter=300)
        monkeypatch.delenv("STS_HW_FUSED")
        for attr in ("alpha", "beta", "gamma"):
            np.testing.assert_allclose(
                np.asarray(getattr(fused, attr)),
                np.asarray(getattr(base, attr)), atol=2e-5)

    monkeypatch.setenv("STS_HW_FUSED", "yes")
    with pytest.raises(ValueError, match="STS_HW_FUSED"):
        hw.fit(y, 6, "additive")


def test_out_of_box_init_projects_before_first_evaluation():
    # minimize_box used to evaluate f0/g0 at the unprojected init, pairing
    # the projected start point with another point's value and gradient —
    # an out-of-box init then converged instantly to a wrong answer
    rng = np.random.default_rng(2)
    t = np.arange(96)
    s = jnp.asarray(90 + 0.3 * t + 7 * np.sin(2 * np.pi * t / 12)
                    + rng.normal(size=96))
    good = hw.fit(s, 12, "additive", max_iter=200)
    wild = hw.fit(s, 12, "additive", max_iter=200, init=(1.5, 0.5, 0.5))
    np.testing.assert_allclose(float(wild.sse(s)), float(good.sse(s)),
                               rtol=0.05)
