"""Pallas ARMA-CSS kernel tests (interpret mode on the CPU tier).

The kernel must agree with the autodiff path it mirrors: residual cost,
J^T J / J^T e normal equations, and the full LM fit trajectory."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_timeseries_tpu.models.arima import (_one_step_errors,
                                               hannan_rissanen_init)
from spark_timeseries_tpu.ops import arma_pallas as ap
from spark_timeseries_tpu.ops.optimize import minimize_least_squares


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(0)
    S, n = 16, 96
    y = np.cumsum(rng.normal(size=(S, n)), axis=1).astype(np.float32)
    diffed = np.diff(y, axis=1)
    params = np.tile(np.array([0.3, 0.2, 0.3, 0.2, 0.1], np.float32), (S, 1))
    params += rng.normal(scale=0.02, size=params.shape).astype(np.float32)
    return jnp.asarray(params), jnp.asarray(diffed)


def _reference(params, diffed, p=2, q=2, icpt=1):
    def resid(prm, yy):
        return _one_step_errors(prm, yy, p, q, icpt)[1]

    r = jax.vmap(resid)(params, diffed)
    J = jax.vmap(jax.jacfwd(resid))(params, diffed)
    return (jnp.einsum("snp,snk->spk", J, J),
            jnp.einsum("snp,sn->sp", J, r),
            jnp.sum(r * r, axis=-1))


def test_normal_equations_match_autodiff(problem):
    params, diffed = problem
    jtj, jtr, cost = ap.css_normal_equations(params, diffed, 2, 2, 1,
                                             interpret=True)
    jtj_ref, jtr_ref, cost_ref = _reference(params, diffed)
    np.testing.assert_allclose(np.asarray(cost), np.asarray(cost_ref),
                               rtol=3e-4)
    np.testing.assert_allclose(np.asarray(jtr), np.asarray(jtr_ref),
                               rtol=3e-3, atol=1e-2)
    np.testing.assert_allclose(np.asarray(jtj), np.asarray(jtj_ref),
                               rtol=3e-3, atol=1e-2)


def test_cost_only_kernel(problem):
    params, diffed = problem
    cost = ap.css_cost(params, diffed, 2, 2, 1, interpret=True)
    _, _, cost_ref = _reference(params, diffed)
    np.testing.assert_allclose(np.asarray(cost), np.asarray(cost_ref),
                               rtol=3e-4)


def test_no_intercept_and_ar_only(problem):
    _, diffed = problem
    S = diffed.shape[0]
    params = jnp.tile(jnp.asarray([0.4, 0.1], jnp.float32), (S, 1))
    jtj, jtr, cost = ap.css_normal_equations(params, diffed, 2, 0, 0,
                                             interpret=True)
    jtj_ref, jtr_ref, cost_ref = _reference(params, diffed, 2, 0, 0)
    np.testing.assert_allclose(np.asarray(cost), np.asarray(cost_ref),
                               rtol=3e-4)
    np.testing.assert_allclose(np.asarray(jtj), np.asarray(jtj_ref),
                               rtol=3e-3, atol=1e-2)


def test_lm_fit_improves_and_tracks_xla_path(problem):
    params, diffed = problem
    x, f, done, it = ap.fit_css_lm(params, diffed, 2, 2, 1, max_iter=30,
                                   interpret=True)
    _, _, cost0 = _reference(params, diffed)
    assert np.all(np.asarray(f) <= np.asarray(cost0) + 1e-3)

    def resid(prm, yy):
        return _one_step_errors(prm, yy, 2, 2, 1)[1]

    res = minimize_least_squares(resid, params, diffed, max_iter=30)
    # both optimizers should reach comparable cost (not identical paths)
    assert np.median(np.asarray(f) - np.asarray(res.fun)) < \
        0.05 * np.median(np.asarray(res.fun))
