"""The structured-metrics subsystem: registry semantics, span nesting,
export golden output, jax.monitoring recompile tracking, and the no-op
fallback when the hooks are absent (ISSUE 1 tentpole)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_timeseries_tpu.ops.optimize import MinimizeResult
from spark_timeseries_tpu.utils import metrics, observability
from spark_timeseries_tpu.utils.metrics import MetricsRegistry


# ---------------------------------------------------------------------------
# registry: counter / gauge / histogram semantics
# ---------------------------------------------------------------------------

def test_counter_semantics():
    reg = MetricsRegistry()
    c = reg.counter("x")
    c.inc()
    c.inc(5)
    assert reg.counter("x") is c                 # get-or-create
    assert reg.snapshot()["counters"]["x"] == 6
    with pytest.raises(ValueError):
        c.inc(-1)                                # counters are monotone


def test_gauge_semantics():
    reg = MetricsRegistry()
    reg.set_gauge("g", 2.5)
    reg.set_gauge("g", 1.0)                      # last write wins
    assert reg.snapshot()["gauges"]["g"] == 1.0


def test_histogram_semantics():
    reg = MetricsRegistry()
    for v in [1.0, 2.0, 3.0, 4.0]:
        reg.record("h", v)
    st = reg.snapshot()["histograms"]["h"]
    assert st["count"] == 4
    assert st["sum"] == 10.0
    assert st["min"] == 1.0 and st["max"] == 4.0
    assert st["mean"] == 2.5
    assert st["p50"] == 2.5
    assert st["p95"] == pytest.approx(3.85)


def test_histogram_sample_cap_keeps_exact_aggregates():
    reg = MetricsRegistry(max_samples=8)
    for v in range(100):
        reg.record("h", float(v))
    st = reg.snapshot()["histograms"]["h"]
    assert st["count"] == 100                    # count/sum exact past cap
    assert st["sum"] == float(sum(range(100)))
    assert st["min"] == 0.0 and st["max"] == 99.0
    # percentiles come from the ring of the most recent 8 samples
    assert 92.0 <= st["p50"] <= 99.0


def test_reset_clears_everything():
    reg = MetricsRegistry()
    reg.inc("c")
    reg.record("h", 1.0)
    reg.record_span("s", 0.1)
    reg.reset()
    snap = reg.snapshot()
    assert snap == {"counters": {}, "gauges": {}, "histograms": {},
                    "spans": {}}


def test_disabled_registry_records_nothing():
    reg = MetricsRegistry()
    reg.enabled = False
    reg.inc("c")
    reg.record("h", 1.0)
    reg.record_span("s", 0.1)
    snap = reg.snapshot()
    assert snap["counters"] == {} and snap["histograms"] == {}
    assert snap["spans"] == {}


# ---------------------------------------------------------------------------
# spans: nesting + timing
# ---------------------------------------------------------------------------

def test_span_nesting_and_timing():
    reg = MetricsRegistry()
    import time as _time
    with metrics.span("outer", registry=reg):
        assert metrics.current_span_path() == "outer"
        with metrics.span("inner", registry=reg):
            assert metrics.current_span_path() == "outer/inner"
            _time.sleep(0.01)
    assert metrics.current_span_path() == ""
    spans = reg.snapshot()["spans"]
    assert set(spans) == {"outer", "outer/inner"}
    assert spans["outer"]["count"] == 1
    assert spans["outer/inner"]["count"] == 1
    # the outer span contains the inner one
    assert spans["outer"]["total_s"] >= spans["outer/inner"]["total_s"]
    assert spans["outer/inner"]["total_s"] >= 0.005


def test_span_distinct_paths_accumulate_separately():
    reg = MetricsRegistry()
    for _ in range(3):
        with metrics.span("a", registry=reg):
            pass
    with metrics.span("b", registry=reg):
        pass
    spans = reg.snapshot()["spans"]
    assert spans["a"]["count"] == 3
    assert spans["b"]["count"] == 1


def test_span_pops_on_exception():
    reg = MetricsRegistry()
    with pytest.raises(RuntimeError):
        with metrics.span("boom", registry=reg):
            raise RuntimeError("x")
    assert metrics.current_span_path() == ""
    assert reg.snapshot()["spans"]["boom"]["count"] == 1


# ---------------------------------------------------------------------------
# export: JSON + Prometheus golden output
# ---------------------------------------------------------------------------

def _golden_registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.counter("fit.arima.series").inc(8)
    reg.set_gauge("panel.n_series", 4)
    for v in [1.0, 2.0, 3.0, 4.0]:
        reg.record("optimize.lm.iters_mean", v)
    reg.record_span("arima.fit_panel", 0.25)
    reg.record_span("arima.fit_panel", 0.75)
    return reg


def test_json_export_round_trips():
    reg = _golden_registry()
    snap = json.loads(reg.to_json())
    assert snap == reg.snapshot()
    assert snap["counters"]["fit.arima.series"] == 8
    assert snap["spans"]["arima.fit_panel"]["count"] == 2
    assert snap["spans"]["arima.fit_panel"]["total_s"] == 1.0


def test_prometheus_export_golden():
    out = _golden_registry().to_prometheus()
    assert out == (
        "# HELP sts_fit_arima_series fit.arima.series (counter)\n"
        "# TYPE sts_fit_arima_series counter\n"
        "sts_fit_arima_series 8\n"
        "# HELP sts_panel_n_series panel.n_series (gauge)\n"
        "# TYPE sts_panel_n_series gauge\n"
        "sts_panel_n_series 4\n"
        "# HELP sts_optimize_lm_iters_mean optimize.lm.iters_mean "
        "(histogram)\n"
        "# TYPE sts_optimize_lm_iters_mean summary\n"
        'sts_optimize_lm_iters_mean{quantile="0.5"} 2.5\n'
        'sts_optimize_lm_iters_mean{quantile="0.95"} 3.85\n'
        "sts_optimize_lm_iters_mean_sum 10\n"
        "sts_optimize_lm_iters_mean_count 4\n"
        "# HELP sts_arima_fit_panel_seconds arima.fit_panel (span)\n"
        "# TYPE sts_arima_fit_panel_seconds summary\n"
        'sts_arima_fit_panel_seconds{quantile="0.5"} 0.5\n'
        'sts_arima_fit_panel_seconds{quantile="0.95"} 0.725\n'
        "sts_arima_fit_panel_seconds_sum 1\n"
        "sts_arima_fit_panel_seconds_count 2\n"
    )


def test_prometheus_empty_registry_exports_empty_string():
    # a lone blank line is not valid exposition text
    assert MetricsRegistry().to_prometheus() == ""


# ---------------------------------------------------------------------------
# jax.monitoring bridge
# ---------------------------------------------------------------------------

def test_recompile_counter_increments_across_forced_rejit():
    reg = MetricsRegistry()
    assert metrics.install_jax_hooks(reg) is True
    assert metrics.install_jax_hooks(reg) is True     # idempotent
    assert metrics.jax_hooks_installed(reg)

    before = reg.snapshot()["counters"]["jax.jit_compiles"]

    @jax.jit
    def f(x):
        return x * 2.0 + 1.0

    f(jnp.ones(7)).block_until_ready()
    after_first = reg.snapshot()["counters"]["jax.jit_compiles"]
    assert after_first > before                       # first compile seen

    f(jnp.ones(7)).block_until_ready()                # cache hit: no re-jit
    assert reg.snapshot()["counters"]["jax.jit_compiles"] == after_first

    f(jnp.ones(11)).block_until_ready()               # new shape: re-jit
    after_second = reg.snapshot()["counters"]["jax.jit_compiles"]
    assert after_second > after_first

    stats = metrics.jax_stats(reg)
    assert stats["hooks_installed"] is True
    assert stats["jit_compiles"] == after_second
    assert stats["compile_s_total"] > 0.0


def test_jax_hooks_noop_fallback_when_absent(monkeypatch):
    import jax.monitoring
    monkeypatch.delattr(jax.monitoring, "register_event_listener")
    reg = MetricsRegistry()
    assert metrics.install_jax_hooks(reg) is False
    assert not metrics.jax_hooks_installed(reg)
    stats = metrics.jax_stats(reg)
    assert stats["hooks_installed"] is False
    assert stats["jit_compiles"] == 0
    assert stats["compile_s_total"] == 0.0


# ---------------------------------------------------------------------------
# host-side instrumentation helpers
# ---------------------------------------------------------------------------

def test_observe_minimize_concrete():
    reg = MetricsRegistry()
    res = MinimizeResult(
        x=jnp.ones((4, 2)),
        fun=jnp.zeros(4),
        converged=jnp.asarray([True, True, False, True]),
        n_iter=jnp.asarray([3, 5, 50, 7]))
    out = metrics.observe_minimize("lm", res, registry=reg)
    assert out is res
    c = reg.snapshot()["counters"]
    assert c["optimize.lm.calls"] == 1
    assert c["optimize.lm.lanes"] == 4
    assert c["optimize.lm.lanes_converged"] == 3
    h = reg.snapshot()["histograms"]
    assert h["optimize.lm.iters_mean"]["count"] == 1
    assert h["optimize.lm.iters_max"]["max"] == 50.0


def test_record_fit_skips_tracers_under_jit():
    """A fit traced under jit must count a retrace, not crash trying to
    materialize tracer diagnostics."""
    from spark_timeseries_tpu.models import ewma

    rng = np.random.default_rng(3)
    y = jnp.asarray(rng.normal(size=(3, 48)).cumsum(axis=1))
    base = metrics.snapshot()["counters"]

    jax.jit(lambda v: ewma.fit(v))(y)

    c = metrics.snapshot()["counters"]
    assert c["fit.ewma.traced"] == base.get("fit.ewma.traced", 0) + 1
    # concrete lane counts did NOT move (nothing concrete was seen)
    assert c.get("fit.ewma.series", 0) == base.get("fit.ewma.series", 0)


def test_model_fit_records_counter_bundle_and_span():
    from spark_timeseries_tpu.models import ewma

    rng = np.random.default_rng(4)
    y = jnp.asarray(rng.normal(size=(5, 64)).cumsum(axis=1))
    base = metrics.snapshot()["counters"]
    ewma.fit(y)
    snap = metrics.snapshot()
    c = snap["counters"]
    assert c["fit.ewma.calls"] == base.get("fit.ewma.calls", 0) + 1
    assert c["fit.ewma.series"] == base.get("fit.ewma.series", 0) + 5
    assert snap["spans"]["ewma.fit"]["count"] >= 1


def test_fit_report_extension_and_registry_bundle():
    from spark_timeseries_tpu.models import ewma

    rng = np.random.default_rng(5)
    y = jnp.asarray(rng.normal(size=(6, 80)).cumsum(axis=1))
    model = ewma.fit(y)
    base = metrics.snapshot()["counters"].get(
        "fit_report.ewma.n_series", 0)
    report = observability.fit_report(model)
    assert report["n_series"] == 6
    assert 0.0 <= report["frac_converged"] <= 1.0
    assert report["iters_p50"] <= report["iters_p95"] <= report["iters_max"]
    after = metrics.snapshot()["counters"]["fit_report.ewma.n_series"]
    assert after == base + 6
    # repeated fits accumulate
    observability.fit_report(model)
    assert metrics.snapshot()["counters"][
        "fit_report.ewma.n_series"] == base + 12


def test_fit_report_family_matches_instrumented_bundle():
    """The auto-derived fit_report family must use the same spelling as
    the @instrument_fit bundle, or per-family dashboards correlate
    nothing (HoltWintersModel -> holt_winters, not holtwinters)."""
    from spark_timeseries_tpu.models import holt_winters

    rng = np.random.default_rng(6)
    t = np.arange(72)
    y = jnp.asarray(10 + 0.1 * t + np.sin(2 * np.pi * t / 12)
                    + 0.1 * rng.normal(size=(2, 72)))
    model = holt_winters.fit(y, period=12, max_iter=50)
    observability.fit_report(model)
    c = metrics.snapshot()["counters"]
    assert "fit.holt_winters.calls" in c
    assert "fit_report.holt_winters.reports" in c
    assert not any(k.startswith("fit_report.holtwinters") for k in c)


def test_auto_fit_carries_diagnostics():
    from spark_timeseries_tpu.models import arima

    rng = np.random.default_rng(7)
    y = jnp.asarray(rng.normal(size=160).cumsum())
    model = arima.auto_fit(y, max_p=1, max_q=1)
    assert model.diagnostics is not None
    report = observability.fit_report(model)
    assert report["n_series"] == 1


def test_timed_min_shared_harness():
    calls = []

    def fn(x):
        calls.append(1)
        return {"y": x * 2}

    best, out = observability.timed_min(fn, jnp.arange(4.0), reps=2,
                                        want_out=True)
    assert len(calls) == 3                       # 1 warm + 2 timed
    assert best >= 0.0
    assert isinstance(out["y"], np.ndarray)      # materialized on host
    np.testing.assert_allclose(out["y"], [0.0, 2.0, 4.0, 6.0])
    # bench re-exports the same protocol
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    try:
        import bench
        assert bench.timed_min(fn, jnp.arange(4.0), reps=1) >= 0.0
    finally:
        sys.path.pop(0)
