"""sts-lint rule-by-rule fixtures, suppression/baseline mechanics, and
the JSON report schema (ISSUE 4 level 1).

Each rule class gets a positive fixture (the seeded violation MUST be
found — the acceptance criterion that `make lint` exits nonzero on a
tree containing one violation per rule class) and negatives pinning the
false-positive boundaries the rules were tuned against on the real tree
(positional dtypes, static jit args, host orchestration code).

Pure-AST: no JAX import, no tracing — the whole file runs in seconds.
"""

import json
import os

import pytest

from tools.sts_lint import lint_paths, load_baseline, write_baseline
from tools.sts_lint.__main__ import main as lint_main
from tools.sts_lint.rules import RULES, TRACER_SAFETY_RULES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HEADER = (
    "import functools\n"
    "import time\n"
    "import jax\n"
    "import jax.numpy as jnp\n"
    "import numpy as np\n"
    "from jax import lax\n"
)


def run_fixture(tmp_path, files, **kw):
    """Write {relpath: source} under tmp_path and lint it."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    result, sources = lint_paths([str(tmp_path)], root=str(tmp_path), **kw)
    return result, sources


def codes(result):
    return sorted({f.code for f in result.new})


# ---------------------------------------------------------------------------
# one seeded violation per rule class -> nonzero exit (acceptance
# criterion), and the clean inverse
# ---------------------------------------------------------------------------

SEEDED = {
    "STS001": HEADER + (
        "@jax.jit\n"
        "def f(x):\n"
        "    t = time.time()\n"
        "    return x + t\n"),
    "STS002": HEADER + (
        "from spark_timeseries_tpu.utils import metrics\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    metrics.inc('nope')\n"
        "    return x\n"),
    "STS003": HEADER + (
        "def f(n):\n"
        "    return jnp.zeros((n, 4))\n"),
    "STS004": HEADER + (
        "def f(n):\n"
        "    return np.zeros((n, 4))\n"),
    "STS005": HEADER + (
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n"),
    "STS006": HEADER + (
        "def f(y):\n"
        "    return jax.jit(lambda v: v * y)(y)\n"),
}


@pytest.mark.parametrize("code", sorted(SEEDED))
def test_seeded_violation_fails_lint(tmp_path, code):
    result, _ = run_fixture(tmp_path, {"ops/seeded.py": SEEDED[code]})
    assert code in codes(result), \
        f"rule {code} missed its seeded violation; found {codes(result)}"
    assert result.exit_code == 1


def test_clean_tree_exits_zero(tmp_path):
    clean = HEADER + (
        "@jax.jit\n"
        "def f(x):\n"
        "    return jnp.where(x > 0, x, -x)\n"
        "def make(n):\n"
        "    return jnp.zeros((n, 4), jnp.float32)\n")
    result, _ = run_fixture(tmp_path, {"ops/clean.py": clean})
    assert result.new == []
    assert result.exit_code == 0


# ---------------------------------------------------------------------------
# STS001 — host sync in traced code
# ---------------------------------------------------------------------------

def test_sts001_scan_body_and_helper_propagation(tmp_path):
    src = HEADER + (
        "def helper(c):\n"
        "    print('step', c)\n"              # traced via scan body ref
        "    return c\n"
        "def run(xs):\n"
        "    def step(c, x):\n"
        "        return helper(c) + x, None\n"
        "    return lax.scan(step, jnp.zeros((), jnp.float32), xs)\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    hits = [f for f in result.new if f.code == "STS001"]
    assert len(hits) == 1 and hits[0].symbol == "helper"


def test_sts001_objective_through_transformer_param(tmp_path):
    # the minimize_* shape: an objective passed to a function whose
    # parameter is (transitively) vmapped is traced, cross-function
    src = HEADER + (
        "def solver(fn, x0):\n"
        "    return jax.vmap(fn)(x0)\n"
        "def fit(v):\n"
        "    def objective(p):\n"
        "        v2 = float(p)\n"             # STS001 inside objective
        "        return p * v2\n"
        "    return solver(objective, v)\n")
    result, _ = run_fixture(tmp_path, {"models/m.py": src})
    hits = [f for f in result.new if f.code == "STS001"]
    assert len(hits) == 1 and hits[0].symbol == "fit.objective"


def test_sts001_host_driver_may_sync(tmp_path):
    src = HEADER + (
        "def driver(v):\n"
        "    t0 = time.time()\n"             # host orchestration: fine
        "    out = jnp.sum(v)\n"
        "    print('took', time.time() - t0, float(out))\n"
        "    return out\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS001"] == []


def test_sts001_item_in_while_body(tmp_path):
    src = HEADER + (
        "def run(x):\n"
        "    def body(c):\n"
        "        return c + c.item()\n"      # blocking sync in trace
        "    def cond(c):\n"
        "        return c[0] < 4\n"
        "    return lax.while_loop(cond, body, x)\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    hits = [f for f in result.new if f.code == "STS001"]
    assert len(hits) == 1 and ".item()" in hits[0].message


# ---------------------------------------------------------------------------
# STS002 — observability in traced code
# ---------------------------------------------------------------------------

def test_sts002_span_from_import_in_jit(tmp_path):
    src = HEADER + (
        "from spark_timeseries_tpu.utils.metrics import span\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    with span('bad'):\n"
        "        return x * 2\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert codes(result) == ["STS002"]


def test_sts002_instrumented_fit_called_from_trace(tmp_path):
    src = HEADER + (
        "from ..utils import metrics as _metrics\n"
        "@_metrics.instrument_fit('toy')\n"
        "def fit(v):\n"
        "    return v\n"
        "def panel_kernel(vs):\n"
        "    def one(v):\n"
        "        return fit(v)\n"            # span fires under trace
        "    return jax.vmap(one)(vs)\n")
    result, _ = run_fixture(tmp_path, {"models/m.py": src})
    hits = [f for f in result.new if f.code == "STS002"]
    assert len(hits) == 1 and "__wrapped__" in hits[0].message


def test_sts002_wrapped_call_is_clean(tmp_path):
    src = HEADER + (
        "from ..utils import metrics as _metrics\n"
        "@_metrics.instrument_fit('toy')\n"
        "def fit(v):\n"
        "    return v\n"
        "def panel_kernel(vs):\n"
        "    def one(v):\n"
        "        return fit.__wrapped__(v)\n"
        "    return jax.vmap(one)(vs)\n")
    result, _ = run_fixture(tmp_path, {"models/m.py": src})
    assert [f for f in result.new if f.code == "STS002"] == []


def test_sts002_span_around_traced_call_is_clean(tmp_path):
    src = HEADER + (
        "from spark_timeseries_tpu.utils import metrics\n"
        "def fit(v):\n"
        "    with metrics.span('fit'):\n"    # host side: the invariant
        "        return jax.vmap(lambda x: x * 2)(v)\n")
    result, _ = run_fixture(tmp_path, {"models/m.py": src})
    assert [f for f in result.new if f.code == "STS002"] == []


# ---------------------------------------------------------------------------
# STS003 / STS004 — dtype discipline
# ---------------------------------------------------------------------------

def test_sts003_positional_and_kwarg_dtype_are_explicit(tmp_path):
    src = HEADER + (
        "def f(n, dtype):\n"
        "    a = jnp.zeros((n,), jnp.float32)\n"      # positional canon
        "    b = jnp.ones((n,), dtype=jnp.int32)\n"   # kwarg
        "    c = jnp.full((n,), 1e-3, dtype)\n"       # positional name
        "    d = jnp.zeros((n,), bool)\n"             # builtin dtype
        "    return a, b, c, d\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS003"] == []


def test_sts003_int_index_math_exempt_float_literals_not(tmp_path):
    src = HEADER + (
        "def f(n):\n"
        "    iota = jnp.arange(n)\n"                  # int index: exempt
        "    ints = jnp.array([1, 2, 3])\n"           # int literal: exempt
        "    floats = jnp.array([0.5, 1.0])\n"        # STS003
        "    return iota, ints, floats\n")
    result, _ = run_fixture(tmp_path, {"models/m.py": src})
    hits = [f for f in result.new if f.code == "STS003"]
    assert len(hits) == 1 and hits[0].line == 10


def test_sts003_only_in_ops_and_models(tmp_path):
    src = HEADER + "def f(n):\n    return jnp.zeros((n,))\n"
    result, _ = run_fixture(tmp_path, {"utils/u.py": src,
                                       "ops/a.py": src})
    hits = [f for f in result.new if f.code == "STS003"]
    assert [f.path for f in hits] == ["ops/a.py"]


def test_sts004_np_float64_flagged(tmp_path):
    src = HEADER + (
        "def f(x):\n"
        "    return x * np.float64(2.0)\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert codes(result) == ["STS004"]


# ---------------------------------------------------------------------------
# STS005 — tracer branching
# ---------------------------------------------------------------------------

def test_sts005_static_config_args_not_tainted(tmp_path):
    # the _remove_effects_one shape: ints threaded through a traced
    # lambda's closure are static — branching on them is fine
    src = HEADER + (
        "def kernel(params, ts, p, q):\n"
        "    if p > 0:\n"                    # p is host config: fine
        "        ts = ts + params[0]\n"
        "    if (params > 0).any():\n"       # params is a tracer: STS005
        "        ts = ts * 2\n"
        "    return ts\n"
        "def fit(vs, ts, p, q):\n"
        "    return jax.vmap(lambda pr, t: kernel(pr, t, p, q))(vs, ts)\n")
    result, _ = run_fixture(tmp_path, {"models/m.py": src})
    hits = [f for f in result.new if f.code == "STS005"]
    assert [h.line for h in hits] == [10]


def test_sts005_static_argnames_honored(tmp_path):
    src = HEADER + (
        "@functools.partial(jax.jit, static_argnames=('mode',))\n"
        "def f(x, mode):\n"
        "    if mode == 'fast':\n"           # static: fine
        "        return x\n"
        "    return x * 2\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS005"] == []


def test_sts005_shape_and_none_checks_exempt(tmp_path):
    src = HEADER + (
        "@jax.jit\n"
        "def f(x, y):\n"
        "    if x.ndim == 2:\n"              # static attribute: fine
        "        x = x[None]\n"
        "    if y is None:\n"                # identity check: fine
        "        return x\n"
        "    while x.shape[0] > 1:\n"        # static attribute: fine
        "        x = x[::2]\n"
        "    return x\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS005"] == []


def test_sts005_taint_flows_through_assignment(tmp_path):
    src = HEADER + (
        "@jax.jit\n"
        "def f(x):\n"
        "    y = jnp.sum(x) + 1\n"
        "    if y > 3:\n"                    # y flows from tracer x
        "        return x\n"
        "    return -x\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    hits = [f for f in result.new if f.code == "STS005"]
    assert [h.line for h in hits] == [10]


# ---------------------------------------------------------------------------
# STS006 — recompile hazards
# ---------------------------------------------------------------------------

def test_sts006_module_level_fn_rejit_is_cached(tmp_path):
    # measured: jax.jit(same module-level fn object) hits the global jit
    # cache; only fresh closures recompile per call
    src = HEADER + (
        "def kernel(v, n):\n"
        "    return v * n\n"
        "def driver(v):\n"
        "    return jax.jit(kernel, static_argnums=(1,))(v, 3)\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS006"] == []


def test_sts006_lru_cached_factory_exempt(tmp_path):
    src = HEADER + (
        "@functools.lru_cache(maxsize=None)\n"
        "def jitted_for(mesh):\n"
        "    return jax.jit(lambda v: v.T, donate_argnums=0)\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS006"] == []


def test_sts006_nested_def_jitted_per_call(tmp_path):
    src = HEADER + (
        "def driver(v, scale):\n"
        "    def kernel(x):\n"
        "        return x * scale\n"         # closure over scale
        "    return jax.jit(kernel)(v)\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    hits = [f for f in result.new if f.code == "STS006"]
    assert len(hits) == 1 and "kernel" in hits[0].message


def test_sts006_module_scope_jit_fine(tmp_path):
    src = HEADER + (
        "square = jax.jit(lambda v: v * v)\n")  # once per process
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS006"] == []


# ---------------------------------------------------------------------------
# suppression + baseline mechanics
# ---------------------------------------------------------------------------

def test_noqa_suppresses_matching_code_only(tmp_path):
    src = HEADER + (
        "def f(n):\n"
        "    a = jnp.zeros((n,))  # sts: noqa[STS003]\n"
        "    b = jnp.zeros((n,))  # sts: noqa[STS001]\n"   # wrong code
        "    c = jnp.zeros((n,))  # sts: noqa\n"           # bare: all
        "    return a, b, c\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert len(result.suppressed) == 2
    assert [f.line for f in result.new] == [9]


def test_baseline_roundtrip(tmp_path):
    files = {"ops/a.py": HEADER + "def f(n):\n    return jnp.zeros((n,))\n"}
    result, sources = run_fixture(tmp_path, files)
    assert result.exit_code == 1
    bl_path = str(tmp_path / "baseline.json")
    entries = write_baseline(bl_path, result, sources)
    assert sum(entries.values()) == 1

    # baselined run is green...
    r2, _ = run_fixture(tmp_path, files, baseline=load_baseline(bl_path))
    assert r2.exit_code == 0
    assert len(r2.baselined) == 1 and r2.new == []

    # ...but a NEW copy of the same pattern still fails
    files["ops/a.py"] += "def g(n):\n    return jnp.zeros((n,))\n"
    r3, _ = run_fixture(tmp_path, files, baseline=load_baseline(bl_path))
    assert r3.exit_code == 1
    assert len(r3.new) == 1 and len(r3.baselined) == 1


def test_baseline_survives_line_drift(tmp_path):
    files = {"ops/a.py": HEADER + "def f(n):\n    return jnp.zeros((n,))\n"}
    result, sources = run_fixture(tmp_path, files)
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(bl_path, result, sources)
    # unrelated edits above the finding must not resurrect it
    files["ops/a.py"] = HEADER + "\n\nX = 1\n\n" + \
        "def f(n):\n    return jnp.zeros((n,))\n"
    r2, _ = run_fixture(tmp_path, files, baseline=load_baseline(bl_path))
    assert r2.exit_code == 0 and len(r2.baselined) == 1


# ---------------------------------------------------------------------------
# JSON report schema + CLI + the shipped tree
# ---------------------------------------------------------------------------

def test_json_report_schema(tmp_path):
    result, _ = run_fixture(tmp_path, {"ops/a.py": SEEDED["STS003"]})
    report = result.to_json()
    assert report["version"] == 1 and report["tool"] == "sts-lint"
    assert set(report["rules"]) == set(RULES)
    for meta in report["rules"].values():
        assert meta["name"] and meta["summary"]
    s = report["summary"]
    assert {"findings", "suppressed", "baselined", "files_scanned",
            "by_code"} <= set(s)
    assert s["findings"] == len(report["findings"]) > 0
    f = report["findings"][0]
    assert {"code", "path", "line", "col", "symbol", "message",
            "status"} <= set(f)


def test_cli_json_out_and_exit_codes(tmp_path, capsys):
    fx = tmp_path / "ops"
    fx.mkdir()
    (fx / "a.py").write_text(SEEDED["STS001"])
    out = str(tmp_path / "report.json")
    rc = lint_main([str(tmp_path), "--root", str(tmp_path),
                    "--no-baseline", "--json", out, "-q"])
    assert rc == 1
    report = json.loads(open(out).read())
    assert report["summary"]["findings"] >= 1
    capsys.readouterr()


def test_cli_write_baseline_then_green(tmp_path, capsys):
    fx = tmp_path / "ops"
    fx.mkdir()
    (fx / "a.py").write_text(SEEDED["STS004"])
    bl = str(tmp_path / "bl.json")
    assert lint_main([str(tmp_path), "--root", str(tmp_path),
                      "--baseline", bl, "--write-baseline"]) == 0
    assert lint_main([str(tmp_path), "--root", str(tmp_path),
                      "--baseline", bl, "-q"]) == 0
    capsys.readouterr()


def test_shipped_tree_is_clean_and_baseline_empty():
    """`make lint` must exit 0 on the shipped tree, and the debt ledger
    must be EMPTY for the tracer-safety/host-sync rules (it is in fact
    empty for every rule — all accepted findings are justified in-source
    via noqa)."""
    from tools.sts_lint import DEFAULT_BASELINE
    baseline = load_baseline(DEFAULT_BASELINE)
    for fp in baseline:
        assert not fp.startswith(TRACER_SAFETY_RULES), \
            f"tracer-safety finding in baseline: {fp}"
    result, _ = lint_paths([os.path.join(REPO, "spark_timeseries_tpu")],
                           root=REPO, baseline=baseline)
    assert result.parse_errors == []
    assert result.new == [], [f.render() for f in result.new]
    # the tracer-safety promise specifically: nothing suppressed either
    assert [f for f in result.suppressed
            if f.code in TRACER_SAFETY_RULES] == []


def test_real_tree_traced_model_sanity():
    """The semantic model must actually mark the known traced surfaces
    of the real tree — guards against the analysis silently going
    vacuous (every rule 'passing' because nothing is traced)."""
    import ast
    from tools.sts_lint.analysis import ModuleModel, Project
    path = os.path.join(REPO, "spark_timeseries_tpu", "ops",
                        "optimize.py")
    src = open(path).read()
    mod = ModuleModel(path, "ops/optimize.py", src, ast.parse(src))
    Project([mod])
    traced = {fi.qualname for fi in mod.functions if fi.traced}
    for expected in ("minimize_bfgs.solve_one", "_minimize_lm_one.body",
                     "_minimize_box_one.body.bt_body"):
        assert expected in traced, f"{expected} not marked traced"
    transformers = {fi.name: fi.transformer_params
                    for fi in mod.functions if fi.transformer_params}
    assert "fn" in transformers.get("minimize_bfgs", set())
    assert "residual_fn" in transformers.get("minimize_least_squares",
                                             set())
