"""sts-lint rule-by-rule fixtures, suppression/baseline mechanics, and
the JSON report schema (ISSUE 4 level 1).

Each rule class gets a positive fixture (the seeded violation MUST be
found — the acceptance criterion that `make lint` exits nonzero on a
tree containing one violation per rule class) and negatives pinning the
false-positive boundaries the rules were tuned against on the real tree
(positional dtypes, static jit args, host orchestration code).

Pure-AST: no JAX import, no tracing — the whole file runs in seconds.
"""

import json
import os

import pytest

from tools.sts_lint import lint_paths, load_baseline, write_baseline
from tools.sts_lint.__main__ import main as lint_main
from tools.sts_lint.rules import (CONCURRENCY_RULES, EXAMPLES,
                                  HOST_BOUNDARY_RULES, RULES,
                                  TRACER_SAFETY_RULES)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HEADER = (
    "import functools\n"
    "import time\n"
    "import jax\n"
    "import jax.numpy as jnp\n"
    "import numpy as np\n"
    "from jax import lax\n"
)


def run_fixture(tmp_path, files, **kw):
    """Write {relpath: source} under tmp_path and lint it."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    result, sources = lint_paths([str(tmp_path)], root=str(tmp_path), **kw)
    return result, sources


def codes(result):
    return sorted({f.code for f in result.new})


# ---------------------------------------------------------------------------
# one seeded violation per rule class -> nonzero exit (acceptance
# criterion), and the clean inverse
# ---------------------------------------------------------------------------

SEEDED = {
    "STS001": HEADER + (
        "@jax.jit\n"
        "def f(x):\n"
        "    t = time.time()\n"
        "    return x + t\n"),
    "STS002": HEADER + (
        "from spark_timeseries_tpu.utils import metrics\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    metrics.inc('nope')\n"
        "    return x\n"),
    "STS003": HEADER + (
        "def f(n):\n"
        "    return jnp.zeros((n, 4))\n"),
    "STS004": HEADER + (
        "def f(n):\n"
        "    return np.zeros((n, 4))\n"),
    "STS005": HEADER + (
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n"),
    "STS006": HEADER + (
        "def f(y):\n"
        "    return jax.jit(lambda v: v * y)(y)\n"),
}

# the concurrency tier's seeded positives (ISSUE 14 acceptance: the
# lint must exit nonzero on one violation per STS10x class)
THREAD_HEADER = "import threading\nimport time\n"

SEEDED.update({
    "STS101": THREAD_HEADER + (
        "class R:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n"
        "    def inc(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n"
        "    def reset(self):\n"
        "        self.n = 0\n"),
    "STS102": THREAD_HEADER + (
        "_a = threading.Lock()\n"
        "_b = threading.Lock()\n"
        "def one():\n"
        "    with _a:\n"
        "        with _b:\n"
        "            pass\n"
        "def two():\n"
        "    with _b:\n"
        "        with _a:\n"
        "            pass\n"),
    "STS103": THREAD_HEADER + (
        "_lock = threading.Lock()\n"
        "def tick():\n"
        "    with _lock:\n"
        "        time.sleep(0.1)\n"),
    "STS104": THREAD_HEADER + (
        "def work():\n"
        "    pass\n"
        "def spawn():\n"
        "    t = threading.Thread(target=work)\n"
        "    t.start()\n"),
})


@pytest.mark.parametrize("code", sorted(SEEDED))
def test_seeded_violation_fails_lint(tmp_path, code):
    result, _ = run_fixture(tmp_path, {"ops/seeded.py": SEEDED[code]})
    assert code in codes(result), \
        f"rule {code} missed its seeded violation; found {codes(result)}"
    assert result.exit_code == 1


def test_clean_tree_exits_zero(tmp_path):
    clean = HEADER + (
        "@jax.jit\n"
        "def f(x):\n"
        "    return jnp.where(x > 0, x, -x)\n"
        "def make(n):\n"
        "    return jnp.zeros((n, 4), jnp.float32)\n")
    result, _ = run_fixture(tmp_path, {"ops/clean.py": clean})
    assert result.new == []
    assert result.exit_code == 0


# ---------------------------------------------------------------------------
# STS001 — host sync in traced code
# ---------------------------------------------------------------------------

def test_sts001_scan_body_and_helper_propagation(tmp_path):
    src = HEADER + (
        "def helper(c):\n"
        "    print('step', c)\n"              # traced via scan body ref
        "    return c\n"
        "def run(xs):\n"
        "    def step(c, x):\n"
        "        return helper(c) + x, None\n"
        "    return lax.scan(step, jnp.zeros((), jnp.float32), xs)\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    hits = [f for f in result.new if f.code == "STS001"]
    assert len(hits) == 1 and hits[0].symbol == "helper"


def test_sts001_objective_through_transformer_param(tmp_path):
    # the minimize_* shape: an objective passed to a function whose
    # parameter is (transitively) vmapped is traced, cross-function
    src = HEADER + (
        "def solver(fn, x0):\n"
        "    return jax.vmap(fn)(x0)\n"
        "def fit(v):\n"
        "    def objective(p):\n"
        "        v2 = float(p)\n"             # STS001 inside objective
        "        return p * v2\n"
        "    return solver(objective, v)\n")
    result, _ = run_fixture(tmp_path, {"models/m.py": src})
    hits = [f for f in result.new if f.code == "STS001"]
    assert len(hits) == 1 and hits[0].symbol == "fit.objective"


def test_sts001_host_driver_may_sync(tmp_path):
    src = HEADER + (
        "def driver(v):\n"
        "    t0 = time.time()\n"             # host orchestration: fine
        "    out = jnp.sum(v)\n"
        "    print('took', time.time() - t0, float(out))\n"
        "    return out\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS001"] == []


def test_sts001_item_in_while_body(tmp_path):
    src = HEADER + (
        "def run(x):\n"
        "    def body(c):\n"
        "        return c + c.item()\n"      # blocking sync in trace
        "    def cond(c):\n"
        "        return c[0] < 4\n"
        "    return lax.while_loop(cond, body, x)\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    hits = [f for f in result.new if f.code == "STS001"]
    assert len(hits) == 1 and ".item()" in hits[0].message


# ---------------------------------------------------------------------------
# STS002 — observability in traced code
# ---------------------------------------------------------------------------

def test_sts002_span_from_import_in_jit(tmp_path):
    src = HEADER + (
        "from spark_timeseries_tpu.utils.metrics import span\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    with span('bad'):\n"
        "        return x * 2\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert codes(result) == ["STS002"]


def test_sts002_instrumented_fit_called_from_trace(tmp_path):
    src = HEADER + (
        "from ..utils import metrics as _metrics\n"
        "@_metrics.instrument_fit('toy')\n"
        "def fit(v):\n"
        "    return v\n"
        "def panel_kernel(vs):\n"
        "    def one(v):\n"
        "        return fit(v)\n"            # span fires under trace
        "    return jax.vmap(one)(vs)\n")
    result, _ = run_fixture(tmp_path, {"models/m.py": src})
    hits = [f for f in result.new if f.code == "STS002"]
    assert len(hits) == 1 and "__wrapped__" in hits[0].message


def test_sts002_wrapped_call_is_clean(tmp_path):
    src = HEADER + (
        "from ..utils import metrics as _metrics\n"
        "@_metrics.instrument_fit('toy')\n"
        "def fit(v):\n"
        "    return v\n"
        "def panel_kernel(vs):\n"
        "    def one(v):\n"
        "        return fit.__wrapped__(v)\n"
        "    return jax.vmap(one)(vs)\n")
    result, _ = run_fixture(tmp_path, {"models/m.py": src})
    assert [f for f in result.new if f.code == "STS002"] == []


def test_sts002_span_around_traced_call_is_clean(tmp_path):
    src = HEADER + (
        "from spark_timeseries_tpu.utils import metrics\n"
        "def fit(v):\n"
        "    with metrics.span('fit'):\n"    # host side: the invariant
        "        return jax.vmap(lambda x: x * 2)(v)\n")
    result, _ = run_fixture(tmp_path, {"models/m.py": src})
    assert [f for f in result.new if f.code == "STS002"] == []


# ---------------------------------------------------------------------------
# STS003 / STS004 — dtype discipline
# ---------------------------------------------------------------------------

def test_sts003_positional_and_kwarg_dtype_are_explicit(tmp_path):
    src = HEADER + (
        "def f(n, dtype):\n"
        "    a = jnp.zeros((n,), jnp.float32)\n"      # positional canon
        "    b = jnp.ones((n,), dtype=jnp.int32)\n"   # kwarg
        "    c = jnp.full((n,), 1e-3, dtype)\n"       # positional name
        "    d = jnp.zeros((n,), bool)\n"             # builtin dtype
        "    return a, b, c, d\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS003"] == []


def test_sts003_int_index_math_exempt_float_literals_not(tmp_path):
    src = HEADER + (
        "def f(n):\n"
        "    iota = jnp.arange(n)\n"                  # int index: exempt
        "    ints = jnp.array([1, 2, 3])\n"           # int literal: exempt
        "    floats = jnp.array([0.5, 1.0])\n"        # STS003
        "    return iota, ints, floats\n")
    result, _ = run_fixture(tmp_path, {"models/m.py": src})
    hits = [f for f in result.new if f.code == "STS003"]
    assert len(hits) == 1 and hits[0].line == 10


def test_sts003_only_in_ops_and_models(tmp_path):
    src = HEADER + "def f(n):\n    return jnp.zeros((n,))\n"
    result, _ = run_fixture(tmp_path, {"utils/u.py": src,
                                       "ops/a.py": src})
    hits = [f for f in result.new if f.code == "STS003"]
    assert [f.path for f in hits] == ["ops/a.py"]


def test_sts004_np_float64_flagged(tmp_path):
    src = HEADER + (
        "def f(x):\n"
        "    return x * np.float64(2.0)\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert codes(result) == ["STS004"]


# ---------------------------------------------------------------------------
# STS005 — tracer branching
# ---------------------------------------------------------------------------

def test_sts005_static_config_args_not_tainted(tmp_path):
    # the _remove_effects_one shape: ints threaded through a traced
    # lambda's closure are static — branching on them is fine
    src = HEADER + (
        "def kernel(params, ts, p, q):\n"
        "    if p > 0:\n"                    # p is host config: fine
        "        ts = ts + params[0]\n"
        "    if (params > 0).any():\n"       # params is a tracer: STS005
        "        ts = ts * 2\n"
        "    return ts\n"
        "def fit(vs, ts, p, q):\n"
        "    return jax.vmap(lambda pr, t: kernel(pr, t, p, q))(vs, ts)\n")
    result, _ = run_fixture(tmp_path, {"models/m.py": src})
    hits = [f for f in result.new if f.code == "STS005"]
    assert [h.line for h in hits] == [10]


def test_sts005_static_argnames_honored(tmp_path):
    src = HEADER + (
        "@functools.partial(jax.jit, static_argnames=('mode',))\n"
        "def f(x, mode):\n"
        "    if mode == 'fast':\n"           # static: fine
        "        return x\n"
        "    return x * 2\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS005"] == []


def test_sts005_shape_and_none_checks_exempt(tmp_path):
    src = HEADER + (
        "@jax.jit\n"
        "def f(x, y):\n"
        "    if x.ndim == 2:\n"              # static attribute: fine
        "        x = x[None]\n"
        "    if y is None:\n"                # identity check: fine
        "        return x\n"
        "    while x.shape[0] > 1:\n"        # static attribute: fine
        "        x = x[::2]\n"
        "    return x\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS005"] == []


def test_sts005_taint_flows_through_assignment(tmp_path):
    src = HEADER + (
        "@jax.jit\n"
        "def f(x):\n"
        "    y = jnp.sum(x) + 1\n"
        "    if y > 3:\n"                    # y flows from tracer x
        "        return x\n"
        "    return -x\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    hits = [f for f in result.new if f.code == "STS005"]
    assert [h.line for h in hits] == [10]


# ---------------------------------------------------------------------------
# STS006 — recompile hazards
# ---------------------------------------------------------------------------

def test_sts006_module_level_fn_rejit_is_cached(tmp_path):
    # measured: jax.jit(same module-level fn object) hits the global jit
    # cache; only fresh closures recompile per call
    src = HEADER + (
        "def kernel(v, n):\n"
        "    return v * n\n"
        "def driver(v):\n"
        "    return jax.jit(kernel, static_argnums=(1,))(v, 3)\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS006"] == []


def test_sts006_lru_cached_factory_exempt(tmp_path):
    src = HEADER + (
        "@functools.lru_cache(maxsize=None)\n"
        "def jitted_for(mesh):\n"
        "    return jax.jit(lambda v: v.T, donate_argnums=0)\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS006"] == []


def test_sts006_nested_def_jitted_per_call(tmp_path):
    src = HEADER + (
        "def driver(v, scale):\n"
        "    def kernel(x):\n"
        "        return x * scale\n"         # closure over scale
        "    return jax.jit(kernel)(v)\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    hits = [f for f in result.new if f.code == "STS006"]
    assert len(hits) == 1 and "kernel" in hits[0].message


def test_sts006_module_scope_jit_fine(tmp_path):
    src = HEADER + (
        "square = jax.jit(lambda v: v * v)\n")  # once per process
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert [f for f in result.new if f.code == "STS006"] == []


# ---------------------------------------------------------------------------
# STS101 — shared-state writes vs the owning lock
# ---------------------------------------------------------------------------

def test_sts101_init_and_locked_writes_clean(tmp_path):
    src = THREAD_HEADER + (
        "class R:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n"                  # __init__: unshared yet
        "    def inc(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n")
    result, _ = run_fixture(tmp_path, {"utils/u.py": src})
    assert [f for f in result.new if f.code == "STS101"] == []


def test_sts101_locked_private_helper_relief(tmp_path):
    # the _pop_tenant shape: a private helper whose EVERY intra-class
    # call site holds the lock writes guarded state legitimately
    src = THREAD_HEADER + (
        "class R:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = {}\n"
        "    def put(self, k, v):\n"
        "        with self._lock:\n"
        "            self._store(k, v)\n"
        "    def drop(self, k):\n"
        "        with self._lock:\n"
        "            self.items.pop(k, None)\n"
        "    def _store(self, k, v):\n"
        "        self.items[k] = v\n")
    result, _ = run_fixture(tmp_path, {"utils/u.py": src})
    assert [f for f in result.new if f.code == "STS101"] == []


def test_sts101_container_mutation_outside_lock(tmp_path):
    src = THREAD_HEADER + (
        "_lock = threading.Lock()\n"
        "_jobs = {}\n"
        "def add(j):\n"
        "    with _lock:\n"
        "        _jobs[j] = 1\n"
        "def drop(j):\n"
        "    _jobs.pop(j, None)\n")
    result, _ = run_fixture(tmp_path, {"utils/u.py": src})
    hits = [f for f in result.new if f.code == "STS101"]
    assert len(hits) == 1 and hits[0].symbol == "drop"


def test_sts101_local_shadow_of_global_not_flagged(tmp_path):
    src = THREAD_HEADER + (
        "_lock = threading.Lock()\n"
        "_jobs = {}\n"
        "def note(j):\n"
        "    with _lock:\n"
        "        _jobs[j] = 1\n"
        "def summarize(items):\n"
        "    _jobs = {}\n"                  # local shadow: not shared
        "    for i in items:\n"
        "        _jobs[i] = 1\n"
        "    return _jobs\n")
    result, _ = run_fixture(tmp_path, {"utils/u.py": src})
    assert [f for f in result.new if f.code == "STS101"] == []


def test_sts101_same_basename_modules_keep_separate_inventories(tmp_path):
    # backtest/api.py vs longseries/api.py: colliding basenames must not
    # overwrite each other's lock inventory — a violation in EACH module
    # fires, and neither resolves through the other's lock
    src = THREAD_HEADER + (
        "_lock = threading.Lock()\n"
        "_state = {}\n"
        "def put(k):\n"
        "    with _lock:\n"
        "        _state[k] = 1\n"
        "def drop(k):\n"
        "    _state.pop(k, None)\n")
    result, _ = run_fixture(tmp_path, {"backtest/api.py": src,
                                       "longseries/api.py": src})
    hits = sorted(f.path for f in result.new if f.code == "STS101")
    assert hits == ["backtest/api.py", "longseries/api.py"], \
        [f.render() for f in result.new]


# ---------------------------------------------------------------------------
# STS102 — lock-order cycles
# ---------------------------------------------------------------------------

def test_sts102_cross_module_cycle(tmp_path):
    # module a holds A then calls into b (which takes B); module b holds
    # B then calls back into a (which takes A): an ABBA cycle only a
    # whole-tree call-through analysis can see
    a = THREAD_HEADER + (
        "from utils.b import take_b\n"
        "_a = threading.Lock()\n"
        "def take_a():\n"
        "    with _a:\n"
        "        pass\n"
        "def hold_a_then_b():\n"
        "    with _a:\n"
        "        take_b()\n")
    b = THREAD_HEADER + (
        "from utils.a import take_a\n"
        "_b = threading.Lock()\n"
        "def take_b():\n"
        "    with _b:\n"
        "        pass\n"
        "def hold_b_then_a():\n"
        "    with _b:\n"
        "        take_a()\n")
    result, _ = run_fixture(tmp_path, {"utils/a.py": a, "utils/b.py": b})
    hits = [f for f in result.new if f.code == "STS102"]
    assert len(hits) == 1, [f.render() for f in result.new]
    assert "a._a" in hits[0].message and "b._b" in hits[0].message


def test_sts102_consistent_order_clean(tmp_path):
    src = THREAD_HEADER + (
        "_a = threading.Lock()\n"
        "_b = threading.Lock()\n"
        "def one():\n"
        "    with _a:\n"
        "        with _b:\n"
        "            pass\n"
        "def two():\n"
        "    with _a:\n"
        "        with _b:\n"
        "            pass\n")
    result, _ = run_fixture(tmp_path, {"utils/u.py": src})
    assert [f for f in result.new if f.code == "STS102"] == []


# ---------------------------------------------------------------------------
# STS103 — blocking under a lock
# ---------------------------------------------------------------------------

def test_sts103_callback_and_call_through(tmp_path):
    src = THREAD_HEADER + (
        "_lock = threading.Lock()\n"
        "def _flush():\n"
        "    time.sleep(1)\n"
        "def drain(on_progress):\n"
        "    with _lock:\n"
        "        on_progress()\n"          # user callback under lock
        "def push():\n"
        "    with _lock:\n"
        "        _flush()\n")              # blocks through a call
    result, _ = run_fixture(tmp_path, {"utils/u.py": src})
    hits = sorted(f.symbol for f in result.new if f.code == "STS103")
    assert hits == ["drain", "push"], \
        [f.render() for f in result.new]


def test_sts103_condition_wait_on_held_lock_exempt(tmp_path):
    # Condition.wait RELEASES the condition's lock while waiting — the
    # one legitimate blocking wait under a with block
    src = THREAD_HEADER + (
        "_cv = threading.Condition()\n"
        "def park():\n"
        "    with _cv:\n"
        "        _cv.wait(0.1)\n")
    result, _ = run_fixture(tmp_path, {"utils/u.py": src})
    assert [f for f in result.new if f.code == "STS103"] == []


def test_sts103_string_join_not_blocking(tmp_path):
    src = THREAD_HEADER + (
        "_lock = threading.Lock()\n"
        "def render(parts):\n"
        "    with _lock:\n"
        "        return ', '.join(parts)\n")
    result, _ = run_fixture(tmp_path, {"utils/u.py": src})
    assert [f for f in result.new if f.code == "STS103"] == []


def test_sts103_work_outside_lock_clean(tmp_path):
    src = THREAD_HEADER + (
        "_lock = threading.Lock()\n"
        "def tick():\n"
        "    with _lock:\n"
        "        x = 1\n"
        "    time.sleep(0.1)\n"
        "    return x\n")
    result, _ = run_fixture(tmp_path, {"utils/u.py": src})
    assert [f for f in result.new if f.code == "STS103"] == []


# ---------------------------------------------------------------------------
# STS104 — thread lifecycle
# ---------------------------------------------------------------------------

def test_sts104_daemon_and_joined_threads_clean(tmp_path):
    src = THREAD_HEADER + (
        "def work():\n"
        "    try:\n"
        "        time.sleep(0)\n"
        "    except Exception:\n"
        "        pass\n"
        "def spawn_daemon():\n"
        "    t = threading.Thread(target=work, daemon=True)\n"
        "    t.start()\n"
        "def spawn_joined():\n"
        "    t = threading.Thread(target=work)\n"
        "    t.start()\n"
        "    t.join()\n")
    result, _ = run_fixture(tmp_path, {"utils/u.py": src})
    assert [f for f in result.new if f.code == "STS104"] == []


def test_sts104_event_with_waiter_clean_without_flagged(tmp_path):
    src = THREAD_HEADER + (
        "def ok():\n"
        "    e = threading.Event()\n"
        "    e.set()\n"
        "    e.wait(0.1)\n"
        "def dead():\n"
        "    done = threading.Event()\n"
        "    done.set()\n")
    result, _ = run_fixture(tmp_path, {"utils/u.py": src})
    hits = [f for f in result.new if f.code == "STS104"]
    assert len(hits) == 1 and hits[0].symbol == "dead" \
        and "done" in hits[0].message


def test_sts104_raise_through_target_flagged(tmp_path):
    src = THREAD_HEADER + (
        "def risky():\n"
        "    open('/tmp/x')\n"            # can raise, no try
        "def contained():\n"
        "    try:\n"
        "        open('/tmp/x')\n"
        "    except BaseException:\n"
        "        pass\n"
        "def spawn():\n"
        "    t = threading.Thread(target=risky, daemon=True)\n"
        "    t.start()\n"
        "    u = threading.Thread(target=contained, daemon=True)\n"
        "    u.start()\n")
    result, _ = run_fixture(tmp_path, {"utils/u.py": src})
    hits = [f for f in result.new if f.code == "STS104"]
    assert len(hits) == 1 and "risky" in hits[0].message


# ---------------------------------------------------------------------------
# the concurrency model on the real tree (anti-vacuousness, as for the
# tracer model below)
# ---------------------------------------------------------------------------

def test_real_tree_concurrency_model_sanity():
    import ast
    from tools.sts_lint.analysis import (ModuleModel, Project,
                                         concurrency_model)
    mods = []
    for rel in ("spark_timeseries_tpu/engine.py",
                "spark_timeseries_tpu/utils/telemetry.py",
                "spark_timeseries_tpu/utils/metrics.py"):
        path = os.path.join(REPO, rel)
        src = open(path).read()
        mods.append(ModuleModel(path, rel, src, ast.parse(src)))
    model = concurrency_model(Project(mods))
    lock_ids = set(model.module_locks.values())
    assert {"engine._jit_lock", "engine._default_lock",
            "telemetry._jobs_lock", "telemetry._server_lock"} <= lock_ids
    assert "_lock" in model.class_locks[("engine", "FitEngine")]
    assert "_lock" in model.class_locks[("metrics", "MetricsRegistry")]
    assert "_lock" in model.class_locks[("telemetry", "JobProgress")]
    # the watchdog worker is modeled as a thread entry, daemon=True
    entries = {fi.qualname for fi in model.thread_entries}
    assert "FitEngine.stream_fit._with_deadline._run" in entries
    assert all(s.daemon for s in model.spawns), \
        [(s.fi.qualname, s.daemon) for s in model.spawns]


# ---------------------------------------------------------------------------
# suppression + baseline mechanics
# ---------------------------------------------------------------------------

def test_noqa_suppresses_matching_code_only(tmp_path):
    src = HEADER + (
        "def f(n):\n"
        "    a = jnp.zeros((n,))  # sts: noqa[STS003]\n"
        "    b = jnp.zeros((n,))  # sts: noqa[STS001]\n"   # wrong code
        "    c = jnp.zeros((n,))  # sts: noqa\n"           # bare: all
        "    return a, b, c\n")
    result, _ = run_fixture(tmp_path, {"ops/a.py": src})
    assert len(result.suppressed) == 2
    assert [f.line for f in result.new] == [9]


def test_baseline_roundtrip(tmp_path):
    files = {"ops/a.py": HEADER + "def f(n):\n    return jnp.zeros((n,))\n"}
    result, sources = run_fixture(tmp_path, files)
    assert result.exit_code == 1
    bl_path = str(tmp_path / "baseline.json")
    entries = write_baseline(bl_path, result, sources)
    assert sum(entries.values()) == 1

    # baselined run is green...
    r2, _ = run_fixture(tmp_path, files, baseline=load_baseline(bl_path))
    assert r2.exit_code == 0
    assert len(r2.baselined) == 1 and r2.new == []

    # ...but a NEW copy of the same pattern still fails
    files["ops/a.py"] += "def g(n):\n    return jnp.zeros((n,))\n"
    r3, _ = run_fixture(tmp_path, files, baseline=load_baseline(bl_path))
    assert r3.exit_code == 1
    assert len(r3.new) == 1 and len(r3.baselined) == 1


def test_baseline_survives_line_drift(tmp_path):
    files = {"ops/a.py": HEADER + "def f(n):\n    return jnp.zeros((n,))\n"}
    result, sources = run_fixture(tmp_path, files)
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(bl_path, result, sources)
    # unrelated edits above the finding must not resurrect it
    files["ops/a.py"] = HEADER + "\n\nX = 1\n\n" + \
        "def f(n):\n    return jnp.zeros((n,))\n"
    r2, _ = run_fixture(tmp_path, files, baseline=load_baseline(bl_path))
    assert r2.exit_code == 0 and len(r2.baselined) == 1


# ---------------------------------------------------------------------------
# JSON report schema + CLI + the shipped tree
# ---------------------------------------------------------------------------

def test_json_report_schema(tmp_path):
    result, _ = run_fixture(tmp_path, {"ops/a.py": SEEDED["STS003"]})
    report = result.to_json()
    assert report["version"] == 1 and report["tool"] == "sts-lint"
    assert set(report["rules"]) == set(RULES)
    for meta in report["rules"].values():
        assert meta["name"] and meta["summary"]
    s = report["summary"]
    assert {"findings", "suppressed", "baselined", "files_scanned",
            "by_code"} <= set(s)
    assert s["findings"] == len(report["findings"]) > 0
    f = report["findings"][0]
    assert {"code", "path", "line", "col", "symbol", "message",
            "status"} <= set(f)


def test_cli_json_out_and_exit_codes(tmp_path, capsys):
    fx = tmp_path / "ops"
    fx.mkdir()
    (fx / "a.py").write_text(SEEDED["STS001"])
    out = str(tmp_path / "report.json")
    rc = lint_main([str(tmp_path), "--root", str(tmp_path),
                    "--no-baseline", "--json", out, "-q"])
    assert rc == 1
    report = json.loads(open(out).read())
    assert report["summary"]["findings"] >= 1
    capsys.readouterr()


def test_cli_write_baseline_then_green(tmp_path, capsys):
    fx = tmp_path / "ops"
    fx.mkdir()
    (fx / "a.py").write_text(SEEDED["STS004"])
    bl = str(tmp_path / "bl.json")
    assert lint_main([str(tmp_path), "--root", str(tmp_path),
                      "--baseline", bl, "--write-baseline"]) == 0
    assert lint_main([str(tmp_path), "--root", str(tmp_path),
                      "--baseline", bl, "-q"]) == 0
    capsys.readouterr()


def test_shipped_tree_is_clean_and_baseline_empty():
    """`make lint` must exit 0 on the shipped tree, and the debt ledger
    must be EMPTY for the tracer-safety/host-sync rules AND the
    concurrency rules (it is in fact empty for every rule — all
    accepted findings are justified in-source via noqa)."""
    from tools.sts_lint import DEFAULT_BASELINE
    baseline = load_baseline(DEFAULT_BASELINE)
    for fp in baseline:
        assert not fp.startswith(TRACER_SAFETY_RULES), \
            f"tracer-safety finding in baseline: {fp}"
        assert not fp.startswith(CONCURRENCY_RULES), \
            f"concurrency finding in baseline: {fp}"
    result, _ = lint_paths([os.path.join(REPO, "spark_timeseries_tpu")],
                           root=REPO, baseline=baseline)
    assert result.parse_errors == []
    assert result.new == [], [f.render() for f in result.new]
    # the tracer-safety promise specifically: nothing suppressed either
    assert [f for f in result.suppressed
            if f.code in TRACER_SAFETY_RULES] == []


# ---------------------------------------------------------------------------
# bench_gate: the static-analysis zero-baseline gates (ISSUE 14)
# ---------------------------------------------------------------------------

def _round_file(tmp_path, n, value, sa=None):
    m = {"spans": {}}
    if sa is not None:
        m["static_analysis"] = sa
    headline = {"metric": "demo", "value": value, "unit": "series/sec",
                "platform": "cpu", "metrics": m}
    wrapper = {"n": n, "cmd": "python bench.py", "rc": 0, "tail": "",
               "parsed": headline}
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(wrapper))


def test_gate_zero_baselines_lint_findings_and_contracts(tmp_path):
    from tools import bench_gate

    clean = {"findings": 0, "suppressed": 11, "baselined": 0,
             "contracts_checked": 45, "contracts_failed": 0}
    for n in (1, 2, 3):
        _round_file(tmp_path, n, 1000.0, sa=clean)
    _round_file(tmp_path, 4, 1000.0,
                sa={"findings": 2, "suppressed": 11, "baselined": 0,
                    "contracts_checked": 45, "contracts_failed": 1})
    verdict = bench_gate.evaluate(bench_gate.load_history(str(tmp_path)))
    rows = {r["metric"]: r for r in verdict["rows"]}
    assert verdict["status"] == "regressed"
    assert rows["lint_findings"]["status"] == "REGRESSED"
    assert rows["contracts_failed"]["status"] == "REGRESSED"
    assert rows["lint_findings"]["delta_pct"] is None   # 0 baseline
    # block present + findings key absent = a measured lint 0 (house
    # gate style); contracts need contracts_checked > 0 to count
    got = bench_gate.extract_metrics(
        {"value": 1.0, "metrics": {"static_analysis": {
            "suppressed": 11, "contracts_checked": 45}}})
    assert got["lint_findings"] == 0.0
    assert got["contracts_failed"] == 0.0
    # a crashed sub-check must NOT read as a clean zero
    got = bench_gate.extract_metrics(
        {"value": 1.0, "metrics": {"static_analysis": {
            "lint_error": "boom", "contracts_error": "boom"}}})
    assert "lint_findings" not in got and "contracts_failed" not in got
    # a SKIPPED contract sweep (BENCH_CONTRACT_FAMILIES="" writes 0/0)
    # is absence of evidence, not a clean zero
    got = bench_gate.extract_metrics(
        {"value": 1.0, "metrics": {"static_analysis": {
            "findings": 0, "contracts_checked": 0,
            "contracts_failed": 0}}})
    assert got["lint_findings"] == 0.0
    assert "contracts_failed" not in got
    # pre-PR-4 rounds without the block: no fabricated zeros
    got = bench_gate.extract_metrics({"value": 1.0, "metrics": {}})
    assert "lint_findings" not in got and "contracts_failed" not in got


def test_gate_passes_on_clean_static_history(tmp_path):
    from tools import bench_gate

    clean = {"findings": 0, "contracts_checked": 45,
             "contracts_failed": 0}
    for n in (1, 2, 3, 4):
        _round_file(tmp_path, n, 1000.0, sa=clean)
    assert bench_gate.main(["--dir", str(tmp_path)]) == 0


# ---------------------------------------------------------------------------
# jax_audit: the pre-upgrade API-touchpoint inventory (ISSUE 14
# satellite; ROADMAP item 2 prerequisite)
# ---------------------------------------------------------------------------

def test_jax_audit_categorizes_fixture(tmp_path):
    from tools.jax_audit import audit_paths

    src = (
        "import jax\n"
        "from jax import monitoring\n"
        "from jax.experimental import pallas as pl\n"
        "from jax.experimental.shard_map import shard_map\n"
        "def hooks():\n"
        "    monitoring.register_event_listener(None)\n"
        "    jax.profiler.start_trace('/tmp/t')\n"
        "    jax.config.update('jax_compilation_cache_dir', '/tmp/c')\n"
        "def kernel():\n"
        "    return pl.pallas_call\n")
    (tmp_path / "m.py").write_text(src)
    report = audit_paths([str(tmp_path)], root=str(tmp_path))
    assert report["parse_errors"] == []
    cats = {t["category"] for t in report["touchpoints"]}
    assert {"monitoring", "profiler", "compilation_cache", "shard_map",
            "pallas"} <= cats
    assert report["counts"]["monitoring"] >= 1
    by_cat = {t["category"]: t for t in report["touchpoints"]}
    assert by_cat["profiler"]["symbol"] == "hooks"
    for t in report["touchpoints"]:
        assert {"category", "path", "line", "symbol", "detail"} <= set(t)


def test_jax_audit_real_tree_finds_known_touchpoints():
    from tools.jax_audit import audit_paths

    report = audit_paths([os.path.join(REPO, "spark_timeseries_tpu")],
                         root=REPO)
    where = {(t["path"], t["category"]) for t in report["touchpoints"]}
    # the sites ROADMAP item 2 names: metrics' jax.monitoring hooks,
    # the engine's compilation-cache config, pallas/shard_map in ops
    assert ("spark_timeseries_tpu/utils/metrics.py",
            "monitoring") in where
    assert ("spark_timeseries_tpu/engine.py",
            "compilation_cache") in where
    assert ("spark_timeseries_tpu/ops/pallas_arma.py", "pallas") in where
    assert ("spark_timeseries_tpu/ops/pallas_arma.py",
            "shard_map") in where
    assert report["counts"]["monitoring"] >= 1
    assert sum(report["counts"].values()) \
        == len(report["touchpoints"]) > 0


def test_jax_audit_cli_json(tmp_path, capsys):
    from tools.jax_audit import main as audit_main

    (tmp_path / "m.py").write_text("from jax.experimental import pallas\n")
    out = str(tmp_path / "audit.json")
    rc = audit_main([str(tmp_path), "--root", str(tmp_path),
                     "--json", out])
    assert rc == 0
    report = json.loads(open(out).read())
    assert report["tool"] == "jax-audit"
    assert report["counts"]["pallas"] == 1
    capsys.readouterr()


def test_real_tree_traced_model_sanity():
    """The semantic model must actually mark the known traced surfaces
    of the real tree — guards against the analysis silently going
    vacuous (every rule 'passing' because nothing is traced)."""
    import ast
    from tools.sts_lint.analysis import ModuleModel, Project
    path = os.path.join(REPO, "spark_timeseries_tpu", "ops",
                        "optimize.py")
    src = open(path).read()
    mod = ModuleModel(path, "ops/optimize.py", src, ast.parse(src))
    Project([mod])
    traced = {fi.qualname for fi in mod.functions if fi.traced}
    for expected in ("minimize_bfgs.solve_one", "_minimize_lm_one.body",
                     "_minimize_box_one.body.bt_body"):
        assert expected in traced, f"{expected} not marked traced"
    transformers = {fi.name: fi.transformer_params
                    for fi in mod.functions if fi.transformer_params}
    assert "fn" in transformers.get("minimize_bfgs", set())
    assert "residual_fn" in transformers.get("minimize_least_squares",
                                             set())


# ---------------------------------------------------------------------------
# STS201–205: the host-boundary tier (ISSUE 19)
# ---------------------------------------------------------------------------
#
# Hot-path scoping is part of the contract, so these fixtures write to
# hot-path relpaths ("engine.py", "statespace/serving.py") instead of
# the ops/ path the other tiers seed.

SEEDED_BOUNDARY = {
    # unsanctioned float() of a compiled-program output
    "STS201": HEADER + (
        "step = jax.jit(lambda x: x * 2)\n"
        "def drive(x):\n"
        "    y = step(x)\n"
        "    return float(y)\n"),
    # jit construction inside the loop body
    "STS202": HEADER + (
        "def sweep(xs):\n"
        "    outs = []\n"
        "    for x in xs:\n"
        "        f = jax.jit(lambda v: v + 1)\n"
        "        outs.append(f(x))\n"
        "    return outs\n"),
    # the pad-slice pattern: per-iteration device-output slice
    "STS203": HEADER + (
        "step = jax.jit(lambda x: x)\n"
        "def gather(xs):\n"
        "    out = step(xs)\n"
        "    res = []\n"
        "    for i in range(4):\n"
        "        res.append(np.asarray(out[i * 8:(i + 1) * 8]))\n"
        "    return res\n"),
    # read of a donated buffer after dispatch
    "STS204": HEADER + (
        "upd = jax.jit(lambda s, x: s + x, donate_argnums=(0,))\n"
        "def tick(state, x):\n"
        "    out = upd(state, x)\n"
        "    return out, state.sum()\n"),
    # dispatch → host transform → dispatch (the fusion inventory); the
    # unsanctioned np.asarray in the middle is itself an STS201, which
    # is what makes this seeded tree exit nonzero (STS205 alone is
    # advice and never gates)
    "STS205": HEADER + (
        "f1 = jax.jit(lambda x: x + 1)\n"
        "f2 = jax.jit(lambda x: x * 2)\n"
        "def chain(x):\n"
        "    a = f1(x)\n"
        "    b = np.asarray(a) * 2\n"
        "    return f2(jnp.asarray(b))\n"),
}


@pytest.mark.parametrize("code", sorted(SEEDED_BOUNDARY))
def test_seeded_boundary_violation_fails_lint(tmp_path, code):
    result, _ = run_fixture(tmp_path,
                            {"engine.py": SEEDED_BOUNDARY[code]})
    found = codes(result) + sorted({f.code for f in result.advice})
    assert code in found, \
        f"rule {code} missed its seeded violation; found {found}"
    assert result.exit_code == 1


def test_boundary_rules_scope_to_hot_path(tmp_path):
    """The same violations OFF the hot path (an ops/ module) are out of
    the STS200 tier's domain — host orchestration there is someone
    else's business."""
    for code, src in SEEDED_BOUNDARY.items():
        result, _ = run_fixture(tmp_path, {"ops/host_tools.py": src},
                                select=list(HOST_BOUNDARY_RULES))
        assert codes(result) == [], \
            f"{code} fired off the hot path: {codes(result)}"


def test_sts205_is_advice_severity(tmp_path):
    """STS205 never gates and never baselines: a chain inside a
    sanctioned site lints green, but the inventory still lists it."""
    src = HEADER + (
        "f1 = jax.jit(lambda x: x + 1)\n"
        "f2 = jax.jit(lambda x: x * 2)\n"
        "class FitEngine:\n"
        "    def stream_fit(self, x):\n"
        "        a = f1(x)\n"
        "        b = np.asarray(a) * 2\n"
        "        return f2(jnp.asarray(b))\n")
    result, sources = run_fixture(tmp_path, {"engine.py": src})
    assert result.exit_code == 0
    assert codes(result) == []
    assert {f.code for f in result.advice} == {"STS205"}
    assert result.summary()["advice"] == 1
    # advice must not be written into the debt ledger
    bl_path = str(tmp_path / "bl.json")
    write_baseline(bl_path, result, sources)
    assert load_baseline(bl_path) == {}


def test_sanctioned_materialize_sites_are_clean(tmp_path):
    """FP boundary: the places results are SUPPOSED to land on the host
    (engine chunk collection, serving delivery) — including host-side
    slicing of an already-materialized array outside a loop."""
    src = HEADER + (
        "step = jax.jit(lambda x: x)\n"
        "class FitEngine:\n"
        "    def stream_fit(self, xs, n):\n"
        "        out = step(xs)\n"
        "        host = np.asarray(out)\n"
        "        return host[:n]\n")
    result, _ = run_fixture(tmp_path, {"engine.py": src},
                            select=["STS201", "STS202", "STS203",
                                    "STS204"])
    assert codes(result) == []


def test_device_slice_outside_loop_not_sts203(tmp_path):
    """FP boundary: a ONE-TIME device slice outside any loop is the
    pad-strip idiom, not the per-iteration pad-slice regression —
    STS203 stays quiet (STS201 still governs where it lands)."""
    src = HEADER + (
        "step = jax.jit(lambda x: x)\n"
        "def deliver(xs, n):\n"
        "    out = step(xs)\n"
        "    return np.asarray(out[:n])\n")
    result, _ = run_fixture(tmp_path, {"engine.py": src},
                            select=["STS203"])
    assert codes(result) == []


def test_tuple_indexing_not_sts203(tmp_path):
    """FP boundary: integer/tuple indexing of a compiled result
    (``out[0]``) is structure access, not the pad-slice pattern."""
    src = HEADER + (
        "step = jax.jit(lambda x: (x, x.sum()))\n"
        "def unpack(xs):\n"
        "    res = []\n"
        "    for x in xs:\n"
        "        out = step(x)\n"
        "        res.append(np.asarray(out[0]))\n"
        "    return res\n")
    result, _ = run_fixture(tmp_path, {"engine.py": src},
                            select=["STS203"])
    assert codes(result) == []


def test_block_until_ready_in_bench_timing_clean(tmp_path):
    """FP boundary: `.block_until_ready()` in timing/bench code off the
    hot path is the CORRECT idiom (async dispatch would otherwise lie
    to the clock) — no STS201."""
    src = HEADER + (
        "fit = jax.jit(lambda x: x * 2)\n"
        "def time_fit(x):\n"
        "    t0 = time.perf_counter()\n"
        "    fit(x).block_until_ready()\n"
        "    return time.perf_counter() - t0\n")
    result, _ = run_fixture(tmp_path, {"benchmarks/timing.py": src},
                            select=list(HOST_BOUNDARY_RULES))
    assert codes(result) == []


def test_host_loop_over_host_values_clean(tmp_path):
    """FP boundary: loops over plain host arrays in a hot-path module
    carry no device taint — nothing to flag."""
    src = HEADER + (
        "def plan(groups):\n"
        "    total = 0\n"
        "    for g in groups:\n"
        "        total += int(np.asarray(g).sum())\n"
        "    return total\n")
    result, _ = run_fixture(tmp_path, {"statespace/serving.py": src},
                            select=list(HOST_BOUNDARY_RULES))
    assert codes(result) == []


def test_boundary_noqa_suppression(tmp_path):
    src = HEADER + (
        "step = jax.jit(lambda x: x * 2)\n"
        "def drive(x):\n"
        "    y = step(x)\n"
        "    return float(y)  # sts: noqa[STS201] — proven cold path\n")
    result, _ = run_fixture(tmp_path, {"engine.py": src},
                            select=["STS201"])
    assert codes(result) == []
    assert len(result.suppressed) == 1


def test_shipped_tree_boundary_tier_clean_and_inventory_burned_down():
    """ISSUE 19 pinned 0 gating STS200 findings and a NON-EMPTY STS205
    inventory (the fusion evidence base); ISSUE 20 consumed that
    inventory — the whole-pipeline-fusion PR eliminated every ranked
    chain (device-resident combine accumulators, async no-materialize
    warmup), so HEAD now pins the inventory EMPTY and names the two
    burned-down chains so a reintroduction fails by symbol."""
    from tools.sts_lint import DEFAULT_BASELINE
    baseline = load_baseline(DEFAULT_BASELINE)
    for fp in baseline:
        assert not fp.startswith(tuple(HOST_BOUNDARY_RULES)), \
            f"host-boundary finding in baseline: {fp}"
    result, _ = lint_paths([os.path.join(REPO, "spark_timeseries_tpu")],
                           root=REPO, baseline=baseline,
                           select=list(HOST_BOUNDARY_RULES))
    assert result.parse_errors == []
    assert result.new == [], [f.render() for f in result.new]
    inventory = {(f.path, f.symbol) for f in result.advice}
    gone = {"combine_segments", "FleetScheduler.warmup"}
    assert not gone & {s for _, s in inventory}, \
        "a burned-down STS205 chain reappeared"
    assert not inventory, \
        f"new STS205 chain(s) on the hot path: {sorted(inventory)}"


def test_fleet_dispatch_slice_regression_pinned():
    """The real finding this PR fixed: per-tenant device-output slicing
    inside _dispatch_group/warmup loops.  Scope the sweep to fleet.py
    so a reintroduction fails here by name."""
    path = os.path.join(REPO, "spark_timeseries_tpu", "statespace",
                        "fleet.py")
    result, _ = lint_paths([path], root=REPO, baseline={},
                           select=["STS201", "STS203"])
    assert result.new == [], [f.render() for f in result.new]


# ---------------------------------------------------------------------------
# --explain: the self-documenting catalogue (ISSUE 19 satellite)
# ---------------------------------------------------------------------------

def test_every_rule_has_an_example_pair():
    assert set(EXAMPLES) == set(RULES)
    for code, (bad, good) in EXAMPLES.items():
        assert bad.strip() and good.strip(), f"{code} example empty"


@pytest.mark.parametrize("code", ["STS001", "STS101", "STS203",
                                  "STS205"])
def test_cli_explain_all_tiers(code, capsys):
    rc = lint_main(["--explain", code])
    assert rc == 0
    out = capsys.readouterr().out
    assert code in out
    assert RULES[code].name in out
    assert "Violates:" in out and "Fixed:" in out
    bad, good = EXAMPLES[code]
    assert bad.splitlines()[0].strip() in out
    assert good.splitlines()[0].strip() in out


def test_cli_explain_reports_severity(capsys):
    assert lint_main(["--explain", "sts205"]) == 0   # case-insensitive
    out = capsys.readouterr().out
    assert "[advice]" in out
    assert lint_main(["--explain", "STS203"]) == 0
    assert "[error]" in capsys.readouterr().out


def test_cli_explain_unknown_code_errors(capsys):
    with pytest.raises(SystemExit) as e:
        lint_main(["--explain", "STS999"])
    assert e.value.code == 2
    capsys.readouterr()
