"""Pallas Holt-Winters fused value-and-grad vs the XLA reference.

``ops.pallas_hw.value_and_grad`` must reproduce
``models.holt_winters._hw_sse_value_and_grad`` (which is itself pinned
to autodiff), and the batched box driver must land on the same optimum
as ``minimize_box``'s vmapped path.  Interpreter mode on the CPU test
tier; the same code compiles on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_timeseries_tpu.models import holt_winters
from spark_timeseries_tpu.models.holt_winters import _hw_sse_value_and_grad
from spark_timeseries_tpu.ops import pallas_hw
from spark_timeseries_tpu.ops.optimize import minimize_box


def _seasonal_panel(rng, S, n, period=8, additive=True):
    t = np.arange(n)
    season = np.sin(2 * np.pi * t / period)
    base = 10.0 + 0.05 * t + 2.0 * season
    noise = 0.3 * rng.normal(size=(S, n))
    if additive:
        y = base[None, :] + noise
    else:
        y = base[None, :] * (1.0 + 0.03 * rng.normal(size=(S, n)))
    return y.astype(np.float32)


@pytest.mark.parametrize("model_type", ["additive", "multiplicative"])
def test_value_and_grad_matches_xla(model_type):
    rng = np.random.default_rng(0)
    S, n, m = 150, 70, 8          # off block boundaries; odd step tail
    y = _seasonal_panel(rng, S, n, m, model_type == "additive")
    params = np.clip(0.3 + 0.1 * rng.normal(size=(S, 3)), 0.05, 0.95) \
        .astype(np.float32)

    f_pl, g_pl = pallas_hw.value_and_grad(
        jnp.asarray(params), jnp.asarray(y), m, model_type,
        interpret=True)
    f_ref, g_ref = jax.vmap(
        lambda p, s: _hw_sse_value_and_grad(p, s, m, model_type))(
        jnp.asarray(params), jnp.asarray(y))

    np.testing.assert_allclose(np.asarray(f_pl), np.asarray(f_ref),
                               rtol=3e-4)
    np.testing.assert_allclose(np.asarray(g_pl), np.asarray(g_ref),
                               rtol=3e-3, atol=3e-1)


def test_box_driver_matches_vmapped_minimize_box():
    rng = np.random.default_rng(1)
    S, n, m = 64, 64, 8
    y = _seasonal_panel(rng, S, n, m)
    x0 = jnp.broadcast_to(jnp.asarray([0.3, 0.1, 0.1], jnp.float32),
                          (S, 3))

    x_pl, f_pl, done_pl, _ = pallas_hw.fit_box(
        x0, jnp.asarray(y), m, "additive", tol=1e-6, max_iter=200,
        interpret=True)

    res = minimize_box(
        lambda p, s: _hw_sse_value_and_grad(p, s, m, "additive")[0],
        x0, 0.0, 1.0, jnp.asarray(y), tol=1e-6, max_iter=200,
        value_and_grad_fn=lambda p, s: _hw_sse_value_and_grad(
            p, s, m, "additive"))

    conv = np.asarray(done_pl) & np.asarray(res.converged)
    assert conv.mean() > 0.8
    f_a, f_b = np.asarray(f_pl)[conv], np.asarray(res.fun)[conv]
    rel_gap = np.abs(f_a - f_b) / np.maximum(np.minimum(f_a, f_b), 1e-9)
    assert np.mean(rel_gap < 1e-3) >= 0.95, np.sort(rel_gap)[-5:]
    dx = np.max(np.abs(np.asarray(x_pl) - np.asarray(res.x)), axis=1)[conv]
    assert np.median(dx) < 2e-2 and np.mean(dx < 5e-2) >= 0.9


def test_fit_routes_through_pallas_hw_when_forced(monkeypatch):
    # STS_PALLAS_HW=1 (the driver's OWN opt-in flag — the shared
    # STS_PALLAS must NOT route the unmeasured driver) pushes
    # holt_winters.fit through the kernel driver end-to-end; the spy
    # proves it (dtype alone cannot)
    rng = np.random.default_rng(2)
    S, n, m = 24, 56, 8
    y = _seasonal_panel(rng, S, n, m)

    m_xla = holt_winters.fit(jnp.asarray(y), m, "additive", max_iter=150)

    calls = []
    real = pallas_hw.fit_box
    monkeypatch.setattr(pallas_hw, "fit_box",
                        lambda *a, **kw: calls.append(1) or real(*a, **kw))
    monkeypatch.setenv("STS_PALLAS", "1")       # shared flag: NOT enough
    holt_winters.fit(jnp.asarray(y), m, "additive", max_iter=150)
    assert not calls
    monkeypatch.setenv("STS_PALLAS_HW", "1")
    m_pl = holt_winters.fit(jnp.asarray(y), m, "additive", max_iter=150)
    assert len(calls) == 1

    conv = np.asarray(m_xla.diagnostics.converged) \
        & np.asarray(m_pl.diagnostics.converged)
    assert conv.mean() > 0.8
    for attr in ("alpha", "beta", "gamma"):
        d = np.abs(np.asarray(getattr(m_pl, attr), np.float64)
                   - np.asarray(getattr(m_xla, attr), np.float64))[conv]
        assert np.median(d) < 2e-2, (attr, np.sort(d)[-3:])

    # ragged panels keep the (mask-aware) XLA path even when forced
    calls.clear()
    y_rag = y.copy()
    y_rag[0, :5] = np.nan
    m_rag = holt_winters.fit(jnp.asarray(y_rag), m, "additive",
                             max_iter=50)
    assert not calls
    assert np.isfinite(np.asarray(m_rag.alpha)).all()
