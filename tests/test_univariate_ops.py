"""Univariate kernels vs scalar reference semantics (ref FillSuite /
UnivariateTimeSeriesSuite contracts), exercised both single-series and batched."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_timeseries_tpu.ops import (
    autocorr,
    differences_at_lag,
    differences_of_order_d,
    downsample,
    fill_linear,
    fill_nearest,
    fill_next,
    fill_previous,
    fill_spline,
    fillts,
    first_not_nan,
    inverse_differences_at_lag,
    inverse_differences_of_order_d,
    lag_matrix,
    lag_matrix_multi,
    last_not_nan,
    ols,
    price2ret,
    quotients,
    roll_mean,
    roll_sum,
    trim_leading,
    trim_trailing,
    upsample,
)

nan = np.nan


def arr(*vals):
    return jnp.asarray(vals, dtype=jnp.float64)


class TestFills:
    def test_fill_previous(self):
        # ref: 1 NaN NaN 2 NaN -> 1 1 1 2 2
        out = fill_previous(arr(1, nan, nan, 2, nan))
        assert list(np.asarray(out)) == [1, 1, 1, 2, 2]

    def test_fill_previous_leading_nan(self):
        out = np.asarray(fill_previous(arr(nan, 3, nan)))
        assert np.isnan(out[0]) and out[1] == 3 and out[2] == 3

    def test_fill_next(self):
        # ref: 1 NaN NaN 2 NaN -> 1 2 2 2 NaN
        out = np.asarray(fill_next(arr(1, nan, nan, 2, nan)))
        assert list(out[:4]) == [1, 2, 2, 2] and np.isnan(out[4])

    def test_fill_nearest(self):
        # ref FillSuite: ties prefer next
        out = np.asarray(fill_nearest(arr(1, nan, nan, nan, 2)))
        assert list(out) == [1, 1, 2, 2, 2]

    def test_fill_nearest_edges(self):
        out = np.asarray(fill_nearest(arr(nan, nan, 5, nan)))
        assert list(out) == [5, 5, 5, 5]

    def test_fill_linear(self):
        out = np.asarray(fill_linear(arr(1, nan, nan, 4, nan)))
        np.testing.assert_allclose(out[:4], [1, 2, 3, 4])
        assert np.isnan(out[4])  # trailing NaN untouched

    def test_fill_linear_leading_untouched(self):
        out = np.asarray(fill_linear(arr(nan, 2, nan, 4)))
        assert np.isnan(out[0]) and out[2] == 3

    def test_fill_spline_matches_knots(self):
        x = np.array([1.0, nan, 9.0, nan, 25.0, nan])
        out = fill_spline(x)
        # knots preserved; interior filled; trailing outside knots untouched
        assert out[0] == 1 and out[2] == 9 and out[4] == 25
        assert not np.isnan(out[1]) and not np.isnan(out[3])
        assert np.isnan(out[5])

    def test_fill_spline_batched_patterns(self):
        # rows: fully observed (skipped), two sharing one NaN pattern (one
        # vectorized spline call), one 2-knot (linear degenerate), one
        # all-NaN (untouched) — the panel-scale grouping paths
        rows = np.array([
            [1.0, 2.0, 3.0, 4.0, 5.0, 6.0],
            [1.0, nan, 9.0, nan, 25.0, nan],
            [2.0, nan, 18.0, nan, 50.0, nan],
            [nan, 4.0, nan, nan, 10.0, nan],
            [nan, nan, nan, nan, nan, nan],
        ])
        out = fill_spline(rows)
        np.testing.assert_allclose(out[0], rows[0])
        for r in (1, 2):
            # same answers as the single-row path
            np.testing.assert_allclose(out[r], fill_spline(rows[r]),
                                       equal_nan=True)
        np.testing.assert_allclose(out[3, 1:5], [4.0, 6.0, 8.0, 10.0])
        assert np.isnan(out[3, 0]) and np.isnan(out[3, 5])
        assert np.all(np.isnan(out[4]))

    def test_fillts_dispatch_and_batch(self):
        x = jnp.stack([arr(1, nan, 3), arr(nan, 2, nan)])
        out = np.asarray(fillts(x, "previous"))
        assert out[0, 1] == 1 and np.isnan(out[1, 0]) and out[1, 2] == 2
        with pytest.raises(ValueError):
            fillts(x, "bogus")

    def test_fill_under_jit_vmap(self):
        x = jnp.stack([arr(1, nan, 2, nan), arr(nan, 5, nan, 7)])
        jit_fill = jax.jit(jax.vmap(fill_linear))
        out = np.asarray(jit_fill(x))
        assert out[0, 1] == 1.5


class TestTrim:
    def test_first_last_not_nan(self):
        x = arr(nan, nan, 1, 2, nan)
        assert int(first_not_nan(x)) == 2
        assert int(last_not_nan(x)) == 4
        assert int(first_not_nan(arr(nan, nan))) == 2
        assert int(last_not_nan(arr(nan, nan))) == 0

    def test_trim(self):
        x = np.array([nan, 1.0, 2.0, nan])
        out = trim_leading(x)
        assert out[0] == 1.0 and len(out) == 3
        out2 = trim_trailing(x)
        assert len(out2) == 3 and np.isnan(out2[0])


class TestDifferencing:
    def test_diff_at_lag(self):
        x = arr(1, 2, 4, 7, 11)
        out = np.asarray(differences_at_lag(x, 1))
        assert list(out) == [1, 1, 2, 3, 4]

    def test_diff_inverse_roundtrip(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(30))
        for lag in (1, 2, 5):
            d = differences_at_lag(x, lag)
            back = inverse_differences_at_lag(d, lag)
            np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=1e-10)

    def test_diff_inverse_roundtrip_start_index(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(20))
        d = differences_at_lag(x, 3, 7)
        back = inverse_differences_at_lag(d, 3, 7)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=1e-10)

    def test_order_d_roundtrip(self):
        # ref ARIMASuite differencing property
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(50))
        for d in (1, 2, 3):
            diffed = differences_of_order_d(x, d)
            back = inverse_differences_of_order_d(diffed, d)
            np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=1e-9)

    def test_order_d_matches_scalar_loop(self):
        # independent scalar implementation of the reference recursion
        rng = np.random.RandomState(3)
        x = rng.randn(25)

        def scalar_diff(ts, lag, start):
            out = ts.copy()
            for i in range(len(ts)):
                out[i] = ts[i] - ts[i - lag] if i >= start else ts[i]
            return out

        expect = x.copy()
        for i in range(1, 3):
            expect = scalar_diff(expect, 1, i)
        got = np.asarray(differences_of_order_d(jnp.asarray(x), 2))
        np.testing.assert_allclose(got, expect, atol=1e-12)

    def test_batched(self):
        x = jnp.stack([arr(1, 2, 4), arr(10, 20, 40)])
        out = np.asarray(differences_at_lag(x, 1))
        assert list(out[1]) == [10, 10, 20]


class TestMisc:
    def test_quotients_price2ret(self):
        x = arr(1, 2, 4, 8)
        assert list(np.asarray(quotients(x, 1))) == [2, 2, 2]
        assert list(np.asarray(price2ret(x, 2))) == [3, 3]

    def test_autocorr_vs_numpy(self):
        rng = np.random.RandomState(4)
        x = rng.randn(100)
        got = np.asarray(autocorr(jnp.asarray(x), 3))
        for lag in range(1, 4):
            s1, s2 = x[lag:], x[:-lag]
            d1, d2 = s1 - s1.mean(), s2 - s2.mean()
            expect = (d1 * d2).sum() / np.sqrt((d1 ** 2).sum() * (d2 ** 2).sum())
            np.testing.assert_allclose(got[lag - 1], expect, atol=1e-10)

    def test_down_up_sample(self):
        x = arr(0, 1, 2, 3, 4, 5)
        assert list(np.asarray(downsample(x, 2))) == [0, 2, 4]
        assert list(np.asarray(downsample(x, 2, phase=1))) == [1, 3, 5]
        up = np.asarray(upsample(arr(1, 2), 3))
        assert up[0] == 1 and np.isnan(up[1]) and up[3] == 2 and len(up) == 6
        up0 = np.asarray(upsample(arr(1, 2), 3, use_zero=True))
        assert list(up0) == [1, 0, 0, 2, 0, 0]

    def test_roll_sum_mean(self):
        x = arr(1, 2, 3, 4, 5)
        assert list(np.asarray(roll_sum(x, 2))) == [3, 5, 7, 9]
        assert list(np.asarray(roll_mean(x, 2))) == [1.5, 2.5, 3.5, 4.5]


class TestLagMatrix:
    def test_docstring_example(self):
        # ref UnivariateTimeSeries.scala:30-38
        x = arr(1, 2, 3, 4, 5)
        m = np.asarray(lag_matrix(x, 2, include_original=True))
        expect = np.array([[3, 2, 1], [4, 3, 2], [5, 4, 3]], dtype=float)
        np.testing.assert_array_equal(m, expect)

    def test_without_original(self):
        x = arr(1, 2, 3, 4, 5)
        m = np.asarray(lag_matrix(x, 2))
        expect = np.array([[2, 1], [3, 2], [4, 3]], dtype=float)
        np.testing.assert_array_equal(m, expect)

    def test_multi_column(self):
        # ref Lag.scala:101-106: [a b] lag 2 -> [a_-1 a_-2 b_-1 b_-2]
        a = np.arange(1.0, 6.0)
        b = np.arange(10.0, 60.0, 10.0)
        x = jnp.asarray(np.stack([a, b], axis=-1))
        m = np.asarray(lag_matrix_multi(x, 2))
        assert m.shape == (3, 4)
        np.testing.assert_array_equal(m[0], [2, 1, 20, 10])

    def test_batched(self):
        x = jnp.stack([arr(1, 2, 3, 4), arr(5, 6, 7, 8)])
        m = lag_matrix(x, 1, include_original=True)
        assert m.shape == (2, 3, 2)


class TestOLS:
    def test_recovers_coefficients(self):
        rng = np.random.RandomState(5)
        X = rng.randn(200, 3)
        beta = np.array([2.0, -1.0, 0.5])
        y = X @ beta + 1.5 + rng.randn(200) * 0.01
        res = ols(jnp.asarray(X), jnp.asarray(y), add_intercept=True)
        np.testing.assert_allclose(np.asarray(res.beta), [1.5, 2.0, -1.0, 0.5],
                                   atol=0.01)

    def test_batched_fit(self):
        rng = np.random.RandomState(6)
        X = rng.randn(4, 100, 2)
        betas = rng.randn(4, 2)
        y = np.einsum("bnp,bp->bn", X, betas)
        res = ols(jnp.asarray(X), jnp.asarray(y))
        np.testing.assert_allclose(np.asarray(res.beta), betas, atol=1e-8)
