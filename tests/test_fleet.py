"""Multi-tenant fleet scheduler (ISSUE 12).

The acceptance scenarios live here:

- coalesced multi-tenant ticks are **bitwise** the per-session ticks
  (≥3 tenants sharing one bucket, one device call per round);
- a flooded tenant queue rejects with the named error and recovers the
  moment the flood clears (admission control + backpressure);
- an SLO burn sheds the worst-health tenant onto the cached-forecast
  lane, reads keep answering, and the tenant restores — with catch-up
  replay — when the burn clears, landing bitwise where an unshed
  session would be;
- ``drain``/``adopt`` move a tenant across schedulers and across a
  ``kill -9`` process boundary bitwise (subprocess pair);
- bundle/geometry mismatches refuse with :class:`FleetRestoreMismatch`;
- the warmed tick path stays at **zero** recompiles with the scheduler
  armed (submit → coalesced pump → forecast).

Fast in-process scenarios run in tier-1; the subprocess pair and the
end-to-end shed ladder are ``slow`` and run via ``make verify-fleet``
(the ``fleet`` marker), which ``verify-faults`` also drives under
``STS_FAULT_INJECT=1``.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax.numpy as jnp

from spark_timeseries_tpu import statespace as ss
from spark_timeseries_tpu.models import arima
from spark_timeseries_tpu.statespace.fleet import (
    AdmissionPolicy, FleetRestoreMismatch, FleetSaturated, FleetScheduler,
    TENANT_LIVE, TENANT_SHED, _slots_for)
from spark_timeseries_tpu.statespace.health import (
    LANE_DIVERGED, shed_priority)
from spark_timeseries_tpu.utils import metrics, resilience

pytestmark = pytest.mark.fleet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

S, N_HIST = 4, 120       # one shared panel geometry -> one shared fit
#                          executable and one serving bucket (8) across
#                          the whole module


def _ar2_panel(n_series, n, seed=0):
    rng = np.random.default_rng(seed)
    e = rng.normal(size=(n_series, n + 16))
    y = np.zeros((n_series, n + 16))
    for t in range(2, n + 16):
        y[:, t] = 0.3 + 0.5 * y[:, t - 1] - 0.2 * y[:, t - 2] + e[:, t]
    return y[:, 16:]


def _tenant_fixtures(n_tenants, *, registry=None, seed0=1):
    """(models, hists) for n same-geometry tenants — same (p,d,q) and
    shape, so every session lands in ONE coalescing group."""
    hists = [_ar2_panel(S, N_HIST, seed=seed0 + i)
             for i in range(n_tenants)]
    models = [arima.fit(2, 0, 0, jnp.asarray(h), warn=False)
              for h in hists]
    return models, hists


def _build_fleet(n_tenants, policy=None, *, registry=None, seed0=1):
    reg = registry if registry is not None else metrics.MetricsRegistry()
    models, hists = _tenant_fixtures(n_tenants, seed0=seed0)
    sched = FleetScheduler(policy, registry=reg, auto_pump=False)
    for i, (m, h) in enumerate(zip(models, hists)):
        sess = ss.ServingSession.start(m, h, label=f"t{i}", registry=reg)
        sched.attach(sess)
    return sched, models, hists, reg


# ---------------------------------------------------------------------------
# policy + plumbing
# ---------------------------------------------------------------------------

def test_admission_policy_validation_rejects_nonsense():
    with pytest.raises(ValueError, match="queue_depth"):
        AdmissionPolicy(queue_depth=0).validate()
    with pytest.raises(ValueError, match="on_full"):
        AdmissionPolicy(on_full="banana").validate()
    with pytest.raises(ValueError, match="coalesce_window_s"):
        AdmissionPolicy(coalesce_window_s=-1.0).validate()
    with pytest.raises(ValueError, match="cache_staleness"):
        AdmissionPolicy(cache_staleness=0).validate()
    assert AdmissionPolicy().validate() == AdmissionPolicy()


def test_slots_are_powers_of_two():
    assert [_slots_for(n) for n in (1, 2, 3, 4, 5, 8, 9)] \
        == [1, 2, 4, 4, 8, 8, 16]


def test_shed_priority_ranks_diverged_then_drifted_then_suspect():
    assert shed_priority(np.array([2, 2, 0, 1])) == (2, 0, 1)
    assert shed_priority(np.array([0, 0])) == (0, 0, 0)
    # the quality tier's drifted code ranks between diverged and suspect
    assert shed_priority(np.array([3, 3, 1])) == (0, 2, 1)
    # lexicographic: one diverged lane outranks any number of drifted/
    # suspect lanes, one drifted outranks any number of suspects
    assert shed_priority(np.array([2])) > shed_priority(
        np.array([3, 3, 3, 3]))
    assert shed_priority(np.array([3])) > shed_priority(
        np.array([1, 1, 1, 1]))


def test_fleet_fault_accessor_validates_modes():
    with pytest.raises(ValueError, match="fleet fault"):
        resilience.fleet_fault("banana")
    with pytest.raises(ValueError, match="serving fault"):
        resilience.serving_fault("tenant_flood")
    assert resilience.fleet_fault("tenant_flood") is None
    with resilience.fault_injection("tenant_flood", n_attempts=4):
        spec = resilience.fleet_fault("tenant_flood")
        assert spec is not None and spec.n_attempts == 4


def test_attach_detach_and_unknown_tenant():
    sched, models, hists, _ = _build_fleet(2)
    assert sched.tenants == ["t0", "t1"]
    assert sched.n_groups == 1               # same key -> one group
    with pytest.raises(ValueError, match="already attached"):
        sched.attach(sched.session("t0"))
    with pytest.raises(KeyError, match="no tenant"):
        sched.submit("nope", np.zeros(S))
    sess = sched.detach("t1")
    assert sched.tenants == ["t0"]
    assert sess.n_series == S                # still servable standalone
    sess.update(hists[1][:, -1])


# ---------------------------------------------------------------------------
# the tentpole pin: coalesced == sequential, bitwise
# ---------------------------------------------------------------------------

def test_coalesced_ticks_bitwise_equal_per_session():
    """≥3 tenants sharing one bucket: every round of ticks dispatches as
    ONE coalesced device call, and every per-lane artifact — filter
    state, covariance, likelihood, health EW, TickResult fields, and the
    forecasts that follow — is bitwise identical to ticking each session
    on its own."""
    n_t = 3
    models, hists = _tenant_fixtures(n_t)
    ref = [ss.ServingSession.start(m, h, label=f"ref{i}",
                                   registry=metrics.MetricsRegistry())
           for i, (m, h) in enumerate(zip(models, hists))]
    sched, _, _, reg = _build_fleet(0)
    for i, (m, h) in enumerate(zip(models, hists)):
        sched.attach(ss.ServingSession.start(
            m, h, label=f"t{i}", registry=reg))
    rng = np.random.default_rng(9)
    ticks = rng.normal(size=(n_t, S, 6))
    for t in range(6):
        for i in range(n_t):
            sched.submit(f"t{i}", ticks[i, :, t])
        reports = sched.pump()
        assert len(reports) == 1, reports    # ONE device call per round
        assert reports[0]["tenants"] == n_t
        for i in range(n_t):
            ref[i].update(ticks[i, :, t])
            np.testing.assert_array_equal(
                np.asarray(sched.session(f"t{i}")._state.a),
                np.asarray(ref[i]._state.a))
    for i in range(n_t):
        a, b = sched.session(f"t{i}"), ref[i]
        assert a.ticks_seen == b.ticks_seen == N_HIST + 6
        np.testing.assert_array_equal(np.asarray(a._state.P),
                                      np.asarray(b._state.P))
        np.testing.assert_array_equal(a.loglik, b.loglik)
        np.testing.assert_array_equal(np.asarray(a._health.ew),
                                      np.asarray(b._health.ew))
        np.testing.assert_array_equal(a.lane_status, b.lane_status)
        np.testing.assert_array_equal(a._ring_history(),
                                      b._ring_history())
        np.testing.assert_array_equal(sched.forecast(f"t{i}", 5),
                                      b.forecast(5))
    snap = reg.snapshot()["counters"]
    assert snap["fleet.coalesced_dispatches"] == 6
    assert snap["fleet.coalesced_ticks"] == 6 * n_t


def test_coalesced_tickresults_match_sequential():
    """The per-tick TickResult surfaces (innovations, variances, loglik
    increments, status) agree bitwise too — not just the end state."""
    models, hists = _tenant_fixtures(2, seed0=21)
    ref = [ss.ServingSession.start(m, h, registry=metrics.MetricsRegistry())
           for m, h in zip(models, hists)]
    sched, _, _, reg = _build_fleet(0)
    tenants = []
    for i, (m, h) in enumerate(zip(models, hists)):
        tenants.append(sched.attach(ss.ServingSession.start(
            m, h, label=f"t{i}", registry=reg)))
    rng = np.random.default_rng(33)
    tick = rng.normal(size=(2, S))
    tick[0, 1] = np.nan                      # a missing tick rides along
    for i, la in enumerate(tenants):
        sched.submit(la, tick[i])
    sched.pump()
    for i, la in enumerate(tenants):
        want = ref[i].update(tick[i])
        sess = sched.session(la)
        # the last absorbed outcome is observable through state deltas;
        # re-derive the innovation check from the public surfaces
        np.testing.assert_array_equal(sess.loglik, ref[i].loglik)
        np.testing.assert_array_equal(sess.lane_status, want.status)


# ---------------------------------------------------------------------------
# admission control: flood -> reject -> recover
# ---------------------------------------------------------------------------

def test_flood_reject_recover():
    sched, models, hists, reg = _build_fleet(
        2, AdmissionPolicy(queue_depth=3, on_full="reject"))
    rng = np.random.default_rng(5)
    # deterministic ingress flood: one submit amplifies into 16 copies
    with resilience.fault_injection("tenant_flood", n_attempts=16):
        with pytest.raises(FleetSaturated, match="t0.*queue is full"):
            sched.submit("t0", rng.normal(size=S))
    snap = reg.snapshot()["counters"]
    assert snap["fleet.rejected"] >= 1
    assert snap["fleet.admitted"] == 3       # the queue really is bounded
    # recovery: drain the backlog, then normal traffic serves again
    sched.pump(force=True)
    before = sched.session("t0").ticks_seen
    sched.submit("t0", rng.normal(size=S))
    sched.submit("t1", rng.normal(size=S))
    sched.pump()
    assert sched.session("t0").ticks_seen > before
    assert all(t.mode == TENANT_LIVE
               for t in sched._tenants.values())


def test_drop_oldest_policy_keeps_newest_tick():
    sched, models, hists, reg = _build_fleet(
        1, AdmissionPolicy(queue_depth=2, on_full="drop_oldest"))
    t = sched._require("t0")
    for k in range(5):
        sched.submit("t0", np.full(S, float(k)))
    assert len(t.queue) == 2
    # the two newest survive; three oldest were evicted and counted
    assert [float(q[0][0]) for q in t.queue] == [3.0, 4.0]
    assert reg.snapshot()["counters"]["fleet.dropped_ticks"] == 3


def test_degrade_policy_sheds_tenant_onto_cache_lane():
    sched, models, hists, reg = _build_fleet(
        1, AdmissionPolicy(queue_depth=2, on_full="degrade",
                           shed_cooldown=1))
    sched.forecast("t0", 4)                  # prime the cache while live
    for k in range(4):
        sched.submit("t0", np.full(S, float(k)))
    t = sched._require("t0")
    assert t.mode == TENANT_SHED and t.shed_reason == "admission"
    # reads keep answering from the cache lane (no tick dispatched)
    fc = sched.forecast("t0", 2)
    assert fc.shape == (S, 2)
    assert reg.snapshot()["counters"].get("fleet.cache_serves", 0) \
        + reg.snapshot()["counters"].get("fleet.cache_stale", 0) >= 1
    # pressure gone -> the pump ladder restores and replays the buffer
    for _ in range(3):
        sched.pump()
    assert t.mode == TENANT_LIVE
    assert sched.session("t0").ticks_seen == N_HIST + 4


# ---------------------------------------------------------------------------
# coalescing window: a straggler cannot stall the batch
# ---------------------------------------------------------------------------

def test_straggler_cannot_stall_the_batch():
    sched, models, hists, _ = _build_fleet(
        3, AdmissionPolicy(coalesce_window_s=10.0))
    rng = np.random.default_rng(11)
    with resilience.fault_injection("coalesce_straggler", lane_stride=3):
        for i in range(3):
            sched.submit(f"t{i}", rng.normal(size=S))
        reports = sched.pump()
    # the two non-straggler tenants flushed as one batch immediately —
    # the silent tenant delayed only itself
    assert len(reports) == 1 and reports[0]["tenants"] == 2
    assert sched.session("t0").ticks_seen == N_HIST      # held
    assert sched.session("t1").ticks_seen == N_HIST + 1
    assert sched.session("t2").ticks_seen == N_HIST + 1
    # fault gone: the held tick is a partial batch again (the others
    # have no ticks), so it flushes on force (or the window deadline)
    reports = sched.pump(force=True)
    assert len(reports) == 1 and reports[0]["tenants"] == 1
    assert sched.session("t0").ticks_seen == N_HIST + 1


def test_partial_batch_flushes_after_window_deadline():
    sched, models, hists, _ = _build_fleet(
        2, AdmissionPolicy(coalesce_window_s=0.02))
    sched.submit("t0", np.zeros(S))          # t1 stays silent
    assert sched.pump() == []                # window still open: wait
    time.sleep(0.1)
    reports = sched.pump()                   # deadline: flush partial
    assert len(reports) == 1 and reports[0]["tenants"] == 1
    assert sched.session("t0").ticks_seen == N_HIST + 1


# ---------------------------------------------------------------------------
# SLO shedding: shed -> cache-serve -> restore (bitwise catch-up)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_slo_burn_sheds_worst_health_first_then_restores_bitwise(
        monkeypatch):
    monkeypatch.setenv("STS_SERVING_SLO_MS", "0.0001")   # every dispatch
    #                                                      burns
    reg = metrics.MetricsRegistry()
    models, hists = _tenant_fixtures(2, seed0=41)
    sessions = [ss.ServingSession.start(m, h, label=f"t{i}",
                                        registry=reg)
                for i, (m, h) in enumerate(zip(models, hists))]
    # t1 carries quarantined lanes: the shed ladder must pick it first
    rng = np.random.default_rng(3)
    with resilience.fault_injection("state_poison", lane_stride=2):
        sessions[1].update(rng.normal(size=S))
    sessions[0].update(rng.normal(size=S))   # keep tick counts aligned
    assert shed_priority(sessions[1].lane_status) \
        > shed_priority(sessions[0].lane_status)

    sched = FleetScheduler(
        AdmissionPolicy(slo_window=4, shed_cooldown=2,
                        cache_staleness=16, catchup_ring=64),
        registry=reg, auto_pump=False)
    for sess in sessions:
        sched.attach(sess)
    for la in sched.tenants:
        sched.forecast(la, 4)                # prime the caches

    ticks = np.random.default_rng(7).normal(size=(2, S, 10))
    shed_at = None
    for t in range(10):
        for i in range(2):
            sched.submit(f"t{i}", ticks[i, :, t])
        sched.pump()
        modes = [sched._tenants[f"t{i}"].mode for i in range(2)]
        if TENANT_SHED in modes:
            shed_at = t
            assert modes[1] == TENANT_SHED and modes[0] == TENANT_LIVE, \
                "the diverged-laden tenant must shed first"
            break
    assert shed_at is not None, "the burn never shed anything"
    assert reg.snapshot()["counters"]["fleet.shed_lanes"] >= S
    assert reg.snapshot()["counters"]["fleet.slo_burns"] >= 1

    # reads on the shed tenant keep answering without tick dispatches:
    # the first read refreshes the (now phase-shifted) cache, the
    # second serves straight from it
    dispatches = reg.snapshot()["counters"]["fleet.coalesced_dispatches"]
    fc = sched.forecast("t1", 3)
    assert fc.shape == (S, 3)
    fc2 = sched.forecast("t1", 3)
    np.testing.assert_array_equal(fc, fc2)
    assert reg.snapshot()["counters"].get("fleet.cache_serves", 0) >= 1
    assert reg.snapshot()["counters"]["fleet.coalesced_dispatches"] \
        == dispatches                        # no tick work for reads

    # burn clears -> ladder restores everything, replaying the buffer
    monkeypatch.delenv("STS_SERVING_SLO_MS")
    sched._slo_ms = None
    for _ in range(10):
        sched.pump()
    assert all(sched._tenants[la].mode == TENANT_LIVE
               for la in sched.tenants)
    assert reg.snapshot()["counters"]["fleet.restored_tenants"] >= 1
    # nothing was lost: every tick submitted before the break reached
    # both sessions (t1's buffered ones through the restore replay)
    for i in range(2):
        assert sched.session(f"t{i}").ticks_seen \
            == N_HIST + 1 + shed_at + 1


@pytest.mark.slow
def test_shed_restore_catchup_is_bitwise_sequential(monkeypatch):
    """A tenant that rode out an overload window shed+restored must land
    bitwise where a never-shed session fed the same stream lands (the
    catch-up replay goes through the same warmed executable)."""
    reg = metrics.MetricsRegistry()
    models, hists = _tenant_fixtures(1, seed0=61)
    sched = FleetScheduler(
        AdmissionPolicy(slo_window=4, shed_cooldown=100,
                        catchup_ring=64),
        registry=reg, auto_pump=False)
    sess = ss.ServingSession.start(models[0], hists[0], label="t0",
                                   registry=reg)
    sched.attach(sess)
    mirror = ss.ServingSession.start(models[0], hists[0],
                                     registry=metrics.MetricsRegistry())
    rng = np.random.default_rng(13)
    ticks = rng.normal(size=(S, 12))
    for t in range(4):                       # live phase
        sched.submit("t0", ticks[:, t])
        sched.pump()
    sched._shed(sched._require("t0"), reason="slo")   # overload hits
    for t in range(4, 9):                    # shed phase: ticks buffer
        sched.submit("t0", ticks[:, t])
        sched.pump()
    assert sess.ticks_seen == N_HIST + 4     # nothing dispatched
    sched._restore(sched._require("t0"))     # burn clears
    for t in range(9, 12):                   # live again
        sched.submit("t0", ticks[:, t])
        sched.pump()
    for t in range(12):
        mirror.update(ticks[:, t])
    np.testing.assert_array_equal(np.asarray(sess._state.a),
                                  np.asarray(mirror._state.a))
    np.testing.assert_array_equal(np.asarray(sess._state.P),
                                  np.asarray(mirror._state.P))
    np.testing.assert_array_equal(sess.loglik, mirror.loglik)
    np.testing.assert_array_equal(sched.forecast("t0", 6),
                                  mirror.forecast(6))


# ---------------------------------------------------------------------------
# zero-recompile pin with the scheduler armed
# ---------------------------------------------------------------------------

def test_warmed_fleet_pump_triggers_zero_compiles():
    metrics.install_jax_hooks()
    sched, models, hists, _ = _build_fleet(3, seed0=71)
    sched.warmup()
    for la in sched.tenants:
        sched.forecast(la, 5)                # warm this horizon
    rng = np.random.default_rng(17)
    before = metrics.jax_stats()["jit_compiles"]
    for t in range(4):
        for i in range(3):
            sched.submit(f"t{i}", rng.normal(size=S))
        sched.pump()
    for la in sched.tenants:
        sched.forecast(la, 5)
    assert metrics.jax_stats()["jit_compiles"] - before == 0, \
        "compiles leaked into the warmed coalesced tick path"


# ---------------------------------------------------------------------------
# migration: drain/adopt (in-process, mismatches, kill -9 pair)
# ---------------------------------------------------------------------------

def test_drain_adopt_roundtrip_with_pending_ticks(tmp_path):
    sched, models, hists, _ = _build_fleet(1, seed0=81)
    mirror = ss.ServingSession.start(models[0], hists[0],
                                     registry=metrics.MetricsRegistry())
    rng = np.random.default_rng(19)
    ticks = rng.normal(size=(S, 6))
    for t in range(4):
        sched.submit("t0", ticks[:, t])
        sched.pump()
    sched.submit("t0", ticks[:, 4])          # two ticks still queued
    sched.submit("t0", ticks[:, 5])
    path = str(tmp_path / "t0.bundle")
    rep = sched.drain("t0", path)
    assert rep["pending"] == 2
    assert sched.tenants == []
    sched2 = FleetScheduler(registry=metrics.MetricsRegistry(),
                            auto_pump=False)
    assert sched2.adopt(path) == "t0"
    for t in range(6):
        mirror.update(ticks[:, t])
    sess = sched2.session("t0")
    assert sess.ticks_seen == mirror.ticks_seen
    np.testing.assert_array_equal(np.asarray(sess._state.a),
                                  np.asarray(mirror._state.a))
    np.testing.assert_array_equal(sess.loglik, mirror.loglik)
    np.testing.assert_array_equal(sched2.forecast("t0", 4),
                                  mirror.forecast(4))


def test_adopt_rejects_mismatched_bundles(tmp_path):
    from spark_timeseries_tpu.utils import checkpoint as ckpt

    sched, models, hists, _ = _build_fleet(1, seed0=91)
    path = str(tmp_path / "ok.bundle")
    sched.drain("t0", path)
    blob = ckpt.load_pytree(path)

    # wrong bundle format
    p = str(tmp_path / "fmt.bundle")
    ckpt.save_pytree_atomic(p, dict(blob, format=99))
    with pytest.raises(FleetRestoreMismatch, match="format"):
        FleetScheduler(registry=metrics.MetricsRegistry()).adopt(p)

    # pending geometry vs n_series
    p = str(tmp_path / "geom.bundle")
    ckpt.save_pytree_atomic(p, dict(blob, pending=np.zeros((1, S + 3))))
    with pytest.raises(FleetRestoreMismatch, match="pending"):
        FleetScheduler(registry=metrics.MetricsRegistry()).adopt(p)

    # the session half's own geometry validation chains through
    bad_sess = dict(blob["session"], bucket=16)
    p = str(tmp_path / "sess.bundle")
    ckpt.save_pytree_atomic(p, dict(blob, session=bad_sess))
    with pytest.raises(FleetRestoreMismatch, match="session half"):
        FleetScheduler(registry=metrics.MetricsRegistry()).adopt(p)

    # unreadable path
    with pytest.raises(FleetRestoreMismatch, match="cannot be read"):
        FleetScheduler(registry=metrics.MetricsRegistry()).adopt(
            str(tmp_path / "missing.bundle"))

    # duplicate label in the adopting scheduler
    sched3 = FleetScheduler(registry=metrics.MetricsRegistry(),
                            auto_pump=False)
    sched3.adopt(path)
    with pytest.raises(FleetRestoreMismatch, match="exactly one"):
        sched3.adopt(path)


_MIGRATE_CHILD = """
import contextlib, os
import numpy as np
import jax.numpy as jnp
from spark_timeseries_tpu import statespace as ss
from spark_timeseries_tpu.models import arima
from spark_timeseries_tpu.utils import resilience

def panel(n_series, n, seed):
    rng = np.random.default_rng(seed)
    e = rng.normal(size=(n_series, n + 16))
    y = np.zeros((n_series, n + 16))
    for t in range(2, n + 16):
        y[:, t] = 0.3 + 0.5*y[:, t-1] - 0.2*y[:, t-2] + e[:, t]
    return y[:, 16:]

S = 4
hist = panel(S, 120, 7)
live = panel(S, 40, 8)
model = arima.fit(2, 0, 0, jnp.asarray(hist), warn=False)
sched = ss.FleetScheduler(auto_pump=False)
sess = ss.ServingSession.start(model, hist, label="mig")
sched.attach(sess)
for t in range(12):
    sched.submit("mig", live[:, t])
    sched.pump()
sched.submit("mig", live[:, 12])   # two undispatched ticks ride the
sched.submit("mig", live[:, 13])   # bundle
with resilience.fault_injection("drop_tenant_process"):
    sched.drain("mig", os.environ["STS_TEST_BUNDLE"])
print("UNREACHABLE: drain survived drop_tenant_process", flush=True)
raise SystemExit(3)
"""


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_drain_kill9_adopt_subprocess_pair(tmp_path):
    """The migration acceptance pin: a process SIGKILLed the instant its
    drain bundle commits loses nothing — another process adopts the
    bundle, replays the queued ticks, and every subsequent tick and
    forecast is bitwise an uninterrupted session's."""
    bundle = str(tmp_path / "mig.bundle")
    inc_dir = str(tmp_path / "incidents")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               STS_TEST_BUNDLE=bundle, STS_INCIDENT_DIR=inc_dir)
    out = subprocess.run([sys.executable, "-c", _MIGRATE_CHILD],
                         capture_output=True, text=True, cwd=REPO,
                         env=env, timeout=600)
    assert out.returncode == -9, (out.returncode, out.stderr[-2000:])
    assert os.path.exists(bundle + ".npz")
    assert os.path.exists(bundle + ".tree.json")
    # the pre-kill forensics bundle landed too
    incidents = [f for f in os.listdir(inc_dir)
                 if "drop_tenant_process" in f] if os.path.isdir(inc_dir) \
        else []
    assert incidents, os.listdir(inc_dir) if os.path.isdir(inc_dir) \
        else "no incident dir"

    # adopt in THIS process; the uninterrupted mirror recomputes the
    # child's whole stream locally (fits are cross-process bitwise
    # deterministic — the journal resume suite already pins that)
    def panel(n_series, n, seed):
        rng = np.random.default_rng(seed)
        e = rng.normal(size=(n_series, n + 16))
        y = np.zeros((n_series, n + 16))
        for t in range(2, n + 16):
            y[:, t] = 0.3 + 0.5 * y[:, t - 1] - 0.2 * y[:, t - 2] \
                + e[:, t]
        return y[:, 16:]

    hist = panel(S, 120, 7)
    live = panel(S, 40, 8)
    model = arima.fit(2, 0, 0, jnp.asarray(hist), warn=False)
    mirror = ss.ServingSession.start(model, hist,
                                     registry=metrics.MetricsRegistry())
    sched = FleetScheduler(registry=metrics.MetricsRegistry(),
                           auto_pump=False)
    label = sched.adopt(bundle)              # replays the 2 queued ticks
    assert label == "mig"
    for t in range(14):
        mirror.update(live[:, t])
    sess = sched.session("mig")
    assert sess.ticks_seen == mirror.ticks_seen == 120 + 14
    np.testing.assert_array_equal(np.asarray(sess._state.a),
                                  np.asarray(mirror._state.a))
    np.testing.assert_array_equal(np.asarray(sess._state.P),
                                  np.asarray(mirror._state.P))
    np.testing.assert_array_equal(sess.loglik, mirror.loglik)
    # and the adopted tenant keeps serving bitwise
    for t in range(14, 18):
        sched.submit("mig", live[:, t])
        sched.pump()
        mirror.update(live[:, t])
    np.testing.assert_array_equal(sched.forecast("mig", 6),
                                  mirror.forecast(6))


# ---------------------------------------------------------------------------
# serving satellites: batch-width errors
# ---------------------------------------------------------------------------

def test_update_batch_width_mismatch_is_named_error():
    models, hists = _tenant_fixtures(1, seed0=95)
    sess = ss.ServingSession.start(models[0], hists[0],
                                   registry=metrics.MetricsRegistry())
    with pytest.raises(ValueError, match="update_batch expects"):
        sess.update_batch(np.zeros((S + 2, 3)))
    with pytest.raises(ValueError, match="at least one tick"):
        sess.update_batch(np.zeros((S, 0)))
    with pytest.raises(ValueError, match="offset per series"):
        sess.update(np.zeros(S), offset=np.zeros(S + 1))
    with pytest.raises(ValueError, match="offsets must match"):
        sess.update_batch(np.zeros((S, 2)), offsets=np.zeros((S, 3)))
    # the happy path is bitwise the sequential updates
    mirror = ss.ServingSession.start(models[0], hists[0],
                                     registry=metrics.MetricsRegistry())
    rng = np.random.default_rng(23)
    batch = rng.normal(size=(S, 4))
    sess.update_batch(batch)
    for t in range(4):
        mirror.update(batch[:, t])
    np.testing.assert_array_equal(np.asarray(sess._state.a),
                                  np.asarray(mirror._state.a))
    np.testing.assert_array_equal(sess.loglik, mirror.loglik)


def test_monitor_panel_width_mismatch_is_named_error():
    from spark_timeseries_tpu.statespace.health import monitor_panel

    models, hists = _tenant_fixtures(1, seed0=97)
    sess = ss.ServingSession.start(models[0], hists[0],
                                   registry=metrics.MetricsRegistry())
    with pytest.raises(ValueError, match="monitor_panel expects"):
        monitor_panel(sess._ssm, sess._state, sess._health,
                      jnp.zeros((S, 5)),     # un-bucketed width
                      sess.meta, sess.policy)


# ---------------------------------------------------------------------------
# telemetry + tooling wiring
# ---------------------------------------------------------------------------

def test_fleet_panel_lands_in_snapshot_and_sts_top():
    from spark_timeseries_tpu.utils import telemetry
    from tools.sts_top import render_snapshot

    sched, models, hists, _ = _build_fleet(2, seed0=99)
    sched.submit("t0", np.zeros(S))
    sched.pump(force=True)
    doc = telemetry.snapshot_doc()
    fleets = [f for f in doc["fleets"]
              if f.get("label") == sched.label]
    assert fleets, doc["fleets"]
    panel = fleets[0]
    assert panel["tenants"] == 2
    rows = {r["tenant"]: r for r in panel["tenant_rows"]}
    assert rows["t0"]["mode"] == TENANT_LIVE
    assert rows["t0"]["admitted"] == 1
    frame = render_snapshot(json.loads(json.dumps(doc)))
    assert "FLEET" in frame
    assert sched.label in frame
    assert "t0" in frame


def test_bench_gate_extracts_fleet_metrics():
    from tools.bench_gate import METRICS, extract_metrics

    names = [m[0] for m in METRICS]
    assert "fleet_ticks_per_s" in names
    assert "fleet_shed_lanes" in names

    h = {"value": 1.0, "fleet_demo": {
        "fleet_ticks_per_s": 5000.0, "sessions": 64}}
    got = extract_metrics(h)
    assert got["fleet_ticks_per_s"] == 5000.0
    assert got["fleet_shed_lanes"] == 0.0    # block present -> measured 0

    h = {"value": 1.0, "fleet_demo": {
        "fleet_ticks_per_s": 5000.0, "shed_lanes": 32}}
    assert extract_metrics(h)["fleet_shed_lanes"] == 32.0

    # pre-fleet rounds and errored demos fabricate nothing
    assert "fleet_ticks_per_s" not in extract_metrics({"value": 1.0})
    assert "fleet_shed_lanes" not in extract_metrics({"value": 1.0})
    assert "fleet_shed_lanes" not in extract_metrics(
        {"value": 1.0, "fleet_demo": {"error": "boom"}})


# ---------------------------------------------------------------------------
# review-finding pins
# ---------------------------------------------------------------------------

def test_cache_phase_keeps_advancing_past_ring_saturation():
    """Review pin: the forecast cache's phase shift is arrival-based —
    once the bounded catch-up ring saturates, its length stops growing,
    but the stream's clock must not: a long-shed tenant's cache goes
    STALE (and refreshes) instead of freezing on one phase forever."""
    sched, models, hists, reg = _build_fleet(
        1, AdmissionPolicy(catchup_ring=4, cache_staleness=2,
                           shed_cooldown=100))
    sched.forecast("t0", 3)                  # prime while live
    sched._shed(sched._require("t0"), reason="slo")
    rng = np.random.default_rng(29)
    for _ in range(10):                      # 10 arrivals >> ring of 4
        sched.submit("t0", rng.normal(size=S))
    t = sched._require("t0")
    assert len(t.catchup) == 4               # ring saturated
    assert t.elapsed_since_cache() > sched.policy.cache_staleness
    sched.forecast("t0", 3)                  # must refresh, not freeze
    assert reg.snapshot()["counters"]["fleet.cache_stale"] >= 1
    # right after the refresh the phase is 0 again: cache-serve
    sched.forecast("t0", 3)
    assert reg.snapshot()["counters"]["fleet.cache_serves"] >= 1
    # and new arrivals advance the phase past the bound once more
    for _ in range(4):
        sched.submit("t0", rng.normal(size=S))
    stale_before = reg.snapshot()["counters"]["fleet.cache_stale"]
    sched.forecast("t0", 3)
    assert reg.snapshot()["counters"]["fleet.cache_stale"] \
        == stale_before + 1


def test_drain_adopt_preserves_catchup_and_offsets(tmp_path):
    """Review pin: the bundle carries the catch-up ring's ticks WITH
    their exogenous offsets (and the queue's), so an adopted tenant that
    was shed mid-drain replays bitwise — offsets included."""
    sched, models, hists, _ = _build_fleet(
        1, AdmissionPolicy(shed_cooldown=100))
    mirror = ss.ServingSession.start(models[0], hists[0],
                                     registry=metrics.MetricsRegistry())
    rng = np.random.default_rng(31)
    ticks = rng.normal(size=(S, 4))
    offs = rng.normal(size=(S, 4)) * 0.1
    sched._shed(sched._require("t0"), reason="slo")
    sched.submit("t0", ticks[:, 0], offset=offs[:, 0])   # -> catchup
    sched.submit("t0", ticks[:, 1], offset=offs[:, 1])
    t = sched._require("t0")
    t.mode = TENANT_LIVE                    # queue the rest as pending
    sched._shed_order.remove("t0")
    t.shed_reason = None
    sched.submit("t0", ticks[:, 2], offset=offs[:, 2])
    sched.submit("t0", ticks[:, 3], offset=offs[:, 3])
    path = str(tmp_path / "offs.bundle")
    rep = sched.drain("t0", path)
    assert rep["pending"] == 2 and rep["catchup"] == 2
    sched2 = FleetScheduler(registry=metrics.MetricsRegistry(),
                            auto_pump=False)
    sched2.adopt(path)
    for k in range(4):
        mirror.update(ticks[:, k], offs[:, k])
    sess = sched2.session("t0")
    np.testing.assert_array_equal(np.asarray(sess._state.a),
                                  np.asarray(mirror._state.a))
    np.testing.assert_array_equal(sess.loglik, mirror.loglik)


def test_adopt_deferred_ingest_keeps_stream_order(tmp_path):
    """Review pin: adopt(replay=False) parks the bundle's ticks at the
    FRONT of the live queue in stream order (catch-up first), so later
    submits can never overtake them."""
    sched, models, hists, _ = _build_fleet(
        1, AdmissionPolicy(shed_cooldown=100))
    mirror = ss.ServingSession.start(models[0], hists[0],
                                     registry=metrics.MetricsRegistry())
    rng = np.random.default_rng(37)
    ticks = rng.normal(size=(S, 5))
    sched._shed(sched._require("t0"), reason="slo")
    sched.submit("t0", ticks[:, 0])          # catchup
    t = sched._require("t0")
    t.mode = TENANT_LIVE
    sched._shed_order.remove("t0")
    t.shed_reason = None
    sched.submit("t0", ticks[:, 1])          # pending
    sched.submit("t0", ticks[:, 2])
    path = str(tmp_path / "order.bundle")
    sched.drain("t0", path)
    sched2 = FleetScheduler(registry=metrics.MetricsRegistry(),
                            auto_pump=False)
    sched2.adopt(path, replay=False)
    # deferred ticks count as stream arrivals (the cache phase clock)
    assert sched2._require("t0").arrived == 3
    sched2.submit("t0", ticks[:, 3])         # newer traffic
    sched2.submit("t0", ticks[:, 4])
    for _ in range(5):
        sched2.pump(force=True)
    for k in range(5):
        mirror.update(ticks[:, k])
    sess = sched2.session("t0")
    assert sess.ticks_seen == mirror.ticks_seen
    np.testing.assert_array_equal(np.asarray(sess._state.a),
                                  np.asarray(mirror._state.a))
    np.testing.assert_array_equal(sess.loglik, mirror.loglik)


def test_warmed_partial_flushes_trigger_zero_compiles():
    """Review pin: warmup covers every power-of-two slot width, so a
    window-deadline/straggler partial flush (G < full group) compiles
    nothing inside the hot pump."""
    metrics.install_jax_hooks()
    sched, models, hists, _ = _build_fleet(3, seed0=51)
    sched.warmup()
    rng = np.random.default_rng(41)
    before = metrics.jax_stats()["jit_compiles"]
    # G=2 flush (slots 2): two tenants only
    sched.submit("t0", rng.normal(size=S))
    sched.submit("t1", rng.normal(size=S))
    sched.pump(force=True)
    # G=1 flush (slots 1)
    sched.submit("t2", rng.normal(size=S))
    sched.pump(force=True)
    # full G=3 flush (slots 4)
    for i in range(3):
        sched.submit(f"t{i}", rng.normal(size=S))
    sched.pump()
    assert metrics.jax_stats()["jit_compiles"] - before == 0, \
        "a partial-width flush compiled inside the warmed pump"


def test_degrade_shed_does_not_oscillate_under_sustained_flood():
    """Review pin: an admission-shed tenant restores only once its
    ingress goes quiet — a producer that keeps flooding must not drive
    a shed/replay/shed oscillation every cooldown."""
    sched, models, hists, reg = _build_fleet(
        1, AdmissionPolicy(queue_depth=2, on_full="degrade",
                           shed_cooldown=1))
    rng = np.random.default_rng(43)
    for k in range(3):                       # saturate -> degrade-shed
        sched.submit("t0", rng.normal(size=S))
    t = sched._require("t0")
    assert t.mode == TENANT_SHED
    for _ in range(6):                       # sustained flood: one
        sched.submit("t0", rng.normal(size=S))   # arrival per pump
        sched.pump()
        assert t.mode == TENANT_SHED, \
            "restored into a live flood (oscillation)"
    assert reg.snapshot()["counters"].get("fleet.restored_tenants",
                                          0) == 0
    sched.pump()                             # quiet pumps: pressure gone
    sched.pump()
    assert t.mode == TENANT_LIVE
    assert reg.snapshot()["counters"]["fleet.restored_tenants"] == 1


def test_malformed_submit_rejected_at_admission_boundary():
    """Review pin: a wrong-width tick fails at submit() — the producer's
    own call — and never reaches a coalesced dispatch where it would
    destroy co-grouped peers' already-dequeued ticks."""
    sched, models, hists, _ = _build_fleet(2, seed0=53)
    sched.submit("t0", np.zeros(S))          # a healthy peer queues
    with pytest.raises(ValueError, match="t1.*one tick per series"):
        sched.submit("t1", np.zeros(S + 2))
    with pytest.raises(ValueError, match="t1.*offset per series"):
        sched.submit("t1", np.zeros(S), offset=np.zeros(S + 1))
    # the peer's queued tick survived the neighbor's bad submit
    assert len(sched._require("t0").queue) == 1
    sched.submit("t1", np.zeros(S))
    reports = sched.pump()
    assert reports and reports[0]["tenants"] == 2


def test_fleet_forecast_offsets_passthrough():
    """Review pin: exogenous offsets flow through the fleet read path
    (request-specific — never cached), live and shed alike."""
    sched, models, hists, _ = _build_fleet(
        1, AdmissionPolicy(shed_cooldown=100))
    offs = np.full((S, 4), 0.5)
    base = sched.forecast("t0", 4)
    shifted = sched.forecast("t0", 4, offsets=offs)
    assert shifted.shape == (S, 4)
    assert not np.array_equal(base, shifted)
    want = sched.session("t0").forecast(4, offsets=offs)
    np.testing.assert_array_equal(shifted, want)
    # shed: still served (off the frozen state), still not cached
    sched._shed(sched._require("t0"), reason="slo")
    shed_shifted = sched.forecast("t0", 4, offsets=offs)
    np.testing.assert_array_equal(shed_shifted, want)
    assert sched._require("t0").cache_fc is None or not \
        np.array_equal(sched._require("t0").cache_fc[:, :4], shifted)


def test_gathered_ssm_is_reused_until_session_heals():
    """Review pin: the static SSM gather is cached per participation
    pattern and re-gathered only when a member's SSM object is swapped
    (heal/splice/restore) — the hot pump must not re-upload O(G·B·m²)
    transition floats every round."""
    import jax

    sched, models, hists, _ = _build_fleet(2, seed0=57)
    rng = np.random.default_rng(59)
    for _ in range(2):
        for i in range(2):
            sched.submit(f"t{i}", rng.normal(size=S))
        sched.pump()
    assert len(sched._gather_cache) == 1
    (refs, gathered), = sched._gather_cache.values()
    for i in range(2):
        sched.submit(f"t{i}", rng.normal(size=S))
    sched.pump()
    (refs2, gathered2), = sched._gather_cache.values()
    assert gathered2 is gathered             # reused, not re-gathered
    # simulate a heal: the session swaps in a NEW ssm pytree
    sess = sched.session("t0")
    sess._ssm = jax.tree_util.tree_map(lambda x: x, sess._ssm)
    for i in range(2):
        sched.submit(f"t{i}", rng.normal(size=S))
    sched.pump()
    (refs3, gathered3), = sched._gather_cache.values()
    assert gathered3 is not gathered         # invalidated + refreshed


def test_bench_gate_flags_first_shedding_round():
    from tools.bench_gate import evaluate

    def mk(r, shed=0):
        return {"round": r, "rc": 0, "path": f"r{r}", "headline": {
            "metric": "t", "value": 100.0, "platform": "cpu",
            "fleet_demo": {"fleet_ticks_per_s": 5000.0,
                           "shed_lanes": shed}}}

    clean = [mk(r) for r in range(1, 4)]
    verdict = evaluate(clean + [mk(4, shed=16)])
    row = next(r for r in verdict["rows"]
               if r["metric"] == "fleet_shed_lanes")
    assert row["status"] == "REGRESSED"
    verdict = evaluate(clean + [mk(4)])
    row = next(r for r in verdict["rows"]
               if r["metric"] == "fleet_shed_lanes")
    assert row["status"] == "ok"
