"""State-space/Kalman serving tier (ISSUE 7).

Oracle strategy mirrors ``test_scalar_oracles.py``: statsmodels/R are not
in the image, so the Kalman filter is checked against a deliberately
scalar, loop-based NumPy re-implementation written from the textbook
prediction-form recursion — no code shared with the JAX kernels — plus
the AR(1) closed-form exact likelihood (stationary prior + conditional
normals), which anchors the companion-form converter and the
``objective="exact"`` fit independently of the filter itself.

The serving pins (the acceptance criteria):

- a warmed ``ServingSession.update`` triggers **zero** XLA compiles
  (same ``metrics.jax_stats`` harness as ``test_engine.py``'s
  compile-amortization pin), at 1024 series too;
- no optimizer / fit entry point is reachable from the tick path;
- exact-objective ARIMA never reports a worse exact log-likelihood than
  the CSS solution on the tier-1 R fixtures.

Fast host-side tests run in tier-1; everything that compiles a large
program or spawns a subprocess is marked ``slow`` and runs via
``make verify-serving`` (the ``serving`` marker).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_timeseries_tpu import statespace as ss
from spark_timeseries_tpu.models import arima, ewma, holt_winters
from spark_timeseries_tpu.statespace.convert import (
    arma_concentrated_neg_ll, companion_arma)
from spark_timeseries_tpu.statespace.kalman import (
    concentrated_loglik, filter_panel, filter_panel_parallel)
from spark_timeseries_tpu.statespace.serving import (
    WARMUP_FAMILIES, warmup_update)
from spark_timeseries_tpu.statespace.ssm import (
    SSMeta, StateSpace, initial_state)
from spark_timeseries_tpu.utils import metrics

pytestmark = pytest.mark.serving

RESOURCES = os.path.join(os.path.dirname(__file__), "resources")


def _r_fixture(name):
    return jnp.asarray(np.loadtxt(os.path.join(RESOURCES, name)))


# ---------------------------------------------------------------------------
# NumPy oracle: scalar, loop-based prediction-form Kalman filter
# ---------------------------------------------------------------------------

def _np_stationary(T, Q):
    m = T.shape[0]
    kron = np.kron(T, T)
    vec_p = np.linalg.solve(np.eye(m * m) - kron, Q.reshape(m * m))
    P = vec_p.reshape(m, m)
    return 0.5 * (P + P.T)


def _np_filter(T, Z, c, d, H, Q, a0, P0, ys):
    """Textbook prediction-form filter, one observation at a time.

    Returns per-step predicted (a, P, v, F) plus the accumulated exact
    loglik and the concentrated pieces (ssq, sumlogf, n_obs)."""
    a, P = a0.copy(), P0.copy()
    path_a, path_p, path_v, path_f = [], [], [], []
    ll = ssq = sumlogf = 0.0
    n_obs = 0
    for y in ys:
        path_a.append(a.copy())
        path_p.append(P.copy())
        v = y - d - Z @ a
        F = Z @ P @ Z + H
        path_v.append(v)
        path_f.append(F)
        if np.isfinite(y):
            K = (T @ P @ Z) / F
            a = T @ a + c + K * v
            P = T @ P @ T.T + Q - F * np.outer(K, K)
            ll += -0.5 * (np.log(2 * np.pi * F) + v * v / F)
            ssq += v * v / F
            sumlogf += np.log(F)
            n_obs += 1
        else:
            a = T @ a + c
            P = T @ P @ T.T + Q
    return (np.array(path_a), np.array(path_p), np.array(path_v),
            np.array(path_f), ll, ssq, sumlogf, n_obs)


def _random_ssm(rng, S, m, dtype=np.float64):
    """A batch of random *stable* exact-mode SSMs (spectral radius 0.7)."""
    Ts, Qs, Zs, cs, ds, Hs = [], [], [], [], [], []
    for _ in range(S):
        A = rng.normal(size=(m, m))
        A *= 0.7 / max(abs(np.linalg.eigvals(A)))
        B = rng.normal(size=(m, m)) * 0.5
        Ts.append(A)
        Qs.append(B @ B.T + 0.1 * np.eye(m))
        Zs.append(rng.normal(size=m))
        cs.append(rng.normal(size=m) * 0.3)
        ds.append(rng.normal() * 0.5)
        Hs.append(0.2 + rng.uniform())
    z = np.zeros((S, m), dtype)
    return StateSpace(
        T=jnp.asarray(np.array(Ts), dtype), Z=jnp.asarray(np.array(Zs), dtype),
        c=jnp.asarray(np.array(cs), dtype), d=jnp.asarray(np.array(ds), dtype),
        H=jnp.asarray(np.array(Hs), dtype), Q=jnp.asarray(np.array(Qs), dtype),
        gain=jnp.asarray(z))


def test_filter_matches_numpy_oracle():
    """filter_panel's predicted means/covs/innovations and exact loglik ==
    the scalar NumPy oracle to 1e-5 (x64 here; includes a NaN tick, which
    must predict-only on that lane)."""
    rng = np.random.default_rng(7)
    S, m, n = 3, 2, 40
    ssm = _random_ssm(rng, S, m)
    meta = SSMeta("arima", "exact", 0, m)
    ys = rng.normal(size=(S, n)) * 1.5
    ys[1, 7] = np.nan                       # missing tick: predict-only
    state0 = initial_state(ssm, meta)
    res = filter_panel(ssm, state0, jnp.asarray(ys), meta,
                       return_path=True)
    pa, pp, pv, pf = (np.asarray(x) for x in res.path)

    for i in range(S):
        T = np.asarray(ssm.T[i])
        Q = np.asarray(ssm.Q[i])
        a0 = np.linalg.solve(np.eye(m) - T, np.asarray(ssm.c[i]))
        P0 = _np_stationary(T, Q)
        # the stationary initialization itself (the "exact" in exact ll)
        np.testing.assert_allclose(np.asarray(state0.a[i]), a0, atol=1e-8)
        np.testing.assert_allclose(np.asarray(state0.P[i]), P0, atol=1e-8)
        oa, op_, ov, of_, ll, ssq, slf, n_obs = _np_filter(
            T, np.asarray(ssm.Z[i]), np.asarray(ssm.c[i]),
            float(ssm.d[i]), float(ssm.H[i]), Q, a0, P0, ys[i])
        np.testing.assert_allclose(pa[i], oa, atol=1e-5)
        np.testing.assert_allclose(pp[i], op_, atol=1e-5)
        np.testing.assert_allclose(pv[i], ov, atol=1e-5)
        np.testing.assert_allclose(pf[i], of_, atol=1e-5)
        np.testing.assert_allclose(float(res.loglik[i]), ll, atol=1e-5)
        # concentrated pieces accumulate identically
        np.testing.assert_allclose(float(res.state.ssq[i]), ssq, atol=1e-5)
        np.testing.assert_allclose(float(res.state.sumlogf[i]), slf,
                                   atol=1e-5)
        assert int(res.state.n_obs[i]) == n_obs
        # and the profiled likelihood follows the documented formula
        sigma2 = ssq / n_obs
        ll_conc = -0.5 * n_obs * (np.log(2 * np.pi * sigma2) + 1.0) \
            - 0.5 * slf
        np.testing.assert_allclose(
            float(concentrated_loglik(res.state)[i]), ll_conc, atol=1e-5)


# ---------------------------------------------------------------------------
# AR(1) closed form: the scalar oracle for the exact ARMA objective
# ---------------------------------------------------------------------------

def _ar1(n, phi, seed, const=0.0):
    rng = np.random.default_rng(seed)
    e = rng.normal(size=n)
    y = np.zeros(n)
    for t in range(1, n):
        y[t] = const + phi * y[t - 1] + e[t]
    return y


def _ar1_concentrated_nll(params, y):
    """Closed-form σ²-profiled exact AR(1) negative loglik: stationary
    prior on y₁ + conditional normals — no Kalman machinery at all."""
    c, phi = params
    n = len(y)
    mu = c / (1.0 - phi)
    f1 = 1.0 / (1.0 - phi * phi)            # unit-scale variance of y₁
    ssq = (y[0] - mu) ** 2 / f1 + np.sum(
        (y[1:] - c - phi * y[:-1]) ** 2)
    sigma2 = ssq / n
    ll = -0.5 * n * (np.log(2 * np.pi * sigma2) + 1.0) - 0.5 * np.log(f1)
    return -ll


def test_arma_concentrated_nll_matches_ar1_closed_form():
    y = _ar1(200, 0.6, seed=3, const=0.8)
    params = np.array([0.5, 0.55])           # deliberately off-MLE
    got = float(arma_concentrated_neg_ll(
        jnp.asarray(params), jnp.asarray(y), 1, 0, 1))
    np.testing.assert_allclose(got, _ar1_concentrated_nll(params, y),
                               rtol=1e-9)


def test_arma_concentrated_nll_ragged_n_valid():
    """A zero-padded lane with n_valid must score exactly like the
    trimmed series (the engine's ragged contract)."""
    y = _ar1(150, 0.5, seed=11)
    padded = np.concatenate([y, np.zeros(50)])
    params = jnp.asarray(np.array([0.0, 0.45]))
    full = float(arma_concentrated_neg_ll(params, jnp.asarray(y), 1, 0, 1))
    ragged = float(arma_concentrated_neg_ll(
        params, jnp.asarray(padded), 1, 0, 1, n_valid=150))
    np.testing.assert_allclose(ragged, full, rtol=1e-10)


def test_exact_objective_ar1_beats_css_on_oracle_scale():
    """fit(objective="exact") scores ≥ the CSS solution under the
    independent closed-form AR(1) exact likelihood."""
    y = jnp.asarray(_ar1(300, 0.6, seed=5, const=0.4))
    css = arima.fit(1, 0, 0, y, warn=False)
    exact = arima.fit(1, 0, 0, y, warn=False, objective="exact")
    nll_css = _ar1_concentrated_nll(np.asarray(css.coefficients),
                                    np.asarray(y))
    nll_ex = _ar1_concentrated_nll(np.asarray(exact.coefficients),
                                   np.asarray(y))
    assert nll_ex <= nll_css + 1e-9
    assert bool(np.all(np.asarray(exact.diagnostics.converged)))
    # diagnostics.fun IS the exact objective for exact fits
    np.testing.assert_allclose(float(exact.diagnostics.fun), nll_ex,
                               rtol=1e-8)


def test_fit_rejects_unknown_objective():
    with pytest.raises(ValueError, match="objective"):
        arima.fit(1, 0, 0, jnp.zeros(50), objective="banana")


@pytest.mark.slow
def test_exact_fit_tier1_fixtures_loglik_dominates_css():
    """Acceptance pin: on both R golden fixtures the exact-objective fit
    converges and its exact loglik is ≥ the CSS solution's."""
    for name, (p, d, q) in (("R_ARIMA_DataSet1.csv", (1, 0, 1)),
                            ("R_ARIMA_DataSet2.csv", (0, 3, 1))):
        data = _r_fixture(name)
        css = arima.fit(p, d, q, data, warn=False)
        exact = arima.fit(p, d, q, data, warn=False, objective="exact")
        ll_css = float(css.log_likelihood_exact(data))
        ll_ex = float(exact.log_likelihood_exact(data))
        assert np.isfinite(ll_ex), (name, ll_ex)
        assert ll_ex >= ll_css - 1e-6, (name, ll_ex, ll_css)
        assert bool(np.all(np.asarray(exact.diagnostics.converged))), name
    # the ARMA(1,1) fixture's known generating parameters stay in reach
    c, ar, ma = np.asarray(exact.coefficients) if False else \
        np.asarray(arima.fit(1, 0, 1, _r_fixture("R_ARIMA_DataSet1.csv"),
                             warn=False, objective="exact").coefficients)
    assert abs(ar - 0.3) < 0.1
    assert abs(ma - 0.7) < 0.1


# ---------------------------------------------------------------------------
# converters: the fitted recurrences ARE the innovations filter
# ---------------------------------------------------------------------------

def _arma_panel(S, n, seed=0, phi=0.5, dtype=np.float64):
    rng = np.random.default_rng(seed)
    e = rng.normal(size=(S, n + 8)).astype(dtype)
    y = np.zeros((S, n + 8), dtype)
    for t in range(1, n + 8):
        y[:, t] = 0.3 + phi * y[:, t - 1] + e[:, t]
    return y[:, 8:]


def test_ewma_session_is_the_smoothing_recurrence():
    panel = _arma_panel(4, 60, seed=21)
    model = ewma.fit(jnp.asarray(panel))
    sess = ss.ServingSession.start(model, panel)
    level = np.asarray(
        model.add_time_dependent_effects(jnp.asarray(panel))[:, -1])
    np.testing.assert_allclose(np.asarray(sess._state.a[:4, 0]), level,
                               rtol=1e-10)
    # one tick advances the level by exactly S' = S + α(y - S)
    tick = panel[:, -1] * 0.5 + 1.0
    sess.update(tick)
    alpha = np.asarray(model.smoothing)
    np.testing.assert_allclose(
        np.asarray(sess._state.a[:4, 0]),
        level + alpha * (tick - level), rtol=1e-10)
    # and the flat SES forecast repeats the level at every horizon
    fc = sess.forecast(5)
    assert fc.shape == (4, 5)
    np.testing.assert_allclose(fc, np.broadcast_to(fc[:, :1], fc.shape),
                               rtol=1e-12)


def test_holt_winters_session_forecast_matches_model():
    period, n = 4, 48
    rng = np.random.default_rng(9)
    t = np.arange(n)
    y = (10.0 + 0.25 * t + 2.0 * np.sin(2 * np.pi * t / period)
         + 0.1 * rng.normal(size=n))
    model = holt_winters.fit(jnp.asarray(y), period)
    sess = ss.ServingSession.start(model, y)
    got = sess.forecast(2 * period)[0]
    want = np.asarray(model.forecast(jnp.asarray(y), 2 * period))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-8)


def test_multiplicative_holt_winters_has_no_statespace_form():
    period, n = 4, 40
    t = np.arange(n, dtype=float)
    y = (10.0 + 0.2 * t) * (1.0 + 0.1 * np.sin(2 * np.pi * t / period))
    model = holt_winters.fit(jnp.asarray(y), period,
                             model_type="multiplicative")
    with pytest.raises(NotImplementedError, match="multiplicative"):
        ss.to_statespace(model)


def test_parallel_prefix_filter_matches_sequential():
    """filter_panel_parallel (associative-scan affine recurrence) ==
    filter_panel on a pinned-gain model, including missing ticks."""
    panel = _arma_panel(3, 50, seed=13)
    panel[2, 17] = np.nan
    model = ewma.fit(jnp.asarray(np.nan_to_num(panel)))
    ssm, meta = ss.to_statespace(model)
    state0 = initial_state(ssm, meta)
    seq = filter_panel(ssm, state0, jnp.asarray(panel), meta)
    par = filter_panel_parallel(ssm, state0, jnp.asarray(panel), meta)
    np.testing.assert_allclose(np.asarray(par.state.a),
                               np.asarray(seq.state.a), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(par.loglik),
                               np.asarray(seq.loglik), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(par.state.ssq),
                               np.asarray(seq.state.ssq), rtol=1e-9)
    assert np.array_equal(np.asarray(par.state.n_obs),
                          np.asarray(seq.state.n_obs))


def test_parallel_filter_rejects_exact_mode():
    rng = np.random.default_rng(1)
    ssm = _random_ssm(rng, 2, 2)
    meta = SSMeta("arima", "exact", 0, 2)
    with pytest.raises(ValueError, match="pinned-gain"):
        filter_panel_parallel(ssm, initial_state(ssm, meta),
                              jnp.zeros((2, 10)), meta)


# ---------------------------------------------------------------------------
# serving sessions: incremental == batch, checkpoint round-trip, 0 compiles
# ---------------------------------------------------------------------------

def test_session_updates_match_batch_bootstrap():
    """Ticking the tail one observation at a time lands on the same
    filtered state as bootstrapping over the full history: the h-step
    forecasts agree to float rounding (σ² calibration differs across the
    two windows, but the Kalman gain — hence the mean path — is
    scale-invariant).  This is the update-vs-batch consistency pin, on a
    d=1 family so the raw-difference ring is exercised too."""
    S, n, k = 6, 160, 12
    rng = np.random.default_rng(17)
    base = _arma_panel(S, n, seed=17)
    panel = np.cumsum(base + 0.1 * rng.normal(size=base.shape), axis=1)
    model = arima.fit(1, 1, 1, jnp.asarray(panel), warn=False)

    batch = ss.ServingSession.start(model, panel)
    inc = ss.ServingSession.start(model, panel[:, :-k])
    for t in range(k):
        out = inc.update(panel[:, n - k + t])
        assert np.isfinite(out.variances).all()
    assert inc.ticks_seen == batch.ticks_seen == n
    np.testing.assert_allclose(inc.forecast(8), batch.forecast(8),
                               rtol=1e-6, atol=1e-8)


def test_checkpoint_roundtrip_resumes_identically(tmp_path):
    panel = _arma_panel(5, 80, seed=23)
    model = arima.fit(2, 0, 1, jnp.asarray(panel), warn=False)
    sess = ss.ServingSession.start(model, panel)
    sess.update(panel[:, -1])
    path = str(tmp_path / "serving.ckpt")
    sess.checkpoint(path)
    back = ss.ServingSession.restore(path)
    assert back.describe() == sess.describe()
    np.testing.assert_allclose(back.loglik, sess.loglik, rtol=0, atol=0)
    # the restored session serves on: identical tick outcome + forecast
    tick = panel[:, -1] * 0.9
    a = sess.update(tick)
    b = back.update(tick)
    np.testing.assert_array_equal(a.innovations, b.innovations)
    np.testing.assert_array_equal(a.loglik_inc, b.loglik_inc)
    np.testing.assert_array_equal(sess.forecast(4), back.forecast(4))


def test_restore_rejects_unknown_format(tmp_path):
    from spark_timeseries_tpu.utils import checkpoint
    path = str(tmp_path / "bad.ckpt")
    checkpoint.save_pytree_atomic(path, {"format": 99})
    with pytest.raises(ValueError, match="format"):
        ss.ServingSession.restore(path)


def test_update_validates_tick_count():
    panel = _arma_panel(3, 40, seed=2)
    model = ewma.fit(jnp.asarray(panel))
    sess = ss.ServingSession.start(model, panel)
    with pytest.raises(ValueError, match="one tick per series"):
        sess.update(np.zeros(5))
    with pytest.raises(ValueError, match="horizon"):
        sess.forecast(0)


def test_nan_tick_is_a_missing_observation():
    panel = _arma_panel(2, 60, seed=31)
    model = arima.fit(1, 0, 1, jnp.asarray(panel), warn=False)
    sess = ss.ServingSession.start(model, panel)
    ll0 = sess.loglik.copy()
    out = sess.update(np.array([np.nan, 1.0]))
    assert np.isnan(out.innovations[0]) and np.isfinite(out.innovations[1])
    assert out.loglik_inc[0] == 0.0 and out.loglik_inc[1] != 0.0
    np.testing.assert_allclose(sess.loglik, ll0 + out.loglik_inc)


def test_warmed_update_triggers_zero_compiles():
    """Acceptance pin (as in test_engine.py): after warmup, N updates and
    a pre-compiled-horizon forecast record exactly zero XLA compiles."""
    metrics.install_jax_hooks()
    panel = _arma_panel(4, 60, seed=41)
    model = arima.fit(1, 0, 1, jnp.asarray(panel), warn=False)
    sess = ss.ServingSession.start(model, panel)
    sess.warmup()
    sess.forecast(6)                        # compile this horizon's program
    before = metrics.jax_stats()["jit_compiles"]
    for t in range(5):
        sess.update(panel[:, t])
    sess.forecast(6)
    after = metrics.jax_stats()["jit_compiles"]
    assert after - before == 0, \
        f"{after - before} compiles leaked into the warmed tick path"


def test_no_optimizer_reachable_from_tick_path(monkeypatch):
    """O(1) guarantee, negatively: with every minimizer and fit entry
    point booby-trapped, update/forecast still serve — no re-optimization
    path is reachable from a tick."""
    panel = _arma_panel(3, 50, seed=43)
    model = arima.fit(1, 0, 1, jnp.asarray(panel), warn=False)
    sess = ss.ServingSession.start(model, panel)
    sess.warmup()

    def boom(*a, **k):
        raise AssertionError("optimizer reached from the tick path")

    from spark_timeseries_tpu.models import (arima as m_arima,
                                             autoregression as m_ar)
    from spark_timeseries_tpu.ops import optimize
    for mod, names in ((optimize, [n for n in dir(optimize)
                                   if n.startswith("minimize_")]),
                       (m_arima, ["fit", "fit_panel"]),
                       (m_ar, ["fit", "fit_panel"])):
        for name in names:
            monkeypatch.setattr(mod, name, boom)
    sess.update(panel[:, 0])
    sess.update(np.array([1.0, np.nan, 2.0]))
    assert sess.forecast(3).shape == (3, 3)


@pytest.mark.slow
def test_1024_series_tick_is_one_cached_step():
    """Acceptance pin: a 1024-series session ticks through the same single
    cached executable — zero compiles after warmup, O(m²) state per lane."""
    metrics.install_jax_hooks()
    n_series, n_hist = 1024, 64
    one = _arma_panel(1, 200, seed=47)[0]
    model = arima.fit(1, 0, 1, jnp.asarray(one), warn=False)  # scalar model
    rng = np.random.default_rng(51)
    hist = rng.normal(size=(n_series, n_hist))
    sess = ss.ServingSession.start(model, hist)   # broadcast over the panel
    assert sess.describe()["bucket"] == 1024
    sess.warmup()
    before = metrics.jax_stats()["jit_compiles"]
    for t in range(3):
        out = sess.update(rng.normal(size=n_series))
        assert out.innovations.shape == (n_series,)
    assert metrics.jax_stats()["jit_compiles"] - before == 0
    # state really is O(m²) per series, not O(history): the filter carry
    # (a, P, ring, 3 accumulators, n_obs) plus the health monitor's
    # O(m) leaves (ew, status, good_a, good_ring)
    m = sess.describe()["state_dim"]
    d = sess.describe()["d_order"]
    per_series = sess.state_bytes / sess.describe()["bucket"]
    assert per_series <= 8 * (m * m + m + 5) + 4 * (m + d + 2)


def test_sessions_share_one_executable_across_instances():
    """Two same-shape sessions share the module-level jit cache — the
    second session's first update compiles nothing."""
    metrics.install_jax_hooks()
    panel = _arma_panel(4, 60, seed=53)
    model = arima.fit(1, 0, 1, jnp.asarray(panel), warn=False)
    first = ss.ServingSession.start(model, panel)
    first.warmup()
    second = ss.ServingSession.start(model, panel * 0.5 + 1.0)
    before = metrics.jax_stats()["jit_compiles"]
    second.update(panel[:, 3])
    assert metrics.jax_stats()["jit_compiles"] - before == 0


# ---------------------------------------------------------------------------
# warmup + gate wiring
# ---------------------------------------------------------------------------

def test_warmup_update_covers_every_serving_family():
    for fam in WARMUP_FAMILIES:
        rep = warmup_update(fam, 8, period=4)
        assert rep["bucket"] == 8
        assert rep["state_dim"] >= 1
        assert rep["mode"] in ("exact", "innovations")
    with pytest.raises(ValueError, match="serving form"):
        warmup_update("garch", 8)


@pytest.mark.slow
def test_engine_cli_serving_warmup(capsys):
    """`python -m spark_timeseries_tpu.engine --serving` warms the
    per-tick executables alongside the fit programs."""
    import json as _json
    from spark_timeseries_tpu import engine as E
    rc = E.main(["--families", "arima", "--shapes", "8x48", "--serving"])
    assert rc == 0
    report = _json.loads(capsys.readouterr().out)
    assert report["serving"], report
    assert report["serving"][0]["family"] == "arima"
    assert report["serving"][0]["bucket"] == 8


def test_bench_gate_extracts_serving_slo():
    from tools.bench_gate import extract_metrics
    # spans nest under their enclosing scope when bench drives the
    # session — the extractor must match the path leaf, preferring the
    # busiest entry, and never confuse "Xserving.update" for a leaf
    headline = {"value": 100.0, "metrics": {"spans": {
        "bench.serving_demo/serving.update":
            {"count": 64, "p50_s": 0.004, "p95_s": 0.009},
        "other/serving.update": {"count": 2, "p50_s": 9.0, "p95_s": 9.0},
        "warmserving.update": {"count": 99, "p50_s": 7.0, "p95_s": 7.0},
    }}}
    got = extract_metrics(headline)
    assert got["serving_update_p50"] == pytest.approx(0.004)
    assert got["serving_update_p95"] == pytest.approx(0.009)
    flat = extract_metrics({"value": 1.0, "metrics": {"spans": {
        "serving.update": {"count": 8, "p50_s": 0.002, "p95_s": 0.003}}}})
    assert flat["serving_update_p50"] == pytest.approx(0.002)
    # absent span (pre-serving rounds) -> no fabricated zeros
    assert "serving_update_p50" not in extract_metrics(
        {"value": 1.0, "metrics": {"spans": {}}})


def test_serving_metrics_accounting():
    reg = metrics.MetricsRegistry()
    panel = _arma_panel(2, 40, seed=61)
    model = ewma.fit(jnp.asarray(panel))
    sess = ss.ServingSession.start(model, panel, registry=reg)
    sess.update(panel[:, -1])
    sess.update(panel[:, -1])
    sess.forecast(3)
    snap = reg.snapshot()
    assert snap["counters"]["serving.sessions"] == 1
    assert snap["counters"]["serving.updates"] == 2
    assert snap["counters"]["serving.ticks"] == 4
    assert snap["counters"]["serving.forecasts"] == 1
    assert snap["gauges"]["serving.state_bytes"] > 0
