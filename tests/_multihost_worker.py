"""Worker process for the 2-process ``jax.distributed`` test.

Invoked as ``python _multihost_worker.py <process_id> <port>``.  Each
process owns 2 virtual CPU devices; together they form a 4-device global
mesh — the CPU-local stand-in for two DCN-connected TPU hosts (the
reference's analogue is Spark `local-cluster` testing,
ref LocalSparkContext.scala:23-61).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

pid = int(sys.argv[1])
port = sys.argv[2]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from spark_timeseries_tpu import parallel  # noqa: E402

got_pid, count = parallel.initialize_multihost(f"127.0.0.1:{port}", 2, pid)
assert got_pid == pid, (got_pid, pid)
assert count == 2, count
assert len(jax.devices()) == 4          # 2 local x 2 processes

mesh = parallel.make_mesh(4, 1)

data = np.arange(48.0).reshape(8, 6)
arr = jax.make_array_from_callback(
    data.shape, parallel.series_sharding(mesh), lambda idx: data[idx])

# driver-collect equivalent: every process materializes the full panel
out = parallel.collect(arr)
assert out.shape == (8, 6)
np.testing.assert_allclose(out, data)

# cross-shard OR-reduction (the aggregate/mask-reduce equivalent)
mask = data > 40.0
marr = jax.make_array_from_callback(
    mask.shape, parallel.series_sharding(mesh), lambda idx: mask[idx])
with mesh:
    any_per_instant = parallel.instant_mask_any(marr, mesh)
collected = parallel.collect(any_per_instant)
np.testing.assert_array_equal(collected, mask.any(axis=0))

# a batched model fit over the globally sharded panel
import jax.numpy as jnp  # noqa: E402
from spark_timeseries_tpu.models import ewma  # noqa: E402

rng = np.random.default_rng(0)
panel_np = rng.normal(size=(8, 64)).cumsum(axis=1)
panel = jax.make_array_from_callback(
    panel_np.shape, parallel.series_sharding(mesh), lambda i: panel_np[i])
fitted = jax.jit(
    lambda v: ewma.fit(v, max_iter=20).smoothing,
    in_shardings=parallel.series_sharding(mesh))(panel)
sm = parallel.collect(fitted)
assert sm.shape == (8,)
assert np.all(np.isfinite(sm))

# sharded-vs-unsharded equivalence ACROSS PROCESS BOUNDARIES (round-4
# verdict item 6): the globally-sharded fit must equal the same fit run
# unsharded in this process, to f64 tolerance — distribution must not
# change per-lane math
ref_sm = np.asarray(ewma.fit(jnp.asarray(panel_np), max_iter=20).smoothing)
np.testing.assert_allclose(sm, ref_sm, rtol=1e-10, atol=1e-12)

from spark_timeseries_tpu.models import arima  # noqa: E402

coef_sharded = parallel.collect(jax.jit(
    lambda v: arima.fit(1, 0, 1, v, warn=False).coefficients,
    in_shardings=parallel.series_sharding(mesh))(panel))
coef_ref = np.asarray(
    arima.fit(1, 0, 1, jnp.asarray(panel_np), warn=False).coefficients)
np.testing.assert_allclose(coef_sharded, coef_ref, rtol=1e-10, atol=1e-12)

print(f"MULTIHOST_OK {pid}", flush=True)
