"""Live forecast-quality plane (ISSUE 15): per-tick anomaly scores,
rolling online accuracy, Page-Hinkley drift alarms, drift-driven
auto-refit.

The acceptance scenario lives here: a seeded regime-shift stream trips
``drifted`` on exactly the shifted lanes (and nothing else),
``heal(drifted=True)`` refits them from the history ring, and post-heal
online sMAPE recovers to within a pinned band of a fresh fit on the
same window — with the warmed tick path at zero recompiles while
quality tracking AND the telemetry exporter are both armed.  The
false-positive half: a stationary 5000-tick stream must alarm nothing
(the same calibration bench's ``drift_false_alarms`` zero-baseline
gate enforces round over round).

Oracle strategy mirrors ``test_statespace.py``: the in-graph anomaly
score is pinned against a scalar loop-based NumPy prediction-form
filter written from the textbook recursion (no code shared with the
JAX kernels), and the EW online metrics against an offline NumPy
recomputation from the session's own one-step forecasts.

Everything here runs in tier-1 and under ``make verify-quality``
(plain + ``STS_FAULT_INJECT=1``, the ``quality`` marker).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_timeseries_tpu import statespace as ss
from spark_timeseries_tpu.models import arima
from spark_timeseries_tpu.statespace.health import (
    LANE_DIVERGED, LANE_DRIFTED, LANE_OK)
from spark_timeseries_tpu.statespace.quality import (
    QualityPolicy, forecast_half_widths, initial_quality, naive_scale,
    quality_panel)
from spark_timeseries_tpu.utils import metrics, resilience

pytestmark = pytest.mark.quality


def _ar2_panel(S, n, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    e = rng.normal(size=(S, n + 16)).astype(dtype)
    y = np.zeros((S, n + 16), dtype)
    for t in range(2, n + 16):
        y[:, t] = 0.3 + 0.5 * y[:, t - 1] - 0.2 * y[:, t - 2] + e[:, t]
    return y[:, 16:]


def _quality_session(S=6, n_hist=300, n_live=80, seed=3, **kwargs):
    panel = _ar2_panel(S, n_hist + n_live, seed=seed)
    hist, live = panel[:, :n_hist], panel[:, n_hist:]
    model = arima.fit(2, 0, 0, jnp.asarray(hist), warn=False)
    sess = ss.ServingSession.start(
        model, hist, quality=kwargs.pop("quality", QualityPolicy()),
        **kwargs)
    return sess, hist, live


# ---------------------------------------------------------------------------
# policy validation + key separation
# ---------------------------------------------------------------------------

def test_quality_policy_validation_rejects_nonsense():
    with pytest.raises(ValueError, match="horizon"):
        QualityPolicy(horizon=0).validate()
    with pytest.raises(ValueError, match="ew_alpha"):
        QualityPolicy(ew_alpha=0.0).validate()
    with pytest.raises(ValueError, match="ph_delta"):
        QualityPolicy(ph_delta=-1.0).validate()
    with pytest.raises(ValueError, match="coverage"):
        QualityPolicy(coverage=1.5).validate()


def test_update_key_separates_quality_from_plain_sessions():
    """Arming quality changes the traced program, so it must change the
    executable key — a quality-on and a quality-off session (or two
    different quality policies) may never coalesce into one fleet
    group."""
    sess_q, hist, _ = _quality_session(S=3, n_live=4)
    model = arima.fit(2, 0, 0, jnp.asarray(hist), warn=False)
    sess_plain = ss.ServingSession.start(model, hist)
    assert sess_q.update_key != sess_plain.update_key
    sess_q2 = ss.ServingSession.start(
        model, hist, quality=QualityPolicy(ph_lambda=99.0))
    assert sess_q2.update_key != sess_q.update_key
    assert sess_q2.update_key[:4] == sess_q.update_key[:4]


# ---------------------------------------------------------------------------
# anomaly-score oracle (the satellite's pinned-equality test)
# ---------------------------------------------------------------------------

def _np_anomaly_path(ssm, a0, P0, ys):
    """Scalar loop-based textbook prediction-form filter: per-tick
    standardized innovations v/sqrt(F) in float64, NaN ticks
    predict-only — written from the recursion, no JAX code shared."""
    S, n = ys.shape
    out = np.full((S, n), np.nan)
    for i in range(S):
        T = np.asarray(ssm.T[i], np.float64)
        Z = np.asarray(ssm.Z[i], np.float64)
        c = np.asarray(ssm.c[i], np.float64)
        d = float(ssm.d[i])
        H = float(ssm.H[i])
        Q = np.asarray(ssm.Q[i], np.float64)
        a = np.asarray(a0[i], np.float64).copy()
        P = np.asarray(P0[i], np.float64).copy()
        for t in range(n):
            y = ys[i, t]
            v = y - d - Z @ a
            F = Z @ P @ Z + H
            out[i, t] = v / np.sqrt(F)
            if np.isfinite(y):
                K = (T @ P @ Z) / F
                a = T @ a + c + K * v
                P = T @ P @ T.T + Q - F * np.outer(K, K)
            else:
                a = T @ a + c
                P = T @ P @ T.T + Q
    return out


def test_anomaly_score_matches_numpy_oracle():
    """Pinned equality of the in-graph per-tick score against an
    offline NumPy standardized-innovation computation on a seeded
    stream, including NaN (missing) and predict-only (quarantined)
    ticks."""
    sess, hist, live = _quality_session(S=4, n_live=24, seed=7)
    k = 16
    ticks = live[:, :k].copy()
    ticks[1, 5] = np.nan                   # a missing tick mid-stream
    a0 = np.asarray(sess._state.a[:4])
    P0 = np.asarray(sess._state.P[:4])
    want = _np_anomaly_path(sess._ssm, a0, P0, ticks.astype(np.float64))
    got = np.stack([sess.update(ticks[:, t]).anomaly for t in range(k)],
                   axis=1)
    # the missing tick reports NaN, everything else matches the oracle
    assert np.isnan(got[1, 5]) and np.isnan(want[1, 5])
    m = np.isfinite(want)
    np.testing.assert_allclose(got[m], want[m], atol=5e-3)
    # and the score is definitionally v/sqrt(F) of the same TickResult
    out = sess.update(live[:, k])
    np.testing.assert_allclose(
        out.anomaly, out.innovations / np.sqrt(out.variances), rtol=1e-6)
    np.testing.assert_allclose(
        out.anomaly_ew, np.asarray(sess._health.ew[:4]), rtol=0, atol=0)
    # quarantined lanes are predict-only: NaN anomaly from the next tick
    with resilience.fault_injection("state_poison", lane_stride=2):
        sess.update(live[:, k + 1])
    out = sess.update(live[:, k + 2])
    assert np.isnan(out.anomaly[::2]).all()
    assert np.isfinite(out.anomaly[1::2]).all()


def test_anomaly_rides_tickresult_without_quality_armed():
    """The anomaly surface is unconditional — a plain (quality-off)
    session reports it too, straight off the health machinery."""
    panel = _ar2_panel(3, 320, seed=11)
    model = arima.fit(2, 0, 0, jnp.asarray(panel[:, :300]), warn=False)
    sess = ss.ServingSession.start(model, panel[:, :300])
    out = sess.update(panel[:, 300])
    np.testing.assert_allclose(
        out.anomaly, out.innovations / np.sqrt(out.variances), rtol=1e-6)
    assert out.anomaly_ew.shape == (3,)
    assert sess.quality_summary() is None
    assert "quality" not in sess.telemetry_summary()


# ---------------------------------------------------------------------------
# online accuracy: the EW metrics match an offline recomputation
# ---------------------------------------------------------------------------

def test_online_accuracy_matches_offline_recomputation():
    """h=1: the ring's due forecast is exactly ``forecast(1)`` off the
    pre-tick state, so recomputing the EW sMAPE/MASE/coverage from the
    session's own forecasts must land on the in-graph EW means."""
    pol = QualityPolicy(ew_alpha=0.1)
    sess, hist, live = _quality_session(S=5, n_live=40, seed=13,
                                        quality=pol)
    sess.warmup()
    k = 32
    scale = np.asarray(sess._qstate.scale[:5], np.float64)
    half = np.asarray(sess._qstate.half[:5], np.float64)
    fcs, ys = [], []
    for t in range(k):
        fcs.append(sess.forecast(1)[:, 0])     # prediction for tick t
        sess.update(live[:, t])
        ys.append(live[:, t])
    fcs = np.asarray(fcs, np.float64)          # (k, S)
    ys = np.asarray(ys, np.float64)

    # offline EW fold with the same definitions (tick 0 is ring warmup)
    ew_s = np.zeros(5)
    ew_m = np.zeros(5)
    ew_c = np.zeros(5)
    seen = np.zeros(5, bool)
    beta = pol.ew_alpha
    for t in range(1, k):
        ae = np.abs(fcs[t] - ys[t])
        denom = np.abs(fcs[t]) + np.abs(ys[t])
        sm = np.where(denom > 0, 200.0 * ae / np.where(denom > 0,
                                                       denom, 1.0), 0.0)
        ms = ae / scale
        cv = (ae <= half).astype(float)
        for ew, pt in ((ew_s, sm), (ew_m, ms), (ew_c, cv)):
            ew[:] = np.where(seen, (1 - beta) * ew + beta * pt, pt)
        seen[:] = True
    np.testing.assert_allclose(np.asarray(sess._qstate.ew_smape[:5]),
                               ew_s, rtol=2e-3)
    np.testing.assert_allclose(np.asarray(sess._qstate.ew_mase[:5]),
                               ew_m, rtol=2e-3)
    np.testing.assert_allclose(np.asarray(sess._qstate.ew_cover[:5]),
                               ew_c, rtol=2e-3)
    assert (np.asarray(sess._qstate.n_scored[:5]) == k - 1).all()
    summ = sess.quality_summary()
    assert summ["scored_lanes"] == 5
    np.testing.assert_allclose(summ["live_smape"], ew_s.mean(),
                               rtol=5e-3)


def test_constant_history_lane_never_dilutes_live_mase():
    """Review-finding pin: a lane whose history is constant has no valid
    MASE scale (naive_scale = 0) — it scores sMAPE/coverage but must be
    EXCLUDED from the live_mase aggregate, not averaged in as a perfect
    0.0."""
    from spark_timeseries_tpu.models import ewma

    S, n_hist = 4, 300
    panel = _ar2_panel(S, n_hist + 20, seed=97)
    panel[0, :] = 5.0                 # constant lane (history + live)
    model = ewma.fit(jnp.asarray(panel[:, :n_hist]))
    sess = ss.ServingSession.start(model, panel[:, :n_hist],
                                   quality=QualityPolicy())
    assert float(sess._qstate.scale[0]) == 0.0
    for t in range(10):
        sess.update(panel[:, n_hist + t])
    qs = np.asarray(sess._qstate.n_scored[:S])
    assert (qs > 0).all()                    # everyone scores sMAPE
    summ = sess.quality_summary()
    want = np.asarray(sess._qstate.ew_mase[1:S]).mean()
    np.testing.assert_allclose(summ["live_mase"], want, rtol=5e-3)


def test_fit_time_baselines_scale_and_half():
    """The MASE scale is the ring history's lag-1 naive MAE and the
    coverage half-width the ψ-weight construction off the calibrated
    ssm — both per-lane, both finite on a healthy fit."""
    sess, hist, _ = _quality_session(S=4, n_live=4, seed=17)
    scale = np.asarray(sess._qstate.scale[:4])
    ring = sess._ring_history()
    want = naive_scale(ring)
    np.testing.assert_allclose(scale, want, rtol=1e-5)
    half = np.asarray(sess._qstate.half[:4])
    assert (half > 0).all() and np.isfinite(half).all()
    # h=1 exact-mode half-width is z * sigma (psi_0 = sigma)
    want_half = np.asarray(forecast_half_widths(
        sess._ssm, sess.meta, 1, 0.9))[:4]
    np.testing.assert_allclose(half, want_half, rtol=1e-6)


# ---------------------------------------------------------------------------
# drift: the closed-loop acceptance scenario + false-alarm calibration
# ---------------------------------------------------------------------------

def test_drift_closed_loop_regime_shift_heal_recovers():
    """Acceptance pin: a seeded regime shift trips ``drifted`` on
    exactly the shifted lanes, ``heal(drifted=True)`` refits them from
    the (post-shift-dominated) history ring, and post-heal online sMAPE
    recovers to within a pinned band of a fresh fit on the same window
    — all with zero recompiles on the warmed tick path."""
    S, n_hist = 8, 300
    n_live = 400
    panel = _ar2_panel(S, n_hist + n_live, seed=29)
    hist, live = panel[:, :n_hist], panel[:, n_hist:]
    model = arima.fit(2, 0, 0, jnp.asarray(hist), warn=False)
    metrics.install_jax_hooks()
    reg = metrics.MetricsRegistry()
    sess = ss.ServingSession.start(model, hist, registry=reg,
                                   quality=QualityPolicy(),
                                   history_ring=128)
    sess.warmup()

    # stationary prefix: nothing drifts
    for t in range(40):
        out = sess.update(live[:, t])
    assert (out.status == LANE_OK).all()
    assert sess._drift_alarms == 0

    # regime shift on lanes ::3: a level shift sized to degrade
    # accuracy persistently but stay far inside the χ² diverged band
    shifted = np.arange(S)[::3]
    shift = np.zeros(S, np.float32)
    shift[shifted] = 1.5
    before = metrics.jax_stats()["jit_compiles"]
    for t in range(40, 190):
        out = sess.update(live[:, t] + shift)
    assert metrics.jax_stats()["jit_compiles"] - before == 0
    drifted = np.flatnonzero(out.status == LANE_DRIFTED)
    np.testing.assert_array_equal(drifted, shifted)
    others = np.setdiff1d(np.arange(S), shifted)
    assert (out.status[others] == LANE_OK).all()
    assert not (out.status == LANE_DIVERGED).any()
    assert sess._drift_alarms == shifted.size
    assert reg.snapshot()["counters"]["serving.drift_alarms"] \
        == shifted.size
    pre_smape = np.asarray(sess._qstate.ew_smape[:S])[shifted].mean()

    # drifted lanes keep serving (finite forecasts — not quarantined)
    assert np.isfinite(sess.forecast(4)).all()

    # close the loop: refit the drifted lanes from the ring (by now
    # the 128-tick ring is pure post-shift regime)
    report = sess.heal(drifted=True)
    assert report["drifted"] == shifted.size
    assert report["healed"] == shifted.size
    assert report["dead"] == 0
    assert (sess.lane_status == LANE_OK).all()
    # quality re-baselined on healed lanes
    assert (np.asarray(sess._qstate.n_scored[:S])[shifted] == 0).all()
    assert not np.asarray(sess._qstate.drifted[:S]).any()

    # post-heal: same warmed executable, zero new tick-path compiles
    before2 = metrics.jax_stats()["jit_compiles"]
    for t in range(190, 320):
        out = sess.update(live[:, t] + shift)
    assert metrics.jax_stats()["jit_compiles"] - before2 == 0
    assert (out.status == LANE_OK).all()     # no re-alarm post-refit
    post_smape = np.asarray(sess._qstate.ew_smape[:S])[shifted].mean()

    # fresh-fit baseline: fit on exactly the shifted-regime window the
    # heal refit saw, stream the same post-heal ticks, compare sMAPE
    ring_window = np.concatenate(
        [hist] + [(live[:, t] + shift)[:, None] for t in range(190)],
        axis=1)[:, -128:]
    fresh_model = arima.fit(2, 0, 0, jnp.asarray(ring_window[shifted]),
                            warn=False)
    fresh = ss.ServingSession.start(fresh_model, ring_window[shifted],
                                    registry=reg,
                                    quality=QualityPolicy())
    for t in range(190, 320):
        fresh.update((live[:, t] + shift)[shifted])
    fresh_smape = np.asarray(
        fresh._qstate.ew_smape[:shifted.size]).mean()
    # the pinned recovery band: healed accuracy ~ fresh-fit accuracy,
    # and clearly better than the drifted pre-heal accuracy
    assert abs(post_smape - fresh_smape) <= 0.25 * fresh_smape + 2.0, \
        (post_smape, fresh_smape)
    assert post_smape < pre_smape, (post_smape, pre_smape)


def test_stationary_5000_ticks_zero_drift_false_alarms():
    """False-positive half of the drift story: 5000 well-specified
    ticks across 32 lanes through the fused quality step (the scan
    driver) must alarm nothing and leave every lane OK — the same
    calibration bench's ``drift_false_alarms`` zero-baseline gate
    enforces."""
    S, n_hist, n_live = 32, 400, 5000
    panel = _ar2_panel(S, n_hist + n_live, seed=41)
    hist, live = panel[:, :n_hist], panel[:, n_hist:]
    model = arima.fit(2, 0, 0, jnp.asarray(hist), warn=False)
    sess = ss.ServingSession.start(model, hist, quality=QualityPolicy())
    padded = np.pad(live, ((0, sess._bucket - S), (0, 0)),
                    constant_values=np.nan)
    state, health, qstate = quality_panel(
        sess._ssm, sess._state, sess._health, sess._qstate,
        jnp.asarray(padded), sess.meta, sess.policy, sess._quality)
    status = np.asarray(health.status[:S])
    assert (status == LANE_OK).all(), status
    assert not np.asarray(qstate.drifted[:S]).any()
    # the CUSUM stays far from the alarm threshold on a healthy stream
    ph = np.asarray(qstate.ph[:S])
    assert float(ph.max()) < sess._quality.ph_lambda / 2, ph.max()
    # and the online metrics are sane: MASE ~ O(1), coverage ~ nominal
    ms = np.asarray(qstate.ew_mase[:S])
    cv = np.asarray(qstate.ew_cover[:S])
    assert 0.3 < float(ms.mean()) < 2.0
    assert 0.75 < float(cv.mean()) <= 1.0


def test_tick_corruption_degrades_to_unscored_never_alarms():
    """Satellite: the serving tier's tick-corruption fault modes with
    quality armed — corrupt wire data must neither score nor advance
    the drift statistic (an unscored tick, not a poisoned metric)."""
    sess, hist, live = _quality_session(S=6, n_live=30, seed=47)
    for t in range(4):
        sess.update(live[:, t])
    ph0 = np.asarray(sess._qstate.ph).copy()
    n0 = np.asarray(sess._qstate.n_scored).copy()
    for mode in ("tick_corrupt_nan", "tick_corrupt_inf"):
        with resilience.fault_injection(mode, lane_stride=1):
            out = sess.update(live[:, 10])
        assert (out.status == LANE_OK).all(), (mode, out.status)
    np.testing.assert_array_equal(np.asarray(sess._qstate.ph), ph0)
    np.testing.assert_array_equal(np.asarray(sess._qstate.n_scored), n0)
    assert sess._drift_alarms == 0
    # clean ticks resume scoring immediately (real lanes; pad lanes of
    # the bucket never score)
    sess.update(live[:, 11])
    assert (np.asarray(sess._qstate.n_scored[:6]) > n0[:6]).all()


# ---------------------------------------------------------------------------
# 0-recompile pin with quality + telemetry armed; snapshot surface
# ---------------------------------------------------------------------------

def test_warmed_update_zero_compiles_with_quality_and_telemetry():
    """Acceptance pin: quality tracking AND the telemetry exporter both
    armed, N warmed updates + a pre-compiled-horizon forecast trigger
    exactly zero XLA compiles — and the scrape surface carries the
    QUALITY panel while traffic flows."""
    import json
    import urllib.request

    from spark_timeseries_tpu.utils import telemetry

    metrics.install_jax_hooks()
    sess, hist, live = _quality_session(S=4, n_live=20, seed=53,
                                        label="qpin")
    srv = telemetry.start(port=0)
    try:
        sess.warmup()
        sess.forecast(6)
        before = metrics.jax_stats()["jit_compiles"]
        for t in range(6):
            sess.update(live[:, t])
        sess.forecast(6)
        assert metrics.jax_stats()["jit_compiles"] - before == 0, \
            "compiles leaked into the quality-armed warmed tick path"
        with urllib.request.urlopen(srv.url + "/snapshot.json",
                                    timeout=5) as resp:
            snap = json.load(resp)
        mine = [s for s in snap["serving_sessions"]
                if s.get("label") == "qpin"]
        assert mine and isinstance(mine[0].get("quality"), dict)
        q = mine[0]["quality"]
        assert q["scored_lanes"] == 4
        assert q["drift_alarms"] == 0
        assert isinstance(q["live_smape"], (int, float))
    finally:
        telemetry.stop()
    # the labeled gauges landed too
    gauges = metrics.snapshot()["gauges"]
    assert "serving.session.qpin.live_smape" in gauges
    assert "serving.session.qpin.anomaly_p95" in gauges
    assert gauges["serving.session.qpin.drift_alarms"] == 0


# ---------------------------------------------------------------------------
# checkpoint round-trip + pre-quality compatibility
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip_with_quality(tmp_path):
    sess, hist, live = _quality_session(S=5, n_live=30, seed=59)
    for t in range(12):
        sess.update(live[:, t])
    path = str(tmp_path / "quality.ckpt")
    sess.checkpoint(path)
    back = ss.ServingSession.restore(path)
    assert back.describe() == sess.describe()
    assert back._quality == sess._quality
    for a, b in zip(sess._qstate, back._qstate):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ta = sess.update(live[:, 12])
    tb = back.update(live[:, 12])
    np.testing.assert_array_equal(ta.anomaly, tb.anomaly)
    np.testing.assert_array_equal(ta.anomaly_ew, tb.anomaly_ew)
    for a, b in zip(sess._qstate, back._qstate):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pre_quality_checkpoint_restores_quality_off(tmp_path):
    """A format-2 checkpoint written before the quality tier (no
    quality keys) restores as a quality-off session — old checkpoints
    are not orphaned by the new optional state."""
    from spark_timeseries_tpu.utils import checkpoint as ckpt

    panel = _ar2_panel(3, 320, seed=61)
    model = arima.fit(2, 0, 0, jnp.asarray(panel[:, :300]), warn=False)
    sess = ss.ServingSession.start(model, panel[:, :300])
    path = str(tmp_path / "prequality.ckpt")
    sess.checkpoint(path)
    blob = ckpt.load_pytree(path)
    blob.pop("quality_policy", None)
    blob.pop("qstate", None)
    old = str(tmp_path / "stripped.ckpt")
    ckpt.save_pytree_atomic(old, blob)
    back = ss.ServingSession.restore(old)
    assert back._quality is None and back._qstate is None
    out = back.update(panel[:, 300])
    assert np.isfinite(out.anomaly).all()


# ---------------------------------------------------------------------------
# fleet: coalesced quality ticks are bitwise the per-session ticks
# ---------------------------------------------------------------------------

def test_fleet_coalesced_quality_matches_solo_sessions():
    """Two quality-armed tenants share one coalescing group (quality
    rides the update key) and their coalesced quality state is bitwise
    the solo sessions' — the fleet pin extended to the quality carry."""
    S, n_hist = 8, 300
    panels = [_ar2_panel(S, n_hist + 8, seed=70 + i) for i in range(2)]
    models = [arima.fit(2, 0, 0, jnp.asarray(p[:, :n_hist]), warn=False)
              for p in panels]
    ref = [ss.ServingSession.start(m, p[:, :n_hist],
                                   quality=QualityPolicy(),
                                   registry=metrics.MetricsRegistry())
           for m, p in zip(models, panels)]
    reg = metrics.MetricsRegistry()
    sched = ss.FleetScheduler(ss.AdmissionPolicy(queue_depth=4),
                              registry=reg, auto_pump=False)
    for i, (m, p) in enumerate(zip(models, panels)):
        sched.attach(ss.ServingSession.start(
            m, p[:, :n_hist], quality=QualityPolicy(),
            label=f"q{i}", registry=reg))
    assert len(sched._groups) == 1           # one coalescing group
    sched.warmup()
    for t in range(6):
        for i in range(2):
            sched.submit(f"q{i}", panels[i][:, n_hist + t])
        reports = sched.pump()
        assert len(reports) == 1
        for i in range(2):
            ref[i].update(panels[i][:, n_hist + t])
    for i in range(2):
        sess = sched.session(f"q{i}")
        for a, b in zip(sess._qstate, ref[i]._qstate):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(sess.lane_status,
                                      ref[i].lane_status)
        assert sess.quality_summary() == ref[i].quality_summary()


# ---------------------------------------------------------------------------
# gate + costs/contracts + console wiring
# ---------------------------------------------------------------------------

def test_bench_gate_extracts_quality_metrics():
    from tools.bench_gate import METRICS, extract_metrics

    names = [m[0] for m in METRICS]
    assert "serving_live_smape" in names
    assert "drift_false_alarms" in names

    h = {"value": 1.0, "serving_demo": {"quality": {
        "live_smape": 4.25, "drift_alarms": 2}}}
    got = extract_metrics(h)
    assert got["serving_live_smape"] == 4.25
    assert got["drift_false_alarms"] == 2.0
    # quality block present, alarms absent = a measured 0 (zero-baseline)
    got = extract_metrics({"value": 1.0, "serving_demo": {
        "quality": {"live_smape": 4.0}}})
    assert got["drift_false_alarms"] == 0.0
    # pre-quality rounds: no fabricated values
    got = extract_metrics({"value": 1.0, "serving_demo": {"panel": 8}})
    assert "serving_live_smape" not in got
    assert "drift_false_alarms" not in got
    # an errored demo contributes nothing
    got = extract_metrics({"value": 1.0,
                           "serving_demo": {"error": "boom"}})
    assert "drift_false_alarms" not in got


def test_bench_gate_flags_first_alarming_round():
    from tools.bench_gate import evaluate

    def mk(r, alarms=None):
        q = {"live_smape": 4.0}
        if alarms is not None:
            q["drift_alarms"] = alarms
        return {"round": r, "rc": 0, "path": f"r{r}", "headline": {
            "metric": "t", "value": 100.0, "platform": "cpu",
            "serving_demo": {"quality": q}}}

    clean = [mk(r) for r in range(1, 4)]
    verdict = evaluate(clean + [mk(4, alarms=3)])
    row = next(r for r in verdict["rows"]
               if r["metric"] == "drift_false_alarms")
    assert row["status"] == "REGRESSED"
    assert verdict["status"] == "regressed"
    verdict = evaluate(clean + [mk(4)])
    assert verdict["status"] == "pass"


def test_quality_update_contract_family():
    """The fused quality-armed program is a first-class contract family:
    no-f64, no-host-callback, stable-jaxpr."""
    from spark_timeseries_tpu.utils.contracts import (CONTRACT_FAMILIES,
                                                      check_family)

    assert "quality_update" in CONTRACT_FAMILIES
    results = check_family("quality_update", 8, 64)
    assert all(r.ok for r in results), \
        [(r.contract, r.detail) for r in results if not r.ok]


def test_warmup_update_compiles_quality_program():
    from spark_timeseries_tpu.statespace.serving import warmup_update

    rep = warmup_update("arima", 8, quality=QualityPolicy())
    assert rep["quality"] is True and rep["bucket"] == 8
    rep = warmup_update("ewma", 8)
    assert rep["quality"] is False


def test_sts_top_quality_panel_renders_and_degrades():
    """The QUALITY panel renders quality-armed sessions, renders its
    absence for quality-off sessions/old exporters, and junk snapshot
    entries never KeyError the frame (the defensive-rendering
    satellite)."""
    from tools.sts_top import render_snapshot

    snap = {"pid": 1, "serving_sessions": [
        {"label": "t0", "family": "arima", "n_series": 8,
         "quality": {"horizon": 1, "scored_lanes": 8,
                     "live_smape": 4.21, "live_mase": 0.93,
                     "live_coverage": 0.91, "anomaly_p95": 1.18,
                     "drifted_lanes": 2, "drift_alarms": 3}},
        {"label": "t1", "family": "ewma", "n_series": 4},   # quality off
        None, "junk",                                        # defensive
    ]}
    frame = render_snapshot(snap)
    assert "QUALITY (1 tracked sessions)" in frame
    assert "4.21" in frame and "t0" in frame
    # an old exporter's snapshot (no quality, no fleets) still renders
    old = {"pid": 2, "serving_sessions": [{"label": "s", "family": "ar"}],
           "jobs": [None], "incidents": ["x"], "fleets": "nope"}
    frame = render_snapshot(old)
    assert "(no quality-tracked sessions)" in frame
    assert "SERVING (1 sessions)" in frame


def test_sts_top_rejects_bad_interval(capsys):
    from tools import sts_top

    for bad in ("0", "-3", "nan"):
        with pytest.raises(SystemExit):
            sts_top.main(["http://127.0.0.1:1", "--once",
                          "--interval", bad])
        assert "--interval" in capsys.readouterr().err
