"""RegressionARIMA (Cochrane-Orcutt) tests — same public textbook datasets
and oracle values as the reference's ``RegressionARIMASuite``
(ref /root/reference/src/test/scala/com/cloudera/sparkts/models/RegressionARIMASuite.scala;
data: PSU STAT 501 metal/vendor example and the UCLA Chatterjee-Price stock
expenditure example)."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_timeseries_tpu.models import regression_arima as ra

METAL = np.array([
    44.2, 44.3, 44.4, 43.4, 42.8, 44.3, 44.4, 44.8, 44.4, 43.1, 42.6, 42.4,
    42.2, 41.8, 40.1, 42, 42.4, 43.1, 42.4, 43.1, 43.2, 42.8, 43, 42.8, 42.5,
    42.6, 42.3, 42.9, 43.6, 44.7, 44.5, 45, 44.8, 44.9, 45.2, 45.2, 45, 45.5,
    46.2, 46.8, 47.5, 48.3, 48.3, 49.1, 48.9, 49.4, 50, 50, 49.6, 49.9, 49.6,
    50.7, 50.7, 50.9, 50.5, 51.2, 50.7, 50.3, 49.2, 48.1])

VENDOR = np.array([
    322.0, 317, 319, 323, 327, 328, 325, 326, 330, 334, 337, 341, 322, 318,
    320, 326, 332, 334, 335, 336, 335, 338, 342, 348, 330, 326, 329, 337,
    345, 350, 351, 354, 355, 357, 362, 368, 348, 345, 349, 355, 362, 367,
    366, 370, 371, 375, 380, 385, 361, 354, 357, 367, 376, 381, 381, 383,
    384, 387, 392, 396])

EXPENDITURE = np.array([
    214.6, 217.7, 219.6, 227.2, 230.9, 233.3, 234.1, 232.3, 233.7, 236.5,
    238.7, 243.2, 249.4, 254.3, 260.9, 263.3, 265.6, 268.2, 270.4, 275.6])

STOCK = np.array([
    159.3, 161.2, 162.8, 164.6, 165.9, 167.9, 168.3, 169.7, 170.5, 171.6,
    173.9, 176.1, 178.0, 179.1, 180.2, 181.2, 181.6, 182.5, 183.3, 184.3])


def test_cochrane_orcutt_metal_with_max_iter():
    # ref RegressionARIMASuite.scala:23-42: PSU oracle beta=(28.918, 0.0479)
    model = ra.fit(jnp.asarray(METAL), jnp.asarray(VENDOR)[:, None],
                   "cochrane-orcutt", 1)
    beta = np.asarray(model.regression_coeff)
    assert abs(beta[0] - 28.918) < 0.01
    assert abs(beta[1] - 0.0479) < 0.001


def test_cochrane_orcutt_stock_data():
    # ref RegressionARIMASuite.scala:44-63: UCLA oracle rho=0.8241,
    # beta=(-235.4889, 2.75306)
    model = ra.fit_cochrane_orcutt(
        jnp.asarray(EXPENDITURE), jnp.asarray(STOCK)[:, None], 11)
    beta = np.asarray(model.regression_coeff)
    rho = float(np.asarray(model.arima_coeff))
    assert abs(rho - 0.8241) < 0.001
    assert abs(beta[0] - (-235.4889)) < 0.1
    assert abs(beta[1] - 2.75306) < 0.001


def test_unknown_method():
    with pytest.raises(NotImplementedError):
        ra.fit(jnp.asarray(METAL), jnp.asarray(VENDOR)[:, None], "banana")


def test_bad_args():
    with pytest.raises(ValueError):
        ra.fit(jnp.asarray(METAL), jnp.asarray(VENDOR)[:, None],
               "cochrane-orcutt", "not-an-int")
    with pytest.raises(ValueError):
        ra.fit(jnp.asarray(METAL), jnp.asarray(VENDOR)[:, None],
               "cochrane-orcutt", 1, 2)
    with pytest.raises(ValueError):
        ra.fit_cochrane_orcutt(jnp.asarray(METAL),
                               jnp.asarray(VENDOR)[:10, None])


def test_effects_unsupported():
    model = ra.RegressionARIMAModel(jnp.zeros(2), (1, 0, 0), jnp.zeros(1))
    with pytest.raises(NotImplementedError):
        model.add_time_dependent_effects(jnp.zeros(10))
    with pytest.raises(NotImplementedError):
        model.remove_time_dependent_effects(jnp.zeros(10))


def test_batched_matches_single():
    panel = jnp.stack([jnp.asarray(EXPENDITURE),
                       jnp.asarray(EXPENDITURE) * 1.1 + 2.0])
    model = ra.fit_cochrane_orcutt(panel, jnp.asarray(STOCK)[:, None], 11)
    assert model.regression_coeff.shape == (2, 2)
    assert model.arima_coeff.shape == (2,)
    single = ra.fit_cochrane_orcutt(
        jnp.asarray(EXPENDITURE), jnp.asarray(STOCK)[:, None], 11)
    np.testing.assert_allclose(np.asarray(model.regression_coeff[0]),
                               np.asarray(single.regression_coeff),
                               rtol=1e-10)
    np.testing.assert_allclose(float(model.arima_coeff[0]),
                               float(np.asarray(single.arima_coeff)),
                               rtol=1e-10)


def test_forecast_and_interval_gls():
    """Point forecast decays from the last residual at rho; band variance
    follows sigma_u^2 * cumsum(rho^{2j})."""
    rng = np.random.default_rng(0)
    n, k, H = 300, 2, 6
    X = rng.normal(size=(n, k))
    e = np.zeros(n)
    w = rng.normal(size=n) * 0.5
    for t in range(1, n):
        e[t] = 0.6 * e[t - 1] + w[t]
    beta = np.array([2.0, 0.8, -0.4])
    y = beta[0] + X @ beta[1:] + e
    m = ra.fit_cochrane_orcutt(jnp.asarray(y),
                                             jnp.asarray(X))
    Xf = rng.normal(size=(H, k))
    pt, lo, hi = m.forecast_interval(jnp.asarray(y), jnp.asarray(X),
                                     jnp.asarray(Xf))
    assert pt.shape == lo.shape == hi.shape == (H,)

    b = np.asarray(m.regression_coeff)
    rho = float(m.arima_coeff)
    resid = y - (b[0] + X @ b[1:])
    e_n = resid[-1]
    expect_pt = b[0] + Xf @ b[1:] + rho ** np.arange(1, H + 1) * e_n
    np.testing.assert_allclose(np.asarray(pt), expect_pt, rtol=1e-6)

    u = resid[1:] - rho * resid[:-1]
    sigma_u2 = np.mean(u * u)
    var = sigma_u2 * np.cumsum(rho ** (2 * np.arange(H)))
    np.testing.assert_allclose(np.asarray(hi - lo) / 2,
                               1.959964 * np.sqrt(var), rtol=1e-5)
    # widths widen toward the stationary limit
    wdt = np.asarray(hi - lo)
    assert (np.diff(wdt) > 0).all()


def test_forecast_interval_batched_shared_design():
    rng = np.random.default_rng(1)
    n, k, H, S = 200, 2, 4, 3
    X = rng.normal(size=(n, k))
    Y = jnp.asarray(np.stack([
        1.0 + X @ [0.5, 0.2] + rng.normal(size=n) for _ in range(S)]))
    m = ra.fit_cochrane_orcutt(Y, jnp.asarray(X))
    Xf = rng.normal(size=(H, k))
    pt, lo, hi = m.forecast_interval(Y, jnp.asarray(X), jnp.asarray(Xf))
    assert pt.shape == (S, H)
    assert bool(jnp.all(jnp.isfinite(hi - lo)))


def test_forecast_negative_rho_tpu_safe():
    # float ** with a negative base NaNs on TPU (exp/log lowering); the
    # cumprod/squared-base forms must survive a negatively autocorrelated
    # fit and produce the sign-alternating decay
    rng = np.random.default_rng(2)
    n, k, H = 300, 1, 5
    X = rng.normal(size=(n, k))
    e = np.zeros(n)
    w = rng.normal(size=n) * 0.5
    for t in range(1, n):
        e[t] = -0.6 * e[t - 1] + w[t]
    y = 1.0 + X[:, 0] * 0.5 + e
    m = ra.fit_cochrane_orcutt(jnp.asarray(y), jnp.asarray(X))
    assert float(m.arima_coeff) < -0.3
    Xf = rng.normal(size=(H, k))
    pt, lo, hi = m.forecast_interval(jnp.asarray(y), jnp.asarray(X),
                                     jnp.asarray(Xf))
    assert np.isfinite(np.asarray(pt)).all()
    assert np.isfinite(np.asarray(hi - lo)).all()
    b = np.asarray(m.regression_coeff)
    rho = float(m.arima_coeff)
    e_n = float((y - (b[0] + X @ b[1:]))[-1])
    expect = b[0] + Xf @ b[1:] + rho ** np.arange(1, H + 1) * e_n
    np.testing.assert_allclose(np.asarray(pt), expect, rtol=1e-6)
